# jaxmc build/check driver — mirrors the reference's Makefile contract
# (/root/reference/Makefile:1-7: all = transpile + test) with the checker
# backend selectable: BACKEND=interp (exact Python oracle) | jax (TPU path).

BACKEND ?= interp
SPEC    ?= specs/transfer_scaled.tla
PY      ?= python3

all: test

# model-check one spec (auto-discovers <spec>.cfg)
check:
	$(PY) -m jaxmc check $(SPEC) --backend $(BACKEND)

# check every checkable spec+cfg with its EXPECTED verdict, the way the
# reference's `make test` runs `tlc *tla` (includes expected-violation
# models); SLOW=--slow adds the multi-minute ones
SLOW ?=
check-corpus:
	$(PY) -m jaxmc sweep --backend $(BACKEND) $(SLOW)

test:
	$(PY) -m pytest tests/ -q

# fault-injection smoke suite (ISSUE 4): every chaos-marked test — the
# JAXMC_FAULTS harness killing pool workers, corrupting checkpoints,
# failing device init, SIGKILLing whole runs mid-level — on the CPU
# backend. The heavyweight kill/resume legs are additionally marked
# `slow`, so they run here but stay out of tier-1 timing.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos

bench:
	$(PY) bench.py

# perf-regression gate: run a short fixed-model exact-engine bench twice
# (one serial leg, one --workers 4 leg) and gate each leg LIKE-FOR-LIKE
# against the baseline artifact saved by the previous bench-check run
# (python -m jaxmc.obs diff --fail-on-regress: states/sec drop, backend
# demotion, phase blowups). First invocation snapshots the baselines;
# run it on main before a perf-sensitive change, then again after.
# `make bench-check-reset` discards the baselines.
BENCH_CHECK_SPEC ?= specs/transfer_scaled.tla
BENCH_CHECK_DIR  ?= /tmp
bench-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --workers 1 --max-states 20000 --quiet \
	    --metrics-out $(BENCH_CHECK_DIR)/jaxmc_bench_check_serial.json
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --workers 4 --max-states 20000 --quiet \
	    --metrics-out $(BENCH_CHECK_DIR)/jaxmc_bench_check_par.json
	@for leg in serial par; do \
	  cur=$(BENCH_CHECK_DIR)/jaxmc_bench_check_$$leg.json; \
	  base=$(BENCH_CHECK_DIR)/jaxmc_bench_check_$$leg.baseline.json; \
	  if [ -f $$base ]; then \
	    echo "== $$leg leg vs saved baseline =="; \
	    $(PY) -m jaxmc.obs diff --fail-on-regress --threshold 25 \
	        $$base $$cur || exit 1; \
	  else \
	    cp $$cur $$base; \
	    echo "$$leg baseline saved -> $$base"; \
	  fi; \
	done

bench-check-reset:
	rm -f $(BENCH_CHECK_DIR)/jaxmc_bench_check_serial.baseline.json \
	      $(BENCH_CHECK_DIR)/jaxmc_bench_check_par.baseline.json

# build the native host fingerprint store (also built on demand at import)
native:
	mkdir -p native/build
	g++ -O2 -shared -fPIC -std=c++17 -pthread native/fps_store.cc -o native/build/libjaxmc_fps.so

.PHONY: all check check-corpus test chaos bench bench-check bench-check-reset native
