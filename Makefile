# jaxmc build/check driver — mirrors the reference's Makefile contract
# (/root/reference/Makefile:1-7: all = transpile + test) with the checker
# backend selectable: BACKEND=interp (exact Python oracle) | jax (TPU path).

BACKEND ?= interp
SPEC    ?= specs/transfer_scaled.tla
PY      ?= python3

all: test

# model-check one spec (auto-discovers <spec>.cfg)
check:
	$(PY) -m jaxmc check $(SPEC) --backend $(BACKEND)

# check every checkable spec+cfg with its EXPECTED verdict, the way the
# reference's `make test` runs `tlc *tla` (includes expected-violation
# models); SLOW=--slow adds the multi-minute ones
SLOW ?=
check-corpus:
	$(PY) -m jaxmc sweep --backend $(BACKEND) $(SLOW)

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

# build the native host fingerprint store (also built on demand at import)
native:
	mkdir -p native/build
	g++ -O2 -shared -fPIC -std=c++17 -pthread native/fps_store.cc -o native/build/libjaxmc_fps.so

.PHONY: all check check-corpus test bench native
