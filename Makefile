# jaxmc build/check driver — mirrors the reference's Makefile contract
# (/root/reference/Makefile:1-7: all = transpile + test) with the checker
# backend selectable: BACKEND=interp (exact Python oracle) | jax (TPU path).

BACKEND ?= interp
SPEC    ?= specs/transfer_scaled.tla
PY      ?= python3

all: test

# model-check one spec (auto-discovers <spec>.cfg)
check:
	$(PY) -m jaxmc check $(SPEC) --backend $(BACKEND)

# check every checkable spec the way `tlc *tla` does
check-corpus:
	$(PY) -m jaxmc check /root/reference/pcal_intro.tla --backend $(BACKEND)
	$(PY) -m jaxmc check /root/reference/atomic_add.tla --backend $(BACKEND)

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

# build the native host fingerprint store (also built on demand at import)
native:
	mkdir -p native/build
	g++ -O2 -shared -fPIC -std=c++17 native/fps_store.cc -o native/build/libjaxmc_fps.so

.PHONY: all check check-corpus test bench native
