# jaxmc build/check driver — mirrors the reference's Makefile contract
# (/root/reference/Makefile:1-7: all = transpile + test) with the checker
# backend selectable: BACKEND=interp (exact Python oracle) | jax (TPU path).

BACKEND   ?= interp
SPEC      ?= specs/transfer_scaled.tla
PY        ?= python3
REFERENCE ?= /root/reference

all: test

# model-check one spec (auto-discovers <spec>.cfg).
# BACKEND=tlc shells out to stock TLC — the reference's own `make test`
# driver (/root/reference/Makefile:6-7) and the 100x target's anchor —
# when a JVM provides it, and refuses with ONE clear line otherwise
# (BASELINE.md documents the full TLC measurement recipe).
check:
	@if [ "$(BACKEND)" = "tlc" ]; then \
	  if command -v tlc >/dev/null 2>&1; then \
	    tlc $(SPEC); \
	  else \
	    echo "BACKEND=tlc: no JVM/tlc on PATH; interp is the oracle here" \
	         "(see BASELINE.md 'Measuring TLC' for the recipe)" >&2; \
	    exit 2; \
	  fi; \
	else \
	  $(PY) -m jaxmc check $(SPEC) --backend $(BACKEND); \
	fi

# check every checkable spec+cfg with its EXPECTED verdict, the way the
# reference's `make test` runs `tlc *tla` (includes expected-violation
# models); SLOW=--slow adds the multi-minute ones
SLOW ?=
check-corpus:
	@if [ "$(BACKEND)" = "tlc" ]; then \
	  if ! command -v tlc >/dev/null 2>&1; then \
	    echo "BACKEND=tlc: no JVM/tlc on PATH; interp is the oracle here" \
	         "(see BASELINE.md 'Measuring TLC' for the recipe)" >&2; \
	    exit 2; \
	  elif [ ! -d $(REFERENCE) ]; then \
	    echo "BACKEND=tlc: reference corpus not mounted at $(REFERENCE)" \
	         "(set REFERENCE=<dir>); interp is the oracle here" >&2; \
	    exit 2; \
	  else \
	    cd $(REFERENCE) && tlc *tla; \
	  fi; \
	else \
	  $(PY) -m jaxmc sweep --backend $(BACKEND) $(SLOW); \
	fi

test:
	$(PY) -m pytest tests/ -q

# static analysis gates (ISSUE 9) — both run inside `make bench-check`:
#   lint-corpus  the TLA+ corpus linter over every manifest pair; the
#                repo-local pairs must be clean modulo explicit waivers
#                (corpus.py Case.lint_waive), the linttoy fixture must
#                produce every expected diagnostic class, and
#                reference-rooted pairs SKIP (parseably) when
#                /root/reference is absent
#   pylint       Python-side static analysis of jaxmc itself — ruff
#                (pyflakes+bugbear, see ruff.toml) when the host has
#                it, else the builtin checker in jaxmc/analyze/pylint.py
lint-corpus:
	$(PY) -m jaxmc.analyze lint-corpus

pylint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check jaxmc; \
	else \
	  $(PY) -m jaxmc.analyze pylint jaxmc; \
	fi

# fault-injection smoke suite (ISSUE 4): every chaos-marked test — the
# JAXMC_FAULTS harness killing pool workers, corrupting checkpoints,
# failing device init, SIGKILLing whole runs mid-level — on the CPU
# backend. The heavyweight kill/resume legs are additionally marked
# `slow`, so they run here but stay out of tier-1 timing.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos

bench:
	$(PY) bench.py

# (re)generate the resumable warm artifacts, deadline-free (ISSUE 5):
#   ck_mcraft3s_bench_warm.ck  resident warm checkpoint the bench's full
#                              rung resumes (steady-state window)
#   ck_mcraft3s.ck             resumable interp checkpoint of the
#                              BASELINE model of record; repeated runs
#                              EXTEND it toward completion
# Requires the reference corpus (raft.tla) at $(REFERENCE).
bench-warm:
	JAXMC_BENCH_CHILD=warmgen $(PY) bench.py

# one-shot TLC measurement of the bench model (BASELINE.md recipe): the
# literature-sourced 5000 st/s estimate becomes a MEASUREMENT wherever a
# JVM exists — divide TLC's reported generated total by wall seconds and
# compare with BENCH_r*.json value. The bench spec transitively EXTENDS
# the reference raft.tla, and plain tlc resolves modules from the cwd —
# so stage the shim + the reference module side by side first.
bench-tlc:
	@command -v tlc >/dev/null 2>&1 || { \
	  echo "bench-tlc: no JVM/tlc on PATH; interp is the oracle here" \
	       "(see BASELINE.md 'Measuring TLC')" >&2; exit 2; }
	@[ -f $(REFERENCE)/examples/raft.tla ] || { \
	  echo "bench-tlc: reference corpus not mounted at $(REFERENCE)" \
	       "(set REFERENCE=<dir>); the bench spec EXTENDS its raft.tla" \
	       >&2; exit 2; }
	rm -rf /tmp/jaxmc_tlc_bench && mkdir -p /tmp/jaxmc_tlc_bench
	cp specs/MCraftMicro.tla specs/MCraft.tla \
	    specs/MCraft_3s_bench.cfg /tmp/jaxmc_tlc_bench/
	cp $(REFERENCE)/examples/raft.tla /tmp/jaxmc_tlc_bench/
	cd /tmp/jaxmc_tlc_bench && time tlc -config MCraft_3s_bench.cfg \
	    MCraftMicro.tla

# resume (or start) the MCserializableSI_env exhaustive run with
# checkpointing — the open count-pin item (VERDICT r5 #5): run until it
# completes, then pin the printed generated/distinct totals in
# jaxmc/corpus.py (the slow test test_si.py::test_si_env_exhaustive_pin
# enforces them from then on)
pin-si-env:
	$(PY) -m jaxmc check specs/MCserializableSI.tla \
	    --cfg specs/MCserializableSI_env.cfg -I $(REFERENCE)/examples \
	    --checkpoint ck_si_env.ck --checkpoint-every 120 \
	    $$( [ -f ck_si_env.ck ] && echo --resume ck_si_env.ck )

# perf-regression gate: run a short fixed-model exact-engine bench twice
# (one serial leg, one --workers 4 leg) and gate each leg LIKE-FOR-LIKE
# against the baseline artifact saved by the previous bench-check run
# (python -m jaxmc.obs diff --fail-on-regress: states/sec drop, backend
# demotion, phase blowups). First invocation snapshots the baselines;
# run it on main before a perf-sensitive change, then again after.
# `make bench-check-reset` discards the baselines.
BENCH_CHECK_SPEC ?= specs/transfer_scaled.tla
BENCH_CHECK_DIR  ?= /tmp
# repo-local kernel-vs-interp rungs (ISSUE 6): the three feature axes —
# plain wide search, cfg VIEW, cfg SYMMETRY — at bench scale
KERNELBENCH_RUNGS ?= specs/transfer_scaled.tla specs/viewtoy_scaled.tla \
                     specs/symtoy_scaled.tla
bench-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --workers 1 --max-states 20000 --quiet \
	    --metrics-out $(BENCH_CHECK_DIR)/jaxmc_bench_check_serial.json
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --workers 4 --max-states 20000 --quiet \
	    --metrics-out $(BENCH_CHECK_DIR)/jaxmc_bench_check_par.json
	# warm-start leg (ISSUE 5): a resident truncation checkpoint, then a
	# steady-state resume — the compile-excluded window the bench's full
	# rung now measures, gated like-for-like against its saved baseline
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --backend jax --platform cpu --resident --no-trace --quiet \
	    --max-states 4000 \
	    --checkpoint $(BENCH_CHECK_DIR)/jaxmc_bench_check_warm.ck
	JAX_PLATFORMS=cpu $(PY) -m jaxmc check $(BENCH_CHECK_SPEC) \
	    --backend jax --platform cpu --resident --no-trace --quiet \
	    --max-states 20000 \
	    --resume $(BENCH_CHECK_DIR)/jaxmc_bench_check_warm.ck \
	    --metrics-out $(BENCH_CHECK_DIR)/jaxmc_bench_check_warmleg.json
	@for leg in serial par warmleg; do \
	  cur=$(BENCH_CHECK_DIR)/jaxmc_bench_check_$$leg.json; \
	  base=$(BENCH_CHECK_DIR)/jaxmc_bench_check_$$leg.baseline.json; \
	  if [ -f $$base ]; then \
	    echo "== $$leg leg vs saved baseline =="; \
	    $(PY) -m jaxmc.obs diff --fail-on-regress --threshold 25 \
	        $$base $$cur || exit 1; \
	  else \
	    cp $$cur $$base; \
	    echo "$$leg baseline saved -> $$base"; \
	  fi; \
	done
	# kernel-vs-interp leg (ISSUE 6): on every repo-local rung the
	# cpu-XLA kernel (steady state: one warm-up excluded) must meet or
	# exceed the serial interpreter's states/sec, with bit-identical
	# counts; jaxmc.kernelbench writes the two artifacts and gates them
	# through `python -m jaxmc.obs diff --fail-on-regress` ([interp,
	# kernel] order — a slower kernel raises the REGRESS flag)
	@for spec in $(KERNELBENCH_RUNGS); do \
	  echo "== kernel-vs-interp leg: $$spec =="; \
	  JAX_PLATFORMS=cpu $(PY) -m jaxmc.kernelbench $$spec \
	      --out-dir $(BENCH_CHECK_DIR) || exit 1; \
	done
	# cross-model batching leg (ISSUE 13): a cold cohort of
	# layout-compatible jobs must run as ONE vmapped engine at >= 2x
	# the sequential cold throughput with bit-identical per-member
	# counts — see batch-check below
	$(MAKE) batch-check
	# checking-as-a-service leg (ISSUE 7): the warm second submission
	# to a live daemon must be a checkpoint-resume with ZERO in-window
	# recompiles — see serve-check below
	$(MAKE) serve-check
	# observability leg (ISSUE 16): live daemon scraped mid-run
	# (/metrics parses, per-job progress gauge moves), multi-process
	# timeline with zero orphan spans — see trace-check below
	$(MAKE) trace-check
	# fleet-serving leg (ISSUE 19): multi-daemon spool under SIGKILLs —
	# lease takeover with bit-identical resumed counts, warm-hit
	# routing beating round-robin, 429 + Retry-After under overload,
	# poison-job quarantine (parseable FLEET-CHECK SKIP on hosts that
	# cannot run a fleet) — see fleet-check below
	$(MAKE) fleet-check
	# multi-chip parity leg (ISSUE 8): D=2 and D=4 virtual-device mesh
	# runs must match the manifest pins bit-for-bit — see
	# multichip-check below
	$(MAKE) multichip-check
	# backend-portability leg (ISSUE 11): preflight oracle smoke +
	# per-live-platform baseline gate (SKIP lines for dead platforms)
	$(MAKE) backend-check
	# out-of-core leg (ISSUE 12): capped exhaustive run via tier spill
	# + fingerprint parity — see ooc-check below
	$(MAKE) ooc-check
	# independence/reduction leg (ISSUE 15): regroup parity, --por
	# verdict preservation + >=30% explored-state reduction, and the
	# predicted capacity rung's zero-growth cold run — see por-check
	$(MAKE) por-check
	# profiler/ledger leg (ISSUE 17): warm `--profile` runs must
	# attribute >= 90% of the search wall to named dispatch sites,
	# profile-on/off counts must be bit-identical, and the temp-ledger
	# regression gate must pass (and trip on a synthesized slowdown)
	# — see prof-check below
	$(MAKE) prof-check
	# static-analysis legs (ISSUE 9): an analyzer regression gates the
	# same way perf regressions do — the corpus must stay lint-clean
	# (modulo manifest waivers) and jaxmc's own Python must stay free
	# of dead imports/locals
	$(MAKE) lint-corpus
	$(MAKE) pylint

# multi-chip parity gate (ISSUE 8/10): the mesh-resident engine
# (owner-routed a2a dedup, seen shards + frontier + trace ring on
# device, scalars-only host reads, rank-merge + fused supersteps) at
# D=2 and D=4 VIRTUAL cpu devices on the repo-local bench rungs
# (+ MCraft_micro when the reference corpus is mounted — a parseable
# SKIP line otherwise).  Counts must equal the corpus manifest pins,
# host_syncs may never exceed the level count (supersteps make it
# smaller), and each leg's metrics artifact gates via
# `python -m jaxmc.obs diff --fail-on-regress` against a saved
# baseline (first run snapshots it; baselines live in
# $(BENCH_CHECK_DIR)/jaxmc_multichip_*.baseline.json).
# The RANK-MERGE leg (ISSUE 10): the default check runs the rank
# strategy; a second fullsort leg on one rung proves the
# JAXMC_MESH_RANKMERGE=0 escape hatch answers bit-identically.
# Finally, when two committed MULTICHIP_r* scaling artifacts exist,
# `obs diff` gates the newer per-rung states/sec/chip against the
# older (wired into `make bench-check` through this target).
MULTICHIP_DEVICES ?= 2,4
# every committed schema>=1 scaling artifact, ordered by recorded
# timestamp inside `obs diff` (ISSUE 17: diff expands globs itself,
# so new MULTICHIP_r* drops join the gate without a Makefile edit;
# r01-r05 predate the /1 schema and stay out of the pattern)
MULTICHIP_GLOB ?= MULTICHIP_r0[6-9].json
multichip-check:
	$(PY) -m jaxmc.meshbench check --devices $(MULTICHIP_DEVICES) \
	    --out-dir $(BENCH_CHECK_DIR)
	$(PY) -m jaxmc.meshbench check --devices 2 \
	    --rung specs/viewtoy_scaled.tla --merge fullsort \
	    --out-dir $(BENCH_CHECK_DIR)
	@if ls $(MULTICHIP_GLOB) >/dev/null 2>&1; then \
	  echo "== multichip scaling curve: $(MULTICHIP_GLOB) =="; \
	  $(PY) -m jaxmc.obs diff --fail-on-regress --threshold 25 \
	      '$(MULTICHIP_GLOB)' || exit 1; \
	fi

# backend-portability gate (ISSUE 11): two legs, both parseable —
#   1. oracle smoke: the preflight oracle (jaxmc/backend/oracle.py)
#      must find at least one live platform inside its deadline (<10s;
#      a wedged accelerator tunnel costs the deadline, never a hang);
#   2. per-backend baseline: for every LIVE platform, one pinned
#      `--backend <plat>` check leg gated against that platform's OWN
#      saved baseline via `python -m jaxmc.obs diff --fail-on-regress`
#      (first run snapshots it — how a new platform's baseline is
#      seeded, BASELINE.md "Per-backend baselines").  Dead platforms
#      print `BACKEND-CHECK SKIP <plat>: <reason>` and never fail, so
#      the target is green on a cpu-only builder box and a TPU pod
#      alike; live platforms must agree on reachable-state counts.
backend-check:
	$(PY) -m jaxmc.backend.check --out-dir $(BENCH_CHECK_DIR)

# out-of-core seen-set gate (ISSUE 12): on the repo-local overflow
# fixture (specs/ooc_scaled.tla) — (1) uncapped exact run == manifest
# pins; (2) JAXMC_SEEN_CAP forces the device seen table to ~17% of the
# state count and a tiny host budget forces the disk tier: the run
# must complete EXHAUSTIVELY via hierarchical tier spill with
# bit-identical counts, gated via `python -m jaxmc.obs diff
# --fail-on-regress` against its saved baseline; (3) --seen
# fingerprint parity + the measured >=4x states-per-device-tier ratio
# (BASELINE.md "Out-of-core"); (4) capped-vs-uncapped violation
# traces byte-identical.  A jax-less container prints `OOC-CHECK
# SKIP ...` and exits 0.
ooc-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.oocbench \
	    --out-dir $(BENCH_CHECK_DIR)

# independence/reduction gate (ISSUE 15): (1) unreduced portoy_ok
# counts == manifest pins; (2) --por completes with >= 30% fewer
# explored distinct states and preserves the deadlock/invariant
# verdicts of the portoy rungs; (3) the grouped host_seen path with
# independence regrouping ON vs OFF stays byte-identical (trace
# compared line-for-line, artifact gated via `python -m jaxmc.obs
# diff --fail-on-regress` against its saved baseline); (4) a COLD
# resident run of the fully-proven fixture takes the `predicted`
# capacity rung and pays zero growth recompiles.  A jax-less
# container still runs the interpreter legs and prints `POR-CHECK
# SKIP ...` for the rest.
por-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.porbench \
	    --out-dir $(BENCH_CHECK_DIR)

# the published scaling curve (ISSUE 8/10): per-rung, per-D warm-up +
# timed fully-warm mesh runs over D in {1,2,4,8} virtual devices
# (real chips when JAXMC_MESHBENCH_PLATFORM names an accelerator) —
# states/sec/chip, per-level exchange bytes, shard balance,
# host_syncs <= levels (supersteps), window_recompiles == 0, and the
# measured expand/exchange/merge phase-wall breakdown (incl. the
# rank-vs-fullsort merge wall and the fused-step hot_share) — written
# to MULTICHIP_r08.json and gated per leg like multichip-check.
MULTICHIP_BENCH_DEVICES ?= 1,2,4,8
MULTICHIP_OUT ?= MULTICHIP_r08.json
multichip-bench:
	$(PY) -m jaxmc.meshbench bench \
	    --devices $(MULTICHIP_BENCH_DEVICES) \
	    --out $(MULTICHIP_OUT) --out-dir $(BENCH_CHECK_DIR)

# cross-model vmapped batching gate (ISSUE 13): the batchtoy cohort
# (one module, four cfgs differing only in liftable constant values)
# submitted cold must run as ONE vmapped engine — full occupancy, one
# engine build, per-member counts bit-identical to solo runs — at
# >= 2x the sequential cold aggregate states/sec (JAXMC_BATCH_GATE_X).
# The warm deep-rung pair is reported and baseline-gated (cpu-XLA's
# ~0.5ms dispatches leave little latency to amortize; the accelerator
# warm measurement is the standing driver-env task).  Prints a
# parseable `BATCH-CHECK SKIP: <reason>` where the leg cannot run.
batch-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.batchbench \
	    --out-dir $(BENCH_CHECK_DIR)
	# same-invocation throughput gate, kernelbench-style: artifacts
	# ordered [sequential, batched], so a batched cohort slower than
	# the sequential one raises the REGRESS states/sec flag (across-
	# run wall baselines are too noisy in shared containers; the
	# same-invocation ratio is load-independent)
	@if [ -f $(BENCH_CHECK_DIR)/jaxmc_batchbench_cold_seq.json ]; then \
	  echo "== batchbench cold cohort: sequential -> batched =="; \
	  $(PY) -m jaxmc.obs diff --fail-on-regress --threshold 25 \
	      '$(BENCH_CHECK_DIR)/jaxmc_batchbench_cold_*.json' \
	      || exit 1; \
	fi

# profiler/ledger gate (ISSUE 17): warm checkpoint-then-resume legs on
# transfer_scaled + symtoy_scaled under `--profile` — per-site walls
# must attribute >= 90% of the search wall (JAXMC_PROF_CHECK_MIN_SHARE
# overrides), profile-on vs profile-off counts must be bit-identical,
# the HBM model must have registered the resident buffers, and the
# legs' TEMP run ledger must pass `python -m jaxmc.obs history
# --fail-on-regress` (with a synthesized 2x slowdown proven to trip
# it).  Prints parseable `PROF-CHECK …` lines; SKIPs without jax.
prof-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.profcheck \
	    --out-dir $(BENCH_CHECK_DIR)

# checking-as-a-service smoke gate (ISSUE 7): fresh spool, in-process
# daemon, two identical jax-resident jobs — the second MUST reuse the
# warm session, resume the first job's final checkpoint, report
# window_recompiles == 0 and a capacity-profile hit, and its artifact
# must pass `python -m jaxmc.obs diff --fail-on-regress` against the
# cold one.  Exit 0 only when every assertion holds.
serve-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.serve smoke

# fleet-observability gate (ISSUE 16): in-process daemon + slow interp
# job with a fork pool + a device-owner jax job; GET /metrics must
# parse as Prometheus text with a MOVING per-job search.progress_est
# mid-run, GET /jobs/<id>/events must answer mid-run, warm counters
# must move on resubmission, and `obs timeline` over the daemon +
# per-job traces must stitch >= 3 distinct OS processes with ZERO
# orphan spans.  Exit 0 only when every assertion holds.
trace-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.tracecheck

# fleet-serving chaos gate (ISSUE 19): several subprocess daemons on
# ONE durable spool.  Legs: (takeover) SIGKILL the daemon that owns a
# slow job mid-run — a peer must steal the expired lease and finish
# from the spool checkpoint with counts bit-identical to a solo
# reference; (routing) identical submissions round-robined across 3
# ports must land on the sig-warm daemon, then `obs timeline
# --fail-on-orphans` must stitch every daemon + job trace with 0
# orphan spans; (admission) a depth-bounded daemon under a burst
# answers 429 + Retry-After with queue gauges while accepted jobs
# complete; (poison) a job whose owner always dies is quarantined
# after the cross-daemon retry budget with a named verdict.  Leg
# artifacts land in $(BENCH_CHECK_DIR) and the run ledger.  Prints
# one parseable `FLEET-CHECK SKIP: ...` line (exit 0) on hosts with
# < 2 CPUs or no bindable loopback port.
fleet-check:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.fleetbench \
	    --out-dir $(BENCH_CHECK_DIR)

# run the checking daemon on a durable spool (jobs/results/checkpoints
# survive restarts; SIGTERM drains gracefully — see README "Checking
# as a service")
SPOOL ?= /tmp/jaxmc_serve
serve:
	JAX_PLATFORMS=cpu $(PY) -m jaxmc.serve run --spool $(SPOOL)

bench-check-reset:
	rm -f $(BENCH_CHECK_DIR)/jaxmc_bench_check_serial.baseline.json \
	      $(BENCH_CHECK_DIR)/jaxmc_bench_check_par.baseline.json \
	      $(BENCH_CHECK_DIR)/jaxmc_bench_check_warmleg.baseline.json \
	      $(BENCH_CHECK_DIR)/jaxmc_bench_check_warm.ck \
	      $(BENCH_CHECK_DIR)/jaxmc_batchbench_cold_seq.json \
	      $(BENCH_CHECK_DIR)/jaxmc_batchbench_cold_batch.json \
	      $(BENCH_CHECK_DIR)/jaxmc_batchbench_warm_seq.json \
	      $(BENCH_CHECK_DIR)/jaxmc_batchbench_warm_batch.json

# build the native host fingerprint store (also built on demand at import)
native:
	mkdir -p native/build
	g++ -O2 -shared -fPIC -std=c++17 -pthread native/fps_store.cc -o native/build/libjaxmc_fps.so

.PHONY: all check check-corpus test chaos bench bench-warm bench-tlc \
        pin-si-env bench-check bench-check-reset serve serve-check \
        trace-check fleet-check batch-check multichip-check \
        multichip-bench backend-check por-check prof-check native \
        lint-corpus pylint
