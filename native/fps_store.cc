// jaxmc native host fingerprint store.
//
// The device BFS keeps its seen-set in accelerator memory; for state spaces
// beyond HBM (SURVEY.md §7.5 "spill seen-set shards to host when full") the
// 128-bit state fingerprints spill into this sorted store. Batch insert
// with membership marking: O(batch log batch + |store|) per level via
// sort + two-pointer merge, the classic external dedup used by explicit
// state model checkers.
//
// C ABI only (bound via ctypes; pybind11 is not available in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Fp {
    uint64_t hi, lo;
    bool operator<(const Fp& o) const {
        return hi != o.hi ? hi < o.hi : lo < o.lo;
    }
    bool operator==(const Fp& o) const { return hi == o.hi && lo == o.lo; }
};

struct Store {
    std::vector<Fp> base;  // sorted, unique
};

}  // namespace

extern "C" {

void* jaxmc_fps_create() { return new Store(); }

void jaxmc_fps_destroy(void* p) { delete static_cast<Store*>(p); }

uint64_t jaxmc_fps_count(void* p) {
    return static_cast<Store*>(p)->base.size();
}

// Marks out_new[i] = 1 for fingerprints absent from the store (first
// occurrence within the batch wins), inserts them, returns the number of
// new fingerprints. hi/lo/out_new are length n.
uint64_t jaxmc_fps_insert(void* p, const uint64_t* hi, const uint64_t* lo,
                          uint64_t n, uint8_t* out_new) {
    Store& st = *static_cast<Store*>(p);
    std::memset(out_new, 0, n);

    std::vector<uint64_t> order(n);
    for (uint64_t i = 0; i < n; ++i) order[i] = i;
    std::sort(order.begin(), order.end(), [&](uint64_t a, uint64_t b) {
        Fp fa{hi[a], lo[a]}, fb{hi[b], lo[b]};
        if (fa == fb) return a < b;  // stable: first occurrence first
        return fa < fb;
    });

    std::vector<Fp> merged;
    merged.reserve(st.base.size() + n);
    uint64_t new_count = 0;
    size_t bi = 0;
    bool have_prev = false;
    Fp prev{0, 0};
    for (uint64_t k = 0; k < n; ++k) {
        uint64_t idx = order[k];
        Fp f{hi[idx], lo[idx]};
        if (have_prev && f == prev) continue;  // duplicate within batch
        // advance base, copying smaller entries
        while (bi < st.base.size() && st.base[bi] < f)
            merged.push_back(st.base[bi++]);
        if (bi < st.base.size() && st.base[bi] == f) {
            prev = f;
            have_prev = true;
            continue;  // already known
        }
        out_new[idx] = 1;
        ++new_count;
        merged.push_back(f);
        prev = f;
        have_prev = true;
    }
    while (bi < st.base.size()) merged.push_back(st.base[bi++]);
    st.base.swap(merged);
    return new_count;
}

// Copies the sorted store contents into hi/lo (each sized to count) —
// the checkpoint/resume serialization surface.
void jaxmc_fps_export(void* p, uint64_t* hi, uint64_t* lo) {
    Store& st = *static_cast<Store*>(p);
    for (size_t i = 0; i < st.base.size(); ++i) {
        hi[i] = st.base[i].hi;
        lo[i] = st.base[i].lo;
    }
}

// Replaces the store contents with n fingerprints; input must be sorted
// and unique (the export format). Returns 1 on success, 0 when the
// ordering invariant does not hold (store left empty in that case).
uint64_t jaxmc_fps_import(void* p, const uint64_t* hi, const uint64_t* lo,
                          uint64_t n) {
    Store& st = *static_cast<Store*>(p);
    st.base.clear();
    st.base.reserve(n);
    for (uint64_t i = 0; i < n; ++i) {
        Fp f{hi[i], lo[i]};
        if (i > 0 && !(st.base.back() < f)) {
            st.base.clear();
            return 0;
        }
        st.base.push_back(f);
    }
    return 1;
}

}  // extern "C"
