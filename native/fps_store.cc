// jaxmc native host fingerprint store — phase 2 (VERDICT r4 #8).
//
// The device BFS keeps its seen-set in accelerator memory; for state spaces
// beyond HBM (SURVEY.md §7.5 "spill seen-set shards to host when full") the
// 128-bit state fingerprints spill into this store. Phase 1 was one sorted
// vector with a full O(|store|) rewrite per batch; phase 2 is an LSM-style
// tiered design built for seen-sets LARGER THAN RAM:
//
//   - immutable sorted RUNS held in mmap regions. Runs at or above a spill
//     threshold are FILE-backed (created in a spill dir, unlinked at once so
//     the space frees itself on process exit): the OS pages cold portions
//     out to disk, so the resident set stays bounded while membership
//     lookups touch only the O(log n) pages a galloping binary search hits.
//     Smaller runs use anonymous mmap.
//   - batch insert sorts + dedups the batch (first occurrence wins, exactly
//     the phase-1 contract), marks membership against every run with a
//     monotone galloping lower_bound (batch is sorted, so per-run probe
//     positions only move forward), and seals the new fingerprints as a
//     fresh run: O(batch x log|run| x runs) per level, never O(|store|).
//   - a BACKGROUND THREAD compacts when the run count exceeds a fan-in
//     bound: it k-way merges a snapshot of the current runs while inserts
//     keep landing as new runs on top; the run list swaps atomically under
//     a mutex when the merge finishes. Runs are immutable once sealed, so
//     the merger reads them without locks.
//
// C ABI only (bound via ctypes; pybind11 is not available in this image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

namespace {

struct Fp {
    uint64_t hi, lo;
    bool operator<(const Fp& o) const {
        return hi != o.hi ? hi < o.hi : lo < o.lo;
    }
    bool operator==(const Fp& o) const { return hi == o.hi && lo == o.lo; }
};

struct Run {
    Fp* data = nullptr;
    size_t n = 0;
    size_t map_bytes = 0;

    ~Run() {
        if (data && map_bytes) munmap(data, map_bytes);
    }
    Run(const Run&) = delete;
    Run& operator=(const Run&) = delete;
    Run() = default;
};

using RunPtr = std::shared_ptr<const Run>;

// mmap a writable region for n fingerprints; file-backed (immediately
// unlinked) when a spill dir is given and the run is large enough.
std::shared_ptr<Run> alloc_run(size_t n, const std::string& spill_dir,
                               uint64_t spill_threshold, int* seq) {
    auto run = std::make_shared<Run>();
    run->n = n;
    run->map_bytes = n * sizeof(Fp);
    if (run->map_bytes == 0) return run;
    if (!spill_dir.empty() && run->map_bytes >= spill_threshold) {
        char path[4096];
        std::snprintf(path, sizeof(path), "%s/jaxmc_fps_%d_%d.run",
                      spill_dir.c_str(), (int)getpid(), (*seq)++);
        int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
        if (fd >= 0) {
            unlink(path);  // space frees itself when the mapping dies
            if (ftruncate(fd, (off_t)run->map_bytes) == 0) {
                void* p = mmap(nullptr, run->map_bytes,
                               PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
                close(fd);
                if (p != MAP_FAILED) {
                    run->data = static_cast<Fp*>(p);
                    return run;
                }
            } else {
                close(fd);
            }
        }
        // fall through to anonymous on any file failure
    }
    void* p = mmap(nullptr, run->map_bytes, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    if (p == MAP_FAILED) {
        run->map_bytes = 0;
        run->n = 0;
        return run;  // callers treat n==0 as empty; insert will report 0
    }
    run->data = static_cast<Fp*>(p);
    return run;
}

// k-way merge of sorted-unique runs into a new sorted-unique run.
std::shared_ptr<Run> merge_runs(const std::vector<RunPtr>& src,
                                const std::string& spill_dir,
                                uint64_t spill_threshold, int* seq) {
    size_t total = 0;
    for (const auto& r : src) total += r->n;
    auto out = alloc_run(total, spill_dir, spill_threshold, seq);
    if (total == 0 || out->data == nullptr) return out;
    std::vector<size_t> pos(src.size(), 0);
    size_t m = 0;
    for (;;) {
        int best = -1;
        for (size_t i = 0; i < src.size(); ++i) {
            if (pos[i] < src[i]->n &&
                (best < 0 || src[i]->data[pos[i]] < src[best]->data[pos[best]]))
                best = (int)i;
        }
        if (best < 0) break;
        Fp f = src[best]->data[pos[best]++];
        if (m == 0 || !(out->data[m - 1] == f)) out->data[m++] = f;
    }
    out->n = m;  // runs hold disjoint sets, so m == total normally
    return out;
}

struct Store {
    std::string spill_dir;        // empty = anonymous mmap only
    uint64_t spill_threshold = 64ull << 20;  // bytes; runs >= this spill
    size_t max_runs = 8;          // compaction fan-in trigger

    std::mutex mu;                // guards runs + count + seq
    std::vector<RunPtr> runs;     // immutable sorted-unique runs
    uint64_t count = 0;
    int seq = 0;

    std::thread merger;
    std::atomic<bool> merging{false};

    ~Store() { join_merger(); }

    void join_merger() {
        if (merger.joinable()) merger.join();
    }

    std::vector<RunPtr> snapshot() {
        std::lock_guard<std::mutex> g(mu);
        return runs;
    }

    // kick a background compaction when the fan-in bound is exceeded;
    // at most one merge in flight (runs created meanwhile stack on top
    // and are picked up by the next compaction)
    void maybe_compact() {
        bool expected = false;
        {
            std::lock_guard<std::mutex> g(mu);
            if (runs.size() <= max_runs) return;
        }
        if (!merging.compare_exchange_strong(expected, true)) return;
        join_merger();  // reap the previous (finished) thread object
        std::vector<RunPtr> src = snapshot();
        merger = std::thread([this, src]() {
            int local_seq;
            {
                std::lock_guard<std::mutex> g(mu);
                local_seq = seq;
                seq += (int)src.size() + 1;
            }
            auto merged = merge_runs(src, spill_dir, spill_threshold,
                                     &local_seq);
            size_t total = 0;
            for (const auto& r : src) total += r->n;
            if (total > 0 && merged->data == nullptr) {
                // allocation failed mid-compaction: keep the source
                // runs untouched (a silent swap-to-empty would erase
                // the seen-set and re-expand visited states); the next
                // insert retries compaction when memory frees up
                merging.store(false);
                return;
            }
            {
                std::lock_guard<std::mutex> g(mu);
                std::vector<RunPtr> next;
                next.push_back(merged);
                // keep every run that arrived after the snapshot
                for (const auto& r : runs) {
                    bool in_src = false;
                    for (const auto& s : src)
                        if (s == r) { in_src = true; break; }
                    if (!in_src) next.push_back(r);
                }
                runs.swap(next);
            }
            merging.store(false);
        });
    }
};

}  // namespace

extern "C" {

void* jaxmc_fps_create_ex(const char* spill_dir,
                          uint64_t spill_threshold_bytes) {
    Store* st = new Store();
    if (spill_dir && spill_dir[0]) st->spill_dir = spill_dir;
    if (spill_threshold_bytes) st->spill_threshold = spill_threshold_bytes;
    return st;
}

void* jaxmc_fps_create() { return jaxmc_fps_create_ex(nullptr, 0); }

void jaxmc_fps_destroy(void* p) { delete static_cast<Store*>(p); }

uint64_t jaxmc_fps_count(void* p) {
    Store& st = *static_cast<Store*>(p);
    std::lock_guard<std::mutex> g(st.mu);
    return st.count;
}

// Marks out_new[i] = 1 for fingerprints absent from the store (first
// occurrence within the batch wins), inserts them, returns the number of
// new fingerprints. hi/lo/out_new are length n.
uint64_t jaxmc_fps_insert(void* p, const uint64_t* hi, const uint64_t* lo,
                          uint64_t n, uint8_t* out_new) {
    Store& st = *static_cast<Store*>(p);
    std::memset(out_new, 0, n);
    if (n == 0) return 0;

    std::vector<uint64_t> order(n);
    for (uint64_t i = 0; i < n; ++i) order[i] = i;
    std::sort(order.begin(), order.end(), [&](uint64_t a, uint64_t b) {
        Fp fa{hi[a], lo[a]}, fb{hi[b], lo[b]};
        if (fa == fb) return a < b;  // stable: first occurrence first
        return fa < fb;
    });

    // unique batch fingerprints in sorted order + their first batch index
    std::vector<Fp> uniq;
    std::vector<uint64_t> first_idx;
    uniq.reserve(n);
    first_idx.reserve(n);
    for (uint64_t k = 0; k < n; ++k) {
        uint64_t idx = order[k];
        Fp f{hi[idx], lo[idx]};
        if (!uniq.empty() && uniq.back() == f) continue;
        uniq.push_back(f);
        first_idx.push_back(idx);
    }

    // membership against every run: the batch is sorted, so each run is
    // probed with a forward-only galloping lower_bound
    std::vector<uint8_t> known(uniq.size(), 0);
    std::vector<RunPtr> runs = st.snapshot();
    for (const auto& run : runs) {
        const Fp* rd = run->data;
        size_t rpos = 0;
        for (size_t u = 0; u < uniq.size(); ++u) {
            if (known[u]) continue;
            const Fp* it = std::lower_bound(rd + rpos, rd + run->n,
                                            uniq[u]);
            rpos = (size_t)(it - rd);
            if (rpos >= run->n) break;
            if (rd[rpos] == uniq[u]) known[u] = 1;
        }
    }

    uint64_t new_count = 0;
    for (size_t u = 0; u < uniq.size(); ++u)
        if (!known[u]) ++new_count;
    if (new_count == 0) return 0;

    std::shared_ptr<Run> fresh;
    {
        std::lock_guard<std::mutex> g(st.mu);
        fresh = alloc_run(new_count, st.spill_dir, st.spill_threshold,
                          &st.seq);
    }
    if (fresh->data == nullptr && new_count > 0)
        return ~0ull;  // allocation failed: LOUD error sentinel — a silent
                       // 0 would mark genuinely-new states as seen and
                       // under-approximate the search
    size_t m = 0;
    for (size_t u = 0; u < uniq.size(); ++u) {
        if (known[u]) continue;
        out_new[first_idx[u]] = 1;
        fresh->data[m++] = uniq[u];
    }
    {
        std::lock_guard<std::mutex> g(st.mu);
        st.runs.push_back(fresh);
        st.count += new_count;
    }
    st.maybe_compact();
    return new_count;
}

// Marks out_found[i] = 1 for fingerprints PRESENT in the store; a pure
// membership probe — nothing is inserted.  Unlike insert's out_new
// (first in-batch occurrence wins), EVERY occurrence of an in-store
// fingerprint is marked: callers read per-row verdicts (the device POR
// filter masks candidate rows individually).  Same probe machinery as
// insert: sort the batch, gallop a forward-only lower_bound per run.
void jaxmc_fps_contains(void* p, const uint64_t* hi, const uint64_t* lo,
                        uint64_t n, uint8_t* out_found) {
    Store& st = *static_cast<Store*>(p);
    std::memset(out_found, 0, n);
    if (n == 0) return;

    std::vector<uint64_t> order(n);
    for (uint64_t i = 0; i < n; ++i) order[i] = i;
    std::sort(order.begin(), order.end(), [&](uint64_t a, uint64_t b) {
        Fp fa{hi[a], lo[a]}, fb{hi[b], lo[b]};
        if (fa == fb) return a < b;
        return fa < fb;
    });

    std::vector<RunPtr> runs = st.snapshot();
    for (const auto& run : runs) {
        const Fp* rd = run->data;
        size_t rpos = 0;
        for (uint64_t k = 0; k < n; ++k) {
            uint64_t idx = order[k];
            if (out_found[idx]) continue;
            Fp f{hi[idx], lo[idx]};
            const Fp* it = std::lower_bound(rd + rpos, rd + run->n, f);
            rpos = (size_t)(it - rd);
            if (rpos >= run->n) break;
            if (rd[rpos] == f) out_found[idx] = 1;
        }
    }
}

// Copies the sorted store contents into hi/lo (each sized to count) —
// the checkpoint/resume serialization surface. Reuses merge_runs (the
// ONE k-way merge in this file) into a scratch anonymous run; an
// allocation failure leaves the output zeroed, which the python side's
// sorted-unique import check rejects loudly.
void jaxmc_fps_export(void* p, uint64_t* hi, uint64_t* lo) {
    Store& st = *static_cast<Store*>(p);
    st.join_merger();
    std::vector<RunPtr> runs = st.snapshot();
    int seq = 0;
    auto merged = merge_runs(runs, std::string(), 0, &seq);
    if (merged->data == nullptr) return;
    for (size_t i = 0; i < merged->n; ++i) {
        hi[i] = merged->data[i].hi;
        lo[i] = merged->data[i].lo;
    }
}

// Replaces the store contents with n fingerprints; input must be sorted
// and unique (the export format). Returns 1 on success, 0 when the
// ordering invariant does not hold (store left empty in that case).
uint64_t jaxmc_fps_import(void* p, const uint64_t* hi, const uint64_t* lo,
                          uint64_t n) {
    Store& st = *static_cast<Store*>(p);
    st.join_merger();
    std::lock_guard<std::mutex> g(st.mu);
    st.runs.clear();
    st.count = 0;
    auto run = alloc_run(n, st.spill_dir, st.spill_threshold, &st.seq);
    if (n > 0 && run->data == nullptr) return 0;
    for (uint64_t i = 0; i < n; ++i) {
        Fp f{hi[i], lo[i]};
        if (i > 0 && !(run->data[i - 1] < f)) {
            return 0;
        }
        run->data[i] = f;
    }
    if (n > 0) {
        st.runs.push_back(std::move(run));
        st.count = n;
    }
    return 1;
}

}  // extern "C"
