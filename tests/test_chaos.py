r"""Fault-injection chaos suite (ISSUE 4) — `make chaos` runs `-m chaos`.

End-to-end proof that jaxmc survives the failures long runs actually
hit, driven by the deterministic JAXMC_FAULTS registry (jaxmc/faults.py):

- a SIGKILLed pool worker: the chunk is requeued, the pool respawned,
  and state counts stay BYTE-IDENTICAL to the serial engine (the ISSUE 4
  acceptance run: worker_kill:level=2, --workers 4, specs/viewtoy.tla);
- exhausted retries degrade to serial expansion with `parallel.degraded`
  telemetry — and still-exact counts;
- a corrupted checkpoint is refused (exit 2), never half-resumed;
- device init failures retry; a terminal device failure demotes to the
  parallel CPU engine RESUMING from the host snapshot;
- SIGKILL of the whole run mid-level (serial / parallel / device):
  resume from the checkpoint reproduces the uninterrupted run's counts
  bit-identically (marked slow — kept out of tier-1 timing).
"""

import json
import multiprocessing
import os
import subprocess
import sys

import pytest

from jaxmc import faults, obs
from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer
from jaxmc.engine.parallel import ParallelExplorer, fork_available

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")

needs_fork = pytest.mark.skipif(not fork_available(),
                                reason="no fork start method")

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_faults(monkeypatch):
    monkeypatch.delenv("JAXMC_FAULTS", raising=False)
    monkeypatch.delenv("JAXMC_FAULTS_STATE", raising=False)
    faults._CACHE = None
    yield
    faults._CACHE = None


def load(spec, cfg=None):
    cfgp = cfg or os.path.splitext(spec)[0] + ".cfg"
    with open(cfgp) as fh:
        c = parse_cfg(fh.read())
    return bind_model(Loader([SPECS]).load_path(spec), c)


def _cli(args, env_extra=None, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    env.pop("JAXMC_FAULTS", None) if env_extra is None else None
    return subprocess.run([sys.executable, "-m", "jaxmc", "check"] + args,
                          capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=timeout)


def _counts(stdout):
    """(generated, distinct) from the CLI summary line."""
    for line in stdout.splitlines():
        if "states generated," in line and "distinct states found" in \
                line and "states/sec" in line:
            parts = line.split()
            return int(parts[0]), int(parts[3])
    raise AssertionError(f"no summary line in:\n{stdout}")


# ------------------------------------------------ parallel crash safety

@needs_fork
class TestWorkerCrash:
    def test_worker_kill_requeue_parity_acceptance(self, monkeypatch):
        # THE ISSUE 4 acceptance scenario, in-process: with
        # JAXMC_FAULTS=worker_kill:level=2 a --workers 4 run on
        # specs/viewtoy.tla completes with counts byte-identical to the
        # serial engine, and telemetry records the requeue/respawn
        rs = Explorer(load(os.path.join(SPECS, "viewtoy.tla"))).run()
        monkeypatch.setenv("JAXMC_FAULTS", "worker_kill:level=2")
        faults._CACHE = None
        tel = obs.Telemetry()
        with obs.use(tel):
            rp = ParallelExplorer(load(os.path.join(SPECS,
                                                    "viewtoy.tla")),
                                  workers=4).run()
        assert (rp.generated, rp.distinct, rp.diameter) == \
            (rs.generated, rs.distinct, rs.diameter)
        assert rp.ok == rs.ok
        assert tel.counters.get("parallel.worker_deaths") == 1
        assert tel.counters.get("parallel.respawns") == 1
        assert tel.counters.get("parallel.requeues", 0) >= 1
        # (faults.injected is counted in the KILLED worker's memory —
        # the parent-side proof of the firing is the worker_death above)
        # recovered, NOT degraded: the pool finished the run
        assert tel.gauges.get("parallel.degraded") is None

    def test_worker_kill_acceptance_via_cli(self, tmp_path):
        # the same scenario through the CLI (what the driver runs),
        # with the requeue/respawn telemetry in the metrics artifact
        spec = os.path.join(SPECS, "viewtoy.tla")
        r_serial = _cli([spec, "--workers", "1"], env_extra={})
        assert r_serial.returncode == 0, r_serial.stderr
        m = str(tmp_path / "m.json")
        r_par = _cli([spec, "--workers", "4", "--metrics-out", m],
                     env_extra={"JAXMC_FAULTS": "worker_kill:level=2"})
        assert r_par.returncode == 0, r_par.stderr
        assert _counts(r_par.stdout) == _counts(r_serial.stdout)
        art = json.load(open(m))
        assert art["counters"].get("parallel.worker_deaths") == 1
        assert art["counters"].get("parallel.respawns") == 1
        assert art["gauges"].get("parallel.degraded") is None

    def test_repeated_kills_exhaust_budget_and_degrade(self, monkeypatch):
        # every respawned worker dies on the same chunk -> after the
        # bounded retry budget the run degrades to serial expansion,
        # with the degradation recorded — and counts STILL exact
        rs = Explorer(load(os.path.join(SPECS, "viewtoy.tla"))).run()
        monkeypatch.setenv("JAXMC_FAULTS", "worker_kill:level=1:n=99")
        faults._CACHE = None
        tel = obs.Telemetry()
        with obs.use(tel):
            rp = ParallelExplorer(load(os.path.join(SPECS,
                                                    "viewtoy.tla")),
                                  workers=2).run()
        assert (rp.generated, rp.distinct) == (rs.generated, rs.distinct)
        assert tel.gauges.get("parallel.degraded")
        assert "retry budget exhausted" in tel.gauges["parallel.degraded"]
        assert tel.counters.get("parallel.degradations") == 1

    def test_transient_chunk_error_retried_inline(self, monkeypatch):
        rs = Explorer(load(os.path.join(SPECS, "constoy.tla"))).run()
        monkeypatch.setenv("JAXMC_FAULTS", "chunk_error:level=1")
        faults._CACHE = None
        tel = obs.Telemetry()
        with obs.use(tel):
            rp = ParallelExplorer(load(os.path.join(SPECS,
                                                    "constoy.tla")),
                                  workers=2).run()
        assert (rp.generated, rp.distinct) == (rs.generated, rs.distinct)
        assert tel.counters.get("parallel.chunk_retries") == 1
        assert tel.gauges.get("parallel.degraded") is None

    def test_no_orphan_processes_after_crashy_run(self, monkeypatch):
        monkeypatch.setenv("JAXMC_FAULTS", "worker_kill:level=2")
        faults._CACHE = None
        ParallelExplorer(load(os.path.join(SPECS, "viewtoy.tla")),
                         workers=3).run()
        assert multiprocessing.active_children() == []


# --------------------------------------------------- checkpoint faults

class TestCheckpointCorruption:
    def test_ckpt_corrupt_fault_rejected_on_resume(self, tmp_path):
        # the harness corrupts every checkpoint write; the resume must
        # refuse with exit 2 + a one-line diagnosis (acceptance: never
        # a traceback, never a silently-wrong resume)
        ck = str(tmp_path / "c.ck")
        spec = os.path.join(SPECS, "constoy.tla")
        r1 = _cli([spec, "--max-states", "10", "--checkpoint", ck,
                   "--checkpoint-every", "0", "--quiet"],
                  env_extra={"JAXMC_FAULTS": "ckpt_corrupt:n=1000"})
        assert r1.returncode == 0, r1.stderr
        assert os.path.exists(ck)
        r2 = _cli([spec, "--resume", ck, "--quiet"], env_extra={})
        assert r2.returncode == 2
        assert "cannot resume" in r2.stderr
        assert "Traceback" not in r2.stderr

    def test_ckpt_corrupt_flip_mode(self, tmp_path, monkeypatch):
        from jaxmc.engine.ckpt import CkptError, write_checkpoint, \
            load_checkpoint
        monkeypatch.setenv("JAXMC_FAULTS", "ckpt_corrupt:mode=flip")
        monkeypatch.setenv("JAXMC_FAULTS_STATE",
                           str(tmp_path / "fstate"))
        os.makedirs(str(tmp_path / "fstate"))
        faults._CACHE = None
        p = str(tmp_path / "c.ck")
        write_checkpoint(p, "interp", {}, {"blob": b"z" * 4096})
        with pytest.raises(CkptError):
            load_checkpoint(p)


# ------------------------------------------------- device fault paths

class TestDeviceFaults:
    def test_device_init_fail_retries_then_succeeds(self, tmp_path):
        m = str(tmp_path / "m.json")
        r = _cli([os.path.join(SPECS, "constoy.tla"), "--backend", "jax",
                  "--quiet", "--metrics-out", m],
                 env_extra={"JAXMC_FAULTS": "device_init_fail:n=2"})
        assert r.returncode == 0, r.stderr
        art = json.load(open(m))
        assert art["counters"].get("device.init_retries") == 2
        assert art["gauges"].get("device.demoted") is None

    def test_terminal_device_failure_demotes_with_snapshot(self,
                                                           tmp_path):
        # ISSUE 4 tentpole (4): on terminal device failure the run falls
        # back to the parallel CPU engine RESUMING from the last host
        # snapshot, completes with the interp's exact counts, and the
        # demotion is machine-readable (device.demoted — obs diff flags
        # its appearance)
        spec = os.path.join(SPECS, "constoy.tla")
        r_interp = _cli([spec], env_extra={})
        assert r_interp.returncode == 0
        ck = str(tmp_path / "c.ck")
        m = str(tmp_path / "m.json")
        r = _cli([spec, "--backend", "jax", "--checkpoint", ck,
                  "--checkpoint-every", "0", "--metrics-out", m],
                 env_extra={"JAXMC_FAULTS": "device_run_fail:level=2"})
        assert r.returncode == 0, r.stderr
        assert _counts(r.stdout) == _counts(r_interp.stdout)
        assert "falling back to the parallel CPU engine" in r.stderr
        assert "resuming from host snapshot" in r.stderr
        assert "completed on the parallel CPU engine" in r.stdout
        art = json.load(open(m))
        assert art["gauges"].get("device.demoted")
        assert art["counters"].get("device.demotions") == 1
        # obs diff raises a REGRESS flag when the demotion appears
        m_clean = str(tmp_path / "m0.json")
        r0 = _cli([spec, "--backend", "jax", "--quiet",
                   "--metrics-out", m_clean], env_extra={})
        assert r0.returncode == 0, r0.stderr
        d = subprocess.run(
            [sys.executable, "-m", "jaxmc.obs", "diff",
             "--fail-on-regress", "--threshold", "10000",
             m_clean, m], capture_output=True, text=True, cwd=REPO)
        assert d.returncode == 1
        assert "REGRESS device demotion" in d.stdout

    def test_no_device_fallback_flag_exits(self, tmp_path):
        r = _cli([os.path.join(SPECS, "constoy.tla"), "--backend", "jax",
                  "--no-device-fallback", "--quiet"],
                 env_extra={"JAXMC_FAULTS": "device_run_fail:level=1"})
        assert r.returncode == 2
        assert "injected fault: device_run_fail" in r.stderr


# --------------------------------------- kill/resume parity (satellite)

@pytest.mark.slow
class TestKillResumeParity:
    """SIGKILL a run mid-level, resume from the checkpoint, and pin the
    final counts + diameter bit-identical to an uninterrupted run —
    serial, parallel, and simulated-device (jax on CPU)."""

    def _kill_resume(self, extra_args, tmp_path, backend_tag):
        spec = os.path.join(SPECS, "constoy.tla")
        clean = _cli([spec] + extra_args, env_extra={})
        assert clean.returncode == 0, clean.stderr
        ck = str(tmp_path / f"{backend_tag}.ck")
        killed = _cli([spec] + extra_args +
                      ["--checkpoint", ck, "--checkpoint-every", "0",
                       "--quiet"],
                      env_extra={"JAXMC_FAULTS": "run_kill:level=3"})
        assert killed.returncode == -9 or killed.returncode == 137, \
            (killed.returncode, killed.stderr)
        assert os.path.exists(ck), "no checkpoint survived the kill"
        resumed = _cli([spec] + extra_args + ["--resume", ck],
                       env_extra={})
        assert resumed.returncode == 0, resumed.stderr
        assert _counts(resumed.stdout) == _counts(clean.stdout)
        # the depth line is printed by the engines on completion
        depth_clean = [ln for ln in clean.stdout.splitlines()
                       if "depth of the complete state graph" in ln]
        depth_res = [ln for ln in resumed.stdout.splitlines()
                     if "depth of the complete state graph" in ln]
        assert depth_res == depth_clean

    def test_serial_kill_resume(self, tmp_path):
        self._kill_resume(["--workers", "1"], tmp_path, "serial")

    @needs_fork
    def test_parallel_kill_resume(self, tmp_path):
        self._kill_resume(["--workers", "3"], tmp_path, "parallel")

    def test_device_kill_resume(self, tmp_path):
        self._kill_resume(["--backend", "jax"], tmp_path, "device")

    @needs_fork
    def test_parallel_resumes_serial_kill(self, tmp_path):
        # cross-engine: a checkpoint left by a SIGKILLed serial run
        # resumes on the parallel engine (no fallback) with exact counts
        spec = os.path.join(SPECS, "constoy.tla")
        clean = _cli([spec, "--workers", "1"], env_extra={})
        ck = str(tmp_path / "x.ck")
        _cli([spec, "--workers", "1", "--checkpoint", ck,
              "--checkpoint-every", "0", "--quiet"],
             env_extra={"JAXMC_FAULTS": "run_kill:level=3"})
        assert os.path.exists(ck)
        m = str(tmp_path / "m.json")
        resumed = _cli([spec, "--workers", "3", "--resume", ck,
                        "--metrics-out", m], env_extra={})
        assert resumed.returncode == 0, resumed.stderr
        assert _counts(resumed.stdout) == _counts(clean.stdout)
        art = json.load(open(m))
        assert art["gauges"].get("parallel.fallback_reason") is None
        assert art["gauges"].get("parallel.workers") == 3


# ------------------------------------------ trace-context chaos (ISSUE 16)

@needs_fork
class TestTraceContextChaos:
    """PR-16: the fleet trace survives the chaos matrix.  A worker that
    is SIGKILLed and respawned rejoins the run's ORIGINAL trace_id
    (fresh pid+span, same tid), and a SIGTERM-drained run plus its
    resume both stitch under the JAXMC_TRACE_CTX they inherited — in
    every case `obs timeline` reconstructs the fleet with zero orphan
    spans."""

    def test_worker_kill_respawn_keeps_trace_id(self, monkeypatch,
                                                tmp_path):
        import io
        from jaxmc.obs import context
        from jaxmc.obs.report import main as obs_main
        monkeypatch.setenv("JAXMC_FAULTS", "worker_kill:level=2")
        faults._CACHE = None
        context.reset()
        trace = str(tmp_path / "kill.trace.jsonl")
        tel = obs.Telemetry(trace_path=trace)
        with obs.use(tel):
            rp = ParallelExplorer(load(os.path.join(SPECS,
                                                    "viewtoy.tla")),
                                  workers=4).run()
        assert rp.ok
        assert tel.counters.get("parallel.worker_deaths") == 1
        assert tel.counters.get("parallel.respawns") == 1
        events = [json.loads(ln) for ln in open(trace)]
        run_tid = context.get().trace_id
        # one trace_id across the whole run — including every event
        # recorded AFTER the kill/respawn cycle
        assert {e.get("tid") for e in events} == {run_tid}
        spans = [e for e in events
                 if e.get("ev") == "parallel.worker_span"]
        # every worker (original or respawned) holds a DISTINCT
        # pid+span, all parented on the run's own span
        assert len(spans) >= 2, spans
        assert len({s["pid"] for s in spans}) == len(spans)
        assert len({s["span"] for s in spans}) == len(spans)
        assert all(s["parent"] == events[0]["psid"] for s in spans)
        buf = io.StringIO()
        rc = obs_main(["timeline", "--fail-on-orphans", trace],
                      out=buf)
        out = buf.getvalue()
        assert rc == 0, out
        assert "orphans=0" in out

    @pytest.mark.slow
    def test_sigterm_drain_and_resume_share_trace(self, tmp_path):
        # a SIGTERM-drained run checkpoints AND leaves a trace stitched
        # under the JAXMC_TRACE_CTX it inherited; the resume, handed
        # the same context, joins the SAME fleet trace — the conductor
        # lane plus both run lanes merge with zero orphans
        import io
        import signal
        import time
        from jaxmc.obs.report import main as obs_main
        from jaxmc.tracecheck import _SLOW_CFG, _SLOW_SPEC

        spec = str(tmp_path / "traceload.tla")
        with open(spec, "w") as fh:
            fh.write(_SLOW_SPEC.format(q=800, bound=15))
        with open(str(tmp_path / "traceload.cfg"), "w") as fh:
            fh.write(_SLOW_CFG)
        parent_tid, parent_span = "ab" * 8, "cd" * 8
        env = {"JAXMC_TRACE_CTX": f"{parent_tid}:{parent_span}"}
        ck = str(tmp_path / "drain.ck")
        t1 = str(tmp_path / "one.trace.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-m", "jaxmc", "check", spec,
             "--workers", "1", "--trace", t1, "--checkpoint", ck,
             "--checkpoint-every", "0"],
            env=dict(os.environ, JAX_PLATFORMS="cpu", **env),
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        deadline = time.time() + 120
        while not (os.path.exists(t1) and os.path.getsize(t1) > 0):
            assert proc.poll() is None, proc.communicate()[1]
            assert time.time() < deadline, "child never wrote a trace"
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=120)
        assert proc.returncode == 143, (proc.returncode, err)
        assert os.path.exists(ck), "the drain left no checkpoint"
        t2 = str(tmp_path / "two.trace.jsonl")
        resumed = _cli([spec, "--workers", "1", "--resume", ck,
                        "--trace", t2], env_extra=env)
        assert resumed.returncode == 0, resumed.stderr
        ev1 = [json.loads(ln) for ln in open(t1)]
        ev2 = [json.loads(ln) for ln in open(t2)]
        assert {e.get("tid") for e in ev1 + ev2} == {parent_tid}
        assert ev1[0]["parent_span"] == parent_span
        assert ev2[0]["parent_span"] == parent_span
        # a one-line conductor lane makes the inherited parent span
        # resolvable, exactly as a bench/serve parent's trace would
        parent_trace = str(tmp_path / "parent.trace.jsonl")
        with open(parent_trace, "w") as fh:
            fh.write(json.dumps({
                "ev": "proc_meta", "t": ev1[0]["t"] - 1.0, "mono": 0.0,
                "pid": 1, "argv": ["conductor"], "psid": parent_span,
                "parent_span": None, "env": {},
                "tid": parent_tid}) + "\n")
        buf = io.StringIO()
        rc = obs_main(["timeline", "--fail-on-orphans", parent_trace,
                       t1, t2], out=buf)
        out = buf.getvalue()
        assert rc == 0, out
        assert "orphans=0" in out
        assert "processes=3" in out


# ------------------------------------------------ fleet serving chaos

@pytest.mark.slow
class TestFleetChaos:
    """ISSUE 19: subprocess daemons sharing one durable spool, under
    the daemon_kill / lease_stall fault sites.  Slow-marked (multi-
    second subprocess scenarios) — `make fleet-check` runs the full
    acceptance versions; these pin the two leg shapes as tests."""

    def _start_daemon(self, spool, extra_env=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
        return subprocess.Popen(
            [sys.executable, "-m", "jaxmc.serve", "run", "--spool",
             spool, "--workers", "1", "--quiet"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    def _heartbeat(self, spool, pid, timeout=120):
        """This pid's heartbeat record (carries its id + bound port)."""
        import glob
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            for path in glob.glob(os.path.join(spool, "daemons",
                                               "*.json")):
                try:
                    with open(path) as fh:
                        rec = json.load(fh)
                except (OSError, ValueError):
                    continue
                if rec.get("pid") == pid:
                    return rec
            time.sleep(0.1)
        raise AssertionError(f"daemon pid {pid} never heartbeated")

    def test_daemon_sigkill_mid_vbatch_cohort_reforms(self, tmp_path):
        # the daemon that popped a 4-member layout-compat cohort
        # SIGKILLs itself right after marking the members running
        # (daemon_kill kind=vbatch); the next daemon life must steal
        # the expired leases, RE-FORM the cohort, and answer every
        # member with counts identical to solo runs
        import time
        from jaxmc.serve import JobQueue
        from jaxmc.serve.protocol import build_config, job_signature
        from jaxmc.session import batch_profile

        spool = str(tmp_path / "spool")
        bt = os.path.join(SPECS, "batchtoy.tla")
        opts = {"backend": "jax", "platform": "cpu", "host_seen": True}
        q = JobQueue(spool)
        jids = []
        for v in ("a", "b", "c", "d"):
            cfg = build_config(bt, os.path.join(
                SPECS, f"batchtoy_{v}.cfg"), opts)
            prof = batch_profile(cfg)
            job = q.new_job(cfg.spec, cfg.cfg, opts,
                            job_signature(cfg),
                            bsig=prof.bsig if prof else None,
                            cost_estimate=prof.cost_estimate
                            if prof else None)
            jids.append(job["id"])

        a = self._start_daemon(spool, {
            "JAXMC_FAULTS": "daemon_kill:kind=vbatch:n=1",
            "JAXMC_LEASE_TTL": "1.0"})
        a.wait(timeout=240)
        assert a.returncode in (-9, 137), \
            f"daemon A exited {a.returncode}, expected the injected " \
            f"SIGKILL"

        b = self._start_daemon(spool, {"JAXMC_LEASE_TTL": "1.0"})
        try:
            rec_b = self._heartbeat(spool, b.pid)
            recs = {}
            deadline = time.time() + 300
            while time.time() < deadline and len(recs) < len(jids):
                assert b.poll() is None, "daemon B died"
                for j in jids:
                    rec = q.load(j)
                    if rec and rec.get("status") == "done":
                        recs[j] = rec
                time.sleep(0.2)
            assert len(recs) == len(jids), \
                f"only {sorted(recs)} of {jids} finished"
            for v, j in zip(("a", "b", "c", "d"), jids):
                solo = _cli([bt, "--cfg",
                             os.path.join(SPECS, f"batchtoy_{v}.cfg"),
                             "--quiet"])
                assert solo.returncode == 0, solo.stderr
                gen, dis = _counts(solo.stdout)
                rec = recs[j]
                assert rec["daemon"] == rec_b["id"]
                assert rec.get("stolen_by") == rec_b["id"]
                assert (rec["generated"], rec["distinct"]) == \
                    (gen, dis), f"member {v} diverged after takeover"
                # the cohort RE-FORMED (members ran batched, not solo)
                assert rec.get("batch_occupancy", 1) >= 2, \
                    f"member {v} ran solo after the steal " \
                    f"(occupancy {rec.get('batch_occupancy')})"
        finally:
            b.terminate()
            try:
                b.wait(timeout=60)
            except subprocess.TimeoutExpired:
                b.kill()

    def test_lease_stall_double_claim_single_winner(self, tmp_path):
        # daemon A claims a slow job but its fleet loop stalls
        # (lease_stall): no renewals, no heartbeats, while its worker
        # keeps running.  Peer B must steal the expired lease and win;
        # A must DROP its late result (serve.lease_lost_drops) so
        # exactly one daemon publishes
        import time
        import urllib.request
        from jaxmc.serve import JobQueue
        from jaxmc.serve.protocol import ServeClient
        from jaxmc.tracecheck import _SLOW_CFG, _SLOW_SPEC

        spec = str(tmp_path / "stallload.tla")
        with open(spec, "w") as fh:
            fh.write(_SLOW_SPEC.format(q=1500, bound=20)
                     .replace("MODULE traceload", "MODULE stallload"))
        with open(str(tmp_path / "stallload.cfg"), "w") as fh:
            fh.write(_SLOW_CFG)
        solo = _cli([spec, "--quiet"])
        assert solo.returncode == 0, solo.stderr
        ref = _counts(solo.stdout)

        spool = str(tmp_path / "spool")
        a = self._start_daemon(spool, {
            "JAXMC_FAULTS": "lease_stall:n=999",
            "JAXMC_LEASE_TTL": "1.0"})
        b = None
        try:
            rec_a = self._heartbeat(spool, a.pid)
            client = ServeClient(rec_a.get("host", "127.0.0.1"),
                                 rec_a["port"])
            code, job = client.submit(spec, None,
                                      {"backend": "interp"})
            assert code == 200, f"submit failed ({code}): {job}"
            jid = job["id"]
            q = JobQueue(spool)
            deadline = time.time() + 120
            while time.time() < deadline:
                rec = q.load(jid) or {}
                if rec.get("status") == "running" and \
                        rec.get("daemon") == rec_a["id"]:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(f"A never claimed {jid}")

            b = self._start_daemon(spool, {
                "JAXMC_LEASE_TTL": "1.0",
                "JAXMC_LEASE_AFFINITY_GRACE": "0.1"})
            rec_b = self._heartbeat(spool, b.pid)
            deadline = time.time() + 240
            while time.time() < deadline:
                rec = q.load(jid) or {}
                if rec.get("status") == "done":
                    break
                time.sleep(0.2)
            assert rec.get("status") == "done", \
                f"job ended {rec.get('status')!r}"
            # exactly one winner: B, through the lease steal
            assert rec["daemon"] == rec_b["id"]
            assert rec.get("stolen_by") == rec_b["id"]
            assert "stolen" in rec.get("requeue_note", "")
            assert (rec["generated"], rec["distinct"]) == ref
            # the stalled loser must DROP its late copy at publish
            # time (the fleet tick that counts serve.lease_lost is
            # exactly what the stall suppresses, so the ownership
            # check in _publishable is the arbitration under test)
            deadline = time.time() + 120
            stalls = drops = 0.0
            while time.time() < deadline:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{rec_a['port']}/metrics",
                        timeout=10) as resp:
                    text = resp.read().decode()
                vals = {}
                for ln in text.splitlines():
                    if ln.startswith("jaxmc_serve_lease_"):
                        name, _, v = ln.rpartition(" ")
                        vals[name] = float(v)
                stalls = vals.get("jaxmc_serve_lease_stalls", 0.0)
                drops = vals.get("jaxmc_serve_lease_lost_drops", 0.0)
                if stalls >= 1 and drops >= 1:
                    break
                time.sleep(0.5)
            assert stalls >= 1, "the lease_stall fault never fired"
            assert drops >= 1, "stalled daemon published a stolen " \
                               "job's result — two winners"
        finally:
            for p in (a, b):
                if p is None:
                    continue
                p.terminate()
                try:
                    p.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    p.kill()
