r"""Persistent run ledger (ISSUE 17, jaxmc/obs/ledger.py): append /
flock-concurrency / torn-line tolerance, artifact backfill over the
COMMITTED BENCH_r* + MULTICHIP_r* history, trajectory rendering via
`python -m jaxmc.obs history`, and the --fail-on-regress gate firing
(exit 1) on a synthesized degraded run.

Pure stdlib + tmp ledgers throughout — conftest pins JAXMC_LEDGER=off
so nothing here (or anywhere in the suite) touches ~/.cache/jaxmc.
"""

import io
import json
import os
import threading

import pytest

from jaxmc.obs import ledger
from jaxmc.obs.report import main as obs_main

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_summary(rate=5000.0, ts=1000.0, platform="cpu", env=None):
    """A minimal jaxmc.metrics summary with a computable states/sec."""
    wall = 2.0
    return {
        "schema": "jaxmc.metrics/4", "started_at": ts,
        "phases": [{"name": "search", "wall_s": wall}],
        "counters": {}, "gauges": {}, "levels": [],
        "env": dict({"platform": platform}, **(env or {})),
        "result": {"ok": True, "generated": int(rate * wall),
                   "distinct": 10, "diameter": 3, "truncated": False,
                   "wall_s": wall},
    }


class TestPathResolution:
    def test_env_off_values_disable(self, monkeypatch):
        for v in ("off", "0", "no", "NONE", " disabled "):
            monkeypatch.setenv("JAXMC_LEDGER", v)
            assert ledger.ledger_path() is None
        monkeypatch.setenv("JAXMC_LEDGER", "/tmp/x.jsonl")
        assert ledger.ledger_path() == "/tmp/x.jsonl"
        # explicit arg beats the env
        assert ledger.ledger_path("/tmp/y.jsonl") == "/tmp/y.jsonl"

    def test_append_summary_disabled_returns_false(self, monkeypatch):
        monkeypatch.setenv("JAXMC_LEDGER", "off")
        assert ledger.append_summary(mk_summary()) is False


class TestAppendRead:
    def test_roundtrip_and_rung_derivation(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        assert ledger.append_summary(
            mk_summary(rate=4000.0), source="/x/warm_leg.json",
            path=lp) is True
        (e,) = ledger.read_entries(lp)
        assert e["rung"] == "warm_leg"
        assert e["states_per_sec"] == pytest.approx(4000.0)
        assert e["platform"] == "cpu" and e["id"]

    def test_no_rate_no_entry(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        s = mk_summary()
        del s["result"]  # trace-only / failed run: no trajectory point
        assert ledger.append_summary(s, path=lp) is False
        assert not os.path.exists(lp)

    def test_torn_tail_and_duplicate_ids_tolerated(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        e = ledger.make_entry("r", 100.0, 1.0)
        ledger.append_entries([e, e], lp)  # same content twice
        with open(lp, "a") as fh:
            fh.write('{"rung": "torn", "states_per_')  # crashed writer
        ents = ledger.read_entries(lp)
        assert len(ents) == 1 and ents[0]["rung"] == "r"

    def test_concurrent_appends_no_torn_lines(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        n_threads, per = 8, 25

        def worker(k):
            for i in range(per):
                ledger.append_entries(
                    [ledger.make_entry(f"t{k}", 1.0 * i, float(i))],
                    lp)

        ts = [threading.Thread(target=worker, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        with open(lp) as fh:
            lines = [ln for ln in fh if ln.strip()]
        assert len(lines) == n_threads * per
        for ln in lines:
            json.loads(ln)  # every line parses: no interleaving
        assert len(ledger.read_entries(lp)) == n_threads * per


class TestBackfill:
    def test_import_committed_history_idempotent(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        pats = [os.path.join(REPO, "BENCH_r*.json"),
                os.path.join(REPO, "MULTICHIP_r*.json")]
        skipped = []
        n = ledger.import_artifacts(pats, lp, skipped=skipped)
        assert n > 0
        ents = ledger.read_entries(lp)
        assert len(ents) == n
        # bench runs land on the shared "bench" rung; multichip curve
        # points land on per-(rung, D) keys like transfer_scaled@D2
        rungs = {e["rung"] for e in ents}
        assert "bench" in rungs
        assert any("@D" in r for r in rungs), rungs
        # pre-/1 multichip artifacts and dead bench runs are recorded
        # as skips, never import failures
        assert all(":" in s for s in skipped)
        # content addressing: the same import is a no-op
        assert ledger.import_artifacts(pats, lp) == 0
        assert len(ledger.read_entries(lp)) == n

    def test_unparseable_artifact_skips_not_raises(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text('{"schema": "nope"}')
        lp = str(tmp_path / "ledger.jsonl")
        skipped = []
        assert ledger.import_artifacts([str(bad)], lp,
                                       skipped=skipped) == 0
        assert len(skipped) == 1 and "bad.json" in skipped[0]


class TestTrajectoryFlags:
    def rows(self, rates, rung="r"):
        return [ledger.make_entry(rung, v, float(i), run=f"run{i}")
                for i, v in enumerate(rates)]

    def test_latest_only_is_judged(self):
        # a historical dip that later runs recovered from must NOT flag
        assert ledger.flag_latest(self.rows([100, 20, 110]),
                                  25.0, 5) is None
        flag = ledger.flag_latest(self.rows([100, 110, 20]), 25.0, 5)
        assert flag and flag.startswith("REGRESS")
        assert "run2" in flag and "-81.8%" in flag

    def test_window_bounds_the_reference(self):
        # the 1000 is outside the 2-run window: no flag vs best-of-2
        assert ledger.flag_latest(self.rows([1000, 90, 100, 95]),
                                  25.0, 2) is None

    def test_env_change_attribution_rides_the_flag(self):
        rows = self.rows([100, 100])
        rows[0]["env"] = {"jax_version": "0.4.1", "platform": "cpu"}
        rows[-1]["env"] = {"jax_version": "0.5.0", "platform": "cpu"}
        rows[-1]["states_per_sec"] = 10.0
        flag = ledger.flag_latest(rows, 25.0, 5)
        assert "env changed" in flag
        assert "jax_version: 0.4.1 -> 0.5.0" in flag


class TestHistoryCli:
    def _seed(self, tmp_path, rates):
        lp = str(tmp_path / "ledger.jsonl")
        ledger.append_entries(
            [ledger.make_entry("warm_leg", v, float(i), run=f"r{i:02d}")
             for i, v in enumerate(rates)], lp)
        return lp

    def test_renders_trajectory_table(self, tmp_path):
        lp = self._seed(tmp_path, [4000, 4400, 4200])
        buf = io.StringIO()
        rc = obs_main(["history", "--ledger", lp], out=buf)
        out = buf.getvalue()
        assert rc == 0
        assert "warm_leg" in out
        assert "4,000 -> 4,400 -> 4,200" in out
        assert "no regressions flagged" in out

    def test_fail_on_regress_exit_1_on_degraded_run(self, tmp_path):
        lp = self._seed(tmp_path, [4000, 4400, 1000])
        buf = io.StringIO()
        rc = obs_main(["history", "--ledger", lp,
                       "--fail-on-regress"], out=buf)
        assert rc == 1
        assert "REGRESS states/sec warm_leg" in buf.getvalue()
        # without the gate flag the same history renders rc 0
        assert obs_main(["history", "--ledger", lp],
                        out=io.StringIO()) == 0

    def test_import_then_render_one_invocation(self, tmp_path):
        art = tmp_path / "warm_leg.json"
        art.write_text(json.dumps(mk_summary(rate=3000.0)))
        lp = str(tmp_path / "ledger.jsonl")
        buf = io.StringIO()
        rc = obs_main(["history", "--ledger", lp,
                       "--import", str(art)], out=buf)
        out = buf.getvalue()
        assert rc == 0
        assert "imported 1 new entry" in out
        assert "warm_leg" in out and "3,000" in out

    def test_rung_filter(self, tmp_path):
        lp = str(tmp_path / "ledger.jsonl")
        ledger.append_entries([ledger.make_entry("a", 1.0, 1.0),
                               ledger.make_entry("b", 2.0, 1.0)], lp)
        buf = io.StringIO()
        assert obs_main(["history", "--ledger", lp, "--rung", "a"],
                        out=buf) == 0
        out = buf.getvalue()
        assert "a" in out and "\n  b " not in out
