r"""Watchdog (jaxmc/obs/watchdog.py) tests: heartbeat events, stall
detection on a synthetic wedged span, episode semantics, and the
median-level stall threshold.

Deterministic and tier-1 fast: the per-beat body (`Watchdog._tick`) is
driven directly with a fake clock — no sleeps, no jax; one short
real-thread test pins the daemon wiring.
"""

import json
import time

import pytest

from jaxmc import obs

pytestmark = pytest.mark.obs


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk(tmp_path, **kw):
    """(telemetry, watchdog, clock, trace_path, stall_msgs)."""
    clk = Clock()
    trace = tmp_path / "trace.jsonl"
    tel = obs.Telemetry(trace_path=str(trace), clock=clk)
    msgs = []
    wd = obs.Watchdog(tel, clock=clk, on_stall=msgs.append,
                      **dict({"interval": 5.0, "stall_factor": 4.0,
                              "min_stall_s": 30.0}, **kw))
    return tel, wd, clk, trace, msgs


def events(trace):
    with open(trace) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


class TestHeartbeat:
    def test_heartbeat_event_validates_and_names_open_span(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)
        h = tel.span("device_init", platform="tpu")
        h.__enter__()
        tel.level(0, frontier=3, wall_s=0.5)
        clk.t += 5
        wd._tick(clk.t)
        h.done()
        evs = events(trace)
        for e in evs:
            obs.validate_trace_event(e)
        (hb,) = [e for e in evs if e["ev"] == "heartbeat"]
        assert hb["open_spans"] == ["device_init"]
        assert hb["last_level"] == 0
        assert hb["wall_s"] == 5
        assert hb["progress_seq"] >= 2
        assert hb["rss_bytes"] is None or hb["rss_bytes"] > 0
        assert tel.counters["watchdog.heartbeats"] == 1
        assert not msgs  # 5s of quiet is not a stall

    def test_daemon_thread_beats_for_real(self, tmp_path):
        tel = obs.Telemetry(trace_path=str(tmp_path / "t.jsonl"))
        wd = obs.Watchdog(tel, interval=0.02, min_stall_s=30.0)
        wd.start()
        deadline = time.time() + 2.0
        while time.time() < deadline and \
                tel.counters.get("watchdog.heartbeats", 0) < 2:
            time.sleep(0.02)
        wd.stop()
        tel.close()
        assert tel.counters.get("watchdog.heartbeats", 0) >= 2

    def test_null_telemetry_never_starts(self):
        wd = obs.Watchdog(obs.NullTelemetry())
        assert wd.start() is wd
        assert wd._thread is None
        wd.stop()  # no-op, no crash


class TestStall:
    def test_synthetic_wedged_span_triggers_stall(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)
        h = tel.span("device_init", platform="tpu")
        h.__enter__()
        wd._tick(clk.t)  # latch: the span-open counts as progress
        clk.t += 31      # ... then 31s of silence beats the 30s floor
        wd._tick(clk.t)
        h.done()
        evs = events(trace)
        for e in evs:
            obs.validate_trace_event(e)
        (st,) = [e for e in evs if e["ev"] == "stall"]
        assert st["open_spans"] == ["device_init"]
        assert st["stalled_for_s"] >= 30
        assert st["threshold_s"] == 30
        assert st["last_level"] is None
        assert tel.counters["watchdog.stalls"] == 1
        assert len(msgs) == 1 and "device_init" in msgs[0]

    def test_one_stall_event_per_episode_highwater_tracks(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)
        tel.span("search").__enter__()
        wd._tick(clk.t)  # latch
        clk.t += 31
        wd._tick(clk.t)
        clk.t += 40  # still wedged: no second stall event, deeper water
        wd._tick(clk.t)
        evs = events(trace)
        assert len([e for e in evs if e["ev"] == "stall"]) == 1
        assert len([e for e in evs if e["ev"] == "heartbeat"]) == 3
        assert tel.counters["watchdog.stalls"] == 1
        assert tel.gauges["watchdog.max_stall_s"] >= 71

    def test_progress_ends_episode_and_rearms(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)
        with tel.span("search"):
            wd._tick(clk.t)          # latch
            clk.t += 31
            wd._tick(clk.t)          # episode 1
            tel.level(0, wall_s=1.0)  # progress: episode over
            clk.t += 1
            wd._tick(clk.t)
            assert not wd._stalled
            clk.t += 31              # quiet again: episode 2
            wd._tick(clk.t)
            assert wd._stalled
        evs = events(trace)
        assert len([e for e in evs if e["ev"] == "stall"]) == 2
        assert tel.counters["watchdog.stalls"] == 2

    def test_threshold_follows_median_level_wall(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)
        # fast levels: the 30s floor governs
        assert wd.stall_threshold_s([0.5, 1.0, 2.0]) == 30.0
        # slow levels: factor * median governs (4 * 20 = 80)
        assert wd.stall_threshold_s([10.0, 20.0, 30.0]) == 80.0
        assert wd.stall_threshold_s([]) == 30.0
        # integration: with recorded slow levels a 31s gap is NOT a stall
        for i, w in enumerate((10.0, 20.0, 30.0)):
            tel.level(i, wall_s=w)
        wd._tick(clk.t)  # latch
        clk.t += 31
        wd._tick(clk.t)
        assert "watchdog.stalls" not in tel.counters
        clk.t += 50  # 81s total beats the 80s threshold
        wd._tick(clk.t)
        assert tel.counters["watchdog.stalls"] == 1
        (st,) = [e for e in events(trace) if e["ev"] == "stall"]
        assert st["median_level_s"] == 20.0

    def test_tick_never_raises(self, tmp_path):
        tel, wd, clk, trace, msgs = mk(tmp_path)

        def boom(m):
            raise RuntimeError("stall callback exploded")

        wd.on_stall = boom
        tel.span("search").__enter__()
        wd._tick(clk.t)  # latch
        clk.t += 31
        wd._tick(clk.t)  # callback error swallowed
        assert tel.counters["watchdog.stalls"] == 1
