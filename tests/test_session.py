r"""CheckSession (jaxmc/session.py): the resumable parse -> compile ->
explore session core under the `check` CLI and the serve daemon.

Pins the ISSUE 7 refactor contract:
  - stage-by-stage results match the engines driven directly (the
    byte-identical-CLI guarantee reduces to this: cli.py renders the
    same CheckResult the engines always produced);
  - stages are ordered, idempotent, and auto-chain;
  - a session resumes mid-search from a checkpoint (truncate -> resume
    parity) and replays a COMPLETED run's final checkpoint instantly;
  - cooperative drain (jaxmc/drain.py): the engine checkpoints at a
    safe boundary, flags the result drained, and the CLI exits 143
    with spans closed — the graceful-shutdown satellite.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jaxmc import drain, obs
from jaxmc.engine.explore import Explorer
from jaxmc.session import CheckSession, SessionConfig, load_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def spec(name):
    return os.path.join(SPECS, f"{name}.tla")


def session(name, **kw):
    return CheckSession(SessionConfig(spec=spec(name), **kw))


@pytest.fixture(autouse=True)
def _clean_drain():
    drain.clear()
    yield
    drain.clear()


class TestStages:
    def test_stage_order_and_idempotence(self):
        s = session("constoy", workers=1)
        assert s.stage is None
        assert s.parse() == "model"
        assert s.stage == "parse"
        assert s.parse() == "model"  # idempotent
        s.compile()
        assert s.stage == "compile"
        eng = s.engine
        s.compile()  # idempotent: same engine object
        assert s.engine is eng
        res = s.explore()
        assert s.stage == "explore" and res.ok

    def test_explore_auto_chains(self):
        s = session("constoy", workers=1)
        res = s.explore()  # parse+compile implicitly
        assert res.ok and s.stage == "explore"

    @pytest.mark.parametrize("name", ["viewtoy", "symtoy", "constoy"])
    def test_parity_with_direct_engine(self, name):
        # the session must produce exactly the CheckResult the serial
        # engine produces — counts, verdict, violation identity
        direct = Explorer(load_model(spec(name), None, False)).run()
        res = session(name, workers=1).explore()
        assert (res.ok, res.distinct, res.generated, res.diameter) == \
            (direct.ok, direct.distinct, direct.generated,
             direct.diameter)
        if direct.violation is not None:
            assert (res.violation.kind, res.violation.name) == \
                (direct.violation.kind, direct.violation.name)
            assert [st for st, _ in res.violation.trace] == \
                [st for st, _ in direct.violation.trace]

    def test_assumes_mode(self, tmp_path, capsys):
        sp = tmp_path / "AsmToy.tla"
        sp.write_text("---- MODULE AsmToy ----\n"
                      "ASSUME 1 + 1 = 2\n"
                      "====\n")
        (tmp_path / "AsmToy.cfg").write_text("\n")
        s = CheckSession(SessionConfig(spec=str(sp)))
        assert s.parse() == "assumes"
        rc = s.run_assumes()
        out = capsys.readouterr().out
        assert rc == 0 and "1 assumption checked" in out

    def test_describe_carries_identity(self):
        s = session("constoy", workers=1)
        s.explore()
        d = s.describe()
        assert d["stage"] == "explore"
        assert d["module"] == "constoy"
        assert d["backend"] == "interp"


class TestResume:
    def test_resume_mid_search(self, tmp_path):
        # truncate at a state limit (writes a checkpoint), then a FRESH
        # session resumes and completes with the uninterrupted totals
        ck = str(tmp_path / "mid.ck")
        full = session("constoy", workers=1).explore()
        part = session("constoy", workers=1, max_states=5,
                       checkpoint=ck).explore()
        assert part.truncated and os.path.exists(ck)
        res = session("constoy", workers=1, resume=ck).explore()
        assert not res.truncated
        assert (res.distinct, res.generated) == \
            (full.distinct, full.generated)

    def test_final_checkpoint_replay(self, tmp_path):
        # final_checkpoint persists a COMPLETED run; resuming it (the
        # serve warm path) replays the same totals over an empty queue
        ck = str(tmp_path / "final.ck")
        s = session("constoy", workers=1, checkpoint=ck,
                    final_checkpoint=True)
        res1 = s.explore()
        assert res1.ok and os.path.exists(ck)
        res2 = s.explore(resume_from=ck)  # warm re-run, same session
        assert (res2.ok, res2.distinct, res2.generated) == \
            (res1.ok, res1.distinct, res1.generated)
        res3 = session("constoy", workers=1, resume=ck).explore()
        assert (res3.distinct, res3.generated) == \
            (res1.distinct, res1.generated)

    def test_jax_session_stamps_layout_sig(self, tmp_path):
        ck = str(tmp_path / "res.ck")
        s = session("constoy", backend="jax", platform="cpu",
                    resident=True, no_trace=True, checkpoint=ck,
                    final_checkpoint=True)
        res = s.explore()
        assert res.ok and s.layout_sig and os.path.exists(ck)
        # warm replay through the SAME engine: zero dispatches, same
        # counts — the serve daemon's warm-hit path
        tel = obs.Telemetry()
        with obs.use_local(tel):
            res2 = s.explore(resume_from=ck)
        assert (res2.distinct, res2.generated) == \
            (res.distinct, res.generated)
        assert sum(1 for lv in tel.levels
                   if lv.get("fresh_compile")) == 0


class TestDrain:
    def test_drained_result_checkpoints(self, tmp_path):
        ck = str(tmp_path / "drain.ck")
        drain.request("unit test")
        res = session("constoy", workers=1, checkpoint=ck).explore()
        assert res.drained and res.truncated and res.ok
        assert any("drained" in w for w in res.warnings)
        assert os.path.exists(ck)
        drain.clear()
        full = session("constoy", workers=1).explore()
        res2 = session("constoy", workers=1, resume=ck).explore()
        assert (res2.distinct, res2.generated) == \
            (full.distinct, full.generated)

    def test_drain_without_checkpoint_warns(self):
        drain.request("unit test")
        res = session("constoy", workers=1).explore()
        assert res.drained
        assert any("no checkpoint was configured" in w
                   for w in res.warnings)

    def test_sigterm_drains_cli_with_named_exit(self, tmp_path):
        # the graceful-shutdown satellite end to end: SIGTERM mid-search
        # -> checkpoint + named reason + exit 143 + NO open spans in the
        # trace; a resume then reproduces the uninterrupted counts
        ck = str(tmp_path / "cli.ck")
        tr = str(tmp_path / "cli.jsonl")
        limit = 30000
        p = subprocess.Popen(
            [sys.executable, "-m", "jaxmc", "check",
             spec("transfer_scaled"), "--workers", "1",
             "--max-states", str(limit), "--checkpoint", ck,
             "--trace", tr, "--quiet"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=dict(os.environ, JAX_PLATFORMS="cpu"))
        time.sleep(2.5)  # well inside the ~6s search
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=60)
        assert p.returncode == 143, (p.returncode, out, err)
        assert "drained" in err and "SIGTERM" in err
        assert os.path.exists(ck)
        events = [json.loads(ln) for ln in open(tr)]
        opens = sum(1 for e in events if e["ev"] == "span_open")
        closes = sum(1 for e in events if e["ev"] == "span")
        assert opens == closes, "drained run left open spans"
        assert any(e["ev"] == "run_end" for e in events)
        # resume completes with the totals of an uninterrupted run
        expect = session("transfer_scaled", workers=1,
                         max_states=limit).explore()
        res = session("transfer_scaled", workers=1, max_states=limit,
                      resume=ck).explore()
        assert (res.distinct, res.generated) == \
            (expect.distinct, expect.generated)


class TestFusedGroups:
    """ISSUE 7 satellite: the JAXMC_FUSED_MAX_INSTANCES ceiling no
    longer drops many-instance models to one-dispatch-per-ACTION on
    CPU — actions split into fused ARM GROUPS of <= the cap, counts
    identical."""

    @pytest.mark.parametrize("name", ["constoy", "viewtoy"])
    def test_grouped_counts_match_interp(self, name, monkeypatch):
        from jaxmc.tpu.bfs import TpuExplorer
        # cap 1 instance per fused group: every action becomes its own
        # fused group, the maximal split — counts must not move
        monkeypatch.setenv("JAXMC_FUSED_MAX_INSTANCES", "1")
        model = load_model(spec(name), None, False)
        direct = Explorer(load_model(spec(name), None, False)).run()
        tel = obs.Telemetry()
        with obs.use_local(tel):
            res = TpuExplorer(model, host_seen=True,
                              store_trace=False).run()
        assert (res.ok, res.distinct, res.generated) == \
            (direct.ok, direct.distinct, direct.generated)
        # the grouped path actually ran: more than one group at cap 1
        assert tel.gauges.get("expand.fused_groups", 0) >= 2
