r"""Device profiler (ISSUE 17, jaxmc/obs/prof.py): dispatch-site
registry, profile-on/off parity, HBM accounting, the watchdog's new
device-memory/dominant-site signals, and `python -m jaxmc.obs top`.

The registry/rollup tests drive a Profiler directly with a fake clock
(deterministic, no jax); the parity and HBM tests run the real resident
engine on the constoy fixture, the same rung test_profile.py already
pays for in tier-1.
"""

import io
import json
import os

import numpy as np
import pytest

from jaxmc import obs
from jaxmc.obs.prof import Profiler, attribution, wrap
from jaxmc.obs.report import main as obs_main

pytestmark = pytest.mark.obs

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class Recompiler:
    """A fake jitted callable whose cache grows every `every` calls —
    pins the _cache_size-delta recompile attribution."""

    def __init__(self, every=2):
        self.calls = 0
        self.every = every

    def __call__(self, x):
        self.calls += 1
        return x

    def _cache_size(self):
        return 1 + self.calls // self.every


class TestSiteRegistry:
    def test_wall_mode_counts_and_walls_monotone(self):
        clk = Clock()
        p = Profiler(mode=Profiler.WALL, clock=clk)

        def fn(x):
            clk.t += 0.25
            return x

        arr = np.zeros(16, dtype=np.int32)
        for i in range(1, 4):
            out = p.record("t.site", fn, (arr,), {})
            assert out is arr
            st = p.sites["t.site"]
            assert st.dispatches == i
            assert st.wall_s == pytest.approx(0.25 * i)
            assert st.arg_bytes == arr.nbytes * i
            assert st.res_bytes == arr.nbytes * i

    def test_cheap_mode_counts_only_no_walls_no_bytes(self):
        p = Profiler()  # default mode is cheap (always-on)
        arr = np.zeros(8, dtype=np.int32)
        for _ in range(5):
            p.record("t.site", lambda x: x, (arr,), {})
        st = p.sites["t.site"]
        assert st.dispatches == 5
        assert st.wall_s == 0.0 and st.arg_bytes == 0

    def test_recompile_attribution_via_cache_size_delta(self):
        p = Profiler()
        fn = Recompiler(every=2)
        for _ in range(6):
            p.record("t.jit", fn, (1,), {})
        # cache sizes 1,2,2,3,3,4 -> three positive deltas
        assert p.sites["t.jit"].recompiles == 3

    def test_dominant_site_prefers_wall_then_dispatches(self):
        p = Profiler()
        p._site("a").dispatches = 9
        p._site("b").dispatches = 1
        assert p.dominant_site() == ("a", 0.9)
        p._site("b").wall_s = 3.0
        p._site("a").wall_s = 1.0
        name, share = p.dominant_site()
        assert name == "b" and share == pytest.approx(0.75)

    def test_wrap_resolves_recorder_at_call_time(self):
        calls = []
        wrapped = wrap("t.wrapped", lambda x: calls.append(x) or x)
        assert wrapped(1) == 1  # NullTelemetry: pass-through
        tel = obs.Telemetry()
        with obs.use(tel):
            wrapped(2)
            wrapped(3)
        assert calls == [1, 2, 3]
        assert tel.prof.sites["t.wrapped"].dispatches == 2
        assert wrapped.profiler_site == "t.wrapped"


class TestHbmModel:
    def test_note_buffer_peak_watermark(self):
        p = Profiler()
        p.note_buffer("seen", 1000)
        p.note_buffer("frontier", 500)
        assert p.hbm_current_bytes() == 1500
        p.note_buffer("seen", 200)     # resize DOWN: current drops,
        p.drop_buffer("frontier")      # peak stays
        assert p.hbm_current_bytes() == 200
        assert p.hbm_peak_bytes == 1500
        assert p.hbm_buffers() == {"seen": 200}

    def test_module_level_note_buffer_needs_live_recorder(self):
        obs.note_buffer("orphan", 99)  # NullTelemetry: silent no-op
        tel = obs.Telemetry()
        with obs.use(tel):
            obs.note_buffer("live", 42)
        assert tel.prof.hbm_buffers() == {"live": 42}


class TestSnapshotRollup:
    def test_cheap_empty_snapshot_is_none_unless_forced(self):
        p = Profiler()
        assert p.snapshot() is None
        forced = p.snapshot(force=True)
        assert forced["mode"] == "cheap" and forced["sites"] == {}

    def test_summary_carries_prof_block_on_schema_4(self):
        tel = obs.Telemetry()
        tel.prof.mode = Profiler.WALL
        clk = Clock()
        tel.prof._clock = clk

        def fn(x):
            clk.t += 0.5
            return x

        with obs.use(tel):
            wrap("t.hot", fn)(np.zeros(4, dtype=np.int32))
        s = tel.summary()
        assert s["schema"] == "jaxmc.metrics/4"
        site = s["prof"]["sites"]["t.hot"]
        assert site["dispatches"] == 1
        assert site["wall_s"] == pytest.approx(0.5)

    def test_attribution_sums_site_and_analysis_walls(self):
        summary = {
            "phases": [{"name": "search", "wall_s": 10.0}],
            "prof": {"mode": "wall", "sites": {
                "a": {"dispatches": 2, "wall_s": 6.0,
                      "analysis_wall_s": 1.0},
                "b": {"dispatches": 1, "wall_s": 2.0},
            }},
        }
        att = attribution(summary)
        assert att["attributed_wall_s"] == pytest.approx(9.0)
        assert att["share"] == pytest.approx(0.9)


class TestResidentEngineProfiled:
    """The real thing: constoy through the resident engine with the
    profiler in wall mode — named sites, HBM buffers, and profile-off
    parity (the acceptance criterion at test scale)."""

    @pytest.fixture()
    def model(self, monkeypatch, tmp_path):
        monkeypatch.setenv("JAXMC_PROFILE_STORE",
                           str(tmp_path / "profiles"))
        from jaxmc.front.cfg import parse_cfg
        from jaxmc.sem.modules import Loader, bind_model
        return bind_model(
            Loader([SPECS]).load_path(
                os.path.join(SPECS, "constoy.tla")),
            parse_cfg(open(os.path.join(SPECS,
                                        "constoy.cfg")).read()))

    def _run(self, model, tel):
        from jaxmc.backend.bfs import TpuExplorer
        with obs.use(tel):
            r = TpuExplorer(model, store_trace=False,
                            resident=True).run()
        return r

    def test_profiled_run_names_sites_and_buffers_parity_off(
            self, model):
        tel_on = obs.Telemetry()
        tel_on.prof.mode = Profiler.WALL
        r_on = self._run(model, tel_on)
        sites = tel_on.prof.sites
        assert "bfs.resident_run" in sites, sorted(sites)
        assert sites["bfs.resident_run"].dispatches >= 1
        assert sites["bfs.resident_run"].wall_s > 0
        bufs = tel_on.prof.hbm_buffers()
        assert any(b.startswith("resident.") for b in bufs), bufs
        assert tel_on.prof.hbm_peak_bytes >= sum(bufs.values())
        # envelope: the model never exceeds what the device reports
        # (CPU usually exposes no memory_stats -> skip the cross-check)
        from jaxmc.obs.telemetry import device_mem_high_water
        measured = device_mem_high_water()
        if measured:
            assert tel_on.prof.hbm_peak_bytes <= measured
        # parity: a cheap-mode (profile-off) run answers identically
        r_off = self._run(model, obs.Telemetry())
        assert (r_on.ok, r_on.generated, r_on.distinct,
                r_on.diameter) == \
               (r_off.ok, r_off.generated, r_off.distinct,
                r_off.diameter)


class TestWatchdogSignals:
    def _mk(self, tmp_path):
        clk = Clock(1000.0)
        trace = tmp_path / "trace.jsonl"
        tel = obs.Telemetry(trace_path=str(trace), clock=clk)
        msgs = []
        wd = obs.Watchdog(tel, clock=clk, on_stall=msgs.append,
                          interval=5.0, stall_factor=4.0,
                          min_stall_s=30.0)
        return tel, wd, clk, trace, msgs

    def test_heartbeat_carries_device_mem(self, tmp_path):
        tel, wd, clk, trace, _ = self._mk(tmp_path)
        tel.prof.note_buffer("resident.seen", 4096)
        clk.t += 5
        wd._tick(clk.t)
        tel.close()
        with open(trace) as fh:
            evs = [json.loads(ln) for ln in fh if ln.strip()]
        (hb,) = [e for e in evs if e["ev"] == "heartbeat"]
        assert hb["device_mem_bytes"] == 4096

    def test_stall_line_names_dominant_site(self, tmp_path):
        tel, wd, clk, trace, msgs = self._mk(tmp_path)
        tel.prof._site("mesh.superstep").wall_s = 9.0
        tel.prof._site("mesh.probe_route").wall_s = 1.0
        wd._tick(clk.t)
        clk.t += 31
        wd._tick(clk.t)
        assert msgs, "stall must fire past the floor"
        assert "90% in mesh.superstep" in msgs[0]


class TestObsTop:
    def _artifact(self, tmp_path, with_prof=True):
        art = {"schema": "jaxmc.metrics/4", "started_at": 1.0,
               "phases": [{"name": "search", "wall_s": 4.0}],
               "counters": {}, "gauges": {}, "levels": [], "env": {},
               "result": {"ok": True, "generated": 10, "distinct": 5,
                          "diameter": 2, "truncated": False,
                          "wall_s": 4.0}}
        if with_prof:
            art["prof"] = {
                "mode": "wall",
                "sites": {"bfs.resident_run": {
                    "dispatches": 3, "recompiles": 1, "wall_s": 3.6,
                    "arg_bytes": 3000, "res_bytes": 300}},
                "hbm": {"buffers": {"resident.seen": 2048},
                        "peak_bytes": 2048}}
        p = tmp_path / ("with.json" if with_prof else "without.json")
        p.write_text(json.dumps(art))
        return str(p)

    def test_top_renders_sites_share_and_hbm(self, tmp_path):
        buf = io.StringIO()
        rc = obs_main(["top", self._artifact(tmp_path)], out=buf)
        out = buf.getvalue()
        assert rc == 0
        assert "bfs.resident_run" in out
        assert "90.0%" in out            # 3.6s of the 4.0s search wall
        assert "attributed" in out
        assert "resident.seen" in out and "2.0KB" in out

    def test_top_exits_2_without_prof_block(self, tmp_path, capfd):
        rc = obs_main(["top", self._artifact(tmp_path,
                                             with_prof=False)])
        assert rc == 2
        assert "no prof block" in capfd.readouterr().err
