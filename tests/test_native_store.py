"""Native fingerprint store: build, membership semantics, scale."""

import numpy as np
import pytest

from jaxmc import native_store


pytestmark = pytest.mark.skipif(not native_store.is_available(),
                                reason=f"no toolchain: "
                                       f"{native_store.build_error()}")


def test_insert_semantics():
    st = native_store.FingerprintStore()
    a = np.arange(20, dtype=np.int32).reshape(5, 4)
    new = st.insert(a)
    assert new.all() and len(st) == 5
    # re-insert: nothing new
    assert not st.insert(a).any()
    # batch with in-batch duplicates and one known row
    b = np.vstack([a[2], a[2] + 100, a[2] + 100, a[0]]).astype(np.int32)
    new = st.insert(b)
    assert list(new) == [False, True, False, False]
    assert len(st) == 6


def test_scale_and_order_independence():
    st = native_store.FingerprintStore()
    rng = np.random.RandomState(0)
    fps = rng.randint(-2**31, 2**31 - 1, size=(50000, 4)).astype(np.int32)
    n1 = st.insert(fps).sum()
    st2 = native_store.FingerprintStore()
    perm = rng.permutation(len(fps))
    n2 = st2.insert(fps[perm]).sum()
    assert n1 == n2 == len(st) == len(st2)
    # everything known now, in any order
    assert not st.insert(fps[perm][:1000]).any()
