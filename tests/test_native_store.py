"""Native fingerprint store: build, membership semantics, scale."""

import numpy as np
import pytest

from jaxmc import native_store


pytestmark = pytest.mark.skipif(not native_store.is_available(),
                                reason=f"no toolchain: "
                                       f"{native_store.build_error()}")


def test_insert_semantics():
    st = native_store.FingerprintStore()
    a = np.arange(20, dtype=np.int32).reshape(5, 4)
    new = st.insert(a)
    assert new.all() and len(st) == 5
    # re-insert: nothing new
    assert not st.insert(a).any()
    # batch with in-batch duplicates and one known row
    b = np.vstack([a[2], a[2] + 100, a[2] + 100, a[0]]).astype(np.int32)
    new = st.insert(b)
    assert list(new) == [False, True, False, False]
    assert len(st) == 6


def test_tiered_runs_differential_vs_python_set():
    """Many small batches force run stacking + background compaction
    (phase 2); the marked-new semantics must match a python set exactly,
    and dump() must stay sorted-unique across compactions."""
    st = native_store.FingerprintStore()
    rng = np.random.RandomState(7)
    seen = set()
    total_new = 0
    for _ in range(40):  # > max_runs batches, duplicates across batches
        batch = rng.randint(0, 500, size=(rng.randint(1, 300), 4)) \
            .astype(np.int32)
        new = st.insert(batch)
        for row, is_new in zip(batch, new):
            key = tuple(int(x) for x in row)
            if key not in seen:
                assert is_new, f"row {key} should be new"
                seen.add(key)
                total_new += 1
            # a known key may appear multiple times in one batch; only
            # non-first occurrences must be False — covered by comparing
            # against `seen` updated row by row
        assert int(new.sum()) <= len(batch)
    assert len(st) == len(seen) == total_new
    d = st.dump()
    assert len(d) == len(seen)
    keys = [tuple(r) for r in d.tolist()]
    assert keys == sorted(keys), "dump must be sorted"
    assert len(set(keys)) == len(keys), "dump must be unique"
    # round-trip through a fresh store
    st2 = native_store.FingerprintStore()
    st2.load(d)
    assert len(st2) == len(seen)
    probe = rng.randint(0, 500, size=(500, 4)).astype(np.int32)
    assert (st.insert(probe) == st2.insert(probe)).all()


def test_spill_dir_file_backed_runs(tmp_path):
    """With a spill dir and a tiny threshold every run is file-backed
    mmap (unlinked at once) — semantics must be unchanged."""
    st = native_store.FingerprintStore(spill_dir=str(tmp_path),
                                       spill_threshold_bytes=1)
    rng = np.random.RandomState(3)
    fps = rng.randint(-2**31, 2**31 - 1, size=(20000, 4)).astype(np.int32)
    n1 = int(st.insert(fps).sum())
    assert n1 == len(st)
    assert not st.insert(fps[:4000]).any()
    ref = native_store.FingerprintStore()
    ref.load(st.dump())
    probe = rng.randint(-2**31, 2**31 - 1, size=(1000, 4)) \
        .astype(np.int32)
    assert (st.insert(probe) == ref.insert(probe)).all()


def test_scale_and_order_independence():
    st = native_store.FingerprintStore()
    rng = np.random.RandomState(0)
    fps = rng.randint(-2**31, 2**31 - 1, size=(50000, 4)).astype(np.int32)
    n1 = st.insert(fps).sum()
    st2 = native_store.FingerprintStore()
    perm = rng.permutation(len(fps))
    n2 = st2.insert(fps[perm]).sum()
    assert n1 == n2 == len(st) == len(st2)
    # everything known now, in any order
    assert not st.insert(fps[perm][:1000]).any()
