r"""jaxmc/faults.py — the deterministic fault-injection registry.

Fast unit coverage (tier-1): grammar, context matchers, the
cross-process `n=` budget, file corruption, and the inject/raise path.
The end-to-end chaos scenarios (killed workers, corrupted checkpoints,
device demotion) live in tests/test_chaos.py.
"""

import os

import pytest

from jaxmc import faults
from jaxmc.faults import FaultInjected, parse_faults


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch, tmp_path):
    monkeypatch.delenv("JAXMC_FAULTS", raising=False)
    monkeypatch.setenv("JAXMC_FAULTS_STATE", str(tmp_path / "state"))
    os.makedirs(str(tmp_path / "state"), exist_ok=True)
    faults._CACHE = None
    yield
    faults._CACHE = None


def test_parse_grammar():
    specs = parse_faults(
        "worker_kill:level=2,chunk_error:p=0.5:n=3, ckpt_corrupt ,"
        "device_init_fail:n=2:mode=flip")
    assert [s.site for s in specs] == [
        "worker_kill", "chunk_error", "ckpt_corrupt", "device_init_fail"]
    assert specs[0].match == {"level": "2"}
    assert specs[1].n == 3
    assert specs[2].n == 1  # default: fire once
    assert specs[3].mode == "flip"


def test_parse_malformed_entries_skipped():
    assert parse_faults(",,:,=x,") == [] or \
        all(s.site for s in parse_faults(",,:,=x,"))
    assert parse_faults("") == []


def test_inactive_without_env():
    assert not faults.active()
    assert faults.fire("worker_kill", level=2) is None


def test_context_matcher_and_budget(monkeypatch):
    monkeypatch.setenv("JAXMC_FAULTS", "chunk_error:level=3:n=2")
    assert faults.fire("chunk_error", level=1) is None  # wrong level
    assert faults.fire("other_site", level=3) is None   # wrong site
    assert faults.fire("chunk_error", level=3) is not None
    assert faults.fire("chunk_error", level=3) is not None
    assert faults.fire("chunk_error", level=3) is None  # budget spent


def test_matcher_on_missing_ctx_key_never_fires(monkeypatch):
    # a typo'd matcher must DISABLE the fault, not fire it everywhere
    monkeypatch.setenv("JAXMC_FAULTS", "chunk_error:levle=3")
    assert faults.fire("chunk_error", level=3) is None


def test_targets(monkeypatch):
    monkeypatch.setenv("JAXMC_FAULTS", "worker_kill:level=2")
    assert faults.targets("worker_kill", "chunk_error")
    assert not faults.targets("ckpt_corrupt")


def test_inject_raises_and_counts(monkeypatch):
    from jaxmc import obs
    monkeypatch.setenv("JAXMC_FAULTS", "device_init_fail")
    tel = obs.Telemetry()
    with obs.use(tel):
        with pytest.raises(FaultInjected, match="device_init_fail"):
            faults.inject("device_init_fail")
        faults.inject("device_init_fail")  # budget spent: no raise
    assert tel.counters.get("faults.injected") == 1


def test_corrupt_file_truncates(monkeypatch, tmp_path):
    monkeypatch.setenv("JAXMC_FAULTS", "ckpt_corrupt")
    p = str(tmp_path / "f.bin")
    with open(p, "wb") as fh:
        fh.write(b"x" * 1000)
    assert faults.corrupt_file("ckpt_corrupt", p)
    assert os.path.getsize(p) == 500
    # budget spent: the second write survives
    with open(p, "wb") as fh:
        fh.write(b"y" * 1000)
    assert not faults.corrupt_file("ckpt_corrupt", p)
    assert os.path.getsize(p) == 1000


def test_corrupt_file_flip_mode(monkeypatch, tmp_path):
    monkeypatch.setenv("JAXMC_FAULTS", "ckpt_corrupt:mode=flip")
    p = str(tmp_path / "f.bin")
    payload = b"a" * 1000
    with open(p, "wb") as fh:
        fh.write(payload)
    assert faults.corrupt_file("ckpt_corrupt", p)
    assert os.path.getsize(p) == 1000  # same size ...
    with open(p, "rb") as fh:
        assert fh.read() != payload    # ... different content


def test_budget_shared_across_forks(monkeypatch):
    # the n=1 budget must be spent ONCE across parent + forked children
    # (the parallel engine's respawned workers share it the same way)
    monkeypatch.setenv("JAXMC_FAULTS", "chunk_error")
    faults.ensure_shared_state()
    import multiprocessing
    ctx = multiprocessing.get_context("fork")
    q = ctx.Queue()

    def child(q):
        q.put(faults.fire("chunk_error") is not None)

    procs = [ctx.Process(target=child, args=(q,)) for _ in range(4)]
    for p in procs:
        p.start()
    fired = [q.get(timeout=10) for _ in procs]
    for p in procs:
        p.join(5)
    assert sum(fired) == 1, fired
