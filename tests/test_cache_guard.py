r"""The guarded persistent compile cache (ISSUE 5, jaxmc/compile/cache.py).

The contract under test: a persistent-cache problem — wedged blob
reload, corrupt entry, foreign build, lock contention — must NEVER
wedge or fail a run.  Every guard defect degrades to cold compilation
(enable returns None, the run proceeds uncached), and the good path
proves cross-process cache hits in `compile.persistent_cache_hits`.
Fault sites: cache_hang / cache_corrupt / cache_lock (jaxmc/faults.py).
"""

import json
import os
import subprocess
import sys

import pytest

from jaxmc import faults, obs
from jaxmc.compile import cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch, tmp_path):
    """Every test gets an isolated cache dir, a clean fault registry,
    and no parked flock from a previous test."""
    monkeypatch.delenv("JAXMC_FAULTS", raising=False)
    monkeypatch.delenv("JAXMC_COMPILE_CACHE", raising=False)
    monkeypatch.setenv("JAXMC_CACHE_PROBE", "0")  # probe-needing tests
    # opt back in explicitly — jax-import subprocesses are expensive
    faults.reset_for_tests()
    cache.release_lock_for_tests()
    yield
    faults.reset_for_tests()
    cache.release_lock_for_tests()


def _dir(tmp_path):
    return str(tmp_path / "xla_cache")


def test_guard_enables_and_fingerprints(tmp_path):
    tel = obs.Telemetry()
    d = cache.enable_guarded_cache(_dir(tmp_path), tel=tel)
    assert d == _dir(tmp_path)
    # the build-fingerprint sentinel exists and matches this build
    meta = json.load(open(os.path.join(d, "jaxmc.cache.meta.json")))
    assert meta["python"] and meta["jax"]
    assert tel.gauges["compile.persistent_cache_guard"].startswith("ok")


def test_env_opt_out_disables_defaults_not_explicit_requests(
        monkeypatch, tmp_path):
    # JAXMC_COMPILE_CACHE=off governs the DEFAULT-ON call sites (bench
    # children, sweep subprocesses — they pass no path)...
    monkeypatch.setenv("JAXMC_COMPILE_CACHE", "off")
    tel = obs.Telemetry()
    assert cache.enable_guarded_cache(tel=tel) is None
    assert tel.gauges["compile.persistent_cache_guard"].startswith(
        "disabled")
    # ...but an EXPLICIT path (cli --compile-cache DIR) is a direct
    # request and overrides the box-wide opt-out
    tel2 = obs.Telemetry()
    assert cache.enable_guarded_cache(_dir(tmp_path), tel=tel2) == \
        _dir(tmp_path)
    assert tel2.gauges["compile.persistent_cache_guard"].startswith("ok")


@pytest.mark.chaos
def test_hang_fault_quarantines_and_falls_back_cold(monkeypatch,
                                                    tmp_path):
    # the known failure class: a blob reload that never returns. The
    # probe child wedges (cache_hang), OUR timeout fires, the dir is
    # quarantined, and the caller gets the cold path — never a hang.
    monkeypatch.setenv("JAXMC_CACHE_PROBE", "1")
    monkeypatch.setenv("JAXMC_FAULTS", "cache_hang")
    faults.reset_for_tests()
    tel = obs.Telemetry()
    d = _dir(tmp_path)
    assert cache.enable_guarded_cache(d, tel=tel, timeout_s=6) is None
    g = tel.gauges["compile.persistent_cache_guard"]
    assert g.startswith("cold-fallback:") and "probe" in g
    assert tel.counters["compile.persistent_cache_fallbacks"] == 1
    assert any(n.startswith("xla_cache.quarantined.")
               for n in os.listdir(tmp_path))
    # the run is intact: a compile still works, just uncached
    import jax
    import jax.numpy as jnp
    assert int(jax.jit(lambda x: x + 1)(jnp.int32(1))) == 2


@pytest.mark.chaos
def test_corrupt_entry_quarantined_cache_continues(monkeypatch,
                                                   tmp_path):
    # one corrupt entry must never disable the whole cache: the scan
    # quarantines it into <dir>/.quarantine and the cache enables
    d = _dir(tmp_path)
    os.makedirs(d)
    with open(os.path.join(d, "jit_f-deadbeef-cache"), "wb") as fh:
        fh.write(b"x" * 64)
    monkeypatch.setenv("JAXMC_FAULTS", "cache_corrupt")
    faults.reset_for_tests()
    tel = obs.Telemetry()
    assert cache.enable_guarded_cache(d, tel=tel) == d
    assert tel.counters["compile.persistent_cache_quarantines"] >= 1
    assert os.listdir(os.path.join(d, ".quarantine")) == \
        ["jit_f-deadbeef-cache"]
    assert "quarantined 1 corrupt entry" in \
        tel.gauges["compile.persistent_cache_guard"]


@pytest.mark.chaos
def test_lock_fault_falls_back_cold(monkeypatch, tmp_path):
    monkeypatch.setenv("JAXMC_FAULTS", "cache_lock")
    faults.reset_for_tests()
    tel = obs.Telemetry()
    assert cache.enable_guarded_cache(_dir(tmp_path), tel=tel) is None
    assert "lock contention" in \
        tel.gauges["compile.persistent_cache_guard"]


def test_real_lock_contention_falls_back_cold(tmp_path):
    # a REAL exclusive flock held elsewhere (a quarantine in flight):
    # this process must not race the rename — cold fallback
    import fcntl
    d = _dir(tmp_path)
    os.makedirs(d)
    fd = os.open(d + ".lock", os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        tel = obs.Telemetry()
        assert cache.enable_guarded_cache(d, tel=tel) is None
        assert "lock contention" in \
            tel.gauges["compile.persistent_cache_guard"]
    finally:
        os.close(fd)


def test_foreign_build_fingerprint_quarantines_dir(tmp_path):
    # a cache written by another build is exactly the reload-hang class:
    # the whole dir is swapped aside BEFORE jax ever reads a blob
    d = _dir(tmp_path)
    os.makedirs(d)
    with open(os.path.join(d, "jaxmc.cache.meta.json"), "w") as fh:
        json.dump({"python": "0.0.0", "jax": "0.0.0",
                   "machine": "vax"}, fh)
    with open(os.path.join(d, "jit_old-cache"), "wb") as fh:
        fh.write(b"foreign blob")
    tel = obs.Telemetry()
    assert cache.enable_guarded_cache(d, tel=tel) == d
    assert tel.counters["compile.persistent_cache_quarantines"] >= 1
    assert not os.path.exists(os.path.join(d, "jit_old-cache"))
    quarantined = [n for n in os.listdir(tmp_path)
                   if n.startswith("xla_cache.quarantined.")]
    assert quarantined, "foreign dir should be parked aside"
    # the fresh dir carries THIS build's fingerprint
    meta = json.load(open(os.path.join(d, "jaxmc.cache.meta.json")))
    assert meta["machine"] != "vax"


def test_failed_foreign_quarantine_falls_back_cold(monkeypatch,
                                                   tmp_path):
    # if the quarantine rename itself fails, the foreign-build dir is
    # STILL on disk — the guard must compile cold, never enable over
    # the very dir it diagnosed as the reload-hang class
    d = _dir(tmp_path)
    os.makedirs(d)
    with open(os.path.join(d, "jaxmc.cache.meta.json"), "w") as fh:
        json.dump({"python": "0.0.0", "jax": "0.0.0",
                   "machine": "vax"}, fh)
    monkeypatch.setattr(cache, "_quarantine_dir", lambda p: None)
    tel = obs.Telemetry()
    assert cache.enable_guarded_cache(d, tel=tel) is None
    g = tel.gauges["compile.persistent_cache_guard"]
    assert g.startswith("cold-fallback:") and "quarantine rename" in g


@pytest.mark.chaos
def test_cross_process_hits_visible(tmp_path):
    # the tentpole's proof obligation: process B reloads what process A
    # compiled, visible in compile.persistent_cache_hits
    d = _dir(tmp_path)
    code = (
        "import os, sys, json\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jaxmc import obs\n"
        "from jaxmc.compile.cache import enable_guarded_cache\n"
        "tel = obs.Telemetry()\n"
        f"assert enable_guarded_cache({d!r}, tel=tel)\n"
        "import jax.numpy as jnp\n"
        "with obs.use(tel):\n"
        "    jax.jit(lambda x: x * 3 + 7)(jnp.arange(5))"
        ".block_until_ready()\n"
        "print('HITS', tel.counters.get("
        "'compile.persistent_cache_hits', 0))\n")
    outs = []
    for _ in range(2):
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=240,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     JAXMC_CACHE_PROBE="0"))
        assert p.returncode == 0, p.stderr[-800:]
        outs.append(int(p.stdout.split("HITS")[1].strip()))
    assert outs[0] == 0, "first process must compile cold"
    assert outs[1] > 0, "second process must hit the persistent cache"
