r"""Telemetry (jaxmc/obs) tests: the metrics schema, cross-backend count
agreement, JSONL trace streaming, and the span/counter API itself.

Tier-1 fast by construction: CPU only (conftest pins jax to cpu), micro
models only (specs/symtoy.tla — 22 distinct states on BOTH backends, the
corpus pin), no reference-corpus dependency.
"""

import json
import os

import pytest

from jaxmc import obs
from jaxmc.cli import main

pytestmark = pytest.mark.obs

SPECS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "specs")
SYMTOY = os.path.join(SPECS, "symtoy.tla")
SYMTOY_CFG = os.path.join(SPECS, "symtoy.cfg")
SYMTOY_DISTINCT = 22   # corpus pin (jaxmc/corpus.py CASES)
SYMTOY_GENERATED = 33


def run_check(tmp_path, backend, extra=()):
    m = tmp_path / f"metrics_{backend}.json"
    tr = tmp_path / f"trace_{backend}.jsonl"
    rc = main(["check", SYMTOY, "--cfg", SYMTOY_CFG, "--backend", backend,
               "--no-deadlock", "--quiet", "--metrics-out", str(m),
               "--trace", str(tr)] + list(extra))
    assert rc == 0
    with open(m) as fh:
        summary = json.load(fh)
    return summary, tr


class TestMetricsArtifact:
    @pytest.fixture(scope="class")
    def both(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("obs")
        return {b: run_check(tmp, b) for b in ("interp", "jax")}

    def test_schema_valid_on_both_backends(self, both):
        for backend, (summary, _) in both.items():
            obs.validate_summary(summary, check_run=True)
            assert summary["backend"] == backend
            assert summary["spec"] == SYMTOY
            assert summary["schema"] == obs.SCHEMA

    def test_env_fingerprint_recorded(self, both):
        # schema v2: the env block obs diff uses to attribute
        # regressions to environment changes
        for backend, (summary, _) in both.items():
            env = summary["env"]
            assert env["jax_version"], backend
            assert env["python"], backend
        # the jax run initialized devices, so platform/count are real
        envj = both["jax"][0]["env"]
        assert envj["platform"] == "cpu"
        assert envj["device_count"] >= 1

    def test_v1_artifacts_still_validate(self):
        # additive migration: a jaxmc.metrics/1 artifact (no env block,
        # no watchdog/compile counters) must keep validating
        tel = obs.Telemetry()
        s = tel.summary()
        s["schema"] = "jaxmc.metrics/1"
        obs.validate_summary(s)
        s["schema"] = "jaxmc.metrics/99"
        with pytest.raises(ValueError):
            obs.validate_summary(s)

    def test_compile_introspection_gauges(self, both):
        sj = both["jax"][0]
        cost = sj["gauges"].get("compile.arm_cost")
        assert cost, "per-arm compile-cost gauge missing"
        assert all("jaxpr_eqns" in v for v in cost.values())
        assert sj["counters"]["compile.jaxpr_eqns_total"] >= 1
        # every jit-cache build is counted; symtoy compiles at least one
        assert sj["counters"].get("compile.cache_misses", 0) >= 1

    def test_distinct_counts_match_explorer_and_backends(self, both):
        for backend, (summary, _) in both.items():
            res = summary["result"]
            assert res["ok"] is True
            assert res["distinct"] == SYMTOY_DISTINCT, backend
            assert res["generated"] == SYMTOY_GENERATED, backend
        assert both["interp"][0]["result"]["distinct"] == \
            both["jax"][0]["result"]["distinct"]

    def test_level_records_monotone_and_consistent(self, both):
        for backend, (summary, _) in both.items():
            levels = summary["levels"]
            assert levels, f"{backend}: no level records"
            idxs = [r["level"] for r in levels]
            assert idxs == sorted(idxs), backend
            # level-by-level accumulation reaches the final result
            assert levels[-1]["distinct"] == SYMTOY_DISTINCT, backend
            for r in levels:
                for k in ("frontier", "generated", "new", "distinct",
                          "wall_s"):
                    assert k in r, (backend, r)

    def test_phase_spans_present(self, both):
        names_i = {p["name"] for p in both["interp"][0]["phases"]}
        assert {"load", "search", "parse"} <= names_i
        names_j = {p["name"] for p in both["jax"][0]["phases"]}
        assert {"load", "search", "engine_build", "device_init",
                "layout_sample", "layout_build", "compile_arm",
                "compile_predicates"} <= names_j
        for _, (summary, _) in both.items():
            for ph in summary["phases"]:
                assert ph["wall_s"] >= 0 and ph["count"] >= 1

    def test_counters_and_gauges(self, both):
        gi = both["interp"][0]["gauges"]
        assert "memo.hits" in gi and "memo.misses" in gi
        assert gi["fingerprint.occupancy"] >= SYMTOY_DISTINCT
        sj = both["jax"][0]
        gj = sj["gauges"]
        assert gj["expand.mode"] in ("compiled", "hybrid", "interp-arms")
        assert gj["expand.arms_total"] >= 1
        assert gj["fingerprint.occupancy"] >= SYMTOY_DISTINCT
        assert sj["counters"].get("compile.kernels_built", 0) >= 1

    def test_trace_jsonl_stream(self, both):
        for backend, (_, tr) in both.items():
            with open(tr) as fh:
                events = [json.loads(ln) for ln in fh if ln.strip()]
            # PR 16: every trace file opens with the process-identity
            # header, then the run record
            assert events[0]["ev"] == "proc_meta"
            assert events[1]["ev"] == "run_start"
            assert events[-1]["ev"] == "run_end"
            kinds = {e["ev"] for e in events}
            assert {"span_open", "span", "level", "log"} <= kinds, backend
            # every span_open eventually closed (clean run)
            opens = sum(1 for e in events if e["ev"] == "span_open")
            closes = sum(1 for e in events if e["ev"] == "span")
            assert opens == closes, backend


class TestTelemetryApi:
    def test_null_telemetry_is_inert(self):
        tel = obs.NullTelemetry()
        with tel.span("x"):
            tel.counter("c")
            tel.level(0, frontier=1)
        assert not tel.enabled

    def test_spans_counters_levels_rollup(self, tmp_path):
        clock = iter(float(i) for i in range(100))
        tel = obs.Telemetry(meta={"backend": "test"},
                            clock=lambda: next(clock))
        with tel.span("a"):
            with tel.span("b"):
                pass
        tel.counter("n", 2)
        tel.counter("n")
        tel.gauge("g", 7)
        tel.high_water("hw", 5)
        tel.high_water("hw", 3)   # lower: ignored
        tel.high_water("hw", None)  # None: ignored
        tel.level(0, frontier=4)
        tel.level(1, frontier=2)
        s = tel.summary(result={"ok": True})
        obs.validate_summary(s)
        assert s["counters"]["n"] == 3
        assert s["gauges"] == {"g": 7, "hw": 5}
        assert [r["level"] for r in s["levels"]] == [0, 1]
        by = {p["name"]: p for p in s["phases"]}
        assert by["a"]["count"] == 1 and by["b"]["count"] == 1
        assert by["a"]["wall_s"] > by["b"]["wall_s"]
        p = tmp_path / "m.json"
        tel.write_metrics(str(p), result={"ok": True})
        with open(p) as fh:
            obs.validate_summary(json.load(fh))

    def test_open_span_reports_partial_wall(self):
        tel = obs.Telemetry()
        h = tel.span("stuck")
        h.__enter__()
        phases = tel.phase_list()
        (ph,) = [p for p in phases if p["name"] == "stuck"]
        assert ph.get("open") is True and ph["wall_s"] >= 0
        h.done()
        (ph2,) = [p for p in tel.phase_list() if p["name"] == "stuck"]
        assert "open" not in ph2

    def test_reset_levels_keeps_monotonicity(self):
        tel = obs.Telemetry()
        tel.level(0)
        tel.level(1)
        tel.reset_levels("restart")
        tel.level(0)
        s = tel.summary()
        obs.validate_summary(s)
        assert [r["level"] for r in s["levels"]] == [0]
        assert s["counters"]["search.restarts"] == 1

    def test_validate_rejects_bad_summaries(self):
        tel = obs.Telemetry()
        s = tel.summary()
        bad = dict(s)
        del bad["phases"]
        with pytest.raises(ValueError):
            obs.validate_summary(bad)
        bad2 = dict(s, levels=[{"level": 2}, {"level": 1}])
        with pytest.raises(ValueError):
            obs.validate_summary(bad2)
        with pytest.raises(ValueError):
            obs.validate_summary(dict(s), check_run=True)  # no result

    def test_logger_single_sink(self):
        tel = obs.Telemetry()
        out = []
        log = obs.Logger(tel, quiet=False, sink=out.append)
        log("Progress(1): hello")
        assert out == ["Progress(1): hello"]
        quiet = obs.Logger(tel, quiet=True, sink=out.append)
        quiet("suppressed")
        assert out == ["Progress(1): hello"]

    def test_current_use_scoping(self):
        base = obs.current()
        tel = obs.Telemetry()
        with obs.use(tel):
            assert obs.current() is tel
        assert obs.current() is base


class TestTraceContext:
    """obs/context.py (PR 16): the JAXMC_TRACE_CTX propagation
    contract every process boundary relies on."""

    @pytest.fixture(autouse=True)
    def _fresh_ctx(self, monkeypatch):
        from jaxmc.obs import context
        monkeypatch.delenv(context.ENV_VAR, raising=False)
        context.reset()
        yield
        context.reset()

    def test_root_when_no_env(self):
        from jaxmc.obs import context
        ctx = context.get()
        assert ctx.parent_span_id is None
        assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 16
        assert context.get() is ctx  # cached within the process

    def test_inherits_env_header(self, monkeypatch):
        from jaxmc.obs import context
        monkeypatch.setenv(context.ENV_VAR, "aaaabbbbccccdddd:1111222233334444")
        context.reset()
        ctx = context.get()
        assert ctx.trace_id == "aaaabbbbccccdddd"
        assert ctx.parent_span_id == "1111222233334444"
        assert ctx.span_id not in ("1111222233334444",
                                   "aaaabbbbccccdddd")

    def test_malformed_header_falls_back_to_root(self, monkeypatch):
        from jaxmc.obs import context
        for bad in ("", "nocolon", ":", "a:", ":b", "a:b:c"):
            monkeypatch.setenv(context.ENV_VAR, bad)
            context.reset()
            assert context.get().parent_span_id is None, bad

    def test_child_env_carries_header(self):
        from jaxmc.obs import context
        ctx = context.get()
        env = context.child_env({"OTHER": "1"})
        assert env["OTHER"] == "1"
        assert env[context.ENV_VAR] == \
            f"{ctx.trace_id}:{ctx.span_id}"

    def test_fork_rederive_keeps_trace_id(self):
        # simulate the fork child's pid mismatch without forking
        from jaxmc.obs import context
        parent = context.get()
        context._ctx = context.TraceContext(
            parent.trace_id, parent.parent_span_id, parent.span_id,
            parent.pid - 1)  # "stale" pid -> get() re-derives
        child = context.get()
        assert child.trace_id == parent.trace_id
        assert child.parent_span_id == parent.span_id
        assert child.span_id != parent.span_id
        assert child.pid == os.getpid()

    def test_exported_restores_environ(self):
        from jaxmc.obs import context
        assert context.ENV_VAR not in os.environ
        with context.exported():
            assert os.environ[context.ENV_VAR] == \
                context.get().header()
        assert context.ENV_VAR not in os.environ

    def test_proc_meta_header_and_tid_stamping(self, tmp_path):
        from jaxmc.obs import context
        tr = tmp_path / "t.jsonl"
        tel = obs.Telemetry(trace_path=str(tr))
        tel.event("log", msg="hello")
        tel.close()
        with open(tr) as fh:
            events = [json.loads(ln) for ln in fh if ln.strip()]
        ctx = context.get()
        meta = events[0]
        assert meta["ev"] == "proc_meta"
        assert meta["pid"] == os.getpid()
        assert meta["psid"] == ctx.span_id
        assert meta["parent_span"] == ctx.parent_span_id
        assert isinstance(meta["mono"], float)
        assert all(e["tid"] == ctx.trace_id for e in events)


class TestProgressEstimator:
    def test_fraction_and_eta_math(self):
        clock = iter(float(i) for i in range(100))
        pe = obs.ProgressEstimator(100, clock=lambda: next(clock))
        assert pe.observe(distinct=10) == 0.10
        assert pe.observe(distinct=40) == 0.40
        s = pe.snapshot()
        assert s["verdict"] == "est" and s["estimate"] == 100
        assert s["rate_states_s"] == 30.0  # (40-10)/(1s)
        assert s["eta_s"] == 2.0           # 60 remaining / 30 per s
        assert "% of est. 100 states" in pe.suffix()

    def test_unbounded_when_no_estimate_or_exceeded(self):
        pe = obs.ProgressEstimator(None)
        assert pe.observe(distinct=5) is None
        assert pe.snapshot()["verdict"] == "unbounded"
        assert pe.suffix() == " (est. unbounded)"
        pe2 = obs.ProgressEstimator(10)
        assert pe2.observe(distinct=11) is None  # bound exceeded
        assert pe2.snapshot()["verdict"] == "unbounded"

    def test_distinct_is_max_accumulated(self):
        pe = obs.ProgressEstimator(100)
        pe.observe(distinct=50)
        pe.observe(distinct=30)   # stale lower reading never regresses
        assert pe.snapshot()["distinct"] == 50
        pe.observe(new=5)
        assert pe.snapshot()["distinct"] == 55

    def test_eta_suffix_empty_without_estimator(self):
        # default runs keep byte-identical progress lines
        assert obs.eta_suffix(10, tel=obs.NullTelemetry()) == ""

    def test_eta_suffix_feeds_gauge(self):
        tel = obs.Telemetry()
        tel.progress_est = obs.ProgressEstimator(200)
        out = obs.eta_suffix(100, tel=tel)
        assert "50% of est. 200 states" in out
        assert tel.gauges["search.progress_est"] == 0.5

    def test_watchdog_heartbeat_carries_progress(self):
        import time
        tel = obs.Telemetry()
        tel.progress_est = obs.ProgressEstimator(100)
        tel.progress_est.observe(distinct=25)
        wd = obs.Watchdog(tel, interval=3600, min_stall_s=7200)
        wd._tick(time.time())
        beats = [e for e in tel.recent_events()
                 if e["ev"] == "heartbeat"]
        assert beats, "no heartbeat in ring"
        assert beats[-1]["progress_fraction"] == 0.25
        assert beats[-1]["progress_verdict"] == "est"


class TestPromName:
    def test_grammar(self):
        assert obs.prom_name("serve.queue_depth") == \
            "jaxmc_serve_queue_depth"
        assert obs.prom_name("search.progress_est") == \
            "jaxmc_search_progress_est"
        assert obs.prom_name("a-b c/d") == "jaxmc_a_b_c_d"


class TestTelemetryRing:
    def test_ring_bounded_and_mid_run_readable(self):
        tel = obs.Telemetry()
        for i in range(5000):
            tel.event("log", msg=f"m{i}")
        evs = tel.recent_events()
        assert len(evs) <= 256 + 8  # ring max + startup events
        assert evs[-1]["msg"] == "m4999"

    def test_null_telemetry_ring_empty(self):
        assert obs.NullTelemetry().recent_events() == []
