r"""Independence-driven hot path (ISSUE 15): per-element container
bounds, commuting-arm regrouping, opt-in POR, and bounds-sized engines.

Pins, all on repo-local fixtures:
  * element-atom footprints: portoy's Step arms commute pairwise
    (cnt[p1]/cnt[p2]/cnt[p3] are distinct atoms), symtoy's shared
    owner/used keep its arms dependent; the group planner beats
    contiguous packing only when it genuinely saves dispatches.
  * per-element bounds: symtoy's EXCEPT-guard container proves
    turns in [0,2]^P — proven element lanes, zero guarded lanes,
    bits/state halved, counts/traces bit-identical analyze on/off;
    record fields keep PER-KEY intervals.
  * verdict taxonomy: dyntoy's multi-binder and nested dynamic \E
    arms are predicted with ground.py's exact reason strings (zero
    futile builds), quantifiers over Nat / unbounded quantifiers
    predict kernel2's exact wording, and the corpus pin_derived
    mechanism fails LOUDLY when the predictor loses coverage.
  * regrouping: byte-identical counts/traces with regrouping on/off
    AND under a deliberately permuted plan (the provenance-restore
    property), on the grouped host_seen path and the mesh-D2 grouped
    expand.
  * POR: --por preserves the ok/deadlock/invariant verdicts across
    serial/parallel/level/resident session configs, reports traces
    that REPLAY under unreduced semantics, cuts portoy's explored
    states >= 30%, and survives a SIGKILL mid-run + resume (chaos).
  * bounds-sized engines: a COLD resident run of the fully-proven
    fixture takes the `predicted` capacity rung and pays exactly one
    compile — no growth-retry recompiles.
"""

import os
import subprocess
import sys

import pytest

from jaxmc import obs
from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer, format_trace

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")
REPO = os.path.dirname(SPECS)


def load(name, cfg_name=None, no_deadlock=False):
    m = Loader([SPECS]).load_path(os.path.join(SPECS, name + ".tla"))
    if cfg_name is None:
        cfg_name = name
    p = os.path.join(SPECS, cfg_name + ".cfg")
    cfg = parse_cfg(open(p).read()) if os.path.exists(p) \
        else ModelConfig(specification="Spec")
    if no_deadlock:
        cfg.check_deadlock = False
    return bind_model(m, cfg)


def write_spec(tmp_path, name, body):
    sp = tmp_path / f"{name}.tla"
    sp.write_text(body)
    return str(sp)


@pytest.fixture(autouse=True)
def _isolated_profiles(tmp_path, monkeypatch):
    monkeypatch.setenv("JAXMC_PROFILE_STORE", str(tmp_path / "prof"))


# ------------------------------------------------- footprints + planner

class TestFootprints:
    def test_portoy_element_atoms_commute(self):
        from jaxmc.analyze.independence import independence_report
        rep = independence_report(load("portoy", "portoy_ok",
                                       no_deadlock=True))
        by = {}
        for i, lb in enumerate(rep.labels):
            by[lb] = i
        s1, s2, s3, fire = (by["Step(p1)"], by["Step(p2)"],
                            by["Step(p3)"], by["Fire"])
        assert rep.commutes[s1][s2] and rep.commutes[s2][s3]
        # Fire reads cnt[p1] (through the CONSTANT P1): dependent on
        # Step(p1) only
        assert not rep.commutes[s1][fire]
        assert rep.commutes[s2][fire] and rep.commutes[s3][fire]
        # no invariant in this cfg: the globally-commuting Steps are
        # por-safe, Step(p1) (dependent on Fire) is not
        assert sorted(rep.por_safe) == sorted((s2, s3))
        fp = rep.footprints[s1]
        assert ("cnt", None) not in fp.writes  # element, not whole-var

    def test_symtoy_shared_vars_block_commutation(self):
        from jaxmc.analyze.independence import independence_report
        rep = independence_report(load("symtoy", no_deadlock=True))
        assert rep.commuting_pairs() == 0  # owner/used shared by Grabs
        # ...but the turns access is still per-element
        grabs = [fp for fp in rep.footprints if fp.label == "Next"]
        assert any(("turns", k) in fp.writes and k is not None
                   for fp in grabs for _v, k in fp.writes)

    def test_plan_arm_groups_shrinks_or_keeps_contiguous(self):
        from jaxmc.analyze.independence import plan_arm_groups
        n = 7
        weights = [2, 2, 2, 3, 1, 1, 1]
        all_commute = [[i != j for j in range(n)] for i in range(n)]
        arm_of = list(range(n))
        groups = plan_arm_groups(weights, arm_of, all_commute, 4)
        assert len(groups) == 3  # contiguous needs 4
        assert sorted(i for g in groups for i in g) == list(range(n))
        for g in groups:
            assert sum(weights[i] for i in g) <= 4
        # no matrix -> legacy contiguous
        base = plan_arm_groups(weights, arm_of, None, 4)
        assert base == [[0, 1], [2], [3, 4], [5, 6]]
        # nothing commutes -> cliques are singletons; contiguous wins
        none_commute = [[False] * n for _ in range(n)]
        assert plan_arm_groups(weights, arm_of, none_commute, 4) == base

    def test_plan_respects_env_optout(self, monkeypatch):
        from jaxmc.analyze.independence import plan_arm_groups
        monkeypatch.setenv("JAXMC_ANALYZE_INDEP", "0")
        weights = [2, 2, 2, 3, 1, 1, 1]
        mat = [[i != j for j in range(7)] for i in range(7)]
        assert plan_arm_groups(weights, list(range(7)), mat, 4) == \
            [[0, 1], [2], [3, 4], [5, 6]]


# ------------------------------------------------- dynamic element keys

class TestDynamicKeys:
    """ISSUE 18: symbolic key-disjointness — tuple keys, binder-domain
    key sets, static key arithmetic, and named bail reasons."""

    def test_msgstoy_send_arms_element_commuting(self):
        from jaxmc.analyze.independence import independence_report
        rep = independence_report(load("msgstoy", no_deadlock=True))
        by = {lb: i for i, lb in enumerate(rep.labels)}
        sends = [by[f"Send({p})"] for p in ("p1", "p2", "p3")]
        for i in sends:
            fp = rep.footprints[i]
            assert fp.exact
            assert ("msgs", None) not in fp.writes
            assert fp.key_class() == "element-commuting"
        for i in sends:
            for j in sends:
                if i != j:
                    assert rep.commutes[i][j]
        # Flush reads msgs[P1] through the CONSTANT: only Send(p1)
        # clashes with it, the other Sends (and Tick) stay por-safe
        assert not rep.commutes[by["Send(p1)"]][by["Flush"]]
        assert rep.commutes[by["Send(p2)"]][by["Flush"]]
        assert sorted(rep.por_safe) == sorted(
            (by["Send(p2)"], by["Send(p3)"], by["Tick"]))

    def test_msgstoy_dynamic_exists_binds_domain_keyset(self):
        from jaxmc.analyze.independence import (_KeySet,
                                                independence_report)
        rep = independence_report(load("msgstoy", no_deadlock=True))
        tick = rep.footprints[rep.labels.index("Tick")]
        ks = [k for v, k in tick.writes if v == "clock"]
        assert len(ks) == 1 and isinstance(ks[0], _KeySet)
        assert ks[0].vals == frozenset((1, 2))  # 1..T through the cfg
        assert tick.key_class() == "element-commuting"

    def test_key_interference_rules(self):
        from jaxmc.analyze.independence import (_interfere, _KeySet,
                                                _TupleKey)
        f = frozenset
        ks12, ks23, ks45 = (_KeySet((1, 2)), _KeySet((2, 3)),
                            _KeySet((4, 5)))
        assert _interfere(f({("v", ks12)}), f({("v", ks23)}))
        assert not _interfere(f({("v", ks12)}), f({("v", ks45)}))
        assert _interfere(f({("v", ks12)}), f({("v", 2)}))
        assert not _interfere(f({("v", ks12)}), f({("v", 3)}))
        assert _interfere(f({("v", None)}), f({("v", ks12)}))
        assert not _interfere(f({("v", ks12)}), f({("w", ks12)}))
        # tuple keys compare componentwise and never equal a scalar
        t12 = _TupleKey((1, 2))
        assert _interfere(f({("v", t12)}), f({("v", _TupleKey((1, 2)))}))
        assert not _interfere(f({("v", t12)}),
                              f({("v", _TupleKey((1, 3)))}))
        assert not _interfere(f({("v", t12)}), f({("v", 1)}))
        assert _interfere(
            f({("v", t12)}),
            f({("v", _TupleKey((_KeySet((1, 9)), 2)))}))
        assert not _interfere(
            f({("v", t12)}),
            f({("v", _TupleKey((_KeySet((3, 9)), 2)))}))

    def test_static_key_arithmetic(self):
        from jaxmc.analyze.independence import (_key_arith, _KeySet,
                                                _NOKEY)
        assert _key_arith("+", 2, 3) == 5
        assert _key_arith("-", 7, 2) == 5
        assert _key_arith("-", _KeySet((1, 2)), 1) == _KeySet((0, 1))
        assert _key_arith("+", "a", 1) is _NOKEY
        assert _key_arith("+", True, 1) is _NOKEY

    def test_tuple_keys_resolve_through_split_bindings(self, tmp_path):
        # the raft message-table shape at analysis level: arms writing
        # distinct <<p, q>> channels commute element-wise, and static
        # +1 arithmetic resolves split-binder keys to concrete ints
        spec = write_spec(tmp_path, "tuptoy", r"""
---------------------------- MODULE tuptoy ----------------------------
EXTENDS Naturals
CONSTANTS Procs
VARIABLES msgs, acks

Chans == {<<p, q>> : p \in Procs, q \in Procs}

Init == /\ msgs = [c \in Chans |-> 0]
        /\ acks = [n \in 1..3 |-> 0]

Send(p, q) == /\ msgs[<<p, q>>] < 2
              /\ msgs' = [msgs EXCEPT ![<<p, q>>] = @ + 1]
              /\ UNCHANGED acks

Shift(n) == /\ acks[n + 1] < 2
            /\ acks' = [acks EXCEPT ![n + 1] = @ + 1]
            /\ UNCHANGED msgs

Next == (\E p \in Procs, q \in Procs : Send(p, q))
          \/ (\E n \in 1..2 : Shift(n))
=======================================================================
""")
        from jaxmc.analyze.independence import (_TupleKey,
                                                independence_report)
        cfg = parse_cfg("INIT Init\nNEXT Next\n"
                        "CONSTANTS\n  Procs = {a, b}\n")
        cfg.check_deadlock = False
        m = bind_model(Loader([str(tmp_path)]).load_path(spec), cfg)
        rep = independence_report(m)
        by = {lb: i for i, lb in enumerate(rep.labels)}
        sab, sba = by["Send(a, b)"], by["Send(b, a)"]
        assert rep.commutes[sab][sba]
        fp = rep.footprints[sab]
        assert fp.exact and ("msgs", None) not in fp.writes
        assert any(isinstance(k, _TupleKey) for _v, k in fp.writes)
        # Shift(1) writes acks[2], Shift(2) writes acks[3]: disjoint
        assert rep.commutes[by["Shift(1)"]][by["Shift(2)"]]
        assert ("acks", 2) in rep.footprints[by["Shift(1)"]].writes
        assert ("acks", 3) in rep.footprints[by["Shift(2)"]].writes
        # every arm resolved to element atoms
        assert all(fp.key_class() == "element-commuting"
                   for fp in rep.footprints)

    def test_bail_reason_named(self, tmp_path):
        spec = write_spec(tmp_path, "bailtoy", r"""
---------------------------- MODULE bailtoy ---------------------------
EXTENDS Naturals
VARIABLES x

Init == x = 0

Rec(n) == IF n = 0 THEN x' = x + 1 ELSE Rec(n - 1)

Next == Rec(x)
=======================================================================
""")
        from jaxmc.analyze.independence import independence_report
        cfg = parse_cfg("INIT Init\nNEXT Next\n")
        cfg.check_deadlock = False
        m = bind_model(Loader([str(tmp_path)]).load_path(spec), cfg)
        rep = independence_report(m)
        fp = rep.footprints[0]
        assert not fp.exact
        assert fp.bail_reason and "Rec" in fp.bail_reason
        assert "full-footprint bail" in fp.key_class()
        assert "Rec" in fp.key_class()


# ------------------------------------------------- per-element bounds

class TestPerElementBounds:
    def test_symtoy_except_guard_container_proves(self):
        from jaxmc.analyze.bounds import infer_state_bounds
        rep = infer_state_bounds(load("symtoy", no_deadlock=True))
        assert rep is not None and rep.converged
        assert rep.lane_bounds().get("turns") == (0, 2)
        eb = rep.element_bounds()["turns"]
        assert eb.rng is not None and eb.rng.all == (0, 2)

    def test_record_fields_keep_per_key_intervals(self, tmp_path):
        spec = write_spec(tmp_path, "rectoy", r"""
---------------------------- MODULE rectoy ----------------------------
EXTENDS Naturals
VARIABLES r

Init == r = [small |-> 0, big |-> 100]

Bump == /\ r.small < 3
        /\ r' = [r EXCEPT !.small = @ + 1]

Next == Bump

Spec == Init /\ [][Next]_<<r>>
=======================================================================
""")
        from jaxmc.analyze.bounds import infer_state_bounds
        m = bind_model(Loader([str(tmp_path)]).load_path(spec),
                       ModelConfig(specification="Spec",
                                   check_deadlock=False))
        rep = infer_state_bounds(m)
        assert rep is not None and rep.converged
        eb = rep.element_bounds()["r"]
        assert eb.keys["small"].all == (0, 3)   # strong field update
        assert eb.keys["big"].all == (100, 100)
        assert rep.lane_bounds()["r"] == (0, 100)

    def test_symtoy_proven_element_lanes_device_parity(self):
        pytest.importorskip("jax")
        from jaxmc.tpu.bfs import TpuExplorer
        ri = Explorer(load("symtoy", no_deadlock=True)).run()
        runs = {}
        for tag, env in (("on", {}), ("off",
                                      {"JAXMC_ANALYZE_BOUNDS": "0"})):
            old = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            tel = obs.Telemetry()
            try:
                with obs.use(tel):
                    r = TpuExplorer(load("symtoy", no_deadlock=True),
                                    store_trace=False).run()
            finally:
                for k, v in old.items():
                    (os.environ.pop(k, None) if v is None
                     else os.environ.__setitem__(k, v))
            runs[tag] = (r, tel)
        for r, _t in runs.values():
            assert (r.distinct, r.generated) == (ri.distinct,
                                                 ri.generated)
        tel_on, tel_off = runs["on"][1], runs["off"][1]
        # the 3 turns element lanes prove; nothing stays guarded
        assert tel_on.gauges.get("analyze.proven_lanes") == 3
        assert tel_on.gauges.get("layout.pack_guarded_lanes") == 0
        assert tel_off.gauges.get("analyze.proven_lanes") == 0
        assert tel_on.gauges.get("layout.bits_per_state") < \
            tel_off.gauges.get("layout.bits_per_state")

    def test_state_space_estimates(self):
        from jaxmc.analyze.bounds import (infer_state_bounds,
                                          state_space_estimate)
        m = load("portoy", "portoy_ok", no_deadlock=True)
        assert state_space_estimate(m, infer_state_bounds(m)) == 432
        m = load("symtoy", no_deadlock=True)
        est = state_space_estimate(m, infer_state_bounds(m))
        assert est is not None and est >= 22  # covers the real 22
        # racing unbounded counters must NOT produce an estimate
        m = load("transfer_scaled")
        assert state_space_estimate(m, infer_state_bounds(m)) is None


# ------------------------------------------------- verdict taxonomy

class TestVerdictTaxonomy:
    def test_dyntoy_predicted_equals_built(self):
        pytest.importorskip("jax")
        from jaxmc import native_store
        from jaxmc.tpu.bfs import TpuExplorer
        from jaxmc.analyze import predict_arm_demotions
        from jaxmc.compile.ground import (DYN_NESTED_MSG,
                                          DYN_SHAPE_MSG, split_arms)
        m = load("dyntoy")
        arms = split_arms(m)
        pred = {arms[i].label: r for i, r in
                predict_arm_demotions(m, arms).items()}
        assert pred == {"Pair": DYN_SHAPE_MSG, "Relay": DYN_NESTED_MSG}
        if not native_store.is_available():
            pytest.skip("hybrid needs the native store")
        old = os.environ.get("JAXMC_ANALYZE_PREDICT")
        os.environ["JAXMC_ANALYZE_PREDICT"] = "0"
        try:
            ex = TpuExplorer(load("dyntoy"), store_trace=False,
                             host_seen=True)
        finally:
            (os.environ.pop("JAXMC_ANALYZE_PREDICT", None) if old is
             None else os.environ.__setitem__("JAXMC_ANALYZE_PREDICT",
                                              old))
        built = {a.label: w for a, w in ex.fb_arms}
        assert built == pred  # identical wording, both classes

    def test_quantifier_domain_classes_predicted(self, tmp_path):
        """The two new taxonomy classes carry kernel2's raise-site
        constants (UNBOUNDED_QUANTIFIER_MSG / cannot_enumerate_message
        — the same one-constant contract the unroll message pins).  No
        engine build here: a spec quantifying over Nat in an enabled
        guard is uncheckable by EVERY backend, so the predictor is the
        only component that can name it before the crash."""
        spec = write_spec(tmp_path, "quanttoy", r"""
--------------------------- MODULE quanttoy ---------------------------
EXTENDS Naturals
VARIABLES n

Init == n = 0

OverNat == /\ \A m \in Nat : m >= 0
           /\ n' = n + 1

Unbounded == /\ \A m : m = m
             /\ n' = n

Next == OverNat \/ Unbounded

Spec == Init /\ [][Next]_<<n>>
=======================================================================
""")
        from jaxmc.analyze import predict_arm_demotions
        from jaxmc.compile.ground import split_arms
        from jaxmc.compile.kernel2 import (UNBOUNDED_QUANTIFIER_MSG,
                                           cannot_enumerate_message)
        from jaxmc.sem.values import InfiniteSet
        m = bind_model(Loader([str(tmp_path)]).load_path(spec),
                       ModelConfig(specification="Spec",
                                   check_deadlock=False))
        arms = split_arms(m)
        pred = {arms[i].label: r for i, r in
                predict_arm_demotions(m, arms).items()}
        assert pred.get("OverNat") == \
            cannot_enumerate_message(InfiniteSet("Nat")) == \
            "cannot enumerate Nat"
        assert pred.get("Unbounded") == UNBOUNDED_QUANTIFIER_MSG == \
            "unbounded quantifier"

    def test_predictor_still_silent_on_compilable_fixtures(self):
        from jaxmc.analyze import predict_arm_demotions
        from jaxmc.compile.ground import split_arms
        for name, cfg in (("portoy", "portoy_ok"),
                          ("viewtoy", None), ("constoy", None)):
            m = load(name, cfg, no_deadlock=True)
            assert predict_arm_demotions(m, split_arms(m)) == {}, name

    def test_corpus_pin_derived_mechanism(self, monkeypatch):
        pytest.importorskip("jax")
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("hybrid needs the native store")
        from jaxmc.corpus import CASES, run_case
        case = next(c for c in CASES
                    if (c.cfg_path() or "").endswith("dyntoy.cfg"))
        assert case.pin_derived
        s, d, _r, mode = run_case(case, "jax")
        assert s == "pass" and mode == "interp-arms"
        assert "[pin derived by predictor]" in d
        # a predictor that loses coverage FAILS the case loudly
        import jaxmc.analyze as _an
        monkeypatch.setattr(_an, "predict_arm_demotions",
                            lambda model, arms: {})
        s2, d2, _r2, _m2 = run_case(case, "jax")
        assert s2 == "fail" and "PREDICTOR REGRESSION" in d2
        # ...and JAXMC_PIN_DERIVE=0 restores the measured pin
        monkeypatch.setenv("JAXMC_PIN_DERIVE", "0")
        s3, d3, _r3, m3 = run_case(case, "jax")
        assert s3 == "pass" and m3 == "interp-arms"
        assert "[pin derived by predictor]" not in d3


# ------------------------------------------------- regroup parity

def _device_run(model, tel=None, **kw):
    from jaxmc.tpu.bfs import TpuExplorer
    tel = tel or obs.Telemetry()
    with obs.use(tel):
        ex = TpuExplorer(model, **kw)
        r = ex.run()
    return r, tel


@pytest.mark.usefixtures("_isolated_profiles")
class TestRegroupParity:
    @pytest.mark.parametrize("name,cfg,ndl", [
        ("portoy", "portoy_bad", False),
        ("symtoy", "symtoy", True),
    ])
    def test_grouped_host_seen_byte_identical(self, name, cfg, ndl,
                                              monkeypatch):
        pytest.importorskip("jax")
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("needs the native store")
        monkeypatch.setenv("JAXMC_FUSED_MAX_INSTANCES", "2")
        results = {}
        for indep in ("1", "0"):
            monkeypatch.setenv("JAXMC_ANALYZE_INDEP", indep)
            r, tel = _device_run(load(name, cfg, no_deadlock=ndl),
                                 host_seen=True)
            assert tel.gauges.get("expand.fused_groups", 0) >= 2
            results[indep] = r
        a, b = results["1"], results["0"]
        assert (a.distinct, a.generated, a.ok) == \
            (b.distinct, b.generated, b.ok)
        if a.violation is not None:
            assert format_trace(a.violation) == \
                format_trace(b.violation)

    def test_permuted_plan_provenance_restored(self, monkeypatch):
        """ANY group permutation must be byte-identical — the scatter
        at the merge restores original instance order."""
        pytest.importorskip("jax")
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("needs the native store")
        from jaxmc.tpu.bfs import TpuExplorer
        monkeypatch.setenv("JAXMC_FUSED_MAX_INSTANCES", "2")
        base, _ = _device_run(load("portoy", "portoy_bad"),
                              host_seen=True)
        monkeypatch.setattr(
            TpuExplorer, "_arm_group_plan",
            lambda self, fused_max: [[3, 1], [2, 0]])
        perm, tel = _device_run(load("portoy", "portoy_bad"),
                                host_seen=True)
        assert (perm.distinct, perm.generated, perm.ok) == \
            (base.distinct, base.generated, base.ok)
        assert format_trace(perm.violation) == \
            format_trace(base.violation)

    def test_mesh_d2_grouped_byte_identical(self, monkeypatch):
        pytest.importorskip("jax")
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_FUSED_MAX_INSTANCES", "2")
        monkeypatch.setenv("JAXMC_MESH_GROUPED", "1")
        results = {}
        for indep in ("1", "0"):
            monkeypatch.setenv("JAXMC_ANALYZE_INDEP", indep)
            tel = obs.Telemetry()
            with obs.use(tel):
                r = MeshExplorer(load("portoy", "portoy_ok",
                                      no_deadlock=True)).run()
            assert tel.gauges.get("mesh.grouped_expand", 0) >= 2
            results[indep] = r
        a, b = results["1"], results["0"]
        assert (a.distinct, a.generated) == (b.distinct, b.generated) \
            == (150, 366)


# ------------------------------------------------- POR

def _replays(model, trace):
    """Every step of a reported trace must be a REAL transition of the
    unreduced semantics (the --por trace-validity contract)."""
    from jaxmc.sem.enumerate import enumerate_init, enumerate_next
    ctx = model.ctx()
    inits = enumerate_init(model.init, ctx, model.vars)
    assert trace[0][0] in inits, "trace root is not an initial state"
    for (s0, _l0), (s1, _l1) in zip(trace, trace[1:]):
        succs = [succ for succ, _ in
                 enumerate_next(model.next, ctx, model.vars, s0)]
        assert s1 in succs, "trace step is not an unreduced transition"


class TestPOR:
    def test_por_reduction_and_trace_replay(self):
        m = load("portoy", "portoy_bad")
        tel = obs.Telemetry()
        with obs.use(tel):
            r = Explorer(m, por=True).run()
        full = Explorer(load("portoy", "portoy_bad")).run()
        assert not r.ok and r.violation.kind == "invariant" \
            and full.violation.kind == "invariant"
        assert r.distinct < full.distinct
        assert tel.gauges.get("por.enabled") is True
        assert tel.gauges.get("por.ample_ratio") > 0
        assert tel.gauges.get("por.reduced_states") == r.distinct
        _replays(m, r.violation.trace)

    def test_por_thirty_percent_reduction_acceptance(self):
        full = Explorer(load("portoy", "portoy_ok",
                             no_deadlock=True)).run()
        red = Explorer(load("portoy", "portoy_ok", no_deadlock=True),
                       por=True).run()
        assert full.ok and red.ok
        assert red.distinct <= 0.7 * full.distinct, \
            f"{red.distinct} vs {full.distinct}: < 30% reduction"

    def test_por_deadlock_verdict_and_replay(self):
        m = load("portoy", "portoy")
        r = Explorer(m, por=True).run()
        assert not r.ok and r.violation.kind == "deadlock"
        _replays(m, r.violation.trace)
        # the deadlock state must genuinely deadlock unreduced
        from jaxmc.sem.enumerate import enumerate_next
        last = r.violation.trace[-1][0]
        assert not list(enumerate_next(m.next, m.ctx(), m.vars, last))

    def test_por_disabled_with_named_reason(self):
        # symtoy declares SYMMETRY: POR must refuse, run unreduced,
        # and say why
        ri = Explorer(load("symtoy", no_deadlock=True)).run()
        tel = obs.Telemetry()
        with obs.use(tel):
            r = Explorer(load("symtoy", no_deadlock=True),
                         por=True).run()
        assert (r.distinct, r.generated) == (ri.distinct, ri.generated)
        assert "SYMMETRY" in tel.gauges.get("por.disabled_reason", "")
        assert any("--por requested but reduction disabled" in w
                   for w in r.warnings)

    @pytest.mark.parametrize("scfg", [
        {"backend": "interp", "workers": 1},
        {"backend": "interp", "workers": 3},
        {"backend": "jax", "platform": "cpu"},
        {"backend": "jax", "platform": "cpu", "resident": True,
         "no_trace": True},
        {"backend": "jax", "platform": "cpu", "host_seen": True},
    ])
    def test_por_verdict_parity_across_engines(self, scfg):
        """--por through CheckSession: every engine config reports the
        SAME violation verdict its unreduced run reports.  Since ISSUE
        18 the jax configs run the ample mask INSIDE the fused device
        step (por.engine == "device"), not the interpreter demotion."""
        if scfg["backend"] == "jax":
            pytest.importorskip("jax")
        from jaxmc.session import CheckSession, SessionConfig
        spec = os.path.join(SPECS, "portoy.tla")
        cfgp = os.path.join(SPECS, "portoy_bad.cfg")
        base = CheckSession(SessionConfig(spec=spec, cfg=cfgp, **scfg))
        rb = base.explore()
        tel = obs.Telemetry()
        with obs.use(tel):
            s = CheckSession(SessionConfig(spec=spec, cfg=cfgp,
                                           por=True, **scfg))
            rp = s.explore()
        assert not rb.ok and not rp.ok
        assert rp.violation.kind == rb.violation.kind == "invariant"
        assert rp.distinct <= rb.distinct
        if not scfg.get("no_trace"):
            _replays(load("portoy", "portoy_bad"), rp.violation.trace)
        if scfg["backend"] == "jax":
            assert tel.gauges.get("por.engine") == "device"
            assert tel.gauges.get("por.device_masked_arms", 0) > 0
        elif scfg.get("workers", 1) > 1:
            assert tel.gauges.get("parallel.fallback_reason") == "por"

    def test_por_rides_the_job_signature(self):
        from jaxmc.session import SessionConfig
        from jaxmc.serve.protocol import build_config, job_signature
        spec = os.path.join(SPECS, "portoy.tla")
        cfgp = os.path.join(SPECS, "portoy_bad.cfg")
        a = job_signature(SessionConfig(spec=spec, cfg=cfgp))
        b = job_signature(SessionConfig(spec=spec, cfg=cfgp, por=True))
        assert a != b  # reduced and unreduced runs are different jobs
        cfg = build_config(spec, cfgp, {"por": True})
        assert cfg.por is True


@pytest.mark.chaos
@pytest.mark.slow
class TestPORChaos:
    def test_sigkill_midrun_por_resume_parity(self, tmp_path):
        """SIGKILL a --por run mid-level; the resumed --por run must
        finish with counts identical to an uninterrupted --por run
        (the ample choice is a deterministic function of the seen
        set, which the checkpoint preserves)."""
        spec = os.path.join(SPECS, "portoy.tla")
        args = [spec, "--cfg", os.path.join(SPECS, "portoy_ok.cfg"),
                "--no-deadlock", "--por"]

        def cli(extra, env_extra=None):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       **(env_extra or {}))
            return subprocess.run(
                [sys.executable, "-m", "jaxmc", "check"] + args + extra,
                capture_output=True, text=True, cwd=REPO, env=env,
                timeout=300)

        clean = cli([])
        assert clean.returncode == 0, clean.stderr
        ck = str(tmp_path / "por.ck")
        killed = cli(["--checkpoint", ck, "--checkpoint-every", "0",
                      "--quiet"],
                     {"JAXMC_FAULTS": "run_kill:level=3"})
        assert killed.returncode in (-9, 137), killed.stderr
        assert os.path.exists(ck), "no checkpoint survived the kill"
        resumed = cli(["--resume", ck])
        assert resumed.returncode == 0, resumed.stderr

        def counts(stdout):
            for line in stdout.splitlines():
                if "states generated," in line and \
                        "distinct states found" in line and \
                        "states/sec" in line:
                    parts = line.split()
                    return int(parts[0]), int(parts[3])
            raise AssertionError(f"no summary in:\n{stdout}")

        assert counts(resumed.stdout) == counts(clean.stdout)


# ------------------------------------------------- bounds-sized engines

class TestPredictedCapacityRung:
    def test_cold_resident_run_zero_growth_recompiles(self):
        """Acceptance: a fully-proven spec with NO saved capacity
        profile completes with zero in-window recompiles — the
        predicted rung sizes every bucket from the bounds fixpoint."""
        pytest.importorskip("jax")
        m = load("portoy", "portoy_ok", no_deadlock=True)
        r, tel = _device_run(m, resident=True, store_trace=False)
        assert r.ok and (r.generated, r.distinct) == (366, 150)
        assert tel.gauges.get("profile.predicted_states") == 432
        assert tel.gauges.get("profile.predicted_caps")
        fresh = [bool(lv.get("fresh_compile")) for lv in tel.levels]
        assert sum(fresh) == 1 and fresh[0], \
            f"growth recompiles on the predicted rung: {tel.levels}"

    def test_prediction_refused_when_unproven(self, monkeypatch):
        pytest.importorskip("jax")
        # transfer-style racing counters: no estimate, no prediction —
        # the ladder falls through to the platform defaults as before
        from jaxmc.tpu.bfs import TpuExplorer
        tel = obs.Telemetry()
        with obs.use(tel):
            ex = TpuExplorer(load("viewtoy"), store_trace=False,
                             resident=True)
        assert tel.gauges.get("profile.predicted_states") == 15
        monkeypatch.setenv("JAXMC_PREDICT_MAX", "0")
        tel2 = obs.Telemetry()
        with obs.use(tel2):
            TpuExplorer(load("viewtoy"), store_trace=False,
                        resident=True)
        assert tel2.gauges.get("profile.predicted_states") is None

    def test_fast_lane_reads_widened_estimate(self):
        from jaxmc.session import SessionConfig, batch_profile
        prof = batch_profile(SessionConfig(
            spec=os.path.join(SPECS, "portoy.tla"),
            cfg=os.path.join(SPECS, "portoy_ok.cfg"),
            backend="jax", host_seen=True))
        # enum/bool/fun cardinalities now estimate specs the pure-int
        # rule refused: the serve fast lane gets a real cost bound
        assert prof is not None and prof.cost_estimate == 432
