r"""JAX backend tests: kernel compilation, device BFS, mesh sharding.

Equivalence contract (BASELINE.json): identical reachable-state counts
between BACKEND=interp and BACKEND=jax on full (non-violating) runs; same
verdicts on violating ones. Runs on CPU; conftest provides an 8-device
virtual mesh.
"""

import os

import numpy as np
import pytest

from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model

from conftest import REFERENCE, needs_reference

SPECS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "specs")


def load(path, cfg=None):
    m = Loader([os.path.dirname(os.path.abspath(path))]).load_path(path)
    return bind_model(m, cfg or ModelConfig(specification="Spec"))


@pytest.fixture(scope="module")
def pcal_model():
    cfg = parse_cfg(open(os.path.join(REFERENCE, "pcal_intro.cfg")).read())
    return load(os.path.join(REFERENCE, "pcal_intro.tla"), cfg)


class TestLayout:
    @needs_reference
    def test_roundtrip(self, pcal_model):
        from jaxmc.compile.vspec import Bounds
        from jaxmc.compile.kernel2 import build_layout2
        from jaxmc.sem.enumerate import enumerate_init
        inits = enumerate_init(pcal_model.init, pcal_model.ctx(),
                               pcal_model.vars)
        lay = build_layout2(pcal_model, inits, Bounds())
        for st in inits[:10]:
            row = lay.encode(st)
            back = lay.decode(row)
            assert back == st

    @needs_reference
    def test_grounding_labels(self, pcal_model):
        from jaxmc.compile.ground import ground_actions
        gas = ground_actions(pcal_model)
        labels = {g.label for g in gas}
        assert any(l.startswith("Transfer(") for l in labels)
        assert "Terminating" in labels


class TestDeviceBFS:
    @needs_reference
    def test_atomic_add_counts(self):
        from jaxmc.tpu.bfs import TpuExplorer
        model = load(os.path.join(REFERENCE, "atomic_add.tla"))
        r = TpuExplorer(model).run()
        assert r.ok and r.distinct == 5 and r.generated == 7

    @needs_reference
    def test_pcal_intro_matches_interp(self, pcal_model):
        from jaxmc.tpu.bfs import TpuExplorer
        r = TpuExplorer(pcal_model).run()
        assert r.ok
        assert r.distinct == 3800     # == interpreter == oracle counts
        assert r.generated == 5850

    def test_buggy_assert_found_with_trace(self):
        from jaxmc.tpu.bfs import TpuExplorer
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        r = TpuExplorer(model).run()
        assert not r.ok and r.violation.kind == "assert"
        assert len(r.violation.trace) == 6  # same depth as TLC's trace
        # the trace must be a genuine behavior: replay it on the interpreter
        from jaxmc.sem.enumerate import enumerate_init, enumerate_next
        ctx = model.ctx()
        inits = enumerate_init(model.init, ctx, model.vars)
        assert r.violation.trace[0][0] in inits
        for (st, _), (succ, _) in zip(r.violation.trace,
                                      r.violation.trace[1:]):
            succs = []
            try:
                for s2, _lbl in enumerate_next(model.next, ctx, model.vars,
                                               st):
                    succs.append(s2)
            except Exception:
                pass  # assert may fire during full expansion
            assert succ in succs

    def test_invariant_violation(self):
        from jaxmc.tpu.bfs import TpuExplorer
        cfg = ModelConfig(specification="Spec",
                          invariants=["MoneyInvariant"])
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"), cfg)
        r = TpuExplorer(model).run()
        assert not r.ok and r.violation.kind == "invariant"
        assert r.violation.name == "MoneyInvariant"
        # violating state really violates it
        st = r.violation.trace[-1][0]
        assert st["alice_account"] + st["bob_account"] != st["account_total"]


def _replay_trace(model, trace):
    """The trace must be a genuine behavior: its head an initial state,
    every step an enabled transition (interpreter replay)."""
    from jaxmc.sem.enumerate import enumerate_init, enumerate_next
    ctx = model.ctx()
    inits = enumerate_init(model.init, ctx, model.vars)
    assert trace[0][0] in inits
    for (st, _), (succ, _) in zip(trace, trace[1:]):
        succs = []
        try:
            for s2, _lbl in enumerate_next(model.next, ctx, model.vars,
                                           st):
                succs.append(s2)
        except Exception:
            pass  # assert may fire during full expansion
        assert succ in succs


class TestMesh:
    @needs_reference
    def test_pcal_intro_mesh_counts(self, pcal_model):
        import jax
        from jaxmc.tpu.mesh import MeshExplorer
        assert len(jax.devices()) >= 8
        r = MeshExplorer(pcal_model).run()
        assert r.ok
        assert r.distinct == 3800
        assert r.generated == 5850

    @needs_reference
    def test_atomic_add_mesh(self):
        from jaxmc.tpu.mesh import MeshExplorer
        model = load(os.path.join(REFERENCE, "atomic_add.tla"))
        r = MeshExplorer(model).run()
        assert r.ok and r.distinct == 5 and r.generated == 7

    # ---- mesh parity (VERDICT r2 #5): traces, named violations,
    # checkpoint/resume ----

    def test_mesh_assert_violation_trace_replays(self):
        from jaxmc.tpu.mesh import MeshExplorer
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        r = MeshExplorer(model).run()
        assert not r.ok and r.violation.kind == "assert"
        # mesh BFS finds a shortest-path trace with action provenance
        assert len(r.violation.trace) == 6  # TLC's depth
        assert r.violation.trace[-1][1] != "Initial predicate"
        _replay_trace(model, r.violation.trace)

    def test_mesh_invariant_violation_named_with_trace(self):
        from jaxmc.tpu.mesh import MeshExplorer
        cfg = ModelConfig(specification="Spec",
                          invariants=["MoneyInvariant"])
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"), cfg)
        r = MeshExplorer(model).run()
        assert not r.ok and r.violation.kind == "invariant"
        assert r.violation.name == "MoneyInvariant"  # NAMED (r2: generic)
        st = r.violation.trace[-1][0]
        assert st["alice_account"] + st["bob_account"] != \
            st["account_total"]
        _replay_trace(model, r.violation.trace)

    @needs_reference
    def test_mesh_checkpoint_resume_exact(self, pcal_model, tmp_path):
        from jaxmc.tpu.mesh import MeshExplorer
        ck = str(tmp_path / "mesh.ck")
        r1 = MeshExplorer(pcal_model, max_states=1000,
                          checkpoint_path=ck, checkpoint_every=0).run()
        assert r1.truncated and os.path.exists(ck)
        r2 = MeshExplorer(pcal_model, resume_from=ck).run()
        assert r2.ok
        # resumed full-run counts match the direct full run exactly
        assert r2.distinct == 3800 and r2.generated == 5850

    @needs_reference
    def test_mesh_a2a_exchange_counts_and_trace(self, pcal_model):
        # hash-routed all_to_all exchange (SURVEY §2.3 comm rows): same
        # exact counts as the all_gather path, provenance intact through
        # the routed src-index lane
        from jaxmc.tpu.mesh import MeshExplorer
        r = MeshExplorer(pcal_model, exchange="a2a").run()
        assert r.ok
        assert r.distinct == 3800 and r.generated == 5850
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        r2 = MeshExplorer(model, exchange="a2a").run()
        assert not r2.ok and r2.violation.kind == "assert"
        assert len(r2.violation.trace) == 6
        _replay_trace(model, r2.violation.trace)

    @needs_reference
    def test_mesh_a2a_bucket_overflow_grows_gamma(self, pcal_model):
        # force a tiny capacity factor: the first level must overflow
        # the per-peer bucket, double gamma (possibly repeatedly), and
        # still finish with EXACT counts
        from jaxmc.tpu.mesh import MeshExplorer
        ex = MeshExplorer(pcal_model, exchange="a2a")
        ex._a2a_gamma = 0.05
        r = ex.run()
        assert r.ok
        assert r.distinct == 3800 and r.generated == 5850
        assert ex._a2a_gamma > 0.05  # growth actually happened

    def test_mesh_deadlock_trace(self, tmp_path):
        from jaxmc.tpu.mesh import MeshExplorer
        spec = tmp_path / "countdown.tla"
        spec.write_text("""---- MODULE countdown ----
EXTENDS Naturals
VARIABLE n
Init == n = 3
Next == n > 0 /\\ n' = n - 1
Spec == Init /\\ [][Next]_n
====""")
        model = load(str(spec))
        r = MeshExplorer(model).run()
        assert not r.ok and r.violation.kind == "deadlock"
        # deadlocked at n=0, depth 3: full provenance trace
        assert len(r.violation.trace) == 4
        assert r.violation.trace[-1][0]["n"] == 0
        _replay_trace(model, r.violation.trace)


class TestGraftEntry:
    @needs_reference
    def test_entry_compiles(self):
        import sys
        sys.path.insert(0, os.path.dirname(SPECS))
        import importlib
        import __graft_entry__ as g
        importlib.reload(g)
        import jax
        fn, args = g.entry()
        en, succ = jax.jit(fn)(*args)
        assert en.shape[1] == args[0].shape[0]
        assert succ.shape[-1] == args[0].shape[1]

    @needs_reference
    def test_dryrun_multichip(self):
        import sys
        sys.path.insert(0, os.path.dirname(SPECS))
        import __graft_entry__ as g
        g.dryrun_multichip(8)


class TestHostSeen:
    @needs_reference
    def test_host_seen_exact_counts(self):
        from jaxmc import native_store
        if not native_store.is_available():
            import pytest
            pytest.skip("no native toolchain")
        from jaxmc.tpu.bfs import TpuExplorer
        cfg = parse_cfg(open(os.path.join(REFERENCE, "pcal_intro.cfg")).read())
        model = load(os.path.join(REFERENCE, "pcal_intro.tla"), cfg)
        r = TpuExplorer(model, host_seen=True).run()
        assert r.ok and r.distinct == 3800 and r.generated == 5850

    def test_host_seen_finds_violation_with_trace(self):
        from jaxmc import native_store
        if not native_store.is_available():
            import pytest
            pytest.skip("no native toolchain")
        from jaxmc.tpu.bfs import TpuExplorer
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        r = TpuExplorer(model, host_seen=True).run()
        assert not r.ok and r.violation.kind == "assert"
        assert len(r.violation.trace) >= 2


class TestDeviceSymmetry:
    # cfg SYMMETRY on the device backends (VERDICT r1 #7): rows are
    # canonicalized to orbit representatives before fingerprinting
    # (compile/symmetry2.py), so device counts equal the interp's
    # symmetry-reduced counts

    def test_symtoy_reduced_counts_match_interp(self):
        from jaxmc.engine.explore import Explorer
        from jaxmc.tpu.bfs import TpuExplorer
        cfg = parse_cfg(open(os.path.join(SPECS, "symtoy.cfg")).read())
        cfg.check_deadlock = False
        model = load(os.path.join(SPECS, "symtoy.tla"), cfg)
        ri = Explorer(model).run()
        ex = TpuExplorer(model)
        assert ex.canon_fn is not None
        rj = ex.run()
        assert ri.ok and rj.ok
        # symmetry-reduced (unreduced would be 109/81)
        assert (ri.generated, ri.distinct) == (33, 22)
        assert (rj.generated, rj.distinct) == (33, 22)
        assert not rj.warnings  # reduction applied: no SYMMETRY warning

    def test_multiinit_orbit_dedup_matches_interp(self):
        # advisor r2 high: with `Init == owner \in P` the |P| raw init
        # states share one orbit; _prepare_init must dedup them by
        # canonical representative or device counts inflate (and seen is
        # seeded with duplicate canonical fingerprints)
        from jaxmc.engine.explore import Explorer
        from jaxmc.tpu.bfs import TpuExplorer
        cfg = parse_cfg(
            open(os.path.join(SPECS, "symtoy_multiinit.cfg")).read())
        cfg.check_deadlock = False
        model = load(os.path.join(SPECS, "symtoy_multiinit.tla"), cfg)
        ri = Explorer(model).run()
        assert ri.ok
        ex = TpuExplorer(model)
        assert ex.canon_fn is not None
        rj = ex.run()
        exr = TpuExplorer(model, resident=True)
        rr = exr.run()
        assert rj.ok and rr.ok
        assert (rj.generated, rj.distinct) == (ri.generated, ri.distinct)
        assert (rr.generated, rr.distinct) == (ri.generated, ri.distinct)

    @pytest.mark.slow
    def test_mcvoting_reduced_counts_match_interp(self):
        # the corpus's symmetry workhorse (MCPaxos's symmetry is the
        # identity over its singleton sets): growset-of-records lanes
        # exercise the element-remap + segment re-sort transform
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples", "Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCVoting.cfg")).read())
        cfg.check_deadlock = False
        model = load(os.path.join(d, "MCVoting.tla"), cfg)
        ex = TpuExplorer(model)
        assert ex.canon_fn is not None
        r = ex.run()
        assert r.ok
        assert (r.generated, r.distinct) == (406, 77)  # interp pin


class TestDeviceCheckpoint:
    # checkpoint/resume on the device backends (VERDICT r1 #7): every
    # device mode checkpoints at level/dispatch boundaries and a resumed
    # run must finish with IDENTICAL full-run counts and verdicts

    def _pcal(self):
        cfg = parse_cfg(open(os.path.join(REFERENCE,
                                          "pcal_intro.cfg")).read())
        return load(os.path.join(REFERENCE, "pcal_intro.tla"), cfg)

    @needs_reference
    def test_level_mode_resume_exact(self, tmp_path):
        from jaxmc.tpu.bfs import TpuExplorer
        ckp = str(tmp_path / "ck.pkl")
        model = self._pcal()
        r1 = TpuExplorer(model, checkpoint_path=ckp,
                         checkpoint_every=0.0).run()
        assert r1.ok and (r1.generated, r1.distinct) == (5850, 3800)
        assert os.path.exists(ckp)
        r2 = TpuExplorer(model, resume_from=ckp).run()
        assert r2.ok
        assert (r2.generated, r2.distinct) == (5850, 3800)
        assert r2.diameter == r1.diameter

    def test_level_mode_resume_finds_violation_with_trace(self, tmp_path):
        from jaxmc.tpu.bfs import TpuExplorer
        ckp = str(tmp_path / "ck.pkl")
        model = load(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        r1 = TpuExplorer(model, checkpoint_path=ckp,
                         checkpoint_every=0.0).run()
        assert not r1.ok and os.path.exists(ckp)
        r2 = TpuExplorer(model, resume_from=ckp).run()
        assert not r2.ok and r2.violation.kind == r1.violation.kind
        # the restored trace levels still reconstruct a full trace
        assert len(r2.violation.trace) >= 2

    @needs_reference
    def test_host_seen_resume_exact(self, tmp_path):
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("no native toolchain")
        from jaxmc.tpu.bfs import TpuExplorer
        ckp = str(tmp_path / "ck.pkl")
        model = self._pcal()
        r1 = TpuExplorer(model, host_seen=True, checkpoint_path=ckp,
                         checkpoint_every=0.0).run()
        assert r1.ok and os.path.exists(ckp)
        r2 = TpuExplorer(model, host_seen=True, resume_from=ckp).run()
        assert r2.ok
        assert (r2.generated, r2.distinct) == (5850, 3800)

    @needs_reference
    def test_resident_resume_exact(self, tmp_path):
        from jaxmc.tpu.bfs import TpuExplorer
        ckp = str(tmp_path / "ck.pkl")
        model = self._pcal()
        ex = TpuExplorer(model, resident=True, chunk=256,
                         checkpoint_path=ckp, checkpoint_every=0.0)
        ex._res_maxlvl = 1  # checkpoint between every level
        r1 = ex.run()
        assert r1.ok and os.path.exists(ckp)
        ex2 = TpuExplorer(model, resident=True, chunk=256,
                          resume_from=ckp)
        ex2._res_maxlvl = 1
        r2 = ex2.run()
        assert r2.ok
        assert (r2.generated, r2.distinct) == (5850, 3800)

    @needs_reference
    def test_resume_mode_mismatch_rejected(self, tmp_path):
        from jaxmc.tpu.bfs import TpuExplorer
        ckp = str(tmp_path / "ck.pkl")
        model = self._pcal()
        TpuExplorer(model, checkpoint_path=ckp,
                    checkpoint_every=0.0).run()
        with pytest.raises(ValueError, match="device mode"):
            TpuExplorer(model, resident=True, resume_from=ckp).run()


class TestResident:
    # resident mode: the whole BFS inside one jitted while_loop
    # (tpu/bfs.py _run_resident) — built for the high-latency TPU tunnel;
    # counts must still match the interpreter exactly

    @staticmethod
    def _raft_micro():
        ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
        return bind_model(
            ldr.load_path(os.path.join(SPECS, "MCraftMicro.tla")),
            parse_cfg(open(os.path.join(SPECS, "MCraft_micro.cfg")).read()))

    @needs_reference
    def test_raft_micro_exact_counts_and_truncation(self):
        # flagship workload at the scale that completes (pinned 6185/694
        # in test_kernel2 for interp/host_seen); small chunk exercises
        # the multi-chunk accumulator path
        from jaxmc.tpu.bfs import TpuExplorer
        ex = TpuExplorer(self._raft_micro(), resident=True, chunk=128)
        r = ex.run()
        assert r.ok
        assert (r.generated, r.distinct) == (6185, 694)

        # truncation at a state limit (same instance: jit cache reused)
        ex.max_states = 100
        r2 = ex.run()
        assert r2.ok and r2.truncated and r2.distinct >= 100

    @pytest.mark.slow
    def test_resident_growth_redo_exactness(self):
        # tiny starting caps force every grow-and-redo status (each
        # growth recompiles, hence slow-marked); counts stay exact
        from jaxmc.tpu.bfs import TpuExplorer
        ex = TpuExplorer(self._raft_micro(), resident=True, chunk=128)
        ex._res_caps = {"SC": 1 << 8, "FCap": 128, "AccCap": 1 << 9,
                        "VC": 1 << 8}
        r = ex.run()
        assert r.ok
        assert (r.generated, r.distinct) == (6185, 694)
        # capacities were learned by growth during the run
        assert ex._res_caps["SC"] >= 1024

    def test_resident_deadlock_depth_matches_interp(self, tmp_path):
        # deadlock states live in the CURRENT frontier: resident must
        # report the same diameter as the interp backend (regression:
        # the level loop used to advance depth before exiting)
        from jaxmc.engine.explore import Explorer
        from jaxmc.tpu.bfs import TpuExplorer
        spec = tmp_path / "cnt.tla"
        spec.write_text("""---- MODULE cnt ----
EXTENDS Naturals
VARIABLE x
Init == x = 0
Next == x < 2 /\\ x' = x + 1
Spec == Init /\\ [][Next]_x
====
""")
        model = load(str(spec), ModelConfig(specification="Spec"))
        ri = Explorer(model).run()
        rr = TpuExplorer(model, resident=True).run()
        assert not ri.ok and not rr.ok
        assert ri.violation.kind == rr.violation.kind == "deadlock"
        assert ri.diameter == rr.diameter

    @needs_reference
    def test_resident_rejects_host_seen_combo(self):
        # mutually exclusive seen-set homes: must be diagnosed up front,
        # not silently resolved in favor of one mode
        from jaxmc.compile.vspec import CompileError
        from jaxmc.tpu.bfs import TpuExplorer
        with pytest.raises(CompileError, match="mutually exclusive"):
            TpuExplorer(self._raft_micro(), resident=True, host_seen=True)

    @needs_reference
    def test_resident_rejects_temporal_models(self):
        from jaxmc.compile.vspec import CompileError
        from jaxmc.tpu.bfs import TpuExplorer
        path = os.path.join(REFERENCE, "examples", "SpecifyingSystems",
                            "HourClock", "HourClock2.tla")
        cfg = parse_cfg(open(os.path.join(
            REFERENCE, "examples", "SpecifyingSystems", "HourClock",
            "HourClock2.cfg")).read())
        model = load(path, cfg)
        with pytest.raises(CompileError):
            TpuExplorer(model, resident=True)


class TestCorpusOnDevice:
    # seq-heavy corpus models must reproduce the interpreter's exact
    # counts on the device backend (tuple messages, Tail, Lose's dynamic
    # sequence surgery, record-set TypeInvariants)
    CASES = [
        ("examples/SpecifyingSystems/FIFO/MCInnerFIFO.tla", 3864, 9660),
        ("examples/SpecifyingSystems/TLC/MCAlternatingBit.tla", 240, 1392),
    ]

    @pytest.mark.parametrize("rel,distinct,generated", CASES,
                             ids=[c[0].split("/")[-1] for c in CASES])
    @needs_reference
    def test_corpus_model_exact(self, rel, distinct, generated):
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("no native toolchain")
        from jaxmc.tpu.bfs import TpuExplorer
        spec = os.path.join(REFERENCE, rel)
        cfg = parse_cfg(open(spec[:-4] + ".cfg", encoding="utf-8",
                             errors="replace").read())
        model = load(spec, cfg)
        r = TpuExplorer(model, host_seen=True, store_trace=False).run()
        assert r.ok
        assert r.distinct == distinct
        assert r.generated == generated


class TestRefinementOnDevice:
    # refinement PROPERTYs check stepwise on the jax backend too (host-
    # side over the streamed candidate edges) — verdict parity with interp

    @needs_reference
    def test_hourclock2_equivalence_checked(self):
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/HourClock")
        cfg = parse_cfg(open(os.path.join(d, "HourClock2.cfg")).read())
        model = load(os.path.join(d, "HourClock2.tla"), cfg)
        r = TpuExplorer(model).run()
        assert r.ok
        assert r.distinct == 12 and r.generated == 24
        assert not any("HC2" in w for w in r.warnings)

    @needs_reference
    def test_alternating_bit_abcspec_checked(self):
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/TLC")
        cfg = parse_cfg(open(os.path.join(d, "MCAlternatingBit.cfg")).read())
        model = load(os.path.join(d, "MCAlternatingBit.tla"), cfg)
        r = TpuExplorer(model).run()
        assert r.ok
        assert r.distinct == 240 and r.generated == 1392
        # r3: ABCSpec's ABCFairness half is checked over the streamed
        # behavior graph too — no "NOT checked" warning remains
        assert not any("NOT checked" in w for w in r.warnings), r.warnings

    def test_non_refinement_detected(self, tmp_path):
        from jaxmc.tpu.bfs import TpuExplorer
        spec = tmp_path / "badhc.tla"
        spec.write_text("""---- MODULE badhc ----
EXTENDS Naturals
VARIABLE hr
HCini == hr \\in 1..12
HCnxt == hr' = IF hr >= 11 THEN 1 ELSE hr + 2
HC == HCini /\\ [][HCnxt]_hr
Jump == hr' = IF hr = 12 THEN 1 ELSE hr + 1
JumpSpec == HCini /\\ [][Jump]_hr
====
""")
        cfg = ModelConfig(specification="HC", properties=["JumpSpec"],
                          check_deadlock=False)
        model = load(str(spec), cfg)
        r = TpuExplorer(model).run()
        assert not r.ok
        assert r.violation.kind == "property"
        assert r.violation.name == "JumpSpec"
        # the trace ends with the non-refining step
        assert len(r.violation.trace) >= 2


class TestLevelRankMergeParity:
    """Parity pins for the host-loop rank-merge port (ISSUE 11
    tentpole b): the level mode — the LEGACY host loop refinement and
    temporal PROPERTY checking runs on — merges each level's candidates
    into the sorted seen prefix by rank instead of full-sorting
    seen+candidates.  JAXMC_LEVEL_RANKMERGE=0 keeps the full sort as
    the parity oracle; counts, verdicts and traces must be
    bit-identical either way."""

    REFINE_OK = """---- MODULE rmhc ----
EXTENDS Naturals
VARIABLE hr
HCini == hr \\in 1..12
HCnxt == hr' = IF hr = 12 THEN 1 ELSE hr + 1
HC == HCini /\\ [][HCnxt]_hr
====
"""
    REFINE_BAD = """---- MODULE rmbad ----
EXTENDS Naturals
VARIABLE hr
HCini == hr \\in 1..12
HCnxt == hr' = IF hr >= 11 THEN 1 ELSE hr + 2
HC == HCini /\\ [][HCnxt]_hr
Jump == hr' = IF hr = 12 THEN 1 ELSE hr + 1
JumpSpec == HCini /\\ [][Jump]_hr
====
"""
    TEMPORAL = """---- MODULE rmlive ----
EXTENDS Naturals
VARIABLE hr
Init == hr \\in 1..4
Next == hr' = (hr %% 12) + 1
Spec == Init /\\ [][Next]_hr /\\ WF_hr(Next)
Cycles == []<><<Next>>_hr
====
""".replace("%%", "%")

    def _pair(self, monkeypatch, mk):
        """One run per merge strategy on fresh explorers."""
        out = []
        for flag in ("0", "1"):
            monkeypatch.setenv("JAXMC_LEVEL_RANKMERGE", flag)
            out.append(mk().run())
        return out

    def _write(self, tmp_path, name, text):
        p = tmp_path / name
        p.write_text(text)
        return str(p)

    def test_refinement_counts_identical(self, tmp_path, monkeypatch):
        from jaxmc.tpu.bfs import TpuExplorer
        spec = self._write(tmp_path, "rmhc.tla", self.REFINE_OK)
        cfg = ModelConfig(specification="HC", properties=["HC"],
                          check_deadlock=False)
        full, rank = self._pair(
            monkeypatch, lambda: TpuExplorer(load(spec, cfg)))
        assert full.ok and rank.ok
        assert (full.distinct, full.generated, full.diameter) == \
            (rank.distinct, rank.generated, rank.diameter)

    def test_refinement_violation_trace_identical(self, tmp_path,
                                                  monkeypatch):
        from jaxmc.tpu.bfs import TpuExplorer
        spec = self._write(tmp_path, "rmbad.tla", self.REFINE_BAD)
        cfg = ModelConfig(specification="HC", properties=["JumpSpec"],
                          check_deadlock=False)
        full, rank = self._pair(
            monkeypatch, lambda: TpuExplorer(load(spec, cfg)))
        assert not full.ok and not rank.ok
        assert full.violation.name == rank.violation.name == "JumpSpec"
        # bit-identical trace: same states, same action labels
        assert full.violation.trace == rank.violation.trace

    def test_temporal_counts_identical(self, tmp_path, monkeypatch):
        # the behavior-graph liveness path streams every level's edges
        # through the same merged frontier the rank merge produces
        from jaxmc.tpu.bfs import TpuExplorer
        spec = self._write(tmp_path, "rmlive.tla", self.TEMPORAL)
        cfg = ModelConfig(specification="Spec", properties=["Cycles"],
                          check_deadlock=False)
        full, rank = self._pair(
            monkeypatch, lambda: TpuExplorer(load(spec, cfg)))
        assert full.ok and rank.ok
        assert (full.distinct, full.generated, full.diameter) == \
            (rank.distinct, rank.generated, rank.diameter)

    def test_temporal_violation_parity(self, tmp_path, monkeypatch):
        # without fairness the cycle property fails: both merges must
        # agree on the verdict and the counterexample prefix
        from jaxmc.tpu.bfs import TpuExplorer
        spec = self._write(tmp_path, "rmlive.tla", self.TEMPORAL)
        cfg = ModelConfig(init="Init", next="Next",
                          properties=["Cycles"], check_deadlock=False)
        full, rank = self._pair(
            monkeypatch, lambda: TpuExplorer(load(spec, cfg)))
        assert not full.ok and not rank.ok
        assert full.violation.name == rank.violation.name
        assert full.violation.trace == rank.violation.trace


@pytest.mark.slow
def test_mesh_raft_micro_counts():
    # the flagship wide-state workload shards: MCraftMicro on an 8-device
    # mesh matches the interp/single-chip counts exactly
    import jax
    from jaxmc.tpu.mesh import MeshExplorer
    assert len(jax.devices()) >= 8
    ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
    model = bind_model(
        ldr.load_path(os.path.join(SPECS, "MCraftMicro.tla")),
        parse_cfg(open(os.path.join(SPECS, "MCraft_micro.cfg")).read()))
    r = MeshExplorer(model).run()
    assert r.ok
    assert r.distinct == 694 and r.generated == 6185


@needs_reference
def test_mesh_innerfifo_counts():
    # mesh-vs-interp equality on a corpus model with constraints and a
    # canonically-sorted container (the fp128-key dedup path)
    import jax
    from jaxmc.tpu.mesh import MeshExplorer
    assert len(jax.devices()) >= 8
    d = os.path.join(REFERENCE, "examples/SpecifyingSystems/FIFO")
    cfg = parse_cfg(open(os.path.join(d, "MCInnerFIFO.cfg")).read())
    model = load(os.path.join(d, "MCInnerFIFO.tla"), cfg)
    r = MeshExplorer(model).run()
    assert r.ok
    assert r.distinct == 3864 and r.generated == 9660


class TestHybrid:
    """Hybrid execution (VERDICT r3 #2): uncompilable actions,
    invariants, or constraints demote to the exact interpreter inside
    the host_seen device mode instead of rejecting the whole spec."""

    @needs_reference
    def test_consensus_invariant_fallback_counts(self):
        # MCConsensus's Inv uses IsFiniteSet (uncompilable): the
        # invariant demotes to host evaluation over decoded rows while
        # the actions stay compiled; counts match the interp pin
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples/Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCConsensus.cfg")).read())
        cfg.check_deadlock = False
        model = load(os.path.join(d, "MCConsensus.tla"), cfg)
        ex = TpuExplorer(model, store_trace=True, host_seen=True)
        assert [nm for nm, _, _ in ex.fb_invs] == ["Inv"]
        assert not ex.fb_arms
        r = ex.run()
        assert r.ok and (r.generated, r.distinct) == (7, 4)

    @needs_reference
    def test_asynch_interface_action_fallback_counts(self):
        # AsynchInterface's Send leaves val' nondeterministic (val' \in
        # Data): that arm demotes to interpreter enumeration, Rcv stays
        # compiled; counts match the interp pin
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE,
                         "examples/SpecifyingSystems/AsynchronousInterface")
        cfg = parse_cfg(open(os.path.join(d, "AsynchInterface.cfg")).read())
        model = load(os.path.join(d, "AsynchInterface.tla"), cfg)
        ex = TpuExplorer(model, store_trace=True, host_seen=True)
        assert [a.label for a, _ in ex.fb_arms] == ["Send"]
        r = ex.run()
        assert r.ok and (r.generated, r.distinct) == (30, 12)

    @needs_reference
    def test_hybrid_requires_host_seen(self):
        # level mode cannot interleave interpreter work: a spec that
        # needs hybrid execution is rejected with a MODE error (fix is
        # a flag, not a different backend)
        from jaxmc.tpu.bfs import TpuExplorer
        from jaxmc.compile.vspec import ModeError
        d = os.path.join(REFERENCE, "examples/Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCConsensus.cfg")).read())
        cfg.check_deadlock = False
        model = load(os.path.join(d, "MCConsensus.tla"), cfg)
        with pytest.raises(ModeError, match="hybrid"):
            TpuExplorer(model, store_trace=True, host_seen=False)

    @pytest.mark.slow
    def test_paxos_demoted_guard_restart_counts(self):
        # MCPaxos Phase2a's Q1bv guard compiles only via conjunct
        # demotion (False + abort flag); the abort fires on a reachable
        # state, the engine demotes those arms to the interpreter,
        # restarts, and the counts match the interp pin exactly
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples/Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCPaxos.cfg")).read())
        model = load(os.path.join(d, "MCPaxos.tla"), cfg)
        ex = TpuExplorer(model, store_trace=True, host_seen=True)
        assert ex._demotable  # Phase2a arms carry demoted guards
        r = ex.run()
        assert r.ok and (r.generated, r.distinct) == (82, 25)
        assert any("Phase2a" in a.label for a, _ in ex.fb_arms)

    @pytest.mark.slow
    def test_ssi_small_full_arm_fallback_counts(self):
        # the SSI envelope model: EVERY action arm demotes (recursion/
        # CHOOSE-heavy), so the device contributes hashing/dedup while
        # the interpreter enumerates — first SI-class workload running
        # through the device engine, counts exact
        from jaxmc.tpu.bfs import TpuExplorer
        ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
        model = bind_model(
            ldr.load_path(os.path.join(SPECS, "MCserializableSI.tla")),
            parse_cfg(open(os.path.join(
                SPECS, "MCserializableSI_small.cfg")).read()))
        ex = TpuExplorer(model, store_trace=True, host_seen=True)
        assert ex.fb_arms
        r = ex.run()
        assert r.ok and (r.generated, r.distinct) == (945, 569)


class TestScalarUnions:
    """Scalar variants in the union lane encoding (VERDICT r3 #3): the
    CachingMemory shape — buf[p] holds NoVal (enum) or a request record
    — encodes as a tagged union with $scalar variants."""

    def test_scalar_union_encode_roundtrip(self):
        # fast pure-vspec coverage: NoVal (enum) and a request record
        # share one tagged union; encode/decode roundtrips both and the
        # merge error still names the OBSERVED kinds
        from jaxmc.compile.vspec import (CompileError, EnumUniverse,
                                         decode, encode, infer, merge)
        from jaxmc.sem.values import Fcn, ModelValue
        uni = EnumUniverse()
        nv = ModelValue("NoVal")
        rec = Fcn({"adr": ModelValue("a1"), "op": "Rd", "val": 3})
        u = merge(infer(nv, uni), infer(rec, uni))
        assert u.kind == "union" and len(u.variants) == 2
        for v in (nv, rec):
            out = []
            encode(v, u, uni, out)
            assert len(out) == u.width
            back, _ = decode(out, 0, u, uni)
            assert back == v and (isinstance(back, bool)
                                  == isinstance(v, bool))
        with pytest.raises(CompileError, match="enum and seq"):
            merge(infer(nv, uni), infer(Fcn({1: 5, 2: 6}), uni))

    @pytest.mark.slow
    def test_internal_memory_counts(self):
        # previously rejected with "cannot merge shapes enum and fcn";
        # Req/Rsp arms demote (memInt' nondeterminism via Send/Reply),
        # Do(p) stays compiled
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE,
                         "examples/SpecifyingSystems/CachingMemory")
        cfg = parse_cfg(open(os.path.join(d,
                                          "MCInternalMemory.cfg")).read())
        model = load(os.path.join(d, "MCInternalMemory.tla"), cfg)
        r = TpuExplorer(model, store_trace=False, host_seen=True).run()
        assert r.ok and (r.generated, r.distinct) == (21400, 4408)

    @pytest.mark.slow
    def test_golden_inner_serial_device_run(self):
        # THE golden run: the corpus's only captured full TLC output
        # (testout2:265-266 — TLC 1.57 took 22 hours) reproduced on the
        # device backend: 6181 generated / 195 distinct, diameter 5
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE,
                         "examples/SpecifyingSystems/AdvancedExamples")
        cfg = parse_cfg(open(os.path.join(d, "MCInnerSerial.cfg")).read())
        model = load(os.path.join(d, "MCInnerSerial.tla"), cfg)
        r = TpuExplorer(model, store_trace=False, host_seen=True).run()
        assert r.ok and (r.generated, r.distinct) == (6181, 195)

    @pytest.mark.slow
    def test_live_write_through_cache_device_run(self):
        # liveness PROPERTIES check through the hybrid edge stream on a
        # scalar-union model: LM_Inner_LISpec + LM_Inner_Liveness verify
        # with no "NOT checked" warnings beyond the host_seen note
        from jaxmc.tpu.bfs import TpuExplorer
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/Liveness")
        cfg = parse_cfg(open(os.path.join(
            d, "MCLiveWriteThroughCache.cfg")).read())
        model = load(os.path.join(d, "MCLiveWriteThroughCache.tla"), cfg)
        r = TpuExplorer(model, store_trace=True, host_seen=True).run()
        assert r.ok and (r.generated, r.distinct) == (28170, 5196)
        assert not [w for w in r.warnings if "NOT checked" in w]


@pytest.mark.slow
def test_multihost_dcn_dryrun():
    # the DCN layer (SURVEY §2.3/§5 distributed comm backend): 2 jax
    # PROCESSES x 4 virtual CPU devices, jax.distributed.initialize with
    # a localhost coordinator, collectives crossing process boundaries
    # (Gloo on CPU; same program rides ICI/DCN on a pod). Full
    # MCraftMicro with exact counts on every process.
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", os.path.join(
            os.path.dirname(SPECS), "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multihost(num_processes=2, local_devices=4)


@pytest.mark.slow
def test_mcraft_3s_mid4_completes_exhaustively():
    # The MCraft_3s ladder's first completed rung (VERDICT r4 #2):
    # reference raft.tla with Server={s1,s2,s3}, MaxMsgDomain 4
    # (specs/MCraft_3s_mid4.cfg — one step below the BASELINE model of
    # record). First measured completion: 11,883,463 generated /
    # 714,286 distinct, no violation, via the per-arm-granular hybrid
    # with strided adaptive relayout (one relayout recovered the
    # message variant the sampler missed). ~46 min on the contended
    # 1-core dev box at 6.6k st/s steady state.
    from jaxmc.tpu.bfs import TpuExplorer
    ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
    model = bind_model(
        ldr.load_path(os.path.join(SPECS, "MCraftMicro.tla")),
        parse_cfg(open(os.path.join(SPECS, "MCraft_3s_mid4.cfg")).read()))
    ex = TpuExplorer(model, store_trace=False, host_seen=True,
                     sample_cfg=(3000, 200, 100))
    r = ex.run()
    assert r.ok
    assert (r.generated, r.distinct) == (11883463, 714286)


@pytest.mark.slow
def test_multihost_trace_parity(tmp_path):
    # VERDICT r4 #7: a violating model on the 2x4 multi-host dryrun must
    # reproduce the EXACT single-chip counterexample trace. The child
    # processes record only their own frontier/provenance shards and
    # reassemble the chain with the process_allgather pull protocol;
    # every process prints the same trace, equal line-for-line to the
    # single-process MeshExplorer's over the same 8 global devices.
    import socket
    import subprocess
    import sys as _sys
    import time as _time
    spec = tmp_path / "mhviol.tla"
    spec.write_text("""---- MODULE mhviol ----
EXTENDS Naturals
VARIABLES x, y
Init == x = 0 /\\ y = 0
Next == \\/ x < 6 /\\ x' = x + 1 /\\ UNCHANGED y
        \\/ y < 6 /\\ y' = y + 1 /\\ UNCHANGED x
Inv == x + y < 5
====
""")
    cfgp = tmp_path / "mhviol.cfg"
    cfgp.write_text("INIT Init\nNEXT Next\nINVARIANT Inv\n")

    # single-chip reference: MeshExplorer over this process's 8 virtual
    # devices (same global device count as 2 procs x 4 below)
    from jaxmc.tpu.mesh import MeshExplorer
    from jaxmc.tpu.multihost import fmt_trace_line
    model = load(str(spec), parse_cfg(cfgp.read_text()))
    r = MeshExplorer(model).run()
    assert not r.ok and r.violation.kind == "invariant"
    assert r.violation.name == "Inv"
    _replay_trace(model, r.violation.trace)
    ref_lines = [fmt_trace_line(i, st, lbl)
                 for i, (st, lbl) in enumerate(r.violation.trace)]

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(SPECS)
    procs, logs = [], []
    for pid in range(2):
        env = dict(os.environ, PYTHONPATH=repo)
        env.pop("JAX_PLATFORMS", None)
        log = tmp_path / f"mh{pid}.log"
        logs.append(log)
        procs.append(subprocess.Popen(
            [_sys.executable, "-m", "jaxmc.tpu.multihost",
             "--process-id", str(pid), "--num-processes", "2",
             "--coordinator", f"localhost:{port}",
             "--local-devices", "4",
             "--spec", str(spec), "--cfg", str(cfgp)],
            stdout=open(log, "w"), stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo))
    deadline = _time.time() + 1200
    for p in procs:
        try:
            p.wait(timeout=max(1.0, deadline - _time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    per_proc = []
    for pid, log in enumerate(logs):
        text = log.read_text()
        assert procs[pid].returncode == 0, text[-2000:]
        assert "MHVIOLATION" in text, text[-2000:]
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("MHTRACE ")]
        per_proc.append(lines)
    assert per_proc[0] == per_proc[1], "processes disagree on the trace"
    assert per_proc[0] == ref_lines, (
        "multi-host trace differs from the single-chip mesh trace:\n"
        + "\n".join(per_proc[0]) + "\n--- vs ---\n" + "\n".join(ref_lines))


class TestMeshRefinementTemporal:
    """Refinement + temporal PROPERTYs on the MESH backend (VERDICT r3
    #9): the host runs the same stepwise/behavior-graph checkers over
    the streamed exchanged-candidate edges; verdicts match interp."""

    @needs_reference
    def test_mesh_hourclock2_refinement_checked(self):
        from jaxmc.tpu.mesh import MeshExplorer
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/HourClock")
        cfg = parse_cfg(open(os.path.join(d, "HourClock2.cfg")).read())
        model = load(os.path.join(d, "HourClock2.tla"), cfg)
        r = MeshExplorer(model).run()
        assert r.ok and r.distinct == 12 and r.generated == 24
        assert not any("NOT checked" in w for w in r.warnings), r.warnings

    def test_mesh_non_refinement_detected(self, tmp_path):
        from jaxmc.tpu.mesh import MeshExplorer
        spec = tmp_path / "badhc.tla"
        spec.write_text("""---- MODULE badhc ----
EXTENDS Naturals
VARIABLE hr
HCini == hr \\in 1..12
HCnxt == hr' = IF hr >= 11 THEN 1 ELSE hr + 2
HC == HCini /\\ [][HCnxt]_hr
Jump == hr' = IF hr = 12 THEN 1 ELSE hr + 1
JumpSpec == HCini /\\ [][Jump]_hr
====
""")
        cfg = ModelConfig(specification="HC", properties=["JumpSpec"],
                          check_deadlock=False)
        model = load(str(spec), cfg)
        r = MeshExplorer(model).run()
        assert not r.ok
        assert r.violation.kind == "property"
        assert r.violation.name == "JumpSpec"
        assert len(r.violation.trace) >= 2

    @pytest.mark.slow
    def test_mesh_alternating_bit_liveness_checked(self):
        # SentLeadsToRcvd (under ABSpec fairness) + ABCSpec refinement
        # verified over the mesh's streamed behavior graph — the exact
        # deliverable model of VERDICT r3 #9
        from jaxmc.tpu.mesh import MeshExplorer
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/TLC")
        cfg = parse_cfg(open(os.path.join(d, "MCAlternatingBit.cfg")).read())
        model = load(os.path.join(d, "MCAlternatingBit.tla"), cfg)
        r = MeshExplorer(model).run()
        assert r.ok and r.distinct == 240 and r.generated == 1392
        assert not any("NOT checked" in w for w in r.warnings), r.warnings


def test_per_arm_demotion_keeps_siblings_compiled(tmp_path):
    # VERDICT r4 #3 (finer demotion granularity): Next has raft's shape
    # /\ (\/ ...actions...) /\ rider (raft.tla:482-493). split_arms now
    # distributes the rider over the disjuncts, so ONE uncompilable
    # action (recursion here) demotes only its own arm — the sibling
    # arms stay compiled — and the hybrid run still matches the
    # interpreter exactly. Before this, the whole conjunction was a
    # single arm and any demotion sent 100% of the model to the interp.
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer
    spec = tmp_path / "armgran.tla"
    spec.write_text("""---- MODULE armgran ----
EXTENDS Naturals
VARIABLES x, h
RECURSIVE Fib(_)
Fib(n) == IF n <= 1 THEN n ELSE Fib(n - 1) + Fib(n - 2)
Init == x = 0 /\\ h = {}
Bump == x < 6 /\\ x' = x + 1
Drop == x > 2 /\\ x' = x - 2
Weird == x = 6 /\\ x' = Fib(x) % 5
Next == /\\ Bump \\/ Drop \\/ Weird
        /\\ h' = h \\cup {x}
====
""")
    cfg = ModelConfig(specification=None, init="Init", next="Next",
                      check_deadlock=False)
    model = load(str(spec), cfg)
    ri = Explorer(model).run()
    assert ri.ok
    ex = TpuExplorer(model, store_trace=False, host_seen=True)
    assert len(ex.fb_arms) == 1, \
        [r for _, r in ex.fb_arms]  # only Weird demotes
    assert ex.A >= 2  # Bump and Drop (with the rider) stay compiled
    assert len(ex.arms) == 3
    r = ex.run()
    assert r.ok
    assert (r.generated, r.distinct) == (ri.generated, ri.distinct)


def test_adaptive_relayout_recovers_unobserved_variant(tmp_path):
    # hybrid adaptive relayout (r4): a value shape the layout sampler
    # never OBSERVED (a record appearing only at depth 10) makes its
    # encode fail mid-search; the engine re-samples from the abort-time
    # frontier, rebuilds the layout with the variant present, restarts,
    # and completes with exact counts — no arm demotion needed
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer
    spec = tmp_path / "deepvar.tla"
    spec.write_text("""---- MODULE deepvar ----
EXTENDS Naturals
VARIABLES x, n
Init == n = 0 /\\ x = "none"
Step == n < 9 /\\ n' = n + 1 /\\ UNCHANGED x
Deep == n = 9 /\\ n' = n /\\ x' = [a |-> n]
Next == Step \\/ Deep
====
""")
    cfg = ModelConfig(specification=None, init="Init", next="Next",
                      check_deadlock=False)
    model = load(str(spec), cfg)
    ri = Explorer(model).run()
    assert ri.ok
    # sampling far too shallow to ever see the Deep record
    ex = TpuExplorer(model, store_trace=False, host_seen=True,
                     sample_cfg=(3, 2, 3))
    r = ex.run()
    assert r.ok
    assert (r.generated, r.distinct) == (ri.generated, ri.distinct)
