"""Front-end tests: lexer, TLA+ parser, cfg parser.

Corpus-as-regression-test (SURVEY.md §4.1): every module and cfg in the
reference corpus must parse (axiomatic Standard arithmetic modules excepted —
they are implemented natively, per SURVEY.md §1 L2).
"""

import glob
import os

import pytest

from jaxmc.front.lexer import tokenize
from jaxmc.front.parser import parse_module_text, parse_expr_text
from jaxmc.front.cfg import parse_cfg, CfgModelValue
from jaxmc.front import tla_ast as A

from conftest import REFERENCE, needs_reference

# Axiomatic constructions implemented as machine arithmetic, not parsed
# (/root/reference/examples/SpecifyingSystems/Standard/Naturals.tla:4-16 etc.)
NATIVE_STDLIB = {"Naturals", "Integers", "Reals", "ProtoReals"}


def corpus_files(pattern):
    return sorted(glob.glob(os.path.join(REFERENCE, "**", pattern), recursive=True))


def test_lexer_basics():
    toks = tokenize('x == 1 .. 20 \\* comment\ny\' = "hi"')
    kinds = [(t.kind, t.text) for t in toks]
    assert ("op", "==") in kinds
    assert ("number", "1") in kinds
    assert ("op", "..") in kinds
    assert ("op", "'") in kinds
    assert ("string", "hi") in kinds
    assert not any(t.text == "comment" for t in toks)


def test_lexer_junction_columns():
    toks = tokenize("/\\ a\n/\\ b")
    assert toks[0].col == 1 and toks[2].col == 1


def test_parse_junction_list():
    e = parse_expr_text("/\\ a\n/\\ b\n/\\ c")
    assert isinstance(e, A.OpApp) and e.name == "/\\"


def test_parse_nested_junctions():
    e = parse_expr_text("\\/ /\\ a\n   /\\ b\n\\/ c")
    assert isinstance(e, A.OpApp) and e.name == "\\/"
    inner = e.args[0]
    assert isinstance(inner, A.OpApp) and inner.name == "/\\"


def test_junction_ends_at_left_column():
    m = parse_module_text(
        "---- MODULE t ----\n"
        "Init == /\\ x = 1\n"
        "        /\\ y = 2\n"
        "Next == x = 2\n"
        "====\n"
    )
    names = [u.name for u in m.units]
    assert names == ["Init", "Next"]


def test_parse_except_and_records():
    e = parse_expr_text("[f EXCEPT ![i].term = @ + 1, ![j] = 0]")
    assert isinstance(e, A.Except) and len(e.updates) == 2
    e2 = parse_expr_text("[mtype |-> Req, mterm |-> currentTerm[i]]")
    assert isinstance(e2, A.RecordExpr)


def test_parse_temporal():
    e = parse_expr_text("Init /\\ [][Next]_vars /\\ WF_vars(Next)")
    assert isinstance(e, A.OpApp) and e.name == "/\\"
    e2 = parse_expr_text("[]<><<HCnxt>>_hr")
    assert isinstance(e2, A.OpApp) and e2.name == "[]"


def test_parse_quantifier_patterns():
    e = parse_expr_text("\\A <<k, v>> \\in S : k = v")
    assert isinstance(e, A.Quant)
    assert e.binders[0][0][0] == ("k", "v")
    e2 = parse_expr_text("{<<a, b>> \\in S \\X T : a < b}")
    assert isinstance(e2, A.SetFilter) and e2.var == ("a", "b")
    e3 = parse_expr_text("{<<s>> : s \\in S}")
    assert isinstance(e3, A.SetMap)


def test_parse_bang_paths():
    e = parse_expr_text("Inner(mem, ctl, buf)!ISpec")
    assert isinstance(e, A.OpApp) and e.name == "ISpec"
    assert e.path[0][0] == "Inner" and len(e.path[0][1]) == 3
    e2 = parse_expr_text("Inv!2")
    assert isinstance(e2, A.OpApp) and e2.name == "!sel"


def test_parse_conjunct_rhs_junction():
    # raft.tla:302 — junction list as the RHS of '='
    e = parse_expr_text(
        "x' = \\/ ( a < b )\n"
        "     \\/ \\E j \\in 1..2 : c[j] /= d[j]"
    )
    assert isinstance(e, A.OpApp) and e.name == "="


@pytest.mark.parametrize("path", corpus_files("*.tla"))
def test_parse_corpus_module(path):
    name = os.path.basename(path)[:-4]
    if name in NATIVE_STDLIB:
        pytest.skip("axiomatic stdlib module implemented natively")
    src = open(path, encoding="utf-8", errors="replace").read()
    m = parse_module_text(src)
    assert m.name == name or m.name  # inner module headers may rename


@pytest.mark.parametrize("path", corpus_files("*.cfg"))
def test_parse_corpus_cfg(path):
    parse_cfg(open(path, encoding="utf-8", errors="replace").read())


def test_cfg_statements():
    cfg = parse_cfg(
        'SPECIFICATION Spec\nINVARIANT A B\nPROPERTY P\n'
        'CONSTANTS X = {a1, "s", 3}\n  Y <- MCX\n  Ballot <-[Voting] MCB\n'
        'SYMMETRY Sym\nCONSTRAINT C1\n'
    )
    assert cfg.specification == "Spec"
    assert cfg.invariants == ["A", "B"]
    assert cfg.constants["X"] == frozenset({CfgModelValue("a1"), "s", 3})
    assert cfg.overrides["Y"] == "MCX"
    assert cfg.scoped_overrides[("Voting", "Ballot")] == "MCB"
    assert cfg.symmetry == "Sym"


@needs_reference
def test_parse_raft_shape():
    src = open(os.path.join(REFERENCE, "examples/raft.tla")).read()
    m = parse_module_text(src)
    defs = {u.name: u for u in m.units if isinstance(u, A.OpDef)}
    assert "Next" in defs and "Init" in defs and "Spec" in defs
    consts = [n for u in m.units if isinstance(u, A.Constants) for n, _ in u.names]
    assert "Server" in consts and "MaxClientRequests" in consts
