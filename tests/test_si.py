r"""The snapshot-isolation stress specs (SURVEY.md §3.5).

textbookSnapshotIsolation.tla (1297 LoC) and
serializableSnapshotIsolation.tla (1584 LoC) are the corpus's designated
stress workload — round-1 could not run them at all (unbounded CHOOSE).
Covered here: the fresh-value CHOOSE idiom, the spec's own in-spec unit
tests through the evaluator, the "should NEVER be violated" invariant
suites on small models, and SSI's serializability guarantee.
"""

import os

import pytest

from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model, bind_model_defs
from jaxmc.sem.eval import Ctx, eval_expr, _flatten_junction
from jaxmc.engine.explore import Explorer
from jaxmc.front.parser import parse_expr_text

from conftest import REFERENCE, needs_reference

# every test here loads reference-corpus specs (driver env only)
pytestmark = [needs_reference]

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")
EXAMPLES = os.path.join(REFERENCE, "examples")


def run(shim, cfgname, max_states=None):
    ldr = Loader([EXAMPLES, SPECS])
    model = bind_model(
        ldr.load_path(os.path.join(SPECS, shim)),
        parse_cfg(open(os.path.join(SPECS, cfgname)).read()))
    return Explorer(model, max_states=max_states).run()


def test_fresh_choose_idiom():
    # CHOOSE x : x \notin S (textbookSnapshotIsolation.tla:32 NoLock) is
    # TLC's fresh-value special case: stable, outside S, self-equal
    ldr = Loader([EXAMPLES])
    cfg = ModelConfig()
    from jaxmc.front.cfg import CfgModelValue
    cfg.constants["Key"] = frozenset({CfgModelValue("k1")})
    cfg.constants["TxnId"] = frozenset({CfgModelValue("t1")})
    defs = bind_model_defs(ldr.load("textbookSnapshotIsolation"), cfg)
    ctx = Ctx(defs)
    v1 = eval_expr(parse_expr_text("NoLock"), ctx)
    v2 = eval_expr(parse_expr_text("NoLock"), ctx)
    assert v1 is v2
    assert eval_expr(parse_expr_text("NoLock \\notin (Key \\union TxnId)"),
                     ctx) is True


@pytest.mark.parametrize("module", ["textbookSnapshotIsolation",
                                    "serializableSnapshotIsolation"])
def test_in_spec_unit_tests(module):
    # the spec's own operator unit tests (textbookSnapshotIsolation.tla
    # :673-682, :789-810, :1235-1263), evaluated the Toolbox way. The
    # test histories use string ids, so the constants are the strings
    # they reference
    cfg = ModelConfig()
    cfg.constants["Key"] = frozenset({"K_X", "K_Y"})
    cfg.constants["TxnId"] = frozenset({"T_1", "T_2", "T_3"})
    defs = bind_model_defs(Loader([EXAMPLES]).load(module), cfg)
    ctx = Ctx(defs)
    names = [nm for nm in defs
             if nm.startswith("UnitTest")]
    assert names, "spec lost its unit tests?"
    for name in names:
        clo = defs[name]
        for i, conj in enumerate(_flatten_junction(clo.body, "/\\")):
            assert eval_expr(conj, ctx) is True, (name, i + 1)


def test_textbook_si_small_model_invariants():
    # the full "should NEVER be violated" suite (spec header :70-89):
    # TypeInv, well-formedness, lock-manager cross-checks, SI semantics
    # (CorrectReadView, FirstCommitterWins), and the Cahill=Bernstein
    # serializability-encoding agreement
    r = run("MCtextbookSI.tla", "MCtextbookSI_small.cfg")
    assert r.ok
    assert r.distinct == 569 and r.generated == 945


def test_ssi_small_model_serializable():
    # Cahill's SSI must HOLD serializability in every reachable state
    # (serializableSnapshotIsolation.tla:75-79)
    r = run("MCserializableSI.tla", "MCserializableSI_small.cfg")
    assert r.ok
    assert r.distinct == 569 and r.generated == 945


WRITE_SKEW = r"""<<
  [op |-> "begin",  txnid |-> "T_1"],
  [op |-> "write",  txnid |-> "T_1", key |-> "K_1"],
  [op |-> "write",  txnid |-> "T_1", key |-> "K_2"],
  [op |-> "commit", txnid |-> "T_1"],
  [op |-> "begin",  txnid |-> "T_2"],
  [op |-> "read",   txnid |-> "T_2", key |-> "K_1", ver |-> "T_1"],
  [op |-> "write",  txnid |-> "T_2", key |-> "K_2"],
  [op |-> "begin",  txnid |-> "T_3"],
  [op |-> "commit", txnid |-> "T_2"],
  [op |-> "write",  txnid |-> "T_3", key |-> "K_1"],
  [op |-> "read",   txnid |-> "T_3", key |-> "K_2", ver |-> "T_1"],
  [op |-> "commit", txnid |-> "T_3"]>>"""


def test_write_skew_history_not_serializable():
    # the write-skew anomaly the seeded search finds (depth 9 from
    # MCInitSeeded): T_2 reads K_1/writes K_2, T_3 writes K_1/reads K_2,
    # both reading T_1's versions — a 2-cycle of rw-antidependencies. SI
    # permits it; both serializability encodings must agree it is NOT
    # serializable (textbookSnapshotIsolation.tla:83-96)
    cfg = ModelConfig()
    cfg.constants["Key"] = frozenset({"K_1", "K_2"})
    cfg.constants["TxnId"] = frozenset({"T_1", "T_2", "T_3"})
    defs = bind_model_defs(Loader([EXAMPLES]).load(
        "textbookSnapshotIsolation"), cfg)
    ctx = Ctx(defs)
    assert eval_expr(parse_expr_text(
        f"CahillSerializable({WRITE_SKEW})"), ctx) is False
    assert eval_expr(parse_expr_text(
        f"BernsteinSerializable({WRITE_SKEW})"), ctx) is False
    # well-formed, so the anomaly is a legal SI history, not garbage
    assert eval_expr(parse_expr_text(
        f"WellFormedTransactionsInHistory({WRITE_SKEW})"), ctx) is True


@pytest.mark.slow
def test_seeded_search_finds_serializability_violation():
    # the corpus's negative test (textbookSnapshotIsolation.tla:91-96):
    # TLC MUST find a CahillSerializable violation — proving the model is
    # not over-constrained. ~45 min on the interp (seeded + abort-free)
    r = run("MCtextbookSI.tla", "MCtextbookSI_skew.cfg")
    assert not r.ok
    assert r.violation.kind == "invariant"
    assert r.violation.name == "MCSerializable"


def _load_ssi(cfgname):
    ldr = Loader([EXAMPLES, SPECS])
    return bind_model(
        ldr.load_path(os.path.join(SPECS, "MCserializableSI.tla")),
        parse_cfg(open(os.path.join(SPECS, cfgname)).read()))


class TestSSIMutations:
    """The spec's own verification protocol (SURVEY.md §4.6, VERDICT r2
    #4): each of the eight documented rule-breaks of Cahill's algorithm
    (serializableSnapshotIsolation.tla:115-123) is applied as a
    programmatic AST edit (jaxmc/sem/mutate.py) and must make the search
    find the serializability violation the unbroken algorithm prevents."""

    def test_all_eight_mutations_apply(self):
        # every documented mutation finds its AST target (a drifted spec
        # cannot silently turn the suite vacuous) and actually changes
        # the definition body
        from jaxmc.sem.mutate import SSI_MUTATIONS, apply_ssi_mutation
        assert len(SSI_MUTATIONS) == 8
        for name in SSI_MUTATIONS:
            model = _load_ssi("MCserializableSI_mut.cfg")
            before = model.defs[SSI_MUTATIONS[name][0]].body
            apply_ssi_mutation(model, name)
            after = model.defs[SSI_MUTATIONS[name][0]].body
            assert after != before, name

    def test_unknown_target_errors_loudly(self):
        from jaxmc.sem.mutate import (MutationError, apply_mutation,
                                      assign_unchanged, if_false,
                                      let_empty_set)
        import pytest as _pytest
        model = _load_ssi("MCserializableSI_mut.cfg")
        with _pytest.raises(MutationError):
            apply_mutation(model, "Commit", assign_unchanged("nosuchvar"))
        with _pytest.raises(MutationError):
            apply_mutation(model, "Commit", if_false(99))
        with _pytest.raises(MutationError):
            apply_mutation(model, "Commit", let_empty_set("NoSuchLet"))

    def test_commit_cannot_abort_finds_violation_end_to_end(self):
        # the semantic pin the AST-diff checks can't give: a mutated
        # model must actually LOSE serializability. On the tightly
        # seeded model the pivot's dangerous-structure commit abort is
        # the last line of defense — removing it lets both remaining
        # transactions commit a write-skew history (~20 s search)
        from jaxmc.sem.mutate import apply_ssi_mutation
        model = _load_ssi("MCserializableSI_mut2.cfg")
        apply_ssi_mutation(model, "commit_cannot_abort")
        r = Explorer(model).run()
        assert not r.ok
        assert r.violation.kind == "invariant"
        assert r.violation.name == "MCCahillSerializableAtCommit"

    def test_unmutated_model_passes(self):
        # control: the mutation model itself (seeded, 2 keys x 3 txns,
        # at-commit serializability check) is clean without mutations —
        # bounded prefix (the full completion is the slow env-cfg pin)
        r = run("MCserializableSI.tla", "MCserializableSI_mut.cfg",
                max_states=3000)
        assert r.ok


# (mutation, model cfg) pairs verified to reach their expected
# serializability violation, with measured standalone search times on
# this box.
VERIFIED_MUTATIONS = [
    ("commit_cannot_abort", "MCserializableSI_mut2.cfg"),      # ~20 s
    ("commit_no_loser_aborts", "MCserializableSI_mut2.cfg"),   # ~90 s
    pytest.param("read_no_siread_lock", "MCserializableSI_mut.cfg",
                 marks=pytest.mark.slow),                      # ~26 min
    pytest.param("read_no_inconflict", "MCserializableSI_mut.cfg",
                 marks=pytest.mark.slow),                      # ~45 min
]

# The write-family mutations and read_cannot_abort are MEASURED CLEAN at
# their escalation envelopes (r4): coverage-guided directed simulation
# (200 seeds x 40 walks x depth 24, ~90 min per mutation on this box)
# found no violation on the 3-key/4-txn (write family) and 2-key/4-txn
# (read_cannot_abort) models, consistent with the hand analysis in
# specs/MCserializableSI.tla (Cahill's remaining read+commit checks
# close every cycle a single one of these mutations opens at these
# envelopes); the r3 BFS escalations likewise ran 600k+ states without
# a violation before exceeding their budgets. The test below pins the
# SHAPE of that evidence cheaply: the mutation applies, the model runs,
# and a bounded directed search stays clean — so any future semantic
# drift that makes these mutations trivially violating is caught.
CLEAN_AT_ENVELOPE = [
    ("write_cannot_abort", "MCserializableSI_mut3.cfg"),
    ("write_no_outconflict", "MCserializableSI_mut3.cfg"),
    ("read_cannot_abort", "MCserializableSI_mut4.cfg"),
]


@pytest.mark.slow
def test_write_no_inconflict_found_violating_by_simulation():
    # the SIXTH measured-VIOLATING documented check (r4): removing the
    # writer's inConflict bookkeeping lets a 4-txn/3-key pyramid commit
    # a non-serializable history — found by coverage-guided directed
    # simulation (seed 42, ~25 s), after BFS escalation exceeded every
    # budget. TLC -simulate parity: a violation found by random walks
    # IS a measured verdict; the 20-event witness history is quoted in
    # ROADMAP.md.
    from jaxmc.sem.mutate import apply_ssi_mutation
    from jaxmc.engine.simulate import random_walks
    model = _load_ssi("MCserializableSI_mut3.cfg")
    apply_ssi_mutation(model, "write_no_inconflict")
    v = random_walks(model, n_walks=40, depth=24, seed=42,
                     check_invariants=True, coverage_guided=True)
    assert v is not None
    assert v.kind == "invariant"
    assert v.name == "MCCahillSerializableAtCommit"


@pytest.mark.slow
@pytest.mark.parametrize("name,cfgname", CLEAN_AT_ENVELOPE)
def test_ssi_mutation_clean_at_envelope(name, cfgname):
    from jaxmc.sem.mutate import apply_ssi_mutation
    from jaxmc.engine.simulate import random_walks
    model = _load_ssi(cfgname)
    apply_ssi_mutation(model, name)
    v = random_walks(model, n_walks=30, depth=24, seed=7,
                     check_invariants=True, coverage_guided=True)
    assert v is None, (
        f"{name} found VIOLATING at its envelope — promote it to "
        f"VERIFIED_MUTATIONS with this trace")


@pytest.mark.slow
@pytest.mark.parametrize("name,cfgname", VERIFIED_MUTATIONS)
def test_ssi_mutation_finds_violation(name, cfgname):
    from jaxmc.sem.mutate import apply_ssi_mutation
    model = _load_ssi(cfgname)
    apply_ssi_mutation(model, name)
    r = Explorer(model).run()
    assert not r.ok
    assert r.violation.kind == "invariant"
    assert r.violation.name == "MCCahillSerializableAtCommit"


@pytest.mark.slow
def test_si_env_exhaustive_pin():
    # the open count-pin item (ISSUE 5 satellite, VERDICT r5 #5): the
    # SSI envelope-floor model (2 keys x 3 txns, seeded, voluntary
    # aborts pruned) explored EXHAUSTIVELY in one sitting; for a
    # checkpointed/resumable version of the same run use `make
    # pin-si-env` (it passes --checkpoint/--resume, which run_case does
    # not). Once jaxmc/corpus.py carries the pin, run_case enforces it;
    # until then this test FAILS with the measured totals in its
    # message so pinning is a one-line edit.
    from jaxmc.corpus import CASES, run_case
    case = next(c for c in CASES
                if c.cfg == "specs/MCserializableSI_env.cfg")
    status, detail, r, _mode = run_case(case)
    assert status == "pass", detail
    assert r is not None and r.ok and not r.truncated
    if case.distinct is None:
        pytest.fail(
            f"MCserializableSI_env counts measured but not yet pinned: "
            f"add distinct={r.distinct}, generated={r.generated} to its "
            f"Case in jaxmc/corpus.py (exhaustive, diameter "
            f"{r.diameter})")


@pytest.mark.slow
def test_deadlock_prevention_mutation_finds_spec_deadlock():
    # the spec's NINTH documented check
    # (serializableSnapshotIsolation.tla:103-107): break the Write
    # action's waits-for cycle prevention and the checker must report
    # the resulting specification-deadlock (~3 min; the author's own
    # note: "2 keys 3 txns, found a violation in a few minutes")
    from jaxmc.sem.mutate import apply_deadlock_mutation
    model = _load_ssi("MCserializableSI_dl.cfg")
    apply_deadlock_mutation(model)
    r = Explorer(model).run()
    assert not r.ok
    assert r.violation.kind == "deadlock"
    assert len(r.violation.trace) >= 2
