r"""`python -m jaxmc.obs` report/diff tests: artifact normalization,
the trajectory table, regression flags (seeded throughput drop, phase
blowup, backend demotion), --fail-on-regress gating, and the subprocess
smoke test that guards the entrypoint against import rot.

Tier-1 fast: fixture artifacts are built with a fake-clock Telemetry
(no jax); the one real run is an interp check on the symtoy micro model.
"""

import io
import json
import os
import subprocess
import sys

import pytest

from jaxmc import obs
from jaxmc.obs import report

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def mk_artifact(path, rate, platform, phases, jax_version="0.4.37",
                generated=100000):
    """A minimal-but-valid jaxmc.metrics/2 check artifact: `generated`
    states over generated/rate seconds, the given phase walls."""
    clk = {"t": 1000.0}
    tel = obs.Telemetry(clock=lambda: clk["t"])
    for name, wall in phases.items():
        h = tel.span(name)
        h.__enter__()
        clk["t"] += wall
        h.done()
    tel.level(0, frontier=1, generated=generated, wall_s=sum(
        phases.values()))
    tel.set_meta(backend="jax" if platform != "interp" else "interp",
                 spec="specs/symtoy.tla",
                 env={"jax_version": jax_version, "platform":
                      None if platform == "interp" else platform,
                      "device_count":
                      None if platform == "interp" else 1})
    tel.write_metrics(str(path), result={
        "ok": True, "distinct": generated // 2, "generated": generated,
        "diameter": 10, "truncated": False,
        "wall_s": generated / rate})
    with open(path) as fh:
        obs.validate_summary(json.load(fh), check_run=True)
    return str(path)


def mk_bench(path, n, value, metric):
    with open(path, "w") as fh:
        json.dump({"n": n, "cmd": "python bench.py", "rc": 0,
                   "parsed": {"metric": metric, "value": value,
                              "unit": "states/sec", "vs_baseline": 1.0,
                              "vs_tlc_estimate": 0.5}}, fh)
    return str(path)


def run_cli(argv):
    out = io.StringIO()
    rc = report.main(argv, out=out)
    return rc, out.getvalue()


class TestReport:
    def test_report_renders_phases_and_result(self, tmp_path):
        p = mk_artifact(tmp_path / "a.json", rate=5000.0, platform="tpu",
                        phases={"load": 0.5, "device_init": 12.0,
                                "search": 7.5})
        rc, out = run_cli(["report", p])
        assert rc == 0
        assert "device_init" in out and "search" in out
        assert "ok=True" in out and "generated=100000" in out
        assert "5,000" in out  # states/sec

    def test_report_unreadable_exits_2(self, tmp_path):
        bad = tmp_path / "nope.json"
        bad.write_text("{\"hello\": 1}")
        assert report.main(["report", str(bad)]) == 2
        assert report.main(["report", str(tmp_path / "missing.json")]) == 2


class TestDiff:
    def seeded(self, tmp_path):
        good = mk_artifact(tmp_path / "r1.json", rate=8000.0,
                           platform="tpu",
                           phases={"device_init": 2.0, "search": 10.0})
        bad = mk_artifact(tmp_path / "r2.json", rate=900.0,
                          platform="interp",
                          phases={"device_init": 95.0, "search": 10.5},
                          jax_version="0.5.0")
        return good, bad

    def test_seeded_regression_is_flagged(self, tmp_path):
        good, bad = self.seeded(tmp_path)
        rc, out = run_cli(["diff", good, bad])
        assert rc == 0  # informational without --fail-on-regress
        assert "REGRESS states/sec" in out
        assert "REGRESS backend demotion" in out and "tpu -> interp" in out
        assert "REGRESS phase device_init" in out
        # the env-change note attributes it (jax upgrade in the fixture)
        assert "jax_version: 0.4.37 -> 0.5.0" in out

    def test_fail_on_regress_gates_exit_code(self, tmp_path):
        good, bad = self.seeded(tmp_path)
        rc, _ = run_cli(["diff", good, bad, "--fail-on-regress"])
        assert rc == 1
        # reversed order is an improvement: exit 0
        rc, out = run_cli(["diff", bad, good, "--fail-on-regress"])
        assert rc == 0
        # self-diff: no flags
        rc, out = run_cli(["diff", good, good, "--fail-on-regress"])
        assert rc == 0 and "no regressions flagged" in out

    def test_bench_family_demotion(self, tmp_path):
        b4 = mk_bench(tmp_path / "BENCH_r04.json", 4, 1729.6,
                      "states/sec, exhaustive raft (... COMPLETED, "
                      "platform=cpu, device-resident BFS)")
        b5 = mk_bench(tmp_path / "BENCH_r05.json", 5, 6204.1,
                      "states/sec, exhaustive raft (... COMPLETED, "
                      "EXACT PYTHON INTERPRETER ONLY ...)")
        rc, out = run_cli(["diff", b4, b5, "--fail-on-regress"])
        assert rc == 1
        assert "REGRESS backend demotion r04 -> r05" in out
        assert "cpu -> interp" in out

    def test_repo_bench_artifacts_ingest(self, tmp_path):
        r4 = os.path.join(REPO, "BENCH_r04.json")
        r5 = os.path.join(REPO, "BENCH_r05.json")
        if not (os.path.exists(r4) and os.path.exists(r5)):
            pytest.skip("repo bench artifacts not present")
        rc, out = run_cli(["diff", r4, r5, "--fail-on-regress"])
        # r05 demoted to the interpreter: the flag (and gate) must fire
        assert rc == 1
        assert "REGRESS backend demotion" in out

    def test_mixed_kinds_and_three_way(self, tmp_path):
        a = mk_artifact(tmp_path / "a.json", rate=4000.0, platform="cpu",
                        phases={"search": 5.0})
        b = mk_bench(tmp_path / "b.json", 7, 4100.0,
                     "raft (... platform=cpu ...)")
        c = mk_artifact(tmp_path / "c.json", rate=4500.0, platform="cpu",
                        phases={"search": 4.0})
        rc, out = run_cli(["diff", a, b, c])
        assert rc == 0
        for label in ("a", "r07", "c"):
            assert label in out

    def test_multichip_artifact_diff_gates_per_chip_rate(
            self, tmp_path):
        # ISSUE 10 CI satellite: two jaxmc.multichip/1 scaling
        # artifacts diff directly — per-(rung, D) states/sec/chip
        # drops raise REGRESS and gate the exit code
        def art(path, rate):
            obj = {"schema": "jaxmc.multichip/1", "platform": "cpu",
                   "mode": "mesh-resident", "ok": True,
                   "rungs": [{"rung": "toy", "curve": [
                       {"devices": 2, "states_per_sec_per_chip": rate,
                        "host_syncs": 3, "levels": 6,
                        "merge": "rank"}]}]}
            p = str(tmp_path / path)
            json.dump(obj, open(p, "w"))
            return p
        a, b = art("r06.json", 1000.0), art("r07.json", 400.0)
        rc, out = run_cli(["diff", "--fail-on-regress",
                           "--threshold", "25", a, b])
        assert rc == 1 and "REGRESS states/sec/chip toy@D2" in out
        rc, out = run_cli(["diff", "--fail-on-regress", a, a])
        assert rc == 0 and "no regressions" in out
        rc, out = run_cli(["report", a])
        assert rc == 0 and "toy@D2" in out and "syncs=3/6" in out

    def test_multichip_regress_attributes_platform_swap(self,
                                                        tmp_path):
        # ISSUE 11 satellite: a backend swap between two multichip
        # artifacts must read as an ATTRIBUTED environment change
        # alongside the REGRESS, not an unexplained drop (the platform
        # lives top-level in the artifact, env.platform is None)
        def art(path, rate, platform):
            obj = {"schema": "jaxmc.multichip/1", "platform": platform,
                   "mode": "mesh-resident", "ok": True,
                   "env": {"jax_version": "0.4.37", "platform": None,
                           "device_count": None},
                   "rungs": [{"rung": "toy", "curve": [
                       {"devices": 2, "states_per_sec_per_chip": rate,
                        "host_syncs": 3, "levels": 6,
                        "merge": "rank"}]}]}
            p = str(tmp_path / path)
            json.dump(obj, open(p, "w"))
            return p
        a = art("r07.json", 9000.0, "tpu")
        b = art("r08.json", 1000.0, "cpu")
        rc, out = run_cli(["diff", "--fail-on-regress", a, b])
        assert rc == 1
        assert "REGRESS states/sec/chip toy@D2" in out
        assert "environment changed" in out
        assert "platform: tpu -> cpu" in out

    def test_metrics_regress_attributes_platform_swap(self, tmp_path):
        # same attribution on plain metrics artifacts whose env block
        # predates the platform field (env.platform None, platform
        # resolved from gauges): the swap must surface in the note
        good = mk_artifact(tmp_path / "g.json", rate=9000.0,
                           platform="tpu", phases={"search": 3.0})
        bad = mk_artifact(tmp_path / "b.json", rate=900.0,
                          platform="interp", phases={"search": 3.0})
        for p, plat in ((good, "tpu"), (bad, None)):
            obj = json.load(open(p))
            obj["env"]["platform"] = None
            if plat:
                obj.setdefault("gauges", {})["device.platform"] = plat
            json.dump(obj, open(p, "w"))
        rc, out = run_cli(["diff", good, bad])
        assert "REGRESS backend demotion" in out
        assert "environment changed" in out
        assert "platform: tpu -> interp" in out

    def test_diff_needs_two(self, tmp_path):
        a = mk_artifact(tmp_path / "a.json", rate=1000.0,
                        platform="cpu", phases={"search": 1.0})
        assert report.main(["diff", a]) == 2


class TestPhaseWallsParsing:
    """probe_phase_walls rows in multichip artifacts (ISSUE 11
    satellite): missing-phase and malformed rows render instead of
    crashing, and the hot-share acceptance metric surfaces when the
    probe timed the fused step."""

    def art(self, tmp_path, name, pw):
        obj = {"schema": "jaxmc.multichip/1", "platform": "cpu",
               "mode": "mesh-resident", "ok": True,
               "rungs": [{"rung": "toy", "curve": [
                   {"devices": 2, "states_per_sec_per_chip": 1000.0,
                    "host_syncs": 3, "levels": 6, "merge": "rank",
                    "phase_walls": pw}]}]}
        p = str(tmp_path / name)
        json.dump(obj, open(p, "w"))
        return p

    def test_full_row_renders_hot_share(self, tmp_path):
        p = self.art(tmp_path, "full.json",
                     {"levels": 4, "expand_s": 1.0, "exchange_s": 0.1,
                      "merge_rank_s": 2.0, "merge_fullsort_s": 3.5,
                      "merge_s": 2.0, "step_levels": 4,
                      "step_s": 12.0, "hot_share": 0.25})
        rc, out = run_cli(["report", p])
        assert rc == 0
        assert "merge(rank)=2.0s" in out
        assert "merge(fullsort)=3.5s" in out
        assert "hot_share=25%" in out and "step=12.0s" in out

    def test_missing_phase_rows_render_dashes(self, tmp_path):
        # a probe that outgrew its caps before timing the fused step
        # reports only what it measured — older artifacts (r07) also
        # lack step_s/hot_share entirely
        p = self.art(tmp_path, "partial.json", {"expand_s": 1.0})
        rc, out = run_cli(["report", p])
        assert rc == 0
        assert "expand=1.0s" in out
        assert "merge(rank)=-s" in out
        assert "hot_share" not in out

    def test_malformed_row_named_not_fatal(self, tmp_path):
        for bad, tname in ((["not", "a", "dict"], "list"),
                           ("walls", "str"), (3.5, "float")):
            p = self.art(tmp_path, f"bad_{tname}.json", bad)
            rc, out = run_cli(["report", p])
            assert rc == 0, out
            assert f"walls=(malformed: {tname})" in out

    def test_absent_row_is_silent(self, tmp_path):
        p = self.art(tmp_path, "none.json", None)
        rc, out = run_cli(["report", p])
        assert rc == 0
        assert "walls" not in out

    def test_repo_r07_artifact_renders(self):
        # the committed scaling artifact keeps parsing as the schema
        # grows fields
        r07 = os.path.join(REPO, "MULTICHIP_r07.json")
        if not os.path.exists(r07):
            pytest.skip("MULTICHIP_r07.json not present")
        rc, out = run_cli(["report", r07])
        assert rc == 0
        assert "transfer_scaled@D1" in out
        assert "merge(rank)=" in out


class TestOracleHighlights:
    """The preflight oracle's verdict gauges (ISSUE 11 satellite)
    surface in `obs report` highlights: the chosen platform, the
    preflight wall, and one cell per candidate probe."""

    def art(self, tmp_path):
        clk = {"t": 1000.0}
        tel = obs.Telemetry(clock=lambda: clk["t"])
        with tel.span("search"):
            clk["t"] += 2.0
        tel.level(0, frontier=1, generated=1000, wall_s=2.0)
        tel.gauge("backend.oracle_choice", "cpu")
        tel.gauge("backend.oracle_wall_s", 1.23)
        tel.gauge("backend.oracle_probe", {
            "tpu": {"live": False,
                    "error": "probe wedged past 7.0s (dead tunnel?)"},
            "cpu": {"live": True, "devices": 1, "compile_s": 0.4,
                    "dispatch_s": 0.012}})
        tel.set_meta(backend="jax", spec="specs/symtoy.tla",
                     env={"jax_version": "0.4.37", "platform": "cpu",
                          "device_count": 1})
        p = tmp_path / "oracle.json"
        tel.write_metrics(str(p), result={
            "ok": True, "distinct": 500, "generated": 1000,
            "diameter": 3, "truncated": False, "wall_s": 2.0})
        return str(p)

    def test_verdict_and_probe_walls_in_highlights(self, tmp_path):
        rc, out = run_cli(["report", self.art(tmp_path)])
        assert rc == 0
        assert "backend.oracle_choice=cpu" in out
        assert "backend.oracle_wall_s=1.23" in out
        assert "cpu=0.012s" in out
        assert "tpu=dead(probe wedged past 7.0s" in out


class TestEntrypointSmoke:
    """Guards `python -m jaxmc.obs` against import rot: a real interp
    run's artifact must render with exit 0 and a non-empty phase table
    through the actual module entrypoint (fresh interpreter)."""

    def test_report_subprocess_on_real_artifact(self, tmp_path):
        from jaxmc.cli import main as cli_main
        art = tmp_path / "interp.metrics.json"
        rc = cli_main(["check", os.path.join(SPECS, "symtoy.tla"),
                       "--cfg", os.path.join(SPECS, "symtoy.cfg"),
                       "--no-deadlock", "--quiet",
                       "--metrics-out", str(art)])
        assert rc == 0 and art.exists()
        r = subprocess.run(
            [sys.executable, "-m", "jaxmc.obs", "report", str(art)],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "phases:" in r.stdout
        # non-empty table: the interp pipeline's phases all render
        for phase in ("load", "search"):
            assert phase in r.stdout, r.stdout

    def test_diff_subprocess_exit_codes(self, tmp_path):
        good = mk_artifact(tmp_path / "g.json", rate=9000.0,
                           platform="tpu", phases={"search": 3.0})
        bad = mk_artifact(tmp_path / "b.json", rate=100.0,
                          platform="interp", phases={"search": 3.0})
        r = subprocess.run(
            [sys.executable, "-m", "jaxmc.obs", "diff", good, bad,
             "--fail-on-regress"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "states/sec" in r.stdout


# ------------------------------------------------------- obs timeline

def _trace_file(path, psid, parent, pid, events=(), t0=1000.0,
                command="check"):
    """A synthetic PR-16 trace file: proc_meta header + events."""
    lines = [{"ev": "proc_meta", "t": t0, "mono": 1.0, "pid": pid,
              "argv": ["jaxmc"], "psid": psid, "parent_span": parent,
              "env": {}, "tid": "t" * 16},
             {"ev": "run_start", "t": t0,
              "meta": {"command": command}, "tid": "t" * 16}]
    lines += list(events)
    with open(path, "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")
    return str(path)


class TestTimeline:
    def run_timeline(self, files, extra=()):
        buf = io.StringIO()
        rc = report.main(["timeline"] + list(extra) + list(files),
                         out=buf)
        return rc, buf.getvalue()

    def test_stitches_parent_child_and_workers(self, tmp_path):
        parent = _trace_file(
            tmp_path / "daemon.jsonl", "p" * 16, None, 100,
            events=[{"ev": "parallel.worker_span", "t": 1001.0,
                     "pid": 201, "span": "w" * 16,
                     "parent": "p" * 16, "level": 1, "tid": "t" * 16}])
        child = _trace_file(tmp_path / "job.jsonl", "c" * 16,
                            "p" * 16, 150, t0=1000.5, command="serve")
        rc, out = self.run_timeline([parent, child])
        assert rc == 0
        assert "summary: files=2 processes=3 lanes=3 events=5 " \
               "orphans=0 gaps=0" in out
        assert "parent=P0" in out       # child + worker parented
        assert "ORPHAN" not in out

    def test_orphan_flagged_and_gates(self, tmp_path):
        lost = _trace_file(tmp_path / "lost.jsonl", "c" * 16,
                           "f" * 16, 150)  # parent span in no file
        rc, out = self.run_timeline([lost])
        assert rc == 0                  # informational without the flag
        assert "orphans=1" in out and "ORPHAN" in out
        rc2, out2 = self.run_timeline([lost],
                                      extra=["--fail-on-orphans"])
        assert rc2 == 1

    def test_gap_detection(self, tmp_path):
        f = _trace_file(
            tmp_path / "slow.jsonl", "p" * 16, None, 100,
            events=[{"ev": "log", "t": 1100.0, "msg": "late",
                     "tid": "t" * 16}])
        rc, out = self.run_timeline([f], extra=["--gap-threshold", "30"])
        assert rc == 0
        assert "gaps=1" in out and "silent for" in out

    def test_tolerates_pre_pr16_artifacts_and_torn_lines(self, tmp_path):
        p = tmp_path / "old.jsonl"
        with open(p, "w") as fh:
            fh.write(json.dumps({"ev": "run_start", "t": 1.0,
                                 "meta": {}}) + "\n")
            fh.write('{"ev": "log", "t": 2.0, "msg": "x"}\n')
            fh.write('{"ev": "level", "t": 2.5, "lev')  # torn tail
        rc, out = self.run_timeline([str(p)])
        assert rc == 0
        assert "events=2" in out and "orphans=0" in out

    def test_real_run_timeline_subprocess(self, tmp_path):
        """Entrypoint guard: a real interp run's trace renders through
        `python -m jaxmc.obs timeline` with zero orphans."""
        from jaxmc.cli import main as cli_main
        tr = tmp_path / "run.trace.jsonl"
        rc = cli_main(["check", os.path.join(SPECS, "symtoy.tla"),
                       "--cfg", os.path.join(SPECS, "symtoy.cfg"),
                       "--no-deadlock", "--quiet", "--trace", str(tr)])
        assert rc == 0
        r = subprocess.run(
            [sys.executable, "-m", "jaxmc.obs", "timeline",
             "--fail-on-orphans", str(tr)],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "orphans=0" in r.stdout
        assert "run_start check" in r.stdout
