r"""jaxmc.analyze — static bounds/type inference, demotion prediction,
and the corpus linter (ISSUE 9).

Layers:
  1. bounds inference soundness: the inferred per-variable summary must
     CONTAIN every integer observed in sampled reachable states, on the
     fixtures whose shapes span the lattice (viewtoy/symtoy/constoy/
     transfer_scaled);
  2. proven lanes: counts/traces bit-identical with inference on vs
     off, with `analyze.proven_lanes > 0` where inference converges and
     the previously guarded lanes gone;
  3. predicted demotions: interparm_toy's build-time-demoted arm is
     named BEFORE kernel construction, with the build path's exact
     reason string and zero futile builds;
  4. the linter: every diagnostic class on the linttoy fixture, the
     strict-mode exit-2 CLI contract, and the serve daemon rejecting a
     statically-broken submission with the diagnostics in the payload.
"""

import json
import os
import subprocess
import sys
import urllib.request

import pytest

from jaxmc.engine.explore import Explorer, format_trace
from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.sem.values import Fcn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def load(name, cfg=None):
    cfgp = os.path.join(SPECS, cfg or f"{name}.cfg")
    mod = Loader([SPECS]).load_path(os.path.join(SPECS, f"{name}.tla"))
    with open(cfgp) as fh:
        return bind_model(mod, parse_cfg(fh.read()))


def _ints_of(v, out):
    if isinstance(v, bool):
        return
    if isinstance(v, int):
        out.append(v)
    elif isinstance(v, (frozenset, set, tuple, list)):
        for x in v:
            _ints_of(x, out)
    elif isinstance(v, Fcn):
        for k, val in v.d.items():
            _ints_of(k, out)
            _ints_of(val, out)


# ------------------------------------------------------- bounds inference

@pytest.mark.parametrize("name", ["viewtoy", "symtoy", "constoy",
                                  "transfer_scaled"])
def test_inferred_bounds_contain_observed(name):
    """Soundness on real reachable states: every int component of every
    sampled state must sit inside the variable's inferred summary."""
    from jaxmc.analyze import infer_state_bounds
    from jaxmc.engine.simulate import sample_states

    model = load(name)
    rep = infer_state_bounds(model)
    assert rep is not None, "analysis bailed on a repo fixture"
    summaries = rep.summaries()
    sampled = sample_states(model, bfs_states=600, n_walks=30,
                            walk_depth=40)
    assert sampled, "sampler produced no states"
    for st in sampled:
        for var, val in st.items():
            ints = []
            _ints_of(val, ints)
            if not ints:
                continue
            assert var in summaries, \
                f"{name}.{var} holds ints but has no summary"
            s = summaries[var]
            for i in ints:
                assert (s.lo is None or i >= s.lo) and \
                    (s.hi is None or i <= s.hi), \
                    f"{name}.{var}: observed {i} outside inferred " \
                    f"[{s.lo}, {s.hi}]"


def test_inference_proves_expected_fixture_bounds():
    """The converged intervals on the hand-checkable fixtures."""
    from jaxmc.analyze import infer_state_bounds
    lanes = infer_state_bounds(load("viewtoy")).lane_bounds()
    assert lanes == {"x": (0, 4), "noise": (0, 2)}
    # constoy needs the x+y<=c CONSTRAINT refinement: successors of
    # constrained states reach 6
    lanes = infer_state_bounds(load("constoy")).lane_bounds()
    assert lanes == {"a": (0, 6), "b": (0, 6)}
    # transfer_scaled: money is Init-bounded and UNCHANGED everywhere;
    # alice/bob grow without a provable bound and must NOT be proven
    lanes = infer_state_bounds(load("transfer_scaled")).lane_bounds()
    assert lanes == {"money": (1, 12)}


def _device_run(name, env, **kw):
    from jaxmc import obs
    from jaxmc.tpu.bfs import TpuExplorer
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    tel = obs.Telemetry()
    try:
        with obs.use(tel):
            ex = TpuExplorer(load(name), **kw)
            r = ex.run()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return r, tel, ex


@pytest.mark.parametrize("name", ["viewtoy", "constoy", "symtoy"])
def test_proven_lanes_counts_and_traces_identical(name):
    """Inference on vs off: bit-identical counts/violations, proven
    lanes replace guarded lanes where the proof converges."""
    ri = Explorer(load(name)).run()
    ron, tel_on, _ = _device_run(name, {})
    roff, tel_off, _ = _device_run(name, {"JAXMC_ANALYZE_BOUNDS": "0"})
    for r in (ron, roff):
        assert (r.distinct, r.generated) == (ri.distinct, ri.generated)
        assert r.ok == ri.ok
    if ri.violation is not None:
        assert format_trace(ron.violation) == \
            format_trace(roff.violation) == format_trace(ri.violation)
    on_proven = tel_on.gauges.get("analyze.proven_lanes", 0)
    off_proven = tel_off.gauges.get("analyze.proven_lanes", 0)
    assert off_proven == 0
    if name in ("viewtoy", "constoy"):
        # both int lanes proven: the guarded (observed-range) count
        # drops to zero — no OV_PACK re-sample cycle is reachable
        assert on_proven == 2
        assert tel_on.gauges.get("layout.pack_guarded_lanes") == 0
        assert tel_off.gauges.get("layout.pack_guarded_lanes") == 2
        # proven widths pack TIGHTER than margin-widened sampling
        assert tel_on.gauges.get("layout.bits_per_state") < \
            tel_off.gauges.get("layout.bits_per_state")


# ---------------------------------------------------- demotion prediction

def test_predicted_demotion_matches_build_time_reason():
    """interparm_toy's Pick arm: predicted BEFORE kernel construction,
    zero futile build attempts, and the exact build-time reason string
    (the satellite's no-divergent-wording contract)."""
    from jaxmc import native_store
    if not native_store.is_available():
        pytest.skip("hybrid needs the native store")
    rp, telp, exp = _device_run("interparm_toy", {}, store_trace=False,
                                host_seen=True)
    rb, telb, exb = _device_run("interparm_toy",
                                {"JAXMC_ANALYZE_PREDICT": "0"},
                                store_trace=False, host_seen=True)
    # same demotion table, identical wording, on both paths
    assert [(a.label, w) for a, w in exp.fb_arms] == \
        [(a.label, w) for a, w in exb.fb_arms] == \
        [("Pick", "SUBSET of symbolic set")]
    assert exp.arm_verdicts and not exb.arm_verdicts
    assert telp.counters.get("analyze.predicted_demotions") == 1
    assert telp.gauges.get("analyze.arm_verdicts") == \
        {"Pick": "SUBSET of symbolic set"}
    # zero futile builds: only Bump's kernel was ever constructed on
    # the predicted path; the build path also pays Pick's attempt
    assert telp.counters.get("compile.kernels_built") == 1
    assert telb.counters.get("compile.kernels_built", 0) >= 2
    # verdicts change nothing about the answer
    assert (rp.distinct, rp.generated) == (rb.distinct, rb.generated) \
        == (19, 29)


def test_predictor_is_silent_on_compilable_fixtures():
    from jaxmc.analyze import predict_arm_demotions
    from jaxmc.compile.ground import split_arms
    for name in ("viewtoy", "constoy", "symtoy", "symtoy_scaled",
                 "viewtoy_scaled", "transfer_scaled", "symid"):
        model = load(name)
        assert predict_arm_demotions(model, split_arms(model)) == {}, \
            f"false demotion verdict on {name}"


def test_unroll_message_constant_matches_raise_site():
    """The predictor's recursion wording IS kernel2's raise wording."""
    from jaxmc.compile.kernel2 import unroll_limit_message
    msg = unroll_limit_message("Depth", 64)
    assert msg.startswith("recursive operator Depth exceeds the "
                          "compile-time unroll limit (64; raise with "
                          "JAXMC_OP_UNROLL_LIMIT)")


# -------------------------------------------------------------- linter

LINTTOY = os.path.join(SPECS, "linttoy.tla")
LINTTOY_CFG = os.path.join(SPECS, "linttoy.cfg")


def test_linttoy_fires_every_diagnostic_class():
    from jaxmc.analyze import lint_pair
    diags = lint_pair(LINTTOY, LINTTOY_CFG)
    codes = {d.code for d in diags}
    assert codes == {"JMC101", "JMC102", "JMC201", "JMC202", "JMC203",
                     "JMC301", "JMC302"}
    by_code = {d.code: d for d in diags}
    assert "Missing" in by_code["JMC101"].message
    assert by_code["JMC101"].severity == "error"
    assert "Ghost" in by_code["JMC102"].message
    assert "ghost" in by_code["JMC201"].message
    assert "Stuck" in by_code["JMC202"].message
    assert by_code["JMC202"].severity == "warning"
    assert "CHOOSE" in by_code["JMC203"].message
    assert "Orphan" in by_code["JMC301"].message
    assert by_code["JMC301"].severity == "info"
    # every diagnostic is located
    for d in diags:
        assert d.path and d.line, d.render()


def test_repo_corpus_pairs_lint_clean_modulo_waivers():
    """The satellite gate, in-process: repo-local manifest pairs stay
    clean except for explicitly waived codes."""
    from jaxmc.analyze import lint_pair
    from jaxmc.corpus import CASES
    for case in CASES:
        if case.root != "repo" or case.lint_only or case.includes:
            continue
        diags = lint_pair(case.spec_path(), case.cfg_path())
        unwaived = [d for d in diags if d.code not in case.lint_waive]
        assert not unwaived, \
            f"{case.spec}: {[d.render() for d in unwaived]}"


def test_lint_cli_exit_codes(tmp_path):
    from jaxmc.analyze.__main__ import main as analyze_main
    assert analyze_main(["lint", os.path.join(SPECS, "viewtoy.tla")]) \
        == 0
    assert analyze_main(["lint", LINTTOY, LINTTOY_CFG]) == 2
    # warnings only (no cfg errors): a copy whose cfg assigns Ghost
    # and names only defined invariants
    cfg2 = tmp_path / "linttoy.cfg"
    cfg2.write_text(
        "SPECIFICATION Spec\nINVARIANT TypeInv HazInv\n"
        "SYMMETRY Perms\nCONSTANTS\n  P = {a1, a2}\n  Limit = 4\n"
        "  Unused = 7\n  Ghost = 9\n")
    assert analyze_main(["lint", LINTTOY, str(cfg2)]) == 1
    assert analyze_main(["lint", LINTTOY, str(cfg2),
                         "--errors-only"]) == 0


def test_session_analyze_stage_and_strict_contract():
    from jaxmc.session import AnalyzeError, CheckSession, SessionConfig
    # clean pair: stage runs, no diagnostics, search unaffected
    sess = CheckSession(SessionConfig(
        spec=os.path.join(SPECS, "viewtoy.tla"), analyze="warn"))
    assert sess.analyze() == []
    res = sess.explore()
    assert (res.distinct, res.generated) == (5, 11)
    # broken pair under strict: AnalyzeError BEFORE any engine exists
    sess2 = CheckSession(SessionConfig(
        spec=LINTTOY, cfg=LINTTOY_CFG, analyze="strict"))
    with pytest.raises(AnalyzeError) as ei:
        sess2.analyze()
    assert {d.code for d in ei.value.diagnostics} >= \
        {"JMC101", "JMC102"}
    assert sess2.engine is None
    # the strict refusal HOLDS: a driver that caught the first error
    # cannot stage-chain past it — every later analyze() re-raises
    with pytest.raises(AnalyzeError):
        sess2.analyze()
    assert sess2.engine is None


def test_check_cli_strict_exit2_subprocess():
    """The CLI contract: --analyze=strict exits 2 with the diagnostics
    on stderr, --analyze=off preserves the old behavior."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.run(
        [sys.executable, "-m", "jaxmc", "check", LINTTOY,
         "--cfg", LINTTOY_CFG, "--analyze", "strict"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert p.returncode == 2
    assert "JMC101" in p.stderr and "JMC202" in p.stderr
    assert "--analyze=strict refused the run" in p.stderr
    # a typo'd JAXMC_ANALYZE env default must fail loudly, never
    # silently degrade the gate to warn
    bad = subprocess.run(
        [sys.executable, "-m", "jaxmc", "check",
         os.path.join(SPECS, "viewtoy.tla")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env=dict(env, JAXMC_ANALYZE="stirct"))
    assert bad.returncode == 2
    assert "invalid --analyze/JAXMC_ANALYZE" in bad.stderr
    # warn on a clean spec: identical stdout to --analyze=off (modulo
    # the wall-clock/rate numbers in the summary line)
    import re
    outs = {}
    for mode in ("off", "warn"):
        q = subprocess.run(
            [sys.executable, "-m", "jaxmc", "check",
             os.path.join(SPECS, "viewtoy.tla"), "--quiet",
             "--analyze", mode],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=120)
        assert q.returncode == 0
        outs[mode] = re.sub(r"\(\d+ states/sec[^)]*\)", "(RATE)",
                            q.stdout)
    assert outs["off"] == outs["warn"]


# ---------------------------------------------------------- serve gate

def test_serve_rejects_statically_broken_job():
    """Submit-time rejection e2e: the daemon refuses the job with the
    diagnostics in the 400 payload, before any worker touches it."""
    import tempfile

    from jaxmc import drain
    from jaxmc.serve import ServeDaemon
    from jaxmc.serve.protocol import BadJob

    drain.clear()
    with tempfile.TemporaryDirectory() as spool:
        d = ServeDaemon(spool=spool, workers=1, quiet=True).start()
        try:
            # in-process surface
            with pytest.raises(BadJob) as ei:
                d.submit({"spec": LINTTOY, "cfg": LINTTOY_CFG})
            assert "JMC101" in str(ei.value)
            assert d.tel.counters.get("serve.jobs_rejected") == 1
            # HTTP surface: 400 with the diagnostic in the payload
            req = urllib.request.Request(
                f"http://{d.host}:{d.port}/jobs",
                data=json.dumps({"spec": LINTTOY,
                                 "cfg": LINTTOY_CFG}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as he:
                assert he.code == 400
                payload = json.loads(he.read().decode())
                assert "JMC101" in payload["error"]
            # a clean job still queues fine afterwards
            job = d.submit({"spec": os.path.join(SPECS, "viewtoy.tla"),
                            "options": {"max_states": 50}})
            assert job["id"]
        finally:
            d.initiate_drain("test done")
            d.shutdown()
    drain.clear()
