r"""Cross-model vmapped batching (ISSUE 13).

Covers the acceptance surface:
  - parse-time compatibility: liftable-constant analysis + batch_sig
    equality across the batchtoy family (and inequality elsewhere);
  - the vmapped engine: 4 layout-compatible NON-identical jobs through
    ONE compiled program (occupancy 4, one engine build), per-job
    counts/diameters/violations/traces byte-identical to solo runs —
    including the mixed batch where one member violates while the
    others run to exhaustion;
  - serve fleet wiring: cold-spool cohort pops by bsig and runs as one
    vbatch; artifacts carry the batch block + cost estimate; fast-lane
    jobs jump the queue;
  - the claimed-follower race and the warm-registry sig-lock eviction
    race (ISSUE 13 bugfix), pinned with concurrency tests;
  - chaos: mid-batch drain parks members as drained and the next
    daemon life re-answers them with identical counts; device-owner
    death requeues (never loses) the in-flight cohort and respawns.
"""

import os
import threading
import time

import pytest

from jaxmc import drain
from jaxmc.engine.explore import Explorer, format_trace
from jaxmc.serve import JobQueue, ServeDaemon
from jaxmc.serve.protocol import ServeClient, build_config, job_signature
from jaxmc.session import SessionConfig, batch_profile, load_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")
BT = os.path.join(SPECS, "batchtoy.tla")


def btcfg(v):
    return os.path.join(SPECS, f"batchtoy_{v}.cfg")


JAX_OPTS = {"backend": "jax", "platform": "cpu", "host_seen": True}


def session_cfg(v, **kw):
    return SessionConfig(spec=BT, cfg=btcfg(v), backend="jax",
                         platform="cpu", host_seen=True, **kw)


@pytest.fixture(autouse=True)
def _clean_drain():
    drain.clear()
    yield
    drain.clear()


_SOLO_CACHE = {}


def _solo(v):
    """Solo host_seen reference run, cached per variant — every parity
    assertion reuses one engine build (builds dominate suite wall)."""
    if v not in _SOLO_CACHE:
        from jaxmc.backend.bfs import TpuExplorer
        m = load_model(BT, btcfg(v), False)
        _SOLO_CACHE[v] = TpuExplorer(m, host_seen=True).run()
    return _SOLO_CACHE[v]


def _result_tuple(r):
    viol = None
    if r.violation is not None:
        viol = (r.violation.kind, r.violation.name,
                format_trace(r.violation))
    return (r.ok, r.distinct, r.generated, r.diameter,
            bool(r.truncated), viol)


class TestCompat:
    def test_batchtoy_constants_all_liftable(self):
        from jaxmc.analyze.bounds import liftable_constants
        for v in ("a", "b", "c", "bad"):
            m = load_model(BT, btcfg(v), False)
            assert liftable_constants(m) == \
                ("Bound", "Limit", "Step", "WrapCap")

    def test_view_constants_pinned(self):
        # constants reachable from a cfg VIEW feed the dedup-key basis
        # outside the const-lane install sites: never liftable
        from jaxmc.analyze.bounds import liftable_constants
        m = load_model(os.path.join(SPECS, "viewtoy.tla"),
                       os.path.join(SPECS, "viewtoy.cfg"), False)
        for n in m.cfg.constants:
            assert n not in liftable_constants(m) or \
                m.view is None

    def test_batch_profile_equality(self):
        profs = [batch_profile(session_cfg(v))
                 for v in ("a", "b", "c", "bad")]
        assert all(p is not None for p in profs)
        assert len({p.bsig for p in profs}) == 1
        assert profs[0].lift == ("Bound", "Limit", "Step", "WrapCap")
        # the analyze cost estimate rides the profile (fast-lane oracle)
        assert all(isinstance(p.cost_estimate, int) for p in profs)

    def test_batch_profile_separates_other_models_and_options(self):
        base = batch_profile(session_cfg("a"))
        other = batch_profile(SessionConfig(
            spec=os.path.join(SPECS, "transfer_scaled.tla"),
            backend="jax", platform="cpu", host_seen=True))
        assert other is None or other.bsig != base.bsig
        opt = batch_profile(session_cfg("a", max_states=7))
        assert opt.bsig != base.bsig
        # non-batchable configurations profile to None, never crash
        assert batch_profile(SessionConfig(spec=BT, cfg=btcfg("a"))) \
            is None  # interp backend
        assert batch_profile(session_cfg("a")) is not None


class TestVmappedEngine:
    @pytest.fixture(scope="class")
    def batch_run(self):
        from jaxmc.backend.batch import BatchCheckEngine
        cfgs = [session_cfg(v) for v in ("a", "b", "c", "bad")]
        be = BatchCheckEngine(cfgs).build()
        members = be.run()
        return be, members

    def test_one_engine_serves_all(self, batch_run):
        be, members = batch_run
        donor = members[0].engine
        # followers share the donor's compiled kernels + caches — zero
        # extra engine builds (the "one compile" criterion)
        for mem in members[1:]:
            assert mem.engine.compiled is donor.compiled
            assert mem.engine.layout is donor.layout
            assert mem.engine._hstep_cache is donor._hstep_cache
        assert be.dispatcher.max_width == 4
        assert be.dispatcher.dispatches > 0
        assert be.lift_names == ("Bound", "Limit", "Step", "WrapCap")

    def test_per_member_solo_parity(self, batch_run):
        _be, members = batch_run
        for v, mem in zip(("a", "b", "c", "bad"), members):
            assert mem.error is None, f"{v}: {mem.error}"
            assert _result_tuple(mem.result) == \
                _result_tuple(_solo(v)), v

    def test_mixed_batch_verdicts(self, batch_run):
        # one member violates; the others run to exhaustion — the
        # continuous-batching membership change between supersteps
        _be, members = batch_run
        ok = {v: m.result for v, m in
              zip(("a", "b", "c", "bad"), members)}
        assert ok["bad"].violation is not None
        assert ok["bad"].violation.kind == "invariant"
        assert ok["bad"].violation.name == "InBound"
        for v in ("a", "b", "c"):
            assert ok[v].ok and ok[v].violation is None
            assert not ok[v].truncated

    def test_member_counts_differ(self, batch_run):
        # NON-identical jobs: the whole point vs PR 7's coalescing
        _be, members = batch_run
        assert len({m.result.distinct for m in members}) == 4

    def test_interp_parity(self, batch_run):
        _be, members = batch_run
        for v, mem in zip(("a", "b", "c"), members):
            exp = Explorer(load_model(BT, btcfg(v), False)).run()
            assert (mem.result.distinct, mem.result.generated) == \
                (exp.distinct, exp.generated)

    def test_incompatible_cohort_refused(self):
        from jaxmc.backend.batch import (BatchCheckEngine,
                                         BatchIncompatible)
        cfgs = [session_cfg("a"),
                SessionConfig(spec=os.path.join(SPECS,
                                                "transfer_scaled.tla"),
                              backend="jax", platform="cpu",
                              host_seen=True)]
        with pytest.raises(BatchIncompatible):
            BatchCheckEngine(cfgs).build()


class TestStructuralMerge:
    """Structural batch-bound merge (ISSUE 18): the donor keeps
    per-element EB trees — the interval-union over members — instead of
    collapsing every container to a whole-variable summary, so the
    shared plan never packs wider than the worst solo member."""

    def _eb(self, **kw):
        from jaxmc.analyze.bounds import EB
        return EB(**kw)

    def test_merge_eb_interval_union(self):
        from jaxmc.analyze.bounds import merge_eb
        a = self._eb(all=(0, 2), rng=self._eb(all=(0, 2)))
        b = self._eb(all=(1, 5), rng=self._eb(all=(1, 5)))
        m = merge_eb(a, b)
        assert m.all == (0, 5)
        assert m.rng.all == (0, 5)

    def test_merge_eb_none_child_drops(self):
        # a child proven on only one side is NOT kept: the consumer
        # falls back to the merged covering interval, a superset for
        # both members — never a narrower guess
        from jaxmc.analyze.bounds import merge_eb
        a = self._eb(all=(0, 2), rng=self._eb(all=(0, 2)))
        b = self._eb(all=(0, 9))
        m = merge_eb(a, b)
        assert m.all == (0, 9) and m.rng is None
        assert merge_eb(a, None) is None

    def test_merge_eb_keys_intersect(self):
        from jaxmc.analyze.bounds import merge_eb
        a = self._eb(all=(0, 3), keys={"x": self._eb(all=(0, 1)),
                                       "y": self._eb(all=(0, 3))})
        b = self._eb(all=(0, 4), keys={"x": self._eb(all=(2, 4))})
        m = merge_eb(a, b)
        assert set(m.keys) == {"x"}
        assert m.keys["x"].all == (0, 4)

    def test_merge_element_bounds_any_none_member(self):
        from jaxmc.analyze.bounds import merge_element_bounds
        d = {"v": self._eb(all=(0, 1))}
        assert merge_element_bounds([d, None]) == {}
        assert merge_element_bounds([]) == {}
        m = merge_element_bounds([d, {"v": self._eb(all=(3, 4)),
                                      "w": self._eb(all=(0, 1))}])
        assert set(m) == {"v"} and m["v"].all == (0, 4)

    def test_merged_bounds_backfills_lane_proofs(self):
        # lane-proven vars without a structured tree still reach pack
        # as a covering EB — the lane precision never regresses
        from jaxmc.backend.batch import _MergedBounds
        mb = _MergedBounds(merged={"v": (0, 5)},
                           merged_eb={"w": self._eb(all=(1, 2))})
        eb = mb.element_bounds()
        assert eb["v"].all == (0, 5) and eb["w"].all == (1, 2)

    @pytest.fixture(scope="class")
    def msgstoy_cohort(self, tmp_path_factory):
        # same module, Cap=2 vs Cap=3: `msgs` is a per-process table,
        # so the donor layout depends on MERGED per-element bounds
        from jaxmc.backend.batch import BatchCheckEngine
        spec = os.path.join(SPECS, "msgstoy.tla")
        cfg2 = os.path.join(SPECS, "msgstoy.cfg")
        cfg3 = str(tmp_path_factory.mktemp("msgstoy") / "cap3.cfg")
        with open(cfg3, "w") as f:
            f.write("INIT Init\nNEXT Next\nINVARIANT DoneOK\n"
                    "CONSTANTS\n  Procs = {p1, p2, p3}\n  Cap = 3\n"
                    "  T = 2\n  P1 = p1\n")
        cfgs = [SessionConfig(spec=spec, cfg=c, backend="jax",
                              platform="cpu", host_seen=True)
                for c in (cfg2, cfg3)]
        be = BatchCheckEngine(cfgs).build()
        members = be.run()
        solos = []
        for c in (cfg2, cfg3):
            from jaxmc.backend.bfs import TpuExplorer
            eng = TpuExplorer(load_model(spec, c, False), host_seen=True)
            solos.append((eng.run(), eng.plan.batch_descriptor()))
        return be, members, solos

    def test_donor_plan_no_wider_than_worst_solo(self, msgstoy_cohort):
        be, members, solos = msgstoy_cohort
        donor = members[0].engine.plan.batch_descriptor()
        worst = max(d["bits_per_state"] for _, d in solos)
        assert donor["bits_per_state"] <= worst
        assert donor["proven_lanes"] >= \
            min(d["proven_lanes"] for _, d in solos)

    def test_donor_keeps_structured_proofs(self, msgstoy_cohort):
        be, members, _solos = msgstoy_cohort
        m0 = members[0].engine.model
        rep = m0._bounds_report
        eb = rep.element_bounds()
        # the union tree: msgs rng covers BOTH members' Cap
        assert eb["msgs"].rng.all == (0, 3)
        # clock never makes lane_bounds (no whole-variable summary)
        # but its structured dom proof survives the merge
        assert "clock" not in rep.lane_bounds()
        assert eb["clock"].dom is not None

    def test_members_match_solo(self, msgstoy_cohort):
        _be, members, solos = msgstoy_cohort
        for mem, (sr, _d) in zip(members, solos):
            assert mem.error is None
            assert _result_tuple(mem.result) == _result_tuple(sr)

    def test_record_cohort_element_merge_beats_lane_union(
            self, tmp_path):
        # the satellite fixture: a record whose fields have wildly
        # different ranges.  The whole-variable union (0,103) widens
        # BOTH fields to 7 bits (14 bits/state); the structural merge
        # keeps small at (0,3) and big at (100,103) — 4 bits/state,
        # exactly the worst solo member's plan
        from jaxmc.analyze.bounds import (infer_state_bounds,
                                          merge_element_bounds,
                                          merge_lane_bounds)
        from jaxmc.backend.batch import BatchCheckEngine
        from jaxmc.backend.bfs import TpuExplorer
        spec = str(tmp_path / "recbatch.tla")
        with open(spec, "w") as f:
            f.write(
                "---------------- MODULE recbatch ----------------\n"
                "EXTENDS Naturals\nCONSTANTS Lim\nVARIABLES r\n"
                "Init == r = [small |-> 0, big |-> 100]\n"
                "BumpS == /\\ r.small < 3\n"
                "         /\\ r' = [r EXCEPT !.small = @ + 1]\n"
                "BumpB == /\\ r.big < Lim\n"
                "         /\\ r' = [r EXCEPT !.big = @ + 1]\n"
                "Next == BumpS \\/ BumpB\n"
                "Spec == Init /\\ [][Next]_<<r>>\n"
                "=================================================\n")
        paths = []
        for tag, lim in (("a", 101), ("b", 103)):
            p = str(tmp_path / f"{tag}.cfg")
            with open(p, "w") as f:
                f.write(f"SPECIFICATION Spec\nCONSTANTS\n"
                        f"  Lim = {lim}\n")
            paths.append(p)
        reports = [infer_state_bounds(load_model(spec, p, True))
                   for p in paths]
        # the lane union widens member a's (0,101) proof AND swallows
        # small's (0,3) into one 7-bit interval...
        assert merge_lane_bounds(
            [r.lane_bounds() for r in reports]) == {"r": (0, 103)}
        # ...while the structural merge keeps each field's own width
        meb = merge_element_bounds(
            [r.element_bounds() for r in reports])
        assert meb["r"].keys["small"].all == (0, 3)
        assert meb["r"].keys["big"].all == (100, 103)

        solos = []
        for p in paths:
            eng = TpuExplorer(load_model(spec, p, True),
                              host_seen=True)
            solos.append((eng.run(), eng.plan.batch_descriptor()))
        cfgs = [SessionConfig(spec=spec, cfg=p, backend="jax",
                              platform="cpu", host_seen=True,
                              no_deadlock=True) for p in paths]
        members = BatchCheckEngine(cfgs).build().run()
        donor = members[0].engine.plan.batch_descriptor()
        worst = max(d["bits_per_state"] for _, d in solos)
        assert donor["bits_per_state"] <= worst
        assert donor["bits_per_state"] < 14  # the lane-union width
        for mem, (sr, _d) in zip(members, solos):
            assert mem.error is None
            assert _result_tuple(mem.result) == _result_tuple(sr)


def prime_spool(spool, variants, opts=JAX_OPTS):
    """Queue one job per variant in a COLD spool (before any daemon
    life), so the first pop claims the whole cohort."""
    q = JobQueue(spool)
    jids = []
    for v in variants:
        cfg = build_config(BT, btcfg(v), opts)
        prof = batch_profile(cfg)
        job = q.new_job(cfg.spec, cfg.cfg, opts, job_signature(cfg),
                        bsig=prof.bsig if prof else None,
                        cost_estimate=prof.cost_estimate
                        if prof else None)
        jids.append(job["id"])
    return jids


class TestServeFleet:
    def test_cold_cohort_one_vbatch(self, tmp_path):
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("a", "b", "c", "bad"))
        d = ServeDaemon(spool, workers=2, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d.port)
            recs = {j: c.wait(j, timeout=240) for j in jids}
            for v, j in zip(("a", "b", "c", "bad"), jids):
                solo = _solo(v)
                assert recs[j]["status"] == "done"
                assert recs[j]["ok"] == solo.ok
                assert recs[j]["distinct"] == solo.distinct
                assert recs[j]["generated"] == solo.generated
                assert recs[j]["batch_occupancy"] == 4
            st = d.status()
            assert st["gauges"]["serve.batch_occupancy"] == 4
            assert st["gauges"]["serve.batch_compiles"] == 1
            assert st["counters"]["serve.vbatch_jobs"] == 4
            # artifacts: batch block + cost estimate + trace for the
            # violating member
            code, res = c.result(jids[3])
            assert code == 200
            sv = res["serve"]
            assert sv["batch_occupancy"] == 4
            assert sv["lifted_consts"] == ["Bound", "Limit", "Step",
                                           "WrapCap"]
            assert isinstance(sv["cost_estimate"], int)
            assert res["result"]["violation"]["name"] == "InBound"
            solo_bad = _solo("bad")
            assert res["result"]["trace"] == \
                format_trace(solo_bad.violation)
        finally:
            d.shutdown()

    def test_fast_lane_jumps_queue(self, tmp_path, monkeypatch):
        # batchtoy's proven estimate (~65-95 states) sits under the
        # bound; transfer_scaled's (~768) sits over it
        monkeypatch.setenv("JAXMC_SERVE_FASTLANE_BOUND", "100")
        spool = str(tmp_path / "spool")
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d.port)
            # occupy the single worker so queue order is observable
            # (bench1 compiles + runs for a few seconds)
            code, blocker = c.submit(BT, btcfg("bench1"), JAX_OPTS)
            deadline = time.time() + 60
            while time.time() < deadline and \
                    (d.q.load(blocker["id"]) or {}).get("status") \
                    != "running":
                time.sleep(0.01)
            code, slow = c.submit(
                os.path.join(SPECS, "transfer_scaled.tla"),
                options={"backend": "jax", "platform": "cpu",
                         "host_seen": True, "max_states": 50})
            code, fast = c.submit(BT, btcfg("a"), JAX_OPTS)
            assert fast.get("fast_lane") is True
            with d._cv:
                pending = list(d._pending)
            # the proven-small job queued FIRST despite arriving last
            assert pending.index(fast["id"]) < \
                pending.index(slow["id"])
            assert d.tel.counters.get("serve.fastlane_jobs", 0) >= 1
        finally:
            d.shutdown()

    def test_owner_solo_device_job(self, tmp_path, monkeypatch):
        # owner mode routes SOLO device jobs out of the daemon process
        # too; the result is solo-identical and the record says so
        monkeypatch.setenv("JAXMC_SERVE_DEVICE_OWNER", "1")
        spool = str(tmp_path / "spool")
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d.port)
            code, job = c.submit(BT, btcfg("a"), JAX_OPTS)
            assert code == 200
            rec = c.wait(job["id"], timeout=240)
            assert rec["status"] == "done"
            assert rec["device_owner"] is True
            solo = _solo("a")
            assert rec["distinct"] == solo.distinct
            code, res = c.result(job["id"])
            assert res["serve"]["device_owner"] is True
            assert d.status()["device_owner_pid"] is not None
        finally:
            d.shutdown()

    def test_batch_disabled_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JAXMC_SERVE_BATCH", "0")
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("a", "b"))
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d.port)
            for j in jids:
                assert c.wait(j, timeout=240)["status"] == "done"
            assert d.tel.counters.get("serve.vbatch_jobs", 0) == 0
        finally:
            d.shutdown()


class TestRaces:
    def test_claimed_followers_never_double_run(self, tmp_path):
        # 6 jobs in one compat class, 3 workers racing to pop: every
        # job must land exactly one terminal result, each claimed
        # member registered in _running while in flight
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("a", "b", "c", "a", "b", "c"))
        d = ServeDaemon(spool, workers=3, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d.port)
            for j in jids:
                rec = c.wait(j, timeout=240)
                assert rec["status"] == "done", rec
            done = d.tel.counters.get("serve.jobs_done", 0)
            vb = d.tel.counters.get("serve.vbatch_jobs", 0)
            assert done == 6
            assert vb >= 4  # at least one cross-model cohort formed
            # exactly one result artifact per job, written once
            for j in jids:
                assert d.q.load_result(j) is not None
        finally:
            d.shutdown()

    def test_sig_lock_eviction_race_fixed(self, tmp_path):
        # ISSUE 13 bugfix: _locked_sig must hold the REGISTERED lock
        # even when eviction popped + a fresh lock was registered
        # between the fetch and the acquire
        d = ServeDaemon(str(tmp_path / "spool"), workers=1, quiet=True)
        stale = threading.Lock()
        real = d._sig_lock
        first = []

        def fetch(sig):
            if not first:
                first.append(1)
                with d._cv:
                    # simulate: eviction dropped the entry and another
                    # submission re-registered a fresh lock after this
                    # worker fetched `stale`
                    d._sig_locks[sig] = threading.Lock()
                return stale
            return real(sig)

        d._sig_lock = fetch
        with d._locked_sig("s1"):
            with d._cv:
                held = d._sig_locks["s1"]
            assert held.locked(), \
                "worker must end up holding the registered lock"
            assert not stale.locked(), \
                "the stale pre-fetched lock must have been released"
        assert not d._sig_locks["s1"].locked()

    def test_eviction_never_pops_held_sig_lock(self, tmp_path):
        d = ServeDaemon(str(tmp_path / "spool"), workers=1, quiet=True)
        d.warm_max = 0
        lk = d._sig_lock("busy")
        lk.acquire()
        try:
            with d._cv:
                d.warm["busy"] = {"session": None, "completed": True}
                d._evict_warm_locked()
                # held lock -> the sig survives eviction untouched
                assert d._sig_locks.get("busy") is lk
        finally:
            lk.release()


@pytest.mark.chaos
@pytest.mark.slow
class TestChaos:
    # chaos+slow (the pytest.ini pattern): `make chaos` runs these;
    # tier-1 timing stays inside its budget
    def test_drain_mid_batch_then_resume_parity(self, tmp_path):
        # deep cohort, drain mid-flight: members park as drained (no
        # result yet), requeue next life, and the re-run answers with
        # solo-identical counts — a batch can be delayed, never lost
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("bench1", "bench2", "bench3",
                                   "bench4"))
        d = ServeDaemon(spool, workers=2, quiet=True).start()
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(d.q.load(j).get("status") == "running"
                   for j in jids):
                break
            time.sleep(0.02)
        d.initiate_drain("test drain mid-batch")
        d.shutdown()
        statuses = {d.q.load(j).get("status") for j in jids}
        assert statuses <= {"queued", "drained", "done"}, statuses
        # next life: recover() requeues drained members, all complete
        d2 = ServeDaemon(spool, workers=2, quiet=True).start()
        try:
            c = ServeClient("127.0.0.1", d2.port)
            for v, j in zip(("bench1", "bench2", "bench3", "bench4"),
                            jids):
                rec = c.wait(j, timeout=300)
                assert rec["status"] == "done", rec
                solo = _solo(v)
                assert rec["distinct"] == solo.distinct
                assert rec["generated"] == solo.generated
        finally:
            d2.shutdown()

    def test_device_owner_death_requeues_and_respawns(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("JAXMC_SERVE_DEVICE_OWNER", "1")
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("bench1", "bench2", "bench3",
                                   "bench4"))
        d = ServeDaemon(spool, workers=2, quiet=True).start()
        try:
            import signal as _sig
            # kill the owner while the cohort is in flight
            deadline = time.time() + 180
            killed = False
            while time.time() < deadline and not killed:
                pid = d.owner.pid
                if pid is not None and any(
                        d.q.load(j).get("status") == "running"
                        for j in jids):
                    try:
                        os.kill(pid, _sig.SIGKILL)
                        killed = True
                    except ProcessLookupError:
                        pass
                time.sleep(0.05)
            c = ServeClient("127.0.0.1", d.port)
            for v, j in zip(("bench1", "bench2", "bench3", "bench4"),
                            jids):
                rec = c.wait(j, timeout=300)
                assert rec["status"] == "done", rec
                solo = _solo(v)
                assert rec["distinct"] == solo.distinct
            if killed:
                assert d.tel.counters.get("serve.owner_respawns",
                                          0) >= 1
                assert d.owner.spawns >= 2
        finally:
            d.shutdown()


class TestObs:
    def test_fleet_artifact_highlight_row(self, tmp_path, capsys):
        spool = str(tmp_path / "spool")
        jids = prime_spool(spool, ("a", "b", "c"))
        out = str(tmp_path / "fleet.json")
        d = ServeDaemon(spool, workers=1, quiet=True,
                        metrics_out=out).start()
        c = ServeClient("127.0.0.1", d.port)
        for j in jids:
            c.wait(j, timeout=240)
        d.shutdown()
        import argparse
        import io
        from jaxmc.obs.report import cmd_report
        buf = io.StringIO()
        rc = cmd_report(argparse.Namespace(file=out), out=buf)
        assert rc == 0
        assert "batch[occupancy=3" in buf.getvalue()
