import os
import sys

# Tests run on CPU with a virtual 8-device mesh so multi-chip sharding logic
# is exercised without TPU hardware (the driver separately dry-runs
# multichip). The axon TPU plugin registers itself in sitecustomize at
# interpreter start, so setting JAX_PLATFORMS in os.environ here is too late
# — jax.config.update is the reliable runtime switch.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # NOTE: do NOT enable jax_compilation_cache_dir here — XLA:CPU
    # persists AOT-compiled blobs whose reload can hang when the cache
    # was written by a different machine/build (observed: cache hit on
    # the resident-mode while_loop program never returns).
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ISSUE 17: every metrics write appends a trajectory point to the run
# ledger (~/.cache/jaxmc/ledger.jsonl) unless redirected — the suite
# must never pollute the developer's real history.  Tests that need a
# live ledger monkeypatch JAXMC_LEDGER to a tmp path themselves.
os.environ.setdefault("JAXMC_LEDGER", "off")

REFERENCE = os.environ.get("JAXMC_REFERENCE", "/root/reference")

# The reference spec corpus is mounted in the DRIVER environment only —
# builder/CI containers run without it (ISSUE 6 satellite).  Tests that
# load reference specs skip with this named marker instead of failing,
# so tier-1 is green wherever the repo is checked out.
HAVE_REFERENCE = os.path.isdir(os.path.join(REFERENCE, "examples"))

import pytest  # noqa: E402

needs_reference = pytest.mark.skipif(
    not HAVE_REFERENCE,
    reason=f"needs the reference spec corpus at {REFERENCE} (driver "
           f"environment only; point JAXMC_REFERENCE at a checkout)")
