import os
import sys

# Tests run on CPU with a virtual 8-device mesh so multi-chip sharding logic is
# exercised without TPU hardware (the driver separately dry-runs multichip).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REFERENCE = os.environ.get("JAXMC_REFERENCE", "/root/reference")
