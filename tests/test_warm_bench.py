r"""Warm-start steady-state bench machinery (ISSUE 5).

The contract: a resident-mode truncation checkpoint is RESUMABLE, and a
resumed run's final counts are bit-identical to a cold run's — so the
bench's steady-state window (timed run resumed from the warm
checkpoint) measures exactly the cold workload with compile/warm-up
excluded.  Repo-local models only (transfer_scaled, symtoy); the bench
model itself needs the reference tree and is covered by the slow-marked
leg at the bottom.
"""

import os

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from jaxmc.front.cfg import parse_cfg  # noqa: E402
from jaxmc.sem.modules import Loader, bind_model  # noqa: E402
from jaxmc.tpu.bfs import TpuExplorer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def load(spec, cfg):
    ldr = Loader([SPECS, "/root/reference/examples"])
    with open(os.path.join(SPECS, cfg)) as fh:
        return bind_model(ldr.load_path(os.path.join(SPECS, spec)),
                          parse_cfg(fh.read()))


def test_resident_truncation_checkpoint_resume_parity(tmp_path):
    # cold truncated run vs (warm prefix -> checkpoint -> resume) at the
    # same bound: counts, diameter and truncation must be identical.
    # max_states is evaluated per LEVEL inside the device loop, so the
    # truncation point is deterministic regardless of dispatch batching.
    cold = TpuExplorer(load("transfer_scaled.tla",
                            "transfer_scaled.cfg"),
                       store_trace=False, resident=True,
                       max_states=8000).run()
    assert cold.truncated
    ck = str(tmp_path / "warm.ck")
    rw = TpuExplorer(load("transfer_scaled.tla", "transfer_scaled.cfg"),
                     store_trace=False, resident=True, max_states=600,
                     checkpoint_path=ck).run()
    assert rw.truncated and os.path.exists(ck), \
        "truncation must write a resumable checkpoint"
    assert rw.distinct < cold.distinct, "prefix must stop earlier"
    r = TpuExplorer(load("transfer_scaled.tla", "transfer_scaled.cfg"),
                    store_trace=False, resident=True, max_states=8000,
                    resume_from=ck).run()
    assert (r.generated, r.distinct, r.diameter, r.truncated) == \
        (cold.generated, cold.distinct, cold.diameter, cold.truncated)


def test_resident_warm_resume_full_run_parity(tmp_path):
    # the bench shape end to end on a tiny model: cold COMPLETE run vs
    # warm-checkpoint resume run to completion — bit-identical totals
    # and verdict
    cold = TpuExplorer(load("symtoy.tla", "symtoy.cfg"),
                       store_trace=False, resident=True).run()
    ck = str(tmp_path / "warm.ck")
    TpuExplorer(load("symtoy.tla", "symtoy.cfg"), store_trace=False,
                resident=True, max_states=8, checkpoint_path=ck).run()
    r = TpuExplorer(load("symtoy.tla", "symtoy.cfg"), store_trace=False,
                    resident=True, resume_from=ck).run()
    assert (r.generated, r.distinct, r.ok, r.truncated) == \
        (cold.generated, cold.distinct, cold.ok, cold.truncated)


def test_res_caps_hint_respected():
    # the bench passes known steady-state caps so the one warm-up
    # compile covers the whole run — the hint must floor the defaults
    ex = TpuExplorer(load("symtoy.tla", "symtoy.cfg"),
                     store_trace=False, resident=True,
                     res_caps={"SC": 1 << 16})
    ex.run()
    assert ex._res_caps["SC"] >= (1 << 16)


def test_warm_start_skips_garbage_and_uses_probe_dir_ck(tmp_path,
                                                        monkeypatch):
    # bench._warm_start's source ladder: a garbage committed artifact is
    # REFUSED by the container integrity checks and the probe-dir copy
    # from a previous round is used instead — the warm start can never
    # corrupt the measurement
    import bench
    from jaxmc import obs
    spec = os.path.join(SPECS, "transfer_scaled.tla")
    cfg = os.path.join(SPECS, "transfer_scaled.cfg")
    monkeypatch.setattr(bench, "SPEC", spec)
    monkeypatch.setattr(bench, "CFG_FULL", cfg)
    monkeypatch.setattr(bench, "_PROBE_DIR", str(tmp_path))
    garbage = tmp_path / "committed.ck"
    garbage.write_bytes(b"not a checkpoint at all")
    monkeypatch.setattr(bench, "_WARM_CK_COMMITTED", str(garbage))
    # a previous round's scratch checkpoint:
    scratch = str(tmp_path / "jaxmc_bench_warm_full.ck")
    TpuExplorer(load("transfer_scaled.tla", "transfer_scaled.cfg"),
                store_trace=False, resident=True, max_states=600,
                checkpoint_path=scratch).run()
    tel = obs.Telemetry()
    ex = TpuExplorer(load("transfer_scaled.tla", "transfer_scaled.cfg"),
                     store_trace=False, resident=True)
    with obs.use(tel):
        steady, r_warm = bench._warm_start(tel, ex)
    assert steady is not None and steady["source"] == "probe-dir"
    assert r_warm is None, "checkpoint resume needs no full warm pass"
    assert ex.resume_from == scratch and ex.max_states is None
    assert steady["resumed_generated"] > 0


@pytest.mark.slow
def test_bench_model_warm_resume_parity(tmp_path):
    # the ISSUE 5 acceptance pin on the REAL bench model (needs the
    # reference raft tree; slow): warm resume reproduces the manifest's
    # cold-run totals exactly
    from jaxmc.corpus import case_for_cfg
    pin = case_for_cfg("MCraft_3s_bench.cfg")
    assert pin is not None and pin.distinct is not None
    ck = str(tmp_path / "warm.ck")
    TpuExplorer(load("MCraftMicro.tla", "MCraft_3s_bench.cfg"),
                store_trace=False, resident=True, max_states=20000,
                checkpoint_path=ck).run()
    r = TpuExplorer(load("MCraftMicro.tla", "MCraft_3s_bench.cfg"),
                    store_trace=False, resident=True,
                    resume_from=ck).run()
    assert (r.distinct, r.generated) == (pin.distinct, pin.generated)
    assert r.ok and not r.truncated
