r"""engine/ckpt.py — checkpoint integrity + the CLI exit-code contract.

ISSUE 4 acceptance: a truncated or checksum-corrupted checkpoint is
rejected with a clear one-line error (exit 2), never a traceback or a
silently-wrong resume.  Each Explorer resume defect (missing path,
module mismatch, corruption, legacy format) has its message pinned
here, through both the library surface (CkptError) and the CLI.
"""

import os
import subprocess
import sys

import pytest

from jaxmc.engine.ckpt import (CkptError, load_checkpoint,
                               load_interp_checkpoint, read_header,
                               write_checkpoint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def _payload():
    return {"module": "toy", "vars": ["x"], "states": [{"x": 1}],
            "seen_items": [((1,), 0)], "numbers": list(range(100))}


class TestContainer:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "c.ck")
        n = write_checkpoint(p, "interp", {"module": "toy"}, _payload())
        assert n == os.path.getsize(p)
        header, payload = load_checkpoint(p, kind="interp")
        assert header["kind"] == "interp"
        assert header["meta"] == {"module": "toy"}
        assert payload == _payload()
        assert read_header(p)["payload_bytes"] == \
            header["payload_bytes"]

    def test_missing_file(self, tmp_path):
        with pytest.raises(CkptError, match="no checkpoint at"):
            load_checkpoint(str(tmp_path / "absent.ck"))

    def test_truncated_rejected(self, tmp_path):
        p = str(tmp_path / "c.ck")
        write_checkpoint(p, "interp", {}, _payload())
        size = os.path.getsize(p)
        with open(p, "r+b") as fh:
            fh.truncate(size - size // 3)
        with pytest.raises(CkptError, match="truncated"):
            load_checkpoint(p)

    def test_bitflip_rejected(self, tmp_path):
        p = str(tmp_path / "c.ck")
        write_checkpoint(p, "interp", {}, _payload())
        size = os.path.getsize(p)
        with open(p, "r+b") as fh:
            fh.seek(size - 10)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(CkptError, match="integrity check"):
            load_checkpoint(p)

    def test_garbage_rejected(self, tmp_path):
        p = str(tmp_path / "c.ck")
        with open(p, "wb") as fh:
            fh.write(b"this is not a checkpoint at all" * 4)
        with pytest.raises(CkptError, match="not a jaxmc checkpoint"):
            load_checkpoint(p)

    def test_legacy_raw_pickle_rejected(self, tmp_path):
        # pre-ISSUE-4 checkpoints were bare pickles: refuse with a
        # version message, don't unpickle blind
        import pickle
        p = str(tmp_path / "old.ck")
        with open(p, "wb") as fh:
            pickle.dump({"states": [], "seen_items": []}, fh)
        with pytest.raises(CkptError, match="not a jaxmc checkpoint"):
            load_checkpoint(p)

    def test_kind_mismatch_rejected(self, tmp_path):
        p = str(tmp_path / "c.ck")
        write_checkpoint(p, "device", {}, _payload())
        with pytest.raises(CkptError,
                           match="'device' engine, this run expects "
                                 "'interp'"):
            load_checkpoint(p, kind="interp")

    def test_atomic_write_keeps_previous_on_damage(self, tmp_path):
        # the tmp+rename protocol: a second write that fails must not
        # destroy the first checkpoint
        p = str(tmp_path / "c.ck")
        write_checkpoint(p, "interp", {}, {"v": 1})
        _, payload = load_checkpoint(p)
        assert payload == {"v": 1}
        class Unpicklable:
            def __reduce__(self):
                raise RuntimeError("nope")
        with pytest.raises(Exception):
            write_checkpoint(p, "interp", {}, {"v": Unpicklable()})
        _, payload = load_checkpoint(p)
        assert payload == {"v": 1}  # previous checkpoint intact


class TestNonFatalPeriodicWrites:
    def test_failed_checkpoint_write_does_not_kill_the_search(self):
        # the write-side contract: disk trouble mid-run logs a warning
        # and keeps searching on the previous checkpoint — a robustness
        # PR must never ADD a way to lose hours of progress
        from jaxmc import obs
        from jaxmc.front.cfg import parse_cfg
        from jaxmc.sem.modules import Loader, bind_model
        from jaxmc.engine.explore import Explorer
        with open(os.path.join(SPECS, "constoy.cfg")) as fh:
            cfg = parse_cfg(fh.read())
        m = bind_model(
            Loader([SPECS]).load_path(os.path.join(SPECS,
                                                   "constoy.tla")), cfg)
        tel = obs.Telemetry()
        logs = []
        with obs.use(tel):
            r = Explorer(m, log=logs.append,
                         checkpoint_path="/nonexistent-dir/x/ck.bin",
                         checkpoint_every=0.0).run()
        assert r.ok and (r.generated, r.distinct) == (43, 21)
        assert any("checkpoint write failed" in l for l in logs)
        assert tel.counters.get("checkpoint.write_failures", 0) > 0


class TestExplorerResumeContract:
    """Satellite: Explorer resume errors route through the CLI as exit
    2 with a one-line remedy — path, module mismatch, corruption."""

    def _write_ck(self, tmp_path, quiet=True):
        ck = str(tmp_path / "run.ck")
        r = subprocess.run(
            [sys.executable, "-m", "jaxmc", "check",
             os.path.join(SPECS, "constoy.tla"), "--max-states", "10",
             "--checkpoint", ck, "--checkpoint-every", "0", "--quiet"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stderr
        assert os.path.exists(ck)
        return ck

    def _resume(self, spec, ck):
        return subprocess.run(
            [sys.executable, "-m", "jaxmc", "check",
             os.path.join(SPECS, spec), "--resume", ck, "--quiet"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))

    def test_missing_path_exit_2(self, tmp_path):
        r = self._resume("constoy.tla", str(tmp_path / "nope.ck"))
        assert r.returncode == 2
        assert "no checkpoint at" in r.stderr
        assert "Traceback" not in r.stderr
        assert r.stderr.count("\n") <= 2  # one actionable line

    def test_module_mismatch_exit_2(self, tmp_path):
        ck = self._write_ck(tmp_path)
        r = self._resume("viewtoy.tla", ck)
        assert r.returncode == 2
        assert "is for module 'constoy'" in r.stderr
        assert "not 'viewtoy'" in r.stderr
        assert "Traceback" not in r.stderr

    def test_corruption_exit_2(self, tmp_path):
        ck = self._write_ck(tmp_path)
        size = os.path.getsize(ck)
        with open(ck, "r+b") as fh:
            fh.truncate(size // 2)
        r = self._resume("constoy.tla", ck)
        assert r.returncode == 2
        assert "truncated" in r.stderr
        assert "Traceback" not in r.stderr

    def test_checksum_corruption_exit_2(self, tmp_path):
        ck = self._write_ck(tmp_path)
        size = os.path.getsize(ck)
        with open(ck, "r+b") as fh:
            fh.seek(size - 8)
            fh.write(b"\x00" * 8)
        r = self._resume("constoy.tla", ck)
        assert r.returncode == 2
        assert "integrity check" in r.stderr
        assert "Traceback" not in r.stderr

    def test_library_surface_module_mismatch(self, tmp_path):
        from jaxmc.front.cfg import parse_cfg
        from jaxmc.sem.modules import Loader, bind_model
        with open(os.path.join(SPECS, "viewtoy.cfg")) as fh:
            cfg = parse_cfg(fh.read())
        model = bind_model(
            Loader([SPECS]).load_path(os.path.join(SPECS, "viewtoy.tla")),
            cfg)
        ck = str(tmp_path / "other.ck")
        write_checkpoint(ck, "interp", {}, {
            "module": "constoy", "vars": ["a", "b"], "states": [],
            "seen_items": []})
        with pytest.raises(CkptError, match="is for module 'constoy'"):
            load_interp_checkpoint(ck, model, model.vars, False)
