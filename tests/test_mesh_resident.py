r"""Mesh-resident sharded BFS (ISSUE 8 + ISSUE 10): owner-routed a2a
dedup, O(new) rank-merge, multi-level fused supersteps.

Pins, on repo-local models only (no reference corpus needed):
  * a2a is the DEFAULT exchange for D > 1 (JAXMC_MESH_EXCHANGE
    overrides); rank-merge is the DEFAULT dedup-merge
    (JAXMC_MESH_RANKMERGE=0 forces the PR-8 fullsort);
  * the resident loop reads ONE scalar ring per SUPERSTEP —
    mesh.host_syncs counts supersteps (<= level records, < on any
    multi-level run), no row traffic; JAXMC_MESH_SUPERSTEP=1 restores
    one-sync-per-level exactly;
  * rank vs fullsort and superstep vs one-level are BIT-IDENTICAL:
    counts, distinct totals, violation traces, and (post the PR-10
    stale-tail fix) seen-shard occupancy — including under the
    mesh_skew fault and mid-superstep capacity growth;
  * a second run on a warm engine has window_recompiles == 0, and a
    FRESH engine starting from the persisted (module, layout, D,
    exchange) capacity profile compiles exactly once with zero
    growth redos;
  * checkpoint/resume parity under a2a at D=4 — truncation resume,
    a SIGTERM drain at a superstep boundary, and a SIGKILL mid-run
    (chaos) all finish with totals and traces bit-identical to the
    uninterrupted run;
  * the mesh_skew fault forces every state onto shard 0: the spill
    pass drains the overflow and counts/traces stay exact.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")
REPO = os.path.dirname(SPECS)


def load(name, cfg_name=None, no_deadlock=False):
    p = os.path.join(SPECS, name + ".tla")
    m = Loader([SPECS]).load_path(p)
    if cfg_name is None and os.path.exists(
            os.path.join(SPECS, name + ".cfg")):
        cfg_name = name
    if cfg_name:
        cfg = parse_cfg(open(os.path.join(SPECS,
                                          cfg_name + ".cfg")).read())
    else:
        cfg = ModelConfig(specification="Spec")
    if no_deadlock:
        cfg.check_deadlock = False
    return bind_model(m, cfg)


@pytest.fixture(autouse=True)
def _no_profile_store(tmp_path, monkeypatch):
    # isolate every test's capacity profiles (and keep the box-wide
    # store out of the parity measurements)
    monkeypatch.setenv("JAXMC_PROFILE_STORE", str(tmp_path / "prof"))


def mesh4():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:4]), ("d",))


class TestExchangeDefault:
    def test_a2a_default_for_multidevice(self):
        from jaxmc.tpu.mesh import MeshExplorer
        me = MeshExplorer(load("constoy"))
        assert me.D > 1 and me.exchange == "a2a"
        assert me._exchange_src == "default"

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("JAXMC_MESH_EXCHANGE", "gather")
        from jaxmc.tpu.mesh import MeshExplorer
        me = MeshExplorer(load("constoy"))
        assert me.exchange == "gather"
        assert me._exchange_src == "JAXMC_MESH_EXCHANGE"

    def test_explicit_arg_beats_env(self, monkeypatch):
        monkeypatch.setenv("JAXMC_MESH_EXCHANGE", "gather")
        from jaxmc.tpu.mesh import MeshExplorer
        me = MeshExplorer(load("constoy"), exchange="a2a")
        assert me.exchange == "a2a"

    def test_single_device_defaults_gather(self):
        import jax
        from jax.sharding import Mesh
        from jaxmc.tpu.mesh import MeshExplorer
        me = MeshExplorer(load("constoy"),
                          mesh=Mesh(np.array(jax.devices()[:1]),
                                    ("d",)))
        assert me.exchange == "gather"


class TestResidentLoop:
    def test_host_syncs_counts_supersteps_scalars_only(self):
        from jaxmc import obs
        from jaxmc.tpu.mesh import MeshExplorer
        from jaxmc.engine.explore import Explorer
        ri = Explorer(load("constoy")).run()
        tel = obs.Telemetry()
        with obs.use(tel):
            me = MeshExplorer(load("constoy"), exchange="a2a")
            r = me.run()
        assert (r.generated, r.distinct, r.ok) == \
            (ri.generated, ri.distinct, ri.ok)
        # one scalar-ring read per SUPERSTEP (ISSUE 10): the adaptive
        # controller fuses levels, so syncs < level records on this
        # multi-level model; a clean run still pulls NO rows
        levels = len(tel.levels)
        assert tel.counters["mesh.host_syncs"] == \
            tel.gauges["mesh.supersteps"] <= levels
        assert tel.counters["mesh.host_syncs"] < levels
        assert tel.gauges["mesh.superstep_levels"] >= 2
        assert "mesh.row_syncs" not in tel.counters
        assert tel.counters["mesh.exchange_bytes"] > 0
        assert tel.gauges["mesh.exchange"] == "a2a"
        assert tel.gauges["mesh.merge"] == "rank"
        assert tel.gauges["dedup.mode"].startswith("fp128")
        assert tel.gauges["mesh.shard_balance"] >= 1.0

    def test_superstep_one_pins_one_sync_per_level(self, monkeypatch):
        monkeypatch.setenv("JAXMC_MESH_SUPERSTEP", "1")
        from jaxmc import obs
        from jaxmc.tpu.mesh import MeshExplorer
        tel = obs.Telemetry()
        with obs.use(tel):
            r = MeshExplorer(load("constoy"), exchange="a2a").run()
        assert r.ok
        assert tel.counters["mesh.host_syncs"] == len(tel.levels)

    def test_second_run_zero_window_recompiles(self):
        from jaxmc import obs
        from jaxmc.tpu.mesh import MeshExplorer
        tel = obs.Telemetry()
        with obs.use(tel):
            me = MeshExplorer(load("constoy"), exchange="a2a")
            r1 = me.run()
            lvl0 = len(tel.levels)
            r2 = me.run()
        fresh = sum(1 for lv in tel.levels[lvl0:]
                    if lv.get("fresh_compile"))
        assert fresh == 0
        assert (r2.generated, r2.distinct) == (r1.generated, r1.distinct)

    def test_profile_warms_a_fresh_engine(self):
        # run 1 persists the (module, layout_sig, D, exchange) profile;
        # a FRESH engine loads it, compiles exactly once, never grows
        from jaxmc import obs
        from jaxmc.tpu.mesh import MeshExplorer
        MeshExplorer(load("viewtoy"), exchange="a2a").run()
        tel = obs.Telemetry()
        with obs.use(tel):
            me = MeshExplorer(load("viewtoy"), exchange="a2a")
            assert me._mesh_caps_hint, "profile did not load"
            me.run()
        assert sum(1 for lv in tel.levels
                   if lv.get("fresh_compile")) == 1
        assert not any(lv.get("redo") for lv in tel.levels)

    def test_profile_is_keyed_by_device_count(self):
        from jaxmc.compile.cache import profile_path
        p4 = profile_path("m", "sig", variant="mesh-d4-a2a")
        p8 = profile_path("m", "sig", variant="mesh-d8-a2a")
        assert p4 != p8

    def test_gather_and_a2a_bit_identical(self):
        from jaxmc.tpu.mesh import MeshExplorer
        rg = MeshExplorer(load("constoy"), exchange="gather").run()
        ra = MeshExplorer(load("constoy"), exchange="a2a").run()
        assert (rg.generated, rg.distinct, rg.ok) == \
            (ra.generated, ra.distinct, ra.ok)

    def test_d4_counts_and_view_symmetry_parity(self):
        from jaxmc.engine.explore import Explorer
        from jaxmc.tpu.mesh import MeshExplorer
        for name, kw in (("viewtoy", {}),
                         ("symtoy", dict(no_deadlock=True))):
            ri = Explorer(load(name, **kw)).run()
            r = MeshExplorer(load(name, **kw), mesh=mesh4(),
                             exchange="a2a").run()
            assert (r.generated, r.distinct, r.ok) == \
                (ri.generated, ri.distinct, ri.ok), name

    def test_violation_trace_parity_with_hostloop(self):
        # the resident loop and the legacy host loop must report the
        # SAME counterexample (rows ride the device ring vs per-level
        # host pulls — one provenance contract)
        from jaxmc.tpu.mesh import MeshExplorer
        r_res = MeshExplorer(load("pcal_intro_buggy"),
                             exchange="a2a").run()
        os.environ["JAXMC_MESH_RESIDENT"] = "0"
        try:
            r_host = MeshExplorer(load("pcal_intro_buggy"),
                                  exchange="a2a").run()
        finally:
            os.environ.pop("JAXMC_MESH_RESIDENT", None)
        assert not r_res.ok and not r_host.ok
        assert r_res.violation.kind == r_host.violation.kind == "assert"
        assert [s for s, _ in r_res.violation.trace] == \
            [s for s, _ in r_host.violation.trace]
        assert [a for _, a in r_res.violation.trace] == \
            [a for _, a in r_host.violation.trace]


class TestMergeStrategies:
    """ISSUE 10: rank-merge vs fullsort bit-identical parity."""

    def test_rankmerge_env_escape_hatch(self, monkeypatch):
        from jaxmc.tpu.mesh import MeshExplorer
        assert MeshExplorer(load("constoy")).merge == "rank"
        monkeypatch.setenv("JAXMC_MESH_RANKMERGE", "0")
        me = MeshExplorer(load("constoy"))
        assert me.merge == "fullsort"
        # fullsort cannot run under the superstep while_loop: it is
        # pinned to the one-level-per-dispatch program
        assert me._ss_fixed == 1

    def test_rank_vs_fullsort_counts_and_occupancy_d2(self,
                                                      monkeypatch):
        from jaxmc.tpu.mesh import MeshExplorer
        ma = MeshExplorer(load("constoy"), exchange="a2a")
        ra = ma.run()
        monkeypatch.setenv("JAXMC_MESH_RANKMERGE", "0")
        mf = MeshExplorer(load("constoy"), exchange="a2a")
        rf = mf.run()
        assert (ra.generated, ra.distinct, ra.ok) == \
            (rf.generated, rf.distinct, rf.ok)
        # the PR-10 stale-tail fix: both strategies agree on the TRUE
        # fingerprint occupancy (the PR-8 fullsort re-counted dup tail
        # rows across levels)
        assert ma._fp_occupancy == mf._fp_occupancy

    def test_rank_vs_fullsort_violation_trace_d2(self, monkeypatch):
        from jaxmc.tpu.mesh import MeshExplorer
        ra = MeshExplorer(load("pcal_intro_buggy"),
                          exchange="a2a").run()
        monkeypatch.setenv("JAXMC_MESH_RANKMERGE", "0")
        rf = MeshExplorer(load("pcal_intro_buggy"),
                          exchange="a2a").run()
        assert not ra.ok and not rf.ok
        assert (ra.generated, ra.distinct, ra.violation.kind) == \
            (rf.generated, rf.distinct, rf.violation.kind)
        assert [s for s, _ in ra.violation.trace] == \
            [s for s, _ in rf.violation.trace]
        assert [a for _, a in ra.violation.trace] == \
            [a for _, a in rf.violation.trace]

    @pytest.mark.slow
    def test_rank_vs_fullsort_view_symmetry_d4(self, monkeypatch):
        # the VIEW and SYMMETRY rungs at D=4: the key basis (cfg VIEW
        # lanes / orbit-canonical packing) must dedup identically
        # under both merge strategies
        from jaxmc.tpu.mesh import MeshExplorer
        for name, kw in (("viewtoy", {}),
                         ("symtoy", dict(no_deadlock=True))):
            monkeypatch.delenv("JAXMC_MESH_RANKMERGE", raising=False)
            ra = MeshExplorer(load(name, **kw), mesh=mesh4(),
                              exchange="a2a").run()
            monkeypatch.setenv("JAXMC_MESH_RANKMERGE", "0")
            rf = MeshExplorer(load(name, **kw), mesh=mesh4(),
                              exchange="a2a").run()
            assert (ra.generated, ra.distinct, ra.ok) == \
                (rf.generated, rf.distinct, rf.ok), name

    @pytest.mark.slow
    def test_rank_vs_fullsort_under_skew_spill(self, monkeypatch):
        # hash-skew (every state on shard 0) exercises the spill pass
        # and the most imbalanced merge inputs — both strategies must
        # stay exact
        from jaxmc import faults
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_FAULTS", "mesh_skew:n=2")
        faults.reset_for_tests()
        ra = MeshExplorer(load("constoy"), exchange="a2a").run()
        monkeypatch.setenv("JAXMC_MESH_RANKMERGE", "0")
        rf = MeshExplorer(load("constoy"), exchange="a2a").run()
        assert (ra.generated, ra.distinct, ra.ok) == \
            (rf.generated, rf.distinct, rf.ok)
        faults.reset_for_tests()


class TestSuperstep:
    """ISSUE 10: multi-level fused supersteps."""

    def test_superstep_vs_one_level_violation_parity(self,
                                                     monkeypatch):
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_MESH_SUPERSTEP", "8")
        rs = MeshExplorer(load("pcal_intro_buggy"),
                          exchange="a2a").run()
        monkeypatch.setenv("JAXMC_MESH_SUPERSTEP", "1")
        r1 = MeshExplorer(load("pcal_intro_buggy"),
                          exchange="a2a").run()
        assert not rs.ok and not r1.ok
        assert (rs.generated, rs.distinct, rs.violation.kind) == \
            (r1.generated, r1.distinct, r1.violation.kind)
        assert [s for s, _ in rs.violation.trace] == \
            [s for s, _ in r1.violation.trace]
        assert [a for _, a in rs.violation.trace] == \
            [a for _, a in r1.violation.trace]

    def test_seen_overflow_mid_superstep_grows_and_redoes(
            self, monkeypatch):
        # pcal_intro_buggy outgrows the 256-key SC floor within the
        # first few levels; with an 8-level budget the overflow lands
        # MID-superstep — the offending level must roll back, grow,
        # and redo with counts/trace identical to a generously-capped
        # run
        from jaxmc import obs
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_MESH_SUPERSTEP", "8")
        tel = obs.Telemetry()
        with obs.use(tel):
            r = MeshExplorer(load("pcal_intro_buggy"),
                             exchange="a2a").run()
        redos = [lv for lv in tel.levels if lv.get("redo")]
        assert redos, "no growth redo fired under the tiny SC floor"
        assert any("SC->" in lv["redo"] for lv in redos)
        rg = MeshExplorer(load("pcal_intro_buggy"), exchange="a2a",
                          mesh_caps={"SC": 1 << 14, "FC": 1 << 10,
                                     "TRL": 16, "GAM16": 32}).run()
        assert (r.generated, r.distinct, r.violation.kind) == \
            (rg.generated, rg.distinct, rg.violation.kind)
        assert [s for s, _ in r.violation.trace] == \
            [s for s, _ in rg.violation.trace]

    @pytest.mark.chaos
    def test_drain_at_superstep_boundary_resume_parity(
            self, tmp_path, monkeypatch):
        # request a drain (the SIGTERM path, jaxmc/drain.py) once the
        # search reaches depth 2: the loop must stop at the NEXT
        # superstep boundary, checkpoint, report drained=True — and a
        # resume must answer bit-identically to an uninterrupted run
        from jaxmc import drain, obs
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_MESH_SUPERSTEP", "2")
        ck = str(tmp_path / "mesh_drain.ck")

        class DrainAt(obs.Telemetry):
            def level(self, lvl, **kw):
                super().level(lvl, **kw)
                if lvl >= 2 and not kw.get("redo"):
                    drain.request("test drain at superstep boundary")

        drain.clear()
        try:
            tel = DrainAt()
            with obs.use(tel):
                r1 = MeshExplorer(load("pcal_intro_buggy"),
                                  exchange="a2a", checkpoint_path=ck,
                                  checkpoint_every=0).run()
            assert r1.drained and r1.truncated and r1.ok
            assert os.path.exists(ck)
        finally:
            drain.clear()
        r2 = MeshExplorer(load("pcal_intro_buggy"), exchange="a2a",
                          resume_from=ck).run()
        rd = MeshExplorer(load("pcal_intro_buggy"),
                          exchange="a2a").run()
        assert (r2.ok, r2.generated, r2.distinct,
                r2.violation.kind) == \
            (rd.ok, rd.generated, rd.distinct, rd.violation.kind)
        assert [s for s, _ in r2.violation.trace] == \
            [s for s, _ in rd.violation.trace]


class TestCheckpointResume:
    def test_truncate_resume_parity_a2a_d4(self, tmp_path):
        from jaxmc.tpu.mesh import MeshExplorer
        ck = str(tmp_path / "mesh.ck")
        r1 = MeshExplorer(load("pcal_intro_buggy"), mesh=mesh4(),
                          exchange="a2a", max_states=20,
                          checkpoint_path=ck,
                          checkpoint_every=0).run()
        assert r1.truncated and os.path.exists(ck)
        r2 = MeshExplorer(load("pcal_intro_buggy"), mesh=mesh4(),
                          exchange="a2a", resume_from=ck).run()
        rd = MeshExplorer(load("pcal_intro_buggy"), mesh=mesh4(),
                          exchange="a2a").run()
        assert (r2.ok, r2.violation.kind) == (rd.ok, rd.violation.kind)
        assert [s for s, _ in r2.violation.trace] == \
            [s for s, _ in rd.violation.trace]

    @pytest.mark.chaos
    @pytest.mark.slow
    def test_kill_resume_parity_a2a_d4(self, tmp_path):
        # SIGKILL the run mid-search (run_kill fault at the mesh
        # engine's level boundary), resume from its checkpoint, and
        # require bit-identical totals + trace vs an uninterrupted run
        from jaxmc import faults
        from jaxmc.tpu.mesh import MeshExplorer
        ck = str(tmp_path / "mesh_kill.ck")
        code = f"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {REPO!r})
from jaxmc.front.cfg import ModelConfig
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.tpu.mesh import MeshExplorer
m = bind_model(Loader([{SPECS!r}]).load_path(
    os.path.join({SPECS!r}, "pcal_intro_buggy.tla")),
    ModelConfig(specification="Spec"))
MeshExplorer(m, exchange="a2a", checkpoint_path={ck!r},
             checkpoint_every=0).run()
"""
        env = dict(os.environ, PYTHONPATH=REPO,
                   JAXMC_FAULTS="run_kill:level=3:engine=mesh",
                   JAXMC_PROFILE_STORE=str(tmp_path / "prof"))
        env.pop("JAXMC_FAULTS_STATE", None)
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=600)
        assert p.returncode == -9, (p.returncode, p.stderr[-500:])
        assert os.path.exists(ck), "no checkpoint before the kill"
        faults.reset_for_tests()
        r2 = MeshExplorer(load("pcal_intro_buggy"), mesh=mesh4(),
                          exchange="a2a", resume_from=ck).run()
        rd = MeshExplorer(load("pcal_intro_buggy"), mesh=mesh4(),
                          exchange="a2a").run()
        assert (r2.ok, r2.violation.kind, r2.generated, r2.distinct) \
            == (rd.ok, rd.violation.kind, rd.generated, rd.distinct)
        assert [s for s, _ in r2.violation.trace] == \
            [s for s, _ in rd.violation.trace]


class TestForcedSpill:
    def test_skew_routes_everything_to_shard_zero(self, monkeypatch):
        from jaxmc import faults
        monkeypatch.setenv("JAXMC_FAULTS", "mesh_skew")
        faults.reset_for_tests()
        from jaxmc.tpu.mesh import MeshExplorer
        from jaxmc.engine.explore import Explorer
        ri = Explorer(load("constoy")).run()
        me = MeshExplorer(load("constoy"), exchange="a2a")
        assert me._skew
        keys = np.arange(40, dtype=np.int32).reshape(8, 5)
        assert (me._owner_from_keys(keys) == 0).all()
        r = me.run()
        assert (r.generated, r.distinct, r.ok) == \
            (ri.generated, ri.distinct, ri.ok)
        faults.reset_for_tests()

    def test_forced_spill_parity(self, monkeypatch):
        # two passes: measure the peak per-destination bucket under
        # skew, then pin FC and size gamma so the peak level lands in
        # the SPILL window (B < need <= B+SB) — the spill pass must
        # drain it with counts and trace bit-identical to the
        # spill-free skewed run
        from jaxmc import faults, obs
        from jaxmc.tpu.mesh import MeshExplorer
        monkeypatch.setenv("JAXMC_FAULTS", "mesh_skew:n=3")
        faults.reset_for_tests()
        tel = obs.Telemetry()
        with obs.use(tel):
            m1 = MeshExplorer(load("pcal_intro_buggy"), exchange="a2a")
            assert m1._skew
            r1 = m1.run()
        assert m1._spill_rows == 0  # generous gamma: no spill yet
        lv = [(r["max_bucket"], r["fc"]) for r in tel.levels
              if r.get("max_bucket")]
        fcmax = max(fc for _, fc in lv)
        mb = max(v for v, _ in lv)
        D, A = m1.D, m1.A
        m2 = MeshExplorer(load("pcal_intro_buggy"), exchange="a2a",
                          mesh_caps={"SC": 1 << 15, "FC": fcmax,
                                     "TRL": 16, "GAM16": 1})
        assert m2._skew
        m2._a2a_gamma = (mb - 1) * D / (A * fcmax)
        r2 = m2.run()
        assert m2._spill_rows > 0, "spill pass never drained a row"
        assert (r2.ok, r2.violation.kind) == (r1.ok, r1.violation.kind)
        assert [s for s, _ in r2.violation.trace] == \
            [s for s, _ in r1.violation.trace]
        faults.reset_for_tests()


class TestEdgeStream:
    def test_gather_edge_stream_covers_foreign_owned_rows(self):
        # regression (review r8): the legacy gather step's host-side
        # edge stream is read from DEVICE 0 ONLY — its explore mask
        # must cover every valid exchanged candidate, not just the
        # rows device 0 happens to own (recomputing validity from the
        # ownership-masked keys dropped ~(D-1)/D of the edges, which
        # would silently skip refinement/liveness checks on them)
        import time as _t
        import jax.numpy as jnp
        from jaxmc.tpu.mesh import MeshExplorer
        me = MeshExplorer(load("viewtoy_scaled"), exchange="gather")
        me.collect_edges = True   # forces the edge-stream outputs
        init_rows, explored, n_init, err = me._prepare_init(
            _t.time(), [])
        assert err is None
        D, SC, FC = me.D, 256, 64
        seen, frontier, fcount, scount = me._init_shards(
            init_rows, explored, D, SC, FC)
        step = me._get_mesh_step(SC, FC)
        outs = step(jnp.asarray(seen), jnp.asarray(scount),
                    jnp.asarray(frontier), jnp.asarray(fcount))
        tot_gen = int(np.asarray(outs[5])[0])
        assert tot_gen > me.D  # wide enough to spread over shards
        eexp0 = np.asarray(outs[19][0])
        assert int(eexp0.sum()) == tot_gen


class TestMeshbenchChild:
    def test_child_leg_constoy_d2(self, tmp_path):
        out = str(tmp_path / "leg.json")
        env = dict(os.environ, PYTHONPATH=REPO,
                   JAXMC_PROFILE_STORE=str(tmp_path / "prof"))
        p = subprocess.run(
            [sys.executable, "-m", "jaxmc.meshbench", "child",
             "--spec", "specs/constoy.tla", "--devices", "2",
             "--timed", "--metrics-out", out],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=600)
        assert p.returncode == 0, p.stderr[-800:]
        line = [l for l in p.stdout.splitlines()
                if l.startswith("MESHBENCH_RESULT ")][0]
        r = json.loads(line[len("MESHBENCH_RESULT "):])
        assert r["ok"] and r["devices"] == 2
        assert (r["generated"], r["distinct"]) == (43, 21)
        assert r["window_recompiles"] == 0       # warm timed window
        # scalar-ring reads only: one per superstep, never more than
        # the level count — and the warm window (learned MSL) must
        # actually fuse levels
        assert r["supersteps"] == r["host_syncs"] <= r["levels"]
        assert r["host_syncs"] < r["levels"]
        assert r["exchange"] == "a2a"
        assert r["merge"] == "rank"
        art = json.load(open(out))
        assert art["schema"] == "jaxmc.metrics/4"
        assert art["multichip"]["devices"] == 2
        assert art["multichip"]["merge"] == "rank"
        assert art["multichip"]["supersteps"] == r["supersteps"]
