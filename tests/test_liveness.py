r"""Behavior-graph liveness checking (engine/liveness.py).

Targets the corpus's temporal-property obligations (VERDICT round-1
Missing #1): the Liveness-chapter properties, MCAlternatingBit's leads-to,
RealTime's expected-to-fail property, and MCInnerSerial's AlwaysResponds —
each with a fairness-free negative control proving the checks are not
vacuous.
"""

import os

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer

from conftest import REFERENCE, needs_reference

# every test here loads reference-corpus specs (driver env only)
pytestmark = [needs_reference]

SS = os.path.join(REFERENCE, "examples/SpecifyingSystems")


def run(spec_path, cfg_text=None, cfg_path=None):
    cfg = parse_cfg(cfg_text if cfg_text is not None
                    else open(cfg_path).read())
    m = Loader([os.path.dirname(spec_path)]).load_path(spec_path)
    return Explorer(bind_model(m, cfg)).run()


class TestLiveHourClock:
    SPEC = os.path.join(SS, "Liveness/LiveHourClock.tla")

    def test_all_properties_hold_under_fairness(self):
        # PROPERTIES AlwaysTick AllTimes TypeInvariance
        # (LiveHourClock.cfg) — []<><<A>>_v, \A-quantified []<>, and []P
        r = run(self.SPEC, cfg_path=os.path.join(
            SS, "Liveness/LiveHourClock.cfg"))
        assert r.ok
        assert not any("NOT checked" in w for w in r.warnings)

    def test_alwaystick_violated_without_fairness(self):
        # HC alone permits infinite stuttering: []<><<HCnxt>>_hr fails
        r = run(self.SPEC, "SPECIFICATION HC\nPROPERTIES AlwaysTick\n")
        assert not r.ok
        assert r.violation.kind == "property"
        assert "AlwaysTick" in r.violation.name

    def test_alltimes_violated_without_fairness(self):
        r = run(self.SPEC, "SPECIFICATION HC\nPROPERTIES AllTimes\n")
        assert not r.ok
        assert "AllTimes" in r.violation.name


class TestAlternatingBit:
    SPEC = os.path.join(SS, "TLC/MCAlternatingBit.tla")
    NOFAIR = """INIT ABInit
NEXT ABNext
CONSTANTS
  Data = {d1, d2}
  msgQLen = 2
  ackQLen = 2
CONSTRAINT SeqConstraint
PROPERTIES SentLeadsToRcvd
CHECK_DEADLOCK FALSE
"""

    def test_sent_leadsto_rcvd_holds_under_wf_sf(self):
        # ABSpec's fairness is WF(ReSndMsg) /\ WF(SndAck) /\ SF(RcvMsg)
        # /\ SF(RcvAck) (AlternatingBit.tla:72-75) — the ~> property needs
        # all of it
        r = run(self.SPEC, cfg_path=os.path.join(
            SS, "TLC/MCAlternatingBit.cfg"))
        assert r.ok
        assert not any("SentLeadsToRcvd" in w for w in r.warnings)

    def test_violated_without_fairness(self):
        r = run(self.SPEC, self.NOFAIR)
        assert not r.ok
        assert "SentLeadsToRcvd" in r.violation.name


class TestRealTimeHourClock:
    def test_error_temporal_found_violated(self):
        # the cfg's PROPERTY ErrorTemporal ([]((now # 4) => <>[](now # 4)),
        # MCRealTimeHourClock.tla:43) is expected to FAIL — finding the
        # violation is the pass criterion
        r = run(os.path.join(SS, "RealTime/MCRealTimeHourClock.tla"),
                cfg_path=os.path.join(SS,
                                      "RealTime/MCRealTimeHourClock.cfg"))
        assert not r.ok
        assert r.violation.kind == "property"
        assert "ErrorTemporal" in r.violation.name
        assert r.distinct == 216 and r.generated == 696


class TestInnerSerial:
    SPEC = os.path.join(SS, "AdvancedExamples/MCInnerSerial.tla")
    NOFAIR = """INIT Init
NEXT Next
CONSTANTS
  Reg = {r1}
  Adr = {a1}
  Val = {v1, v2}
  Proc = {p1, p2}
  InitMem <- MCInitMem
  InitWr = InitWr
  Done = Done
  MaxQLen = 1
  Nat <- MCNat
CONSTRAINT Constraint
PROPERTY AlwaysResponds
CHECK_DEADLOCK FALSE
"""

    def test_always_responds_violated_without_fairness(self):
        # the quantified ~> property needs InnerSerial's WF conjuncts
        # (InnerSerial.tla:109-119); without them a pending request can
        # stutter forever. (The fairness-ful positive run is the golden
        # testout2 model — covered by test_innerserial_matches_golden_
        # testout2, which now also checks AlwaysResponds.)
        r = run(self.SPEC, self.NOFAIR)
        assert not r.ok
        assert "AlwaysResponds" in r.violation.name


class TestFairnessAsProperty:
    """PROPERTY formulas that are themselves fairness/liveness formulas
    (VERDICT r2 #3): MCLiveInternalMemory.cfg:4-7 checks `Liveness`
    (\\A p : WF_vars(Do(p)) /\\ WF_vars(Rsp(p))) as a property, and
    MCLiveWriteThroughCache.cfg:4-10 checks LM_Inner_LISpec (a full fair
    spec whose Init/[][Next]_v half the refinement checker covers) and
    LM_Inner_Liveness (the hand-instantiated []<>~Enabled \\/ []<><<A>>_v
    construction, MCLiveWriteThroughCache.tla:129-143). All must check
    with ZERO 'NOT checked' warnings, and be found violated when the
    specification's own fairness is dropped."""

    LIM = os.path.join(SS, "Liveness/MCLiveInternalMemory.tla")
    WTC = os.path.join(SS, "Liveness/MCLiveWriteThroughCache.tla")
    LIM_CONSTS = """CONSTANTS
  Send  <- MCSend
  Reply <- MCReply
  InitMemInt <- MCInitMemInt
  Proc = {p1, p2}
  Adr = {a1}
  Val = {v1, v2}
  NoVal = NoVal
"""
    WTC_CONSTS = LIM_CONSTS + "  QLen = 1\n"

    def test_mclive_internal_memory_zero_warnings(self):
        # PROPERTY LivenessProperty (~>) + PROPERTY Liveness (WF atoms):
        # both fully checked under LISpec's fairness
        r = run(self.LIM, cfg_path=os.path.join(
            SS, "Liveness/MCLiveInternalMemory.cfg"))
        assert r.ok
        assert (r.distinct, r.generated) == (4408, 21400)
        assert not any("NOT checked" in w for w in r.warnings), r.warnings

    def test_mclive_wtc_zero_warnings(self):
        # PROPERTY LM_Inner_LISpec (refinement half stepwise + fairness
        # half over the behavior graph) + PROPERTY LM_Inner_Liveness
        r = run(self.WTC, cfg_path=os.path.join(
            SS, "Liveness/MCLiveWriteThroughCache.cfg"))
        assert r.ok
        assert (r.distinct, r.generated) == (5196, 28170)
        assert not any("NOT checked" in w for w in r.warnings), r.warnings

    def test_liveness_property_violated_without_fairness(self):
        # negative control: under ISpec (no fairness) a busy processor
        # may stutter forever — WF_vars(Do(p)) fails as a property
        r = run(self.LIM, "SPECIFICATION ISpec\nPROPERTY Liveness\n"
                + self.LIM_CONSTS + "CHECK_DEADLOCK FALSE\n")
        assert not r.ok
        assert r.violation.kind == "property"
        assert "Liveness" in r.violation.name

    def test_lm_inner_liveness_violated_without_fairness(self):
        r = run(self.WTC, "SPECIFICATION Spec\nPROPERTY LM_Inner_Liveness\n"
                + self.WTC_CONSTS + "CHECK_DEADLOCK FALSE\n")
        assert not r.ok
        assert "LM_Inner_Liveness" in r.violation.name

    def test_lm_inner_lispec_fairness_half_violated_without_fairness(self):
        # the spec-shaped property: its refinement half still holds under
        # the unfair spec, so the violation MUST come from the fairness
        # half (the Liveness2 disjunction)
        r = run(self.WTC, "SPECIFICATION Spec\nPROPERTY LM_Inner_LISpec\n"
                + self.WTC_CONSTS + "CHECK_DEADLOCK FALSE\n")
        assert not r.ok
        assert "LM_Inner_LISpec" in r.violation.name
        assert not any("NOT checked" in w for w in r.warnings), r.warnings


class TestDeviceLiveness:
    """The jax backend streams the behavior graph (kept states, edges,
    parents, labels) to the host and runs the SAME LivenessChecker the
    interp uses — verdict parity on every corpus liveness model the
    kernel compiler accepts (tpu/bfs.py _LiveGraph/_check_live)."""

    def run_jax(self, spec_path, cfg_text=None, cfg_path=None, **kw):
        from jaxmc.tpu.bfs import TpuExplorer
        cfg = parse_cfg(cfg_text if cfg_text is not None
                        else open(cfg_path).read())
        m = Loader([os.path.dirname(spec_path)]).load_path(spec_path)
        return TpuExplorer(bind_model(m, cfg), **kw).run()

    def test_livehourclock_properties_hold(self):
        r = self.run_jax(TestLiveHourClock.SPEC, cfg_path=os.path.join(
            SS, "Liveness/LiveHourClock.cfg"))
        assert r.ok
        assert not any("NOT checked" in w for w in r.warnings)

    def test_alwaystick_violated_without_fairness(self):
        r = self.run_jax(TestLiveHourClock.SPEC,
                         "SPECIFICATION HC\nPROPERTIES AlwaysTick\n")
        assert not r.ok
        assert r.violation.kind == "property"
        assert "AlwaysTick" in r.violation.name

    def test_sent_leadsto_rcvd_device_negative(self):
        # fairness-free: the device-built behavior graph must expose the
        # stuttering lasso inside ~Rcvd (proves edges/graph are real)
        r = self.run_jax(os.path.join(SS, "TLC/MCAlternatingBit.tla"),
                         TestAlternatingBit.NOFAIR)
        assert not r.ok
        assert "SentLeadsToRcvd" in r.violation.name

    def test_sent_leadsto_rcvd_device_host_seen(self):
        # same verdicts through the chunked native-store path (its edge
        # accumulation is per-chunk with level-deferred resolution)
        from jaxmc import native_store
        import pytest
        if not native_store.is_available():
            pytest.skip("no native toolchain")
        spec = os.path.join(SS, "TLC/MCAlternatingBit.tla")
        r = self.run_jax(spec, cfg_path=os.path.join(
            SS, "TLC/MCAlternatingBit.cfg"), host_seen=True, chunk=64)
        assert r.ok and r.distinct == 240
        r2 = self.run_jax(spec, TestAlternatingBit.NOFAIR,
                          host_seen=True, chunk=64)
        assert not r2.ok
        assert "SentLeadsToRcvd" in r2.violation.name

    def test_always_only_property_no_edge_log(self):
        # '[]P'-only properties need states but no edge log
        # (collect_edges=False): the device-seen step emits no cand
        # tensor on this path — regression for a KeyError
        r = self.run_jax(TestLiveHourClock.SPEC,
                         "SPECIFICATION HC\nPROPERTIES TypeInvariance\n")
        assert r.ok and r.distinct == 12

    def test_truncated_run_warns(self):
        r = self.run_jax(TestLiveHourClock.SPEC, cfg_path=os.path.join(
            SS, "Liveness/LiveHourClock.cfg"), max_states=3)
        assert r.truncated
        assert any("truncated" in w for w in r.warnings)


class TestCheckpointedLiveness:
    def test_resume_preserves_edge_log(self, tmp_path):
        # liveness after --resume must see pre-checkpoint edges: the
        # fairness-free SentLeadsToRcvd violation must still be found
        # when the search ran in two halves
        spec = os.path.join(SS, "TLC/MCAlternatingBit.tla")
        cfg_text = TestAlternatingBit.NOFAIR
        ckpt = str(tmp_path / "ab.ckpt")
        m1 = Loader([os.path.dirname(spec)]).load_path(spec)
        r1 = Explorer(bind_model(m1, parse_cfg(cfg_text)), max_states=50,
                      checkpoint_path=ckpt, checkpoint_every=0.0).run()
        assert r1.truncated
        m2 = Loader([os.path.dirname(spec)]).load_path(spec)
        r2 = Explorer(bind_model(m2, parse_cfg(cfg_text)),
                      resume_from=ckpt).run()
        assert not r2.ok
        assert "SentLeadsToRcvd" in r2.violation.name
