r"""Bit-packed state lane tests (ISSUE 6, compile/pack.py).

Three layers:
  1. LanePlan round-trip property tests per value shape — every vspec
     kind (seq zero-padding, growset/kvtable SENTINEL padding, union
     overlays, pfcn present/absent) must pack/unpack to the identical
     lane row, host (numpy) and device (jnp) paths agreeing.
  2. Injectivity: distinct lane rows pack to distinct packed rows
     (packed equality == state equality — the exact-dedup guarantee).
  3. Whole-engine parity on the repo-local fixtures: packed and
     unpacked (JAXMC_PACK=0) layouts must produce bit-identical
     generated/distinct counts — and identical counterexample TRACES —
     against the exact interpreter, across the level, resident and
     host_seen device modes.
"""

import os

import numpy as np
import pytest

from conftest import REFERENCE  # noqa: F401  (path side effects)

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer
from jaxmc.engine.simulate import sample_states
from jaxmc.compile.kernel2 import build_layout2
from jaxmc.compile.pack import build_lane_plan, packing_enabled
from jaxmc.compile.vspec import Bounds, SENTINEL_LANE

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")

FIXTURES = {
    "viewtoy": ("viewtoy.tla", "viewtoy.cfg", False),
    "symtoy": ("symtoy.tla", "symtoy.cfg", True),
    "constoy": ("constoy.tla", "constoy.cfg", False),
    "interparm_toy": ("interparm_toy.tla", "interparm_toy.cfg", False),
}


def load(name):
    spec, cfg, no_dl = FIXTURES[name]
    m = bind_model(Loader([SPECS]).load_path(os.path.join(SPECS, spec)),
                   parse_cfg(open(os.path.join(SPECS, cfg)).read()))
    if no_dl:
        m.check_deadlock = False
    return m


def layout_and_rows(name, bfs=300, walks=20, depth=30):
    m = load(name)
    sampled = list(sample_states(m, bfs_states=bfs, n_walks=walks,
                                 walk_depth=depth))
    lay = build_layout2(m, sampled, Bounds())
    rows = np.stack([lay.encode(st) for st in sampled])
    return m, lay, rows


# ---------------------------------------------------------------- layer 1

@pytest.mark.parametrize("name", sorted(FIXTURES))
def test_roundtrip_fixture_layouts(name):
    _m, lay, rows = layout_and_rows(name)
    plan = lay.plan
    back = plan.unpack_np(plan.pack_np(rows))
    assert (back == rows).all(), f"{name}: np pack/unpack not inverse"
    # device path agrees with the host path bit for bit
    import jax
    import jax.numpy as jnp
    pk, ovf = jax.jit(plan.pack_rows)(jnp.asarray(rows))
    assert not bool(np.asarray(ovf).any())
    assert (np.asarray(pk) == plan.pack_np(rows)).all()
    assert (np.asarray(jax.jit(plan.unpack_rows)(pk)) == rows).all()


def test_roundtrip_container_shapes():
    """One synthetic layout covering the shape zoo: seq (zero-padded
    tails), growset + kvtable (SENTINEL-padded slots), pfcn
    (present/absent), union (overlaid payloads), set membership."""
    from jaxmc.sem.values import Fcn, mk_seq
    from jaxmc.compile.vspec import (EnumUniverse, apply_bounds, infer,
                                     merge, encode as vs_encode)
    uni = EnumUniverse()
    vals = [
        mk_seq(["a", "b"]),                     # seq of enums, len 2
        mk_seq([]),                             # zero-padded empty seq
        frozenset({1, 5}),                      # growset of ints
        frozenset(),                            # empty -> all-sentinel
        Fcn({"k": 3}),                          # record variant 1
        Fcn({"t": True, "u": 0}),               # record variant 2
    ]
    specs = []
    for group in ((vals[0], vals[1]), (vals[2], vals[3]),
                  (vals[4], vals[5])):
        sp = None
        for v in group:
            s = infer(v, uni)
            sp = s if sp is None else merge(sp, s)
        specs.append(apply_bounds(sp, Bounds()))

    class FakeLayout:
        vars = ("s", "g", "u")
        width = sum(s.width for s in specs)
        uni2 = uni

        def __init__(self):
            self.specs = dict(zip(self.vars, specs))
            self.uni = uni

    lay = FakeLayout()
    rows = []
    for s, g, u in [(vals[0], vals[2], vals[4]),
                    (vals[1], vals[3], vals[5]),
                    (vals[0], vals[3], vals[5]),
                    (vals[1], vals[2], vals[4])]:
        out = []
        vs_encode(s, specs[0], uni, out)
        vs_encode(g, specs[1], uni, out)
        vs_encode(u, specs[2], uni, out)
        rows.append(np.asarray(out, np.int32))
    rows = np.stack(rows)
    assert (rows == SENTINEL_LANE).any(), "fixture must exercise padding"
    plan = build_lane_plan(lay, list(rows))
    assert not plan.identity, "the shape zoo must actually pack"
    assert plan.packed_width < lay.width
    back = plan.unpack_np(plan.pack_np(rows))
    assert (back == rows).all()


def test_packing_is_injective():
    _m, lay, rows = layout_and_rows("symtoy")
    uniq = np.unique(rows, axis=0)
    packed = lay.plan.pack_np(uniq)
    assert len(np.unique(packed, axis=0)) == len(uniq), \
        "two distinct lane rows packed to the same row"


def test_identity_plan_under_env(monkeypatch):
    monkeypatch.setenv("JAXMC_PACK", "0")
    assert not packing_enabled()
    _m, lay, rows = layout_and_rows("constoy")
    assert lay.plan.identity
    assert lay.plan.packed_width == lay.width
    assert (lay.plan.pack_np(rows) == rows).all()


def test_pack_overflow_guard_raises():
    _m, lay, rows = layout_and_rows("constoy")
    plan = lay.plan
    guarded = np.nonzero(plan.guarded)[0]
    if not len(guarded):
        pytest.skip("no guarded lanes in this layout")
    from jaxmc.compile.vspec import CompileError
    bad = rows[:1].copy()
    i = int(guarded[0])
    bad[0, i] = int(plan.bias[i] + plan.allowed[i] + 1)
    with pytest.raises(CompileError, match="packed lane"):
        plan.pack_np(bad)
    # the device path reports, never raises (engines route to OV_PACK)
    import jax.numpy as jnp
    _pk, ovf = plan.pack_rows(jnp.asarray(bad))
    assert bool(np.asarray(ovf)[0])


# ---------------------------------------------------------------- layer 3

def _device_counts(name, mode, env):
    from jaxmc.tpu.bfs import TpuExplorer
    kw = dict(store_trace=mode != "resident")
    if mode == "resident":
        kw["resident"] = True
        kw["cap_profile"] = False
    elif mode == "host_seen":
        kw["host_seen"] = True
    for k, v in env.items():
        os.environ[k] = v
    try:
        ex = TpuExplorer(load(name), **kw)
        r = ex.run()
    finally:
        for k in env:
            os.environ.pop(k, None)
    return r


@pytest.mark.parametrize("name", sorted(FIXTURES))
@pytest.mark.parametrize("mode", ["level", "resident", "host_seen"])
def test_packed_vs_unpacked_vs_interp_counts(name, mode):
    from jaxmc import native_store
    from jaxmc.compile.vspec import ModeError
    if mode == "host_seen" and not native_store.is_available():
        pytest.skip("host_seen needs the native store")
    ri = Explorer(load(name)).run()
    try:
        rp = _device_counts(name, mode, {})
        ru = _device_counts(name, mode, {"JAXMC_PACK": "0"})
    except ModeError as e:
        if name == "interparm_toy" and mode in ("level", "resident"):
            pytest.skip(f"hybrid model needs host_seen: {e}")
        raise
    for tag, r in (("packed", rp), ("unpacked", ru)):
        assert (r.generated, r.distinct, r.ok) == \
            (ri.generated, ri.distinct, ri.ok), \
            (f"{name}/{mode}/{tag}: {r.generated}/{r.distinct}/{r.ok} "
             f"vs interp {ri.generated}/{ri.distinct}/{ri.ok}")


def _trace_states(violation):
    return [st for st, _lbl in violation.trace]


def test_trace_parity_packed_vs_unpacked_vs_interp():
    """Counterexample TRACES agree: pcal_intro_buggy's assert violation
    (repo-local, jax='yes' in the manifest).  Packed and unpacked
    device layouts must produce the IDENTICAL trace (bit-identical
    dedup partition); against the interpreter the trace must be an
    equally-short counterexample with identical counts (the two engines
    legitimately tie-break equal-depth candidates differently — a
    pre-existing, disclosed difference independent of packing)."""
    from jaxmc.tpu.bfs import TpuExplorer
    spec = os.path.join(SPECS, "pcal_intro_buggy.tla")
    from jaxmc.front.cfg import ModelConfig

    def mk():
        m = Loader([SPECS]).load_path(spec)
        return bind_model(m, ModelConfig(specification="Spec"))

    ri = Explorer(mk()).run()
    assert not ri.ok and ri.violation.kind == "assert"
    runs = {}
    for tag, env in (("packed", {}), ("unpacked", {"JAXMC_PACK": "0"})):
        for k, v in env.items():
            os.environ[k] = v
        try:
            r = TpuExplorer(mk(), store_trace=True).run()
        finally:
            for k in env:
                os.environ.pop(k, None)
        assert not r.ok and r.violation.kind == "assert"
        runs[tag] = r
    assert _trace_states(runs["packed"].violation) == \
        _trace_states(runs["unpacked"].violation), \
        "packing changed the counterexample"
    assert len(_trace_states(runs["packed"].violation)) == \
        len(_trace_states(ri.violation)), \
        "device trace is not an equally-short counterexample"
    # counts at a violation abort reflect engine-specific partial-level
    # progress (the interp stops mid-enumeration, the device finishes
    # its batch) — only packed-vs-unpacked equality is meaningful here
    assert (runs["packed"].generated, runs["packed"].distinct) == \
        (runs["unpacked"].generated, runs["unpacked"].distinct)


def test_symmetry_composes_with_view(tmp_path):
    """SYMMETRY + VIEW together: the view must evaluate over the
    orbit's CANONICAL representative (the interp's state_fingerprint
    order), or symmetric states count as distinct — the review repro
    that caught the original view-of-raw-row keying."""
    from jaxmc.tpu.bfs import TpuExplorer
    spec = tmp_path / "symview.tla"
    spec.write_text("""---- MODULE symview ----
EXTENDS Naturals, FiniteSets, TLC
CONSTANTS P, None
VARIABLES owner, cnt
Perms == Permutations(P)
Init == owner = None /\\ cnt = 0
Grab == \\E p \\in P : owner = None /\\ owner' = p /\\ cnt' = (cnt + 1) % 3
Drop == owner /= None /\\ owner' = None /\\ cnt' = cnt
Next == Grab \\/ Drop
Spec == Init /\\ [][Next]_<<owner, cnt>>
V == <<owner, cnt>>
====
""")
    cfg = parse_cfg("SPECIFICATION Spec\nCONSTANTS\n  P = {p1, p2}\n"
                    "  None = None\nSYMMETRY Perms\nVIEW V\n"
                    "CHECK_DEADLOCK FALSE\n")

    def mk():
        return bind_model(Loader([str(tmp_path)]).load_path(str(spec)),
                          cfg)

    ri = Explorer(mk()).run()
    ex = TpuExplorer(mk(), store_trace=True)
    assert ex.canon_fn is not None and ex.view_fn is not None
    r = ex.run()
    assert (r.generated, r.distinct, r.ok) == \
        (ri.generated, ri.distinct, ri.ok), \
        (f"SYMMETRY+VIEW diverged: device {r.generated}/{r.distinct} "
         f"vs interp {ri.generated}/{ri.distinct}")


@pytest.mark.parametrize("exchange", ["gather", "a2a"])
def test_mesh_packed_rows_survive_sharded_path(exchange):
    """Packed rows survive the mesh path (ISSUE 6): the sharded engine
    exchanges PACKED candidate rows (a2a payloads shrink to K+PW+1
    words) and still produces interp-identical counts — repo-local, so
    the sharded path stays covered without the reference tree."""
    from jaxmc.tpu.mesh import MeshExplorer
    ri = Explorer(load("constoy")).run()
    me = MeshExplorer(load("constoy"), exchange=exchange,
                      store_trace=True)
    assert me.PW < me.W, "constoy must actually pack"
    r = me.run()
    assert (r.generated, r.distinct, r.ok) == \
        (ri.generated, ri.distinct, ri.ok)


def test_symtoy_trace_parity_on_violation():
    """symtoy's deadlock-with-checking-on violation: packed and
    unpacked device traces match the interpreter's (SYMMETRY canonical
    keys, original stored rows)."""
    from jaxmc.tpu.bfs import TpuExplorer

    def mk():
        m = bind_model(
            Loader([SPECS]).load_path(os.path.join(SPECS, "symtoy.tla")),
            parse_cfg(open(os.path.join(SPECS, "symtoy.cfg")).read()))
        return m  # deadlock checking ON: the model deadlocks

    ri = Explorer(mk()).run()
    assert not ri.ok and ri.violation.kind == "deadlock"
    for env in ({}, {"JAXMC_PACK": "0"}):
        for k, v in env.items():
            os.environ[k] = v
        try:
            r = TpuExplorer(mk(), store_trace=True).run()
        finally:
            for k in env:
                os.environ.pop(k, None)
        assert not r.ok and r.violation.kind == "deadlock"
        assert _trace_states(r.violation) == _trace_states(ri.violation)
        assert (r.generated, r.distinct) == (ri.generated, ri.distinct)
