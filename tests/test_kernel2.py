r"""Differential tests: compiled kernels vs the exact interpreter.

For sampled states, the set of successors produced by the compiled action
kernels (decoded back to values) must equal the interpreter's successor set
— the per-transition equivalence underlying the whole-run count equality
(BASELINE.json).
"""

import os

import numpy as np
import pytest

from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.sem.enumerate import enumerate_init, enumerate_next
from jaxmc.engine.explore import Explorer

from conftest import REFERENCE, needs_reference

SPECS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "specs")


def state_key(st, vars):
    return tuple(repr(st[v]) for v in vars)


def kernel_successors(ex, st):
    """Successor states via the compiled kernels for one concrete state
    (slotted kernels evaluated per slot index; kernels jitted once,
    cached on the action object so recycled ids cannot alias)."""
    import jax
    row = ex.layout.encode(st)
    out = set()
    overflow = False
    for ca in ex.compiled:
        jf = getattr(ca, "_jitted", None)
        if jf is None:
            jf = jax.jit(ca.fn)
            ca._jitted = jf
        slots = range(ca.n_slots) if ca.n_slots else [None]
        for k in slots:
            en, aok, ov, succ = (jf(row, k) if k is not None else jf(row))
            if bool(ov):
                overflow = True
            if bool(en):
                dec = ex.layout.decode(np.asarray(succ))
                out.add(state_key(dec, ex.layout.vars))
    return out, overflow


def interp_successors(model, st):
    ctx = model.ctx()
    out = set()
    for succ, _ in enumerate_next(model.next, ctx, model.vars, st):
        out.add(state_key(succ, model.vars))
    return out


@pytest.mark.parametrize("specrel,cfgrel", [
    ("specs/transfer_scaled.tla", "specs/transfer_scaled.cfg"),
])
def test_kernel_matches_interp_transfer(specrel, cfgrel):
    from jaxmc.tpu.bfs import TpuExplorer
    root = os.path.dirname(SPECS)
    model = bind_model(
        Loader([]).load_path(os.path.join(root, specrel)),
        parse_cfg(open(os.path.join(root, cfgrel)).read()))
    ex = TpuExplorer(model, store_trace=False)
    ctx = model.ctx()
    states = enumerate_init(model.init, ctx, model.vars)[:6]
    # a couple of deeper states too
    for st in list(states[:2]):
        for succ, _ in enumerate_next(model.next, ctx, model.vars, st):
            states.append(succ)
            break
    for st in states:
        ks, ov = kernel_successors(ex, st)
        assert not ov
        assert ks == interp_successors(model, st)


@pytest.mark.slow
def test_kernel_matches_interp_raft_tiny():
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.compile.vspec import Bounds
    root = os.path.dirname(SPECS)
    ldr = Loader([os.path.join(REFERENCE, "examples")])
    model = bind_model(
        ldr.load_path(os.path.join(SPECS, "MCraft.tla")),
        parse_cfg(open(os.path.join(SPECS, "MCraft_tiny.cfg")).read()))
    ex = TpuExplorer(model, store_trace=False,
                     bounds=Bounds(seq_cap=2, grow_cap=16, kv_cap=16),
                     sample_cfg=(300, 60, 80))
    from jaxmc.engine.simulate import sample_states
    states = sample_states(model, bfs_states=40, n_walks=6, walk_depth=30)
    for st in states[:25]:
        ks, ov = kernel_successors(ex, st)
        assert not ov, "capacity overflow on sampled state"
        assert ks == interp_successors(model, st)


def test_nested_dynamic_exists_rejected(tmp_path):
    # two dynamic \E binders would share the one traced slot index and
    # silently explore only diagonal (i == j) pairs — the compiler must
    # reject instead (exactness contract: compile exactly or not at all)
    from jaxmc.compile.ground import CompileError
    from jaxmc.tpu.bfs import TpuExplorer
    spec = tmp_path / "nested_dyn.tla"
    spec.write_text(r"""---- MODULE nested_dyn ----
EXTENDS Naturals, Sequences
VARIABLE q
Init == q = <<1, 2>>
Next == \E i \in 1..Len(q) : \E j \in 1..Len(q) :
          q' = [q EXCEPT ![i] = ((q[j] + 1) % 3)]
====
""")
    model = bind_model(Loader([]).load_path(str(spec)),
                       ModelConfig(init="Init", next="Next",
                                   check_deadlock=False))
    with pytest.raises(CompileError, match="nested dynamic"):
        TpuExplorer(model, store_trace=False)


def test_sibling_dynamic_exists_rejected(tmp_path):
    # /\-conjoined sibling dynamic \E binders also land in one grounded
    # action with distinct $slotv markers — same diagonal-only hazard as
    # the nested form, caught at action-compile time
    from jaxmc.compile.ground import CompileError
    from jaxmc.tpu.bfs import TpuExplorer
    spec = tmp_path / "sibling_dyn.tla"
    spec.write_text(r"""---- MODULE sibling_dyn ----
EXTENDS Naturals, Sequences
VARIABLE q
Init == q = <<1, 2>>
Next == (\E i \in 1..Len(q) : q[i] < 9)
        /\ (\E j \in 1..Len(q) : q' = [q EXCEPT ![j] = ((q[j] + 1) % 3)])
====
""")
    model = bind_model(Loader([]).load_path(str(spec)),
                       ModelConfig(init="Init", next="Next",
                                   check_deadlock=False))
    with pytest.raises(CompileError, match="dynamic"):
        TpuExplorer(model, store_trace=False)


def _load_micro():
    ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
    return bind_model(
        ldr.load_path(os.path.join(SPECS, "MCraftMicro.tla")),
        parse_cfg(open(os.path.join(SPECS, "MCraft_micro.cfg")).read()))


@needs_reference
def test_raft_micro_differential_default():
    # default-selected fast slice of the raft kernel-vs-interp
    # differential (the full sweep on MCraft_tiny is slow-marked above)
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.simulate import sample_states
    model = _load_micro()
    ex = TpuExplorer(model, store_trace=False)
    states = sample_states(model, bfs_states=30, n_walks=4, walk_depth=20)
    assert len(states) >= 12
    for st in states[:12]:
        ks, ov = kernel_successors(ex, st)
        assert not ov, "capacity overflow on sampled state"
        assert ks == interp_successors(model, st)


@needs_reference
def test_raft_micro_whole_run_equivalence():
    # the BASELINE.json contract at a scale that COMPLETES: identical
    # generated/distinct counts from the interpreter and the jax backend
    # on a raft model (MCraftMicro bounds raft.tla's message-bag domain so
    # the search is finite; reference hot path raft.tla:482-493)
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc import native_store
    ri = Explorer(_load_micro()).run()
    assert ri.ok
    assert (ri.generated, ri.distinct) == (6185, 694)
    rj = TpuExplorer(_load_micro(), store_trace=False,
                     host_seen=native_store.is_available(),
                     chunk=256).run()
    assert rj.ok
    assert (rj.generated, rj.distinct) == (6185, 694)


@pytest.mark.slow
def test_raft_3s_bench_whole_run_equivalence():
    # backend count-equivalence on the BENCHMARK model itself (bench.py's
    # workload): ~3.5min interp + ~6min jax on CPU
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc import native_store

    def load_bench():
        ldr = Loader([os.path.join(REFERENCE, "examples"), SPECS])
        return bind_model(
            ldr.load_path(os.path.join(SPECS, "MCraftMicro.tla")),
            parse_cfg(open(os.path.join(SPECS,
                                        "MCraft_3s_bench.cfg")).read()))
    ri = Explorer(load_bench()).run()
    assert ri.ok
    assert (ri.generated, ri.distinct) == (1138651, 76654)
    rj = TpuExplorer(load_bench(), store_trace=False,
                     host_seen=native_store.is_available()).run()
    assert rj.ok
    assert (rj.generated, rj.distinct) == (1138651, 76654)


def test_recursive_operator_demotes_predicate_with_named_reason(tmp_path):
    # ISSUE 5: a diverging RECURSIVE operator used to surface as an
    # anonymous RecursionError; the kernel2 unroll counter now trips
    # first and the demotion reason NAMES the operator. Invariants are
    # strict frames (no guard-demotion recovery), so the predicate must
    # land in fb_invs with that reason — while the non-recursive action
    # arm still compiles.
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc import native_store
    if not native_store.is_available():
        pytest.skip("hybrid (demoted invariant) needs the native store")
    (tmp_path / "rec.tla").write_text(
        "---------------- MODULE rec ----------------\n"
        "EXTENDS Naturals\n"
        "VARIABLES x\n"
        "RECURSIVE Depth(_)\n"
        "Depth(k) == IF k <= 0 THEN 0 ELSE 1 + Depth(k - 1)\n"
        "Init == x = 0\n"
        "Next == x < 4 /\\ x' = x + 1\n"
        "Spec == Init /\\ [][Next]_x\n"
        "RecInv == Depth(x) <= 4\n"
        "=============================================\n")
    cfg = parse_cfg("SPECIFICATION Spec\nINVARIANT RecInv\n"
                    "CHECK_DEADLOCK FALSE\n")
    model = bind_model(
        Loader([str(tmp_path)]).load_path(str(tmp_path / "rec.tla")),
        cfg)
    ex = TpuExplorer(model, store_trace=False,
                     host_seen=native_store.is_available())
    assert not ex.fb_arms, "the plain arm must stay compiled"
    assert len(ex.fb_invs) == 1
    nm, _e, reason = ex.fb_invs[0]
    assert nm == "RecInv"
    assert "recursive operator Depth exceeds the compile-time unroll " \
           "limit" in reason
    # and the hybrid run still produces exact counts
    r = ex.run()
    assert r.ok and (r.generated, r.distinct) == (5, 5)
