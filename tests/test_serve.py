r"""jaxmc.serve: the checking-as-a-service daemon (ISSUE 7).

Covers the acceptance surface end to end:
  - submit/poll/result round-trip over a REAL socket (the daemon's own
    HTTP listener, in-process for speed);
  - durable spool: a daemon started over a non-empty on-disk queue
    answers every job; identical queued jobs BATCH through one run;
  - warm second submission: same daemon, identical job — the warm
    session resumes the first job's FINAL checkpoint with
    window_recompiles == 0 and a capacity-profile hit (the jax resident
    scenario is the acceptance criterion verbatim);
  - daemon restart: the signature-keyed checkpoint + persistent compile
    cache + capacity profile make the next life's identical job a
    resume with nonzero persistent-cache hits;
  - SIGTERM drain (real subprocess): the in-flight job checkpoints and
    parks, queued jobs survive, no orphan workers, no open spans in the
    trace, and the next daemon life re-answers everything from
    checkpoints — no job lost.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jaxmc import drain
from jaxmc.engine.explore import Explorer
from jaxmc.serve import JobQueue, ServeDaemon
from jaxmc.serve.protocol import (ServeClient, build_config,
                                  job_signature)
from jaxmc.session import load_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "specs")


def spec(name):
    return os.path.join(SPECS, f"{name}.tla")


_EXPECT = {}


def expect(name, max_states=None):
    """Reference counts from the serial engine (cached per suite)."""
    key = (name, max_states)
    if key not in _EXPECT:
        _EXPECT[key] = Explorer(load_model(spec(name), None, False),
                                max_states=max_states).run()
    return _EXPECT[key]


@pytest.fixture(autouse=True)
def _clean_drain():
    drain.clear()
    yield
    drain.clear()


@pytest.fixture()
def spool(tmp_path):
    return str(tmp_path / "spool")


@pytest.fixture()
def daemon(spool):
    d = ServeDaemon(spool, workers=1, quiet=True).start()
    yield d
    d.shutdown()


def client(d):
    return ServeClient("127.0.0.1", d.port)


JAX_OPTS = {"backend": "jax", "platform": "cpu", "resident": True,
            "no_trace": True}


def start_subprocess_daemon(spool, trace=None, extra_env=None):
    """A REAL daemon process (the restart/SIGTERM scenarios need
    process death, not object teardown).  Returns (Popen, client)."""
    args = [sys.executable, "-m", "jaxmc.serve", "run",
            "--spool", spool, "--workers", "1", "--quiet"]
    if trace:
        args += ["--trace", trace]
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    p = subprocess.Popen(args, cwd=REPO, stdout=subprocess.DEVNULL,
                         stderr=subprocess.PIPE, text=True, env=env)
    stamp = os.path.join(spool, "serve.json")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with open(stamp) as fh:
                info = json.load(fh)
            if info.get("status") == "serving" and \
                    info.get("pid") == p.pid:
                return p, ServeClient(info["host"], info["port"])
        except (OSError, ValueError):
            pass
        assert p.poll() is None, p.stderr.read()
        time.sleep(0.1)
    raise AssertionError("daemon did not stamp the spool in time")


class TestRoundTrip:
    def test_submit_poll_result_over_socket(self, daemon):
        c = client(daemon)
        code, job = c.submit(spec("viewtoy"))
        assert code == 200 and job["status"] == "queued" and job["sig"]
        done = c.wait(job["id"], timeout=60)
        assert done["status"] == "done" and done["ok"]
        code, res = c.result(job["id"])
        assert code == 200
        exp = expect("viewtoy")
        assert res["result"]["distinct"] == exp.distinct
        assert res["result"]["generated"] == exp.generated
        assert str(res["schema"]).startswith("jaxmc.metrics")
        assert res["serve"]["sig"] == job["sig"]
        code, st = c.status()
        assert code == 200 and st["queue_depth"] == 0
        assert st["counters"].get("serve.jobs_done") == 1

    def test_violation_job_carries_trace(self, daemon):
        c = client(daemon)
        _, job = c.submit(spec("symtoy"))
        done = c.wait(job["id"], timeout=60)
        assert done["status"] == "done" and done["ok"] is False
        _, res = c.result(job["id"])
        assert res["result"]["ok"] is False
        assert res["result"]["violation"]["kind"] == "deadlock"
        assert "Error: Deadlock reached." in res["result"]["trace"]
        assert "The behavior up to this point is:" in \
            res["result"]["trace"]

    def test_bad_jobs_rejected(self, daemon):
        c = client(daemon)
        code, body = c.submit(spec("nonexistent_spec"))
        assert code == 400 and "not found" in body["error"]
        code, body = c.submit(spec("viewtoy"),
                              options={"checkpoint": "/tmp/x"})
        assert code == 400 and "forbidden" in body["error"]
        code, body = c.job("j99999999")
        assert code == 404


class TestDurableQueue:
    def test_restart_answers_nonempty_on_disk_queue(self, spool):
        # jobs land in the spool with NO daemon alive; the next daemon
        # start finds and answers them — the restart-survival contract
        q = JobQueue(spool)
        ids = []
        for name in ("viewtoy", "constoy"):
            cfg = build_config(spec(name), None, {})
            ids.append(q.new_job(spec(name), None, {},
                                 job_signature(cfg))["id"])
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = client(d)
            for jid, name in zip(ids, ("viewtoy", "constoy")):
                rec = c.wait(jid, timeout=60)
                assert rec["status"] == "done", rec
                assert rec["distinct"] == expect(name).distinct
        finally:
            d.shutdown()

    def test_identical_queued_jobs_batch_through_one_run(self, spool):
        q = JobQueue(spool)
        cfg = build_config(spec("constoy"), None, {})
        sig = job_signature(cfg)
        ids = [q.new_job(spec("constoy"), None, {}, sig)["id"]
               for _ in range(3)]
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = client(d)
            recs = [c.wait(jid, timeout=60) for jid in ids]
            assert all(r["status"] == "done" for r in recs)
            followers = [r for r in recs if r.get("batch_leader")]
            assert len(followers) == 2, \
                "identical queued jobs must coalesce into one dispatch"
            assert d.tel.counters.get("serve.batched_jobs") == 2
            exp = expect("constoy")
            for jid in ids:
                res = q.load_result(jid)
                assert res["result"]["distinct"] == exp.distinct
        finally:
            d.shutdown()


class TestWarmReuse:
    def test_warm_second_submission_interp(self, daemon):
        c = client(daemon)
        _, j1 = c.submit(spec("constoy"))
        r1 = c.wait(j1["id"], timeout=60)
        _, j2 = c.submit(spec("constoy"))
        r2 = c.wait(j2["id"], timeout=60)
        assert j1["sig"] == j2["sig"]
        assert r1["warm_engine"] is False
        assert r2["warm_engine"] is True
        assert r2["resumed_from_checkpoint"] is True
        assert (r2["distinct"], r2["generated"]) == \
            (r1["distinct"], r1["generated"])
        assert daemon.tel.counters.get("serve.warm_hits") == 1

    def test_warm_jax_resident_zero_recompiles(self, daemon,
                                               monkeypatch, tmp_path):
        # the acceptance criterion verbatim: a second identical spec+cfg
        # job to a warm daemon resumes the first job's checkpoint with
        # window_recompiles == 0 and nonzero capacity-profile hits
        monkeypatch.setenv("JAXMC_PROFILE_STORE",
                           str(tmp_path / "profiles"))
        c = client(daemon)
        _, j1 = c.submit(spec("constoy"), options=JAX_OPTS)
        r1 = c.wait(j1["id"], timeout=180)
        assert r1["status"] == "done", r1
        _, j2 = c.submit(spec("constoy"), options=JAX_OPTS)
        r2 = c.wait(j2["id"], timeout=120)
        assert r2["status"] == "done", r2
        _, res2 = c.result(j2["id"])
        sv = res2["serve"]
        assert sv["warm_engine"] is True
        assert sv["resumed_from_checkpoint"] is True
        assert sv["window_recompiles"] == 0
        assert sv["profile_hits"] >= 1
        assert (r2["distinct"], r2["generated"]) == \
            (r1["distinct"], r1["generated"])
        exp = expect("constoy")
        assert r2["distinct"] == exp.distinct
        # the warm artifact is a normal metrics summary: the session's
        # search span lands in THIS job's recorder, not the cold job's
        assert "search" in {p["name"] for p in res2["phases"]}

    def test_warm_second_submission_jax_level_mode(self, daemon):
        # the DEFAULT device mode (level, traces on) also finalizes a
        # checkpoint on completion: a repeat submission must warm-resume
        # it, not silently re-search
        opts = {"backend": "jax", "platform": "cpu"}
        c = client(daemon)
        _, j1 = c.submit(spec("constoy"), options=opts)
        r1 = c.wait(j1["id"], timeout=180)
        assert r1["status"] == "done", r1
        _, j2 = c.submit(spec("constoy"), options=opts)
        r2 = c.wait(j2["id"], timeout=120)
        assert r2["status"] == "done", r2
        assert r2["warm_engine"] is True
        assert r2["resumed_from_checkpoint"] is True
        assert (r2["distinct"], r2["generated"]) == \
            (r1["distinct"], r1["generated"])

    def test_warm_registry_lru_eviction(self, spool, monkeypatch):
        # ISSUE 10 satellite (ROADMAP item 3): JAXMC_SERVE_WARM_MAX
        # bounds the warm CheckSession registry.  With a 1-session cap,
        # a second signature evicts the first (serve.evictions); the
        # re-submission after eviction is answered from the
        # FINAL-CHECKPOINT resume path — bit-identical, just cold
        monkeypatch.setenv("JAXMC_SERVE_WARM_MAX", "1")
        d = ServeDaemon(spool, workers=1, quiet=True).start()
        try:
            c = client(d)
            _, j1 = c.submit(spec("constoy"))
            r1 = c.wait(j1["id"], timeout=60)
            assert r1["status"] == "done"
            sig1 = j1["sig"]
            _, j2 = c.submit(spec("viewtoy"))
            r2 = c.wait(j2["id"], timeout=60)
            assert r2["status"] == "done"
            assert d.warm_max == 1
            assert d.tel.counters.get("serve.evictions") == 1
            assert sig1 not in d.warm and j2["sig"] in d.warm
            # resubmit the evicted signature: cold engine, but the
            # spool checkpoint survives eviction — same answer
            _, j3 = c.submit(spec("constoy"))
            r3 = c.wait(j3["id"], timeout=60)
            assert r3["status"] == "done"
            assert r3["warm_engine"] is False
            assert r3["resumed_from_checkpoint"] is True
            assert (r3["distinct"], r3["generated"]) == \
                (r1["distinct"], r1["generated"])
            assert d.tel.counters.get("serve.ckpt_resumes") == 1
        finally:
            d.shutdown()

    def test_restart_resumes_with_persistent_cache_hits(
            self, spool, tmp_path):
        # across daemon LIVES (real processes — an in-process pair
        # would be short-circuited by jax's in-memory caches) the
        # durable artifacts carry the warmth: the signature-keyed final
        # checkpoint (resume), the capacity profile (caps), and the
        # persistent compile cache (the one fresh XLA program becomes a
        # disk hit)
        extra_env = {
            "JAXMC_PROFILE_STORE": str(tmp_path / "profiles"),
            "JAXMC_COMPILE_CACHE": str(tmp_path / "xla_cache"),
            "JAXMC_CACHE_PROBE": "0",
        }
        q = JobQueue(spool)
        p1, c1 = start_subprocess_daemon(spool, extra_env=extra_env)
        try:
            _, j1 = c1.submit(spec("constoy"), options=JAX_OPTS)
            r1 = c1.wait(j1["id"], timeout=180)
            assert r1["status"] == "done", r1
            c1.drain()
            assert p1.wait(timeout=60) == 0
        finally:
            if p1.poll() is None:
                p1.kill()
        p2, c2 = start_subprocess_daemon(spool, extra_env=extra_env)
        try:
            _, j2 = c2.submit(spec("constoy"), options=JAX_OPTS)
            r2 = c2.wait(j2["id"], timeout=180)
            assert r2["status"] == "done", r2
            res2 = q.load_result(j2["id"])
            sv = res2["serve"]
            assert sv["warm_engine"] is False  # new process, new engine
            assert sv["resumed_from_checkpoint"] is True
            assert sv["profile_hits"] >= 1
            assert sv["persistent_cache_hits"] >= 1
            assert (r2["distinct"], r2["generated"]) == \
                (r1["distinct"], r1["generated"])
            c2.drain()
            assert p2.wait(timeout=60) == 0
        finally:
            if p2.poll() is None:
                p2.kill()


class TestSigtermDrain:
    def test_sigterm_drains_inflight_and_restart_loses_nothing(
            self, spool, tmp_path):
        trace = str(tmp_path / "fleet.jsonl")
        limit = 30000
        p, c = start_subprocess_daemon(spool, trace=trace)
        try:
            _, slow = c.submit(spec("transfer_scaled"),
                               options={"max_states": limit})
            _, queued = c.submit(spec("viewtoy"))
            # wait until the slow job is actually IN FLIGHT
            deadline = time.time() + 30
            while time.time() < deadline:
                _, st = c.status()
                if slow["id"] in st.get("running", {}):
                    break
                time.sleep(0.1)
            else:
                raise AssertionError("slow job never started")
            time.sleep(1.0)  # well inside the multi-second search
            p.send_signal(signal.SIGTERM)
            rc = p.wait(timeout=120)
        finally:
            if p.poll() is None:
                p.kill()
        assert rc == 0, p.stderr.read()

        q = JobQueue(spool)
        slow_rec = q.load(slow["id"])
        assert slow_rec["status"] == "drained", slow_rec
        assert os.path.exists(q.ckpt_path(slow["sig"])), \
            "drained job must leave a checkpoint"
        assert q.load(queued["id"])["status"] == "queued", \
            "queued job must survive the drain untouched"
        # no open spans in the fleet trace = nothing leaked at drain
        events = [json.loads(ln) for ln in open(trace)]
        opens = sum(1 for e in events if e["ev"] == "span_open")
        closes = sum(1 for e in events if e["ev"] == "span")
        assert opens == closes, "drain left open spans"
        assert any(e["ev"] == "run_end" for e in events)

        # ---- next daemon life: both jobs answered, from checkpoints --
        p2, c2 = start_subprocess_daemon(spool)
        try:
            done_slow = c2.wait(slow["id"], timeout=120)
            assert done_slow["status"] == "done", done_slow
            assert done_slow["resumed_from_checkpoint"] is True
            exp = expect("transfer_scaled", max_states=limit)
            assert (done_slow["distinct"], done_slow["generated"]) == \
                (exp.distinct, exp.generated), \
                "drain+resume must be bit-identical to an uninterrupted run"
            done_q = c2.wait(queued["id"], timeout=60)
            assert done_q["status"] == "done"
            assert done_q["distinct"] == expect("viewtoy").distinct
            c2.drain()
            rc2 = p2.wait(timeout=60)
            assert rc2 == 0
        finally:
            if p2.poll() is None:
                p2.kill()


class TestMetricsRetention:
    """ISSUE 17 satellites: per-job /metrics series outlive the job for
    JAXMC_METRICS_JOB_TTL seconds (a coarse scraper still sees a short
    job's final series), and jax jobs expose jaxmc_prof_site_* /
    jaxmc_hbm_peak_bytes gauges from the always-on profiler."""

    def test_done_job_series_ttl_and_prof_gauges(self, daemon,
                                                 monkeypatch,
                                                 tmp_path):
        monkeypatch.setenv("JAXMC_PROFILE_STORE",
                           str(tmp_path / "profiles"))
        c = client(daemon)
        _, job = c.submit(spec("constoy"), options=JAX_OPTS)
        done = c.wait(job["id"], timeout=180)
        assert done["status"] == "done", done
        jid = job["id"]
        # completed job: the final series linger inside the TTL window
        body = daemon.metrics_text()
        assert f'jaxmc_job_running{{job="{jid}"}} 0' in body
        assert f'jaxmc_prof_site_dispatches{{job="{jid}",' \
               f'site="bfs.resident_run"}}' in body
        assert f'jaxmc_hbm_peak_bytes{{job="{jid}"}}' in body
        # advance the metrics clock past the TTL: the series are pruned
        t0 = time.time()
        daemon._metrics_clock = \
            lambda: t0 + daemon._job_ttl + 1.0
        body2 = daemon.metrics_text()
        assert jid not in body2
        # fleet-level series survive the prune
        assert "jaxmc_serve_jobs_done" in body2


class TestDeviceOwnerDefault:
    """ISSUE 19 satellite: device work leaves the daemon process BY
    DEFAULT now that owner death is supervised (requeue + respawn +
    the cross-daemon retry budget); JAXMC_SERVE_DEVICE_OWNER=0 (or
    `run --no-device-owner`) opts back into the pre-fleet in-process
    layout.  The owner spawn itself is lazy, so constructing the
    daemon does not fork."""

    def test_owner_enabled_by_default(self, tmp_path, monkeypatch):
        monkeypatch.delenv("JAXMC_SERVE_DEVICE_OWNER", raising=False)
        d = ServeDaemon(str(tmp_path / "spool"), workers=1, quiet=True)
        assert d.owner is not None
        assert d.owner.pid is None  # lazy: nothing forked yet
        d.owner.stop()

    def test_env_zero_opts_out(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JAXMC_SERVE_DEVICE_OWNER", "0")
        d = ServeDaemon(str(tmp_path / "spool"), workers=1, quiet=True)
        assert d.owner is None
