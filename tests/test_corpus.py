r"""Corpus-as-regression-test (SURVEY.md §4.1): every checkable spec+cfg in
the reference runs through the interpreter engine with pinned verdicts and
state counts. MCConsensus/MCVoting legitimately terminate, so with deadlock
checking on (TLC's default) they report deadlock — the corpus authors ran
those models with deadlock checking off, which is the pinned configuration
here.
"""

import os

import pytest

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer

from conftest import REFERENCE


def run(rel, no_deadlock=False, max_states=None):
    spec = os.path.join(REFERENCE, rel)
    cfg = parse_cfg(open(spec[:-4] + ".cfg",
                         encoding="utf-8", errors="replace").read())
    if no_deadlock:
        cfg.check_deadlock = False
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    return Explorer(bind_model(m, cfg), max_states=max_states).run()


# (spec, no_deadlock, expect_ok, distinct, generated)
CASES = [
    ("pcal_intro.tla", False, True, 3800, 5850),
    ("examples/Paxos/MCPaxos.tla", False, True, 25, 82),
    ("examples/Paxos/MCConsensus.tla", True, True, 4, 7),
    # MCVoting.cfg declares SYMMETRY: counts are symmetry-reduced
    ("examples/Paxos/MCVoting.tla", True, True, 77, 406),
    ("examples/SpecifyingSystems/HourClock/HourClock.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/HourClock/HourClock2.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/AsynchronousInterface/AsynchInterface.tla",
     False, True, 12, 30),
    ("examples/SpecifyingSystems/AsynchronousInterface/Channel.tla",
     False, True, 12, 30),
    ("examples/SpecifyingSystems/FIFO/MCInnerFIFO.tla",
     False, True, 5808, 9660),
    ("examples/SpecifyingSystems/CachingMemory/MCInternalMemory.tla",
     False, True, 4408, 21400),
    ("examples/SpecifyingSystems/CachingMemory/MCWriteThroughCache.tla",
     False, True, 5196, 28170),
    ("examples/SpecifyingSystems/Liveness/LiveHourClock.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/Liveness/MCLiveInternalMemory.tla",
     False, True, 4408, 21400),
    ("examples/SpecifyingSystems/Liveness/MCLiveWriteThroughCache.tla",
     False, True, 5196, 28170),
    ("examples/SpecifyingSystems/RealTime/MCRealTimeHourClock.tla",
     False, True, 216, 696),
    ("examples/SpecifyingSystems/TLC/ABCorrectness.tla",
     False, True, 20, 36),
    ("examples/SpecifyingSystems/TLC/MCAlternatingBit.tla",
     False, True, 428, 1392),
    ("examples/SpecifyingSystems/AdvancedExamples/MCInnerSequential.tla",
     False, True, 14280, 24368),
]


@pytest.mark.parametrize("rel,no_dl,ok,distinct,generated",
                         CASES, ids=[c[0].split("/")[-1] for c in CASES])
def test_corpus_spec(rel, no_dl, ok, distinct, generated):
    r = run(rel, no_deadlock=no_dl)
    assert r.ok == ok, (r.violation.kind if r.violation else None)
    assert r.distinct == distinct
    assert r.generated == generated


def test_consensus_deadlocks_like_tlc_default():
    # with TLC's default deadlock checking, a terminating spec reports it
    r = run("examples/Paxos/MCConsensus.tla")
    assert not r.ok and r.violation.kind == "deadlock"


def test_raft_explores():
    # raft with the BASELINE.json 3-server model explores correctly on the
    # interpreter (bounded prefix; full run is the TPU-backend target)
    from jaxmc.front.cfg import ModelConfig, CfgModelValue
    spec = os.path.join(REFERENCE, "examples/raft.tla")
    cfg = ModelConfig(specification="Spec")
    for mv in ("Follower", "Candidate", "Leader", "Nil",
               "RequestVoteRequest", "RequestVoteResponse",
               "AppendEntriesRequest", "AppendEntriesResponse"):
        cfg.constants[mv] = CfgModelValue(mv)
    cfg.constants["Server"] = frozenset(
        {CfgModelValue("s1"), CfgModelValue("s2"), CfgModelValue("s3")})
    cfg.constants["MaxClientRequests"] = 2
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    r = Explorer(bind_model(m, cfg), max_states=1500).run()
    assert r.ok and r.truncated
    assert r.distinct == 1500
