r"""Corpus-as-regression-test (SURVEY.md §4.1): every checkable spec+cfg in
the reference runs through the interpreter engine with pinned verdicts and
state counts. MCConsensus/MCVoting legitimately terminate, so with deadlock
checking on (TLC's default) they report deadlock — the corpus authors ran
those models with deadlock checking off, which is the pinned configuration
here.
"""

import os

import pytest

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer

from conftest import REFERENCE


def run(rel, no_deadlock=False, max_states=None):
    spec = os.path.join(REFERENCE, rel)
    cfg = parse_cfg(open(spec[:-4] + ".cfg",
                         encoding="utf-8", errors="replace").read())
    if no_deadlock:
        cfg.check_deadlock = False
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    return Explorer(bind_model(m, cfg), max_states=max_states).run()


# (spec, no_deadlock, expect_ok, distinct, generated)
# distinct counts only CONSTRAINT-satisfying states: TLC fingerprints a
# violating state but discards it (never distinct/checked/explored) —
# semantics pinned by the golden run (testout2:265: 195 distinct, matched
# exactly by test_innerserial_matches_golden_testout2)
CASES = [
    ("pcal_intro.tla", False, True, 3800, 5850),
    ("examples/Paxos/MCPaxos.tla", False, True, 25, 82),
    ("examples/Paxos/MCConsensus.tla", True, True, 4, 7),
    # MCVoting.cfg declares SYMMETRY: counts are symmetry-reduced
    ("examples/Paxos/MCVoting.tla", True, True, 77, 406),
    ("examples/SpecifyingSystems/HourClock/HourClock.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/HourClock/HourClock2.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/AsynchronousInterface/AsynchInterface.tla",
     False, True, 12, 30),
    ("examples/SpecifyingSystems/AsynchronousInterface/Channel.tla",
     False, True, 12, 30),
    ("examples/SpecifyingSystems/FIFO/MCInnerFIFO.tla",
     False, True, 3864, 9660),
    ("examples/SpecifyingSystems/CachingMemory/MCInternalMemory.tla",
     False, True, 4408, 21400),
    ("examples/SpecifyingSystems/CachingMemory/MCWriteThroughCache.tla",
     False, True, 5196, 28170),
    ("examples/SpecifyingSystems/Liveness/LiveHourClock.tla",
     False, True, 12, 24),
    ("examples/SpecifyingSystems/Liveness/MCLiveInternalMemory.tla",
     False, True, 4408, 21400),
    ("examples/SpecifyingSystems/Liveness/MCLiveWriteThroughCache.tla",
     False, True, 5196, 28170),
    # ErrorTemporal is EXPECTED to fail (the cfg checks a property the
    # spec violates, MCRealTimeHourClock.tla:43) — TLC finds it too
    ("examples/SpecifyingSystems/RealTime/MCRealTimeHourClock.tla",
     False, False, 216, 696),
    ("examples/SpecifyingSystems/TLC/ABCorrectness.tla",
     False, True, 20, 36),
    ("examples/SpecifyingSystems/TLC/MCAlternatingBit.tla",
     False, True, 240, 1392),
    ("examples/SpecifyingSystems/AdvancedExamples/MCInnerSequential.tla",
     False, True, 3528, 24368),
]


@pytest.mark.parametrize("rel,no_dl,ok,distinct,generated",
                         CASES, ids=[c[0].split("/")[-1] for c in CASES])
def test_corpus_spec(rel, no_dl, ok, distinct, generated):
    r = run(rel, no_deadlock=no_dl)
    assert r.ok == ok, (r.violation.kind if r.violation else None)
    assert r.distinct == distinct
    assert r.generated == generated


def test_innerserial_matches_golden_testout2():
    # the corpus's only captured FULL TLC run (SURVEY.md §4.3): the golden
    # log pins 6181 generated / 195 distinct / diameter 5 for the
    # MCInnerSerial model (testout2:265-266; TLC 1.57 took 22h02m on it).
    # Our diameter is the 0-based max depth: TLC's "diameter 5" == 4 here
    # (our printed "depth of the complete state graph search" is 1-based
    # and matches TLC's phrasing).
    r = run("examples/SpecifyingSystems/AdvancedExamples/MCInnerSerial.tla")
    assert r.ok
    assert r.generated == 6181
    assert r.distinct == 195
    assert r.diameter == 4


def test_consensus_deadlocks_like_tlc_default():
    # with TLC's default deadlock checking, a terminating spec reports it
    r = run("examples/Paxos/MCConsensus.tla")
    assert not r.ok and r.violation.kind == "deadlock"


def test_raft_explores():
    # raft with the BASELINE.json 3-server model explores correctly on the
    # interpreter (bounded prefix; full run is the TPU-backend target)
    from jaxmc.front.cfg import ModelConfig, CfgModelValue
    spec = os.path.join(REFERENCE, "examples/raft.tla")
    cfg = ModelConfig(specification="Spec")
    for mv in ("Follower", "Candidate", "Leader", "Nil",
               "RequestVoteRequest", "RequestVoteResponse",
               "AppendEntriesRequest", "AppendEntriesResponse"):
        cfg.constants[mv] = CfgModelValue(mv)
    cfg.constants["Server"] = frozenset(
        {CfgModelValue("s1"), CfgModelValue("s2"), CfgModelValue("s3")})
    cfg.constants["MaxClientRequests"] = 2
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    r = Explorer(bind_model(m, cfg), max_states=1500).run()
    assert r.ok and r.truncated
    assert r.distinct == 1500
