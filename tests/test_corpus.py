r"""Corpus-as-regression-test (SURVEY.md §4.1): every checkable spec+cfg in
the reference runs through the interpreter engine with pinned verdicts and
state counts. MCConsensus/MCVoting legitimately terminate, so with deadlock
checking on (TLC's default) they report deadlock — the corpus authors ran
those models with deadlock checking off, which is the pinned configuration
here.
"""

import os

import pytest

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc.engine.explore import Explorer

from conftest import REFERENCE, needs_reference


def run(rel, no_deadlock=False, max_states=None):
    spec = os.path.join(REFERENCE, rel)
    cfg = parse_cfg(open(spec[:-4] + ".cfg",
                         encoding="utf-8", errors="replace").read())
    if no_deadlock:
        cfg.check_deadlock = False
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    return Explorer(bind_model(m, cfg), max_states=max_states).run()


# One manifest drives both this test and `jaxmc sweep` (make check-corpus):
# jaxmc/corpus.py pins every checkable spec+cfg with its expected verdict.
# distinct counts only CONSTRAINT-satisfying states: TLC fingerprints a
# violating state but discards it (never distinct/checked/explored) --
# semantics pinned by the golden run (testout2:265: 195 distinct, matched
# exactly by test_innerserial_matches_golden_testout2)
from jaxmc.corpus import CASES, run_case

FAST = [c for c in CASES if not c.slow]


def _case_needs_reference(case) -> bool:
    """A case depends on the reference tree when its spec lives there OR
    a repo shim pulls includes from it (MCraftMicro EXTENDS raft)."""
    return case.root == "ref" or any(
        not inc.startswith("repo:") for inc in case.includes)


@pytest.mark.parametrize(
    "case", FAST,
    ids=[(c.cfg or c.spec).split("/")[-1] for c in FAST])
def test_corpus_case(case):
    from conftest import HAVE_REFERENCE
    if _case_needs_reference(case) and not HAVE_REFERENCE:
        pytest.skip(f"needs the reference spec corpus at {REFERENCE} "
                    f"(driver environment only)")
    if case.lint_only:
        # deliberately-unclean linter fixture (ISSUE 9): not a
        # checkable model; `make lint-corpus` + tests/test_analyze.py
        # assert its expected diagnostics instead
        pytest.skip("lint-only fixture (covered by lint-corpus)")
    status, detail, _r, _mode = run_case(case)
    assert status == "pass", detail


@needs_reference
def test_innerserial_matches_golden_testout2():
    # the corpus's only captured FULL TLC run (SURVEY.md §4.3): the golden
    # log pins 6181 generated / 195 distinct / diameter 5 for the
    # MCInnerSerial model (testout2:265-266; TLC 1.57 took 22h02m on it).
    # Our diameter is the 0-based max depth: TLC's "diameter 5" == 4 here
    # (our printed "depth of the complete state graph search" is 1-based
    # and matches TLC's phrasing).
    r = run("examples/SpecifyingSystems/AdvancedExamples/MCInnerSerial.tla")
    assert r.ok
    assert r.generated == 6181
    assert r.distinct == 195
    assert r.diameter == 4


@needs_reference
def test_consensus_deadlocks_like_tlc_default():
    # with TLC's default deadlock checking, a terminating spec reports it
    r = run("examples/Paxos/MCConsensus.tla")
    assert not r.ok and r.violation.kind == "deadlock"


class TestModePins:
    """Expansion-mode pinning (ISSUE 5) — repo-local models only, so
    this class runs without the reference tree."""

    @staticmethod
    def _case(spec):
        return next(c for c in CASES if c.spec == spec)

    @staticmethod
    def _needs_native_store():
        from jaxmc import native_store
        if not native_store.is_available():
            pytest.skip("interp-arms pins need the native host store")

    def test_pinned_interp_arms_skips_kernel_construction(self):
        # the r05 sweep's 213s lesson: a pinned interp-arms case must
        # not ground/compile/trace a single kernel — and still produce
        # the pinned counts through the hybrid engine
        import dataclasses
        self._needs_native_store()
        case = dataclasses.replace(self._case("specs/symtoy.tla"),
                                   mode="interp-arms")
        status, detail, r, mode = run_case(case, backend="jax")
        assert status == "pass", detail
        assert mode == "interp-arms" and "[mode pinned]" in detail
        assert "0/4 arms compiled" in detail

    def test_mode_slide_toward_interp_fails(self):
        # interparm_toy is hybrid BY CONSTRUCTION (unguarded
        # SUBSET-of-symbolic-set assignment): pinning it "compiled"
        # must FAIL the sweep, fast, without running the search
        import dataclasses
        self._needs_native_store()
        case = dataclasses.replace(
            self._case("specs/interparm_toy.tla"), mode="compiled")
        status, detail, r, mode = run_case(case, backend="jax")
        assert status == "fail" and "REGRESSION" in detail \
            and "slid" in detail
        assert r is None, "a slid case must fail before the search runs"

    def test_demoted_arm_reasons_named_in_detail(self):
        # the per-arm demotion reason table (VERDICT r5 #4): the demoted
        # arm is NAMED with its reason, not folded into a count
        self._needs_native_store()
        status, detail, _r, mode = run_case(
            self._case("specs/interparm_toy.tla"), backend="jax")
        assert status == "pass", detail
        assert mode == "hybrid"
        assert "demoted arms: Pick: SUBSET of symbolic set" in detail

    def test_mode_improvement_passes_with_manifest_note(self):
        import dataclasses
        case = dataclasses.replace(self._case("specs/symtoy.tla"),
                                   mode="hybrid")
        status, detail, _r, mode = run_case(case, backend="jax")
        assert status == "pass" and mode == "compiled"
        assert "update the manifest" in detail

    def test_pin_escape_hatch_lifts_enforcement(self, monkeypatch):
        # JAXMC_MODE_PIN=0: the diagnosis sweep builds everything again
        import dataclasses
        monkeypatch.setenv("JAXMC_MODE_PIN", "0")
        case = dataclasses.replace(self._case("specs/symtoy.tla"),
                                   mode="interp-arms")
        status, detail, _r, mode = run_case(case, backend="jax")
        assert status == "pass" and mode == "compiled"
        assert "[mode pinned]" not in detail


class TestSymmetryDisclosure:
    """sym=identity vs sym=UNREDUCED-FALLBACK (ISSUE 5 satellite): an
    identity permutation group has no reduction to diverge from — only
    a genuine CompileError fallback may claim divergence."""

    def test_identity_group_reports_identity(self):
        case = next(c for c in CASES if c.spec == "specs/symid.tla")
        status, detail, r, _m = run_case(case, backend="jax")
        assert status == "pass", detail
        assert "sym=identity" in detail
        assert "UNREDUCED" not in detail
        assert not any("SYMMETRY" in w for w in r.warnings), \
            "identity groups must not emit the divergence warning"

    def test_forced_fallback_still_warns(self, monkeypatch):
        # a REAL canonicalizer fallback (group over the unroll limit)
        # keeps the honest divergence disclosure
        import dataclasses
        monkeypatch.setenv("JAXMC_SYM_GROUP_LIMIT", "0")
        case = dataclasses.replace(
            next(c for c in CASES if c.spec == "specs/symtoy.tla"),
            distinct=None, generated=None, mode=None)
        status, detail, _r, _m = run_case(case, backend="jax")
        assert status == "pass", detail
        assert "sym=UNREDUCED-FALLBACK" in detail


@needs_reference
def test_raft_explores():
    # raft with the BASELINE.json 3-server model explores correctly on the
    # interpreter (bounded prefix; full run is the TPU-backend target)
    from jaxmc.front.cfg import ModelConfig, CfgModelValue
    spec = os.path.join(REFERENCE, "examples/raft.tla")
    cfg = ModelConfig(specification="Spec")
    for mv in ("Follower", "Candidate", "Leader", "Nil",
               "RequestVoteRequest", "RequestVoteResponse",
               "AppendEntriesRequest", "AppendEntriesResponse"):
        cfg.constants[mv] = CfgModelValue(mv)
    cfg.constants["Server"] = frozenset(
        {CfgModelValue("s1"), CfgModelValue("s2"), CfgModelValue("s3")})
    cfg.constants["MaxClientRequests"] = 2
    m = Loader([os.path.dirname(spec)]).load_path(spec)
    r = Explorer(bind_model(m, cfg), max_states=1500).run()
    assert r.ok and r.truncated
    assert r.distinct == 1500
