r"""Interpreter + engine tests: evaluator semantics, enumeration, and the
corpus oracle runs recorded in the reference (SURVEY.md §6).
"""

import os

import pytest

from jaxmc.front.parser import parse_expr_text
from jaxmc.front.cfg import CfgModelValue, ModelConfig, parse_cfg
from jaxmc.sem.values import Fcn, ModelValue, fmt, mk_seq
from jaxmc.sem.eval import Ctx, eval_expr
from jaxmc.sem.modules import Loader, bind_model, BASE_IDENTS
from jaxmc.sem.enumerate import enumerate_init, enumerate_next
from jaxmc.engine.explore import Explorer, format_trace

from conftest import REFERENCE, needs_reference

SPECS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "specs")


def ev(src, **bound):
    ctx = Ctx(dict(BASE_IDENTS), bound=bound)
    return eval_expr(parse_expr_text(src), ctx)


class TestEval:
    def test_arith(self):
        assert ev("2 + 3 * 4") == 14
        assert ev("7 \\div 2") == 3
        assert ev("7 % 2") == 1
        assert ev("2 ^ 10") == 1024
        assert ev("-(5) + 1") == -4

    def test_sets(self):
        assert ev("1 .. 3") == frozenset({1, 2, 3})
        assert ev("{1, 2} \\cup {2, 3}") == frozenset({1, 2, 3})
        assert ev("{x \\in 1..10 : x % 2 = 0}") == frozenset({2, 4, 6, 8, 10})
        assert ev("{x * x : x \\in 1..3}") == frozenset({1, 4, 9})
        assert ev("Cardinality(SUBSET (1..3))") == 8
        assert ev("UNION {{1}, {2, 3}}") == frozenset({1, 2, 3})
        assert ev("{1} \\subseteq {1, 2}") is True
        assert ev("1 \\in Nat") is True
        assert ev("-1 \\in Nat") is False
        assert ev("-1 \\in Int") is True

    def test_bool_int_distinct(self):
        assert ev("TRUE \\in {1, 2}") is False
        assert ev("1 \\in {TRUE, FALSE}") is False

    def test_functions(self):
        assert ev("[x \\in 1..3 |-> x * 2][2]") == 4
        assert ev("DOMAIN [x \\in 1..3 |-> x]") == frozenset({1, 2, 3})
        assert ev('[a |-> 1, b |-> 2].b') == 2
        assert ev("[f EXCEPT ![2] = @ + 10][2]",
                  f=Fcn({1: 1, 2: 2})) == 12
        assert ev("Cardinality([b: {0, 1}, c: {0, 1}])") == 4
        assert ev("Cardinality([{1, 2} -> {1, 2, 3}])") == 9
        assert ev("(1 :> 2 @@ 3 :> 4)[3]") == 4

    def test_sequences(self):
        assert ev("Len(<<1, 2, 3>>)") == 3
        assert ev("Append(<<1>>, 2)") == mk_seq([1, 2])
        assert ev("Head(<<1, 2>>)") == 1
        assert ev("Tail(<<1, 2>>)") == mk_seq([2])
        assert ev("<<1, 2>> \\o <<3>>") == mk_seq([1, 2, 3])
        assert ev("SubSeq(<<1, 2, 3, 4>>, 2, 3)") == mk_seq([2, 3])
        assert ev("<<1, 2>> \\in Seq(Nat)") is True
        # a sequence IS the function with domain 1..n
        assert ev("<<4, 5>> = [i \\in 1..2 |-> i + 3]") is True

    def test_quantifiers_choose(self):
        assert ev("\\A x \\in 1..5 : x < 6") is True
        assert ev("\\E x \\in 1..5 : x = 3") is True
        assert ev("CHOOSE x \\in 1..5 : x * x = 9") == 3
        # deterministic lowest witness
        assert ev("CHOOSE x \\in 1..5 : x > 2") == 3

    def test_if_case_let(self):
        assert ev("IF 1 < 2 THEN 10 ELSE 20") == 10
        assert ev("CASE 1 > 2 -> 0 [] 2 > 1 -> 5 [] OTHER -> 9") == 5
        assert ev("LET sq(x) == x * x IN sq(7)") == 49
        assert ev("LET a == 3 b == a + 1 IN a * b") == 12

    def test_recursive_let(self):
        assert ev("LET RECURSIVE f(_) f(n) == IF n = 0 THEN 1 "
                  "ELSE n * f(n - 1) IN f(5)") == 120

    def test_recursive_fn_constructor(self):
        assert ev("LET f[n \\in 0..5] == IF n = 0 THEN 1 ELSE n * f[n - 1] "
                  "IN f[5]") == 120

    def test_tuples_products(self):
        assert ev("Cardinality({1, 2} \\X {3, 4} \\X {5})") == 4
        v = ev("CHOOSE <<a, b>> \\in {1} \\X {2} : TRUE")
        assert v == mk_seq([1, 2])

    def test_strings_model_values(self):
        assert ev('"abc" = "abc"') is True
        assert ev('"abc" \\in STRING') is True


def run_spec(path, cfg=None, **kw):
    ldr = Loader([os.path.dirname(os.path.abspath(path))])
    m = ldr.load_path(path)
    model = bind_model(m, cfg or ModelConfig(specification="Spec"))
    return Explorer(model, **kw).run()


class TestEngine:
    @needs_reference
    def test_atomic_add(self):
        r = run_spec(os.path.join(REFERENCE, "atomic_add.tla"))
        assert r.ok
        assert r.distinct == 5
        assert r.generated == 7

    @needs_reference
    def test_pcal_intro_fixed_passes(self):
        cfg = parse_cfg(open(os.path.join(REFERENCE, "pcal_intro.cfg")).read())
        r = run_spec(os.path.join(REFERENCE, "pcal_intro.tla"), cfg)
        assert r.ok
        assert r.distinct == 3800
        assert r.generated == 5850

    def test_pcal_intro_buggy_matches_tlc_oracle(self):
        # the recorded TLC run: 9097 generated / 6164 distinct at the
        # assertion violation (/root/reference/README.md:319-320)
        r = run_spec(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        assert not r.ok
        assert r.violation.kind == "assert"
        assert r.generated == 9097
        assert r.distinct == 6164
        assert len(r.violation.trace) == 6
        # README's trace: both at Transfer, money <<1, 10>>
        st0 = r.violation.trace[0][0]
        assert fmt(st0["money"]) == "<<1, 10>>"
        assert fmt(st0["pc"]) == '<<"Transfer", "Transfer">>'

    def test_buggy_invariant_violation_found(self):
        cfg = ModelConfig(specification="Spec",
                          invariants=["MoneyInvariant"])
        r = run_spec(os.path.join(SPECS, "pcal_intro_buggy.tla"), cfg)
        assert not r.ok and r.violation.kind == "invariant"
        assert r.violation.name == "MoneyInvariant"

    def test_trace_labels(self):
        r = run_spec(os.path.join(SPECS, "pcal_intro_buggy.tla"))
        labels = [lbl for _, lbl in r.violation.trace]
        assert labels[0] == "Initial predicate"
        assert labels[1].startswith("Transfer(")

    def test_deadlock_detection(self):
        # two processes that each await the other's increment never fire
        import tempfile
        src = """---- MODULE dl ----
EXTENDS Naturals
VARIABLES x, y
Init == x = 0 /\\ y = 0
Next == \\/ x > 0 /\\ y' = y + 1 /\\ x' = x
        \\/ y > 0 /\\ x' = x + 1 /\\ y' = y
====
"""
        with tempfile.NamedTemporaryFile("w", suffix=".tla",
                                         delete=False) as f:
            f.write(src)
            p = f.name
        cfg = ModelConfig(init="Init", next="Next")
        r = run_spec(p, cfg)
        assert not r.ok and r.violation.kind == "deadlock"
        cfg2 = ModelConfig(init="Init", next="Next", check_deadlock=False)
        r2 = run_spec(p, cfg2)
        assert r2.ok
        os.unlink(p)


class TestHourClock:
    @needs_reference
    def test_hourclock(self):
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/HourClock")
        cfg = parse_cfg(open(os.path.join(d, "HourClock.cfg")).read())
        r = run_spec(os.path.join(d, "HourClock.tla"), cfg)
        assert r.ok
        assert r.distinct == 12


class TestPcalSemantics:
    def test_sequential_assignment_reads_updated_value(self):
        # PlusCal statements in one step execute sequentially: `x := 1; y := x`
        # must set y to the NEW x (p-manual semantics; review finding repro)
        import tempfile
        src = """---- MODULE seqassign ----
EXTENDS Naturals, TLC
(* --algorithm seqassign
variables x = 0, y = 0
process P \\in {1}
begin
Step:
  x := 1;
  y := x;
  assert y = 1;
end process
end algorithm *)
====
"""
        with tempfile.NamedTemporaryFile("w", suffix=".tla",
                                         delete=False) as f:
            f.write(src)
            p = f.name
        r = run_spec(p, ModelConfig(specification="Spec"))
        os.unlink(p)
        assert r.ok

    def test_while_loop(self):
        import tempfile
        src = """---- MODULE wl ----
EXTENDS Naturals, TLC
(* --algorithm wl
variables total = 0
process P \\in {1}
  variables i = 0;
begin
Loop:
  while i < 3 do
    total := total + 1;
    i := i + 1;
  end while;
Done1: assert total = 3;
end process
end algorithm *)
====
"""
        with tempfile.NamedTemporaryFile("w", suffix=".tla",
                                         delete=False) as f:
            f.write(src)
            p = f.name
        r = run_spec(p, ModelConfig(specification="Spec"))
        os.unlink(p)
        assert r.ok


class TestRefinement:
    @needs_reference
    def test_paxos_voting_refinement_checked(self):
        # MCPaxos.cfg PROPERTY VotingSpecBar == V!Spec — the Paxos -> Voting
        # refinement (SURVEY.md §3.4) holds stepwise on every edge
        d = os.path.join(REFERENCE, "examples/Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCPaxos.cfg")).read())
        r = run_spec(os.path.join(d, "MCPaxos.tla"), cfg)
        assert r.ok
        assert not any("VotingSpecBar" in w for w in r.warnings)

    @needs_reference
    def test_hourclock2_equivalence_checked(self):
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/HourClock")
        cfg = parse_cfg(open(os.path.join(d, "HourClock2.cfg")).read())
        r = run_spec(os.path.join(d, "HourClock2.tla"), cfg)
        assert r.ok and not r.warnings

    def test_non_refinement_detected(self):
        import tempfile
        src = """---- MODULE badhc ----
EXTENDS Naturals
VARIABLE hr
HCini == hr \\in 1..12
HCnxt == hr' = IF hr >= 11 THEN 1 ELSE hr + 2
HC == HCini /\\ [][HCnxt]_hr
Jump == hr' = IF hr = 12 THEN 1 ELSE hr + 1
JumpSpec == HCini /\\ [][Jump]_hr
====
"""
        with tempfile.NamedTemporaryFile("w", suffix=".tla",
                                         delete=False) as f:
            f.write(src)
            p = f.name
        cfg = ModelConfig(specification="HC", properties=["JumpSpec"],
                          check_deadlock=False)
        r = run_spec(p, cfg)
        os.unlink(p)
        assert not r.ok
        assert r.violation.kind == "property"
        assert r.violation.name == "JumpSpec"

    @needs_reference
    def test_liveness_property_checked_with_refinement(self):
        # MCAlternatingBit.cfg checks ABCSpec (refinement, stepwise, plus
        # its ABCFairness half over the behavior graph — r3) and
        # SentLeadsToRcvd (a ~> property, behavior-graph liveness) in one
        # model — ALL halves genuinely checked, zero warnings
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/TLC")
        cfg = parse_cfg(open(os.path.join(d, "MCAlternatingBit.cfg")).read())
        r = run_spec(os.path.join(d, "MCAlternatingBit.tla"), cfg)
        assert r.ok
        assert not any("NOT checked" in w for w in r.warnings), r.warnings

    @needs_reference
    def test_abcspec_fairness_half_violated_without_spec_fairness(self):
        # negative control for the adopted fairness half: under the
        # fairness-free INIT/NEXT spec a behavior may stutter forever
        # with CRcvMsg enabled, violating ABCFairness's WF_cvars(CRcvMsg)
        # (ABCorrectness.tla:37-39) — the abstract action must classify
        # concrete edges relationally for this to be non-vacuous
        d = os.path.join(REFERENCE, "examples/SpecifyingSystems/TLC")
        cfg = parse_cfg(
            "INIT ABInit\nNEXT ABNext\nCONSTANTS\n  Data = {d1, d2}\n"
            "  msgQLen = 2\n  ackQLen = 2\nCONSTRAINT SeqConstraint\n"
            "PROPERTY ABCSpec\nCHECK_DEADLOCK FALSE\n")
        r = run_spec(os.path.join(d, "MCAlternatingBit.tla"), cfg)
        assert not r.ok
        assert r.violation.kind == "property"
        assert "ABCSpec" in r.violation.name


class TestCheckpoint:
    @needs_reference
    def test_checkpoint_resume_roundtrip(self):
        # truncated run writes a checkpoint; resuming completes with the
        # exact full-run counts (TLC's states/ dir contract, SURVEY.md §5)
        import tempfile
        spec = os.path.join(REFERENCE, "pcal_intro.tla")
        cfg = parse_cfg(open(os.path.join(REFERENCE, "pcal_intro.cfg")).read())
        ckpt = tempfile.mktemp(suffix=".ckpt")
        m1 = Loader([]).load_path(spec)
        r1 = Explorer(bind_model(m1, cfg), max_states=1500,
                      checkpoint_path=ckpt, checkpoint_every=0.0).run()
        assert r1.truncated and os.path.exists(ckpt)
        m2 = Loader([]).load_path(spec)
        r2 = Explorer(bind_model(m2, cfg), resume_from=ckpt).run()
        os.unlink(ckpt)
        assert r2.ok
        assert r2.distinct == 3800
        assert r2.generated == 5850

    def test_checkpoint_resume_with_symmetry(self, tmp_path):
        # the resumed seen-set must be rebuilt with symmetry-canonical
        # keys, or known states get re-added after resume (inflated counts)
        spec = tmp_path / "symm.tla"
        spec.write_text(TestSymmetry.SYMM)
        ckpt = str(tmp_path / "symm.ckpt")

        def model():
            cfg = ModelConfig(init="Init", next="Next", check_deadlock=False,
                              symmetry="Sym")
            cfg.constants["Proc"] = frozenset(
                {CfgModelValue("p1"), CfgModelValue("p2")})
            return bind_model(Loader([]).load_path(str(spec)), cfg)

        r1 = Explorer(model(), max_states=3, checkpoint_path=ckpt,
                      checkpoint_every=0.0).run()
        assert r1.truncated and os.path.exists(ckpt)
        r2 = Explorer(model(), resume_from=ckpt).run()
        assert r2.ok
        assert r2.distinct == 6   # == the unresumed symmetric run

    @needs_reference
    def test_checkpoint_resume_cross_process(self, tmp_path):
        # checkpoints must survive a process boundary: str/frozenset hashes
        # are per-process, so pickled values must not carry cached hashes,
        # and interned ModelValues must re-intern (MCPaxos states hold both)
        import subprocess
        import sys
        ckpt = str(tmp_path / "mcpaxos.ckpt")
        d = os.path.join(REFERENCE, "examples/Paxos")
        base = [sys.executable, "-m", "jaxmc", "check",
                os.path.join(d, "MCPaxos.tla"),
                "--cfg", os.path.join(d, "MCPaxos.cfg")]
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__)))}
        r1 = subprocess.run(base + ["--max-states", "10", "--checkpoint",
                                    ckpt, "--checkpoint-every", "0"],
                            capture_output=True, text=True, env=env)
        assert "TRUNCATED" in r1.stdout, r1.stdout + r1.stderr
        r2 = subprocess.run(base + ["--resume", ckpt],
                            capture_output=True, text=True, env=env)
        # exact full-run counts (the pinned unresumed run: 82/25)
        assert "82 states generated, 25 distinct" in r2.stdout, \
            r2.stdout + r2.stderr
        assert "No error has been found" in r2.stdout


class TestSimulate:
    def test_simulate_finds_assert(self):
        from jaxmc.engine.simulate import random_walks
        model = bind_model(
            Loader([]).load_path(os.path.join(SPECS, "pcal_intro_buggy.tla")),
            ModelConfig(specification="Spec"))
        v = random_walks(model, n_walks=80, depth=12, seed=3,
                         check_invariants=True)
        assert v is not None and v.kind == "assert"

    @needs_reference
    def test_simulate_clean_spec_passes(self):
        from jaxmc.engine.simulate import random_walks
        cfg = parse_cfg(open(os.path.join(REFERENCE, "pcal_intro.cfg")).read())
        model = bind_model(
            Loader([]).load_path(os.path.join(REFERENCE, "pcal_intro.tla")),
            cfg)
        v = random_walks(model, n_walks=25, depth=15, seed=1,
                         check_invariants=True)
        assert v is None


class TestSymmetry:
    SYMM = """---- MODULE symm ----
EXTENDS Naturals, FiniteSets, TLC
CONSTANTS Proc
VARIABLE x
Init == x = [p \\in Proc |-> 0]
Bump(p) == x[p] < 2 /\\ x' = [x EXCEPT ![p] = x[p] + 1]
Next == \\E p \\in Proc : Bump(p)
Sym == Permutations(Proc)
====
"""

    def _model(self, symmetry):
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".tla",
                                         delete=False) as f:
            f.write(self.SYMM)
            p = f.name
        cfg = ModelConfig(init="Init", next="Next", check_deadlock=False,
                          symmetry=symmetry)
        cfg.constants["Proc"] = frozenset(
            {CfgModelValue("p1"), CfgModelValue("p2")})
        m = bind_model(Loader([]).load_path(p), cfg)
        os.unlink(p)
        return m

    def test_symmetry_collapses_orbit(self):
        # 3x3 counter grid collapses to unordered pairs under p1<->p2
        r_full = Explorer(self._model(None)).run()
        r_sym = Explorer(self._model("Sym")).run()
        assert r_full.distinct == 9
        assert r_sym.distinct == 6

    @needs_reference
    def test_mcpaxos_symmetry_cfg_unchanged(self):
        # MCPaxos's SYMMETRY over singleton sets is the identity
        d = os.path.join(REFERENCE, "examples/Paxos")
        cfg = parse_cfg(open(os.path.join(d, "MCPaxos.cfg")).read())
        r = run_spec(os.path.join(d, "MCPaxos.tla"), cfg)
        assert r.ok and r.distinct == 25


VIEWTOY = """---- MODULE viewtoy ----
EXTENDS Naturals
VARIABLES x, noise
Init == x = 0 /\\ noise = 0
Next == x' = (x + 1) % 3 /\\ noise' = 1 - noise
Spec == Init /\\ [][Next]_<<x, noise>>
MyView == x
ParamView(y) == y
AlwaysX1 == []<>(x = 1)
TypeInv == x \\in 0..2 /\\ noise \\in 0..1
====
"""


class TestView:
    """cfg VIEW (ConfigFileGrammar.tla:8-11; VERDICT r2 #8): states
    deduplicate by the view expression's VALUE — implemented on the
    interp and, since ISSUE 6, compiled on the jax backends (the dedup
    keys on the view's value lanes)."""

    def _model(self, tmp_path, with_view):
        spec = tmp_path / "viewtoy.tla"
        spec.write_text(VIEWTOY)
        cfg = parse_cfg("SPECIFICATION Spec\nINVARIANT TypeInv\n"
                        + ("VIEW MyView\n" if with_view else "")
                        + "CHECK_DEADLOCK FALSE\n")
        m = Loader([str(tmp_path)]).load_path(str(spec))
        return bind_model(m, cfg)

    def test_view_collapses_state_space(self, tmp_path):
        r_full = Explorer(self._model(tmp_path, False)).run()
        r_view = Explorer(self._model(tmp_path, True)).run()
        assert r_full.ok and r_view.ok
        # without VIEW: (x, noise) pairs; with VIEW x: one state per x
        assert r_full.distinct == 6
        assert r_view.distinct == 3

    def test_view_compiles_on_jax_backend(self, tmp_path):
        # ISSUE 6: cfg VIEW compiles — the device dedup keys on the
        # view's value lanes, matching the interp's collapsed counts
        from jaxmc.tpu.bfs import TpuExplorer
        ri = Explorer(self._model(tmp_path, True)).run()
        ex = TpuExplorer(self._model(tmp_path, True), store_trace=True)
        assert ex.view_fn is not None
        r = ex.run()
        assert (r.generated, r.distinct, r.ok) == \
            (ri.generated, ri.distinct, ri.ok)
        assert r.distinct == 3  # one state per value of x

    def test_parameterized_view_rejected_at_bind(self, tmp_path):
        # TLC rejects parameterized views at config time; we must too
        # (review r3: it otherwise crashes on the unhashable closure)
        from jaxmc.sem.eval import EvalError
        spec = tmp_path / "viewtoy.tla"
        spec.write_text(VIEWTOY)
        cfg = parse_cfg("SPECIFICATION Spec\nVIEW ParamView\n")
        with pytest.raises(EvalError, match="parameters"):
            bind_model(Loader([str(tmp_path)]).load_path(str(spec)), cfg)

    def test_view_with_liveness_warns_not_checked(self, tmp_path):
        # liveness over the view-collapsed graph would be WRONG (false
        # violations reproduced in review r3); the obligations must be
        # dropped with an explicit warning, and no bogus violation
        spec = tmp_path / "viewtoy.tla"
        spec.write_text(VIEWTOY)
        cfg = parse_cfg("SPECIFICATION Spec\nPROPERTY AlwaysX1\n"
                        "VIEW MyView\nCHECK_DEADLOCK FALSE\n")
        m = Loader([str(tmp_path)]).load_path(str(spec))
        r = Explorer(bind_model(m, cfg)).run()
        assert r.ok
        assert any("VIEW" in w and "NOT checked" in w for w in r.warnings)

    def test_unknown_view_name_errors(self, tmp_path):
        from jaxmc.sem.eval import EvalError
        spec = tmp_path / "viewtoy.tla"
        spec.write_text(VIEWTOY)
        cfg = parse_cfg("SPECIFICATION Spec\nVIEW NoSuchDef\n")
        with pytest.raises(EvalError, match="NoSuchDef"):
            bind_model(Loader([str(tmp_path)]).load_path(str(spec)), cfg)


def test_bool_int_set_mix_raises():
    # TLC comparability semantics: {TRUE, 1} is an error, not a
    # 1-element set (the True == 1 deviation documented in sem/values.py)
    from jaxmc.sem.eval import EvalError
    ctx = Ctx({})
    with pytest.raises(EvalError, match="BOOLEAN and integer"):
        eval_expr(parse_expr_text("{TRUE, 1}"), ctx)
    # homogeneous sets still work
    assert eval_expr(parse_expr_text("{TRUE, FALSE}"), ctx) == \
        frozenset({True, False})
    assert eval_expr(parse_expr_text("{0, 1}"), ctx) == frozenset({0, 1})


def test_bool_int_setop_operand_mix_raises():
    # advisor r3: \cap and \ operand mixes must raise like \cup does —
    # {TRUE} \cap {1} is a comparability error in TLC, not {1}
    from jaxmc.sem.eval import EvalError
    for src in (r"{TRUE} \cap {1}", r"{TRUE} \ {1}", r"{1} \cap {TRUE}",
                r"{FALSE} \cup {0}"):
        with pytest.raises(EvalError, match="BOOLEAN and integer"):
            ev(src)
    # disjoint same-kind operands still fine
    assert ev(r"{TRUE} \cap {FALSE}") == frozenset()
    assert ev(r"{1} \ {0}") == frozenset({1})


def test_nested_bool_int_collapse_raises():
    # r4: NESTED True==1 conflations raise instead of silently collapsing
    # ({{TRUE}, {1}} used to dedup to a 1-element set; TLC raises when it
    # compares the inner TRUE with 1)
    from jaxmc.sem.eval import EvalError
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("{{TRUE}, {1}}")
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("{{0}, {FALSE}}")
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("{{TRUE}} = {{1}}")
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("<<TRUE>> = <<1>>")
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("{1} \\in {{TRUE}}")
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        ev("[a |-> TRUE] = [a |-> 1]")
    # no false positives: genuinely equal / unequal nested values
    assert ev("{{TRUE}} = {{TRUE}}") is True
    assert ev("{{1}} = {{1}}") is True
    assert ev("{{TRUE}, {FALSE}} = {{FALSE}, {TRUE}}") is True
    assert ev("<<1, TRUE>> = <<1, TRUE>>") is True
    assert ev("{1} \\in {{1}, {2}}") is True
    assert ev("Cardinality({{0}, {1}})") == 2


def test_recfcn_bool_collapse_detected():
    # r5 regression (code-review find): the _has_bool cache must force a
    # lazy RecFcn before scanning — probing membership FIRST (which scans
    # the then-empty memo dict) must not cache a stale False that lets a
    # later TRUE-vs-1 equality slip through silently
    from jaxmc.sem.eval import RecFcn
    from jaxmc.sem.values import tla_eq, in_set, Fcn, EvalError
    f = RecFcn([1], lambda a: True)  # f = [x \in {1} |-> TRUE], lazy
    in_set(f, frozenset({Fcn({1: 2})}))  # scans f before it is forced
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        tla_eq(f, Fcn({1: 1}))
    g = RecFcn([1], lambda a: True)
    with pytest.raises(EvalError, match="BOOLEAN vs integer"):
        tla_eq(g, Fcn({1: 1}))
