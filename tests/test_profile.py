r"""Learned capacity profiles (ISSUE 6, compile/cache.py): a completed
resident run persists its capacity buckets next to the compile cache;
the next engine on the same (module, layout) starts there, so its one
warm-up compile covers the whole run and the timed window records ZERO
recompiles.  Stale/foreign profiles degrade to the overflow-growth path
with a named reason — never a wrong-capacity crash.
"""

import json
import os

import pytest

from conftest import REFERENCE  # noqa: F401

from jaxmc.front.cfg import parse_cfg
from jaxmc.sem.modules import Loader, bind_model
from jaxmc import obs

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")


def load_model():
    return bind_model(
        Loader([SPECS]).load_path(os.path.join(SPECS, "constoy.tla")),
        parse_cfg(open(os.path.join(SPECS, "constoy.cfg")).read()))


@pytest.fixture()
def store(tmp_path, monkeypatch):
    d = str(tmp_path / "profiles")
    monkeypatch.setenv("JAXMC_PROFILE_STORE", d)
    monkeypatch.delenv("JAXMC_CAP_PROFILE", raising=False)
    return d


def _run_resident(tel=None, **kw):
    from jaxmc.tpu.bfs import TpuExplorer
    with obs.use(tel or obs.NullTelemetry()):
        ex = TpuExplorer(load_model(), store_trace=False, resident=True,
                         **kw)
        r = ex.run()
    return ex, r


def test_profile_saved_and_drives_zero_window_recompiles(store):
    # run 1: no profile — overflow-growth trains the caps, completion
    # persists them
    tel1 = obs.Telemetry()
    ex1, r1 = _run_resident(tel1)
    assert r1.ok
    assert tel1.gauges.get("profile.status") == "saved"
    files = os.listdir(store)
    assert len(files) == 1 and files[0].endswith(".json")

    # run 2: a FRESH engine (new process in the bench flow) loads the
    # profile; after its one warm-up run, a timed re-run must report
    # zero fresh compiles — the window_recompiles == 0 contract
    tel2 = obs.Telemetry()
    from jaxmc.tpu.bfs import TpuExplorer
    with obs.use(tel2):
        ex2 = TpuExplorer(load_model(), store_trace=False, resident=True)
        assert tel2.gauges.get("profile.status") == "loaded"
        assert ex2._res_caps_hint, "profile caps must hint the engine"
        rw = ex2.run()              # warm-up (the one compile)
        tel2.reset_levels("timed")
        rt = ex2.run()              # timed window
    assert rw.ok and rt.ok
    assert (rt.generated, rt.distinct) == (r1.generated, r1.distinct)
    window_recompiles = sum(1 for lv in tel2.levels
                            if lv.get("fresh_compile"))
    assert window_recompiles == 0, \
        f"profile failed to prevent in-window recompiles: {tel2.levels}"


def test_stale_profile_degrades_with_named_reason(store):
    tel1 = obs.Telemetry()
    _ex, r = _run_resident(tel1)
    assert r.ok
    path = os.path.join(store, os.listdir(store)[0])
    p = json.load(open(path))
    p["layout_sig"] = "0" * 16
    json.dump(p, open(path, "w"))
    tel2 = obs.Telemetry()
    ex2, r2 = _run_resident(tel2)
    assert r2.ok, "a stale profile must never fail the run"
    # the degrade is counted; the final status gauge reads "saved"
    # because the completed run re-persisted a fresh profile
    assert tel2.counters.get("profile.degrades", 0) >= 1
    assert (r2.generated, r2.distinct) == (r.generated, r.distinct)


def test_foreign_schema_and_garbage_degrade(store):
    tel1 = obs.Telemetry()
    _ex, r = _run_resident(tel1)
    path = os.path.join(store, os.listdir(store)[0])
    # foreign schema
    p = json.load(open(path))
    p["schema"] = "somebody.else/9"
    json.dump(p, open(path, "w"))
    from jaxmc.compile.cache import load_capacity_profile
    # single-chip resident profiles live under the backend-platform
    # namespace since ISSUE 11 (variant "cpu" on this box): the load
    # must name the same variant the engine saved
    variant = p.get("variant", "")
    tel = obs.Telemetry()
    assert load_capacity_profile("constoy", p["layout_sig"],
                                 tel=tel, variant=variant) is None
    assert str(tel.gauges.get("profile.status")).startswith(
        "degraded:foreign schema")
    _ex2, r2 = _run_resident(obs.Telemetry())
    assert r2.ok, "a foreign profile must never fail the run"
    # unreadable garbage
    with open(path, "w") as fh:
        fh.write("{not json")
    tel = obs.Telemetry()
    assert load_capacity_profile("constoy", p["layout_sig"],
                                 tel=tel, variant=variant) is None
    assert str(tel.gauges.get("profile.status")).startswith(
        "degraded:unreadable")


def test_profile_opt_out(store, monkeypatch):
    monkeypatch.setenv("JAXMC_CAP_PROFILE", "0")
    tel = obs.Telemetry()
    _ex, r = _run_resident(tel)
    assert r.ok
    assert not os.path.isdir(store) or not os.listdir(store)


def test_malformed_caps_degrade(store):
    from jaxmc.compile.cache import load_capacity_profile, \
        profile_path, _PROFILE_SCHEMA
    os.makedirs(store, exist_ok=True)
    path = profile_path("constoy", "x" * 16)
    json.dump({"schema": _PROFILE_SCHEMA, "module": "constoy",
               "layout_sig": "x" * 16,
               "caps": {"SC": -5, "FCap": 1, "AccCap": 1, "VC": 1}},
              open(path, "w"))
    tel = obs.Telemetry()
    with obs.use(tel):
        assert load_capacity_profile("constoy", "x" * 16, tel=tel) is None
    assert str(tel.gauges.get("profile.status")).startswith(
        "degraded:malformed caps")
