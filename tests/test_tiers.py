r"""Out-of-core hierarchical seen set (ISSUE 12): device -> host -> disk
tiered rank-merge + fingerprint-only mode.

Pins, on repo-local models only (no reference corpus needed):
  * backend/tiers.py unit contract: `_np_rank_merge` is a set-union of
    sorted runs (vs a tuple-set oracle, negative words included),
    `_keyview` maps signed row order onto unsigned byte order, spill /
    host-compaction / disk-flush / LSM disk compaction preserve exact
    membership, and `dump`/`load` round-trips the whole hierarchy;
  * a failed disk write (the `tier_io_error` fault site, or ENOSPC)
    DEGRADES the store to host-tier-only with the named
    `tier.io_degraded` event — counts stay exact, nothing crashes;
    an unreadable run mid-search (wrong counts, not a degraded mode)
    raises instead;
  * the capped engine run on specs/ooc_scaled.tla (device seen table
    forced to ~17% of the state count, host budget forcing the disk
    tier) completes EXHAUSTIVELY with counts bit-identical to the
    manifest pins, on the single-chip level mode AND the mesh-resident
    loop (per-shard tiering, D=2);
  * truncation results name the exhausted resource (trunc_reason) on
    the serial and device engines;
  * --seen fingerprint parity against the manifest pins on EVERY
    repo-local rung (bench-scale rungs marked slow), with the
    collision-probability bound reported in the result; --seen exact
    refuses modes that cannot honor it;
  * chaos (mid-spill robustness, `-m chaos`): SIGKILL + resume and a
    SIGTERM drain + resume both land bit-identical to the clean capped
    run — the checkpoint carries the full tier hierarchy.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from jaxmc import faults, obs
from jaxmc.backend.tiers import TieredSeen, _keyview, _np_rank_merge
from jaxmc.front.cfg import ModelConfig, parse_cfg
from jaxmc.sem.modules import Loader, bind_model

SPECS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs")
REPO = os.path.dirname(SPECS)

#: the ooc_scaled fixture's manifest pins (jaxmc/corpus.py)
OOC_WANT = (12289, 3072)
#: ~17% of the rung's 3072 states — the acceptance cap (<= 25%)
OOC_CAP = 512
#: host-tier key budget small enough that the capped run hits disk
OOC_HOST_KEYS = 1024


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    # per-test capacity-profile store + no ambient tier/fault knobs
    monkeypatch.setenv("JAXMC_PROFILE_STORE", str(tmp_path / "prof"))
    for k in ("JAXMC_SEEN_CAP", "JAXMC_TIER_HOST_KEYS",
              "JAXMC_SPILL_DIR", "JAXMC_FAULTS", "JAXMC_FAULTS_STATE"):
        monkeypatch.delenv(k, raising=False)
    faults._CACHE = None
    yield
    faults._CACHE = None


def load(name, cfg_name=None, no_deadlock=False):
    m = Loader([SPECS]).load_path(os.path.join(SPECS, name + ".tla"))
    cfgp = os.path.join(SPECS, (cfg_name or name) + ".cfg")
    if os.path.exists(cfgp):
        cfg = parse_cfg(open(cfgp).read())
    else:
        cfg = ModelConfig(specification="Spec")
    if no_deadlock:
        cfg.check_deadlock = False
    return bind_model(m, cfg)


def _sorted_rows(rows):
    a = np.asarray(rows, np.int32)
    return a[np.argsort(_keyview(a))]


def _rand_runs(rng, n_a, n_b, kd=3, lo=-(1 << 30), hi=1 << 30):
    a = np.unique(rng.integers(lo, hi, (n_a, kd), dtype=np.int64)
                  .astype(np.int32), axis=0)
    b = np.unique(rng.integers(lo, hi, (n_b, kd), dtype=np.int64)
                  .astype(np.int32), axis=0)
    # force overlap so the dedup path is exercised
    if len(a) and len(b):
        k = min(len(a), len(b) // 3)
        b[:k] = a[:k]
    return _sorted_rows(a), _sorted_rows(np.unique(b, axis=0))


# ------------------------------------------------ numpy merge primitives

class TestRankMergePrimitives:
    def test_keyview_orders_signed_rows(self):
        rng = np.random.default_rng(7)
        rows = rng.integers(-(1 << 31), 1 << 31, (500, 4),
                            dtype=np.int64).astype(np.int32)
        rows[:4] = [[-(1 << 31), 0, 0, 0], [(1 << 31) - 1, 0, 0, 0],
                    [0, -1, 5, 5], [0, 1, -5, -5]]
        got = np.argsort(_keyview(rows), kind="stable")
        want = np.lexsort(rows[:, ::-1].T)  # signed lexicographic
        assert np.array_equal(rows[got], rows[want])

    def test_rank_merge_is_sorted_set_union(self):
        rng = np.random.default_rng(11)
        for n_a, n_b in ((0, 9), (9, 0), (1, 1), (64, 17), (33, 400)):
            a, b = _rand_runs(rng, n_a, n_b)
            m = _np_rank_merge(a, b)
            want = {tuple(r) for r in a} | {tuple(r) for r in b}
            assert {tuple(r) for r in m} == want
            assert len(m) == len(want), "merged run kept a duplicate"
            assert np.array_equal(m, _sorted_rows(m)), "merge unsorted"

    def test_rank_merge_idempotent(self):
        rng = np.random.default_rng(3)
        a, _ = _rand_runs(rng, 80, 0)
        assert np.array_equal(_np_rank_merge(a, a), a)


# ------------------------------------------------ TieredSeen unit layer

class TestTieredSeen:
    KD = 3

    def _store(self, tmp_path, budget=10 ** 9):
        return TieredSeen(self.KD, host_budget_keys=budget,
                          spill_dir=str(tmp_path / "spill"))

    def test_spill_probe_membership(self, tmp_path):
        rng = np.random.default_rng(5)
        a, b = _rand_runs(rng, 200, 150, kd=self.KD)
        t = self._store(tmp_path)
        assert not t.active and len(t) == 0
        t.spill(a)
        t.spill(b)
        assert t.active
        inside = np.vstack([a[::7], b[::5]])
        outside = _sorted_rows(rng.integers(1 << 30, (1 << 31) - 1,
                                            (40, self.KD),
                                            dtype=np.int64)
                               .astype(np.int32))
        hits = t.probe(np.vstack([inside, outside]))
        assert hits[: len(inside)].all()
        assert not hits[len(inside):].any()
        assert t.probe(np.zeros((0, self.KD), np.int32)).shape == (0,)

    def test_host_compaction_fan_in(self, tmp_path):
        rng = np.random.default_rng(9)
        t = self._store(tmp_path)
        all_rows = []
        for _ in range(TieredSeen.MAX_HOST_RUNS + 1):
            r, _ = _rand_runs(rng, 60, 0, kd=self.KD)
            t.spill(r)
            all_rows.append(r)
        assert len(t.host_runs) == 1, "fan-in must compact to one run"
        assert t.compactions >= 1
        every = np.unique(np.vstack(all_rows), axis=0)
        assert t.probe(every).all()
        assert len(t) == len(every)

    def test_disk_flush_and_lsm_compaction(self, tmp_path):
        rng = np.random.default_rng(13)
        t = self._store(tmp_path, budget=64)
        all_rows = []
        for _ in range(TieredSeen.MAX_DISK_RUNS + 2):
            r, _ = _rand_runs(rng, 80, 0, kd=self.KD)
            t.spill(r)  # each spill overflows the 64-key host budget
            all_rows.append(r)
        assert t.disk_keys > 0
        assert len(t.disk_runs) <= TieredSeen.MAX_DISK_RUNS, \
            "disk fan-in never compacted"
        for p in t.disk_runs:
            assert os.path.exists(p) and p.endswith(".npy")
        leftover = [f for f in os.listdir(t.spill_dir)
                    if f.endswith(".npy")]
        assert sorted(leftover) == sorted(
            os.path.basename(p) for p in t.disk_runs), \
            "compaction left dead run files behind"
        every = np.unique(np.vstack(all_rows), axis=0)
        assert t.probe(every).all()
        assert len(t) == len(every)
        assert t.stats()["probe_wall_s"] >= 0

    def test_dump_load_roundtrip(self, tmp_path):
        rng = np.random.default_rng(17)
        t = self._store(tmp_path, budget=64)
        rows = []
        for _ in range(3):
            r, _ = _rand_runs(rng, 70, 0, kd=self.KD)
            t.spill(r)
            rows.append(r)
        assert t.disk_keys > 0 and t.host_keys >= 0
        payload = t.dump()
        t2 = TieredSeen(self.KD, host_budget_keys=64,
                        spill_dir=str(tmp_path / "other"))
        t2.load(payload)
        every = np.unique(np.vstack(rows), axis=0)
        assert t2.probe(every).all()
        assert len(t2) == len(t)
        t3 = TieredSeen(self.KD + 1)
        with pytest.raises(ValueError, match="key_words"):
            t3.load(payload)

    def test_ckpt_path_mode_past_inline_budget(self, tmp_path,
                                               monkeypatch):
        # a disk tier past JAXMC_TIER_CKPT_INLINE_KEYS rides the
        # checkpoint as run-file PATHS (O(host) payload); load
        # re-opens and validates them, and a vanished spill dir is a
        # NAMED error, not a silent wrong count
        monkeypatch.setenv("JAXMC_TIER_CKPT_INLINE_KEYS", "1")
        rng = np.random.default_rng(29)
        t = self._store(tmp_path, budget=32)
        rows = []
        for _ in range(3):
            r, _ = _rand_runs(rng, 60, 0, kd=self.KD)
            t.spill(r)
            rows.append(r)
        assert t.disk_keys > 1
        payload = t.dump()
        assert "disk_paths" in payload and "disk" not in payload
        t2 = TieredSeen(self.KD, host_budget_keys=32)
        t2.load(payload)
        every = np.unique(np.vstack(rows), axis=0)
        assert t2.probe(every).all()
        assert len(t2) == len(t)
        for p in payload["disk_paths"]:
            os.unlink(p)
        t3 = TieredSeen(self.KD, host_budget_keys=32)
        with pytest.raises(ValueError, match="spill directory"):
            t3.load(payload)

    def test_compaction_preserves_ckpt_referenced_runs(self, tmp_path,
                                                       monkeypatch):
        # a path-mode checkpoint must survive later LSM compactions:
        # referenced run files are retired, not unlinked, until a
        # newer dump supersedes them
        monkeypatch.setenv("JAXMC_TIER_CKPT_INLINE_KEYS", "1")
        rng = np.random.default_rng(31)
        t = self._store(tmp_path, budget=32)
        early = []
        for _ in range(3):
            r, _ = _rand_runs(rng, 60, 0, kd=self.KD)
            t.spill(r)
            early.append(r)
        p1 = t.dump()
        assert "disk_paths" in p1
        late = []
        for _ in range(TieredSeen.MAX_DISK_RUNS):
            r, _ = _rand_runs(rng, 60, 0, kd=self.KD)
            t.spill(r)
            late.append(r)
        assert t.compactions >= 1
        for p in p1["disk_paths"]:
            assert os.path.exists(p), \
                "compaction unlinked a checkpoint's only copy"
        t_old = TieredSeen(self.KD, host_budget_keys=32)
        t_old.load(p1)
        assert t_old.probe(np.unique(np.vstack(early), axis=0)).all()
        # live store still answers for everything
        every = np.unique(np.vstack(early + late), axis=0)
        assert t.probe(every).all() and len(t) == len(every)
        # a newer dump supersedes the old references: retired files go
        p2 = t.dump()
        gone = [p for p in p1["disk_paths"]
                if p not in p2.get("disk_paths", [])]
        assert gone and all(not os.path.exists(p) for p in gone)

    def test_spill_shape_mismatch_rejected(self, tmp_path):
        t = self._store(tmp_path)
        with pytest.raises(ValueError, match="key_words"):
            t.spill(np.zeros((4, self.KD + 2), np.int32))

    def test_io_error_degrades_to_host_only(self, tmp_path,
                                            monkeypatch):
        # the tier_io_error fault site: a failed disk write must leave
        # a host-tier-only store with exact membership and the named
        # event — never a crash
        monkeypatch.setenv("JAXMC_FAULTS", "tier_io_error:op=write")
        faults._CACHE = None
        rng = np.random.default_rng(19)
        tel = obs.Telemetry()
        with obs.use_local(tel):
            t = self._store(tmp_path, budget=32)
            rows = []
            for _ in range(3):
                r, _ = _rand_runs(rng, 50, 0, kd=self.KD)
                t.spill(r)  # overflows the budget -> flush -> fault
                rows.append(r)
        assert t.io_degraded and "tier_io_error" in t.io_degraded
        assert t.disk_keys == 0 and not t.disk_runs
        assert "io_degraded" in t.stats()
        assert "tier.io_degraded" in tel.gauges
        every = np.unique(np.vstack(rows), axis=0)
        assert t.probe(every).all(), "degraded store lost keys"
        assert len(t) == len(every)

    def test_unreadable_disk_run_raises(self, tmp_path):
        rng = np.random.default_rng(23)
        t = self._store(tmp_path, budget=32)
        r, _ = _rand_runs(rng, 60, 0, kd=self.KD)
        t.spill(r)
        assert t.disk_runs
        os.unlink(t.disk_runs[0])
        with pytest.raises(RuntimeError, match="unreadable"):
            t.probe(r[:5])


# ------------------------------------------------ capped engine parity

def _capped_kw(tmp_path, cap=OOC_CAP, host=OOC_HOST_KEYS):
    return dict(seen_cap=cap, host_tier_keys=host,
                spill_dir=str(tmp_path / "spill"))


class TestCappedExhaustive:
    def test_level_mode_spills_both_tiers_exact(self, tmp_path):
        # the acceptance run: device table capped at ~17% of the state
        # count, host budget forcing disk — the search must complete
        # exhaustively (no truncation) with the manifest pins
        from jaxmc.backend.bfs import TpuExplorer
        res = TpuExplorer(load("ooc_scaled"),
                          **_capped_kw(tmp_path)).run()
        assert res.ok and not res.truncated
        assert (res.generated, res.distinct) == OOC_WANT
        assert res.seen_mode == "exact"
        assert res.tiers and res.tiers["spills"] > 0
        assert res.tiers["disk_keys"] > 0, "disk tier never exercised"
        assert res.tiers["probe_wall_s"] >= 0

    def test_resident_mode_spills_both_tiers_exact(self, tmp_path):
        # the resident loop's spill path: cap overflow rolls the level
        # back, compacts the sorted prefix out, and redoes the level
        # against an empty table — exhaustive at the manifest pins
        from jaxmc.backend.bfs import TpuExplorer
        res = TpuExplorer(load("ooc_scaled"), resident=True,
                          chunk=256, **_capped_kw(tmp_path)).run()
        assert res.ok and not res.truncated
        assert (res.generated, res.distinct) == OOC_WANT
        assert res.tiers and res.tiers["spills"] > 0
        assert res.tiers["disk_keys"] > 0, "disk tier never exercised"

    def test_mesh_per_shard_tiering_exact(self, tmp_path):
        # per-shard device caps on the mesh-resident loop (D=2):
        # owner-routed keys partition the space, one combined cold
        # store answers membership for every shard
        import jax
        from jax.sharding import Mesh
        from jaxmc.backend.mesh import MeshExplorer
        me = MeshExplorer(load("ooc_scaled"),
                          mesh=Mesh(np.array(jax.devices()[:2]),
                                    ("d",)),
                          **_capped_kw(tmp_path, cap=2 * OOC_CAP))
        res = me.run()  # resident loop: no PROPERTYs/refiners here
        assert res.ok and not res.truncated
        assert (res.generated, res.distinct) == OOC_WANT
        assert res.tiers and res.tiers["spills"] > 0

    def test_engine_io_degrade_keeps_exact_counts(self, tmp_path,
                                                  monkeypatch):
        # end-to-end fault containment: the disk tier dies mid-search,
        # the run degrades to host-tier-only and still lands the pins
        monkeypatch.setenv("JAXMC_FAULTS", "tier_io_error:op=write")
        faults._CACHE = None
        from jaxmc.backend.bfs import TpuExplorer
        res = TpuExplorer(load("ooc_scaled"),
                          **_capped_kw(tmp_path)).run()
        assert res.ok and not res.truncated
        assert (res.generated, res.distinct) == OOC_WANT
        assert res.tiers and res.tiers.get("io_degraded")
        assert res.tiers["disk_keys"] == 0


class TestTruncationAttribution:
    def test_serial_names_max_states(self):
        from jaxmc.engine.explore import Explorer
        res = Explorer(load("constoy"), max_states=5).run()
        assert res.truncated
        assert res.trunc_reason and \
            res.trunc_reason.startswith("max_states")

    def test_device_names_max_states(self, tmp_path):
        from jaxmc.backend.bfs import TpuExplorer
        res = TpuExplorer(load("ooc_scaled"), max_states=500,
                          **_capped_kw(tmp_path)).run()
        assert res.truncated
        assert res.trunc_reason and \
            res.trunc_reason.startswith("max_states")

    def test_complete_run_carries_no_reason(self):
        from jaxmc.engine.explore import Explorer
        res = Explorer(load("constoy")).run()
        assert not res.truncated and res.trunc_reason is None


# ------------------------------------------------ fingerprint-only mode

def _fp_params():
    from jaxmc.corpus import CASES
    out = []
    for c in CASES:
        if c.root != "repo" or c.jax != "yes" or c.expect != "ok" \
                or c.distinct is None or getattr(c, "lint_only", False):
            continue
        marks = []
        if c.slow or (c.generated or 0) > 20000:
            marks.append(pytest.mark.slow)  # bench-scale rungs
        out.append(pytest.param(
            c, id=os.path.basename(c.cfg or c.spec), marks=marks))
    return out


class TestFingerprintMode:
    @pytest.mark.parametrize("case", _fp_params())
    def test_parity_on_repo_rung(self, case):
        # --seen fingerprint must land the exact manifest pins on
        # every repo-local rung and report its collision bound
        for d in case.include_dirs():
            if not os.path.isdir(d):
                pytest.skip(f"needs the reference corpus ({d})")
        from jaxmc.backend.bfs import TpuExplorer
        from jaxmc.compile.vspec import Bounds
        cfg = parse_cfg(open(case.cfg_path()).read())
        if case.no_deadlock:
            cfg.check_deadlock = False
        spec = case.spec_path()
        model = bind_model(
            Loader([os.path.dirname(spec)]
                   + case.include_dirs()).load_path(spec), cfg)
        b = Bounds()
        for k in ("seq_cap", "grow_cap", "kv_cap"):
            if getattr(case, k, None):
                setattr(b, k, getattr(case, k))
        from jaxmc.compile.vspec import ModeError
        try:
            res = TpuExplorer(model, bounds=b,
                              seen_mode="fingerprint").run()
        except ModeError as ex:
            # hybrid-by-construction rungs run in host_seen mode (the
            # same ladder run_case uses)
            if "hybrid" not in str(ex):
                raise
            from jaxmc import native_store
            if not native_store.is_available():
                pytest.skip("hybrid rung needs the native store")
            res = TpuExplorer(model, bounds=b, host_seen=True,
                              seen_mode="fingerprint").run()
        assert res.ok, res.warnings
        assert (res.generated, res.distinct) == \
            (case.generated, case.distinct)
        assert res.seen_mode == "fingerprint"
        # the bound covers every ADMITTED key (constraint-discarded
        # states hold keys too), so it sits between distinct^2 and
        # (generated + distinct)^2 over 2^129
        assert res.collision_p is not None
        lo = res.distinct ** 2 * 2.0 ** -129
        hi = (res.generated + res.distinct) ** 2 * 2.0 ** -129
        assert lo * 0.999 <= res.collision_p <= hi * 1.001

    def test_exact_refuses_fp_only_modes(self):
        from jaxmc.backend.bfs import TpuExplorer
        from jaxmc.compile.vspec import ModeError
        with pytest.raises(ModeError, match="resident"):
            TpuExplorer(load("constoy"), resident=True,
                        seen_mode="exact")

    def test_exact_refused_on_mesh(self):
        # mesh seen shards are fingerprint-based: --seen exact must
        # refuse, not silently fingerprint past the contract
        from jaxmc.backend.mesh import MeshExplorer
        from jaxmc.compile.vspec import ModeError
        with pytest.raises(ModeError, match="mesh"):
            MeshExplorer(load("constoy"), seen_mode="exact")

    def test_unknown_mode_rejected(self):
        from jaxmc.backend.bfs import TpuExplorer
        from jaxmc.compile.vspec import ModeError
        with pytest.raises(ModeError, match="unknown --seen"):
            TpuExplorer(load("constoy"), seen_mode="sketchy")


# ------------------------------------------------ obs diff attribution

class TestObsDiffIoDegrade:
    def _artifact(self, path, degraded):
        tel = obs.Telemetry()
        tel.level(0, frontier=1, generated=100, wall_s=1.0)
        tel.set_meta(backend="jax", spec="specs/ooc_scaled.tla",
                     env={"jax_version": "0", "platform": "cpu",
                          "device_count": 1})
        if degraded:
            tel.gauge("tier.io_degraded", "tier_io_error: op=write")
        tel.write_metrics(str(path), result={
            "ok": True, "distinct": 50, "generated": 100,
            "diameter": 3, "truncated": False, "wall_s": 1.0})
        return str(path)

    def test_io_degrade_appearance_flagged(self, tmp_path):
        import io as _io
        from jaxmc.obs import report
        good = self._artifact(tmp_path / "a.json", degraded=False)
        bad = self._artifact(tmp_path / "b.json", degraded=True)
        out = _io.StringIO()
        rc = report.main(["diff", good, bad, "--fail-on-regress"],
                         out=out)
        assert rc == 1
        assert "REGRESS tier io degradation" in out.getvalue()
        out = _io.StringIO()
        rc = report.main(["diff", bad, bad, "--fail-on-regress"],
                         out=out)
        assert rc == 0, "a standing degradation must not re-flag"


# ------------------------------------------------ chaos: mid-spill

def _cli(args, env_extra, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    return subprocess.run(
        [sys.executable, "-m", "jaxmc", "check"] + args,
        capture_output=True, text=True, cwd=REPO, env=env,
        timeout=timeout)


def _counts(stdout):
    for line in reversed(stdout.splitlines()):
        if "states generated," in line and "distinct states found" in \
                line and "states/sec" in line:
            parts = line.split()
            return int(parts[0]), int(parts[3])
    raise AssertionError(f"no summary line in:\n{stdout}")


_OOC_ARGS = [os.path.join(SPECS, "ooc_scaled.tla"),
             "--backend", "jax", "--platform", "cpu"]


def _capped_env(tmp_path):
    return {"JAXMC_SEEN_CAP": str(OOC_CAP),
            "JAXMC_TIER_HOST_KEYS": str(OOC_HOST_KEYS),
            "JAXMC_SPILL_DIR": str(tmp_path / "spill"),
            "JAXMC_PROFILE_STORE": str(tmp_path / "prof")}


@pytest.mark.chaos
@pytest.mark.slow
class TestMidSpillChaos:
    """SIGKILL and SIGTERM-drain a capped run AFTER it has spilled,
    then resume: the checkpoint carries the full tier hierarchy, so
    the resumed totals must be bit-identical to the manifest pins."""

    def test_kill_resume_parity_mid_spill(self, tmp_path):
        env = _capped_env(tmp_path)
        ck = str(tmp_path / "ooc.ck")
        killed = _cli(_OOC_ARGS + ["--checkpoint", ck,
                                   "--checkpoint-every", "0"],
                      env_extra=dict(env,
                                     JAXMC_FAULTS="run_kill:level=10"))
        assert killed.returncode in (-9, 137), \
            (killed.returncode, killed.stderr[-500:])
        assert "tier:" in killed.stdout, \
            "the run was killed before any spill — not mid-spill"
        assert os.path.exists(ck), "no checkpoint survived the kill"
        resumed = _cli(_OOC_ARGS + ["--resume", ck], env_extra=env)
        assert resumed.returncode == 0, resumed.stderr[-500:]
        assert _counts(resumed.stdout) == OOC_WANT

    def test_sigterm_drain_resume_parity_mid_spill(self, tmp_path):
        env = _capped_env(tmp_path)
        ck = str(tmp_path / "drain.ck")
        p = subprocess.Popen(
            [sys.executable, "-m", "jaxmc", "check"] + _OOC_ARGS
            + ["--checkpoint", ck, "--checkpoint-every", "0"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu", **env))
        # the capped search runs ~8s after a ~4s compile; spills start
        # within the first levels — signal mid-search
        time.sleep(6.0)
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=120)
        if p.returncode == 0:
            pytest.skip("run finished before the signal landed "
                        "(box too fast for the fixed delay)")
        assert p.returncode == 143, (p.returncode, err[-500:])
        assert "drained" in err
        assert os.path.exists(ck)
        resumed = _cli(_OOC_ARGS + ["--resume", ck], env_extra=env)
        assert resumed.returncode == 0, resumed.stderr[-500:]
        assert _counts(resumed.stdout) == OOC_WANT
        # the drained run must have spilled before the signal, or this
        # proved nothing about mid-spill state
        if "tier:" not in out:
            pytest.skip("drain landed before the first spill")
