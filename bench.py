r"""jaxmc benchmark: states/sec of the device BFS backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": R}

Workload: exhaustive search of specs/transfer_scaled.tla (the README
money-transfer race generalized; raft 3-server is the round-2+ metric of
record per BASELINE.md). vs_baseline is the speedup over the exact Python
reference interpreter measured on the same machine — the stand-in for TLC,
which is not installable in this image (no JVM; BASELINE.md documents that
the TLC baseline must be measured where a JVM exists).

Runs on whatever accelerator jax selects (the driver runs this on one real
TPU chip); falls back to CPU if the TPU plugin fails to initialize.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(os.path.abspath(__file__))


def main():
    import jax
    try:
        devs = jax.devices()
        platform = devs[0].platform
    except Exception:
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        platform = "cpu (tpu init failed)"

    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer
    from __graft_entry__ import _load_flagship

    model = _load_flagship()

    # device backend with the native host fingerprint store when the
    # toolchain is available (faster and unbounded by device memory);
    # warm-up run compiles the jit cache, the timed run reuses it
    from jaxmc import native_store
    host_seen = native_store.is_available()
    ex = TpuExplorer(model, store_trace=False, host_seen=host_seen)
    r_warm = ex.run()
    t0 = time.time()
    r = ex.run()
    jax_wall = time.time() - t0
    assert r.ok and r.distinct == r_warm.distinct
    jax_rate = r.generated / jax_wall

    # interpreter baseline on a capped prefix (full run is minutes)
    ri = Explorer(model, max_states=20000).run()
    interp_rate = ri.generated / ri.wall_s

    out = {
        "metric": f"states/sec exhaustive transfer_scaled "
                  f"({r.distinct} distinct states, {platform}, "
                  f"{'native-store' if host_seen else 'device'} seen-set)",
        "value": round(jax_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(jax_rate / interp_rate, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
