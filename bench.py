r"""jaxmc benchmark: raft states/sec on the device BFS backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": R,
   "vs_tlc_estimate": R2}

Workload: the BASELINE.json model of record — the reference raft spec
(/root/reference/examples/raft.tla:482-493 hot path) with Server={s1,s2,s3}
and a bounded log, made finite by the MCraftMicro message-domain constraint
(specs/MCraft_3s_bench.cfg) so the EXHAUSTIVE search completes and the
reported rate covers a full run, not a truncated prefix.

vs_baseline is the speedup over this repo's exact Python interpreter on
the same workload (measured on a capped prefix, cap stated in the metric).
vs_tlc_estimate is the speedup over the DOCUMENTED TLC estimate in
BASELINE.md (no JVM in this image, so the TLC rate is literature-sourced,
NOT measured — clearly labeled there). Backend count-equivalence is pinned
for THIS benchmark model in the slow-marked
tests/test_kernel2.py::test_raft_3s_bench_whole_run_equivalence (and for
the smaller MCraft_micro model in default CI).

Resilience (VERDICT r2 #1): the axon TPU tunnel is flaky — plugin init can
hang for minutes or forever. This script
  1. probes TPU availability in SUBPROCESSES with retry/backoff for up to
     JAXMC_BENCH_TPU_WAIT seconds (default 1200) — not one 180 s shot;
  2. on TPU, first runs profile_tpu.py (subprocess, bounded) so per-step
     device timings survive in PROFILE_TPU.txt even if the full bench
     later dies;
  3. runs the measured bench in a CHILD process pinned to the chosen
     platform; if the TPU child dies mid-run (tunnel drop), retries once,
     then falls back to a CPU child — an honest JSON line is emitted in
     every case.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(os.path.abspath(__file__))

SPEC = os.path.join(_REPO, "specs", "MCraftMicro.tla")
CFG = os.path.join(_REPO, "specs", "MCraft_3s_bench.cfg")
INTERP_CAP = 20000  # distinct-state cap for the interpreter baseline run

# Documented TLC comparison point (BASELINE.md "TLC rate estimate"):
# literature/experience-sourced, NOT measured (no JVM in image).
TLC_EST_STATES_PER_SEC = 5000.0


def _log(msg):
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def probe_tpu_once(timeout_s: float) -> tuple:
    """(status, detail): one subprocess attempt at TPU plugin init.
    status: 'tpu' (up) | 'other' (jax works, no TPU on this machine —
    terminal) | 'retry' (init hung or errored — tunnel may come back)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "retry", f"device init timed out after {timeout_s:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
        return "retry", tail[0][:120]
    plat = r.stdout.strip()
    if plat == "tpu":
        return "tpu", plat
    # jax initialized cleanly on a non-TPU platform: deterministic,
    # terminal — waiting longer cannot produce a TPU
    return "other", plat


def wait_for_tpu() -> tuple:
    """Retry the probe with backoff for up to JAXMC_BENCH_TPU_WAIT
    seconds (default 20 min). Returns (found, last_detail).

    When every probe HANGS (tunnel hard-down, the round-3 state for 8+
    hours straight) the full budget is wasted driver time: without
    evidence the TPU was recently alive (/tmp/tpu_up.marker, written by
    a monitoring loop), cap the wait at ~7 minutes (two hang-length
    probes). A healthy TPU machine answers the FIRST probe in seconds
    either way."""
    env_wait = os.environ.get("JAXMC_BENCH_TPU_WAIT")
    budget = float(env_wait) if env_wait else 1200.0
    if env_wait is None:
        # only the DEFAULT budget is capped — an explicit env request is
        # honored as-is. "Recently alive" = marker younger than 2 h.
        try:
            fresh = (time.time() -
                     os.path.getmtime("/tmp/tpu_up.marker")) < 7200
        except OSError:
            fresh = False
        if not fresh:
            budget = min(budget, 420.0)
    t0 = time.time()
    attempt = 0
    detail = "no attempt"
    while time.time() - t0 < budget:
        attempt += 1
        left = budget - (time.time() - t0)
        status, detail = probe_tpu_once(min(180.0, max(30.0, left)))
        _log(f"tpu probe #{attempt}: "
             f"{'UP' if status == 'tpu' else detail} "
             f"({time.time() - t0:.0f}s in)")
        if status == "tpu":
            return True, detail
        if status == "other":
            return False, f"no TPU on this machine (platform={detail})"
        time.sleep(min(30.0, max(0.0, budget - (time.time() - t0))))
    return False, detail


def run_profile_tpu():
    """Capture per-step device timings before the full bench (so a later
    tunnel drop still leaves evidence). Bounded; failure is non-fatal."""
    out_path = os.path.join(_REPO, "PROFILE_TPU.txt")
    # stream the child's output STRAIGHT to the file: on a timeout-kill,
    # TimeoutExpired.stdout is None with capture_output (verified on this
    # box), so buffering in the parent would lose exactly the partial
    # per-step timings this profile-first step exists to preserve
    try:
        with open(out_path, "w") as fh:
            p = subprocess.Popen([sys.executable,
                                  os.path.join(_REPO, "profile_tpu.py")],
                                 stdout=fh, stderr=subprocess.STDOUT,
                                 text=True)
            try:
                rc = p.wait(timeout=900)
                _log(f"profile_tpu.py rc={rc} -> {out_path}")
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                fh.write("\n--- TIMED OUT at 900s ---\n")
                _log(f"profile_tpu.py timed out (900s); "
                     f"partial -> {out_path}")
    except OSError as ex:
        _log(f"profile_tpu.py failed to run: {ex}")


def child_bench(platform_pin: str):
    """The measured bench body. Runs in a child process with the platform
    pinned BEFORE first jax import; prints the JSON line on stdout."""
    import jax
    # pin BOTH platforms: a tunnel drop between probe and child start
    # must fail this child loudly (parent then retries / falls back),
    # never silently measure on CPU while claiming the TPU slot
    jax.config.update("jax_platforms", platform_pin)
    devs = jax.devices()
    assert devs[0].platform == platform_pin, \
        f"pinned {platform_pin} but got {devs[0].platform}"

    from jaxmc.sem.modules import Loader, bind_model
    from jaxmc.front.cfg import parse_cfg
    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer

    def load_model():
        ldr = Loader([os.path.join(_REPO, "specs"),
                      "/root/reference/examples"])
        return bind_model(ldr.load_path(SPEC), parse_cfg(open(CFG).read()))

    # resident device mode: the whole BFS (frontier, fingerprint set,
    # level loop) runs inside one jitted while_loop on the accelerator —
    # the tunnel's ~160ms round-trip would otherwise dominate. The
    # warm-up run compiles the jit cache AND trains the capacity buckets,
    # so the timed run replays with zero recompiles.
    ex = TpuExplorer(load_model(), store_trace=False, resident=True)
    r_warm = ex.run()
    assert r_warm.ok, "bench workload must pass"
    t0 = time.time()
    r = ex.run()
    jax_wall = time.time() - t0
    assert r.ok and r.distinct == r_warm.distinct
    jax_rate = r.generated / jax_wall

    # interpreter baseline on a capped prefix of the same workload (the
    # interp rate is flat in search depth; full run measured at the same
    # ~5.6k st/s — see specs/MCraft_3s_bench.cfg header)
    ri = Explorer(load_model(), max_states=INTERP_CAP).run()
    interp_rate = ri.generated / ri.wall_s

    out = {
        "metric": (
            f"states/sec, exhaustive raft 3-server "
            f"(reference raft.tla, MCraft_3s_bench: "
            f"{r.generated} generated / {r.distinct} distinct, COMPLETED, "
            f"platform={devs[0].platform}, device-resident BFS); "
            f"vs_baseline = speedup over the exact Python interpreter on "
            f"the same model ({INTERP_CAP}-distinct-state prefix); "
            f"vs_tlc_estimate = speedup over the BASELINE.md documented "
            f"TLC estimate ({TLC_EST_STATES_PER_SEC:.0f} st/s/core, "
            f"literature-sourced, NOT measured — no JVM in image)"),
        "value": round(jax_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(jax_rate / interp_rate, 3),
        "vs_tlc_estimate": round(jax_rate / TLC_EST_STATES_PER_SEC, 3),
    }
    print(json.dumps(out), flush=True)


def run_child(platform_pin: str, timeout_s: float):
    """Run child_bench in a subprocess; returns its JSON line or None."""
    env = dict(os.environ, JAXMC_BENCH_CHILD=platform_pin)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        _log(f"{platform_pin} bench child timed out after {timeout_s:.0f}s")
        return None
    sys.stderr.write(r.stderr or "")
    if r.returncode != 0:
        _log(f"{platform_pin} bench child rc={r.returncode}")
        return None
    for line in (r.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            return line
    _log(f"{platform_pin} bench child produced no JSON line")
    return None


def main():
    pin = os.environ.get("JAXMC_BENCH_CHILD")
    if pin:
        child_bench(pin)
        return

    found, detail = wait_for_tpu()
    if found:
        run_profile_tpu()
        line = run_child("tpu", 2400.0)
        if line is None:
            _log("retrying TPU bench once (tunnel flap?)")
            line = run_child("tpu", 2400.0)
        if line is not None:
            print(line, flush=True)
            return
        _log("TPU bench failed twice — falling back to CPU")
    else:
        _log(f"tpu unavailable after retry window ({detail}) — CPU bench")
    line = run_child("cpu", 3000.0)
    if line is None:
        # last resort: run inline on CPU so SOME line is emitted
        _log("CPU child failed; running inline")
        child_bench("cpu")
        return
    print(line, flush=True)


if __name__ == "__main__":
    main()
