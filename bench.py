r"""jaxmc benchmark: raft states/sec on the device BFS backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": R,
   "vs_tlc_estimate": R2}

Workload: the BASELINE.json model of record — the reference raft spec
(/root/reference/examples/raft.tla:482-493 hot path) with Server={s1,s2,s3}
and a bounded log, made finite by the MCraftMicro message-domain constraint
(specs/MCraft_3s_bench.cfg) so the EXHAUSTIVE search completes and the
reported rate covers a full run, not a truncated prefix. The metric string
DISCLOSES the bench model's parameter deltas vs the BASELINE model of
record (MCraft_3s.cfg) — see _MODEL_DELTAS.

vs_baseline is the speedup over this repo's exact Python interpreter on
the same workload. vs_tlc_estimate is the speedup over the DOCUMENTED TLC
estimate in BASELINE.md (no JVM in this image, so the TLC rate is
literature-sourced, NOT measured — clearly labeled there). Backend
count-equivalence for the bench model is pinned in
tests/test_kernel2.py::test_raft_3s_bench_whole_run_equivalence.

Since ISSUE 5 the full rung measures STEADY-STATE throughput: the timed
run resumes a warm checkpoint (committed artifact, a previous round's
probe-dir copy, or self-provisioned in-child — see _warm_start), so XLA
compile, capacity training and the BFS ramp sit OUTSIDE the measured
window; the compile wall is reported separately in the phases and the
orchestration block's compile_excluded_from_window rollup.  Every child
also enables the GUARDED persistent compile cache by default
(jaxmc/compile/cache.py) — repeat compiles across children and rounds
are disk hits, and a wedged cache degrades to cold compilation.
`make bench-warm` (JAXMC_BENCH_CHILD=warmgen) regenerates the warm
artifacts offline.

Constitutionally unable to produce nothing (VERDICT r3 #1): everything
races in parallel against a hard internal deadline
(JAXMC_BENCH_DEADLINE seconds, default 480):

  - a CPU worker thread immediately runs, in order: an interp-only
    EMERGENCY child (~30-60 s: no XLA compile at all), then the FULL
    bench rung (MCraft_3s_bench — the artifact of record gets the big
    slot, r4 weak #1), then the QUICK rung only if full failed;
  - a TPU worker thread consults the round-long probe loop's verdict
    ($JAXMC_PROBE_DIR/tpu_probe.log, $JAXMC_PROBE_DIR/tpu_up.marker;
    default /tmp — point JAXMC_PROBE_DIR elsewhere to keep parallel
    benches from clobbering each other's verdicts) before burning the
    single core on probe children of its own; if the TPU answers it runs
    the quick rung first (a TPU line as early as possible), then a
    bounded profile capture, then the full rung.

A watchdog heartbeat thread (jaxmc/obs/watchdog.py) rides along in
every child (the processes with real phase activity): a wedged device
init or BFS level is named on stderr WHILE it hangs, instead of only
in the post-mortem rollup.

At the deadline (or earlier, once the best-possible line for the
detected platform exists) the parent prints the best line available,
priority: tpu/full > tpu/quick > cpu/full > cpu/quick > interp. Every
line's metric string says exactly which model/platform/mode it measured.
"""

import json
import os
import subprocess
import sys
import threading
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(os.path.abspath(__file__))

from jaxmc import obs  # noqa: E402  (needs the sys.path insert; no jax)

# Parent-side phase recorder (ISSUE 1 / BENCH_r05 forensics): every child
# run, probe, and profile capture reports a span, and the final JSON line
# carries the rollup — so a deadline blowout names its culprit (device
# init vs compile vs BFS) instead of only "device bench did not finish".
# Children still in flight at emit time surface as open=True partial
# spans with their elapsed-so-far. main() swaps in a real Telemetry.
_TEL = obs.NullTelemetry()

SPEC = os.path.join(_REPO, "specs", "MCraftMicro.tla")
CFG_FULL = os.path.join(_REPO, "specs", "MCraft_3s_bench.cfg")
CFG_QUICK = os.path.join(_REPO, "specs", "MCraft_micro.cfg")

# Probe-loop artifacts (JAXMC_PROBE_DIR, default /tmp): parallel benches
# point this somewhere private so one bench's probe verdict never
# clobbers — or is misread as — another's.
_PROBE_DIR = os.environ.get("JAXMC_PROBE_DIR", "/tmp")
_PROBE_LOG = os.path.join(_PROBE_DIR, "tpu_probe.log")
_UP_MARKER = os.path.join(_PROBE_DIR, "tpu_up.marker")
INTERP_CAP = 20000  # distinct-state cap for the interpreter baseline run

# Documented TLC comparison point (BASELINE.md "TLC rate estimate"):
# literature/experience-sourced, NOT measured (no JVM in image).
TLC_EST_STATES_PER_SEC = 5000.0

# Honest-labeling (VERDICT r3 weak #6): how each rung differs from the
# BASELINE model of record, specs/MCraft_3s.cfg (3 servers, MaxTerm 3,
# MaxLogLen 2, MaxClientRequests 2, message domain unbounded).
_MODEL_DELTAS = {
    "full": ("MCraft_3s_bench vs BASELINE MCraft_3s: MaxClientRequests "
             "1 (vs 2), MaxTerm 2 (vs 3), MaxLogLen 1 (vs 2), "
             "MaxMsgDomain 3 (vs unbounded)"),
    "quick": ("MCraft_micro vs BASELINE MCraft_3s: 2 servers (vs 3), "
              "MaxClientRequests 1 (vs 2), MaxTerm 2 (vs 3), MaxLogLen "
              "1 (vs 2), MaxMsgDomain 2 (vs unbounded)"),
}
_RUNG_CFG = {"full": CFG_FULL, "quick": CFG_QUICK}

# ---- steady-state warm start (ISSUE 5) ----------------------------------
# The full rung measures STEADY-STATE expansion only: the timed run
# RESUMES a warm checkpoint (first ~WARM_STATES distinct states, resident
# device format via engine/ckpt.py) so XLA compile, capacity-bucket
# training and the BFS ramp all happen before the measured window opens.
# Source priority for the warm checkpoint:
#   1. JAXMC_BENCH_WARM_CKPT / the committed repo artifact (make
#      bench-warm regenerates it);
#   2. the probe-dir copy left by a previous bench round on this box;
#   3. self-provisioned inside the child: full warm-up pass (compiles +
#      trains caps exactly like the r02 flow), then a cheap prefix
#      replay writes the checkpoint the timed run resumes.
# A stale checkpoint (changed lane layout, different jaxmc build) is
# REFUSED by the integrity checks and the child falls back to
# self-provisioning — the warm start can never corrupt the measurement.
WARM_STATES = int(os.environ.get("JAXMC_BENCH_WARM_STATES", "20000"))
_WARM_CK_COMMITTED = os.environ.get(
    "JAXMC_BENCH_WARM_CKPT", os.path.join(_REPO, "ck_mcraft3s_bench_warm.ck"))
# steady-state lane capacities for the bench model (max-merged over the
# platform defaults in tpu/bfs.py): every cap growth is a full XLA
# recompile, so the warm-up compile should cover the whole run
_BENCH_RES_CAPS = {"SC": 1 << 18, "FCap": 1 << 16,
                   "AccCap": 1 << 17, "VC": 1 << 13}

_DEADLINE = None  # absolute time.time() deadline, set in main()
_PROBE_SKIPPED = False  # verify probe skipped on a DOWN oracle verdict
# preflight backend-oracle verdict (ISSUE 11, jaxmc/backend/oracle.py):
# main() fills it before the workers start — {platform, probes, wall_s,
# reason}.  The accelerator worker reads it instead of burning deadline
# budget on its own probe children, and the orchestration block records
# it so the artifact says WHY the bench measured the platform it did.
_ORACLE = {}


def _log(msg):
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def _remaining():
    return max(0.0, _DEADLINE - time.time())


# ---------------------------------------------------------------- children

def _warm_start(tel, ex):
    """Point `ex` at a warm checkpoint so its NEXT run() measures
    steady-state expansion only.  Tries the committed artifact, then the
    probe-dir copy from a previous round, then self-provisions (full
    warm-up pass + a cheap prefix replay that writes the checkpoint).
    Returns (steady, r_warm): `steady` is the bookkeeping dict, or None
    when every path failed — the caller falls back to the r02 two-pass
    replay flow; `r_warm` is the completed full warm-up pass when the
    self-provision path ran one (the caller must NOT re-warm — a third
    full pass is exactly the deadline-blowout class this layer kills).
    NOTHING here may corrupt the measurement: a stale/foreign checkpoint
    is refused by the engine/ckpt.py integrity + layout checks and we
    move down the ladder."""
    from jaxmc.engine.ckpt import load_checkpoint
    scratch = os.path.join(_PROBE_DIR, "jaxmc_bench_warm_full.ck")
    for src, path in (("committed", _WARM_CK_COMMITTED),
                      ("probe-dir", scratch)):
        if not os.path.exists(path) or not os.path.getsize(path):
            continue
        try:
            _, ck = load_checkpoint(path, kind="device")
            ex.resume_from = path
            # any bound <= the checkpoint's distinct truncates right
            # after the first resumed level: the warm-up compiles the
            # program (at the checkpoint's trained caps) and touches the
            # device, then stops
            ex.max_states = max(1, int(ck["distinct"]))
            with tel.span("warmup_run", warm_source=src):
                rw = ex.run()
            ex.max_states = None
            assert rw.ok, "warm-up resume failed"
            _log(f"warm start: resuming {src} checkpoint {path} "
                 f"({ck['distinct']} distinct, depth {ck['depth']})")
            return {"source": src, "path": path,
                    "resumed_generated": int(ck["generated"]),
                    "resumed_distinct": int(ck["distinct"]),
                    "resumed_depth": int(ck["depth"])}, None
        except Exception as e:  # noqa: BLE001 — degrade, never corrupt
            _log(f"warm checkpoint {path} unusable ({e}); trying the "
                 f"next warm source")
            ex.resume_from = None
            ex.max_states = None
    # self-provision: the r02 flow's warm-up pass (compiles + trains the
    # capacity buckets), then a prefix replay through the already-jitted
    # program writes the checkpoint the timed run resumes — so even a
    # cold box pays ~1.3 full passes instead of 2, and the NEXT round
    # finds the checkpoint in the probe dir
    rw = None
    try:
        with tel.span("warmup_run", warm_source="self-provision"):
            rw = ex.run()
        assert rw.ok, "bench workload must pass"
        with tel.span("warm_ckpt_build", warm_states=WARM_STATES):
            ex.max_states = WARM_STATES
            ex.checkpoint_path = scratch
            ex.checkpoint_every = 1e9  # the truncation write only
            rp = ex.run()
            ex.max_states = None
            assert rp.truncated, "prefix replay should truncate"
        _, ck = load_checkpoint(scratch, kind="device")
        ex.resume_from = scratch
        _log(f"warm start: self-provisioned checkpoint at {scratch} "
             f"({ck['distinct']} distinct, depth {ck['depth']})")
        return {"source": "self-provisioned", "path": scratch,
                "resumed_generated": int(ck["generated"]),
                "resumed_distinct": int(ck["distinct"]),
                "resumed_depth": int(ck["depth"])}, rw
    except Exception as e:  # noqa: BLE001
        # hand back the COMPLETED warm-up pass (when one ran): the
        # two-pass fallback must reuse it, never pay a third full pass
        _log(f"warm-start self-provision failed ({e}); falling back to "
             f"the two-pass replay flow")
        ex.resume_from = None
        ex.max_states = None
        ex.checkpoint_path = None
        return None, (rw if rw is not None and rw.ok else None)


def child_bench(platform_pin: str, rung: str):
    """The measured bench body. Runs in a child process with the platform
    pinned BEFORE first jax import; prints the JSON line on stdout."""
    tel = obs.Telemetry()
    # stall floor 60s: XLA compiles on this box legitimately run long;
    # the watchdog should name a wedged tunnel, not a working compile
    wd = obs.Watchdog(tel, min_stall_s=60.0,
                      on_stall=lambda m: _log(f"WATCHDOG({platform_pin}/"
                                              f"{rung}): {m}")).start()
    with tel.span("device_init", platform=platform_pin):
        import jax
        # pin the platform: a tunnel drop between probe and child start
        # must fail this child loudly (parent falls back), never silently
        # measure on CPU while claiming the TPU slot
        jax.config.update("jax_platforms", platform_pin)
        # persistent XLA compile cache, ON BY DEFAULT and GUARDED
        # (ISSUE 5): the SECOND child compiling the same arms hits disk
        # instead of re-paying the XLA bill that has been eating the
        # bench deadline since r03 — and a wedged/corrupt/foreign cache
        # degrades to cold compilation instead of hanging the child.
        from jaxmc.compile.cache import enable_guarded_cache
        # tel passed explicitly: obs.use(tel) is entered further down,
        # so obs.current() here would be the no-op NullTelemetry and the
        # cache-dir/entries_start gauges would vanish from the artifact
        cache_dir = enable_guarded_cache(tel=tel)
        devs = jax.devices()
    assert devs[0].platform == platform_pin, \
        f"pinned {platform_pin} but got {devs[0].platform}"

    from jaxmc.sem.modules import Loader, bind_model
    from jaxmc.front.cfg import parse_cfg
    from jaxmc.backend.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer

    cfg_path = _RUNG_CFG[rung]

    def load_model():
        ldr = Loader([os.path.join(_REPO, "specs"),
                      "/root/reference/examples"])
        with open(cfg_path) as fh:
            return bind_model(ldr.load_path(SPEC), parse_cfg(fh.read()))

    # resident device mode: the whole BFS (frontier, fingerprint set,
    # level loop) runs inside one jitted while_loop on the accelerator —
    # the tunnel's ~160ms round-trip would otherwise dominate.
    #
    # STEADY-STATE measurement (ISSUE 5, full rung): the timed run
    # RESUMES a warm checkpoint, so XLA compile, capacity training and
    # the BFS ramp are all OUTSIDE the measured window — the states/sec
    # line covers steady-state expansion only, and the compile wall is
    # reported separately (phases here + the parent's orchestration
    # block). The quick rung keeps the r02 two-pass replay flow (its
    # model is seconds-small; a warm layer would measure noise).
    #
    # Child-side phase breakdown: the spans ride the JSON line out, so
    # the artifact of record says how the child's own wall time split
    # between device init, engine build (layout + kernel compile), the
    # warm-up (XLA compile proper), the timed run, and the interp
    # baseline.
    with obs.use(tel):
        with tel.span("engine_build"):
            # steady-state caps: the corpus manifest's committed
            # res_caps record for this cfg (ISSUE 6), falling back to
            # the full-rung constants; the engine max-merges the
            # PERSISTED capacity profile on top (compile/cache.py), so
            # a second run starts at the learned caps and
            # window_recompiles reads 0
            from jaxmc.corpus import case_for_cfg
            _case = case_for_cfg(os.path.basename(cfg_path))
            _caps = dict(_case.res_caps) if _case is not None \
                and _case.res_caps else (
                dict(_BENCH_RES_CAPS) if rung == "full" else None)
            if _caps:
                _caps.pop("chunk", None)
            ex = TpuExplorer(load_model(), store_trace=False,
                             resident=True, res_caps=_caps)
        steady, r_warm = (_warm_start(tel, ex) if rung == "full"
                          else (None, None))
        if steady is None and r_warm is None:
            with tel.span("warmup_run"):
                r_warm = ex.run()
            assert r_warm.ok, "bench workload must pass"
        tel.reset_levels("timed run")
        t0 = time.time()
        with tel.span("timed_run"):
            r = ex.run()
        jax_wall = time.time() - t0
        assert r.ok and not r.truncated
        if steady is None:
            assert r.distinct == r_warm.distinct
            window_gen = r.generated
        else:
            window_gen = r.generated - steady["resumed_generated"]
            # the resumed totals must be EXACTLY the cold-run totals —
            # the warm start must never shift the measured workload
            from jaxmc.corpus import case_for_cfg
            pin = case_for_cfg(os.path.basename(cfg_path))
            if pin is not None and pin.distinct is not None:
                assert (r.distinct, r.generated) == \
                    (pin.distinct, pin.generated), \
                    (f"warm resume produced {r.distinct}/{r.generated}, "
                     f"manifest pins {pin.distinct}/{pin.generated}")
        jax_rate = window_gen / jax_wall
        # cap growths recompile INSIDE the window — report them (zero
        # when the warm start did its job)
        window_recompiles = sum(1 for lrec in tel.levels
                                if lrec.get("fresh_compile"))

        # interpreter baseline on a capped prefix of the same workload
        # (the interp rate is flat in search depth; full run measured at
        # the same ~5.6k st/s — see specs/MCraft_3s_bench.cfg header)
        with tel.span("interp_baseline"):
            ri = Explorer(load_model(), max_states=INTERP_CAP).run()
        interp_rate = ri.generated / ri.wall_s
        from jaxmc.compile.cache import record_entries_end
        record_entries_end(cache_dir)

    wd.stop()
    window_note = (
        f"STEADY-STATE window: resumed warm checkpoint "
        f"({steady['source']}) at depth {steady['resumed_depth']}/"
        f"{steady['resumed_distinct']} distinct; the value covers the "
        f"{window_gen} states generated AFTER resume; XLA compile + "
        f"warm-up wall excluded (reported in phases/orchestration); "
        f"{window_recompiles} in-window recompiles"
        if steady is not None else
        "replay window: full-space re-run after an identical warm-up "
        "pass (compile excluded via the jit cache)")
    out = {
        "phases": tel.phase_list(),
        "counters": dict(tel.counters),
        "env": obs.environment_meta(),
        "metric": (
            f"states/sec, exhaustive raft (reference raft.tla, "
            f"{os.path.basename(cfg_path)}: "
            f"{r.generated} generated / {r.distinct} distinct, COMPLETED, "
            f"platform={devs[0].platform}, device-resident BFS); "
            f"{window_note}; "
            f"model deltas: {_MODEL_DELTAS[rung]}; "
            f"vs_baseline = speedup over the exact Python interpreter on "
            f"the same model (capped at {INTERP_CAP} distinct); "
            f"vs_tlc_estimate = speedup over the BASELINE.md documented "
            f"TLC estimate ({TLC_EST_STATES_PER_SEC:.0f} st/s/core, "
            f"literature-sourced, NOT measured — no JVM in image)"),
        "value": round(jax_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(jax_rate / interp_rate, 3),
        "vs_tlc_estimate": round(jax_rate / TLC_EST_STATES_PER_SEC, 3),
    }
    if steady is not None:
        out["steady_state"] = dict(steady,
                                   window_generated=window_gen,
                                   window_wall_s=round(jax_wall, 3),
                                   window_recompiles=window_recompiles)
    print(json.dumps(out), flush=True)


def child_emergency():
    """Exact-engine floor measurement: no XLA compile anywhere, so it
    lands in well under a minute. Since ISSUE 3 this line runs on the
    PARALLEL exact engine (engine/parallel.py, results bit-identical to
    the serial interpreter): the emergency rung is the only line five
    bench rounds have ever produced in this environment, so it is the
    one the tentpole must move. Honest label: exact-engine rate with the
    worker count disclosed; vs_baseline 1.0 by construction. Phase spans
    and per-level merge telemetry ride along."""
    from jaxmc.sem.modules import Loader, bind_model
    from jaxmc.front.cfg import parse_cfg
    from jaxmc.engine.parallel import ParallelExplorer, default_workers

    # the acceptance bar is the multi-worker exact engine: oversubscribe
    # to 4 even on smaller boxes (measured near-parity vs core-count
    # workers; JAXMC_WORKERS pins it explicitly)
    workers = default_workers() if os.environ.get("JAXMC_WORKERS") \
        else max(4, default_workers())
    tel = obs.Telemetry()
    wd = obs.Watchdog(tel, on_stall=lambda m: _log(
        f"WATCHDOG(emergency): {m}")).start()
    def load_model():
        ldr = Loader([os.path.join(_REPO, "specs"),
                      "/root/reference/examples"])
        with open(CFG_QUICK) as fh:
            return bind_model(ldr.load_path(SPEC), parse_cfg(fh.read()))

    with obs.use(tel):
        with tel.span("load"):
            model = load_model()
        with tel.span("search", workers=workers):
            ex = ParallelExplorer(model, workers=workers)
            r = ex.run()
        par_levels = list(tel.levels)  # before the serial baseline's
        # level records land in the same recorder
        # measured serial baseline on the SAME model (the r05-class
        # single-core interpreter line, ~1s at this model size) so
        # vs_baseline is a real speedup ratio, not a hardcoded 1.0 that
        # would read as "parallel gives zero speedup" in an obs diff
        from jaxmc.engine.explore import Explorer
        with tel.span("serial_baseline"):
            rb = Explorer(load_model()).run()
    wd.stop()
    assert r.ok
    assert (r.generated, r.distinct) == (rb.generated, rb.distinct), \
        "parallel/serial parity broke on the bench model"
    rate = r.generated / r.wall_s
    serial_rate = rb.generated / rb.wall_s
    out = {
        "phases": tel.phase_list(),
        "env": obs.environment_meta(),
        "workers": workers,
        # per-level exact-engine telemetry: frontier split cost vs the
        # parent's merge cost (the tentpole's measurable shape)
        "levels": [{k: lrec.get(k) for k in
                    ("level", "frontier", "generated", "new", "wall_s",
                     "chunk_wall_s", "merge_wall_s") if k in lrec}
                   for lrec in par_levels],
        "metric": (
            f"states/sec, exhaustive raft (reference raft.tla, "
            f"MCraft_micro: {r.generated} generated / {r.distinct} "
            f"distinct, COMPLETED, EXACT ENGINE ONLY (parallel BFS, "
            f"workers={workers}) — the "
            f"device bench did not finish inside the bench deadline; "
            f"model deltas: {_MODEL_DELTAS['quick']}; "
            f"vs_baseline = speedup over the serial exact interpreter "
            f"measured in this run ({serial_rate:.0f} st/s); "
            f"vs_tlc_estimate vs the BASELINE.md documented TLC estimate "
            f"({TLC_EST_STATES_PER_SEC:.0f} st/s/core, literature-"
            f"sourced, NOT measured)"),
        "value": round(rate, 1),
        "unit": "states/sec",
        "serial_states_per_sec": round(serial_rate, 1),
        "vs_baseline": round(rate / serial_rate, 3),
        "vs_tlc_estimate": round(rate / TLC_EST_STATES_PER_SEC, 3),
    }
    print(json.dumps(out), flush=True)


def child_warmgen():
    """`make bench-warm` (JAXMC_BENCH_CHILD=warmgen): (re)generate the
    resumable warm artifacts, deadline-free.

    1. ck_mcraft3s_bench_warm.ck — resident-format warm checkpoint of
       the MCraft_3s_bench rung: a full caps-training pass first (so the
       checkpoint records the run's FINAL lane capacities and a resumed
       bench compiles exactly once), then a cheap prefix replay through
       the already-jitted program writes the first ~WARM_STATES distinct
       states at a level boundary.  Every future full-rung bench child
       resumes this file; commit it when the box can build it.
    2. ck_mcraft3s.ck — a genuinely RESUMABLE interp-format checkpoint
       of the BASELINE model of record (MCraft_3s — never explored to
       completion anywhere, VERDICT r5 #2), replacing the stale
       round-3 stub.  Continue it with:
         python -m jaxmc check specs/MCraft.tla --cfg specs/MCraft_3s.cfg \
             -I /root/reference/examples --resume ck_mcraft3s.ck \
             --checkpoint ck_mcraft3s.ck
    """
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAXMC_PLATFORM", "cpu"))
    tel = obs.Telemetry()
    with obs.use(tel):
        from jaxmc.compile.cache import enable_guarded_cache
        enable_guarded_cache(tel=tel)
        from jaxmc.sem.modules import Loader, bind_model
        from jaxmc.front.cfg import parse_cfg
        from jaxmc.backend.bfs import TpuExplorer
        from jaxmc.engine.explore import Explorer

        def load(spec, cfg_path):
            ldr = Loader([os.path.join(_REPO, "specs"),
                          "/root/reference/examples"])
            with open(cfg_path) as fh:
                return bind_model(ldr.load_path(spec),
                                  parse_cfg(fh.read()))

        _log("bench-warm 1/2: MCraft_3s_bench resident warm checkpoint "
             "(full caps-training pass, then the prefix replay)")
        with tel.span("warmgen_bench"):
            ex = TpuExplorer(load(SPEC, CFG_FULL), store_trace=False,
                             resident=True, res_caps=_BENCH_RES_CAPS)
            r = ex.run()
            assert r.ok and not r.truncated, "bench workload must pass"
            ex.max_states = WARM_STATES
            ex.checkpoint_path = _WARM_CK_COMMITTED
            ex.checkpoint_every = 1e9  # the truncation write only
            rp = ex.run()
            assert rp.truncated, "prefix replay should truncate"
        _log(f"wrote {_WARM_CK_COMMITTED} ({rp.distinct} distinct, "
             f"depth {rp.diameter}; full run: {r.generated} generated / "
             f"{r.distinct} distinct)")

        _log("bench-warm 2/2: MCraft_3s model-of-record interp "
             "checkpoint (resumable; replaces the stale stub)")
        ck3s = os.path.join(_REPO, "ck_mcraft3s.ck")
        n = int(os.environ.get("JAXMC_WARM_3S_STATES", "20000"))
        with tel.span("warmgen_3s", max_states=n):
            model = load(os.path.join(_REPO, "specs", "MCraft.tla"),
                         os.path.join(_REPO, "specs", "MCraft_3s.cfg"))
            kw = dict(max_states=n, checkpoint_path=ck3s,
                      checkpoint_every=1e9)
            if os.path.exists(ck3s):
                # already partially explored: EXTEND the run by another
                # n distinct states instead of restarting — repeated
                # bench-warm invocations walk the model of record
                # toward completion
                try:
                    from jaxmc.engine.ckpt import load_checkpoint
                    _, ckp = load_checkpoint(ck3s, kind="interp")
                    kw["max_states"] = len(ckp["states"]) + n
                    r3 = Explorer(model, resume_from=ck3s, **kw).run()
                except Exception as e:  # noqa: BLE001 — stale stub
                    _log(f"existing {ck3s} not resumable ({e}); "
                         f"regenerating from scratch")
                    kw["max_states"] = n
                    r3 = Explorer(model, **kw).run()
            else:
                r3 = Explorer(model, **kw).run()
        _log(f"wrote {ck3s} ({r3.distinct} distinct / {r3.generated} "
             f"generated, truncated={r3.truncated})")
    print(json.dumps({"metric": "bench-warm artifacts written",
                      "bench_warm_ckpt": _WARM_CK_COMMITTED,
                      "bench_warm_distinct": rp.distinct,
                      "mcraft3s_ckpt": ck3s,
                      "mcraft3s_distinct": r3.distinct,
                      "phases": tel.phase_list()}), flush=True)


# ------------------------------------------------------------------ parent

class _Results:
    """Thread-safe best-line store with a fixed priority order."""
    PRIORITY = [("tpu", "full"), ("tpu", "quick"),
                ("gpu", "full"), ("gpu", "quick"),
                ("cpu", "full"), ("cpu", "quick"),
                ("interp", "emergency")]

    def __init__(self):
        self._lock = threading.Lock()
        self._lines = {}

    def put(self, platform, rung, line):
        with self._lock:
            self._lines[(platform, rung)] = line
        _log(f"result in: {platform}/{rung}")

    def has(self, platform, rung):
        with self._lock:
            return (platform, rung) in self._lines

    def best(self):
        with self._lock:
            for key in self.PRIORITY:
                if key in self._lines:
                    return key, self._lines[key]
        return None, None


_RESULTS = _Results()
_PROCS = []        # live child Popens, killed at exit
_PROCS_LOCK = threading.Lock()
_STOPPING = threading.Event()  # set by main() before the kill loop
# per-tag child fate for the orchestration block (ISSUE 4): a child that
# died on a signal used to surface only as an opaque partial line — now
# the artifact of record says what killed it and whether a retry saved it
_CHILD_FATE = {}
_CHILD_FATE_LOCK = threading.Lock()


def _note_fate(tag: str, fate: str, retries: int) -> None:
    with _CHILD_FATE_LOCK:
        _CHILD_FATE[tag] = {"fate": fate, "retries": retries}


def _run_child(env_extra: dict, timeout_s: float, tag: str):
    """Run bench.py as a child with env markers; return its JSON line or
    None. Registers the Popen so main() can kill stragglers at exit.
    Each attempt is a parent-side span (outcome in the attrs), so the
    emitted line's phase rollup says where the deadline budget went.
    A child that DIES ON A SIGNAL (OOM kill, a crashed accelerator
    runtime) is retried with backoff (JAXMC_BENCH_CHILD_RETRIES, default
    1) — signal deaths are the transient class; a nonzero exit is a
    deterministic failure and is not retried."""
    retries = int(os.environ.get("JAXMC_BENCH_CHILD_RETRIES", "1"))
    for attempt in range(retries + 1):
        if timeout_s <= 5 or _remaining() <= 5 or _STOPPING.is_set():
            _log(f"{tag}: skipped (no time left)")
            with _CHILD_FATE_LOCK:
                prev = _CHILD_FATE.get(tag)
            # never clobber the real cause of death: a signal-killed
            # child whose retry window expired keeps its signal fate
            if prev and prev["fate"] not in ("ok", "skipped"):
                _note_fate(tag, f"{prev['fate']} (retry skipped: no "
                                f"time left)", attempt)
            else:
                _note_fate(tag, "skipped", attempt)
            return None
        line, fate = _run_child_once(env_extra, min(timeout_s,
                                                    _remaining()), tag)
        if line is not None:
            _note_fate(tag, "ok", attempt)
            return line
        _note_fate(tag, fate, attempt)
        if not fate.startswith("signal"):
            return None  # deterministic failure: retrying cannot help
        if attempt >= retries:
            _log(f"{tag}: child kept dying on a signal ({fate}); "
                 f"giving up after {attempt + 1} attempts")
            return None
        backoff = min(5.0, 1.0 * (2 ** attempt), _remaining())
        _log(f"{tag}: child died on a signal ({fate}); retrying in "
             f"{backoff:.0f}s ({attempt + 1}/{retries})")
        time.sleep(max(0.0, backoff))
    return None


def _run_child_once(env_extra: dict, timeout_s: float, tag: str):
    """(json_line | None, fate) for one child attempt; fate is "ok",
    "timeout", "rc=N", "signal=-N" or "no-json"."""
    # children join the bench's trace: their artifacts merge back into
    # one `obs timeline` view, parented on this process's span
    env = obs.child_env(dict(os.environ, **env_extra))
    with _PROCS_LOCK:
        # check-and-spawn under the lock: a worker racing main()'s kill
        # loop must not start a fresh multi-minute XLA compile that the
        # parent's exit would orphan on this 1-core box
        if _STOPPING.is_set():
            _log(f"{tag}: skipped (shutting down)")
            return None, "skipped"
        p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=env)
        _PROCS.append(p)
    with _TEL.span(f"child:{tag}",
                   timeout_s=round(timeout_s, 1)) as span:
        try:
            out, err = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            _log(f"{tag}: timed out after {timeout_s:.0f}s")
            span.attrs["outcome"] = "timeout"
            return None, "timeout"
        finally:
            with _PROCS_LOCK:
                if p in _PROCS:
                    _PROCS.remove(p)
    sys.stderr.write(err or "")
    if p.returncode != 0:
        _log(f"{tag}: child rc={p.returncode}")
        _TEL.counter("bench.child_signal_deaths" if p.returncode < 0
                     else "bench.child_failures")
        return None, (f"signal={p.returncode}" if p.returncode < 0
                      else f"rc={p.returncode}")
    for line in (out or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            return line, "ok"
    _log(f"{tag}: child produced no JSON line")
    return None, "no-json"


def probe_tpu_once(timeout_s: float) -> tuple:
    """(status, detail): one subprocess attempt at TPU plugin init.
    status: 'tpu' (up) | 'other' (jax works, no TPU on this machine —
    terminal) | 'retry' (init hung or errored — tunnel may come back)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        with _TEL.span("tpu_probe", timeout_s=round(timeout_s, 1)):
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "retry", f"device init timed out after {timeout_s:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
        return "retry", tail[0][:120]
    plat = r.stdout.strip()
    if plat == "tpu":
        return "tpu", plat
    # jax initialized cleanly on a non-TPU platform: deterministic,
    # terminal — waiting longer cannot produce a TPU
    return "other", plat


def _cpu_worker():
    """Emergency interp line first (floor), then the FULL bench rung —
    the artifact of record (BENCH_r02 proved it lands on this box when
    given the window; r4 starved it behind the quick rung + probes,
    VERDICT r4 weak #1) — then the quick rung only as a leftover filler."""
    line = _run_child({"JAXMC_BENCH_CHILD": "emergency"},
                      min(150.0, _remaining()), "cpu/emergency")
    if line:
        _RESULTS.put("interp", "emergency", line)
    line = _run_child({"JAXMC_BENCH_CHILD": "cpu", "JAXMC_BENCH_RUNG":
                       "full"}, _remaining(), "cpu/full")
    if line:
        _RESULTS.put("cpu", "full", line)
    else:
        line = _run_child({"JAXMC_BENCH_CHILD": "cpu", "JAXMC_BENCH_RUNG":
                           "quick"}, _remaining(), "cpu/quick")
        if line:
            _RESULTS.put("cpu", "quick", line)


def _tunnel_oracle() -> str:
    """'up' / 'down' / 'unknown' from the round-long probe-loop artifacts
    (the probe loop writes $JAXMC_PROBE_DIR/tpu_probe.log every ~10 min
    and $JAXMC_PROBE_DIR/tpu_up.marker on success; default /tmp). A fresh
    verdict saves the bench from burning the single core on its own 120 s
    probe children — the r4 starvation mode — while a stale or absent log
    falls back to probing."""
    fresh_s = 30 * 60
    try:
        if (time.time() - os.path.getmtime(_UP_MARKER)
                < fresh_s):
            return "up"
    except OSError:
        pass
    try:
        with open(_PROBE_LOG) as fh:
            lines = [ln.strip() for ln in fh if ln.strip()]
        if lines and (time.time() - os.path.getmtime(_PROBE_LOG)
                      < fresh_s):
            # exact line grammar of the probe loop: success is
            # "HH:MM:SS TPU UP (...)"; failures are "no tpu (...)" /
            # "probe timed out ..." / "probe error ..." — substring
            # matching on "tpu" alone would read "no tpu" as up
            last = lines[-1]
            if "TPU UP" in last:
                return "up"
            if ("no tpu" in last or "timed out" in last
                    or "probe error" in last):
                return "down"
    except OSError:
        pass
    return "unknown"


def _accel_worker():
    """The accelerator-side worker.  Consults the PREFLIGHT backend
    oracle first (ISSUE 11): a live accelerator verdict skips every
    legacy probe and goes straight to measuring on that platform; a
    cpu verdict means every accelerator probe failed in seconds — the
    worker exits immediately so the whole deadline belongs to the
    cpu/full rung.  Only when the preflight itself produced nothing
    (_ORACLE empty/None verdict) does the legacy TPU probe-loop path
    run."""
    choice = _ORACLE.get("platform")
    if choice in ("tpu", "gpu"):
        _log(f"backend oracle: {choice} is live "
             f"({_ORACLE.get('reason')}) — measuring on it")
        _accel_rungs(choice)
        return
    if choice == "cpu":
        # all accelerator probes failed fast: the cpu worker owns the
        # deadline; recorded like the legacy probe-loop DOWN verdict
        _log("backend oracle: no live accelerator — cpu/full gets the "
             "whole deadline")
        global _PROBE_SKIPPED
        _PROBE_SKIPPED = True
        _TEL.event("tpu_probe_skipped",
                   reason="backend oracle verdict: cpu only")
        return
    _tpu_worker()


def _accel_rungs(platform: str):
    """quick rung first (earliest accelerator line), bounded profile
    capture (tpu only), then the full rung — on the oracle's chosen
    platform."""
    try:  # evidence for the monitoring loop pattern (tpu_up.marker)
        if platform == "tpu":
            with open(_UP_MARKER, "w") as fh:
                fh.write(str(time.time()))
    except OSError:
        pass
    line = _run_child({"JAXMC_BENCH_CHILD": platform,
                       "JAXMC_BENCH_RUNG": "quick"},
                      _remaining(), f"{platform}/quick")
    if line:
        _RESULTS.put(platform, "quick", line)
    if platform == "tpu" and _remaining() > 240:
        _run_profile_tpu(min(300.0, _remaining() / 3))
    line = _run_child({"JAXMC_BENCH_CHILD": platform,
                       "JAXMC_BENCH_RUNG": "full"},
                      _remaining(), f"{platform}/full")
    if line:
        _RESULTS.put(platform, "full", line)


def _tpu_worker():
    """LEGACY probe path (only when the preflight oracle produced no
    verdict): probe for the tunnel; on success run quick rung first
    (earliest possible TPU line), bounded profile capture, then the
    full rung."""
    oracle = _tunnel_oracle()
    found = oracle == "up"
    if found:
        _log("tunnel oracle: probe loop says TPU is UP — skipping probes")
    elif oracle == "down":
        # the probe loop has FRESH evidence the tunnel is dead: skip the
        # verify probe entirely instead of burning up to 60s of deadline
        # budget (and the single core the cpu/full child needs) on a
        # known-dead device — recorded as probe_skipped in the
        # orchestration block
        _log("tunnel oracle: probe loop says tunnel is DOWN — "
             "skipping the verify probe")
        global _PROBE_SKIPPED
        _PROBE_SKIPPED = True
        _TEL.event("tpu_probe_skipped", reason="probe loop verdict: down")
        return
    else:
        attempt = 0
        # leave >=90 s for a quick TPU rung after the last probe; at most
        # two probes so the cpu/full child keeps the core (r4 weak #1)
        while _remaining() > 90 and attempt < 2:
            attempt += 1
            status, detail = probe_tpu_once(min(120.0, _remaining() - 60))
            _log(f"tpu probe #{attempt}: "
                 f"{'UP' if status == 'tpu' else detail}")
            if status == "tpu":
                found = True
                break
            if status == "other":
                _log(f"no TPU on this machine (platform={detail})")
                return
            time.sleep(min(20.0, _remaining()))
    if not found:
        return
    _accel_rungs("tpu")


def _run_profile_tpu(timeout_s: float):
    """Capture per-step device timings; failure is non-fatal. Streams
    STRAIGHT to the file so a timeout-kill keeps the partial output."""
    out_path = os.path.join(_REPO, "PROFILE_TPU.txt")
    try:
        with _TEL.span("profile_tpu", timeout_s=round(timeout_s, 1)), \
                open(out_path, "w") as fh:
            p = subprocess.Popen([sys.executable,
                                  os.path.join(_REPO, "profile_tpu.py")],
                                 stdout=fh, stderr=subprocess.STDOUT,
                                 text=True)
            with _PROCS_LOCK:
                _PROCS.append(p)
            try:
                rc = p.wait(timeout=timeout_s)
                _log(f"profile_tpu.py rc={rc} -> {out_path}")
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                fh.write(f"\n--- TIMED OUT at {timeout_s:.0f}s ---\n")
                _log(f"profile_tpu.py timed out; partial -> {out_path}")
            finally:
                with _PROCS_LOCK:
                    if p in _PROCS:
                        _PROCS.remove(p)
    except OSError as ex:
        _log(f"profile_tpu.py failed to run: {ex}")


def _reference_missing() -> Optional[str]:
    """Named skip reason when the raft bench workload cannot load here
    (ISSUE 6 satellite): every bench rung EXTENDS the reference
    raft.tla, so a container without the reference tree must SKIP with
    a parseable line instead of failing five minutes in."""
    ref = os.environ.get("JAXMC_REFERENCE", "/root/reference")
    if os.path.exists(os.path.join(ref, "examples", "raft.tla")):
        return None
    return (f"reference corpus not mounted at {ref} (driver environment "
            f"only; set JAXMC_REFERENCE) — the bench rungs EXTEND its "
            f"raft.tla")


def main():
    global _DEADLINE, _TEL
    skip = _reference_missing()
    if skip is not None:
        _log(f"SKIP: {skip}")
        print(json.dumps({
            "metric": f"bench SKIPPED: {skip}", "value": None,
            "unit": "states/sec", "vs_baseline": None,
            "skip_reason": skip}), flush=True)
        return
    pin = os.environ.get("JAXMC_BENCH_CHILD")
    if pin == "emergency":
        child_emergency()
        return
    if pin == "warmgen":
        child_warmgen()
        return
    if pin:
        child_bench(pin, os.environ.get("JAXMC_BENCH_RUNG", "full"))
        return

    budget = float(os.environ.get("JAXMC_BENCH_DEADLINE", "480"))
    _DEADLINE = time.time() + budget
    # every device child shares one GUARDED persistent XLA compile cache
    # (children call enable_guarded_cache, defaulting to
    # cache.default_cache_dir() — derived from JAXMC_PROBE_DIR like the
    # probe artifacts): the quick rung's compiles prepay the full
    # rung's, and the NEXT bench round starts warm. Stamp the resolved
    # dir into the env so the orchestration block discloses ONE path and
    # the children agree with it.
    from jaxmc.compile.cache import cache_disabled_by_env, \
        default_cache_dir
    if not cache_disabled_by_env():
        os.environ.setdefault("JAXMC_COMPILE_CACHE", default_cache_dir())
    _TEL = obs.Telemetry(meta={"command": "bench",
                               "deadline_s": budget})
    # NO parent watchdog: the parent's only telemetry is one child:* span
    # per attempt, held open for the child's whole (healthy, multi-minute)
    # run — any parent-side stall threshold under the deadline would flag
    # normal rounds. The CHILDREN carry the watchdogs: they have real
    # phase activity (device_init/engine_build/warmup/timed), so their
    # stall lines name the actual wedge on the shared stderr.
    _log(f"deadline: {budget:.0f}s from now")

    # PREFLIGHT backend oracle (ISSUE 11): answer "which live platform
    # should this round measure?" in seconds — concurrent hang-proof
    # subprocess probes of every visible platform — and then spend the
    # WHOLE remaining deadline measuring on the winner instead of
    # discovering a dead tunnel 120 s at a time mid-round.  Best-effort:
    # an oracle failure falls back to the legacy probe-loop path.
    try:
        from jaxmc.backend.oracle import preflight
        with _TEL.span("backend_oracle"):
            _ORACLE.update(preflight(
                deadline_s=float(os.environ.get("JAXMC_ORACLE_DEADLINE",
                                                "10")),
                tel=_TEL, use_cache=False))
        _log(f"backend oracle: {_ORACLE.get('platform') or 'none'} "
             f"({_ORACLE.get('reason')}; {_ORACLE.get('wall_s')}s)")
    except Exception as ex:  # noqa: BLE001 — preflight must never
        # kill the bench round it exists to speed up
        _log(f"backend oracle failed ({ex}); legacy probe path")

    accel = _ORACLE.get("platform") \
        if _ORACLE.get("platform") in ("tpu", "gpu") else "tpu"
    t_cpu = threading.Thread(target=_cpu_worker, daemon=True)
    t_tpu = threading.Thread(target=_accel_worker, daemon=True)
    t_cpu.start()
    t_tpu.start()

    # wait until the deadline, or stop early once the best line this
    # environment can produce is in hand
    while _remaining() > 10:
        if _RESULTS.has(accel, "full"):
            break
        if not t_tpu.is_alive() and not t_cpu.is_alive():
            break
        if not t_tpu.is_alive():
            # accel worker exited: its quick line (if it landed)
            # outranks any later cpu line — waiting further cannot
            # improve best(); without it, cpu/full is the ceiling
            if _RESULTS.has(accel, "quick") or _RESULTS.has("cpu", "full"):
                break
        time.sleep(3)

    with _PROCS_LOCK:
        _STOPPING.set()  # under the lock: no worker can spawn past this
        for p in _PROCS:
            try:
                p.kill()
            except OSError:
                pass
    key, line = _RESULTS.best()
    # orchestration phases: every child attempt/probe/profile span, with
    # open=True partials for work still in flight at emit time — the
    # record that says where the deadline budget went even when the
    # device path never produced a line
    with _CHILD_FATE_LOCK:
        child_fate = {t: dict(f) for t, f in _CHILD_FATE.items()}
    orch = {"deadline_s": budget,
            "spent_s": round(budget - _remaining(), 1),
            "probe_skipped": _PROBE_SKIPPED,
            # the preflight verdict (ISSUE 11): which platform this
            # round measured and why — per-candidate probe walls
            # included, so a dead-tunnel round is attributed in the
            # artifact of record
            "backend_oracle": dict(_ORACLE) if _ORACLE else None,
            "compile_cache": os.environ.get("JAXMC_COMPILE_CACHE"),
            # per-child fate + retry count (ISSUE 4): a signal-killed
            # child names its signal here instead of an opaque partial
            "child_retries": sum(f["retries"]
                                 for f in child_fate.values()),
            "child_fate": child_fate,
            "phases": _TEL.phase_list(),
            "env": obs.environment_meta()}
    if line is None:
        # truly nothing (emergency child itself failed): emit an explicit
        # failure record rather than silence — parseable, value null
        _log("NO measurement landed before the deadline")
        print(json.dumps({
            "metric": "bench produced no measurement before deadline "
                      "(see stderr)", "value": None,
            "unit": "states/sec", "vs_baseline": None,
            "orchestration": orch}), flush=True)
        sys.exit(1)
    _log(f"emitting {key[0]}/{key[1]} line")
    try:
        rec = json.loads(line)
        # compile wall OUTSIDE the measured window, rolled up from the
        # winning child's own phase spans (ISSUE 5): the steady-state
        # states/sec claim and the one-time compile cost are SEPARATE
        # numbers in the artifact of record
        excl = {p["name"]: p["wall_s"] for p in rec.get("phases", [])
                if p.get("name") in ("device_init", "engine_build",
                                     "warmup_run", "warm_ckpt_build",
                                     "interp_baseline")}
        if excl:
            orch["compile_excluded_from_window"] = {
                "phases": excl, "total_s": round(sum(excl.values()), 1)}
        rec["orchestration"] = orch
        line = json.dumps(rec)
    except ValueError:
        pass  # never let telemetry break the artifact of record
    print(line, flush=True)


if __name__ == "__main__":
    main()
