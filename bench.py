r"""jaxmc benchmark: raft states/sec on the device BFS backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": R}

Workload: the BASELINE.json model of record — the reference raft spec
(/root/reference/examples/raft.tla:482-493 hot path) with Server={s1,s2,s3}
and a bounded log, made finite by the MCraftMicro message-domain constraint
(specs/MCraft_3s_bench.cfg) so the EXHAUSTIVE search completes and the
reported rate covers a full run, not a truncated prefix.

vs_baseline is the speedup over this repo's exact Python interpreter on
the same workload (measured on a capped prefix, cap stated in the metric).
It is NOT the BASELINE.md TLC ratio: TLC needs a JVM, which this image
does not have — BASELINE.md documents that the TLC baseline must be
measured where one exists. Backend count-equivalence is pinned for THIS
benchmark model in the slow-marked
tests/test_kernel2.py::test_raft_3s_bench_whole_run_equivalence (and for
the smaller MCraft_micro model in default CI).

Platform: probes TPU availability in a SUBPROCESS first (the axon TPU
plugin can hang the whole process at init when the tunnel is down — a
timed-out probe costs the subprocess, not the bench), then pins the
surviving platform before first jax use in this process.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(os.path.abspath(__file__))

SPEC = os.path.join(_REPO, "specs", "MCraftMicro.tla")
CFG = os.path.join(_REPO, "specs", "MCraft_3s_bench.cfg")
INTERP_CAP = 20000  # distinct-state cap for the interpreter baseline run


def probe_platform(timeout_s: float = 180.0) -> str:
    """'tpu'/'cpu'/... if device init works; 'cpu (tpu init failed: ...)'
    when the plugin fails or hangs (diagnosed, not silent)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "cpu (tpu init failed: device init timed out after " \
               f"{timeout_s:.0f}s — axon tunnel down?)"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
        return f"cpu (tpu init failed: {tail[0][:120]})"
    return r.stdout.strip()


def load_model():
    from jaxmc.sem.modules import Loader, bind_model
    from jaxmc.front.cfg import parse_cfg
    ldr = Loader([os.path.join(_REPO, "specs"),
                  "/root/reference/examples"])
    return bind_model(ldr.load_path(SPEC), parse_cfg(open(CFG).read()))


def main():
    platform = probe_platform()
    import jax
    if platform.startswith("cpu ("):
        # plugin is broken/hanging: pin the CPU platform before first use
        jax.config.update("jax_platforms", "cpu")
        print(f"bench: {platform}", file=sys.stderr)
    devs = jax.devices()

    from jaxmc.tpu.bfs import TpuExplorer
    from jaxmc.engine.explore import Explorer

    # resident device mode: the whole BFS (frontier, fingerprint set,
    # level loop) runs inside one jitted while_loop on the accelerator —
    # the tunnel's ~160ms round-trip would otherwise dominate. The
    # warm-up run compiles the jit cache AND trains the capacity buckets,
    # so the timed run replays with zero recompiles.
    ex = TpuExplorer(load_model(), store_trace=False, resident=True)
    r_warm = ex.run()
    assert r_warm.ok, "bench workload must pass"
    t0 = time.time()
    r = ex.run()
    jax_wall = time.time() - t0
    assert r.ok and r.distinct == r_warm.distinct
    jax_rate = r.generated / jax_wall

    # interpreter baseline on a capped prefix of the same workload (the
    # interp rate is flat in search depth; full run measured at the same
    # ~5.6k st/s — see specs/MCraft_3s_bench.cfg header)
    ri = Explorer(load_model(), max_states=INTERP_CAP).run()
    interp_rate = ri.generated / ri.wall_s

    out = {
        "metric": (
            f"states/sec, exhaustive raft 3-server "
            f"(reference raft.tla, MCraft_3s_bench: "
            f"{r.generated} generated / {r.distinct} distinct, COMPLETED, "
            f"platform={devs[0].platform}, device-resident BFS); "
            f"vs_baseline = speedup over the exact Python interpreter on "
            f"the same model ({INTERP_CAP}-distinct-state prefix), NOT "
            f"TLC (no JVM in image; BASELINE.md documents the TLC-ratio "
            f"target separately)"),
        "value": round(jax_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(jax_rate / interp_rate, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
