r"""`python -m jaxmc.fleetbench` — the `make fleet-check` chaos gate.

tracecheck.py proves ONE daemon's observability surface; this gate
proves the FLEET substrate (ISSUE 19): several subprocess daemons on
one durable spool, leased claims, crash takeover, warm-hit routing,
admission control, and poison-job quarantine — each leg an end-to-end
subprocess scenario with SIGKILLs, not a unit test:

  takeover    a reference run on a solo spool records the ground-truth
              counts; then 3 daemons share a fleet spool, a slow job
              lands on one of them, and the harness SIGKILLs that
              daemon mid-run (pid parsed from the job's `daemon` id).
              A peer must detect the expired lease, steal the job
              (stolen_by + requeue_note on the record), resume it from
              the spool checkpoint, and finish with counts
              BIT-IDENTICAL to the solo reference; survivors' /metrics
              must show the takeover.
  routing     daemon A is warmed on a signature, then two cold peers
              join.  Identical submissions round-robined across all
              three ports must land on A (submit defers cold
              non-fast-lane sigs to the fleet scan; A adopts on warm
              affinity inside the grace window) — A's share must beat
              the 1/3 a round-robin placement would give it.  After a
              clean drain, `obs timeline --fail-on-orphans` over every
              daemon trace + per-job trace must stitch >= 3 processes
              with ZERO orphan spans.
  admission   a depth-bounded daemon under a submit burst: overflow
              gets a FAST 429 with Retry-After and the queue gauges in
              the body, the admission counter moves, and every
              ACCEPTED job still completes.
  poison      a job whose owner dies on every attempt (daemon_kill
              fault, shared cross-process budget) under a respawning
              supervisor: after JAXMC_JOB_RETRIES cross-daemon deaths
              the job must land in spool/quarantine/<id>.json with a
              named verdict, the spent-retry count, and fault context
              — and GET /jobs/<id> on a live daemon must answer with
              that verdict, not a 404.

Completed-leg result artifacts are copied into --out-dir and appended
to the run ledger (source="fleetbench", rung=<leg>).  When the host
cannot support a fleet (fewer than 2 CPUs, or no loopback port to
bind) the gate prints one parseable `FLEET-CHECK SKIP: <reason>` line
and exits 0.  Exit 0 only when every leg holds; each failure prints
one `fleet-check: FAIL: ...` line.  `make bench-check` runs this after
the trace check.
"""

from __future__ import annotations

import glob
import io
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from .tracecheck import _SLOW_CFG, _SLOW_SPEC, _scrape, _summary_counts

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _skip(reason: str) -> Optional[str]:
    """The skip verdict (None = the host can run a fleet)."""
    return reason


def _host_verdict() -> Optional[str]:
    if os.environ.get("JAXMC_FLEET_FORCE", "").strip() in \
            ("1", "on", "yes", "true"):
        return None
    if (os.cpu_count() or 1) < 2:
        return "need >= 2 CPUs for a multi-daemon fleet " \
               "(JAXMC_FLEET_FORCE=1 overrides)"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("127.0.0.1", 0))
    except OSError as ex:
        return f"cannot bind a loopback port ({ex})"
    return None


def _write_spec(spec_dir: str, name: str, q: int, bound: int) -> str:
    os.makedirs(spec_dir, exist_ok=True)
    spec = os.path.join(spec_dir, f"{name}.tla")
    with open(spec, "w", encoding="utf-8") as fh:
        fh.write(_SLOW_SPEC.format(q=q, bound=bound)
                 .replace("MODULE traceload", f"MODULE {name}"))
    with open(os.path.join(spec_dir, f"{name}.cfg"), "w",
              encoding="utf-8") as fh:
        fh.write(_SLOW_CFG)
    return spec


class _Fleet:
    """Subprocess daemons sharing one spool, discovered through their
    heartbeat records (the serve.json stamp is last-writer-wins, so
    per-daemon ports only live in spool/daemons/<id>.json)."""

    def __init__(self, spool: str, env: Dict[str, str],
                 trace_dir: Optional[str] = None):
        self.spool = spool
        self.env = dict(env)
        self.trace_dir = trace_dir
        self.procs: List[subprocess.Popen] = []

    def start(self, n: int = 1) -> None:
        for _ in range(n):
            i = len(self.procs)
            args = [sys.executable, "-m", "jaxmc.serve", "run",
                    "--spool", self.spool, "--workers", "1", "--quiet"]
            if self.trace_dir:
                args += ["--trace", os.path.join(
                    self.trace_dir, f"daemon{i}.trace.jsonl")]
            env = dict(os.environ, JAX_PLATFORMS="cpu", **self.env)
            self.procs.append(subprocess.Popen(
                args, cwd=_REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

    def daemons(self, live_only: bool = True) -> List[Dict[str, Any]]:
        """Heartbeat records of OUR daemons (matched by pid)."""
        pids = {p.pid for p in self.procs
                if not live_only or p.poll() is None}
        out = []
        for path in sorted(glob.glob(
                os.path.join(self.spool, "daemons", "*.json"))):
            try:
                with open(path, encoding="utf-8") as fh:
                    rec = json.load(fh)
            except (OSError, ValueError):
                continue
            if rec.get("pid") in pids:
                out.append(rec)
        return out

    def wait_up(self, n: int, timeout: float = 60.0
                ) -> List[Dict[str, Any]]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            recs = self.daemons()
            if len(recs) >= n:
                return recs
            if all(p.poll() is not None for p in self.procs):
                break
            time.sleep(0.1)
        raise AssertionError(
            f"only {len(self.daemons())}/{n} daemons heartbeating in "
            f"{self.spool} after {timeout:.0f}s")

    def client(self, rec: Dict[str, Any]):
        from .serve.protocol import ServeClient
        return ServeClient(rec.get("host", "127.0.0.1"), rec["port"])

    def any_client(self):
        recs = self.daemons()
        assert recs, f"no live daemon on {self.spool}"
        return self.client(recs[0])

    def stop(self, graceful: bool = True, timeout: float = 30.0) -> None:
        for p in self.procs:
            if p.poll() is None and graceful:
                p.terminate()  # SIGTERM -> cooperative drain, exit 0
        deadline = time.time() + timeout
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(10)


def _job_record(spool: str, jid: str) -> Optional[Dict[str, Any]]:
    """Read a job record straight off the spool — robust to every
    daemon being dead, which is the point of this gate."""
    for sub in ("jobs", "quarantine"):
        try:
            with open(os.path.join(spool, sub, f"{jid}.json"),
                      encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            continue
    return None


def _wait_spool(spool: str, jid: str, statuses: Tuple[str, ...],
                timeout: float) -> Dict[str, Any]:
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        rec = _job_record(spool, jid)
        if rec is not None:
            last = rec.get("status")
            if last in statuses:
                return rec
        time.sleep(0.15)
    raise AssertionError(f"job {jid} still {last!r} after "
                         f"{timeout:.0f}s (wanted {statuses})")


def _daemon_pid(daemon_id: str) -> int:
    """Heartbeat ids are `d<pid>-<hex>` so a chaos harness can aim a
    SIGKILL without a side channel."""
    return int(daemon_id[1:].split("-", 1)[0])


def _metric_total(recs: List[Dict[str, Any]], name: str) -> float:
    total = 0.0
    for rec in recs:
        try:
            text = _scrape(rec.get("host", "127.0.0.1"), rec["port"])
        except OSError:
            continue
        for ln in text.splitlines():
            if ln.startswith(name + " "):
                total += float(ln.rsplit(" ", 1)[1])
    return total


def _keep_artifact(spool: str, jid: str, out_dir: str, leg: str,
                   rec: Optional[Dict[str, Any]] = None) -> None:
    """Copy the leg's result artifact into --out-dir and append it to
    the run ledger (rung = the leg name); never fails the gate."""
    try:
        os.makedirs(out_dir, exist_ok=True)
        dst = os.path.join(out_dir, f"jaxmc_fleetbench_{leg}.json")
        src = os.path.join(spool, "results", f"{jid}.json")
        if os.path.exists(src):
            shutil.copyfile(src, dst)
            from .obs.ledger import append_summary
            with open(src, encoding="utf-8") as fh:
                append_summary(json.load(fh), source="fleetbench",
                               rung=leg)
        elif rec is not None:
            with open(dst, "w", encoding="utf-8") as fh:
                json.dump(rec, fh, indent=1)
        print(f"fleet-check: {leg}: artifact {dst}")
    except (OSError, ValueError) as ex:
        print(f"fleet-check: {leg}: artifact copy skipped ({ex})",
              file=sys.stderr)


# ---------------------------------------------------------------- legs

def _leg_takeover(work: str, out_dir: str, timeout: float,
                  failures: List[str]) -> None:
    spec = _write_spec(os.path.join(work, "specs"), "takeoverload",
                      q=1500, bound=20)
    opts = {"backend": "interp", "progress_every": 2}

    # ground truth: the same job on a solo spool, no chaos
    solo = _Fleet(os.path.join(work, "spool_solo"),
                  {"JAXMC_SERVE_CKPT_EVERY": "0.3"})
    solo.start(1)
    try:
        rec = solo.wait_up(1)[0]
        client = solo.client(rec)
        code, job = client.submit(spec, None, opts)
        assert code == 200, f"solo submit failed ({code}): {job}"
        ref = _wait_spool(solo.spool, job["id"], ("done",), timeout)
    finally:
        solo.stop()
    ref_counts = (ref.get("generated"), ref.get("distinct"))

    # the fleet: 3 daemons, short leases, eager checkpoints
    fleet = _Fleet(os.path.join(work, "spool_fleet"), {
        "JAXMC_SERVE_CKPT_EVERY": "0.3",
        "JAXMC_LEASE_TTL": "1.5",
        "JAXMC_LEASE_AFFINITY_GRACE": "0.2",
    })
    fleet.start(3)
    try:
        recs = fleet.wait_up(3)
        code, job = fleet.client(recs[0]).submit(spec, None, opts)
        assert code == 200, f"fleet submit failed ({code}): {job}"
        jid = job["id"]

        # wait until a daemon owns it, give it one checkpoint cadence,
        # then SIGKILL the owner (pid parsed from the daemon id)
        deadline = time.time() + timeout
        owner = None
        while time.time() < deadline:
            rec = _job_record(fleet.spool, jid) or {}
            if rec.get("status") == "running" and rec.get("daemon"):
                owner = rec["daemon"]
                break
            time.sleep(0.1)
        assert owner, f"job {jid} never started running"
        time.sleep(1.0)  # let at least one spool checkpoint land
        os.kill(_daemon_pid(owner), signal.SIGKILL)

        done = _wait_spool(fleet.spool, jid, ("done", "failed",
                                              "quarantined"), timeout)
        if done.get("status") != "done":
            failures.append(
                f"takeover: job ended {done.get('status')!r} "
                f"({done.get('verdict') or done.get('error')})")
            return
        if done.get("daemon") == owner:
            failures.append(f"takeover: job finished on the KILLED "
                            f"daemon {owner} — lease takeover never "
                            f"happened")
        if not done.get("stolen_by"):
            failures.append("takeover: finished record carries no "
                            "stolen_by — the peer did not go through "
                            "the lease steal")
        got = (done.get("generated"), done.get("distinct"))
        if got != ref_counts:
            failures.append(f"takeover: counts {got} != solo "
                            f"reference {ref_counts} — the resumed "
                            f"run diverged")
        takeovers = _metric_total(fleet.daemons(),
                                  "jaxmc_serve_takeovers")
        if takeovers < 1:
            failures.append(f"takeover: survivors report "
                            f"{takeovers:.0f} jaxmc_serve_takeovers, "
                            f"expected >= 1")
        if not failures:
            print(f"fleet-check: takeover: ok — {owner} killed "
                  f"mid-run, {done.get('daemon')} finished with "
                  f"identical counts {got} "
                  f"(note={done.get('requeue_note')!r})")
        _keep_artifact(fleet.spool, jid, out_dir, "takeover")
    finally:
        fleet.stop()


def _leg_routing(work: str, out_dir: str, timeout: float,
                 failures: List[str]) -> None:
    spec = _write_spec(os.path.join(work, "specs"), "routeload",
                      q=200, bound=12)
    opts = {"backend": "interp"}
    trace_dir = os.path.join(work, "routing_traces")
    os.makedirs(trace_dir, exist_ok=True)
    fleet = _Fleet(os.path.join(work, "spool_routing"), {
        # nothing rides the fast lane, so cold sigs DEFER to the fleet
        # scan and warm affinity decides placement
        "JAXMC_SERVE_FASTLANE_BOUND": "0",
        "JAXMC_LEASE_AFFINITY_GRACE": "5.0",
    }, trace_dir=trace_dir)
    # warm daemon A ALONE first (fleet of 1 enqueues locally)
    fleet.start(1)
    try:
        rec_a = fleet.wait_up(1)[0]
        code, job = fleet.client(rec_a).submit(spec, None, opts)
        assert code == 200, f"warmup submit failed ({code}): {job}"
        _wait_spool(fleet.spool, job["id"], ("done",), timeout)
        a_id = rec_a["id"]

        # two cold peers join, then identical jobs round-robin across
        # every port — warm-hit routing must beat that placement
        fleet.start(2)
        recs = fleet.wait_up(3)
        time.sleep(1.5)  # let every fleet scan see fleet_size == 3
        jids = []
        for i in range(4):
            rec = recs[i % len(recs)]
            code, job = fleet.client(rec).submit(spec, None, opts)
            assert code == 200, \
                f"routing submit {i} failed ({code}): {job}"
            jids.append(job["id"])
        owners = [_wait_spool(fleet.spool, j, ("done",),
                              timeout).get("daemon") for j in jids]
        share = sum(1 for o in owners if o == a_id) / len(owners)
        if share <= 1 / 3:
            failures.append(
                f"routing: warm daemon {a_id} ran only "
                f"{share:.0%} of identical jobs ({owners}) — no "
                f"better than round-robin placement")
        live = fleet.daemons()
        deferred = _metric_total(live, "jaxmc_serve_jobs_deferred")
        affine = _metric_total(live, "jaxmc_serve_affinity_adoptions")
        if deferred < 1:
            failures.append("routing: no submission was deferred to "
                            "the fleet scan — the routing path never "
                            "engaged")
        if affine < 1:
            failures.append("routing: no affinity adoption recorded — "
                            "the warm daemon won by luck, not routing")
        if not failures:
            print(f"fleet-check: routing: ok — warm daemon took "
                  f"{share:.0%} of 4 round-robined jobs "
                  f"(deferred={deferred:.0f}, affine={affine:.0f})")
        _keep_artifact(fleet.spool, jids[-1], out_dir, "routing")

        # drain cleanly, then the orphan gate over EVERY trace
        fleet.stop(graceful=True)
        traces = sorted(glob.glob(
            os.path.join(trace_dir, "*.trace.jsonl"))) + sorted(
            glob.glob(os.path.join(fleet.spool, "results",
                                   "*.trace.jsonl")))
        from .obs.report import main as obs_main
        buf = io.StringIO()
        rc = obs_main(["timeline", "--fail-on-orphans"] + traces,
                      out=buf)
        counts = _summary_counts(buf.getvalue())
        if rc != 0 or counts.get("orphans", -1) != 0:
            failures.append(
                f"routing: obs timeline found "
                f"{counts.get('orphans')} orphan spans (rc={rc}) "
                f"across the fleet's traces")
        elif counts.get("processes", 0) < 3:
            failures.append(
                f"routing: timeline stitched only "
                f"{counts.get('processes')} processes, expected the "
                f"3 daemons")
        else:
            print(f"fleet-check: routing: timeline ok — "
                  f"{counts['processes']} processes, "
                  f"{counts['events']} events, 0 orphans")
    finally:
        fleet.stop()


def _leg_admission(work: str, out_dir: str, timeout: float,
                   failures: List[str]) -> None:
    spec = _write_spec(os.path.join(work, "specs"), "admitload",
                      q=1500, bound=20)
    opts = {"backend": "interp"}
    fleet = _Fleet(os.path.join(work, "spool_admission"),
                   {"JAXMC_SERVE_MAX_DEPTH": "2"})
    fleet.start(1)
    try:
        rec = fleet.wait_up(1)[0]
        client = fleet.client(rec)
        accepted, rejected = [], []
        for i in range(8):
            code, job = client.submit(spec, None, opts,
                                      tenant="burst")
            if code == 200:
                accepted.append(job["id"])
            elif code == 429:
                rejected.append((dict(client.last_headers), job))
            else:
                failures.append(f"admission: submit {i} got "
                                f"unexpected {code}: {job}")
                return
            time.sleep(0.05)
        if not rejected:
            failures.append("admission: 8 submissions into a "
                            "depth-2 spool produced no 429")
            return
        headers, body = rejected[0]
        retry = headers.get("Retry-After")
        if not retry or float(retry) < 1:
            failures.append(f"admission: 429 Retry-After "
                            f"{retry!r}, expected >= 1s")
        if body.get("reason") not in ("queue_full", "tenant_rate"):
            failures.append(f"admission: 429 body carries no named "
                            f"reason: {body}")
        if body.get("reason") == "queue_full" and \
                "queue_depth" not in body:
            failures.append(f"admission: queue_full 429 body lacks "
                            f"the queue gauges: {body}")
        n429 = _metric_total([rec], "jaxmc_serve_admission_rejected")
        if n429 < len(rejected):
            failures.append(
                f"admission: /metrics shows {n429:.0f} "
                f"admission_rejected for {len(rejected)} 429s")
        # every job the daemon ACCEPTED must still complete
        for jid in accepted:
            done = _wait_spool(fleet.spool, jid, ("done", "failed"),
                               timeout)
            if done.get("status") != "done":
                failures.append(f"admission: accepted job {jid} "
                                f"ended {done.get('status')!r}")
        if not failures:
            print(f"fleet-check: admission: ok — "
                  f"{len(accepted)} accepted (all completed), "
                  f"{len(rejected)} refused with 429 "
                  f"Retry-After={retry}s "
                  f"reason={body.get('reason')}")
        if accepted:
            _keep_artifact(fleet.spool, accepted[0], out_dir,
                           "admission")
    finally:
        fleet.stop()


def _leg_poison(work: str, out_dir: str, timeout: float,
                failures: List[str]) -> None:
    spec = _write_spec(os.path.join(work, "specs"), "poisonload",
                      q=50, bound=6)
    retries = 2
    fault_state = os.path.join(work, "poison_fault_state")
    os.makedirs(fault_state, exist_ok=True)
    fleet = _Fleet(os.path.join(work, "spool_poison"), {
        # every daemon that marks this spec running SIGKILLs itself;
        # the budget latch dir is SHARED so respawned lives keep
        # spending the same cross-daemon budget
        "JAXMC_FAULTS": "daemon_kill:spec=poisonload.tla:n=99",
        "JAXMC_FAULTS_STATE": fault_state,
        "JAXMC_JOB_RETRIES": str(retries),
        "JAXMC_LEASE_TTL": "1.0",
        "JAXMC_LEASE_AFFINITY_GRACE": "0.1",
        "JAXMC_SERVE_CKPT_EVERY": "0.3",
    })
    fleet.start(2)
    try:
        recs = fleet.wait_up(2)
        code, job = fleet.client(recs[0]).submit(
            spec, None, {"backend": "interp"})
        assert code == 200, f"poison submit failed ({code}): {job}"
        jid = job["id"]

        # supervisor: respawn dead daemons until quarantine verdict
        qpath = os.path.join(fleet.spool, "quarantine", f"{jid}.json")
        deadline = time.time() + timeout
        respawns = 0
        while time.time() < deadline and not os.path.exists(qpath):
            dead = sum(1 for p in fleet.procs if p.poll() is not None)
            live = len(fleet.procs) - dead
            while live < 2 and respawns < 8:
                fleet.start(1)
                live += 1
                respawns += 1
            time.sleep(0.2)
        rec = _job_record(fleet.spool, jid) or {}
        if rec.get("status") != "quarantined":
            failures.append(
                f"poison: job never quarantined (status "
                f"{rec.get('status')!r} after {respawns} respawns) — "
                f"the cross-daemon retry budget never exhausted")
            return
        if "poison" not in str(rec.get("verdict", "")):
            failures.append(f"poison: quarantine verdict is not "
                            f"named: {rec.get('verdict')!r}")
        if rec.get("retries_spent") != retries:
            failures.append(
                f"poison: {rec.get('retries_spent')} retries spent, "
                f"budget was {retries} — quarantine fired early or "
                f"late")
        if not rec.get("fault_context"):
            failures.append("poison: quarantine record carries no "
                            "fault context for triage")
        # a live daemon must answer for the quarantined id by name
        fleet.wait_up(1)
        code, got = fleet.any_client().job(jid)
        if code != 200 or got.get("status") != "quarantined":
            failures.append(
                f"poison: GET /jobs/{jid} on a live daemon returned "
                f"{code} status={got.get('status')!r}, expected the "
                f"quarantine verdict")
        if not failures:
            print(f"fleet-check: poison: ok — quarantined after "
                  f"{retries} cross-daemon deaths "
                  f"(verdict={rec.get('verdict')!r}, "
                  f"trace_tail={len(rec.get('trace_tail', []))} "
                  f"lines)")
        _keep_artifact(fleet.spool, jid, out_dir, "poison", rec=rec)
    finally:
        fleet.stop(graceful=False)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.fleetbench",
        description="the make fleet-check multi-daemon chaos gate")
    ap.add_argument("--out-dir", default="/tmp",
                    help="where leg artifacts land (the bench-check "
                         "run ledger imports them)")
    ap.add_argument("--work", default=None,
                    help="scratch root; default: a fresh temp dir")
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="per-leg wall budget")
    ap.add_argument("--legs", default="takeover,routing,admission,"
                                      "poison",
                    help="comma-separated subset to run")
    args = ap.parse_args(argv)

    verdict = _host_verdict()
    if verdict is not None:
        print(f"FLEET-CHECK SKIP: {verdict}")
        return 0

    work = args.work or tempfile.mkdtemp(prefix="jaxmc_fleet_check_")
    print(f"fleet-check: scratch {work}")
    legs = {"takeover": _leg_takeover, "routing": _leg_routing,
            "admission": _leg_admission, "poison": _leg_poison}
    failures: List[str] = []
    ran = []
    for name in args.legs.split(","):
        name = name.strip()
        if not name:
            continue
        fn = legs.get(name)
        if fn is None:
            failures.append(f"unknown leg {name!r}")
            continue
        before = len(failures)
        try:
            fn(work, args.out_dir, args.timeout, failures)
        except AssertionError as ex:
            failures.append(f"{name}: {ex}")
        ran.append(name)
        if len(failures) == before:
            print(f"fleet-check: leg {name}: PASS")
    for f in failures:
        print(f"fleet-check: FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"fleet-check: PASS — legs {', '.join(ran)} all held "
              f"(SIGKILL takeover resumed bit-identically; overload "
              f"answers 429 + Retry-After; poison jobs quarantine "
              f"with a named verdict)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
