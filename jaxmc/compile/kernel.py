r"""Kernel compiler: grounded actions -> jit/vmap-able transition kernels
(SURVEY.md §7.4).

Each GroundedAction compiles to f(row: i32[W]) -> (enabled: bool,
assert_ok: bool, succ_row: i32[W]); invariants compile to row -> bool.
The compiler is a symbolic evaluator over the same AST the interpreter
walks: state variables decode to trees of traced jnp scalars, guards fold
into an enabled mask, IF on a traced condition becomes jnp.where, and
anything outside the compilable subset raises CompileError so the caller
falls back to the interpreter.

TPU notes: everything is i32/bool lanes — no dynamic shapes, no python
control flow on traced values, so XLA fuses each action into straight-line
vector code that vmaps over the frontier.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..front import tla_ast as A
from ..sem.values import EvalError, Fcn, ModelValue, fmt, in_set, sort_key
from ..sem.eval import Ctx, OpClosure, eval_expr, bind_pattern
from ..sem.modules import Model
from .ground import (CompileError, EnumUniverse, GroundedAction, Spec_,
                     StateLayout, ground_actions)


# ---- symbolic values ----
# int  -> jnp i32 scalar or python int
# bool -> jnp bool scalar or python bool
# enum -> SEnum (index, possibly traced)
# fcn  -> SFcn {static key -> symbolic value}
# sets/strings stay static python values

class SEnum:
    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


class SFcn:
    __slots__ = ("d",)

    def __init__(self, d: Dict[Any, Any]):
        self.d = d


def _is_traced(v) -> bool:
    return isinstance(v, jnp.ndarray) or hasattr(v, "aval")


def _sym_decode(row, spec: Spec_, off: int, uni: EnumUniverse):
    if spec.kind == "int":
        return row[off], off + 1
    if spec.kind == "bool":
        return row[off] != 0, off + 1
    if spec.kind == "enum":
        return SEnum(row[off]), off + 1
    if spec.kind == "fcn":
        d = {}
        for k, es in zip(spec.dom, spec.elems):
            d[k], off = _sym_decode(row, es, off, uni)
        return SFcn(d), off
    if spec.kind == "set":
        d = {}
        for m in spec.dom:
            d[m] = row[off] != 0
            off += 1
        return ("$symset", d), off
    raise CompileError(f"cannot symbolically decode {spec.kind}")


def _sym_encode(v, spec: Spec_, uni: EnumUniverse, out: List):
    if spec.kind == "int":
        out.append(_as_int(v))
    elif spec.kind == "bool":
        b = _as_bool(v)
        out.append(jnp.where(b, 1, 0) if _is_traced(b) else (1 if b else 0))
    elif spec.kind == "enum":
        out.append(_enum_idx(v, uni))
    elif spec.kind == "fcn":
        if isinstance(v, Fcn):
            v = SFcn(dict(v.d))
        if not isinstance(v, SFcn):
            raise CompileError(f"expected function value, got {v!r}")
        if set(map(_key, v.d.keys())) != set(map(_key, spec.dom)):
            raise CompileError("function domain drifted from layout")
        lookup = { _key(k): val for k, val in v.d.items() }
        for k, es in zip(spec.dom, spec.elems):
            _sym_encode(lookup[_key(k)], es, uni, out)
    elif spec.kind == "set":
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "$symset":
            d = v[1]
            for m in spec.dom:
                b = d.get(m, False)
                out.append(jnp.where(b, 1, 0) if _is_traced(b)
                           else (1 if b else 0))
        elif isinstance(v, frozenset):
            extra = v - frozenset(spec.dom)
            if extra:
                raise CompileError(f"set outside universe: {fmt(extra)}")
            for m in spec.dom:
                out.append(1 if m in v else 0)
        else:
            raise CompileError(f"expected set value, got {v!r}")
    else:
        raise AssertionError(spec.kind)


def _key(k):
    return (type(k).__name__, k.name if isinstance(k, ModelValue) else k)


def _as_int(v):
    if isinstance(v, bool):
        raise CompileError("boolean used as integer")
    if isinstance(v, int) or _is_traced(v):
        return v
    raise CompileError(f"expected integer, got {v!r}")


def _as_bool(v):
    if isinstance(v, bool) or _is_traced(v):
        return v
    raise CompileError(f"expected boolean, got {v!r}")


def _enum_idx(v, uni: EnumUniverse):
    if isinstance(v, SEnum):
        return v.idx
    if isinstance(v, (str, ModelValue)):
        return uni.index(v)
    raise CompileError(f"expected enum value, got {v!r}")


def _land(a, b):
    if a is True:
        return b
    if b is True:
        return a
    if a is False or b is False:
        return False
    return jnp.logical_and(a, b)


def _lor(a, b):
    if a is False:
        return b
    if b is False:
        return a
    if a is True or b is True:
        return True
    return jnp.logical_or(a, b)


def _lnot(a):
    if isinstance(a, bool):
        return not a
    return jnp.logical_not(a)


def _where(c, a, b):
    """Symbolic IF merging two symbolic values of matching structure."""
    if isinstance(c, bool):
        return a if c else b
    if isinstance(a, SEnum) or isinstance(b, SEnum):
        return SEnum(jnp.where(c, _sel(a, "enum"), _sel(b, "enum")))
    if isinstance(a, SFcn) or isinstance(b, SFcn):
        da = a.d if isinstance(a, SFcn) else dict(a.d)  # Fcn static
        db = b.d if isinstance(b, SFcn) else dict(b.d)
        ka = {_key(k): k for k in da}
        kb = {_key(k): k for k in db}
        if set(ka) != set(kb):
            raise CompileError("IF branches build different function domains")
        return SFcn({ka[k]: _where(c, da[ka[k]], db[kb[k]]) for k in ka})
    return jnp.where(c, a, b)


def _sel(v, kind):
    if kind == "enum":
        if isinstance(v, SEnum):
            return v.idx
        raise CompileError(f"IF branch mixes enum with {v!r}")
    return v


class SymCtx:
    __slots__ = ("model", "uni", "bound", "state", "primes")

    def __init__(self, model, uni, bound, state, primes):
        self.model = model
        self.uni = uni
        self.bound = bound    # static + symbolic bindings
        self.state = state    # var -> symbolic tree
        self.primes = primes  # var -> symbolic tree (partial)

    def with_bound(self, extra):
        return SymCtx(self.model, self.uni, {**self.bound, **extra},
                      self.state, self.primes)


def _sym_eq(a, b, uni):
    """Symbolic equality; returns bool or traced bool."""
    # unwrap static Fcn to SFcn for uniform handling
    if isinstance(a, Fcn):
        a = SFcn(dict(a.d))
    if isinstance(b, Fcn):
        b = SFcn(dict(b.d))
    if isinstance(a, SEnum) or isinstance(b, SEnum):
        ia, ib = _enum_idx(a, uni), _enum_idx(b, uni)
        if isinstance(ia, int) and isinstance(ib, int):
            return ia == ib
        return jnp.equal(ia, ib)
    if isinstance(a, SFcn) and isinstance(b, SFcn):
        ka = {_key(k): k for k in a.d}
        kb = {_key(k): k for k in b.d}
        if set(ka) != set(kb):
            return False
        acc = True
        for k in ka:
            acc = _land(acc, _sym_eq(a.d[ka[k]], b.d[kb[k]], uni))
        return acc
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return a == b
        return jnp.equal(a, b)
    if isinstance(a, int) and isinstance(b, int):
        return a == b
    sa = isinstance(a, tuple) and len(a) == 2 and a[0] == "$symset"
    sb = isinstance(b, tuple) and len(b) == 2 and b[0] == "$symset"
    if sa or sb:
        da = a[1] if sa else ({m: True for m in a}
                              if isinstance(a, frozenset) else None)
        db = b[1] if sb else ({m: True for m in b}
                              if isinstance(b, frozenset) else None)
        if da is None or db is None:
            raise CompileError("set compared with non-set value")
        acc = True
        for m in set(map(_key, da)) | set(map(_key, db)):
            la = {_key(k): v for k, v in da.items()}.get(m, False)
            lb = {_key(k): v for k, v in db.items()}.get(m, False)
            same = jnp.equal(la, lb) if (_is_traced(la) or _is_traced(lb))                 else (la == lb)
            acc = _land(acc, same)
        return acc
    if _is_traced(a) or _is_traced(b):
        return jnp.equal(a, b)
    # both static non-traced values
    from ..sem.values import tla_eq
    from ..sem.values import EvalError as _EE
    try:
        return tla_eq(a, b)
    except _EE as ex:
        raise CompileError(str(ex))


def sym_eval(e: A.Node, s: SymCtx):
    """Symbolic evaluation; returns a symbolic value or raises CompileError."""
    uni = s.uni
    t = type(e)
    if t is A.Num:
        return e.val
    if t is A.Str:
        return SEnum(uni.index(e.val)) if e.val in uni.to_idx else e.val
    if t is A.Bool:
        return e.val
    if t is A.Ident:
        name = e.name
        if name in s.bound:
            return _wrap_static(s.bound[name], uni)
        if name in s.state:
            return s.state[name]
        d = s.model.defs.get(name)
        if isinstance(d, OpClosure):
            if d.params:
                raise CompileError(f"operator {name} used as value")
            return sym_eval(d.body, s)
        if d is not None:
            return _wrap_static(d, uni)
        raise CompileError(f"unknown identifier {name}")
    if t is A.Prime:
        if not isinstance(e.expr, A.Ident):
            raise CompileError("primed non-variable")
        name = e.expr.name
        if name not in s.primes:
            raise CompileError(f"{name}' read before assignment")
        return s.primes[name]
    if t is A.OpApp:
        return _sym_opapp(e, s)
    if t is A.FnApp:
        f = sym_eval(e.fn, s)
        args = [sym_eval(a, s) for a in e.args]
        return _sym_apply(f, args, s)
    if t is A.Dot:
        f = sym_eval(e.expr, s)
        return _sym_apply(f, [e.fld], s)
    if t is A.If:
        c = sym_eval(e.cond, s)
        if isinstance(c, bool):
            return sym_eval(e.then if c else e.els, s)
        a = sym_eval(e.then, s)
        b = sym_eval(e.els, s)
        return _where(c, a, b)
    if t is A.Case:
        # fold to nested IF
        node = None
        for g, b in reversed(e.arms):
            if node is None:
                if e.other is not None:
                    node = A.If(g, b, e.other)
                else:
                    node = b  # last guard assumed true when taken
            else:
                node = A.If(g, b, node)
        return sym_eval(node, s)
    if t is A.Except:
        f = sym_eval(e.fn, s)
        if isinstance(f, Fcn):
            f = SFcn(dict(f.d))
        if not isinstance(f, SFcn):
            raise CompileError("EXCEPT on non-function")
        d = dict(f.d)
        for path, rhs in e.updates:
            d = _sym_except(d, list(path), rhs, s)
        return SFcn(d)
    if t is A.TupleExpr:
        return SFcn({i + 1: sym_eval(x, s) for i, x in enumerate(e.items)})
    if t is A.FnDef:
        # [x \in S |-> body] with static S
        entries = {}
        binders = []
        for names, sexpr in e.binders:
            sval = _static_set(sexpr, s)
            for pat in names:
                binders.append((pat, sval))
        if len(binders) != 1:
            raise CompileError("multi-binder function constructors "
                               "not compilable yet")
        pat, sval = binders[0]
        for v in sorted(sval, key=sort_key):
            b = bind_pattern(pat, v) if isinstance(pat, tuple) else {pat: v}
            entries[v] = sym_eval(e.body, s.with_bound(b))
        return SFcn(entries)
    if t is A.Quant:
        acc = True if e.kind == "A" else False
        for b in _static_bindings(e.binders, s):
            v = _as_bool(sym_eval(e.body, s.with_bound(b)))
            acc = _land(acc, v) if e.kind == "A" else _lor(acc, v)
        return acc
    if t is A.SetFilter:
        # only static filtering is compilable
        sval = _static_set(e.set, s)
        out = []
        for v in sorted(sval, key=sort_key):
            b = bind_pattern(e.var, v) if isinstance(e.var, tuple) \
                else {e.var: v}
            p = sym_eval(e.pred, s.with_bound(b))
            if not isinstance(p, bool):
                raise CompileError("set filter over traced predicate")
            if p:
                out.append(v)
        return frozenset(out)
    if t is A.Let:
        defs = {}
        for d in e.defs:
            if isinstance(d, A.OpDef) and not d.params:
                defs[d.name] = ("$letdef", d.body)
            elif isinstance(d, A.OpDef):
                defs[d.name] = ("$letop", d)
            else:
                raise CompileError("non-operator LET in compiled expression")
        return sym_eval(e.body, s.with_bound(defs))
    if t is A.Choose:
        # static CHOOSE only
        sval = _static_set(e.set, s) if e.set is not None else None
        if sval is None:
            raise CompileError("unbounded CHOOSE")
        for v in sorted(sval, key=sort_key):
            b = bind_pattern(e.var, v) if isinstance(e.var, tuple) \
                else {e.var: v}
            p = sym_eval(e.pred, s.with_bound(b))
            if not isinstance(p, bool):
                raise CompileError("CHOOSE over traced predicate")
            if p:
                return v
        raise CompileError("CHOOSE: no witness")
    raise CompileError(f"cannot compile {t.__name__} node")


def _wrap_static(v, uni):
    if isinstance(v, tuple) and len(v) == 2 and v[0] == "$letdef":
        raise CompileError("internal: unexpanded let")
    if isinstance(v, (str, ModelValue)) and v in uni.to_idx:
        return SEnum(uni.index(v))
    return v


def _static_set(sexpr, s: SymCtx):
    from ..sem.values import enumerate_set
    try:
        ctx = Ctx(s.model.defs, {k: v for k, v in s.bound.items()
                                 if not _symbolic(v)}, None, None, ())
        return frozenset(enumerate_set(eval_expr(sexpr, ctx)))
    except EvalError as ex:
        raise CompileError(f"non-static set in compiled position: {ex}")


def _symbolic(v):
    return isinstance(v, (SEnum, SFcn)) or _is_traced(v)


def _static_bindings(binders, s: SymCtx):
    import itertools
    groups = []
    for names, sexpr in binders:
        sval = sorted(_static_set(sexpr, s), key=sort_key)
        for pat in names:
            groups.append((pat, sval))
    for combo in itertools.product(*[g[1] for g in groups]):
        b = {}
        for (pat, _), v in zip(groups, combo):
            if isinstance(pat, tuple):
                b.update(bind_pattern(pat, v))
            else:
                b[pat] = v
        yield b


def _sym_apply(f, args, s: SymCtx):
    if isinstance(f, tuple) and len(f) == 2 and f[0] == "$letdef":
        raise CompileError("internal: let in apply")
    if isinstance(f, Fcn):
        f = SFcn(dict(f.d))
    if isinstance(f, SFcn):
        key = args[0] if len(args) == 1 else tuple(args)
        if isinstance(key, SEnum):
            if isinstance(key.idx, int):
                key = s.uni.value(key.idx)
            else:
                # symbolic index: select across domain
                acc = None
                for k, v in f.d.items():
                    if not isinstance(k, (str, ModelValue)):
                        raise CompileError("symbolic application over "
                                           "non-enum domain")
                    cond = jnp.equal(key.idx, s.uni.index(k))
                    acc = v if acc is None else _where(cond, v, acc)
                return acc
        if _is_traced(key):
            # symbolic integer index over int-keyed domain
            acc = None
            for k, v in f.d.items():
                if not isinstance(k, int):
                    raise CompileError("symbolic int application over "
                                       "non-int domain")
                cond = jnp.equal(key, k)
                acc = v if acc is None else _where(cond, v, acc)
            return acc
        lookup = {_key(k): v for k, v in f.d.items()}
        kk = _key(key)
        if kk not in lookup:
            raise CompileError(f"application outside static domain: {key!r}")
        return lookup[kk]
    raise CompileError(f"cannot apply {f!r}")


def _sym_except(d: Dict, path, rhs, s: SymCtx):
    kind, arg = path[0]
    if kind == "idx":
        keys = [sym_eval(a, s) for a in arg]
        key = keys[0] if len(keys) == 1 else tuple(keys)
        if isinstance(key, SEnum):
            if not isinstance(key.idx, int):
                raise CompileError("EXCEPT with traced key")
            key = s.uni.value(key.idx)
        if _is_traced(key):
            raise CompileError("EXCEPT with traced key")
    else:
        key = arg
    lookup = {_key(k): k for k in d}
    kk = _key(key)
    if kk not in lookup:
        raise CompileError(f"EXCEPT key outside domain: {key!r}")
    real_key = lookup[kk]
    old = d[real_key]
    out = dict(d)
    if len(path) == 1:
        out[real_key] = sym_eval(rhs, s.with_bound({"@": old}))
    else:
        inner = old
        if isinstance(inner, Fcn):
            inner = SFcn(dict(inner.d))
        if not isinstance(inner, SFcn):
            raise CompileError("EXCEPT path into non-function")
        out[real_key] = SFcn(_sym_except(dict(inner.d), path[1:], rhs, s))
    return out


_INT_OPS = {
    "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
}
_CMP_OPS = {
    "<": jnp.less, ">": jnp.greater, "<=": jnp.less_equal,
    "=<": jnp.less_equal, "\\leq": jnp.less_equal,
    ">=": jnp.greater_equal, "\\geq": jnp.greater_equal,
}


def _sym_opapp(e: A.OpApp, s: SymCtx):
    name = e.name
    uni = s.uni
    if e.path:
        raise CompileError("instance paths not compilable yet")
    if name == "/\\":
        return _land(_as_bool(sym_eval(e.args[0], s)),
                     _as_bool(sym_eval(e.args[1], s)))
    if name == "\\/":
        return _lor(_as_bool(sym_eval(e.args[0], s)),
                    _as_bool(sym_eval(e.args[1], s)))
    if name == "~":
        return _lnot(_as_bool(sym_eval(e.args[0], s)))
    if name == "=>":
        return _lor(_lnot(_as_bool(sym_eval(e.args[0], s))),
                    _as_bool(sym_eval(e.args[1], s)))
    if name in ("<=>", "\\equiv"):
        a = _as_bool(sym_eval(e.args[0], s))
        b = _as_bool(sym_eval(e.args[1], s))
        if isinstance(a, bool) and isinstance(b, bool):
            return a == b
        return jnp.equal(a, b)
    if name == "=":
        return _sym_eq(sym_eval(e.args[0], s), sym_eval(e.args[1], s), uni)
    if name in ("/=", "#"):
        return _lnot(_sym_eq(sym_eval(e.args[0], s),
                             sym_eval(e.args[1], s), uni))
    if name in _INT_OPS:
        a = _as_int(sym_eval(e.args[0], s))
        b = _as_int(sym_eval(e.args[1], s))
        if isinstance(a, int) and isinstance(b, int):
            return {"+": a + b, "-": a - b, "*": a * b}[name]
        return _INT_OPS[name](a, b)
    if name in _CMP_OPS:
        a = _as_int(sym_eval(e.args[0], s))
        b = _as_int(sym_eval(e.args[1], s))
        if isinstance(a, int) and isinstance(b, int):
            return {"<": a < b, ">": a > b, "<=": a <= b, "=<": a <= b,
                    "\\leq": a <= b, ">=": a >= b,
                    "\\geq": a >= b}[name]
        return _CMP_OPS[name](a, b)
    if name == "\\div":
        a = _as_int(sym_eval(e.args[0], s))
        b = _as_int(sym_eval(e.args[1], s))
        if isinstance(a, int) and isinstance(b, int):
            return a // b
        return jnp.floor_divide(a, b)
    if name == "%":
        a = _as_int(sym_eval(e.args[0], s))
        b = _as_int(sym_eval(e.args[1], s))
        if isinstance(a, int) and isinstance(b, int):
            return a % b
        return jnp.mod(a, b)
    if name == "-.":
        a = _as_int(sym_eval(e.args[0], s))
        return -a if isinstance(a, int) else jnp.negative(a)
    if name == "\\in":
        v = sym_eval(e.args[0], s)
        sv = sym_eval(e.args[1], s)
        if isinstance(sv, frozenset):
            if not _symbolic(v):
                return in_set(v, sv)
            acc = False
            for m in sorted(sv, key=sort_key):
                acc = _lor(acc, _sym_eq(v, _wrap_static(m, uni), uni))
            return acc
        if isinstance(sv, tuple) and len(sv) == 2 and sv[0] == "$symset":
            d = sv[1]
            if _symbolic(v):
                acc = False
                for m, memb in d.items():
                    acc = _lor(acc, _land(
                        memb, _sym_eq(v, _wrap_static(m, uni), uni)))
                return acc
            lookup = {_key(k): b for k, b in d.items()}
            return lookup.get(_key(v), False)
        raise CompileError("\\in over non-static set")
    if name == "\\notin":
        return _lnot(_sym_opapp(A.OpApp("\\in", e.args), s))
    if name == "..":
        a = sym_eval(e.args[0], s)
        b = sym_eval(e.args[1], s)
        if isinstance(a, int) and isinstance(b, int):
            return frozenset(range(a, b + 1))
        raise CompileError("traced interval bounds")
    if name == "Assert":
        raise CompileError("Assert in non-guard position")
    if name == "DOMAIN":
        f = sym_eval(e.args[0], s)
        if isinstance(f, Fcn):
            return f.domain()
        if isinstance(f, SFcn):
            return frozenset(f.d.keys())
        raise CompileError("DOMAIN of non-function")
    if name in ("\\cup", "\\union", "\\cap", "\\intersect", "\\",
                "SUBSET", "UNION", "Cardinality", "\\X", "\\subseteq"):
        # static set algebra only
        args = [sym_eval(a, s) for a in e.args]
        if any(_symbolic(a) for a in args):
            raise CompileError(f"{name} over symbolic operand")
        from ..sem.stdlib import BUILTIN_OPS
        ctx = Ctx(s.model.defs, {}, None, None, ())
        return BUILTIN_OPS[name](args, ctx)
    # user-defined operator
    d = s.model.defs.get(name) if name not in s.bound else s.bound[name]
    if isinstance(d, tuple) and len(d) == 2 and d[0] == "$letdef":
        if e.args:
            raise CompileError("let-operator with args")
        return sym_eval(d[1], s)
    if isinstance(d, tuple) and len(d) == 2 and d[0] == "$letop":
        od = d[1]
        args = [sym_eval(a, s) for a in e.args]
        return sym_eval(od.body, s.with_bound(dict(zip(od.params, args))))
    if isinstance(d, OpClosure):
        args = [sym_eval(a, s) for a in e.args]
        return sym_eval(d.body, s.with_bound(dict(zip(d.params, args))))
    if d is not None and not e.args:
        return _wrap_static(d, uni)
    raise CompileError(f"cannot compile operator {name}")


# ---- action compilation ----

@dataclass
class CompiledAction:
    label: str
    fn: Callable  # row -> (enabled, assert_ok, succ_row)


def compile_action(model: Model, layout: StateLayout,
                   ga: GroundedAction) -> CompiledAction:
    uni = layout.uni
    vars = layout.vars

    def fn(row):
        state = {}
        off = 0
        for v in vars:
            state[v], off = _sym_decode(row, layout.specs[v], off, uni)
        primes: Dict[str, Any] = {}
        enabled = True
        assert_ok = True

        for expr, bound in ga.items:
            sctx = SymCtx(model, uni, dict(bound), state, primes)
            tgt = _prime_target(expr, vars)
            if tgt is not None:
                var, rhs = tgt
                if var in primes:
                    # equality filter on second assignment
                    enabled = _land(enabled, _as_bool(
                        _sym_eq(primes[var], sym_eval(rhs, sctx), uni)))
                else:
                    primes[var] = sym_eval(rhs, sctx)
                continue
            if isinstance(expr, A.Unchanged):
                _apply_unchanged(expr.expr, model, state, primes, vars)
                continue
            if isinstance(expr, A.OpApp) and expr.name == "Assert":
                cond = _as_bool(sym_eval(expr.args[0], sctx))
                # assert fires only if the action is otherwise taken
                if cond is True:
                    continue
                bad = _land(enabled, _lnot(cond))
                assert_ok = _land(assert_ok, _lnot(bad))
                continue
            g = _as_bool(sym_eval(expr, sctx))
            enabled = _land(enabled, g)
        missing = [v for v in vars if v not in primes]
        if missing:
            raise CompileError(
                f"action {ga.label} leaves {missing} unassigned")
        out: List = []
        for v in vars:
            _sym_encode(primes[v], layout.specs[v], uni, out)
        succ = jnp.stack([jnp.asarray(x, dtype=jnp.int32) for x in out])
        en = enabled if _is_traced(enabled) else jnp.asarray(bool(enabled))
        ak = assert_ok if _is_traced(assert_ok) else jnp.asarray(bool(assert_ok))
        return en, ak, succ

    return CompiledAction(ga.label, fn)


def _prime_target(e: A.Node, vars) -> Optional[Tuple[str, A.Node]]:
    if isinstance(e, A.OpApp) and e.name == "=" and \
            isinstance(e.args[0], A.Prime) and \
            isinstance(e.args[0].expr, A.Ident) and \
            e.args[0].expr.name in vars:
        return e.args[0].expr.name, e.args[1]
    return None


def _apply_unchanged(e: A.Node, model: Model, state, primes, vars):
    if isinstance(e, A.Ident):
        if e.name in vars:
            if e.name not in primes:
                primes[e.name] = state[e.name]
            return
        d = model.defs.get(e.name)
        if isinstance(d, OpClosure) and not d.params:
            _apply_unchanged(d.body, model, state, primes, vars)
            return
        raise CompileError(f"UNCHANGED of non-variable {e.name}")
    if isinstance(e, A.TupleExpr):
        for x in e.items:
            _apply_unchanged(x, model, state, primes, vars)
        return
    raise CompileError(f"unsupported UNCHANGED {e!r}")


def compile_predicate(model: Model, layout: StateLayout,
                      expr: A.Node) -> Callable:
    """Compile a state predicate (invariant/constraint) to row -> bool."""
    uni = layout.uni

    def fn(row):
        state = {}
        off = 0
        for v in layout.vars:
            state[v], off = _sym_decode(row, layout.specs[v], off, uni)
        sctx = SymCtx(model, uni, {}, state, {})
        r = _as_bool(sym_eval(expr, sctx))
        return r if _is_traced(r) else jnp.asarray(bool(r))

    return fn
