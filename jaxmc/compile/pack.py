r"""Bit-packed lane plans: per-variable-width state rows (ISSUE 6).

The vspec layout spends one full int32 lane per value component, so a
row is W >= the number of scalar components even when almost every lane
holds a boolean, a tiny enum index, or a capacity-bounded count.  The
frontier, the seen table, and the 128-bit fingerprint loop all pay for
that padding in HBM traffic (and, for `fingerprint128`, in hash
iterations: one per lane).

A LanePlan maps each unpacked lane to a (word, shift, mask, bias) bit
field inside a packed row of `packed_width` int32 words.  Bit widths
come from two sources, combined per lane:

  structural bounds — GUARANTEED by the encoding itself, so packing
      them can never overflow at runtime:
        bool / set-membership / pfcn-present lanes    1 bit
        enum lanes                                    ceil(log2(|uni|))
        seq length / growset / kvtable count lanes    ceil(log2(cap+1))
        union tag lanes                               ceil(log2(#variants))
  observed ranges — raw int lanes are unbounded in principle; their
      range is profiled over the encoded layout-sample rows and widened
      by a margin.  Such lanes are GUARDED: a runtime value outside the
      profiled range raises the engine's packed-lane overflow (the
      engines abort exactly, naming JAXMC_PACK=0 as the escape hatch —
      never a silently wrong count).

Exactness: the lane -> field mapping is injective over the admissible
ranges and SENTINEL_LANE padding maps to a reserved per-lane code, so
packed-row equality == unpacked-row equality == TLA+ value equality.
Exact dedup and fingerprinting over packed rows therefore partition
states exactly as the unpacked rows do (the fp128 collision story is
unchanged).  Zero-padding contexts (sequence tails, absent pfcn values,
short union payloads) force 0 into every affected lane's range so
padding always packs cleanly.

JAXMC_PACK=0|off disables packing (identity plan: packed == unpacked).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .vspec import SENTINEL_LANE, VS

_PACK_OFF = ("0", "off", "none", "disabled")


def packing_enabled() -> bool:
    return os.environ.get("JAXMC_PACK", "1").strip().lower() \
        not in _PACK_OFF


@dataclass
class _LaneClass:
    """Admissible value range of one unpacked lane.

    lo/hi of None mean "no structural bound — profile from observed
    rows and guard at runtime".  `proven` marks a bound derived by the
    static analyzer (jaxmc/analyze/bounds.py): packed at the proven
    width with NO sampling margin, but keeping the runtime OV_PACK
    check as a soundness net — a fired check names the analyzer, and
    the recovery re-profile widens past it (observed ranges always
    extend the bound at plan time)."""
    lo: Optional[int]
    hi: Optional[int]
    guarded: bool
    sent_ok: bool      # the lane can hold SENTINEL_LANE padding
    zero_pad: bool     # the lane can hold 0 padding
    proven: bool = False

    def merge(self, other: "_LaneClass") -> "_LaneClass":
        lo = None if (self.lo is None or other.lo is None) \
            else min(self.lo, other.lo)
        hi = None if (self.hi is None or other.hi is None) \
            else max(self.hi, other.hi)
        return _LaneClass(lo, hi, self.guarded or other.guarded,
                          self.sent_ok or other.sent_ok,
                          self.zero_pad or other.zero_pad,
                          self.proven or other.proven)


def _sb_all(static) -> Optional[Tuple[int, int]]:
    """The covering interval of a static bound: a plain (lo, hi) tuple
    is itself; an analyze.bounds.EB contributes its `all` field."""
    if static is None or isinstance(static, tuple):
        return static
    return static.all


def _sb_child(static, role: str, key=None):
    """Descend a static bound alongside the vspec tree (ISSUE 15).

    A plain (lo, hi) tuple covers every int component, so it passes
    through unchanged (the pre-ISSUE-15 whole-variable behavior).  An
    EB picks the per-key bound when `key` matches a tracked record
    field, else the role child (rng/elem interchange: a tuple value
    abstracted as a sequence still covers function-encoded layouts and
    vice versa), else falls back to the covering `all` interval —
    every fallback is a superset, never a narrower guess."""
    if static is None or isinstance(static, tuple):
        return static
    if key is not None and static.keys and key in static.keys:
        c = static.keys[key]
        return c if c is not None else static.all
    alts = {"rng": ("rng", "elem"), "elem": ("elem", "rng"),
            "dom": ("dom",)}[role]
    for r in alts:
        c = getattr(static, r)
        if c is not None:
            return c
    return static.all


def _walk(spec: VS, uni_n: int, zero_pad: bool, sent_ok: bool,
          out: List[_LaneClass], static=None) -> None:
    """Emit one _LaneClass per lane, in exactly vspec.encode's order.

    `static` is the variable's analyzer-proven bound (ISSUE 9/15):
    either a plain (lo, hi) summary interval covering EVERY integer
    scalar component anywhere in the value, or a structured
    analyze.bounds.EB whose dom/rng/elem/per-key children bound each
    container side separately — element lanes then pack at their own
    proven widths (the EXCEPT-guard container win)."""
    k = spec.kind
    if k == "justempty":
        return
    if k == "int":
        b = _sb_all(static)
        if b is not None:
            out.append(_LaneClass(b[0], b[1], True, sent_ok,
                                  zero_pad, proven=True))
        else:
            out.append(_LaneClass(None, None, True, sent_ok, zero_pad))
    elif k == "bool":
        out.append(_LaneClass(0, 1, False, sent_ok, zero_pad))
    elif k == "enum":
        out.append(_LaneClass(0, max(uni_n - 1, 0), False, sent_ok,
                              zero_pad))
    elif k == "fcn":
        for kk, e in zip(spec.dom, spec.elems):
            _walk(e, uni_n, zero_pad, sent_ok, out,
                  _sb_child(static, "rng", key=kk))
    elif k == "seq":
        out.append(_LaneClass(0, spec.cap, False, sent_ok, zero_pad))
        for _ in range(spec.cap):
            # tail slots beyond the length are zero-padded
            _walk(spec.elem, uni_n, True, sent_ok, out,
                  _sb_child(static, "elem"))
    elif k == "set":
        for _ in spec.dom:
            out.append(_LaneClass(0, 1, False, sent_ok, zero_pad))
    elif k == "growset":
        out.append(_LaneClass(0, spec.cap, False, sent_ok, zero_pad))
        for _ in range(spec.cap):
            # slots beyond the cardinality are SENTINEL-padded
            _walk(spec.elem, uni_n, zero_pad, True, out,
                  _sb_child(static, "elem"))
    elif k == "pfcn":
        for kk, e in zip(spec.dom, spec.elems):
            out.append(_LaneClass(0, 1, False, sent_ok, zero_pad))
            # absent keys zero their value lanes
            _walk(e, uni_n, True, sent_ok, out,
                  _sb_child(static, "rng", key=kk))
    elif k == "union":
        out.append(_LaneClass(0, max(len(spec.variants) - 1, 0), False,
                              sent_ok, zero_pad))
        pay = spec.width - 1
        # payload lanes are OVERLAID across variants: merge the classes
        # positionally; lanes past a variant's width are zero-padded —
        # only the covering interval is sound across the overlay
        cover = _sb_all(static)
        lanes = [_LaneClass(0, 0, False, sent_ok, True)
                 for _ in range(pay)]
        for _names, fields in spec.variants:
            sub: List[_LaneClass] = []
            for f in fields:
                _walk(f, uni_n, True, sent_ok, sub, cover)
            for i, lc in enumerate(sub):
                lanes[i] = lanes[i].merge(lc)
        out.extend(lanes)
    elif k == "kvtable":
        out.append(_LaneClass(0, spec.cap, False, sent_ok, zero_pad))
        for _ in range(spec.cap):
            _walk(spec.elem, uni_n, zero_pad, True, out,
                  _sb_child(static, "dom"))
            _walk(spec.val, uni_n, zero_pad, True, out,
                  _sb_child(static, "rng"))
    else:
        raise AssertionError(k)


def _nbits(n_codes: int) -> int:
    """Bits to address n_codes distinct codes (>= 1 bit)."""
    b = 1
    while (1 << b) < n_codes:
        b += 1
    return b


class LanePlan:
    """The packed layout: per-lane field descriptors + packed width.

    Per-lane arrays (length W):
      word / shift / mask   bit-field placement inside the packed row
      bias                  code = value - bias
      allowed               largest VALID code (sentinel code included)
      sent_code             reserved code for SENTINEL_LANE, -1 if none
      guarded               True for observed-range (int) lanes AND for
                            analyzer-proven lanes: a code outside
                            [0, allowed] at pack time raises the
                            packed-lane overflow
      proven                True for lanes whose bound came from the
                            static analyzer (no sampling margin; the
                            guard is a soundness net that should never
                            fire)
      full                  True for 32-bit (unpacked) lanes: raw bitcast,
                            never guarded
    """

    def __init__(self, width: int, classes: List[_LaneClass],
                 obs_lo: np.ndarray, obs_hi: np.ndarray,
                 obs_seen: np.ndarray, force_identity: bool = False):
        self.width = width
        W = width
        bits = np.zeros(W, np.int64)
        bias = np.zeros(W, np.int64)
        allowed = np.zeros(W, np.int64)
        sent_code = np.full(W, -1, np.int64)
        guarded = np.zeros(W, bool)
        proven = np.zeros(W, bool)
        full = np.zeros(W, bool)
        for i, lc in enumerate(classes):
            lo, hi = lc.lo, lc.hi
            if lo is None or hi is None:
                # observed-range lane (raw int)
                if not obs_seen[i]:
                    # never observed holding a real value: keep the full
                    # word — there is no profile to pack against
                    full[i] = True
                    bits[i] = 32
                    continue
                olo, ohi = int(obs_lo[i]), int(obs_hi[i])
                # symmetric margin of one observed span (floor 4) on
                # both sides, then 4x the resulting code count (+2
                # bits): BFS-depth-growing counters routinely reach a
                # multiple of the sampled max, and a spurious OV_PACK
                # abort costs a whole run — two extra bits per guarded
                # lane is cheap insurance
                span = max(ohi - olo, 4)
                lo = olo - span
                hi = lo + (ohi + span - lo + 1) * 4 - 1
                guarded[i] = True
            else:
                # structural OR analyzer-proven bound; extend with the
                # observed range as a belt-and-braces guard against
                # walk-order/analyzer defects (an extension here means
                # wider lanes, never wrong ones)
                if obs_seen[i]:
                    lo = min(lo, int(obs_lo[i]))
                    hi = max(hi, int(obs_hi[i]))
                if lc.proven:
                    # proven-width lane: packed exactly (no sampling
                    # margin), runtime-checked as a soundness net — the
                    # check cannot fire unless the static inference was
                    # wrong, and then the engine aborts exactly and the
                    # re-profile recovery widens past the bad bound
                    proven[i] = True
                    guarded[i] = True
            if lc.zero_pad:
                lo = min(lo, 0)
                hi = max(hi, 0)
            codes = hi - lo + 1
            if lc.sent_ok:
                sent_code[i] = codes
                codes += 1
            b = _nbits(max(codes, 1))
            if b >= 32:
                full[i] = True
                bits[i] = 32
                sent_code[i] = -1
                guarded[i] = False
                proven[i] = False
                continue
            bits[i] = b
            bias[i] = lo
            allowed[i] = codes - 1
        # greedy sequential word assignment (no lane spans two words)
        word = np.zeros(W, np.int64)
        shift = np.zeros(W, np.int64)
        w = 0
        used = 0
        for i in range(W):
            b = int(bits[i])
            if used + b > 32:
                w += 1
                used = 0
            word[i] = w
            shift[i] = used
            used += b
        packed_width = (w + 1) if W else 0
        self.identity = bool(force_identity or packed_width >= W)
        if self.identity:
            packed_width = W
            word = np.arange(W, dtype=np.int64)
            shift = np.zeros(W, np.int64)
            bits = np.full(W, 32, np.int64)
            bias = np.zeros(W, np.int64)
            sent_code = np.full(W, -1, np.int64)
            guarded = np.zeros(W, bool)
            proven = np.zeros(W, bool)
            full = np.ones(W, bool)
            allowed = np.zeros(W, np.int64)
        self.packed_width = packed_width
        self.bits = bits
        self.word = word
        self.shift = shift
        self.mask = ((np.int64(1) << bits) - 1).astype(np.uint64) \
            .astype(np.uint32) if W else np.zeros(0, np.uint32)
        self.bias = bias
        self.allowed = allowed
        self.sent_code = sent_code
        self.guarded = guarded
        self.proven = proven
        self.full = full
        self.bits_per_state = int(bits.sum())
        # the two int-lane accounting gauges are disjoint: a lane is
        # either proven (static bound, no margin) or observed-range
        # guarded (sampled + margin + runtime abort)
        self.proven_lanes = int(proven.sum())
        self.guarded_lanes = int((guarded & ~proven).sum())

    # deterministic description for layout signatures (checkpoint/resume
    # compatibility: a resumed run must rebuild the identical plan)
    def signature(self) -> str:
        return repr((self.width, self.packed_width, self.identity,
                     self.word.tolist(), self.shift.tolist(),
                     self.bits.tolist(), self.bias.tolist(),
                     self.sent_code.tolist()))

    def batch_descriptor(self) -> Dict[str, int]:
        """The compat surface the cross-model batcher reports and
        verifies (ISSUE 13): the packed word width and lane accounting
        every member of a vmapped batch shares — per-model CONSTANT
        values are batch-axis lanes, so they are deliberately NOT in
        here."""
        return {"width": self.width, "packed_width": self.packed_width,
                "identity": int(self.identity),
                "bits_per_state": self.bits_per_state,
                "proven_lanes": self.proven_lanes,
                "guarded_lanes": self.guarded_lanes}

    # ---------------- host (numpy) pack/unpack ----------------

    def pack_np(self, rows: np.ndarray) -> np.ndarray:
        """[N, W] int32 -> [N, PW] int32.  Raises on an out-of-range
        guarded lane (host rows come from exact encodes, so an overflow
        here is an observation gap — same contract as vspec capacity
        errors)."""
        rows = np.ascontiguousarray(rows, np.int32)
        if self.identity:
            return rows
        from .vspec import CompileError
        v = rows.astype(np.int64)
        sent_l = (self.sent_code >= 0)[None, :]
        sent = (v == SENTINEL_LANE) & sent_l
        code = np.where(sent, self.sent_code[None, :],
                        v - self.bias[None, :])
        bad = (~self.full[None, :]) & \
            ((code < 0) | (code > self.allowed[None, :]))
        if bad.any():
            i = int(np.nonzero(bad.any(axis=0))[0][0])
            if self.proven[i]:
                raise CompileError(
                    f"packed lane {i} overflow: value outside the "
                    f"STATICALLY PROVEN range [{self.bias[i]}, "
                    f"{self.bias[i] + self.allowed[i]}] — the bounds "
                    f"analyzer derived a wrong interval (please report)"
                    f"; JAXMC_ANALYZE_BOUNDS=0 or JAXMC_PACK=0 works "
                    f"around it")
            raise CompileError(
                f"packed lane {i} overflow: value outside the profiled "
                f"range [{self.bias[i]}, {self.bias[i] + self.allowed[i]}]"
                f" — deepen layout sampling or set JAXMC_PACK=0")
        code_u = np.where(self.full[None, :], rows.view(np.uint32),
                          code.astype(np.uint32))
        packed = np.zeros((len(rows), self.packed_width), np.uint32)
        shifted = (code_u & self.mask[None, :]) << \
            self.shift.astype(np.uint32)[None, :]
        for i in range(self.width):
            packed[:, self.word[i]] |= shifted[:, i]
        return packed.view(np.int32)

    def unpack_np(self, packed: np.ndarray) -> np.ndarray:
        """[N, PW] int32 -> [N, W] int32 (total inverse of pack_np)."""
        packed = np.ascontiguousarray(packed, np.int32)
        if self.identity:
            return packed
        pu = packed.view(np.uint32)
        w = pu[:, self.word]                       # [N, W]
        raw = (w >> self.shift.astype(np.uint32)[None, :]) & \
            self.mask[None, :]
        v = raw.astype(np.int64) + self.bias[None, :]
        v = np.where(self.full[None, :],
                     raw.astype(np.uint32).view(np.int32).astype(np.int64),
                     v)
        sent = (self.sent_code >= 0)[None, :] & \
            (raw.astype(np.int64) == self.sent_code[None, :])
        v = np.where(sent, SENTINEL_LANE, v)
        return v.astype(np.int32)

    # ---------------- device (jnp) pack/unpack ----------------
    #
    # Plain functions over traced arrays — call them INSIDE a jitted
    # step; they lower to one gather + shifts/masks (unpack) or one
    # scatter-add of disjoint fields (pack).

    def unpack_rows(self, packed):
        """[N, PW] i32 traced -> [N, W] i32."""
        import jax.numpy as jnp
        from jax import lax
        if self.identity:
            return packed
        pu = lax.bitcast_convert_type(packed, jnp.uint32)
        w = jnp.take(pu, jnp.asarray(self.word, jnp.int32), axis=1)
        raw = (w >> jnp.asarray(self.shift, jnp.uint32)[None, :]) & \
            jnp.asarray(self.mask, jnp.uint32)[None, :]
        # raw < 2^31 for every packed (<32-bit) lane, so the bitcast is
        # the identity there; for full lanes it restores the sign bit
        v = lax.bitcast_convert_type(raw, jnp.int32)
        bias = jnp.asarray(self.bias, jnp.int32)[None, :]
        full = jnp.asarray(self.full)[None, :]
        out = jnp.where(full, v, v + bias)
        sent = jnp.asarray(self.sent_code >= 0)[None, :] & \
            (v == jnp.asarray(self.sent_code, jnp.int32)[None, :])
        return jnp.where(sent, jnp.int32(SENTINEL_LANE), out)

    def pack_rows(self, rows):
        """[N, W] i32 traced -> (packed [N, PW] i32, ovf [N] bool).

        ovf marks rows with a guarded lane outside its profiled range —
        callers mask it by row validity and route it into the engine's
        overflow channel (OV_PACK): an abort, never a wrong count."""
        import jax.numpy as jnp
        from jax import lax
        if self.identity:
            return rows, jnp.zeros(rows.shape[0], bool)
        bias = jnp.asarray(self.bias, jnp.int32)[None, :]
        sent_l = jnp.asarray(self.sent_code >= 0)[None, :]
        sentc = jnp.asarray(np.where(self.sent_code >= 0,
                                     self.sent_code, 0), jnp.int32)[None, :]
        full = jnp.asarray(self.full)[None, :]
        sent = sent_l & (rows == jnp.int32(SENTINEL_LANE))
        code = jnp.where(sent, sentc, rows - bias)
        allowed = jnp.asarray(self.allowed, jnp.int32)[None, :]
        bad = (~full) & ((code < 0) | (code > allowed))
        ovf = jnp.any(bad, axis=1)
        code_u = jnp.where(full,
                           lax.bitcast_convert_type(rows, jnp.uint32),
                           lax.bitcast_convert_type(code, jnp.uint32))
        shifted = (code_u & jnp.asarray(self.mask, jnp.uint32)[None, :]) \
            << jnp.asarray(self.shift, jnp.uint32)[None, :]
        packed = jnp.zeros((rows.shape[0], self.packed_width),
                           jnp.uint32)
        packed = packed.at[:, jnp.asarray(self.word, jnp.int32)] \
            .add(shifted)
        return lax.bitcast_convert_type(packed, jnp.int32), ovf


def identity_plan(width: int) -> LanePlan:
    return LanePlan(width, [], np.zeros(0), np.zeros(0),
                    np.zeros(0, bool), force_identity=True) \
        if width == 0 else LanePlan(
            width,
            [_LaneClass(None, None, True, False, False)] * width,
            np.zeros(width, np.int64), np.zeros(width, np.int64),
            np.zeros(width, bool), force_identity=True)


def build_lane_plan(layout, sample_rows: List[np.ndarray],
                    static_bounds: Optional[Dict[str, Tuple[int, int]]]
                    = None) -> LanePlan:
    """Plan for a Layout2 from its specs + the encoded sample rows.

    static_bounds (ISSUE 9): per-variable PROVEN summary intervals from
    jaxmc/analyze/bounds.py — every raw-int lane under such a variable
    is packed at the proven width (no sampling margin, no re-profile
    cycle) instead of the guarded observed range."""
    classes: List[_LaneClass] = []
    uni_n = len(layout.uni)
    for v in layout.vars:
        _walk(layout.specs[v], uni_n, False, False, classes,
              (static_bounds or {}).get(v))
    W = layout.width
    if len(classes) != W:
        # a walk-order defect would corrupt every row: refuse to pack
        return identity_plan(W)
    if sample_rows:
        mat = np.asarray(np.stack(sample_rows), np.int64)
        sent_l = np.asarray([c.sent_ok for c in classes])
        is_sent = (mat == SENTINEL_LANE) & sent_l[None, :]
        real = ~is_sent
        big = np.int64(2 ** 62)
        obs_lo = np.where(real, mat, big).min(axis=0)
        obs_hi = np.where(real, mat, -big).max(axis=0)
        obs_seen = real.any(axis=0)
        obs_lo = np.where(obs_seen, obs_lo, 0)
        obs_hi = np.where(obs_seen, obs_hi, 0)
    else:
        obs_lo = np.zeros(W, np.int64)
        obs_hi = np.zeros(W, np.int64)
        obs_seen = np.zeros(W, bool)
    return LanePlan(W, classes, obs_lo, obs_hi, obs_seen,
                    force_identity=not packing_enabled())
