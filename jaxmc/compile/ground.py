r"""Static action grounding (SURVEY.md §7.3).

Action grounding statically expands the Next disjunction: operator expansion,
\/ splits, and \E over constant domains become a finite list of
GroundedActions, each a conjunct list evaluated by the kernel compiler
(compile/kernel2.py; state encodings live in compile/vspec.py). This is the
raft.tla:482-493 shape: ~10 action families x parameter instantiations
(SURVEY.md §3.3).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..front import tla_ast as A
from ..sem.values import EvalError, fmt
from ..sem.eval import Ctx, OpClosure, eval_expr, iter_binders
from ..sem.modules import Model


# ONE CompileError class for the whole compile package — ground and
# vspec/kernel2 raise interchangeably and callers catch one type
from .vspec import CompileError  # noqa: F401  (re-export)


# shared demotion-reason wording for the dynamic-\E slot axis (ISSUE
# 15): analyze/verdicts.py predicts these ground-time demotions and
# must report the exact build-time string — both sides read the one
# constant (the PR 9 SUBSET_SYMBOLIC_MSG pattern)
DYN_NESTED_MSG = ("nested dynamic \\E binders not supported "
                  "(one slot axis per action)")
DYN_SHAPE_MSG = ("dynamic \\E with multiple binders/patterns not "
                 "supported (one slot axis per action)")


# ---------------- static action grounding ----------------

@dataclass
class GroundedAction:
    label: str
    # ordered conjuncts with their static binding environments
    items: List[Tuple[A.Node, Dict[str, Any]]]


@dataclass
class ActionArm:
    """One top-level disjunct of Next, pre-grounding: the unit of hybrid
    fallback. Compiled arms ground into GroundedActions; an arm whose
    grounding or kernel compilation fails is enumerated by the exact
    interpreter (sem/enumerate.py) over decoded frontier states instead
    of rejecting the whole spec (VERDICT r3 #2). `bound` holds static
    VALUE bindings only (operator params, static \\E binders) so the
    interpreter can evaluate the arm via ctx.with_bound(bound). `label`
    is None when no operator expansion named the arm yet — grounding's
    first-leaf-conjunct policy (walk2) then assigns it, so a None must
    be passed through to ground_arm unchanged (display sites default it
    to "Next")."""
    label: Optional[str]
    expr: A.Node
    bound: Dict[str, Any]


def _static_ctx(model: Model) -> Ctx:
    """Context with constants/defs only — evaluating anything that touches
    state raises, which is how we detect non-static constructs."""
    return Ctx(model.defs, {}, None, None, ())


def split_arms(model: Model) -> List[ActionArm]:
    """Decompose Next into its disjunct arms: operator expansion, \\/
    splits, static \\E instantiation, AND distribution of rider
    conjuncts over a splitting conjunct (VERDICT r4 #3) — the same top
    structure ground_actions walks, stopping at anything non-static
    (those stay whole inside one arm). The concatenation of ground_arm()
    over these arms equals ground_actions() on Next — same instances,
    same order, same labels, same conjunct exprs — so compiled-path
    labels and traces are unchanged. Sole deviation: a rider conjunct
    distributed under a \\E's static binding carries that binding in its
    static env (inert by construction — occurs_free guarantees the rider
    never references it; the whole-grounding walk scopes the binding to
    the \\E body only).

    Conjunction distribution: raft's
    Next == /\\ (\\/ ...10 action families...) /\\ allLogs' = ...
    (/root/reference/examples/raft.tla:482-493) is ONE top-level
    conjunction; without distribution the whole transition relation was
    a single arm, so one uncompilable message variant demoted ALL of
    raft to the interpreter (the r4 mid4 abort). (a /\\ b) where a
    splits into arms L_i becomes arms (L_i /\\ b) — exact by
    distributivity of /\\ over \\/, order-preserving (left-outer /
    right-inner, ground_actions' own walk order). New binder bindings
    introduced by one side must not capture free names of the other
    (occurs_free); on a collision the conjunction stays one arm."""
    ctx = _static_ctx(model)
    from ..front.subst import occurs_free

    def walk(e: A.Node, bound: Dict[str, Any], label) -> List[ActionArm]:
        if isinstance(e, A.OpApp) and e.name == "\\/":
            res: List[ActionArm] = []
            for arm in e.args:
                res.extend(walk(arm, bound, label))
            return res
        if isinstance(e, A.OpApp) and e.name == "/\\":
            left = walk(e.args[0], bound, label)
            right = walk(e.args[1], bound, label)
            if len(left) == 1 and len(right) == 1:
                # nothing under the conjunction splits: stay one arm
                # (the grounder expands it; do NOT decompose a plain
                # conjunction into per-conjunct arms)
                return [ActionArm(label, e, dict(bound))]
            base = set(bound)
            res = []
            for la in left:
                newl = set(la.bound) - base
                for ra in right:
                    newr = set(ra.bound) - base
                    if (newl & newr or occurs_free(ra.expr, newl)
                            or occurs_free(la.expr, newr)):
                        # capture risk: keep the whole conjunction as
                        # one arm rather than mis-scope a rider
                        return [ActionArm(label, e, dict(bound))]
                    res.append(ActionArm(
                        la.label or ra.label or label,
                        A.OpApp("/\\", (la.expr, ra.expr), ()),
                        {**la.bound, **ra.bound}))
            return res
        if isinstance(e, A.Quant) and e.kind == "E":
            try:
                bindings = list(iter_binders(
                    e.binders, ctx.with_bound(bound), eval_expr))
            except EvalError:
                # dynamic domain: the whole \E is one arm (the grounder
                # slot-expands it on the compiled path; the interpreter
                # enumerates it natively on the fallback path)
                return [ActionArm(label, e, dict(bound))]
            res = []
            for b in bindings:
                res.extend(walk(e.body, {**bound, **b}, label))
            return res
        if isinstance(e, A.OpApp) and e.name not in _LEAF_OPS \
                and not e.path and e.name not in bound:
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and len(d.params) == len(e.args):
                args = []
                argable = True
                for a in e.args:
                    try:
                        args.append(eval_expr(a, ctx.with_bound(bound)))
                    except EvalError:
                        argable = False
                        break
                if argable:
                    nb = {**bound, **dict(zip(d.params, args))}
                    return walk(d.body, nb, _mk_label(e.name, args))
                # non-static args (assigns through params / reads state):
                # one arm; both paths expand it themselves
        if isinstance(e, A.Ident):
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params \
                    and e.name not in bound:
                return walk(d.body, bound, e.name)
        return [ActionArm(label, e, dict(bound))]

    return walk(model.next, {}, None)


def ground_arm(model: Model, arm: ActionArm, max_actions: int = 4096,
               dyn_slots: int = 0) -> List[GroundedAction]:
    """Ground one arm (see split_arms); raises CompileError when the arm
    holds constructs the grounder can't expand — the hybrid engine then
    demotes that arm to interpreter enumeration."""
    return _ground_expr(model, arm.expr, arm.bound, arm.label,
                        max_actions, dyn_slots)


def ground_actions(model: Model, max_actions: int = 4096,
                   dyn_slots: int = 0) -> List[GroundedAction]:
    """Statically expand Next. dyn_slots > 0 additionally expands
    \\E x \\in <state-dependent set> (raft's
    \\E m \\in ValidMessage(messages), raft.tla:449-478) into one instance
    per table slot; the kernel binds x to slot k's element guarded by the
    slot's membership mask."""
    return _ground_expr(model, model.next, {}, None, max_actions,
                        dyn_slots)


def _ground_expr(model: Model, root: A.Node, root_bound: Dict[str, Any],
                 root_label, max_actions: int,
                 dyn_slots: int) -> List[GroundedAction]:
    ctx = _static_ctx(model)

    def static_eval(e, bound):
        return eval_expr(e, ctx.with_bound(bound))

    results: List[GroundedAction] = []

    def walk2(e: A.Node, bound, label) -> List[Tuple[Optional[str], List]]:
        # label policy: operator expansion overwrites the label; the first
        # leaf conjunct freezes it (conjunction prefers the left label) —
        # mirrors sem/enumerate.py so traces agree across backends
        if isinstance(e, A.OpApp) and e.name == "/\\":
            left = walk2(e.args[0], bound, label)
            out2 = []
            for ll, litems in left:
                for rl, ritems in walk2(e.args[1], bound, ll or label):
                    out2.append((ll or rl, litems + ritems))
            return out2
        if isinstance(e, A.OpApp) and e.name == "\\/":
            out2 = []
            for arm in e.args:
                out2.extend(walk2(arm, bound, label))
            return out2
        if isinstance(e, A.Quant) and e.kind == "E":
            try:
                bindings = list(iter_binders(
                    e.binders, ctx.with_bound(_clean(bound)), eval_expr))
            except EvalError as ex:
                if dyn_slots > 0 and len(e.binders) == 1 \
                        and len(e.binders[0][0]) == 1 \
                        and isinstance(e.binders[0][0][0], str):
                    if any(isinstance(bv, tuple) and len(bv) == 2
                           and bv[0] == "$slotv" for bv in bound.values()):
                        # two dynamic binders would share the one traced
                        # slot index and only explore diagonal pairs —
                        # reject rather than silently drop transitions
                        raise CompileError(DYN_NESTED_MSG) from ex
                    # one vectorized instance: the kernel binds the slot
                    # element by a traced slot index and the engine vmaps
                    # over slots (keeps trace size O(1) in table capacity)
                    var = e.binders[0][0][0]
                    sexpr = e.binders[0][1]
                    nb = {**bound, var: ("$slotv", sexpr)}
                    return walk2(e.body, nb, label)
                if dyn_slots > 0:
                    # dynamic domain but an UNSIZED slot axis: the
                    # binder shape disqualifies slot expansion — a
                    # constant reason the predictor mirrors verbatim
                    raise CompileError(DYN_SHAPE_MSG) from ex
                raise CompileError(f"\\E over non-static domain: {ex}") \
                    from ex
            out2 = []
            for b in bindings:
                out2.extend(walk2(e.body, {**bound, **b}, label))
            return out2
        if isinstance(e, A.Let):
            nb = dict(bound)
            for d in e.defs:
                if isinstance(d, A.OpDef) and not d.params:
                    nb[d.name] = ("$letexpr", d.body)
                elif isinstance(d, A.OpDef):
                    nb[d.name] = ("$op", d, {})
                else:
                    raise CompileError("unsupported LET in action")
            return walk2(e.body, nb, label)
        if isinstance(e, A.OpApp) and e.name not in _LEAF_OPS and not e.path \
                and e.name not in bound:
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and len(d.params) == len(e.args):
                args = []
                argable = True
                for a in e.args:
                    # bound-marker references pass through symbolically
                    if isinstance(a, A.Ident) and isinstance(
                            bound.get(a.name), tuple):
                        args.append(bound[a.name])
                        continue
                    try:
                        args.append(static_eval(a, _clean(bound)))
                    except EvalError:
                        argable = False
                        break
                if not argable:
                    from ..front.subst import contains_prime, subst
                    if contains_prime(d.body):
                        # the body assigns through its parameters or primes
                        # variables (Reply, Send, the raft handlers):
                        # call-by-name expansion keeps the assignment
                        # structure visible to the action compiler
                        body = subst(d.body, dict(zip(d.params, e.args)))
                        return walk2(body, bound, _mk_label(e.name, []))
                    # pure read: leave as a leaf for the kernel's symbolic
                    # evaluator
                    return [(label, [(e, dict(bound))])]
                nb = {**bound, **dict(zip(d.params, args))}
                return walk2(d.body, nb, _mk_label(
                    e.name, [a for a in args
                             if not isinstance(a, tuple)]))
        if isinstance(e, A.Ident):
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params \
                    and e.name not in bound:
                return walk2(d.body, bound, e.name)
        return [(label, [(e, dict(bound))])]

    for label, items in walk2(root, dict(root_bound), root_label):
        results.append(GroundedAction(label or "Next", items))
        if len(results) > max_actions:
            raise CompileError(f"more than {max_actions} grounded actions")
    return results


def _clean(bound):
    """Drop compile-time marker bindings before interpreter evaluation."""
    return {k: v for k, v in bound.items() if not isinstance(v, tuple)}


def _mk_label(name, args):
    if not args:
        return name
    return f"{name}({', '.join(fmt(a) for a in args)})"


_LEAF_OPS = {
    "=", "/=", "#", "<", ">", "<=", ">=", "=<", "\\leq", "\\geq",
    "\\in", "\\notin", "+", "-", "*", "^", "\\div", "%", "..",
    "~", "=>", "<=>", "\\equiv", "\\cup", "\\cap", "\\", "\\union",
    "\\intersect", "\\subseteq", "\\subset", "\\supseteq", "\\supset",
    "SUBSET", "UNION", "DOMAIN", "\\X", "@@", ":>", "-.", "!sel",
    "Assert", "Print", "PrintT", "Cardinality", "Len",
}
