r"""Model grounder: fixed-width integer state encodings + static action
grounding (SURVEY.md §7.3).

With cfg constants bound, every state variable gets a fixed-width i32
encoding: ints and booleans one lane each, strings/model values as indices
into a global enum universe, functions with a fixed finite domain as one
encoded block per domain element, sets over a small static universe as 0/1
membership lanes. The layout is derived from the initial states (structure
must be Next-stable — the cross-check tests validate this against the
interpreter).

Action grounding statically expands the Next disjunction: operator expansion,
\/ splits, and \E over constant domains become a finite list of
GroundedActions, each a conjunct list evaluated by the kernel compiler
(compile/kernel.py). This is the raft.tla:482-493 shape: ~10 action families
x parameter instantiations (SURVEY.md §3.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..front import tla_ast as A
from ..sem.values import (EvalError, Fcn, InfiniteSet, ModelValue,
                          enumerate_set, fmt, sort_key)
from ..sem.eval import Ctx, OpClosure, eval_expr, iter_binders, bind_pattern
from ..sem.modules import Model


# ONE CompileError class for the whole compile package — ground and
# vspec/kernel2 raise interchangeably and callers catch one type
from .vspec import CompileError  # noqa: F401  (re-export)


# ---------------- enum universe ----------------

class EnumUniverse:
    """Global index space for strings and model values appearing in the
    model (pc labels, role names, message types, Nil, ...)."""

    def __init__(self):
        self.to_idx: Dict[Any, int] = {}
        self.values: List[Any] = []

    def add(self, v):
        if v not in self.to_idx:
            self.to_idx[v] = len(self.values)
            self.values.append(v)

    def index(self, v) -> int:
        try:
            return self.to_idx[v]
        except KeyError:
            raise CompileError(f"value {fmt(v)} not in enum universe")

    def value(self, i: int):
        return self.values[i]

    def __len__(self):
        return len(self.values)


def collect_enums(model: Model) -> EnumUniverse:
    uni = EnumUniverse()

    def walk_ast(e):
        if isinstance(e, A.Str):
            uni.add(e.val)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, A.Node):
                walk_ast(v)
            elif isinstance(v, tuple):
                for x in _flat(v):
                    if isinstance(x, A.Node):
                        walk_ast(x)

    def _flat(t):
        for x in t:
            if isinstance(x, tuple):
                yield from _flat(x)
            else:
                yield x

    def walk_value(v):
        if isinstance(v, ModelValue):
            uni.add(v)
        elif isinstance(v, str):
            uni.add(v)
        elif isinstance(v, frozenset):
            for x in v:
                walk_value(x)
        elif isinstance(v, Fcn):
            for k, x in v.d.items():
                walk_value(k)
                walk_value(x)

    for d in model.defs.values():
        if isinstance(d, OpClosure):
            if isinstance(d.body, A.Node):
                walk_ast(d.body)
        else:
            walk_value(d)
    for u in model.module.ast.units:
        if isinstance(u, (A.OpDef,)):
            walk_ast(u.body)
    return uni


# ---------------- value specs ----------------

@dataclass(frozen=True)
class Spec_:
    kind: str                     # 'int' | 'bool' | 'enum' | 'fcn' | 'set'
    dom: Tuple = ()               # fcn: ordered domain keys; set: universe
    elems: Tuple = ()             # fcn: per-key element spec

    @property
    def width(self) -> int:
        if self.kind in ("int", "bool", "enum"):
            return 1
        if self.kind == "fcn":
            return sum(e.width for e in self.elems)
        if self.kind == "set":
            return len(self.dom)
        raise AssertionError(self.kind)


def infer_spec(v, uni: EnumUniverse) -> Spec_:
    if isinstance(v, bool):
        return Spec_("bool")
    if isinstance(v, int):
        return Spec_("int")
    if isinstance(v, (str, ModelValue)):
        uni.add(v)
        return Spec_("enum")
    if isinstance(v, Fcn):
        keys = sorted(v.d.keys(), key=sort_key)
        for k in keys:
            if isinstance(k, (str, ModelValue)):
                uni.add(k)
        elems = tuple(infer_spec(v.d[k], uni) for k in keys)
        return Spec_("fcn", tuple(keys), elems)
    if isinstance(v, frozenset):
        # set over a universe discovered from observed members; engine
        # validates closure at encode time
        members = tuple(sorted(v, key=sort_key))
        for m in members:
            if isinstance(m, (str, ModelValue)):
                uni.add(m)
        return Spec_("set", members)
    raise CompileError(f"cannot derive fixed-width encoding for {fmt(v)}")


def merge_spec(a: Spec_, b: Spec_) -> Spec_:
    if a.kind != b.kind:
        raise CompileError(f"unstable value structure: {a.kind} vs {b.kind}")
    if a.kind == "fcn":
        if a.dom != b.dom:
            raise CompileError("function domains differ across states")
        return Spec_("fcn", a.dom,
                     tuple(merge_spec(x, y) for x, y in zip(a.elems, b.elems)))
    if a.kind == "set":
        if a.dom == b.dom:
            return a
        merged = tuple(sorted(set(a.dom) | set(b.dom), key=sort_key))
        return Spec_("set", merged)
    return a


def encode_value(v, spec: Spec_, uni: EnumUniverse, out: List[int]):
    if spec.kind == "int":
        if isinstance(v, bool) or not isinstance(v, int):
            raise CompileError(f"expected int, got {fmt(v)}")
        out.append(v)
    elif spec.kind == "bool":
        if not isinstance(v, bool):
            raise CompileError(f"expected bool, got {fmt(v)}")
        out.append(1 if v else 0)
    elif spec.kind == "enum":
        out.append(uni.index(v))
    elif spec.kind == "fcn":
        if not isinstance(v, Fcn):
            raise CompileError(f"expected function, got {fmt(v)}")
        if len(v.d) != len(spec.dom):
            raise CompileError("function domain changed")
        for k, es in zip(spec.dom, spec.elems):
            encode_value(v.apply(k), es, uni, out)
    elif spec.kind == "set":
        if not isinstance(v, frozenset):
            raise CompileError(f"expected set, got {fmt(v)}")
        for m in spec.dom:
            out.append(1 if m in v else 0)
        extra = v - frozenset(spec.dom)
        if extra:
            raise CompileError(f"set value outside universe: {fmt(extra)}")
    else:
        raise AssertionError(spec.kind)


def decode_value(row, i: int, spec: Spec_, uni: EnumUniverse):
    if spec.kind == "int":
        return int(row[i]), i + 1
    if spec.kind == "bool":
        return bool(row[i]), i + 1
    if spec.kind == "enum":
        return uni.value(int(row[i])), i + 1
    if spec.kind == "fcn":
        d = {}
        for k, es in zip(spec.dom, spec.elems):
            d[k], i = decode_value(row, i, es, uni)
        return Fcn(d), i
    if spec.kind == "set":
        members = []
        for m in spec.dom:
            if int(row[i]):
                members.append(m)
            i += 1
        return frozenset(members), i
    raise AssertionError(spec.kind)


@dataclass
class StateLayout:
    vars: Tuple[str, ...]
    specs: Dict[str, Spec_]
    uni: EnumUniverse
    width: int = 0

    def __post_init__(self):
        self.width = sum(self.specs[v].width for v in self.vars)
        self.offsets = {}
        off = 0
        for v in self.vars:
            self.offsets[v] = off
            off += self.specs[v].width

    def encode(self, state: Dict[str, Any]) -> np.ndarray:
        out: List[int] = []
        for v in self.vars:
            encode_value(state[v], self.specs[v], self.uni, out)
        return np.asarray(out, dtype=np.int32)

    def decode(self, row) -> Dict[str, Any]:
        st = {}
        i = 0
        for v in self.vars:
            st[v], i = decode_value(row, i, self.specs[v], self.uni)
        return st


def build_layout(model: Model, init_states: List[Dict[str, Any]]) -> StateLayout:
    if not init_states:
        raise CompileError("no initial states to derive a layout from")
    uni = collect_enums(model)
    specs: Dict[str, Spec_] = {}
    for v in model.vars:
        sp = infer_spec(init_states[0][v], uni)
        for st in init_states[1:]:
            sp = merge_spec(sp, infer_spec(st[v], uni))
        specs[v] = sp
    return StateLayout(tuple(model.vars), specs, uni)


# ---------------- static action grounding ----------------

@dataclass
class GroundedAction:
    label: str
    # ordered conjuncts with their static binding environments
    items: List[Tuple[A.Node, Dict[str, Any]]]


def _static_ctx(model: Model) -> Ctx:
    """Context with constants/defs only — evaluating anything that touches
    state raises, which is how we detect non-static constructs."""
    return Ctx(model.defs, {}, None, None, ())


def ground_actions(model: Model, max_actions: int = 4096,
                   dyn_slots: int = 0) -> List[GroundedAction]:
    """Statically expand Next. dyn_slots > 0 additionally expands
    \\E x \\in <state-dependent set> (raft's
    \\E m \\in ValidMessage(messages), raft.tla:449-478) into one instance
    per table slot; the kernel binds x to slot k's element guarded by the
    slot's membership mask."""
    ctx = _static_ctx(model)

    def static_eval(e, bound):
        return eval_expr(e, ctx.with_bound(bound))

    results: List[GroundedAction] = []

    def walk2(e: A.Node, bound, label) -> List[Tuple[Optional[str], List]]:
        # label policy: operator expansion overwrites the label; the first
        # leaf conjunct freezes it (conjunction prefers the left label) —
        # mirrors sem/enumerate.py so traces agree across backends
        if isinstance(e, A.OpApp) and e.name == "/\\":
            left = walk2(e.args[0], bound, label)
            out2 = []
            for ll, litems in left:
                for rl, ritems in walk2(e.args[1], bound, ll or label):
                    out2.append((ll or rl, litems + ritems))
            return out2
        if isinstance(e, A.OpApp) and e.name == "\\/":
            out2 = []
            for arm in e.args:
                out2.extend(walk2(arm, bound, label))
            return out2
        if isinstance(e, A.Quant) and e.kind == "E":
            try:
                bindings = list(iter_binders(
                    e.binders, ctx.with_bound(_clean(bound)), eval_expr))
            except EvalError as ex:
                if dyn_slots > 0 and len(e.binders) == 1 \
                        and len(e.binders[0][0]) == 1 \
                        and isinstance(e.binders[0][0][0], str):
                    if any(isinstance(bv, tuple) and len(bv) == 2
                           and bv[0] == "$slotv" for bv in bound.values()):
                        # two dynamic binders would share the one traced
                        # slot index and only explore diagonal pairs —
                        # reject rather than silently drop transitions
                        raise CompileError(
                            "nested dynamic \\E binders not supported "
                            "(one slot axis per action)")
                    # one vectorized instance: the kernel binds the slot
                    # element by a traced slot index and the engine vmaps
                    # over slots (keeps trace size O(1) in table capacity)
                    var = e.binders[0][0][0]
                    sexpr = e.binders[0][1]
                    nb = {**bound, var: ("$slotv", sexpr)}
                    return walk2(e.body, nb, label)
                raise CompileError(f"\\E over non-static domain: {ex}") \
                    from ex
            out2 = []
            for b in bindings:
                out2.extend(walk2(e.body, {**bound, **b}, label))
            return out2
        if isinstance(e, A.Let):
            nb = dict(bound)
            for d in e.defs:
                if isinstance(d, A.OpDef) and not d.params:
                    nb[d.name] = ("$letexpr", d.body)
                elif isinstance(d, A.OpDef):
                    nb[d.name] = ("$op", d, {})
                else:
                    raise CompileError("unsupported LET in action")
            return walk2(e.body, nb, label)
        if isinstance(e, A.OpApp) and e.name not in _LEAF_OPS and not e.path \
                and e.name not in bound:
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and len(d.params) == len(e.args):
                args = []
                argable = True
                for a in e.args:
                    # bound-marker references pass through symbolically
                    if isinstance(a, A.Ident) and isinstance(
                            bound.get(a.name), tuple):
                        args.append(bound[a.name])
                        continue
                    try:
                        args.append(static_eval(a, _clean(bound)))
                    except EvalError:
                        argable = False
                        break
                if not argable:
                    from ..front.subst import contains_prime, subst
                    if contains_prime(d.body):
                        # the body assigns through its parameters or primes
                        # variables (Reply, Send, the raft handlers):
                        # call-by-name expansion keeps the assignment
                        # structure visible to the action compiler
                        body = subst(d.body, dict(zip(d.params, e.args)))
                        return walk2(body, bound, _mk_label(e.name, []))
                    # pure read: leave as a leaf for the kernel's symbolic
                    # evaluator
                    return [(label, [(e, dict(bound))])]
                nb = {**bound, **dict(zip(d.params, args))}
                return walk2(d.body, nb, _mk_label(
                    e.name, [a for a in args
                             if not isinstance(a, tuple)]))
        if isinstance(e, A.Ident):
            d = model.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params \
                    and e.name not in bound:
                return walk2(d.body, bound, e.name)
        return [(label, [(e, dict(bound))])]

    for label, items in walk2(model.next, {}, None):
        results.append(GroundedAction(label or "Next", items))
        if len(results) > max_actions:
            raise CompileError(f"more than {max_actions} grounded actions")
    return results


def _clean(bound):
    """Drop compile-time marker bindings before interpreter evaluation."""
    return {k: v for k, v in bound.items() if not isinstance(v, tuple)}


def _mk_label(name, args):
    if not args:
        return name
    return f"{name}({', '.join(fmt(a) for a in args)})"


_LEAF_OPS = {
    "=", "/=", "#", "<", ">", "<=", ">=", "=<", "\\leq", "\\geq",
    "\\in", "\\notin", "+", "-", "*", "^", "\\div", "%", "..",
    "~", "=>", "<=>", "\\equiv", "\\cup", "\\cap", "\\", "\\union",
    "\\intersect", "\\subseteq", "\\subset", "\\supseteq", "\\supset",
    "SUBSET", "UNION", "DOMAIN", "\\X", "@@", ":>", "-.", "!sel",
    "Assert", "Print", "PrintT", "Cardinality", "Len",
}
