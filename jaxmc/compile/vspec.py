r"""Value-shape inference and fixed-width lane encodings for the TPU path.

The checker cannot know statically whether an empty TLA+ function value is a
sequence, a map, or a message bag — so the layout is inferred by sampling
reachable states with the exact interpreter and merging observed shapes
(SURVEY.md §7.3 "model grounder"). The merge lattice:

  int / bool / enum                     one i32 lane each
  fcn   (stable finite domain)          concatenated element blocks
  seq   (int keys 1..n, n varies)      len lane + cap x elem lanes, zero-pad
  set   (members all enums)            |universe| membership lanes
  growset (members anything else)      count lane + cap x elem lanes,
                                        elements sorted by lane tuple,
                                        SENTINEL padding  (raft's allLogs,
                                        elections — history sets that only
                                        grow, raft.tla:43-48)
  pfcn  (enum keys, domain varies)     per-key present lane + value lanes,
                                        zeroed when absent (voterLog[i])
  union (records with differing keys)  tag lane + max-width payload,
                                        zero-pad (raft's message records,
                                        raft.tla:28-32 in Paxos, mtype
                                        dispatch raft.tla:449-464)
  kvtable (keys anything else -> val)  count lane + cap x (key+val) lanes,
                                        sorted by key lanes, SENTINEL pad
                                        (the message bag Message -> Nat,
                                        raft.tla:33-36,117-132)

Exactness: encodings are canonical (sorted containers, deterministic
padding), so lane-tuple equality == TLA+ value equality, and capacity
overflow is a hard error — state counts stay exact (BASELINE.json).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..sem.values import Fcn, ModelValue, fmt, sort_key


class CompileError(Exception):
    """Raised when a construct cannot be compiled to the TPU path; callers
    fall back to the interpreter (SURVEY.md §7.2)."""


class ModeError(CompileError):
    """An unsupported option/mode combination (e.g. --resident with
    --host-seen, or resident mode on a model with temporal properties) —
    the fix is different flags, not a different backend, so the CLI must
    not advise 'this spec is outside the compilable subset'."""


SENTINEL_LANE = 2**31 - 1


@dataclass
class Bounds:
    """Capacity FLOORS for the lane encodings. A container's capacity is
    max(floor, observed_max * margin) — observed over constraint-satisfying
    sampled states; the floors exist to be raised when sampling
    under-observes a model (the runtime overflow guard aborts exactly if a
    search outgrows the inferred caps, naming the flag to raise)."""
    seq_cap: int = 4        # sequence length floor
    grow_cap: int = 4       # growing-set cardinality floor
    kv_cap: int = 4         # message-table domain floor
    observed_margin: int = 2  # caps at least observed_max * margin


class EnumUniverse:
    """Global index space for strings and model values (pc labels, roles,
    message types, Nil, ...)."""

    def __init__(self):
        self.to_idx: Dict[Any, int] = {}
        self.values: List[Any] = []

    def add(self, v):
        if v not in self.to_idx:
            self.to_idx[v] = len(self.values)
            self.values.append(v)

    def index(self, v) -> int:
        try:
            return self.to_idx[v]
        except KeyError:
            raise CompileError(f"value {fmt(v)} not in enum universe")

    def value(self, i: int):
        return self.values[i]

    def __len__(self):
        return len(self.values)


@dataclass(frozen=True)
class VS:
    """A value spec node."""
    kind: str
    # fcn: dom=ordered keys, elems=per-key spec
    # seq: cap=int, elem=spec
    # set: dom=universe members
    # growset: cap, elem
    # pfcn: dom=key universe, elem (uniform value spec)
    # union: variants=tuple of (fieldnames_tuple, fields_spec_tuple)
    # kvtable: cap, elem (key spec), val (value spec)
    dom: Tuple = ()
    elems: Tuple = ()
    elem: Optional["VS"] = None
    val: Optional["VS"] = None
    cap: int = 0
    variants: Tuple = ()

    @property
    def width(self) -> int:
        k = self.kind
        if k == "justempty":
            return 0
        if k in ("int", "bool", "enum"):
            return 1
        if k == "fcn":
            return sum(e.width for e in self.elems)
        if k == "seq":
            return 1 + self.cap * self.elem.width
        if k == "set":
            return len(self.dom)
        if k == "growset":
            return 1 + self.cap * self.elem.width
        if k == "pfcn":
            return sum(1 + e.width for e in self.elems)
        if k == "union":
            return 1 + max((sum(f.width for f in fs)
                            for _, fs in self.variants), default=0)
        if k == "kvtable":
            return 1 + self.cap * (self.elem.width + self.val.width)
        raise AssertionError(k)


_EMPTY_MARKER = VS("empty")


def infer(v, uni: EnumUniverse) -> VS:
    """Shape of a single observed value."""
    if isinstance(v, bool):
        return VS("bool")
    if isinstance(v, int):
        return VS("int")
    if isinstance(v, (str, ModelValue)):
        uni.add(v)
        return VS("enum")
    if isinstance(v, Fcn):
        if len(v.d) == 0:
            return _EMPTY_MARKER
        keys = sorted(v.d.keys(), key=sort_key)
        if all(isinstance(k, int) and not isinstance(k, bool) for k in keys) \
                and keys == list(range(1, len(keys) + 1)):
            try:
                elem = None
                for k in keys:
                    s = infer(v.d[k], uni)
                    elem = s if elem is None else merge(elem, s)
                return VS("seq", cap=len(keys), elem=elem)
            except CompileError:
                # heterogeneous tuple (<<data, bit>> pairs in
                # AlternatingBit): a fixed int-keyed record, not a sequence
                pass
        for k in keys:
            if isinstance(k, (str, ModelValue)):
                uni.add(k)
        elems = tuple(infer(v.d[k], uni) for k in keys)
        return VS("fcn", dom=tuple(keys), elems=elems)
    if isinstance(v, frozenset):
        if not v:
            return VS("emptyset")
        members = sorted(v, key=sort_key)
        mspecs = [infer(m, uni) for m in members]
        if all(s.kind == "enum" for s in mspecs):
            return VS("set", dom=tuple(members))
        elem = mspecs[0]
        for s in mspecs[1:]:
            elem = merge(elem, s)
        return VS("growset", cap=len(members), elem=elem)
    raise CompileError(f"cannot infer a lane encoding for {fmt(v)}")


def _is_record(spec: VS) -> bool:
    return spec.kind == "fcn" and all(isinstance(k, str) for k in spec.dom)


def merge(a: VS, b: VS) -> VS:
    """Least upper bound of two observed shapes."""
    if a.kind == b.kind and a.kind in ("int", "bool", "enum"):
        return a
    if a.kind in ("empty", "justempty"):
        a, b = b, a
    if b.kind in ("empty", "justempty"):
        # an empty function: compatible with seq / pfcn / kvtable
        if a.kind in ("seq", "pfcn", "kvtable", "empty", "justempty"):
            return a
        if a.kind == "fcn":
            # stable-domain fcn seen with an empty variant -> partial fcn
            return _fcn_to_pfcn(a)
        raise CompileError(f"empty function merged with {a.kind}")
    if a.kind == "emptyset":
        a, b = b, a
    if b.kind == "emptyset":
        if a.kind in ("set", "growset", "emptyset"):
            return a
        raise CompileError(f"empty set merged with {a.kind}")
    if a.kind == b.kind:
        k = a.kind
        if k == "seq":
            return VS("seq", cap=max(a.cap, b.cap),
                      elem=merge(a.elem, b.elem))
        if k == "set":
            return VS("set", dom=tuple(sorted(set(a.dom) | set(b.dom),
                                              key=sort_key)))
        if k == "growset":
            return VS("growset", cap=max(a.cap, b.cap),
                      elem=merge(a.elem, b.elem))
        if k == "fcn":
            if a.dom == b.dom:
                return VS("fcn", dom=a.dom,
                          elems=tuple(merge(x, y)
                                      for x, y in zip(a.elems, b.elems)))
            if _is_record(a) and _is_record(b):
                return _merge_unions(_record_to_union(a),
                                     _record_to_union(b))
            return merge(_fcn_to_pfcn(a), _fcn_to_pfcn(b))
        if k == "pfcn":
            keys = sorted(set(a.dom) | set(b.dom), key=sort_key)
            ae = dict(zip(a.dom, a.elems))
            be = dict(zip(b.dom, b.elems))
            elems = []
            for kk in keys:
                if kk in ae and kk in be:
                    elems.append(merge(ae[kk], be[kk]))
                else:
                    elems.append(ae.get(kk) or be[kk])
            return VS("pfcn", dom=tuple(keys), elems=tuple(elems))
        if k == "union":
            return _merge_unions(a, b)
        if k == "kvtable":
            return VS("kvtable", cap=max(a.cap, b.cap),
                      elem=merge(a.elem, b.elem), val=merge(a.val, b.val))
    # cross-kind promotions
    pair = {a.kind, b.kind}
    if pair == {"fcn", "seq"}:
        f = a if a.kind == "fcn" else b
        s = a if a.kind == "seq" else b
        if all(isinstance(kk, int) for kk in f.dom):
            elem = s.elem
            for e in f.elems:
                elem = merge(elem, e)
            return VS("seq", cap=max(s.cap, len(f.dom)), elem=elem)
        raise CompileError("sequence merged with non-int-keyed function")
    if pair == {"fcn", "pfcn"}:
        f = a if a.kind == "fcn" else b
        return merge(_fcn_to_pfcn(f), a if a.kind == "pfcn" else b)
    if pair == {"fcn", "union"} and _is_record(a if a.kind == "fcn" else b):
        f = a if a.kind == "fcn" else b
        u = a if a.kind == "union" else b
        return _merge_unions(_record_to_union(f), u)
    if pair == {"fcn", "kvtable"}:
        f = a if a.kind == "fcn" else b
        t = a if a.kind == "kvtable" else b
        kspec = None
        vspec = None
        for kk, e in zip(f.dom, f.elems):
            ks = infer_key(kk)
            kspec = ks if kspec is None else merge(kspec, ks)
            vspec = e if vspec is None else merge(vspec, e)
        return VS("kvtable", cap=max(t.cap, len(f.dom)),
                  elem=merge(t.elem, kspec) if kspec else t.elem,
                  val=merge(t.val, vspec) if vspec else t.val)
    if pair == {"set", "growset"}:
        g = a if a.kind == "growset" else b
        s = a if a.kind == "set" else b
        elem = g.elem
        return VS("growset", cap=max(g.cap, len(s.dom)), elem=elem)
    # scalar/RECORD mixes become tagged unions with scalar variants
    # (CachingMemory's buf[p]). Scalar/scalar mixes (int vs enum) still
    # RAISE: the heterogeneous-tuple inference (<<bit, data>> pairs,
    # AlternatingBit) depends on that failure to pick the int-keyed
    # record layout instead.
    orig_kinds = (a.kind, b.kind)

    def _unionable(x):
        return (x.kind == "union" or
                (x.kind == "fcn" and _is_record(x)))

    if (a.kind in _SCALARS and _unionable(b)) or \
            (b.kind in _SCALARS and _unionable(a)):
        if a.kind in _SCALARS:
            a = _scalar_to_union(a)
        if b.kind in _SCALARS:
            b = _scalar_to_union(b)
        if a.kind == "fcn":
            a = _record_to_union(a)
        if b.kind == "fcn":
            b = _record_to_union(b)
        return _merge_unions(a, b)
    raise CompileError(
        f"cannot merge shapes {orig_kinds[0]} and {orig_kinds[1]}")


def collect_enums_from_value(v, uni: EnumUniverse):
    """Register every string/model value reachable inside v (including ones
    nested in container keys) in the enum universe. Run over all sampled
    states before shape inference."""
    if isinstance(v, (str, ModelValue)):
        uni.add(v)
    elif isinstance(v, frozenset):
        for x in v:
            collect_enums_from_value(x, uni)
    elif isinstance(v, Fcn):
        for k, x in v.d.items():
            collect_enums_from_value(k, uni)
            collect_enums_from_value(x, uni)


def infer_key(k) -> VS:
    """Shape of a container key (enums were pre-registered by
    collect_enums_from_value, so a throwaway universe suffices here)."""
    if isinstance(k, bool):
        return VS("bool")
    if isinstance(k, int):
        return VS("int")
    if isinstance(k, (str, ModelValue)):
        return VS("enum")
    if isinstance(k, Fcn):
        return infer(k, EnumUniverse())
    raise CompileError(f"unsupported key value {fmt(k)}")


def _fcn_to_pfcn(f: VS) -> VS:
    if not all(isinstance(k, (str, ModelValue)) or isinstance(k, int)
               for k in f.dom):
        # composite keys -> kvtable
        kspec = None
        vspec = None
        for kk, e in zip(f.dom, f.elems):
            ks = infer_key(kk)
            kspec = ks if kspec is None else merge(kspec, ks)
            vspec = e if vspec is None else merge(vspec, e)
        return VS("kvtable", cap=len(f.dom), elem=kspec, val=vspec)
    return VS("pfcn", dom=f.dom, elems=f.elems)


def _record_to_union(f: VS) -> VS:
    return VS("union", variants=((tuple(f.dom), f.elems),))


def _scalar_to_union(s: VS) -> VS:
    """A scalar (enum/int/bool) as a one-variant union: variant name is
    the reserved marker ("$scalar:<kind>",) so scalars of different
    kinds coexist as distinct variants and never merge with record
    variants (CachingMemory's buf[p] in MReq u Val u {NoVal},
    /root/reference/examples/SpecifyingSystems/CachingMemory)."""
    return VS("union", variants=(((f"$scalar:{s.kind}",), (s,)),))


_SCALARS = ("int", "bool", "enum")


def is_scalar_variant(names: Tuple) -> bool:
    return len(names) == 1 and isinstance(names[0], str) and \
        names[0].startswith("$scalar:")


def _merge_unions(a: VS, b: VS) -> VS:
    vs = {names: list(fields) for names, fields in a.variants}
    for names, fields in b.variants:
        if names in vs:
            vs[names] = [merge(x, y) for x, y in zip(vs[names], fields)]
        else:
            vs[names] = list(fields)
    return VS("union", variants=tuple(
        (names, tuple(fields)) for names, fields in sorted(vs.items())))


def apply_bounds(spec: VS, bounds: Bounds) -> VS:
    """Grow inferred caps to the configured bounds."""
    k = spec.kind
    if k == "seq":
        return VS("seq",
                  cap=max(bounds.seq_cap,
                          spec.cap * bounds.observed_margin),
                  elem=apply_bounds(spec.elem, bounds))
    if k == "growset":
        return VS("growset",
                  cap=max(bounds.grow_cap, spec.cap * bounds.observed_margin),
                  elem=apply_bounds(spec.elem, bounds))
    if k == "kvtable":
        return VS("kvtable",
                  cap=max(bounds.kv_cap, spec.cap * bounds.observed_margin),
                  elem=apply_bounds(spec.elem, bounds),
                  val=apply_bounds(spec.val, bounds))
    if k == "fcn":
        return VS("fcn", dom=spec.dom,
                  elems=tuple(apply_bounds(e, bounds) for e in spec.elems))
    if k == "pfcn":
        return VS("pfcn", dom=spec.dom,
                  elems=tuple(apply_bounds(e, bounds) for e in spec.elems))
    if k == "union":
        return VS("union", variants=tuple(
            (names, tuple(apply_bounds(f, bounds) for f in fields))
            for names, fields in spec.variants))
    if k == "empty":
        # only ever observed as the empty function: encode as zero lanes;
        # if a later state grows it, encoding raises a hard error and the
        # run aborts exactly (sample deeper or raise caps)
        return VS("justempty")
    if k == "emptyset":
        return VS("set", dom=())
    return spec


# ---------------- encode / decode ----------------

def encode(v, spec: VS, uni: EnumUniverse, out: List[int]):
    k = spec.kind
    if k == "justempty":
        if not (isinstance(v, Fcn) and len(v.d) == 0):
            raise CompileError(
                f"value {fmt(v)} appeared where only empty functions were "
                f"sampled - deepen layout sampling")
        return
    if k == "int":
        if isinstance(v, bool) or not isinstance(v, int):
            raise CompileError(f"expected int, got {fmt(v)}")
        out.append(v)
    elif k == "bool":
        if not isinstance(v, bool):
            raise CompileError(f"expected bool, got {fmt(v)}")
        out.append(1 if v else 0)
    elif k == "enum":
        out.append(uni.index(v))
    elif k == "fcn":
        if not isinstance(v, Fcn) or set(map(_hk, v.d)) != set(map(_hk,
                                                                   spec.dom)):
            raise CompileError(f"expected function over {spec.dom}, "
                               f"got {fmt(v)}")
        lookup = {_hk(kk): val for kk, val in v.d.items()}
        for kk, es in zip(spec.dom, spec.elems):
            encode(lookup[_hk(kk)], es, uni, out)
    elif k == "seq":
        if not isinstance(v, Fcn) or not (len(v) == 0 or v.is_seq()):
            raise CompileError(f"expected sequence, got {fmt(v)}")
        lst = v.as_list()
        if len(lst) > spec.cap:
            raise CompileError(
                f"sequence length {len(lst)} exceeds capacity {spec.cap} - "
                f"raise --seq-cap")
        out.append(len(lst))
        for x in lst:
            encode(x, spec.elem, uni, out)
        for _ in range(spec.cap - len(lst)):
            out.extend([0] * spec.elem.width)
    elif k == "set":
        if not isinstance(v, frozenset):
            raise CompileError(f"expected set, got {fmt(v)}")
        extra = v - frozenset(spec.dom)
        if extra:
            raise CompileError(f"set member outside universe: {fmt(extra)}")
        for m in spec.dom:
            out.append(1 if m in v else 0)
    elif k == "growset":
        if not isinstance(v, frozenset):
            raise CompileError(f"expected set, got {fmt(v)}")
        if len(v) > spec.cap:
            raise CompileError(f"set cardinality {len(v)} exceeds capacity "
                               f"{spec.cap} - raise --grow-cap")
        encs = []
        for m in v:
            buf: List[int] = []
            encode(m, spec.elem, uni, buf)
            encs.append(buf)
        encs.sort()
        out.append(len(v))
        for e in encs:
            out.extend(e)
        for _ in range(spec.cap - len(encs)):
            out.extend([SENTINEL_LANE] * spec.elem.width)
    elif k == "pfcn":
        if not isinstance(v, Fcn):
            raise CompileError(f"expected function, got {fmt(v)}")
        lookup = {_hk(kk): val for kk, val in v.d.items()}
        seen = set()
        for kk, es in zip(spec.dom, spec.elems):
            h = _hk(kk)
            if h in lookup:
                out.append(1)
                encode(lookup[h], es, uni, out)
                seen.add(h)
            else:
                out.append(0)
                out.extend([0] * es.width)
        extra = set(lookup) - seen
        if extra:
            raise CompileError(f"pfcn key outside universe: {extra}")
    elif k == "union":
        if not isinstance(v, Fcn):
            want = f"$scalar:{_scalar_kind(v)}"
            for tag, (vnames, vfields) in enumerate(spec.variants):
                if vnames == (want,):
                    out.append(tag)
                    n0 = len(out)
                    encode(v, vfields[0], uni, out)
                    out.extend([0] * (spec.width - 1 - (len(out) - n0)))
                    return
            raise CompileError(
                f"scalar {fmt(v)} not a variant of the union")
        if not v.is_record():
            raise CompileError(f"expected record, got {fmt(v)}")
        names = tuple(sorted(v.d.keys()))
        for tag, (vnames, vfields) in enumerate(spec.variants):
            if vnames == names:
                out.append(tag)
                n0 = len(out)
                for nm, fs in zip(vnames, vfields):
                    encode(v.d[nm], fs, uni, out)
                pay = spec.width - 1
                out.extend([0] * (pay - (len(out) - n0)))
                return
        raise CompileError(f"record shape {names} not in union variants")
    elif k == "kvtable":
        if not isinstance(v, Fcn):
            raise CompileError(f"expected function, got {fmt(v)}")
        if len(v.d) > spec.cap:
            raise CompileError(f"table domain {len(v.d)} exceeds capacity "
                               f"{spec.cap} - raise --kv-cap")
        rows = []
        for kk, val in v.d.items():
            kb: List[int] = []
            encode(kk, spec.elem, uni, kb)
            vb: List[int] = []
            encode(val, spec.val, uni, vb)
            rows.append((kb, vb))
        rows.sort(key=lambda r: r[0])
        out.append(len(rows))
        for kb, vb in rows:
            out.extend(kb)
            out.extend(vb)
        pad = spec.elem.width + spec.val.width
        for _ in range(spec.cap - len(rows)):
            out.extend([SENTINEL_LANE] * pad)
    else:
        raise AssertionError(k)


def _hk(k):
    return (type(k).__name__, k.name if isinstance(k, ModelValue) else k)


def _scalar_kind(v) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, (str, ModelValue)):
        return "enum"
    raise CompileError(f"not a scalar: {fmt(v)}")


def decode(row, i: int, spec: VS, uni: EnumUniverse):
    k = spec.kind
    if k == "justempty":
        from ..sem.values import EMPTY_FCN
        return EMPTY_FCN, i
    if k == "int":
        return int(row[i]), i + 1
    if k == "bool":
        return bool(row[i]), i + 1
    if k == "enum":
        return uni.value(int(row[i])), i + 1
    if k == "fcn":
        d = {}
        for kk, es in zip(spec.dom, spec.elems):
            d[kk], i = decode(row, i, es, uni)
        return Fcn(d), i
    if k == "seq":
        n = int(row[i])
        i += 1
        items = []
        for j in range(spec.cap):
            v, i = decode(row, i, spec.elem, uni)
            if j < n:
                items.append(v)
        from ..sem.values import mk_seq
        return mk_seq(items), i
    if k == "set":
        members = []
        for m in spec.dom:
            if int(row[i]):
                members.append(m)
            i += 1
        return frozenset(members), i
    if k == "growset":
        n = int(row[i])
        i += 1
        items = []
        for j in range(spec.cap):
            v_i = i
            if j < n:
                v, _ = decode(row, v_i, spec.elem, uni)
                items.append(v)
            i += spec.elem.width
        return frozenset(items), i
    if k == "pfcn":
        d = {}
        for kk, es in zip(spec.dom, spec.elems):
            present = int(row[i])
            i += 1
            v, _ = decode(row, i, es, uni)
            if present:
                d[kk] = v
            i += es.width
        return Fcn(d), i
    if k == "union":
        tag = int(row[i])
        i += 1
        names, fields = spec.variants[tag]
        if is_scalar_variant(names):
            v, _ = decode(row, i, fields[0], uni)
            return v, i + spec.width - 1
        d = {}
        j = i
        for nm, fs in zip(names, fields):
            d[nm], j = decode(row, j, fs, uni)
        return Fcn(d), i + spec.width - 1
    if k == "kvtable":
        n = int(row[i])
        i += 1
        d = {}
        for j in range(spec.cap):
            if j < n:
                kk, mid = decode(row, i, spec.elem, uni)
                vv, _ = decode(row, mid, spec.val, uni)
                d[kk] = vv
            i += spec.elem.width + spec.val.width
        return Fcn(d), i
    raise AssertionError(k)
