r"""Persistent XLA compilation-cache wiring (JAXMC_COMPILE_CACHE).

The per-arm XLA compiles have repeatedly eaten the bench deadline
(BENCH_r03..r05: every device child pays the full compile bill even when
the previous child compiled the identical programs minutes earlier).
JAX's persistent compilation cache (`jax_compilation_cache_dir`) makes
repeat compiles disk hits; this module is the ONE place that enables it
and exposes its effectiveness as obs counters:

  compile.persistent_cache_hits    (jax monitoring event
                                    '/jax/compilation_cache/cache_hits')
  gauge compile.persistent_cache_dir
  gauge compile.persistent_cache_entries_start / _end
  gauge compile.persistent_cache_guard   ("ok[...]" | "cold-fallback:..")
  counter compile.persistent_cache_fallbacks / _quarantines

Two entry points:

  enable_persistent_cache  the RAW enabler (PR 3).  Opt-in only: point
                           it at a dir and it trusts the dir.
  enable_guarded_cache     the DEFAULT for bench.py children and sweep
                           subprocesses (ISSUE 5).  Same cache, wrapped
                           in the guard battery below, because XLA:CPU
                           blob reloads written by a DIFFERENT
                           machine/build have been observed to HANG
                           (tests/conftest.py) — a shared default cache
                           must never be able to wedge a run.

The guard battery (every step fails COLD, never broken — a cache
problem degrades to cold compilation, it cannot fail or hang the run):

  1. flock scope: every user holds a SHARED flock on `<dir>.lock` for
     the life of the process; quarantining (steps 2/4) requires a
     NON-BLOCKING EXCLUSIVE upgrade.  If another live process holds the
     lock, the guard skips the quarantine and falls back cold for this
     process only — it never yanks a directory under a reader.
  2. build fingerprint: `<dir>/jaxmc.cache.meta.json` records
     {python, jax, machine}.  A mismatch is exactly the cross-build
     reload-hang class — the whole dir is quarantined (renamed aside to
     `<dir>.quarantined.<ts>`) and a fresh one started.
  3. corruption scan: zero-length `*-cache` entries and stale `*.tmp`
     writer droppings are moved into `<dir>/.quarantine/` (jax looks
     entries up by exact filename, so the subdir is invisible to it)
     and the cache continues — one bad entry never disables the cache.
  4. health probe: a SUBPROCESS jits a trivial program against the dir
     under a hard timeout (JAXMC_CACHE_GUARD_TIMEOUT, default 60 s).  A
     wedge or crash quarantines the dir and falls back cold.  The probe
     result is stamped (`<dir>/jaxmc.cache.probe.ok`) so a round of
     sweep children pays for it ONCE, not per case
     (JAXMC_CACHE_PROBE=0 skips it entirely).

Fault sites (jaxmc/faults.py, chaos suite): `cache_hang` wedges the
health probe, `cache_corrupt` zero-truncates one entry before the scan,
`cache_lock` simulates a held exclusive lock.  tests/test_cache_guard.py
pins that each one degrades to cold compilation with the run intact.

JAXMC_COMPILE_CACHE=0|off|none disables the cache outright (both entry
points); any other value is the cache dir.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional, Tuple

_OFF_VALUES = ("0", "off", "none", "disabled")

# the process-lifetime shared flock fd (step 1); module global so the
# lock lives exactly as long as the process uses the cache
_LOCK_FD: Optional[int] = None

_META_NAME = "jaxmc.cache.meta.json"
_PROBE_STAMP = "jaxmc.cache.probe.ok"
_PROBE_FRESH_S = 3600.0  # one probe per dir per hour, not per process


def cache_dir_from_env() -> Optional[str]:
    d = os.environ.get("JAXMC_COMPILE_CACHE")
    if d is None or d.strip().lower() in _OFF_VALUES or not d.strip():
        return None
    return d


def cache_disabled_by_env() -> bool:
    """True when JAXMC_COMPILE_CACHE explicitly opts OUT (0/off/none) —
    the default-on call sites (bench children, sweep subprocesses)
    honor it; an unset env var is not an opt-out there."""
    d = os.environ.get("JAXMC_COMPILE_CACHE")
    return d is not None and d.strip().lower() in _OFF_VALUES


def default_cache_dir() -> str:
    """The box-wide default dir for the default-on call sites: shared
    across bench children, sweep subprocesses and rounds on one box
    (JAXMC_PROBE_DIR keeps parallel harnesses apart, same as the bench
    probe artifacts)."""
    base = os.environ.get("JAXMC_PROBE_DIR", tempfile.gettempdir())
    return os.path.join(base, "jaxmc_xla_cache")


_LISTENER_REGISTERED = False


def _count_entries(path: str) -> Optional[int]:
    try:
        return sum(1 for n in os.listdir(path)
                   if not n.endswith(".tmp")
                   and n not in (_META_NAME, _PROBE_STAMP, ".quarantine"))
    except OSError:
        return None


def _fingerprint() -> dict:
    """The build identity whose mismatch marks a foreign cache (the
    cross-build reload-hang class). jax import only — no device init."""
    import platform
    fp = {"python": platform.python_version(),
          "machine": platform.machine()}
    try:
        import jax
        fp["jax"] = jax.__version__
    except Exception:  # noqa: BLE001 — fingerprint must never raise
        fp["jax"] = "unavailable"
    return fp


def _flock(fd: int, exclusive: bool) -> bool:
    """Non-blocking flock; False on contention or any failure."""
    try:
        import fcntl
        fcntl.flock(fd, (fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH)
                    | fcntl.LOCK_NB)
        return True
    except OSError:
        return False


def _quarantine_dir(path: str) -> Optional[str]:
    """Rename the whole cache dir aside; returns the new path or None."""
    dst = f"{path}.quarantined.{int(time.time())}.{os.getpid()}"
    try:
        os.rename(path, dst)
        os.makedirs(path, exist_ok=True)
        return dst
    except OSError:
        return None


def _guard(path: str, timeout_s: float, tel) -> Tuple[bool, str]:
    """Run the guard battery over `path`. Returns (enable?, detail).
    Mutates module state only to park the shared flock fd."""
    from .. import faults
    global _LOCK_FD
    os.makedirs(path, exist_ok=True)

    # -- step 1: the flock scope ------------------------------------
    lock_path = path.rstrip("/") + ".lock"
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
    if faults.fire("cache_lock") is not None or not _flock(fd, False):
        # someone holds the exclusive lock (a quarantine in flight):
        # this process compiles cold rather than racing the rename
        os.close(fd)
        return False, "lock contention on the cache writer lock"

    def _upgrade_exclusive() -> bool:
        return _flock(fd, True)

    def _downgrade_shared() -> None:
        _flock(fd, False)

    notes = []

    # -- step 2: build fingerprint ----------------------------------
    meta_path = os.path.join(path, _META_NAME)
    fp = _fingerprint()
    stale = None
    try:
        with open(meta_path) as fh:
            old = json.load(fh)
        if old != fp:
            stale = f"cache written by another build ({old})"
    except FileNotFoundError:
        pass
    except (OSError, ValueError):
        stale = "unreadable cache fingerprint"
    if stale:
        if not _upgrade_exclusive():
            os.close(fd)
            return False, (f"{stale} and still in use by another "
                           f"process — compiling cold")
        q = _quarantine_dir(path)
        if q is None:
            # the rename failed (permissions, a concurrent re-create):
            # the foreign dir is STILL there, and it is exactly the
            # reload-hang class — never enable over it, compile cold
            os.close(fd)
            return False, (f"{stale} and the quarantine rename failed "
                           f"— compiling cold")
        tel.counter("compile.persistent_cache_quarantines")
        notes.append(f"quarantined stale dir -> {q}")
        _downgrade_shared()
    if not os.path.exists(meta_path):
        try:
            tmp = meta_path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(fp, fh)
            os.replace(tmp, meta_path)
        except OSError:
            pass  # another process won the race; theirs matches or
            # the next enable quarantines

    # -- step 3: corruption scan ------------------------------------
    # chaos site: damage one entry right before the scan so the test
    # harness can pin "detected, quarantined, run continues"
    if faults.fire("cache_corrupt") is not None:
        victims = [n for n in os.listdir(path) if n.endswith("-cache")]
        victim = os.path.join(
            path, victims[0] if victims else "poisoned-entry-cache")
        try:
            with open(victim, "w"):
                pass  # zero-truncate (or create empty): detectably bad
        except OSError:
            pass
    qdir = os.path.join(path, ".quarantine")
    bad = 0
    try:
        now = time.time()
        for name in os.listdir(path):
            if name in (_META_NAME, _PROBE_STAMP, ".quarantine"):
                continue
            p = os.path.join(path, name)
            if not os.path.isfile(p):
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            is_bad = (name.endswith("-cache") and st.st_size == 0) or \
                (name.endswith(".tmp") and now - st.st_mtime > 3600)
            if is_bad:
                try:
                    os.makedirs(qdir, exist_ok=True)
                    os.rename(p, os.path.join(qdir, name))
                    bad += 1
                except OSError:
                    pass
    except OSError:
        pass
    if bad:
        tel.counter("compile.persistent_cache_quarantines", bad)
        notes.append(f"quarantined {bad} corrupt entr"
                     f"{'y' if bad == 1 else 'ies'}")

    # -- step 4: health probe under a hard timeout ------------------
    if os.environ.get("JAXMC_CACHE_PROBE", "1") != "0":
        stamp = os.path.join(path, _PROBE_STAMP)
        fresh = False
        try:
            fresh = time.time() - os.path.getmtime(stamp) < _PROBE_FRESH_S
        except OSError:
            pass
        if not fresh:
            ok, why = _health_probe(path, timeout_s)
            if not ok:
                if _upgrade_exclusive():
                    q = _quarantine_dir(path)
                    tel.counter("compile.persistent_cache_quarantines")
                    why += f"; dir quarantined -> {q}"
                    _downgrade_shared()
                os.close(fd)
                return False, f"health probe failed ({why})"
            try:
                with open(stamp, "w") as fh:
                    fh.write(str(time.time()))
            except OSError:
                pass
            notes.append("probed ok")

    _LOCK_FD = fd  # park the shared lock for the process lifetime
    return True, "; ".join(notes) if notes else "ok"


def _health_probe(path: str, timeout_s: float) -> Tuple[bool, str]:
    """Jit one trivial program against the cache dir in a SUBPROCESS so
    a wedged blob reload (the known failure class) hits OUR timeout, not
    the run's deadline. The `cache_hang` fault site wedges the child."""
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    code = (
        "import os, sys, time\n"
        "sys.path.insert(0, " + repr(repo) + ")\n"
        "from jaxmc import faults\n"
        "if faults.fire('cache_hang') is not None:\n"
        "    time.sleep(3600)  # the simulated wedge\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_compilation_cache_dir', " + repr(path) +
        ")\n"
        "import jax.numpy as jnp\n"
        "jax.jit(lambda x: x * 2 + 1)(jnp.arange(3)).block_until_ready()"
        "\n")
    try:
        from ..obs import context as trace_context
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s,
                           env=dict(trace_context.child_env(),
                                    JAX_PLATFORMS="cpu"))
    except subprocess.TimeoutExpired:
        return False, f"wedged past {timeout_s:.0f}s"
    except OSError as ex:
        return False, f"probe could not run: {ex}"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-1:] or ["?"]
        return False, f"probe rc={p.returncode}: {tail[0][:120]}"
    return True, "ok"


def enable_guarded_cache(path: Optional[str] = None, tel=None,
                         timeout_s: Optional[float] = None
                         ) -> Optional[str]:
    """The DEFAULT-ON entry (bench children, sweep subprocesses): run
    the guard battery, then enable the cache.  Returns the cache dir
    when enabled, None on opt-out or cold fallback.  NEVER raises and
    never hangs: every guard defect degrades to cold compilation."""
    from .. import obs
    if tel is None:
        tel = obs.current()
    # the env opt-out governs the DEFAULT-ON call sites only: an
    # explicit `path` (cli --compile-cache DIR) is a direct request and
    # overrides a box-wide JAXMC_COMPILE_CACHE=off
    if path is None and cache_disabled_by_env():
        tel.gauge("compile.persistent_cache_guard",
                  "disabled:JAXMC_COMPILE_CACHE opt-out")
        return None
    path = path or cache_dir_from_env() or default_cache_dir()
    if timeout_s is None:
        timeout_s = float(os.environ.get("JAXMC_CACHE_GUARD_TIMEOUT",
                                         "60"))
    try:
        ok, detail = _guard(path, timeout_s, tel)
    except Exception as ex:  # noqa: BLE001 — guard bugs degrade cold
        ok, detail = False, f"guard error: {type(ex).__name__}: {ex}"
    if not ok:
        tel.gauge("compile.persistent_cache_guard",
                  f"cold-fallback:{detail}")
        tel.counter("compile.persistent_cache_fallbacks")
        return None
    d = enable_persistent_cache(path, tel=tel)
    if d is None:
        # the guard battery passed but the raw enabler could not turn
        # the cache on (jax unavailable/config failure): the verdict
        # gauge must say COLD, not "ok" — an artifact claiming an
        # enabled cache with zero hits would misattribute the compile
        tel.gauge("compile.persistent_cache_guard",
                  "cold-fallback:enable failed (jax unavailable or "
                  "cache config rejected)")
        tel.counter("compile.persistent_cache_fallbacks")
        return None
    tel.gauge("compile.persistent_cache_guard",
              f"ok ({detail})" if detail != "ok" else "ok")
    return d


def enable_persistent_cache(path: Optional[str] = None,
                            tel=None) -> Optional[str]:
    """Configure jax's persistent compilation cache at `path` (default:
    env JAXMC_COMPILE_CACHE) and register a monitoring listener that
    mirrors cache hits into the active obs telemetry.  Pass `tel` when
    the caller's recorder is not yet installed process-wide (bench
    children enable the cache inside their device_init span, before
    obs.use).  Returns the cache dir when enabled, None when not
    requested or jax is unavailable.  Never raises: a broken cache setup
    must not break a check run.  This is the RAW enabler — default-on
    call sites go through enable_guarded_cache."""
    path = path or cache_dir_from_env()
    if not path:
        return None
    try:
        import jax
        from .. import obs
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: the per-arm kernels are small but numerous,
        # and the default min-compile-time floor would skip most of them
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — knob absent on old jax
                pass
        if tel is None:
            tel = obs.current()
        tel.gauge("compile.persistent_cache_dir", path)
        n0 = _count_entries(path)
        if n0 is not None:
            tel.gauge("compile.persistent_cache_entries_start", n0)

        def _on_event(event: str, **kw) -> None:
            # route through current() at fire time: the telemetry active
            # when the compile runs, not when the cache was enabled
            if "compilation_cache" not in event:
                return
            from .. import obs as _obs
            name = event.rsplit("/", 1)[-1]  # e.g. 'cache_hits'
            if name.startswith("cache_"):
                name = name[len("cache_"):]
            _obs.current().counter(f"compile.persistent_cache_{name}")

        # register exactly once per process: jax.monitoring keeps every
        # listener, so a second enable call (library user running two
        # checks) would double-count every cache event
        global _LISTENER_REGISTERED
        if not _LISTENER_REGISTERED:
            try:
                from jax import monitoring
                monitoring.register_event_listener(_on_event)
                _LISTENER_REGISTERED = True
            except Exception:  # noqa: BLE001 — monitoring API drift
                pass
        return path
    except Exception:  # noqa: BLE001
        return None


def record_entries_end(path: Optional[str], tel=None) -> None:
    """Stamp the end-of-run entry count (a second identical run shows
    entries_start == entries_end AND persistent_cache_hits > 0)."""
    if not path:
        return
    from .. import obs
    n = _count_entries(path)
    if n is not None:
        (tel if tel is not None else obs.current()).gauge(
            "compile.persistent_cache_entries_end", n)


# ---------------------------------------------------------------------
# Learned per-spec CAPACITY PROFILES (ISSUE 6).
#
# The resident engine's capacity buckets (SC/FCap/AccCap/VC) are learned
# by overflow-growth — and every growth is a full XLA recompile of the
# whole while_loop program, potentially inside somebody's measured
# window.  A capacity profile persists the caps a completed resident run
# ended with, keyed by (module, layout signature), NEXT TO the compile
# cache: the next run on the same spec starts at the learned caps, its
# one warm-up compile covers the whole run, and `window_recompiles`
# reads 0 in the steady-state bench.
#
# Safety: a profile is a pure PERFORMANCE hint — wrong caps can only
# cost a recompile (the engine's overflow-growth path still works), so a
# stale/foreign profile is IGNORED with a named reason, never trusted
# into a crash.  Validation: schema, module name, layout signature (it
# covers the lane plan, so a packing change invalidates profiles), and
# sane positive-int caps.  JAXMC_CAP_PROFILE=0 disables load AND save.

_PROFILE_SCHEMA = "jaxmc.capacity-profile/1"
_PROFILE_CAP_KEYS = ("SC", "FCap", "AccCap", "VC")


def profiles_enabled() -> bool:
    return os.environ.get("JAXMC_CAP_PROFILE", "1").strip().lower() \
        not in _OFF_VALUES


def profile_dir() -> str:
    d = os.environ.get("JAXMC_PROFILE_STORE")
    if d:
        return d
    return (cache_dir_from_env() or default_cache_dir()) + ".profiles"


def profile_path(module: str, layout_sig: str, variant: str = "") -> str:
    safe = "".join(ch if ch.isalnum() or ch in "._-" else "_"
                   for ch in module)[:80]
    vtag = ""
    if variant:
        vsafe = "".join(ch if ch.isalnum() or ch in "._-" else "_"
                        for ch in variant)[:40]
        vtag = f".{vsafe}"
    return os.path.join(profile_dir(),
                        f"{safe}.{layout_sig[:16]}{vtag}.json")


def load_capacity_profile(module: str, layout_sig: str, tel=None,
                          variant: str = "",
                          keys: Tuple[str, ...] = _PROFILE_CAP_KEYS,
                          optional: Tuple[str, ...] = ()
                          ) -> Optional[dict]:
    """The validated caps dict, or None with a NAMED degrade reason in
    the `profile.status` gauge (absent / unreadable / foreign schema /
    module mismatch / stale layout / bad caps).  Never raises.

    `variant` keys engine families apart: the resident single-chip
    engine stores the default variant, the mesh engine stores one
    profile per (device count, exchange strategy) — `mesh-d4-a2a` —
    because its capacity shape (per-SHARD seen/frontier, trace-ring
    levels, the a2a bucket factor) depends on D (ISSUE 8).  `keys`
    names the cap fields that variant persists."""
    from .. import obs
    tel = tel if tel is not None else obs.current()
    if not profiles_enabled():
        tel.gauge("profile.status", "disabled:JAXMC_CAP_PROFILE")
        return None
    path = profile_path(module, layout_sig, variant)

    def _no(reason: str) -> None:
        tel.gauge("profile.status", f"degraded:{reason}")
        tel.counter("profile.degrades")

    try:
        with open(path, encoding="utf-8") as fh:
            p = json.load(fh)
    except FileNotFoundError:
        tel.gauge("profile.status", "absent")
        return None
    except (OSError, ValueError) as ex:
        _no(f"unreadable profile ({type(ex).__name__})")
        return None
    if not isinstance(p, dict) or p.get("schema") != _PROFILE_SCHEMA:
        _no(f"foreign schema {p.get('schema') if isinstance(p, dict) else type(p).__name__!r}")
        return None
    if p.get("module") != module:
        _no(f"module mismatch ({p.get('module')!r})")
        return None
    if p.get("layout_sig") != layout_sig:
        # the one expected staleness class: the model/bounds/pack plan
        # changed since the profile was learned
        _no("stale layout signature (model, caps or packing changed)")
        return None
    if p.get("variant", "") != variant:
        _no(f"variant mismatch ({p.get('variant')!r})")
        return None
    caps = p.get("caps")
    if not isinstance(caps, dict) or not all(
            isinstance(caps.get(k), int) and 0 < caps[k] < (1 << 31)
            for k in keys):
        _no("malformed caps")
        return None
    tel.gauge("profile.status", "loaded")
    tel.counter("profile.hits")
    out = {k: int(caps[k]) for k in keys}
    # `optional` names caps newer engines persist but older profiles
    # (or strategy configurations that never learn them — ISSUE 11's
    # mesh VC under the fullsort escape hatch) may lack: validated the
    # same way when present, silently absent otherwise
    for k in optional:
        if isinstance(caps.get(k), int) and 0 < caps[k] < (1 << 31):
            out[k] = int(caps[k])
    return out


def save_capacity_profile(module: str, layout_sig: str,
                          caps: dict, tel=None, variant: str = "",
                          keys: Tuple[str, ...] = _PROFILE_CAP_KEYS,
                          optional: Tuple[str, ...] = (),
                          **extra) -> Optional[str]:
    """Persist the caps a completed resident run ended with (atomic
    write; max-merged over any existing valid profile so alternating
    workloads never thrash each other downward).  Never raises.
    `optional` caps persist when the run learned them and are dropped
    (without vetoing the save) when it did not."""
    from .. import obs
    tel = tel if tel is not None else obs.current()
    if not profiles_enabled():
        return None
    try:
        prev = load_capacity_profile(module, layout_sig,
                                     tel=obs.NullTelemetry(),
                                     variant=variant, keys=keys,
                                     optional=optional)
        merged = {k: int(caps[k]) for k in keys
                  if isinstance(caps.get(k), int)}
        if len(merged) != len(keys):
            return None
        for k in optional:
            if isinstance(caps.get(k), int):
                merged[k] = int(caps[k])
        if prev:
            for k in list(merged):
                if k in prev:
                    merged[k] = max(merged[k], prev[k])
            for k in optional:
                if k in prev and k not in merged:
                    merged[k] = prev[k]
        d = profile_dir()
        os.makedirs(d, exist_ok=True)
        path = profile_path(module, layout_sig, variant)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"schema": _PROFILE_SCHEMA, "module": module,
                       "layout_sig": layout_sig, "variant": variant,
                       "caps": merged,
                       "build": _fingerprint(), "saved_at": time.time(),
                       **extra}, fh)
        os.replace(tmp, path)
        tel.gauge("profile.status", "saved")
        tel.counter("profile.saves")
        return path
    except Exception:  # noqa: BLE001 — a profile is a hint, never a crash
        return None


def release_lock_for_tests() -> None:
    """Drop the parked shared flock so tests can exercise contention."""
    global _LOCK_FD
    if _LOCK_FD is not None:
        try:
            os.close(_LOCK_FD)
        except OSError:
            pass
        _LOCK_FD = None
