r"""Persistent XLA compilation-cache wiring (JAXMC_COMPILE_CACHE).

The per-arm XLA compiles have repeatedly eaten the bench deadline
(BENCH_r03..r05: every device child pays the full compile bill even when
the previous child compiled the identical programs minutes earlier).
JAX's persistent compilation cache (`jax_compilation_cache_dir`) makes
repeat compiles disk hits; this module is the ONE place that enables it
and exposes its effectiveness as obs counters:

  compile.persistent_cache_hits    (jax monitoring event
                                    '/jax/compilation_cache/cache_hits')
  gauge compile.persistent_cache_dir
  gauge compile.persistent_cache_entries_start / _end

Opt-in only (env JAXMC_COMPILE_CACHE=<dir> or cli --compile-cache):
XLA:CPU blob reloads written by a DIFFERENT machine/build have been
observed to hang (tests/conftest.py), so nothing enables it implicitly —
bench.py opts its children in because they share one box and build.
"""

from __future__ import annotations

import os
from typing import Optional


def cache_dir_from_env() -> Optional[str]:
    d = os.environ.get("JAXMC_COMPILE_CACHE")
    return d or None


_LISTENER_REGISTERED = False


def _count_entries(path: str) -> Optional[int]:
    try:
        return sum(1 for n in os.listdir(path)
                   if not n.endswith(".tmp"))
    except OSError:
        return None


def enable_persistent_cache(path: Optional[str] = None,
                            tel=None) -> Optional[str]:
    """Configure jax's persistent compilation cache at `path` (default:
    env JAXMC_COMPILE_CACHE) and register a monitoring listener that
    mirrors cache hits into the active obs telemetry.  Pass `tel` when
    the caller's recorder is not yet installed process-wide (bench
    children enable the cache inside their device_init span, before
    obs.use).  Returns the cache dir when enabled, None when not
    requested or jax is unavailable.  Never raises: a broken cache setup
    must not break a check run."""
    path = path or cache_dir_from_env()
    if not path:
        return None
    try:
        import jax
        from .. import obs
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: the per-arm kernels are small but numerous,
        # and the default min-compile-time floor would skip most of them
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — knob absent on old jax
                pass
        if tel is None:
            tel = obs.current()
        tel.gauge("compile.persistent_cache_dir", path)
        n0 = _count_entries(path)
        if n0 is not None:
            tel.gauge("compile.persistent_cache_entries_start", n0)

        def _on_event(event: str, **kw) -> None:
            # route through current() at fire time: the telemetry active
            # when the compile runs, not when the cache was enabled
            if "compilation_cache" not in event:
                return
            from .. import obs as _obs
            name = event.rsplit("/", 1)[-1]  # e.g. 'cache_hits'
            if name.startswith("cache_"):
                name = name[len("cache_"):]
            _obs.current().counter(f"compile.persistent_cache_{name}")

        # register exactly once per process: jax.monitoring keeps every
        # listener, so a second enable call (library user running two
        # checks) would double-count every cache event
        global _LISTENER_REGISTERED
        if not _LISTENER_REGISTERED:
            try:
                from jax import monitoring
                monitoring.register_event_listener(_on_event)
                _LISTENER_REGISTERED = True
            except Exception:  # noqa: BLE001 — monitoring API drift
                pass
        return path
    except Exception:  # noqa: BLE001
        return None


def record_entries_end(path: Optional[str], tel=None) -> None:
    """Stamp the end-of-run entry count (a second identical run shows
    entries_start == entries_end AND persistent_cache_hits > 0)."""
    if not path:
        return
    from .. import obs
    n = _count_entries(path)
    if n is not None:
        (tel if tel is not None else obs.current()).gauge(
            "compile.persistent_cache_entries_end", n)
