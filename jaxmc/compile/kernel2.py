r"""Lanes-first kernel compiler: grounded actions -> jit/vmap transition
kernels over vspec layouts (SURVEY.md §7.4).

Every symbolic value is a SymV(spec, lanes): a vspec shape plus its encoded
i32 lanes (python ints when static, traced scalars otherwise). Because
encodings are canonical (vspec.py), equality is lane equality, IF is a
lane-wise where, and containers are lane slices — one uniform rule set
covers raft's sequences, message unions, bags, and history sets.

Spec unification: before comparing/merging two values their specs are
vspec.merge'd and both re-encoded (coerce) — e.g. a 2-entry log literal
meets the cap-4 log layout, a concrete RequestVote record meets the
message-union spec.

Capacity overflow (Append past seq cap, bag insert past table cap, interval
past the int-set universe) raises an overflow flag that the engine treats
as a hard error — never silent truncation, counts stay exact
(BASELINE.json).
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from ..front import tla_ast as A
from ..sem.values import (EvalError, Fcn, InfiniteSet, ModelValue,
                          in_set, sort_key, tla_eq)
from ..sem.eval import OpClosure, bind_pattern
from ..sem.modules import Model
from .vspec import (Bounds, CompileError, EnumUniverse, SENTINEL_LANE, VS,
                    encode as vs_encode, merge as vs_merge)

BOOL = VS("bool")
INT = VS("int")
ENUM = VS("enum")


def _is_traced(v) -> bool:
    return isinstance(v, jnp.ndarray) or hasattr(v, "aval")


class SymV:
    """A symbolic value: vspec shape + its encoded lanes as ONE i32 array
    (np.ndarray when fully static, a traced jax array otherwise). Array
    lanes keep the jaxpr O(expression size): slices, splices, equality and
    selects are single XLA ops over the whole block instead of per-lane
    scalar graphs."""
    __slots__ = ("spec", "lanes")

    def __init__(self, spec: VS, lanes):
        self.spec = spec
        if isinstance(lanes, (list, tuple)):
            lanes = _cat([_as_lane_arr(x) for x in lanes]) if lanes \
                else np.zeros(0, np.int32)
        self.lanes = lanes

    @property
    def static(self) -> bool:
        return isinstance(self.lanes, np.ndarray)

    def __repr__(self):
        return f"SymV({self.spec.kind}, {len(self.lanes)} lanes)"


def _as_lane_arr(x):
    """One lane (scalar int/bool, traced scalar, or an array) as a 1-D
    lane array segment."""
    if isinstance(x, np.ndarray):
        return x.astype(np.int32) if x.ndim else x.reshape(1).astype(np.int32)
    if _is_traced(x):
        if x.ndim == 0:
            x = jnp.reshape(x, (1,))
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.int32)
        return x.astype(jnp.int32)
    if isinstance(x, bool):
        return np.asarray([1 if x else 0], np.int32)
    return np.asarray([x], np.int32)


def _cat(segs):
    """Concatenate lane segments; stays numpy when all static."""
    segs = [sg for sg in segs if len(sg)]
    if not segs:
        return np.zeros(0, np.int32)
    if len(segs) == 1:
        return segs[0]
    if all(isinstance(sg, np.ndarray) for sg in segs):
        return np.concatenate(segs)
    return jnp.concatenate([jnp.asarray(sg) for sg in segs])


def _zeros(n):
    return np.zeros(n, np.int32)


def _fill(n, v):
    return np.full(n, v, np.int32)


def _ite(c, a, b):
    """where() on single lanes with static shortcuts."""
    if isinstance(c, bool):
        return a if c else b
    if isinstance(a, (int, bool)) and isinstance(b, (int, bool)) and a == b:
        return a
    return jnp.where(c, a, b)


def _npbool(x):
    return bool(x) if isinstance(x, np.bool_) else x


def _land(a, b):
    a, b = _npbool(a), _npbool(b)
    if a is True:
        return b
    if b is True:
        return a
    if a is False or b is False:
        return False
    return jnp.logical_and(a, b)


def _lor(a, b):
    a, b = _npbool(a), _npbool(b)
    if a is False:
        return b
    if b is False:
        return a
    if a is True or b is True:
        return True
    return jnp.logical_or(a, b)


def _lnot(a):
    a = _npbool(a)
    return (not a) if isinstance(a, bool) else jnp.logical_not(a)


def _eq_lane(a, b):
    if not _is_traced(a) and not _is_traced(b):
        return a == b
    return jnp.equal(a, b)


class KernelCtx:
    """Compilation context for one model."""

    def __init__(self, model: Model, layout, bounds: Bounds):
        self.model = model
        self.layout = layout
        self.uni: EnumUniverse = layout.uni
        self.bounds = bounds
        self.iset_cap = max([bounds.seq_cap] +
                            [s.cap for s in layout.specs.values()
                             if s.kind == "seq"])
        # per-operator unroll depth (ISSUE 5): a RECURSIVE operator on
        # symbolic arguments unrolls forever at trace time. Catching it
        # as a Python RecursionError loses the culprit's name; this
        # counter trips FIRST and raises a CompileError that NAMES the
        # recursing operator — the per-arm demotion reason table then
        # says "Serializable diverges", not just "RecursionError".
        # Same-name re-entry 64 deep is legitimate only for concrete
        # (terminating) recursion far larger than any corpus model uses
        # (JAXMC_OP_UNROLL_LIMIT raises it).
        self.op_depth: Dict[str, int] = {}
        self.op_unroll_limit = int(
            os.environ.get("JAXMC_OP_UNROLL_LIMIT", "64"))
        # LIFTED CONSTANTS (ISSUE 13): name -> traced int32 scalar.
        # When a name is present here, identifier resolution returns the
        # traced lane instead of baking the model's concrete value into
        # the kernel — the same compiled program then serves every
        # layout-compatible model, with per-model CONSTANT values fed in
        # as batch-axis inputs (backend/batch.py).  Installed at TRACE
        # time by the engine (bfs.py installs the tracers at the top of
        # each jitted step / forced abstract trace), empty otherwise.
        # A lifted constant used where compilation needs a STATIC value
        # (a quantifier domain bound, a container cap) raises the usual
        # CompileError at trace time — the batch planner treats that as
        # "not batchable", never as a wrong kernel.
        self.const_lanes: Dict[str, Any] = {}


class Frame:
    """Per-expression evaluation frame."""
    __slots__ = ("kc", "bound", "state", "primes", "overflow", "strict",
                 "guard", "demo", "memo")

    def __init__(self, kc: KernelCtx, bound, state, primes, overflow,
                 strict=False, guard=True, demo=None, memo=None):
        self.kc = kc
        self.bound = bound      # name -> SymV | static python value
        self.state = state      # var -> SymV
        self.primes = primes    # var -> SymV
        self.overflow = overflow  # list with one traced/py bool cell
        # strict frames (compiled predicates) may not use overflow-guarded
        # recovery: a wrong False from an invariant would be a spurious
        # violation, a wrong True a missed one — fail the compile instead
        self.strict = strict
        # liveness of the current evaluation context: bodies evaluated for
        # dead quantifier/set members (mask false) must not abort the run
        self.guard = guard
        # DEMOTION cell (may be None): flags from `except CompileError`
        # recovery sites — compiler limitations the hybrid engine can fix
        # by demoting the arm to the interpreter — land here, separate
        # from genuine capacity overflows (see flag_demoted)
        self.demo = demo
        # STRICT-frame symbolic-value memo (sym_eval2): predicates carry
        # no overflow flags (they raise instead) and guard never affects
        # VALUES, so identical (expr, relevant-bound) subterms can share
        # one traced result — this collapses exponential unrolls
        # (MCVoting's mutually recursive VotesSafeAt) into a DAG
        self.memo = memo

    def with_bound(self, extra):
        return Frame(self.kc, {**self.bound, **extra}, self.state,
                     self.primes, self.overflow, self.strict, self.guard,
                     self.demo, self.memo)

    def with_guard(self, g):
        return Frame(self.kc, self.bound, self.state, self.primes,
                     self.overflow, self.strict, _land(self.guard, g),
                     self.demo, self.memo)

    def flag_overflow(self, cond, why=None):
        """A genuine capacity/spec overflow: a value outgrew its lanes
        (the fix is a larger --seq-cap/--kv-cap/--grow-cap)."""
        cond = _land(self.guard, _npbool(cond))
        if self.strict and cond is not False:
            raise CompileError(
                "uncompilable subterm in a predicate (no overflow "
                "recovery in invariants)"
                + (f": {why}" if why else ""))
        self.overflow[0] = _lor(self.overflow[0], cond)

    def flag_demoted(self, cond, why=None):
        """A compile-limitation recovery (an `except CompileError` site):
        the compiled guard/value deviates from TLC unless the run aborts
        when cond holds. Kept in a separate cell so the hybrid engine can
        demote the arm to exact interpreter enumeration and restart,
        instead of reporting a spurious capacity overflow.  `why` (the
        recovered CompileError's message) survives into the strict-mode
        refusal so a demoted PREDICATE's reason still names the real
        culprit (e.g. which recursive operator diverged)."""
        cond = _land(self.guard, _npbool(cond))
        if self.strict and cond is not False:
            raise CompileError(
                "uncompilable subterm in a predicate (no overflow "
                "recovery in invariants)"
                + (f": {why}" if why else ""))
        cell = self.demo if self.demo is not None else self.overflow
        cell[0] = _lor(cell[0], cond)


def static_to_symv(v, kc: KernelCtx, spec: Optional[VS] = None) -> SymV:
    """Encode a concrete interpreter value as lanes."""
    if spec is None:
        from .vspec import infer
        spec = infer(v, kc.uni)
        from .vspec import apply_bounds
        spec = apply_bounds(spec, kc.bounds)
    out: List[int] = []
    vs_encode(v, spec, kc.uni, out)
    return SymV(spec, np.asarray(out, np.int32))


def coerce(v: SymV, spec: VS, fr: Frame) -> SymV:
    """Re-encode v's lanes under a (merged, wider) spec."""
    if v.spec == spec:
        return v
    return SymV(spec, _coerce_lanes(v.spec, spec, v.lanes, fr))


def _coerce_lanes(src: VS, dst: VS, lanes, fr: Frame):
    """Re-encode a lane array from spec src to spec dst (array in/out)."""
    if src == dst:
        return lanes
    uni = fr.kc.uni
    sk, dk = src.kind, dst.kind
    if sk == "justempty":
        if dk == "seq":
            return _zeros(dst.width)
        if dk == "kvtable":
            return _cat([_zeros(1), _fill(dst.width - 1, SENTINEL_LANE)])
        if dk == "pfcn":
            return _zeros(dst.width)
        if dk == "fcn":
            fr.flag_overflow(len(dst.dom) > 0)
            return _zeros(dst.width)
        raise CompileError(f"cannot coerce empty function to {dk}")
    if dk == "justempty":
        # storing into an only-ever-empty layout slot: exact as long as the
        # value is empty at runtime; otherwise the overflow flag aborts
        if sk in ("seq", "kvtable"):
            fr.flag_overflow(_lnot(_eq_lane(lanes[0], 0)))
            return _zeros(0)
        if sk == "pfcn":
            off = 0
            for kk, es in zip(src.dom, src.elems):
                fr.flag_overflow(_eq_lane(lanes[off], 1))
                off += 1 + es.width
            return _zeros(0)
        if sk == "fcn":
            fr.flag_overflow(len(src.dom) > 0)
            return _zeros(0)
    if sk == "emptyset" or (sk == "set" and not src.dom):
        if dk == "set":
            return _zeros(len(dst.dom))
        if dk == "growset":
            return _cat([_zeros(1), _fill(dst.width - 1, SENTINEL_LANE)])
        if dk == "iset":
            return _zeros(len(dst.dom))
        raise CompileError(f"cannot coerce empty set to {dk}")
    if sk == dk == "seq":
        if dst.cap < src.cap:
            # shrinking is sound when the runtime length fits; otherwise
            # the overflow flag aborts the run (universe-sized constructor
            # results coerce into tighter layout slots)
            fr.flag_overflow(_ge_lane(lanes[0], dst.cap + 1))
        segs = [lanes[0:1]]
        for i in range(min(src.cap, dst.cap)):
            segs.append(_coerce_lanes(
                src.elem, dst.elem,
                lanes[1 + i * src.elem.width:
                      1 + (i + 1) * src.elem.width], fr))
        if dst.cap > src.cap:
            segs.append(_zeros((dst.cap - src.cap) * dst.elem.width))
        return _cat(segs)
    if sk == dk == "set":
        pos = {m: i for i, m in enumerate(src.dom)}
        if set(src.dom) - set(dst.dom):
            raise CompileError("set coercion drops members")
        segs = [lanes[pos[m]:pos[m] + 1] if m in pos else _zeros(1)
                for m in dst.dom]
        return _cat(segs)
    if sk == dk == "iset" or (sk == "set" and dk == "iset"):
        pos = {m: i for i, m in enumerate(src.dom)}
        if set(src.dom) - set(dst.dom):
            raise CompileError("iset coercion drops members")
        segs = [lanes[pos[m]:pos[m] + 1] if m in pos else _zeros(1)
                for m in dst.dom]
        return _cat(segs)
    if sk == dk == "growset":
        if src.elem != dst.elem:
            raise CompileError("growset element coercion unsupported")
        if dst.cap < src.cap:
            raise CompileError("growset coercion would shrink capacity")
        return _cat([lanes,
                     _fill((dst.cap - src.cap) * dst.elem.width,
                           SENTINEL_LANE)])
    if sk == dk == "kvtable":
        if src.elem != dst.elem or src.val != dst.val:
            raise CompileError("kvtable element coercion unsupported")
        if dst.cap < src.cap:
            raise CompileError("kvtable coercion would shrink capacity")
        pad = dst.elem.width + dst.val.width
        return _cat([lanes, _fill((dst.cap - src.cap) * pad,
                                  SENTINEL_LANE)])
    if sk == "fcn" and dk == "union":
        names = tuple(k for k in src.dom)
        for tag, (vnames, vfields) in enumerate(dst.variants):
            if vnames == names:
                segs = [np.asarray([tag], np.int32)]
                off = 0
                w = 1
                for (kk, es), fs in zip(zip(src.dom, src.elems), vfields):
                    seg = _coerce_lanes(es, fs,
                                        lanes[off:off + es.width], fr)
                    segs.append(seg)
                    off += es.width
                    w += fs.width
                segs.append(_zeros(dst.width - w))
                return _cat(segs)
        raise CompileError(f"record {names} not a variant of the union")
    if sk in ("int", "bool", "enum") and dk == "union":
        # scalar into a tagged union (buf[p] := NoVal alongside request
        # records — the CachingMemory shape)
        want = (f"$scalar:{sk}",)
        for tag, (vnames, vfields) in enumerate(dst.variants):
            if vnames == want:
                return _cat([np.asarray([tag], np.int32),
                             _as_seg(lanes, 1),
                             _zeros(dst.width - 2)])
        raise CompileError(f"scalar {sk} not a variant of the union")
    if sk == "union" and dk == "union" and src != dst:
        # re-tag into a superset union (a sub-union value constructed in
        # an expression lands in the var's merged layout union)
        smap = {names: (t, fields)
                for t, (names, fields) in enumerate(src.variants)}
        dmap = {names: (t, fields)
                for t, (names, fields) in enumerate(dst.variants)}
        for names in smap:
            if names not in dmap:
                raise CompileError(
                    f"union variant {names} not in the target union")
        tag_l = lanes[0]
        acc_tag = None
        acc_pay = None
        for names, (stag, sfields) in smap.items():
            dtag, dfields = dmap[names]
            off = 1
            segs = []
            w = 0
            for sf, df in zip(sfields, dfields):
                segs.append(_coerce_lanes(
                    sf, df, lanes[off:off + sf.width], fr))
                off += sf.width
                w += df.width
            segs.append(_zeros(dst.width - 1 - w))
            pay = _cat(segs)
            cond = _eq_lane(tag_l, stag)
            dt = np.asarray([dtag], np.int32)
            acc_tag = dt if acc_tag is None else _select_lanes(
                cond, dt, acc_tag)
            acc_pay = pay if acc_pay is None else _select_lanes(
                cond, pay, acc_pay)
        return _cat([_as_seg(acc_tag, 1), acc_pay])
    if sk == "fcn" and dk == "pfcn":
        srcmap = {}
        off = 0
        for kk, es in zip(src.dom, src.elems):
            srcmap[kk] = (es, lanes[off:off + es.width])
            off += es.width
        if set(srcmap) - set(dst.dom):
            raise CompileError("pfcn coercion drops keys")
        segs = []
        for kk, es in zip(dst.dom, dst.elems):
            if kk in srcmap:
                ses, sl = srcmap[kk]
                segs.append(np.asarray([1], np.int32))
                segs.append(_coerce_lanes(ses, es, sl, fr))
            else:
                segs.append(_zeros(1 + es.width))
        return _cat(segs)
    if sk == "fcn" and dk == "seq":
        if not all(isinstance(k, int) for k in src.dom):
            raise CompileError("cannot coerce non-int function to sequence")
        n = len(src.dom)
        if n > dst.cap:
            raise CompileError("sequence literal exceeds capacity")
        segs = [np.asarray([n], np.int32)]
        off = 0
        for kk, es in zip(src.dom, src.elems):
            segs.append(_coerce_lanes(es, dst.elem,
                                      lanes[off:off + es.width], fr))
            off += es.width
        segs.append(_zeros((dst.cap - n) * dst.elem.width))
        return _cat(segs)
    if sk == "fcn" and dk == "kvtable":
        rows = []
        off = 0
        for kk, es in zip(src.dom, src.elems):
            kb: List[int] = []
            vs_encode(kk, dst.elem, uni, kb)
            vlanes = _coerce_lanes(es, dst.val,
                                   lanes[off:off + es.width], fr)
            rows.append((kb, vlanes))
            off += es.width
        rows.sort(key=lambda r: r[0])
        if len(rows) > dst.cap:
            raise CompileError("table literal exceeds capacity")
        segs = [np.asarray([len(rows)], np.int32)]
        for kb, vl in rows:
            segs.append(np.asarray(kb, np.int32))
            segs.append(vl)
        pad = dst.elem.width + dst.val.width
        segs.append(_fill((dst.cap - len(rows)) * pad, SENTINEL_LANE))
        return _cat(segs)
    if sk == "fcn" and dk == "fcn":
        if tuple(src.dom) != tuple(dst.dom):
            raise CompileError("function domains differ in coercion")
        segs = []
        off = 0
        for (kk, ses), des in zip(zip(src.dom, src.elems), dst.elems):
            segs.append(_coerce_lanes(ses, des,
                                      lanes[off:off + ses.width], fr))
            off += ses.width
        return _cat(segs)
    if sk == "pfcn" and dk == "fcn":
        # sound when every dst key is present; absent keys flag overflow
        srcmap = {}
        off = 0
        for kk, es in zip(src.dom, src.elems):
            srcmap[kk] = (lanes[off], es, lanes[off + 1:off + 1 + es.width])
            off += 1 + es.width
        segs = []
        for kk, es in zip(dst.dom, dst.elems):
            if kk not in srcmap:
                raise CompileError("pfcn->fcn coercion missing key")
            pres, ses, sl = srcmap[kk]
            fr.flag_overflow(_eq_lane(pres, 0))
            segs.append(_coerce_lanes(ses, es, sl, fr))
        return _cat(segs)
    if sk == "pfcn" and dk == "pfcn":
        srcmap = {}
        off = 0
        for kk, es in zip(src.dom, src.elems):
            srcmap[kk] = (lanes[off:off + 1], es,
                          lanes[off + 1:off + 1 + es.width])
            off += 1 + es.width
        segs = []
        for kk, es in zip(dst.dom, dst.elems):
            if kk in srcmap:
                pres, ses, sl = srcmap[kk]
                segs.append(pres)
                segs.append(_coerce_lanes(ses, es, sl, fr))
            else:
                segs.append(_zeros(1 + es.width))
        return _cat(segs)
    if sk == "iset" and dk == "set":
        raise CompileError("cannot view integer set as enum set")
    raise CompileError(f"cannot coerce {sk} to {dk}")


def unify(a: SymV, b: SymV, fr: Frame) -> Tuple[SymV, SymV]:
    if a.spec == b.spec:
        return a, b
    m = vs_merge(a.spec, b.spec)
    from .vspec import apply_bounds
    m = apply_bounds(m, fr.kc.bounds)
    return coerce(a, m, fr), coerce(b, m, fr)


def sym_eq(a: SymV, b: SymV, fr: Frame):
    a, b = unify(a, b, fr)
    if a.static and b.static:
        return bool(np.array_equal(a.lanes, b.lanes))
    if len(a.lanes) == 0:
        return True
    return jnp.all(jnp.asarray(a.lanes) == jnp.asarray(b.lanes))


def _rows_lex_lt(rows, x):
    """Vectorized lexicographic rows[i] < x over a [n, w] matrix: decided
    at each row's first differing lane. w == 0 rows compare equal."""
    if rows.shape[1] == 0:
        return jnp.zeros(rows.shape[0], bool)
    neq = rows != x[None, :]
    first = jnp.argmax(neq, axis=1)
    srow = jnp.take_along_axis(rows, first[:, None], axis=1)[:, 0]
    return jnp.where(jnp.any(neq, axis=1), srow < x[first], False)


# ---------------------------------------------------------------------------
# symbolic evaluation
# ---------------------------------------------------------------------------

def as_bool(v, fr: Frame):
    if isinstance(v, bool):
        return v
    if isinstance(v, SymV):
        if v.spec.kind != "bool":
            raise CompileError(f"expected boolean, got {v.spec.kind}")
        x = v.lanes[0]
        if v.static:
            return bool(x)
        return x != 0
    if _is_traced(v):
        return v if v.dtype == jnp.bool_ else v != 0
    raise CompileError(f"expected boolean, got {v!r}")


def as_int_lane(v):
    if isinstance(v, SymV):
        if v.spec.kind != "int":
            raise CompileError(f"expected integer, got {v.spec.kind}")
        x = v.lanes[0]
        return int(x) if v.static else x
    if isinstance(v, bool):
        raise CompileError("boolean used as integer")
    if isinstance(v, int) or _is_traced(v):
        return v
    if isinstance(v, np.integer):
        return int(v)
    raise CompileError(f"expected integer, got {v!r}")


def mk_bool(x) -> SymV:
    return SymV(BOOL, [x])


def mk_int(x) -> SymV:
    return SymV(INT, [x])


def _lift(v, fr: Frame) -> SymV:
    """Lift a static python value to SymV."""
    if isinstance(v, SymV):
        return v
    if isinstance(v, bool):
        return SymV(BOOL, [v])
    if isinstance(v, int):
        return SymV(INT, [v])
    return static_to_symv(v, fr.kc)


def _seq_elem(v: SymV, i: int):
    ew = v.spec.elem.width
    return v.lanes[1 + i * ew: 1 + (i + 1) * ew]


def _slots_matrix(lanes, off, cap, w):
    """View lanes[off : off+cap*w] as a [cap, w] matrix (one reshape)."""
    seg = lanes[off:off + cap * w]
    if isinstance(seg, np.ndarray):
        return seg.reshape(cap, w)
    return jnp.reshape(seg, (cap, w))


def _select_lanes(cond, a, b):
    """Lane-block select: one XLA where over the whole segment."""
    if isinstance(cond, bool):
        return a if cond else b
    a = a if not isinstance(a, (list, tuple)) else \
        _cat([_as_lane_arr(x) for x in a])
    b = b if not isinstance(b, (list, tuple)) else \
        _cat([_as_lane_arr(x) for x in b])
    return jnp.where(cond, a, b)


def sym_apply(f, args: List, fr: Frame) -> Any:
    """Function application f[k]."""
    if not isinstance(f, SymV):
        # static python Fcn with possibly-symbolic argument
        if isinstance(f, Fcn):
            f = _lift(f, fr)
        else:
            raise CompileError(f"cannot apply {f!r}")
    key = args[0] if len(args) == 1 else None
    if key is None:
        # f[a, b] == f[<<a, b>>]
        raise CompileError("multi-argument application not supported yet")
    sp = f.spec
    if sp.kind == "fcn":
        if isinstance(key, SymV) and key.static or not isinstance(key, SymV):
            kk = _static_key_value(key, fr)
            off = 0
            for dk, es in zip(sp.dom, sp.elems):
                if _keys_equal(dk, kk):
                    return SymV(es, f.lanes[off:off + es.width])
                off += es.width
            raise CompileError(f"application outside static domain: {kk!r}")
        # symbolic key: select across domain entries
        ks = key
        acc = None
        off = 0
        for dk, es in zip(sp.dom, sp.elems):
            dk_s = static_to_symv(dk, fr.kc)
            cond = sym_eq(ks, dk_s, fr)
            cur = f.lanes[off:off + es.width]
            acc = cur if acc is None else _select_lanes(cond, cur, acc)
            off += es.width
        espec = sp.elems[0]
        for e in sp.elems[1:]:
            if e != espec:
                raise CompileError("symbolic application over heterogeneous "
                                   "function")
        return SymV(espec, acc)
    if sp.kind == "pfcn":
        kk = None
        if not isinstance(key, SymV) or key.static:
            kk = _static_key_value(key, fr)
        off = 0
        for dk, es in zip(sp.dom, sp.elems):
            if kk is not None and _keys_equal(dk, kk):
                # TLC errors on applying outside DOMAIN; compiled path
                # returns the (zeroed-when-absent) value — guards in the
                # spec keep this sound, as with TLC's lazy evaluation
                return SymV(es, f.lanes[off + 1:off + 1 + es.width])
            off += 1 + es.width
        if kk is not None:
            raise CompileError(f"pfcn key outside universe: {kk!r}")
        acc = None
        off = 0
        espec = sp.elems[0]
        for dk, es in zip(sp.dom, sp.elems):
            cond = sym_eq(key, static_to_symv(dk, fr.kc), fr)
            cur = f.lanes[off + 1:off + 1 + es.width]
            acc = cur if acc is None else _select_lanes(cond, cur, acc)
            off += 1 + es.width
        return SymV(espec, acc)
    if sp.kind == "seq":
        idx = as_int_lane(key)
        if isinstance(idx, int):
            if not 1 <= idx <= sp.cap:
                raise CompileError(f"static sequence index {idx} out of "
                                   f"capacity {sp.cap}")
            return SymV(sp.elem, _seq_elem(f, idx - 1))
        elems = jnp.asarray(_slots_matrix(f.lanes, 1, sp.cap,
                                          sp.elem.width))
        safe = jnp.clip(idx - 1, 0, sp.cap - 1)
        return SymV(sp.elem, elems[safe])
    if sp.kind == "kvtable":
        # msgs[m]: one vectorized key match + select
        kw, vw = sp.elem.width, sp.val.width
        kv = coerce(key if isinstance(key, SymV) else _lift(key, fr),
                    sp.elem, fr)
        rows = jnp.asarray(_slots_matrix(f.lanes, 1, sp.cap, kw + vw))
        match = jnp.all(rows[:, :kw] ==
                        jnp.asarray(_as_seg(kv.lanes, kw))[None, :], axis=1)
        sel = jnp.where(match[:, None], rows[:, kw:], 0)
        return SymV(sp.val, jnp.sum(sel, axis=0).astype(jnp.int32))
    if sp.kind == "union":
        raise CompileError("cannot apply a record value")
    if sp.kind == "justempty":
        raise CompileError("application of an always-empty function")
    raise CompileError(f"cannot apply value of kind {sp.kind}")


def _static_key_value(key, fr: Frame):
    if isinstance(key, SymV):
        if key.spec.kind == "int":
            return int(key.lanes[0])
        if key.spec.kind == "enum":
            return fr.kc.uni.value(int(key.lanes[0]))
        if key.spec.kind == "bool":
            return bool(key.lanes[0])
        raise CompileError(f"unsupported static key kind {key.spec.kind}")
    if isinstance(key, np.integer):
        return int(key)
    return key


def _keys_equal(a, b) -> bool:
    if isinstance(a, ModelValue) or isinstance(b, ModelValue):
        return a is b
    if isinstance(a, np.integer):
        a = int(a)
    if isinstance(b, np.integer):
        b = int(b)
    if type(a) is not type(b) and not (isinstance(a, int)
                                       and isinstance(b, int)):
        return False
    return a == b


def sym_dot(v, fld: str, fr: Frame) -> SymV:
    if not isinstance(v, SymV):
        v = _lift(v, fr)
    sp = v.spec
    if sp.kind == "fcn":
        return sym_apply(v, [fld], fr)
    if sp.kind == "union":
        acc = None
        espec = None
        for tag, (names, fields) in enumerate(sp.variants):
            if fld not in names:
                continue
            off = 1
            for nm, fs in zip(names, fields):
                if nm == fld:
                    cur = v.lanes[off:off + fs.width]
                    espec = fs if espec is None else espec
                    if fs != espec:
                        cur = _coerce_lanes(fs, espec, cur, fr)
                    cond = _eq_lane(v.lanes[0], tag)
                    acc = cur if acc is None else _select_lanes(cond, cur,
                                                                acc)
                    break
                off += fs.width
        if acc is None:
            raise CompileError(f"no union variant has field {fld}")
        return SymV(espec, acc)
    raise CompileError(f"field access .{fld} on {sp.kind}")


# ---- sets ----

def _set_of(v, fr: Frame):
    """Normalize to ('static', frozenset) | ('sym', SymV with set/iset/
    growset spec)."""
    if isinstance(v, frozenset):
        return ("static", v)
    if isinstance(v, SymV) and v.spec.kind in ("set", "iset", "growset",
                                               "emptyset"):
        return ("sym", v)
    if isinstance(v, InfiniteSet):
        return ("inf", v)
    raise CompileError(f"expected a set, got {v!r}")


def sym_in(x, s, fr: Frame):
    kind, sv = _set_of(s, fr)
    if kind == "inf":
        if not isinstance(x, SymV):
            # static value against an infinite set: the interpreter rule
            return in_set(x, sv)
        # membership in Nat/Int/Seq(S): type-level for compiled values
        if isinstance(x, SymV):
            if sv.kind == "Nat":
                return jnp.greater_equal(as_int_lane(x), 0) \
                    if _is_traced(as_int_lane(x)) else as_int_lane(x) >= 0
            if sv.kind == "Int":
                return True
            if sv.kind == "Seq":
                # q \in Seq(S): every used element in S (TypeInvariant,
                # InnerFIFO.tla) — vacuous beyond the length
                if x.spec.kind == "justempty":
                    return True
                if x.spec.kind == "seq":
                    acc = True
                    n = x.lanes[0]
                    for i in range(x.spec.cap):
                        el = SymV(x.spec.elem, _seq_elem(x, i))
                        inn = _generic_in(el, sv.param, fr)
                        unused = _ge_lane(i, n)
                        acc = _land(acc, _lor(unused, inn))
                    return acc
                if x.spec.kind == "fcn" and all(
                        isinstance(k, int) for k in x.spec.dom) and \
                        tuple(x.spec.dom) == tuple(
                            range(1, len(x.spec.dom) + 1)):
                    # heterogeneous tuple encoded as int-keyed record
                    acc = True
                    off = 0
                    for kk, es in zip(x.spec.dom, x.spec.elems):
                        el = SymV(es, x.lanes[off:off + es.width])
                        acc = _land(acc, _generic_in(el, sv.param, fr))
                        off += es.width
                    return acc
                return False
        raise CompileError(f"membership in {sv!r} not compilable")
    if kind == "static":
        if not isinstance(x, SymV) or x.static:
            xv = x if not isinstance(x, SymV) else _decode_static(x, fr)
            return in_set(xv, sv)
        acc = False
        for m in sorted(sv, key=sort_key):
            acc = _lor(acc, sym_eq(x, static_to_symv(m, fr.kc), fr))
        return acc
    sp = sv.spec
    if sp.kind in ("set", "iset"):
        acc = False
        for i, m in enumerate(sp.dom):
            memb = sv.lanes[i]
            acc = _lor(acc, _land(
                memb if isinstance(memb, bool) else _eq_lane(memb, 1),
                as_bool(sym_eq(_lift(x, fr), static_to_symv(m, fr.kc), fr),
                        fr)))
        return acc
    if sp.kind == "growset":
        xe = coerce(_lift(x, fr), sp.elem, fr)
        ew = sp.elem.width
        slots = _slots_matrix(sv.lanes, 1, sp.cap, ew)
        used = jnp.arange(sp.cap) < sv.lanes[0]
        hits = jnp.all(jnp.asarray(slots) == jnp.asarray(xe.lanes)[None, :],
                       axis=1) & used
        return jnp.any(hits)
    raise CompileError(f"membership in {sp.kind} not supported")


def _lt_lane(a, b):
    if not _is_traced(a) and not _is_traced(b):
        return a < b
    return jnp.less(a, b)


def _decode_static(v: SymV, fr: Frame):
    from .vspec import decode
    val, _ = decode([int(x) for x in v.lanes], 0, v.spec, fr.kc.uni)
    return val


def set_elements(s, fr: Frame):
    """Iterate a set as (guard, element) pairs — guards may be traced."""
    kind, sv = _set_of(s, fr)
    if kind == "static":
        for m in sorted(sv, key=sort_key):
            yield True, m
        return
    if kind == "inf":
        raise CompileError(cannot_enumerate_message(sv))
    sp = sv.spec
    if sp.kind in ("set", "iset"):
        for i, m in enumerate(sp.dom):
            memb = sv.lanes[i]
            yield (memb if isinstance(memb, bool)
                   else _eq_lane(memb, 1)), m
        return
    if sp.kind == "growset":
        ew = sp.elem.width
        for slot in range(sp.cap):
            base = 1 + slot * ew
            used = _lt_lane(slot, sv.lanes[0])
            yield used, SymV(sp.elem, sv.lanes[base:base + ew])
        return
    raise CompileError(f"cannot enumerate {sp.kind}")


def grow_insert(s: SymV, x: SymV, fr: Frame) -> SymV:
    """s \\cup {x} on a growset — sorted insertion, canonical, vectorized
    over the slot matrix."""
    sp = s.spec
    xe = coerce(x, sp.elem, fr)
    ew = sp.elem.width
    cnt = s.lanes[0]
    if ew == 0:
        # zero-width elements (a growset of always-empty values) are all
        # indistinguishable: the set is {} or a singleton
        newcnt = jnp.maximum(jnp.asarray(cnt), 1)
        return SymV(sp, jnp.reshape(newcnt, (1,)).astype(jnp.int32))
    slots = jnp.asarray(_slots_matrix(s.lanes, 1, sp.cap, ew))
    xl = jnp.asarray(xe.lanes)
    used = jnp.arange(sp.cap) < cnt
    present = jnp.any(jnp.all(slots == xl[None, :], axis=1) & used)
    lt = _rows_lex_lt(slots, xl)
    pos = jnp.sum(used & lt)
    fr.flag_overflow(jnp.logical_and(jnp.logical_not(present),
                                     _ge_lane(cnt, sp.cap)))
    idx = jnp.arange(sp.cap)
    prev = jnp.concatenate([jnp.zeros((1, ew), jnp.int32), slots[:-1]])
    ins = jnp.where((idx < pos)[:, None], slots,
                    jnp.where((idx == pos)[:, None], xl[None, :], prev))
    out_slots = jnp.where(present, slots, ins)
    newcnt = jnp.where(present, cnt, cnt + 1)
    lanes = jnp.concatenate([jnp.reshape(newcnt, (1,)).astype(jnp.int32),
                             out_slots.reshape(-1)])
    return SymV(sp, lanes)


def _ge_lane(a, b):
    if not _is_traced(a) and not _is_traced(b):
        return a >= b
    return jnp.greater_equal(a, b)


def set_union(a, b, fr: Frame):
    """a \\cup b with symbolic support."""
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        return a | b
    # growset target: insert the other side's (guarded) elements
    if isinstance(a, SymV) and a.spec.kind == "growset":
        out = a
        for g, e in _elements(b, fr):
            ev = _lift(e, fr) if not isinstance(e, SymV) else e
            ins = grow_insert(out, ev, fr)
            gb = g if isinstance(g, bool) else g
            out = ins if gb is True else SymV(
                out.spec, _select_lanes(gb, ins.lanes, out.lanes))
        return out
    if isinstance(b, SymV) and b.spec.kind == "growset":
        return set_union(b, a, fr)
    if isinstance(a, Elems) or isinstance(b, Elems):
        # fold symbolic elements into a mask set when the other side is
        # one (votesGranted[i] \cup {j} with slot-bound j, raft.tla:372)
        other = b if isinstance(a, Elems) else a
        el = a if isinstance(a, Elems) else b
        try:
            mask = _to_mask_set(other, fr)
        except UnrollLimitError:
            raise
        except CompileError:
            items = list(_elements(a, fr)) + list(_elements(b, fr))
            return Elems(items)
        lanes = list(mask.lanes)
        for g, e in el.items:
            ev = _lift(e, fr) if not isinstance(e, (SymV, frozenset, Fcn)) \
                else e
            for i, m in enumerate(mask.spec.dom):
                hit = _land(g, as_bool(mk_bool(_generic_eq(
                    ev, _lift(m, fr) if not isinstance(m, (frozenset, Fcn))
                    else m, fr)), fr))
                cur = lanes[i]
                cb = cur if isinstance(cur, bool) else _eq_lane(cur, 1)
                r = _lor(cb, hit)
                lanes[i] = _ite(r, 1, 0) if not isinstance(r, bool) \
                    else (1 if r else 0)
        return SymV(mask.spec, lanes)
    # enum/int mask sets
    sa = _to_mask_set(a, fr)
    sb = _to_mask_set(b, fr)
    sa, sb = unify(sa, sb, fr)
    lanes = [_lor(_eq_lane(x, 1) if not isinstance(x, bool) else x,
                  _eq_lane(y, 1) if not isinstance(y, bool) else y)
             for x, y in zip(sa.lanes, sb.lanes)]
    return SymV(sa.spec, [_ite(l, 1, 0) if not isinstance(l, bool)
                          else (1 if l else 0) for l in lanes])


def _to_mask_set(v, fr: Frame) -> SymV:
    kind, sv = _set_of(v, fr)
    if kind == "sym":
        if sv.spec.kind in ("set", "iset"):
            return sv
        raise CompileError("growset in mask-set position")
    members = sorted(sv, key=sort_key)
    if all(isinstance(m, (str, ModelValue)) for m in members):
        return static_to_symv(sv, fr.kc, VS("set", dom=tuple(members)))
    if all(isinstance(m, int) and not isinstance(m, bool) for m in members):
        return SymV(VS("iset", dom=tuple(members)), [1] * len(members))
    raise CompileError("heterogeneous static set")


def interval_iset(lo, hi, fr: Frame) -> SymV:
    """a..b with traced bounds -> iset over 1..iset_cap universe."""
    lo_l = as_int_lane(lo)
    hi_l = as_int_lane(hi)
    cap = fr.kc.iset_cap
    uni_members = tuple(range(0, cap + 2))
    ms = jnp.arange(0, cap + 2)
    lanes = ((ms >= lo_l) & (ms <= hi_l)).astype(jnp.int32)
    # overflow if the interval reaches beyond the universe
    fr.flag_overflow(_land(_ge_lane(hi_l, cap + 2),
                           _ge_lane(hi_l, lo_l)))
    return SymV(VS("iset", dom=uni_members), lanes)


# ---- sequences ----

def seq_len(v: SymV) -> SymV:
    if v.spec.kind == "seq":
        return mk_int(v.lanes[0])
    if v.spec.kind == "justempty":
        return mk_int(0)
    raise CompileError(f"Len of {v.spec.kind}")


def seq_append(v: SymV, x, fr: Frame) -> SymV:
    if v.spec.kind == "justempty":
        # promote to a sequence of the appended element's shape; if the
        # layout truly has no room the target coercion raises cleanly
        xe = _lift(x, fr)
        from .vspec import apply_bounds
        sp = apply_bounds(VS("seq", cap=1, elem=xe.spec), fr.kc.bounds)
        v = SymV(sp, _zeros(sp.width))
    sp = v.spec
    xe = coerce(_lift(x, fr), sp.elem, fr)
    if v.static and xe.static:
        # static fast path: fold on python values so constants stay static
        from ..sem.values import mk_seq as _mk_seq
        sv = _decode_static(v, fr)
        xv = _decode_static(xe, fr)
        return static_to_symv(_mk_seq(sv.as_list() + [xv]), fr.kc)
    ew = sp.elem.width
    n = v.lanes[0]
    fr.flag_overflow(_ge_lane(n, sp.cap))
    elems = jnp.asarray(_slots_matrix(v.lanes, 1, sp.cap, ew))
    at = (jnp.arange(sp.cap) == n)[:, None]
    out = jnp.where(at, jnp.asarray(xe.lanes)[None, :], elems)
    lanes = jnp.concatenate([
        jnp.reshape(n + 1, (1,)).astype(jnp.int32), out.reshape(-1)])
    return SymV(sp, lanes)


def seq_subseq(v: SymV, m, n, fr: Frame) -> SymV:
    """SubSeq(v, m, n) with traced bounds; empty when m > n. One gather."""
    if v.spec.kind == "justempty":
        ml, nl = as_int_lane(m), as_int_lane(n)
        fr.flag_overflow(_ge_lane(nl, ml))
        return v
    sp = v.spec
    ml = as_int_lane(m)
    nl = as_int_lane(n)
    ew = sp.elem.width
    outlen = jnp.maximum(nl - ml + 1, 0)
    elems = jnp.asarray(_slots_matrix(v.lanes, 1, sp.cap, ew))
    src = ml - 1 + jnp.arange(sp.cap)          # 0-based source indices
    gathered = jnp.take(elems, jnp.clip(src, 0, sp.cap - 1), axis=0)
    keep = (jnp.arange(sp.cap) < outlen)[:, None]
    out = jnp.where(keep, gathered, 0)
    lanes = jnp.concatenate([
        jnp.reshape(outlen, (1,)).astype(jnp.int32), out.reshape(-1)])
    return SymV(sp, lanes)


def seq_concat(a: SymV, b: SymV, fr: Frame) -> SymV:
    if a.spec.kind == "justempty":
        return b
    if b.spec.kind == "justempty":
        return a
    sp = vs_merge(a.spec, b.spec)
    from .vspec import apply_bounds
    sp = apply_bounds(sp, fr.kc.bounds)
    a = coerce(a, sp, fr)
    b = coerce(b, sp, fr)
    ew = sp.elem.width
    na, nb = a.lanes[0], b.lanes[0]
    total = na + nb
    fr.flag_overflow(_ge_lane(total, sp.cap + 1))
    ea = jnp.asarray(_slots_matrix(a.lanes, 1, sp.cap, ew))
    eb = jnp.asarray(_slots_matrix(b.lanes, 1, sp.cap, ew))
    idx = jnp.arange(sp.cap)
    bsrc = jnp.clip(idx - na, 0, sp.cap - 1)
    from_b = jnp.take(eb, bsrc, axis=0)
    out = jnp.where((idx < na)[:, None], ea, from_b)
    out = jnp.where((idx < total)[:, None], out, 0)
    lanes = jnp.concatenate([
        jnp.reshape(total, (1,)).astype(jnp.int32), out.reshape(-1)])
    return SymV(sp, lanes)


# ---- EXCEPT ----

def _splice(lanes, off, width, new_seg):
    """lanes with [off:off+width] replaced by new_seg (3 segments, O(1) ops)."""
    return _cat([lanes[:off], _as_seg(new_seg, width), lanes[off + width:]])


def _as_seg(x, width):
    if isinstance(x, (list, tuple)):
        return _cat([_as_lane_arr(i) for i in x])
    if _is_traced(x) and x.ndim == 0:
        return jnp.reshape(x, (1,))
    if isinstance(x, np.ndarray) and x.ndim == 0:
        return x.reshape(1)
    if isinstance(x, (int, bool)):
        return _as_lane_arr(x)
    return x


def sym_except(f: SymV, path, rhs_eval, fr: Frame) -> SymV:
    """[f EXCEPT !path = rhs]; rhs_eval(old: SymV) -> value."""
    sp = f.spec
    kind, arg = path[0]
    if sp.kind == "fcn":
        key = arg if kind == "dot" else None
        keysym = None
        if key is None:
            if isinstance(arg, list):
                if len(arg) != 1:
                    raise CompileError("multi-key EXCEPT not supported")
                kv = arg[0]
            else:
                kv = arg
            if not isinstance(kv, SymV) or kv.static:
                key = _static_key_value(kv, fr)
            else:
                keysym = kv
        if key is not None:
            off = 0
            for dk, es in zip(sp.dom, sp.elems):
                if _keys_equal(dk, key):
                    old = SymV(es, f.lanes[off:off + es.width])
                    new = _apply_rest(old, path[1:], rhs_eval, fr)
                    new = coerce(_lift(new, fr), es, fr)
                    return SymV(sp, _splice(f.lanes, off, es.width,
                                            new.lanes))
                off += es.width
            raise CompileError(f"EXCEPT key {key!r} outside domain")
        # symbolic key over (usually homogeneous) fcn: guarded per-key
        # segments, concatenated once
        segs = []
        off = 0
        for dk, es in zip(sp.dom, sp.elems):
            cond = as_bool(mk_bool(sym_eq(
                keysym, static_to_symv(dk, fr.kc), fr)), fr)
            old = SymV(es, f.lanes[off:off + es.width])
            new = coerce(_lift(_apply_rest(old, path[1:], rhs_eval, fr),
                               fr), es, fr)
            segs.append(_as_seg(_select_lanes(
                cond, new.lanes, f.lanes[off:off + es.width]), es.width))
            off += es.width
        return SymV(sp, _cat(segs))
    if sp.kind == "seq":
        kv = arg[0] if kind == "idx" else arg
        idx = as_int_lane(kv)
        ew = sp.elem.width
        # old element: one gather; new: one masked scatter over the matrix
        elems = jnp.asarray(_slots_matrix(f.lanes, 1, sp.cap, ew))
        safe = jnp.clip(idx - 1, 0, sp.cap - 1)
        old = SymV(sp.elem, elems[safe])
        new = coerce(_lift(_apply_rest(old, path[1:], rhs_eval, fr), fr),
                     sp.elem, fr)
        at = (jnp.arange(sp.cap) == (idx - 1))[:, None]
        out = jnp.where(at, jnp.asarray(_as_seg(new.lanes, ew))[None, :],
                        elems)
        lanes = jnp.concatenate([jnp.reshape(f.lanes[0], (1,)).astype(
            jnp.int32), out.reshape(-1)])
        return SymV(sp, lanes)
    if sp.kind == "kvtable":
        kv = arg[0] if kind == "idx" else arg
        kl = coerce(_lift(kv, fr), sp.elem, fr)
        kw, vw = sp.elem.width, sp.val.width
        rows = jnp.asarray(_slots_matrix(f.lanes, 1, sp.cap, kw + vw))
        match = jnp.all(rows[:, :kw] == jnp.asarray(kl.lanes)[None, :],
                        axis=1)
        # old value: the matching row's value lanes (or zeros)
        mpos = jnp.argmax(match)
        old = SymV(sp.val, rows[mpos, kw:])
        new = coerce(_lift(_apply_rest(old, path[1:], rhs_eval, fr), fr),
                     sp.val, fr)
        newvals = jnp.where(match[:, None],
                            jnp.asarray(_as_seg(new.lanes, vw))[None, :],
                            rows[:, kw:])
        out = jnp.concatenate([rows[:, :kw], newvals], axis=1)
        lanes = jnp.concatenate([jnp.reshape(f.lanes[0], (1,)).astype(
            jnp.int32), out.reshape(-1)])
        return SymV(sp, lanes)
    if sp.kind == "pfcn":
        kv = arg[0] if kind == "idx" else arg
        if isinstance(kv, SymV) and not kv.static and kind == "idx":
            # traced key: guarded per-key segments, concatenated once
            segs = []
            off = 0
            for dk, es in zip(sp.dom, sp.elems):
                cond = as_bool(mk_bool(sym_eq(
                    kv, static_to_symv(dk, fr.kc), fr)), fr)
                old = SymV(es, f.lanes[off + 1:off + 1 + es.width])
                new = coerce(_lift(_apply_rest(old, path[1:], rhs_eval,
                                               fr), fr), es, fr)
                pres = _ite(cond, 1, f.lanes[off])
                sel = _select_lanes(cond, new.lanes,
                                    f.lanes[off + 1:off + 1 + es.width])
                segs.append(_as_lane_arr(pres))
                segs.append(_as_seg(sel, es.width))
                off += 1 + es.width
            return SymV(sp, _cat(segs))
        key = _static_key_value(kv, fr) if kind == "idx" else arg
        off = 0
        for dk, es in zip(sp.dom, sp.elems):
            if _keys_equal(dk, key):
                old = SymV(es, f.lanes[off + 1:off + 1 + es.width])
                new = coerce(_lift(_apply_rest(old, path[1:], rhs_eval,
                                               fr), fr), es, fr)
                return SymV(sp, _splice(
                    f.lanes, off, 1 + es.width,
                    _cat([np.asarray([1], np.int32),
                          _as_seg(new.lanes, es.width)])))
            off += 1 + es.width
        raise CompileError(f"EXCEPT key {key!r} outside pfcn universe")
    raise CompileError(f"EXCEPT on {sp.kind}")


def _apply_rest(old: SymV, rest, rhs_eval, fr: Frame):
    if not rest:
        return rhs_eval(old)
    return sym_except(old, rest, rhs_eval, fr)


def kv_merge_insert(f: SymV, key: SymV, val: SymV, fr: Frame) -> SymV:
    """f @@ (key :> val): insert if key absent (f wins on overlap),
    keeping the table sorted by key lanes — vectorized."""
    sp = f.spec
    kl = coerce(key, sp.elem, fr)
    vl = coerce(val, sp.val, fr)
    kw, vw = sp.elem.width, sp.val.width
    cnt = f.lanes[0]
    rows = jnp.asarray(_slots_matrix(f.lanes, 1, sp.cap, kw + vw))
    keys = rows[:, :kw]
    xl = jnp.asarray(_as_seg(kl.lanes, kw))
    used = jnp.arange(sp.cap) < cnt
    if kw == 0:
        present = cnt > 0 if isinstance(cnt, int) else jnp.asarray(cnt) > 0
    else:
        present = jnp.any(jnp.all(keys == xl[None, :], axis=1) & used)
    lt = _rows_lex_lt(keys, xl)
    pos = jnp.sum(used & lt)
    fr.flag_overflow(jnp.logical_and(jnp.logical_not(present),
                                     _ge_lane(cnt, sp.cap)))
    newrow = jnp.concatenate([xl, jnp.asarray(_as_seg(vl.lanes, vw))])
    idx = jnp.arange(sp.cap)
    prev = jnp.concatenate([jnp.zeros((1, kw + vw), jnp.int32), rows[:-1]])
    ins = jnp.where((idx < pos)[:, None], rows,
                    jnp.where((idx == pos)[:, None], newrow[None, :], prev))
    out = jnp.where(present, rows, ins)
    newcnt = jnp.where(present, cnt, cnt + 1)
    lanes = jnp.concatenate([jnp.reshape(newcnt, (1,)).astype(jnp.int32),
                             out.reshape(-1)])
    return SymV(sp, lanes)


def kv_domain_slots(f: SymV):
    """(used_guard, key SymV, val SymV) per slot of a kvtable."""
    sp = f.spec
    kw, vw = sp.elem.width, sp.val.width
    cnt = f.lanes[0]
    for s in range(sp.cap):
        base = 1 + s * (kw + vw)
        used = _lt_lane(s, cnt)
        yield used, SymV(sp.elem, f.lanes[base:base + kw]), \
            SymV(sp.val, f.lanes[base + kw:base + kw + vw])


# ---------------------------------------------------------------------------
# the expression evaluator
# ---------------------------------------------------------------------------

_ARITH = {"+", "-", "*", "\\div", "%", "^"}
_CMP = {"<", ">", "<=", ">=", "=<", "\\leq", "\\geq"}

# action-kernel overflow codes (the `ov` output of CompiledAction2.fn):
# 0 = none; OV_CAPACITY = a value outgrew its lanes (fix: raise caps);
# OV_PACK = a value escaped its packed lane's profiled bit range (fix:
# deepen sampling or JAXMC_PACK=0 — raised by the ENGINES' pack step,
# compile/pack.py, never by a kernel);
# OV_DEMOTED = an `except CompileError` recovery fired (fix: the hybrid
# engine demotes the arm to the interpreter and restarts)
OV_CAPACITY = 1
OV_DEMOTED = 2
OV_PACK = 3


class Elems:
    """A set given extensionally as guarded symbolic elements — the result
    of {e : x \\in S} (SetMap) before it lands in a union/membership."""
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items  # list of (guard, SymV | static)


_IDENT_NAMES_CACHE: Dict[int, Tuple[Any, frozenset]] = {}


def _ident_names(e) -> frozenset:
    """Every name under e that a symbolic evaluation may look up in
    fr.bound: Ident names, OpApp operator names (LET-bound operators
    resolve through bound), and "@" for EXCEPT's A.At. A cheap
    over-approximation of the free variables, memoized by node identity
    — the node object is pinned in the cache value so ids cannot be
    recycled. The cache is size-capped: a long-lived process sweeping
    many models must not pin every AST it ever compiled."""
    hit = _IDENT_NAMES_CACHE.get(id(e))
    if hit is not None and hit[0] is e:
        return hit[1]
    out = set()

    def walk(x):
        if isinstance(x, A.Ident):
            out.add(x.name)
        elif isinstance(x, A.OpApp):
            out.add(x.name)
        elif isinstance(x, A.At):
            out.add("@")
        if isinstance(x, A.Node):
            for fname in getattr(x, "__dataclass_fields__", {}):
                walk(getattr(x, fname))
        elif isinstance(x, (tuple, list)):
            for y in x:
                walk(y)

    walk(e)
    ns = frozenset(out)
    if len(_IDENT_NAMES_CACHE) > 400_000:
        _IDENT_NAMES_CACHE.clear()
    _IDENT_NAMES_CACHE[id(e)] = (e, ns)
    return ns


_MEMO_TYPES = (A.OpApp, A.Quant, A.Let, A.If, A.Choose, A.Dot,
               A.FnApp, A.SetFilter, A.SetMap)
_CASE_CHAIN_CACHE: Dict[int, Tuple[Any, Any]] = {}
_MISS = object()


def sym_eval2(e: A.Node, fr: Frame):
    memo = fr.memo
    # memoize only under a statically-True guard: in strict frames a
    # statically-False guard SUPPRESSES the CompileError that
    # flag_overflow/flag_demoted would raise, so a recovery value cached
    # in a guarded-out context must never replay into a live one
    if memo is not None and fr.guard is True \
            and isinstance(e, _MEMO_TYPES):
        # the key covers (expr id, bound-value ids) but NOT fr.state or
        # fr.primes — sound only because memos are created fresh per
        # compile_predicate2 trace, where state is a single fixed tuple
        # and primes stays empty. Fail loudly if a future caller ever
        # hands a memo to action frames whose primes mutate mid-trace
        assert not fr.primes, \
            "sym_eval2 memo used in a frame with primes (stale replay)"
        names = _ident_names(e)
        bound = fr.bound
        rel = tuple(sorted((n, id(bound[n]))
                           for n in names if n in bound))
        key = (id(e), rel)
        hit = memo.get(key, _MISS)
        if hit is not _MISS:
            return hit[1]
        r = _sym_eval2_inner(e, fr)
        # the entry PINS the bound values: their ids appear in the key,
        # so they must stay alive as long as the entry does (CPython id
        # recycling would otherwise alias a later binding to this one)
        memo[key] = (tuple(bound[n] for n in names if n in bound), r)
        return r
    return _sym_eval2_inner(e, fr)


def _sym_eval2_inner(e: A.Node, fr: Frame):
    t = type(e)
    kc = fr.kc
    if t is A.Num:
        return mk_int(e.val)
    if t is A.Bool:
        return SymV(BOOL, [e.val])
    if t is A.Str:
        if e.val in kc.uni.to_idx:
            return SymV(ENUM, [kc.uni.index(e.val)])
        return e.val
    if t is A.Ident:
        name = e.name
        if name in fr.bound:
            v = fr.bound[name]
            if isinstance(v, tuple) and v:
                if v[0] == "$letexpr":
                    return sym_eval2(v[1], fr)
                if v[0] == "$slot":
                    raise CompileError("unresolved dynamic-set binding")
                if v[0] == "$op":
                    raise CompileError(f"operator {name} used as value")
            return v
        if name in fr.state:
            return fr.state[name]
        if kc.const_lanes and name in kc.const_lanes:
            # lifted CONSTANT (ISSUE 13): a traced per-model lane, not
            # the baked concrete value
            return mk_int(kc.const_lanes[name])
        d = kc.model.defs.get(name)
        if isinstance(d, OpClosure):
            if d.params:
                raise CompileError(f"operator {name} used as a value")
            if isinstance(d.body, A.FnConstrDef):
                raise CompileError("recursive functions not compilable")
            return sym_eval2(d.body, fr)
        if d is None:
            raise CompileError(f"unknown identifier {name}")
        return _static_const(d, fr)
    if t is A.Prime:
        if not isinstance(e.expr, A.Ident):
            raise CompileError("primed non-variable")
        nm = e.expr.name
        if nm not in fr.primes:
            raise CompileError(f"{nm}' read before assignment")
        return fr.primes[nm]
    if t is A.OpApp:
        return _sym_opapp2(e, fr)
    if t is A.FnApp:
        f = sym_eval2(e.fn, fr)
        args = [sym_eval2(a, fr) for a in e.args]
        return sym_apply(f, args, fr)
    if t is A.Dot:
        return sym_dot(sym_eval2(e.expr, fr), e.fld, fr)
    if t is A.If:
        c = as_bool(sym_eval2(e.cond, fr), fr)
        if isinstance(c, bool):
            return sym_eval2(e.then if c else e.els, fr)
        # traced condition: if one branch is uncompilable (e.g. applies an
        # always-empty function), keep the other and flag overflow when the
        # failing branch would have been taken — exactness preserved
        try:
            a = sym_eval2(e.then, fr)
        except UnrollLimitError:
            raise
        except CompileError as ex:
            fr.flag_demoted(c, why=str(ex))
            return sym_eval2(e.els, fr)
        try:
            b = sym_eval2(e.els, fr)
        except UnrollLimitError:
            raise
        except CompileError as ex:
            fr.flag_demoted(_lnot(c), why=str(ex))
            return a
        return _merge_values(c, a, b, fr)
    if t is A.Case:
        # cache the If-chain rewrite per Case node: fresh allocations on
        # every evaluation would defeat the memo (new ids each time) and
        # churn _IDENT_NAMES_CACHE with one-shot pinned entries
        hit = _CASE_CHAIN_CACHE.get(id(e))
        if hit is not None and hit[0] is e:
            node = hit[1]
        else:
            node = None
            for g, b in reversed(e.arms):
                if node is None:
                    node = A.If(g, b, e.other) if e.other is not None \
                        else b
                else:
                    node = A.If(g, b, node)
            # capped like _IDENT_NAMES_CACHE: a long-lived process
            # sweeping many models must not pin every Case AST forever
            if len(_CASE_CHAIN_CACHE) > 100_000:
                _CASE_CHAIN_CACHE.clear()
            _CASE_CHAIN_CACHE[id(e)] = (e, node)
        return sym_eval2(node, fr)
    if t is A.TupleExpr:
        items = [sym_eval2(x, fr) for x in e.items]
        return _tuple_symv(items, fr)
    if t is A.SetEnum:
        items = [sym_eval2(x, fr) for x in e.items]
        conc = _try_concrete(items, fr)
        if conc is not None:
            return frozenset(conc)
        return Elems([(True, x) for x in items])
    if t is A.RecordExpr:
        fields = sorted(((k, sym_eval2(v, fr)) for k, v in e.fields),
                        key=lambda kv: kv[0])
        lanes: List = []
        specs = []
        for k, v in fields:
            sv = _lift(v, fr)
            specs.append(sv.spec)
            lanes.extend(sv.lanes)
        return SymV(VS("fcn", dom=tuple(k for k, _ in fields),
                       elems=tuple(specs)), lanes)
    if t is A.Except:
        f = _lift(sym_eval2(e.fn, fr), fr)
        for path, rhs in e.updates:
            epath = []
            for k, arg in path:
                if k == "idx":
                    epath.append(("idx", [sym_eval2(a, fr) for a in arg]))
                else:
                    epath.append(("dot", arg))

            def rhs_eval(old, rhs=rhs):
                return sym_eval2(rhs, fr.with_bound({"@": old}))
            f = sym_except(f, epath, rhs_eval, fr)
        return f
    if t is A.At:
        if "@" not in fr.bound:
            raise CompileError("@ outside EXCEPT")
        return fr.bound["@"]
    if t is A.FnDef:
        return _sym_fndef(e, fr)
    if t is A.SetFilter:
        return _sym_setfilter(e, fr)
    if t is A.SetMap:
        return _sym_setmap(e, fr)
    if t is A.Quant:
        acc = True if e.kind == "A" else False
        for b in _binder_combos(e.binders, fr):
            guard, bound = b
            v = as_bool(sym_eval2(
                e.body, fr.with_bound(bound).with_guard(guard)), fr)
            if e.kind == "A":
                acc = _land(acc, _lor(_lnot(guard), v))
            else:
                acc = _lor(acc, _land(guard, v))
        return mk_bool(acc)
    if t is A.Choose:
        return _sym_choose(e, fr)
    if t is A.Let:
        defs = {}
        frame = fr
        for d in e.defs:
            if isinstance(d, A.OpDef) and not d.params:
                defs[d.name] = sym_eval2(d.body, frame.with_bound(defs))
            elif isinstance(d, A.OpDef):
                defs[d.name] = ("$op", d, dict(defs))
            else:
                raise CompileError("unsupported LET body in compiled expr")
        return sym_eval2(e.body, fr.with_bound(defs))
    if t is A.RecordSet:
        # [a: S, b: T] — static record sets materialize like SUBSET
        from ..sem.values import mk_record
        fields = []
        for k, sexpr in e.fields:
            sval = sym_eval2(sexpr, fr)
            if not isinstance(sval, frozenset):
                raise CompileError("record set over symbolic field set")
            fields.append((k, sorted(sval, key=sort_key)))
        out = []
        for combo in itertools.product(*[vs for _, vs in fields]):
            out.append(mk_record({k: v for (k, _), v
                                  in zip(fields, combo)}))
        return frozenset(out)
    if t is A.FnSet:
        dom = sym_eval2(e.dom, fr)
        rng = sym_eval2(e.rng, fr)
        if isinstance(dom, frozenset) and isinstance(rng, frozenset):
            from ..sem.values import FcnSetV
            return frozenset(FcnSetV(dom, rng).materialize())
        raise CompileError("function set over symbolic operands")
    if t is A.Unchanged:
        raise CompileError("UNCHANGED in expression position")
    raise CompileError(f"cannot compile {t.__name__}")


def _static_const(d, fr: Frame):
    """A cfg-bound constant or plain value from the defs table."""
    if isinstance(d, (int, bool, str, ModelValue, frozenset, Fcn)):
        if isinstance(d, (frozenset, Fcn)) or isinstance(d, InfiniteSet):
            return d
        return _lift(d, fr)
    if isinstance(d, InfiniteSet):
        return d
    raise CompileError(f"cannot compile constant {d!r}")


def _tuple_symv(items, fr: Frame) -> SymV:
    espec = None
    lifted = []
    hetero = False
    for x in items:
        sv = _lift(x, fr)
        lifted.append(sv)
        try:
            espec = sv.spec if espec is None else vs_merge(espec, sv.spec)
        except CompileError:
            hetero = True
    if espec is None and not hetero:
        return SymV(VS("justempty"), [])
    if hetero:
        # heterogeneous tuple: fixed int-keyed record
        return SymV(VS("fcn", dom=tuple(range(1, len(lifted) + 1)),
                       elems=tuple(sv.spec for sv in lifted)),
                    _cat([_as_seg(sv.lanes, sv.spec.width)
                          for sv in lifted]))
    from .vspec import apply_bounds
    espec = apply_bounds(espec, fr.kc.bounds)
    n = len(lifted)
    lanes = [n]
    for sv in lifted:
        lanes.extend(coerce(sv, espec, fr).lanes)
    cap = max(n, 1)
    return SymV(VS("seq", cap=cap, elem=espec), lanes)


def _merge_values(c, a, b, fr: Frame):
    if isinstance(a, Elems) or isinstance(b, Elems):
        raise CompileError("IF over extensional sets")
    if not isinstance(a, SymV) and not isinstance(b, SymV) \
            and isinstance(a, frozenset) and isinstance(b, frozenset):
        a = _to_mask_set(a, fr) if a or b else a
        if isinstance(a, frozenset):
            return a  # both empty
        b = _to_mask_set(b, fr)
    a = _lift(a, fr)
    b = _lift(b, fr)
    a, b = unify(a, b, fr)
    return SymV(a.spec, _select_lanes(c, a.lanes, b.lanes))


def _binder_combos(binders, fr: Frame):
    """Yield (guard, bound-dict) combinations for quantifier binders."""
    groups = []
    for names, sexpr in binders:
        if sexpr is None:
            raise CompileError(UNBOUNDED_QUANTIFIER_MSG)
        sval = sym_eval2(sexpr, fr)
        elems = list(_elements(sval, fr))
        for pat in names:
            groups.append((pat, elems))
    for combo in itertools.product(*[g[1] for g in groups]):
        guard = True
        bound = {}
        for (pat, _), (g, v) in zip(groups, combo):
            guard = _land(guard, g)
            if isinstance(pat, tuple):
                if isinstance(v, SymV):
                    if v.spec.kind != "seq" or len(pat) > v.spec.cap:
                        raise CompileError("cannot destructure value")
                    for i, nm in enumerate(pat):
                        bound[nm] = SymV(v.spec.elem, _seq_elem(v, i))
                else:
                    bound.update(bind_pattern(pat, v))
            else:
                bound[pat] = v
        yield guard, bound


def _elements(sval, fr: Frame):
    if isinstance(sval, Elems):
        for g, v in sval.items:
            yield g, v
        return
    yield from set_elements(sval, fr)


def _sym_fndef(e: A.FnDef, fr: Frame) -> SymV:
    if len(e.binders) != 1 or len(e.binders[0][0]) != 1:
        raise CompileError("multi-binder function constructor")
    pat, sexpr = e.binders[0][0][0], e.binders[0][1]
    sval = sym_eval2(sexpr, fr)
    if isinstance(sval, frozenset) and not sval:
        # [j \in {} |-> ...] — voterLog resets, raft.tla:190
        return SymV(VS("justempty"), [])
    if isinstance(sval, frozenset):
        keys = sorted(sval, key=sort_key)
        vals = []
        specs = []
        for k in keys:
            b = bind_pattern(pat, k) if isinstance(pat, tuple) else {pat: k}
            b = {nm: (_lift(v, fr) if not isinstance(v, (frozenset, Fcn))
                      else v) for nm, v in b.items()}
            v = _lift(sym_eval2(e.body, fr.with_bound(b)), fr)
            vals.append(v)
            specs.append(v.spec)
        if all(isinstance(k, int) for k in keys) \
                and list(keys) == list(range(1, len(keys) + 1)):
            espec = specs[0]
            for s in specs[1:]:
                espec = vs_merge(espec, s)
            from .vspec import apply_bounds
            espec = apply_bounds(espec, fr.kc.bounds)
            lanes = [len(keys)]
            for v in vals:
                lanes.extend(coerce(v, espec, fr).lanes)
            return SymV(VS("seq", cap=len(keys), elem=espec), lanes)
        lanes = []
        for v in vals:
            lanes.extend(v.lanes)
        return SymV(VS("fcn", dom=tuple(keys), elems=tuple(specs)), lanes)
    if isinstance(sval, SymV) and sval.spec.kind == "iset":
        # [j \in 1..newCommitIndex |-> log[i][j]] -> a sequence
        members = sval.spec.dom
        ints = [m for m in members if isinstance(m, int) and m >= 1]
        vals = []
        length = 0
        for m in sorted(ints):
            idx = members.index(m)
            g = sval.lanes[idx]
            gb = g if isinstance(g, bool) else _eq_lane(g, 1)
            b = {pat: mk_int(m)}
            try:
                v = _lift(sym_eval2(e.body,
                                    fr.with_bound(b).with_guard(gb)), fr)
            except UnrollLimitError:
                raise
            except CompileError as ex:
                # body uncompilable for this universe member (q[j+1] past
                # the sequence capacity for dead j): zeros, and abort the
                # run if the member is ever actually in the set
                fr.flag_demoted(gb, why=str(ex))
                if vals:
                    v = SymV(vals[0][1].spec, _zeros(vals[0][1].spec.width))
                else:
                    continue
            vals.append((gb, v))
            length = length + (_ite(gb, 1, 0) if not isinstance(gb, bool)
                               else (1 if gb else 0))
        if not vals:
            raise CompileError("empty iset function constructor")
        espec = vals[0][1].spec
        for _, v in vals[1:]:
            espec = vs_merge(espec, v.spec)
        from .vspec import apply_bounds
        espec = apply_bounds(espec, fr.kc.bounds)
        lanes = [length]
        # contiguity: iset from 1..k is a prefix, so position = value - 1
        for gb, v in vals:
            cv = coerce(v, espec, fr)
            lanes.extend(_select_lanes(gb, cv.lanes, [0] * espec.width))
        return SymV(VS("seq", cap=len(vals), elem=espec), lanes)
    raise CompileError("function constructor over non-static domain")


def _sym_setfilter(e: A.SetFilter, fr: Frame):
    sval = sym_eval2(e.set, fr)
    if isinstance(sval, frozenset):
        # static domain, possibly symbolic predicate -> mask set
        members = sorted(sval, key=sort_key)
        all_static = True
        lanes = []
        kept = []
        for m in members:
            b = bind_pattern(e.var, m) if isinstance(e.var, tuple) \
                else {e.var: m}
            b = {nm: (_lift(v, fr) if not isinstance(v, (frozenset, Fcn))
                      else v) for nm, v in b.items()}
            p = as_bool(sym_eval2(e.pred, fr.with_bound(b)), fr)
            if isinstance(p, bool):
                if p:
                    kept.append(m)
                lanes.append(1 if p else 0)
            else:
                all_static = False
                lanes.append(_ite(p, 1, 0))
        if all_static:
            return frozenset(kept)
        if all(isinstance(m, (str, ModelValue)) for m in members):
            return SymV(VS("set", dom=tuple(members)), lanes)
        if all(isinstance(m, int) for m in members):
            return SymV(VS("iset", dom=tuple(members)), lanes)
        raise CompileError("symbolic filter over heterogeneous set")
    if isinstance(sval, SymV) and sval.spec.kind in ("set", "iset"):
        lanes = []
        for i, m in enumerate(sval.spec.dom):
            b = {e.var: _lift(m, fr) if not isinstance(m, (frozenset, Fcn))
                 else m} if not isinstance(e.var, tuple) else None
            if b is None:
                raise CompileError("pattern filter over mask set")
            p = as_bool(sym_eval2(e.pred, fr.with_bound(b)), fr)
            memb = sval.lanes[i]
            mb = memb if isinstance(memb, bool) else _eq_lane(memb, 1)
            both = _land(mb, p)
            lanes.append(_ite(both, 1, 0) if not isinstance(both, bool)
                         else (1 if both else 0))
        return SymV(sval.spec, lanes)
    if isinstance(sval, Elems) or (isinstance(sval, SymV)
                                   and sval.spec.kind == "growset"):
        out = []
        for g, v in _elements(sval, fr):
            b = {e.var: v}
            p = as_bool(sym_eval2(e.pred, fr.with_bound(b)), fr)
            out.append((_land(g, p), v))
        return Elems(out)
    raise CompileError("unsupported set filter")


def _sym_setmap(e: A.SetMap, fr: Frame):
    out = []
    for guard, bound in _binder_combos(e.binders, fr):
        v = sym_eval2(e.expr, fr.with_bound(bound).with_guard(guard))
        out.append((guard, v))
    if all(g is True for g, _ in out):
        conc = _try_concrete([v for _, v in out], fr)
        if conc is not None:
            return frozenset(conc)
    return Elems(out)


def _try_concrete(items, fr: Frame):
    """If every item is static, give back concrete python values."""
    conc = []
    for x in items:
        if isinstance(x, SymV):
            if not x.static:
                return None
            conc.append(_decode_static(x, fr))
        elif isinstance(x, Elems):
            return None
        else:
            conc.append(x)
    return conc


def _sym_choose(e: A.Choose, fr: Frame):
    """CHOOSE x \\in S : P. Static sets resolve statically; the Min/Max
    idiom (raft.tla:151-154) over symbolic int sets compiles to masked
    min/max."""
    if e.set is None:
        raise CompileError("unbounded CHOOSE")
    sval = sym_eval2(e.set, fr)
    if isinstance(sval, frozenset):
        for m in sorted(sval, key=sort_key):
            b = bind_pattern(e.var, m) if isinstance(e.var, tuple) \
                else {e.var: m}
            b = {nm: (_lift(v, fr) if not isinstance(v, (frozenset, Fcn))
                      else v) for nm, v in b.items()}
            p = as_bool(sym_eval2(e.pred, fr.with_bound(b)), fr)
            if not isinstance(p, bool):
                raise CompileError("CHOOSE with traced predicate over "
                                   "static set")
            if p:
                return _lift(m, fr) if not isinstance(m, (frozenset, Fcn)) \
                    else m
        raise CompileError(f"CHOOSE: no witness in static set {sval!r} (var {e.var}, pred {e.pred})")
    mode = _minmax_pattern(e)
    if mode and isinstance(sval, Elems):
        # Min({Len(log[i]), nextIndex[i][j]}) — fold over guarded items
        # (raft.tla:229)
        best = None
        for g, v in sval.items:
            x = as_int_lane(_lift(v, fr))
            masked = _ite(as_bool(mk_bool(g), fr) if not isinstance(g, bool)
                          else g, x, -10**6 if mode == "max" else 10**6)
            if best is None:
                best = masked
            else:
                best = jnp.maximum(best, masked) if mode == "max" \
                    else jnp.minimum(best, masked)
        if best is None:
            raise CompileError("CHOOSE over empty extensional set")
        return mk_int(best)
    if mode and isinstance(sval, SymV) and sval.spec.kind == "iset":
        # masked min/max over the int universe; value is unspecified when
        # the set is empty (the spec guards emptiness, as TLC does lazily)
        best = None
        for i, m in enumerate(sval.spec.dom):
            memb = sval.lanes[i]
            mb = memb if isinstance(memb, bool) else _eq_lane(memb, 1)
            if best is None:
                best = _ite(mb, m, -10**6 if mode == "max" else 10**6)
            else:
                cand = _ite(mb, m, -10**6 if mode == "max" else 10**6)
                best = jnp.maximum(best, cand) if mode == "max" \
                    else jnp.minimum(best, cand)
        return mk_int(best)
    raise CompileError("CHOOSE over symbolic set (not a Min/Max pattern)")


def _minmax_pattern(e: A.Choose) -> Optional[str]:
    """Min(s): CHOOSE x \\in s : \\A y \\in s : x <= y (raft.tla:151-154)."""
    p = e.pred
    if not (isinstance(p, A.Quant) and p.kind == "A" and len(p.binders) == 1
            and isinstance(p.body, A.OpApp)):
        return None
    op = p.body.name
    if op in ("<=", "=<", "\\leq"):
        return "min"
    if op in (">=", "\\geq"):
        return "max"
    return None


def _sym_opapp2(e: A.OpApp, fr: Frame):
    name = e.name
    kc = fr.kc
    if e.path:
        raise CompileError("instance paths not compilable yet")
    if name == "/\\":
        # lazy like TLC: a statically-false left guard protects the right
        # (IF agreeIndexes /= {} /\ log[i][Max(agreeIndexes)]...,
        # raft.tla:288-295); with a TRACED guard, an uncompilable right
        # side is recovered by flagging overflow where it would be needed
        a = as_bool(sym_eval2(e.args[0], fr), fr)
        if a is False:
            return mk_bool(False)
        try:
            b = as_bool(sym_eval2(e.args[1], fr), fr)
        except UnrollLimitError:
            raise
        except CompileError as ex:
            if a is True:
                raise
            fr.flag_demoted(a, why=str(ex))
            return mk_bool(False)
        return mk_bool(_land(a, b))
    if name == "\\/":
        a = as_bool(sym_eval2(e.args[0], fr), fr)
        if a is True:
            return mk_bool(True)
        try:
            b = as_bool(sym_eval2(e.args[1], fr), fr)
        except UnrollLimitError:
            raise
        except CompileError as ex:
            if a is False:
                raise
            fr.flag_demoted(_lnot(a), why=str(ex))
            return mk_bool(a)
        return mk_bool(_lor(a, b))
    if name == "~":
        return mk_bool(_lnot(as_bool(sym_eval2(e.args[0], fr), fr)))
    if name == "=>":
        a = as_bool(sym_eval2(e.args[0], fr), fr)
        if a is False:
            return mk_bool(True)
        return mk_bool(_lor(_lnot(a),
                            as_bool(sym_eval2(e.args[1], fr), fr)))
    if name in ("<=>", "\\equiv"):
        a = as_bool(sym_eval2(e.args[0], fr), fr)
        b = as_bool(sym_eval2(e.args[1], fr), fr)
        if isinstance(a, bool) and isinstance(b, bool):
            return mk_bool(a == b)
        return mk_bool(jnp.equal(a, b))
    if name in ("=", "/=", "#"):
        a = sym_eval2(e.args[0], fr)
        b = sym_eval2(e.args[1], fr)
        r = _generic_eq(a, b, fr)
        return mk_bool(r if name == "=" else _lnot(r))
    if name in ("\\in", "\\notin"):
        x = sym_eval2(e.args[0], fr)
        s = sym_eval2(e.args[1], fr)
        r = _generic_in(x, s, fr)
        return mk_bool(r if name == "\\in" else _lnot(r))
    if name in _ARITH:
        a = as_int_lane(sym_eval2(e.args[0], fr))
        b = as_int_lane(sym_eval2(e.args[1], fr))
        if isinstance(a, int) and isinstance(b, int):
            return mk_int({"+": a + b, "-": a - b, "*": a * b,
                           "\\div": a // b if b else 0,
                           "%": a % b if b else 0,
                           "^": a ** b}[name])
        ops = {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
               "\\div": jnp.floor_divide, "%": jnp.mod,
               "^": jnp.power}
        return mk_int(ops[name](a, b))
    if name in _CMP:
        a = as_int_lane(sym_eval2(e.args[0], fr))
        b = as_int_lane(sym_eval2(e.args[1], fr))
        if isinstance(a, int) and isinstance(b, int):
            return mk_bool({"<": a < b, ">": a > b}.get(
                name, a <= b if name in ("<=", "=<", "\\leq") else a >= b))
        ops = {"<": jnp.less, ">": jnp.greater}
        f = ops.get(name, jnp.less_equal if name in ("<=", "=<", "\\leq")
                    else jnp.greater_equal)
        return mk_bool(f(a, b))
    if name == "-.":
        a = as_int_lane(sym_eval2(e.args[0], fr))
        return mk_int(-a if isinstance(a, int) else jnp.negative(a))
    if name == "..":
        a = sym_eval2(e.args[0], fr)
        b = sym_eval2(e.args[1], fr)
        al, bl = as_int_lane(a), as_int_lane(b)
        if isinstance(al, int) and isinstance(bl, int):
            return frozenset(range(al, bl + 1))
        return interval_iset(al, bl, fr)
    if name in ("\\cup", "\\union"):
        return set_union(sym_eval2(e.args[0], fr),
                         sym_eval2(e.args[1], fr), fr)
    if name in ("\\cap", "\\intersect", "\\"):
        a = sym_eval2(e.args[0], fr)
        b = sym_eval2(e.args[1], fr)
        if isinstance(a, frozenset) and isinstance(b, frozenset):
            return a & b if name != "\\" else a - b
        ma, mb = _to_mask_set(a, fr), _to_mask_set(b, fr)
        ma, mb = unify(ma, mb, fr)
        out = []
        for x, y in zip(ma.lanes, mb.lanes):
            xb = x if isinstance(x, bool) else _eq_lane(x, 1)
            yb = y if isinstance(y, bool) else _eq_lane(y, 1)
            r = _land(xb, yb) if name != "\\" else _land(xb, _lnot(yb))
            out.append(_ite(r, 1, 0) if not isinstance(r, bool)
                       else (1 if r else 0))
        return SymV(ma.spec, out)
    if name == "\\subseteq":
        a = sym_eval2(e.args[0], fr)
        b = sym_eval2(e.args[1], fr)
        acc = True
        for g, m in _elements(a, fr):
            inn = _generic_in(m, b, fr)
            acc = _land(acc, _lor(_lnot(g), inn))
        return mk_bool(acc)
    if name == "Cardinality":
        s = sym_eval2(e.args[0], fr)
        if isinstance(s, frozenset):
            return mk_int(len(s))
        n = 0
        for g, _ in _elements(s, fr):
            n = n + (_ite(g, 1, 0) if not isinstance(g, bool)
                     else (1 if g else 0))
        return mk_int(n)
    if name == "SUBSET":
        s = sym_eval2(e.args[0], fr)
        if isinstance(s, frozenset):
            out = []
            ms = sorted(s, key=sort_key)
            for r in range(len(ms) + 1):
                for c in itertools.combinations(ms, r):
                    out.append(frozenset(c))
            return frozenset(out)
        raise CompileError(SUBSET_SYMBOLIC_MSG)
    if name == "UNION":
        s = sym_eval2(e.args[0], fr)
        if isinstance(s, frozenset):
            out = frozenset()
            for m in s:
                out = out | m
            return out
        raise CompileError("UNION of symbolic set")
    if name == "DOMAIN":
        f = sym_eval2(e.args[0], fr)
        if isinstance(f, Fcn):
            return f.domain()
        if isinstance(f, SymV):
            sp = f.spec
            if sp.kind == "fcn":
                return frozenset(sp.dom)
            if sp.kind == "seq":
                return interval_iset(mk_int(1), seq_len(f), fr)
            if sp.kind == "kvtable":
                return Elems([(g, k) for g, k, _ in kv_domain_slots(f)])
            if sp.kind == "pfcn":
                lanes = []
                off = 0
                for dk, es in zip(sp.dom, sp.elems):
                    lanes.append(f.lanes[off])
                    off += 1 + es.width
                if all(isinstance(m, (str, ModelValue)) for m in sp.dom):
                    return SymV(VS("set", dom=sp.dom), lanes)
                return SymV(VS("iset", dom=sp.dom), lanes)
        raise CompileError("DOMAIN of non-function")
    if name == "Len":
        return seq_len(_lift(sym_eval2(e.args[0], fr), fr))
    if name == "Append":
        return seq_append(_lift(sym_eval2(e.args[0], fr), fr),
                          sym_eval2(e.args[1], fr), fr)
    if name == "SubSeq":
        return seq_subseq(_lift(sym_eval2(e.args[0], fr), fr),
                          sym_eval2(e.args[1], fr),
                          sym_eval2(e.args[2], fr), fr)
    if name in ("\\o", "\\circ"):
        return seq_concat(_lift(sym_eval2(e.args[0], fr), fr),
                          _lift(sym_eval2(e.args[1], fr), fr), fr)
    if name == "Head":
        return sym_apply(_lift(sym_eval2(e.args[0], fr), fr), [mk_int(1)],
                         fr)
    if name == "Tail":
        v = _lift(sym_eval2(e.args[0], fr), fr)
        if v.spec.kind != "seq":
            raise CompileError("Tail of non-sequence")
        # the interpreter raises on Tail(<<>>); a reachable empty-Tail is
        # a spec error, so the overflow flag aborts equivalently
        fr.flag_overflow(_eq_lane(v.lanes[0], 0))
        return seq_subseq(v, mk_int(2), seq_len(v), fr)
    if name == ":>":
        k = _lift(sym_eval2(e.args[0], fr), fr)
        v = _lift(sym_eval2(e.args[1], fr), fr)
        return ("$single", k, v)
    if name == "@@":
        f = sym_eval2(e.args[0], fr)
        g = sym_eval2(e.args[1], fr)
        if isinstance(g, tuple) and g and g[0] == "$single":
            f = _lift(f, fr)
            if f.spec.kind == "kvtable":
                return kv_merge_insert(f, g[1], g[2], fr)
            if f.spec.kind == "pfcn":
                def same(old):
                    return g[2]
                return sym_except(f, [("idx", [g[1]])], lambda old: g[2],
                                  fr)
        raise CompileError("@@ outside table-insert idiom")
    if name in ("\\X", "\\times"):
        args = [sym_eval2(a, fr) for a in e.args]
        if all(isinstance(a, frozenset) for a in args):
            from ..sem.values import mk_seq as _mkseq
            out = []
            for combo in itertools.product(
                    *[sorted(a, key=sort_key) for a in args]):
                out.append(_mkseq(list(combo)))
            return frozenset(out)
        raise CompileError("cartesian product over symbolic sets")
    if name == "Seq":
        sv = sym_eval2(e.args[0], fr)
        if isinstance(sv, frozenset):
            return InfiniteSet("Seq", sv)
        raise CompileError("Seq over symbolic set")
    if name == "Assert":
        raise CompileError("Assert in expression position")
    if name == "!sel":
        base, num = e.args
        if isinstance(base, A.Ident):
            d = kc.model.defs.get(base.name)
            if isinstance(d, OpClosure):
                conjs = _flatten_conj(d.body)
                if 1 <= num.val <= len(conjs):
                    return sym_eval2(conjs[num.val - 1], fr)
        raise CompileError("!sel not resolvable")
    # user-defined operators
    d = fr.bound.get(name)
    if d is None:
        d = kc.model.defs.get(name)
    if isinstance(d, tuple) and d and d[0] == "$op":
        od, captured = d[1], d[2]
        args = [sym_eval2(a, fr) for a in e.args]
        with _op_unroll(kc, name):
            return sym_eval2(od.body, fr.with_bound(
                {**captured, **dict(zip(od.params, args))}))
    if isinstance(d, OpClosure):
        args = [sym_eval2(a, fr) for a in e.args]
        with _op_unroll(kc, name):
            return sym_eval2(d.body,
                             fr.with_bound(dict(zip(d.params, args))))
    if d is not None and not e.args:
        if kc.const_lanes and name in kc.const_lanes:
            return mk_int(kc.const_lanes[name])  # lifted CONSTANT
        if isinstance(d, (SymV, frozenset, Fcn, Elems)):
            return d
        return _static_const(d, fr)
    raise CompileError(f"cannot compile operator {name}")


class UnrollLimitError(CompileError):
    """A RECURSIVE operator exceeded the compile-time unroll limit.
    Deliberately NON-RECOVERABLE: the `except CompileError` recovery
    sites re-raise it, because recovering would retry the sibling
    branch of every unroll frame — exponential recursion (Fib) would
    turn one failed trace into ~2^limit recovery attempts.  The arm (or
    predicate) demotes whole, with the operator's name in the reason."""


# shared demotion-reason wording (ISSUE 9): jaxmc/analyze/verdicts.py
# predicts these demotions BEFORE any build, and the predicted verdict
# must carry the exact string the build-time path reports — both sides
# read the one constant, so the wording cannot diverge
SUBSET_SYMBOLIC_MSG = "SUBSET of symbolic set"

# ISSUE 15 taxonomy additions: a quantifier with no domain at all, and
# a quantifier/enumeration over an infinite constant set (Nat, Int,
# STRING, Seq(S)) — both certain demotions the predictor can name
# before any build
UNBOUNDED_QUANTIFIER_MSG = "unbounded quantifier"


def cannot_enumerate_message(sv) -> str:
    return f"cannot enumerate {sv!r}"


def unroll_limit_message(name: str, limit: int) -> str:
    return (f"recursive operator {name} exceeds the compile-time "
            f"unroll limit ({limit}; raise with JAXMC_OP_UNROLL_LIMIT) "
            f"— its expansion diverges on symbolic arguments")


class _op_unroll:
    """Same-name re-entry counter around user-operator expansion: trips
    BEFORE Python's recursion limit so a diverging RECURSIVE operator
    demotes with its NAME in the CompileError (the per-arm demotion
    reason table) instead of an anonymous RecursionError."""
    __slots__ = ("kc", "name")

    def __init__(self, kc: KernelCtx, name: str):
        self.kc = kc
        self.name = name
        depth = kc.op_depth.get(name, 0)
        if depth >= kc.op_unroll_limit:
            raise UnrollLimitError(
                unroll_limit_message(name, kc.op_unroll_limit))
        kc.op_depth[name] = depth + 1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.kc.op_depth[self.name] -= 1
        return False


def _flatten_conj(e):
    if isinstance(e, A.OpApp) and e.name == "/\\":
        return _flatten_conj(e.args[0]) + _flatten_conj(e.args[1])
    return [e]


def _generic_eq(a, b, fr: Frame):
    if isinstance(a, Elems) or isinstance(b, Elems):
        raise CompileError("equality over extensional sets")
    if not isinstance(a, SymV) and not isinstance(b, SymV):
        try:
            return tla_eq(a, b)
        except EvalError as ex:
            raise CompileError(str(ex))
    if isinstance(a, frozenset) or isinstance(b, frozenset):
        # set vs symbolic set: subset both ways
        st = a if isinstance(a, frozenset) else b
        sy = b if isinstance(a, frozenset) else a
        if isinstance(sy, SymV) and sy.spec.kind in ("set", "iset"):
            acc = True
            for i, m in enumerate(sy.spec.dom):
                memb = sy.lanes[i]
                mb = memb if isinstance(memb, bool) else _eq_lane(memb, 1)
                want = in_set(m, st)
                acc = _land(acc, mb if want else _lnot(mb))
            extra = st - frozenset(sy.spec.dom)
            if extra:
                return False
            return acc
        if isinstance(sy, SymV) and sy.spec.kind == "growset":
            return sym_eq(sy, static_to_symv(st, fr.kc, sy.spec), fr)
        raise CompileError("set equality with unsupported operand")
    a = _lift(a, fr)
    b = _lift(b, fr)
    return sym_eq(a, b, fr)


def _generic_in(x, s, fr: Frame):
    if isinstance(s, Elems):
        acc = False
        for g, v in s.items:
            acc = _lor(acc, _land(g, _generic_eq(x, v, fr)))
        return acc
    return sym_in(x, s, fr)


# ---------------------------------------------------------------------------
# layout + action compilation
# ---------------------------------------------------------------------------

class Layout2:
    """vspec-based state layout (replaces compile.ground.StateLayout).

    Carries the bit-packed LanePlan (compile/pack.py) alongside the
    unpacked lane specs: kernels compute on unpacked lanes, while the
    engines store frontier/seen/trace rows packed.  A Layout2 built
    outside build_layout2 (tests) lazily defaults to the identity plan
    (packed == unpacked)."""

    def __init__(self, vars: Tuple[str, ...], specs: Dict[str, VS],
                 uni: EnumUniverse):
        self.vars = vars
        self.specs = specs
        self.uni = uni
        self.width = sum(specs[v].width for v in vars)
        self.offsets = {}
        off = 0
        for v in vars:
            self.offsets[v] = off
            off += specs[v].width
        self._plan = None

    @property
    def plan(self):
        if self._plan is None:
            from .pack import identity_plan
            self._plan = identity_plan(self.width)
        return self._plan

    @plan.setter
    def plan(self, p):
        self._plan = p

    @property
    def packed_width(self) -> int:
        return self.plan.packed_width

    def encode(self, state: Dict[str, Any]):
        import numpy as np
        out: List[int] = []
        for v in self.vars:
            vs_encode(state[v], self.specs[v], self.uni, out)
        return np.asarray(out, dtype=np.int32)

    def decode(self, row) -> Dict[str, Any]:
        from .vspec import decode as vs_decode
        st = {}
        i = 0
        for v in self.vars:
            st[v], i = vs_decode(row, i, self.specs[v], self.uni)
        return st

    # ---- packed-row boundary helpers (engine storage format) ----

    def pack_np(self, rows):
        import numpy as np
        rows = np.asarray(rows, np.int32)
        if rows.ndim == 1:
            return self.plan.pack_np(rows[None, :])[0]
        return self.plan.pack_np(rows)

    def unpack_np(self, packed):
        import numpy as np
        packed = np.asarray(packed, np.int32)
        if packed.ndim == 1:
            return self.plan.unpack_np(packed[None, :])[0]
        return self.plan.unpack_np(packed)

    def encode_packed(self, state: Dict[str, Any]):
        return self.pack_np(self.encode(state))

    def decode_packed(self, packed_row) -> Dict[str, Any]:
        return self.decode(self.unpack_np(packed_row))


def build_layout2(model: Model, sampled_states: List[Dict[str, Any]],
                  bounds: Bounds,
                  static_bounds: Optional[Dict[str, Tuple[int, int]]]
                  = None) -> Layout2:
    from .vspec import (apply_bounds, collect_enums_from_value, infer)
    from .. import obs
    uni = EnumUniverse()
    # enum universe: every sampled value + every string literal in the
    # module AST + cfg model values (guards may compare against literals
    # no sampled state contains)
    for st in sampled_states:
        for v in st.values():
            collect_enums_from_value(v, uni)
    for d in model.defs.values():
        if not isinstance(d, OpClosure):
            collect_enums_from_value(d, uni)
    _collect_ast_strings(model, uni)
    specs: Dict[str, VS] = {}
    for var in model.vars:
        sp = None
        for st in sampled_states:
            s2 = infer(st[var], uni)
            sp = s2 if sp is None else vs_merge(sp, s2)
        specs[var] = apply_bounds(sp, bounds)
    lay = Layout2(tuple(model.vars), specs, uni)
    # bit-packed lane plan (ISSUE 6): structural bounds + observed int
    # ranges over the encoded sample rows decide per-lane bit widths
    from .pack import build_lane_plan
    sample_rows = []
    for st in sampled_states:
        try:
            sample_rows.append(lay.encode(st))
        except (CompileError, EvalError):
            # a sampled state the merged layout cannot encode would have
            # failed the search anyway; the plan just profiles without it
            continue
    lay.plan = build_lane_plan(lay, sample_rows, static_bounds)
    tel = obs.current()
    tel.gauge("layout.enum_universe", len(uni.values))
    tel.gauge("layout.samples", len(sampled_states))
    tel.gauge("layout.packed_width_lanes", lay.plan.packed_width)
    tel.gauge("layout.bits_per_state", lay.plan.bits_per_state)
    tel.gauge("layout.pack_ratio",
              round(lay.plan.packed_width / max(lay.width, 1), 4))
    tel.gauge("layout.pack_guarded_lanes", lay.plan.guarded_lanes)
    # statically-proven int lanes (ISSUE 9): previously observed-range
    # guarded lanes whose width now comes from the bounds analyzer —
    # read against layout.pack_guarded_lanes (the two are disjoint)
    tel.gauge("analyze.proven_lanes", lay.plan.proven_lanes)
    return lay


def _collect_ast_strings(model: Model, uni: EnumUniverse):
    def walk(e):
        if isinstance(e, A.Str):
            uni.add(e.val)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, A.Node):
                walk(v)
            elif isinstance(v, tuple):
                _walk_tuple(v)

    def _walk_tuple(t):
        for x in t:
            if isinstance(x, A.Node):
                walk(x)
            elif isinstance(x, tuple):
                _walk_tuple(x)

    for d in model.defs.values():
        if isinstance(d, OpClosure) and isinstance(d.body, A.Node):
            walk(d.body)


@dataclass
class CompiledAction2:
    label: str
    fn: Callable  # (row[, slot]) -> (enabled, assert_ok, overflow, succ_row)
    n_slots: int = 0  # >0: fn takes a traced slot index in [0, n_slots)
    # guard conjuncts the compiler DEMOTED (recovered as `False` +
    # runtime overflow flag) during tracing: a kernel with demoted
    # guards under-approximates the transition relation behind an abort
    # guard — the hybrid engine prefers to fall the whole arm back to
    # the interpreter instead (filled in at trace time, so only
    # populated after the fn has been traced, e.g. via jax.eval_shape)
    demoted_guards: list = field(default_factory=list)


def _slotv_markers(ga) -> dict:
    """The distinct $slotv binder markers in a grounded action, keyed by
    identity (a binder's marker tuple is shared by reference across items),
    each mapped to one bound_env it appears in (for slot-count probing)."""
    markers = {}
    for item in ga.items:
        _, bound_env = item
        for v in bound_env.values():
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "$slotv":
                markers[id(v)] = (v, bound_env)
    return markers


def _probe_slot_count(kc: KernelCtx, sexpr: A.Node, bound_env) -> int:
    """Structural slot count of a dynamic \\E set: trace the set expression
    abstractly (jax.eval_shape, no compile) and count its element slots —
    the same enumeration _slot_bind_traced performs inside the kernel, so
    the count is exact per action instead of the global kv_cap ceiling."""
    layout = kc.layout
    clean = {k: v for k, v in bound_env.items()
             if not (isinstance(v, tuple) and len(v) == 2
                     and v[0] == "$slotv")}
    holder = {}

    def probe(row):
        state = {}
        off = 0
        for v in layout.vars:
            sp = layout.specs[v]
            state[v] = SymV(sp, row[off:off + sp.width])
            off += sp.width
        fr = Frame(kc, _lift_bound(clean, kc), state, {}, [False])
        sval = sym_eval2(sexpr, fr)
        holder["n"] = len(list(_elements(sval, fr)))
        return jnp.zeros(())

    jax.eval_shape(probe, jax.ShapeDtypeStruct((layout.width,), jnp.int32))
    return holder["n"]


def compile_action2(kc: KernelCtx, ga) -> CompiledAction2:
    layout = kc.layout
    vars = layout.vars
    markers = _slotv_markers(ga)
    if len(markers) > 1:
        # every $slotv resolves through the ONE traced slot index, so two
        # distinct dynamic binders (nested or /\-conjoined sibling \E)
        # would only explore equal-index pairs — reject rather than
        # silently drop off-diagonal transitions (ground.py catches the
        # nested form early; this catches the rest)
        raise CompileError(
            f"action {ga.label}: multiple dynamic \\E binders not "
            f"supported (one slot axis per action)")
    slotted = bool(markers)
    n_slots = 0
    if slotted:
        (marker, benv), = markers.values()
        try:
            n_slots = _probe_slot_count(kc, marker[1], benv)
        except Exception as ex:
            # an unsized slot axis could silently drop transitions —
            # reject (interp backend still checks the model)
            raise CompileError(
                f"action {ga.label}: cannot size the dynamic \\E slot "
                f"axis ({ex})") from ex
        if n_slots == 0:
            # structurally empty dynamic set: the action can never fire
            n_slots = 1  # keep one (always-disabled) instance

    demoted_guards: List[str] = []

    def fn(row, slot=None):
        state = {}
        off = 0
        for v in vars:
            sp = layout.specs[v]
            state[v] = SymV(sp, row[off:off + sp.width])
            off += sp.width
        primes: Dict[str, SymV] = {}
        # THREE overflow cells (VERDICT r4 under-generation fix):
        #   succ_ovf  — successor-VALUE capacity overflows: only matter
        #               on taken transitions, masked by the final `en`;
        #   guard_ovf — capacity overflows inside GUARD evaluation: the
        #               guard's value may be wrong whenever they fire, so
        #               they are NEVER masked by `en` (en itself may be
        #               the wrong value — the round-3 MCPaxos bug);
        #   demo      — `except CompileError` recovery flags (demoted
        #               conjuncts, IF/SetMap/lazy-conj recoveries, prime
        #               RHS recovery): compiler limitations the hybrid
        #               engine fixes by demoting the arm to the
        #               interpreter and restarting — reported as overflow
        #               code 2 so the engine can tell them from genuine
        #               capacity overflows (code 1, fix = raise caps).
        succ_ovf = [False]
        guard_ovf = [False]
        demo = [False]
        enabled = True
        assert_ok = True

        for item in ga.items:
            if isinstance(item, tuple) and len(item) == 2 \
                    and isinstance(item[0], A.Node):
                expr, bound_env = item
            else:
                raise CompileError(f"bad grounded item {item!r}")
            # TLC evaluates conjuncts left-to-right: an error (here, a
            # recovery overflow) in conjunct j only surfaces when the
            # conjuncts before it hold — thread enabled-so-far as the
            # frame guard so recovery flags inside this item are masked
            # by the prior conjuncts, exactly TLC's laziness
            fr = Frame(kc, _lift_bound(bound_env, kc), state, primes,
                       guard_ovf, guard=enabled, demo=demo)
            # dynamic-\E slot binding guards (traced slot index)
            slot_guards = []
            bound2 = dict(fr.bound)
            for nm, bv in list(bound2.items()):
                if isinstance(bv, tuple) and len(bv) == 2 \
                        and bv[0] == "$slotv":
                    g, val = _slot_bind_traced(bv[1], slot, fr, n_slots)
                    slot_guards.append(g)
                    bound2[nm] = val
            if slot_guards:
                for g in slot_guards:
                    enabled = _land(enabled, g)
                fr = Frame(kc, bound2, state, primes, guard_ovf,
                           guard=enabled, demo=demo)

            tgt = _prime_target2(expr, vars)
            if tgt is not None:
                var, rhs = tgt
                frv = Frame(kc, fr.bound, state, primes, succ_ovf,
                            guard=enabled, demo=demo)
                try:
                    val = _lift(sym_eval2(rhs, frv), frv)
                    val = coerce(val, layout.specs[var], frv)
                except UnrollLimitError:
                    raise
                except CompileError as ex:
                    if enabled is True:
                        raise
                    # uncompilable only along paths the guards exclude:
                    # demotion-abort if the action is ever enabled
                    frv.flag_demoted(enabled, why=str(ex))
                    val = SymV(layout.specs[var],
                               [0] * layout.specs[var].width)
                if var in primes:
                    enabled = _land(enabled, sym_eq(primes[var], val, fr))
                else:
                    primes[var] = val
                continue
            if isinstance(expr, A.Unchanged):
                _unchanged2(expr.expr, kc, state, primes, vars)
                continue
            if isinstance(expr, A.OpApp) and expr.name == "Assert":
                cond = as_bool(sym_eval2(expr.args[0], fr), fr)
                if cond is not True:
                    bad = _land(enabled, _lnot(cond))
                    assert_ok = _land(assert_ok, _lnot(bad))
                continue
            try:
                g = as_bool(sym_eval2(expr, fr), fr)
            except UnrollLimitError:
                raise
            except CompileError as gex:
                if enabled is True:
                    raise
                # demoted conjunct: False + abort-if-reached, recorded so
                # the hybrid engine can prefer interp enumeration of the
                # whole arm over an abort-guarded under-approximation
                fr.flag_demoted(enabled, why=str(gex))
                if not any(r == str(gex) for r in demoted_guards):
                    demoted_guards.append(str(gex))
                g = False
            enabled = _land(enabled, g)

        missing = [v for v in vars if v not in primes]
        if missing:
            raise CompileError(f"action {ga.label} leaves {missing} "
                               f"unassigned")
        succ = jnp.concatenate(
            [jnp.asarray(primes[v].lanes, dtype=jnp.int32)
             for v in vars])
        en = enabled if _is_traced(enabled) else jnp.asarray(bool(enabled))
        ak = assert_ok if _is_traced(assert_ok) \
            else jnp.asarray(bool(assert_ok))
        sov = succ_ovf[0] if _is_traced(succ_ovf[0]) \
            else jnp.asarray(bool(succ_ovf[0]))
        gov = guard_ovf[0] if _is_traced(guard_ovf[0]) \
            else jnp.asarray(bool(guard_ovf[0]))
        dmo = demo[0] if _is_traced(demo[0]) \
            else jnp.asarray(bool(demo[0]))
        if demo[0] is not False and \
                "expression recovery engaged" not in demoted_guards:
            # structural marker, set at trace time: the hybrid engine
            # only restart-demotes arms whose kernels CAN demote
            demoted_guards.append("expression recovery engaged")
        # successor-value capacity overflow only matters on taken
        # transitions; guard capacity overflow always aborts; demotion
        # flags win the code so the engine can demote-and-restart
        cap = jnp.logical_or(jnp.logical_and(en, sov), gov)
        ov = jnp.where(dmo, OV_DEMOTED,
                       jnp.where(cap, OV_CAPACITY, 0)).astype(jnp.int32)
        return en, ak, ov, succ

    from .. import obs
    obs.current().counter("compile.kernels_built")
    if slotted:
        obs.current().counter("compile.slotted_instances", n_slots)
        return CompiledAction2(ga.label, fn, n_slots=n_slots,
                               demoted_guards=demoted_guards)
    return CompiledAction2(ga.label, lambda row: fn(row, None),
                           demoted_guards=demoted_guards)


def _lift_bound(bound_env: Dict[str, Any], kc: KernelCtx) -> Dict[str, Any]:
    out = {}
    for k, v in bound_env.items():
        if isinstance(v, (frozenset, Fcn, InfiniteSet)) or \
                (isinstance(v, tuple) and v and v[0] == "$slot"):
            out[k] = v
        elif isinstance(v, (int, bool, str, ModelValue)):
            if isinstance(v, bool):
                out[k] = SymV(BOOL, [v])
            elif isinstance(v, int):
                out[k] = SymV(INT, [v])
            else:
                out[k] = SymV(ENUM, [kc.uni.index(v)])
        else:
            out[k] = v
    return out


def _slot_bind_traced(setexpr: A.Node, slot, fr: Frame, n_slots: int):
    """Bind the slot-th element (traced index) of a dynamic set — a
    select-chain over the table slots, so the trace stays O(capacity)
    per ACTION FAMILY instead of per instance."""
    sval = sym_eval2(setexpr, fr)
    items = list(_elements(sval, fr))
    if len(items) > n_slots:
        # the engine only vmaps n_slots slot indices (probed by
        # _probe_slot_count from this same enumeration) — a divergence
        # here would silently drop the elements beyond the probe
        raise CompileError(
            f"dynamic \\E set has {len(items)} potential elements but "
            f"the probed slot axis has {n_slots}")
    if not items:
        return False, None
    first = items[0][1]
    if not isinstance(first, SymV):
        first = _lift(first, fr)
    spec = first.spec
    mat = []
    guards = []
    for g, v in items:
        sv = v if isinstance(v, SymV) else _lift(v, fr)
        mat.append(jnp.asarray(coerce(sv, spec, fr).lanes))
        gb = g if not isinstance(g, bool) else jnp.asarray(g)
        guards.append(gb)
    mat = jnp.stack(mat)                       # [n_items, w]
    gs = jnp.stack([jnp.asarray(g) for g in guards])
    safe = jnp.clip(slot, 0, len(items) - 1)
    guard = jnp.where(slot < len(items), gs[safe], False)
    return guard, SymV(spec, mat[safe])


def _prime_target2(e: A.Node, vars):
    if isinstance(e, A.OpApp) and e.name == "=" and \
            isinstance(e.args[0], A.Prime) and \
            isinstance(e.args[0].expr, A.Ident) and \
            e.args[0].expr.name in vars:
        return e.args[0].expr.name, e.args[1]
    return None


def _unchanged2(e: A.Node, kc: KernelCtx, state, primes, vars):
    if isinstance(e, A.Ident):
        if e.name in vars:
            if e.name not in primes:
                primes[e.name] = state[e.name]
            return
        d = kc.model.defs.get(e.name)
        if isinstance(d, OpClosure) and not d.params:
            _unchanged2(d.body, kc, state, primes, vars)
            return
        raise CompileError(f"UNCHANGED of non-variable {e.name}")
    if isinstance(e, A.TupleExpr):
        for x in e.items:
            _unchanged2(x, kc, state, primes, vars)
        return
    raise CompileError(f"unsupported UNCHANGED {e!r}")


def introspect_kernel(fn: Callable, args, want_cost: bool = True
                      ) -> Dict[str, int]:
    """Compile-cost introspection for one kernel (ISSUE 2): jaxpr size
    (equations — the compile-time driver: XLA:CPU compile wall grows
    superlinearly in it, the r3 MCVoting blowup) and, when the backend's
    HLO cost model answers, lowered flops / bytes accessed.

    The make_jaxpr trace DOUBLES AS THE FORCED ABSTRACT TRACE: it raises
    lazy CompileError/RecursionError exactly like jax.eval_shape, so a
    telemetry-enabled build calls this INSTEAD of eval_shape — one trace,
    not two, and the compile_arm span measures what an untelemetered run
    would pay. Only the cost-analysis half is best-effort/never-raise
    (the cost model is absent on some backends; the lowering it needs is
    also the expensive part, so JAXMC_COMPILE_INTROSPECT=0 skips it).

    Returns {jaxpr_eqns} plus {hlo_flops, hlo_bytes} when available;
    when the persistent compilation cache is active (compile/cache.py)
    the one-time `compile.persistent_cache_active` gauge records that
    this run's arm compiles were eligible for disk hits."""
    jx = jax.make_jaxpr(fn)(*args)  # propagates trace-time errors
    out: Dict[str, int] = {"jaxpr_eqns": len(jx.eqns)}
    try:
        if jax.config.jax_compilation_cache_dir:
            from .. import obs
            obs.current().gauge("compile.persistent_cache_active", True)
    except AttributeError:  # config knob absent on old jax
        pass
    if not want_cost or \
            os.environ.get("JAXMC_COMPILE_INTROSPECT") == "0":
        return out
    try:
        ca = jax.jit(fn).lower(*args).cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one per device
            ca = ca[0] if ca else None
        if ca:
            flops = ca.get("flops")
            nbytes = ca.get("bytes accessed")
            if flops is not None and flops == flops:  # NaN-guard
                out["hlo_flops"] = int(flops)
            if nbytes is not None and nbytes == nbytes:
                out["hlo_bytes"] = int(nbytes)
    except Exception:  # noqa: BLE001 — cost model absent on some backends
        pass
    return out


def compile_value2(kc: KernelCtx, expr: A.Node) -> Callable:
    """Compile an expression to its encoded VALUE lanes: fn(row) -> 1-D
    i32 lane array.  Used for cfg VIEW (ISSUE 6): the engines key their
    dedup on the view's value lanes instead of the state row, matching
    TLC's fingerprint-the-view semantics.  Strict frame like predicates:
    an uncompilable view raises CompileError at trace time (the interp
    backend remains the checker)."""
    layout = kc.layout

    def fn(row):
        state = {}
        off = 0
        for v in layout.vars:
            sp = layout.specs[v]
            state[v] = SymV(sp, row[off:off + sp.width])
            off += sp.width
        fr = Frame(kc, {}, state, {}, [False], strict=True, memo={})
        val = _lift(sym_eval2(expr, fr), fr)
        lanes = val.lanes
        if isinstance(lanes, np.ndarray):
            # a row-independent view (constant value): still a valid
            # partition — every state shares one key
            return jnp.asarray(lanes.astype(np.int32))
        lanes = jnp.asarray(lanes)
        return lanes.astype(jnp.int32) if lanes.dtype != jnp.int32 \
            else lanes

    return fn


def compile_predicate2(kc: KernelCtx, expr: A.Node) -> Callable:
    layout = kc.layout

    def fn(row):
        state = {}
        off = 0
        for v in layout.vars:
            sp = layout.specs[v]
            state[v] = SymV(sp, row[off:off + sp.width])
            off += sp.width
        fr = Frame(kc, {}, state, {}, [False], strict=True, memo={})
        r = as_bool(sym_eval2(expr, fr), fr)
        return r if _is_traced(r) else jnp.asarray(bool(r))

    return fn
