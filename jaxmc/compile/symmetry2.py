r"""Device-side SYMMETRY canonicalization over encoded state rows.

Every cfg SYMMETRY permutation of model values induces an exact
transformation of the fixed-width lane encoding (compile/vspec.py):
enum lanes remap through a value table, function/set lanes permute
position-wise with the domain, and containers with a canonical internal
order (growset, kvtable) are re-sorted after the element remap — so
``decode . transform == apply_perm . decode`` lane-for-lane. The device
canonical representative of a state row is the lexicographic minimum of
the row over the (closed) permutation group; hashing canonical rows in
``TpuExplorer._keys_of`` gives the same orbit partition — and therefore
the same distinct/generated counts — as the interp backend's
``make_canonicalizer`` (engine/explore.py), TLC's symmetry reduction
(SURVEY.md §5 state-space reduction).

Encodings that cannot be permuted exactly (a permuted domain member
missing from a layout universe, heterogeneous per-key function specs
inside one orbit) raise CompileError; TpuExplorer then falls back to the
unreduced search with the existing SYMMETRY warning.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .vspec import VS, EnumUniverse, SENTINEL_LANE, CompileError

SENTINEL = np.int32(SENTINEL_LANE)


def _hk(k):
    from .vspec import _hk as h
    return h(k)


def _value_table(pd: Dict, uni: EnumUniverse) -> Optional[np.ndarray]:
    """Index remap table over the enum universe for permutation pd, or
    None when pd fixes every universe member (identity on enum lanes)."""
    n = len(uni)
    tab = np.arange(n, dtype=np.int32)
    changed = False
    for i, v in enumerate(uni.values):
        w = pd.get(v, v)
        if w is not v:
            try:
                tab[i] = uni.index(w)
            except CompileError:
                raise CompileError(
                    f"symmetry image {w} not in the layout's enum "
                    f"universe - deepen layout sampling")
            changed = True
    return tab if changed else None


def _lex_sort_rows(m, key_cols: int):
    """Stable lexicographic sort of the rows of m [c, w] by the first
    key_cols columns (LSD: chained single-key stable sorts — multi-key
    comparators explode XLA compile time inside while loops). SENTINEL
    padding rows sort last (SENTINEL is the int32 maximum)."""
    cols = [m[:, j] for j in range(m.shape[1])]
    for c in reversed(range(key_cols)):
        res = lax.sort(tuple([cols[c]] + cols), num_keys=1,
                       is_stable=True)
        cols = list(res[1:])
    return jnp.stack(cols, axis=1)


def _seg_tf(spec: VS, pd: Dict, uni: EnumUniverse,
            tab: Optional[np.ndarray]) -> Optional[Callable]:
    """Transform for one encoded segment (length spec.width) under pd.
    Returns None when the transform is the identity (common: int lanes,
    domains untouched by pd). Raises CompileError when the encoding
    cannot be permuted exactly."""
    k = spec.kind
    if k in ("justempty", "int", "bool"):
        return None
    if k == "enum":
        if tab is None:
            return None
        jt = jnp.asarray(tab)

        def enum_tf(seg):
            v = seg[0]
            out = jnp.where(v == SENTINEL, v,
                            jt[jnp.clip(v, 0, len(tab) - 1)])
            return out[None]
        return enum_tf

    if k == "fcn":
        # new[key] = old[pd^-1(key)]: position i takes the segment of the
        # source key, itself element-transformed
        inv = {_hk(v): kk for kk, v in pd.items()}
        pos = {_hk(kk): i for i, kk in enumerate(spec.dom)}
        offs = np.cumsum([0] + [e.width for e in spec.elems])
        src_idx, sub_tfs, moved = [], [], False
        for i, kk in enumerate(spec.dom):
            src = inv.get(_hk(kk), kk)
            j = pos.get(_hk(src))
            if j is None:
                raise CompileError(
                    f"symmetry moves {src} outside the function domain "
                    f"{spec.dom}")
            if spec.elems[j] != spec.elems[i]:
                raise CompileError(
                    "heterogeneous function-value specs within one "
                    "symmetry orbit")
            src_idx.append(j)
            moved = moved or j != i
            sub_tfs.append(_seg_tf(spec.elems[j], pd, uni, tab))
        if not moved and all(t is None for t in sub_tfs):
            return None

        def fcn_tf(seg):
            parts = []
            for i, j in enumerate(src_idx):
                sub = seg[offs[j]:offs[j + 1]]
                parts.append(sub if sub_tfs[i] is None else sub_tfs[i](sub))
            return jnp.concatenate(parts) if parts else seg
        return fcn_tf

    if k == "set":
        inv = {_hk(v): kk for kk, v in pd.items()}
        pos = {_hk(m): i for i, m in enumerate(spec.dom)}
        src_idx = []
        for i, m in enumerate(spec.dom):
            src = inv.get(_hk(m), m)
            j = pos.get(_hk(src))
            if j is None:
                raise CompileError(
                    f"symmetry moves {src} outside the set universe "
                    f"{spec.dom}")
            src_idx.append(j)
        if src_idx == list(range(len(spec.dom))):
            return None
        gidx = jnp.asarray(np.asarray(src_idx, np.int32))

        def set_tf(seg):
            return jnp.take(seg, gidx)
        return set_tf

    if k == "seq":
        sub = _seg_tf(spec.elem, pd, uni, tab)
        if sub is None:
            return None
        ew = spec.elem.width

        def seq_tf(seg):
            n = seg[0]
            parts = [seg[:1]]
            for j in range(spec.cap):
                s = seg[1 + j * ew:1 + (j + 1) * ew]
                # zero padding beyond the length lane must NOT remap
                parts.append(jnp.where(j < n, sub(s), s))
            return jnp.concatenate(parts)
        return seq_tf

    if k == "growset":
        sub = _seg_tf(spec.elem, pd, uni, tab)
        if sub is None:
            return None  # remap is identity => sorted order unchanged
        ew = spec.elem.width

        def growset_tf(seg):
            n = seg[0]
            parts = []
            for j in range(spec.cap):
                s = seg[1 + j * ew:1 + (j + 1) * ew]
                # SENTINEL padding beyond the count must NOT remap
                parts.append(jnp.where(j < n, sub(s), s))
            m = jnp.reshape(jnp.concatenate(parts), (spec.cap, ew))
            m = _lex_sort_rows(m, ew)
            return jnp.concatenate([seg[:1], m.reshape(-1)])
        return growset_tf

    if k == "pfcn":
        inv = {_hk(v): kk for kk, v in pd.items()}
        pos = {_hk(kk): i for i, kk in enumerate(spec.dom)}
        offs = np.cumsum([0] + [1 + e.width for e in spec.elems])
        src_idx, sub_tfs, moved = [], [], False
        for i, kk in enumerate(spec.dom):
            src = inv.get(_hk(kk), kk)
            j = pos.get(_hk(src))
            if j is None:
                raise CompileError(
                    f"symmetry moves {src} outside the pfcn universe")
            if spec.elems[j] != spec.elems[i]:
                raise CompileError(
                    "heterogeneous pfcn value specs within one symmetry "
                    "orbit")
            src_idx.append(j)
            moved = moved or j != i
            sub_tfs.append(_seg_tf(spec.elems[j], pd, uni, tab))
        if not moved and all(t is None for t in sub_tfs):
            return None

        def pfcn_tf(seg):
            parts = []
            for i, j in enumerate(src_idx):
                blk = seg[offs[j]:offs[j + 1]]
                bit, val = blk[:1], blk[1:]
                if sub_tfs[i] is not None:
                    # absent entries are zero-padded: remap only present
                    val = jnp.where(bit[0] == 1, sub_tfs[i](val), val)
                parts.append(jnp.concatenate([bit, val]))
            return jnp.concatenate(parts)
        return pfcn_tf

    if k == "union":
        var_tfs = []
        any_tf = False
        for vnames, vfields in spec.variants:
            offs = np.cumsum([0] + [f.width for f in vfields])
            subs = [_seg_tf(f, pd, uni, tab) for f in vfields]
            if any(s is not None for s in subs):
                any_tf = True

            def vtf(seg, offs=offs, subs=subs):
                parts = []
                for i, s in enumerate(subs):
                    fld = seg[offs[i]:offs[i + 1]]
                    parts.append(fld if s is None else s(fld))
                parts.append(seg[offs[-1]:])  # zero tail padding
                return jnp.concatenate(parts)
            var_tfs.append(vtf)
        if not any_tf:
            return None

        def union_tf(seg):
            tag, payload = seg[0], seg[1:]
            out = payload
            for t, vtf in enumerate(var_tfs):
                out = jnp.where(tag == t, vtf(payload), out)
            return jnp.concatenate([seg[:1], out])
        return union_tf

    if k == "kvtable":
        ksub = _seg_tf(spec.elem, pd, uni, tab)
        vsub = _seg_tf(spec.val, pd, uni, tab)
        if ksub is None and vsub is None:
            return None
        kw, vw = spec.elem.width, spec.val.width
        rw = kw + vw

        def kv_tf(seg):
            n = seg[0]
            parts = []
            for j in range(spec.cap):
                blk = seg[1 + j * rw:1 + (j + 1) * rw]
                kb, vb = blk[:kw], blk[kw:]
                nk = kb if ksub is None else ksub(kb)
                nv = vb if vsub is None else vsub(vb)
                nb = jnp.concatenate([nk, nv])
                # SENTINEL padding rows must NOT remap
                parts.append(jnp.where(j < n, nb, blk))
            m = jnp.reshape(jnp.concatenate(parts), (spec.cap, rw))
            # encode sorts rows by the key lanes (keys unique, so the
            # stable key-only sort is deterministic)
            m = _lex_sort_rows(m, kw)
            return jnp.concatenate([seg[:1], m.reshape(-1)])
        return kv_tf

    raise AssertionError(k)


def build_canon2(model, layout) -> Optional[Callable]:
    """Canonicalizer over encoded rows: vmapped fn(rows [N, W]) -> rows,
    each row replaced by the lexicographic minimum of its symmetry
    orbit. None when the model declares no (non-identity) symmetry.
    Raises CompileError when some lane encoding cannot be permuted."""
    from ..sem.symmetry import symmetry_group
    perms = symmetry_group(model)
    if not perms:
        return None
    # compile-time guard (advisor r2): canon_row unrolls one transform
    # per non-identity group element into EVERY jitted kernel.
    # Permutations of a 5-6 element set closes to 119-719 transforms —
    # an XLA compile explosion. Fall back to the unreduced search (the
    # caller reports the SYMMETRY warning) above the threshold.
    limit = int(os.environ.get("JAXMC_SYM_GROUP_LIMIT", "64"))
    if len(perms) > limit:
        raise CompileError(
            f"symmetry group has {len(perms)} non-identity elements "
            f"(> {limit}): device canonicalization would unroll that "
            f"many transforms into every kernel; falling back to the "
            f"unreduced search (set JAXMC_SYM_GROUP_LIMIT to raise)")

    row_tfs = []
    widths = [layout.specs[v].width for v in layout.vars]
    offs = np.cumsum([0] + widths)
    for pd in perms:
        tab = _value_table(pd, layout.uni)
        seg_tfs = [_seg_tf(layout.specs[v], pd, layout.uni, tab)
                   for v in layout.vars]
        if all(t is None for t in seg_tfs):
            continue  # permutation fixes every lane

        def row_tf(row, seg_tfs=seg_tfs):
            parts = []
            for i, t in enumerate(seg_tfs):
                seg = row[offs[i]:offs[i + 1]]
                parts.append(seg if t is None else t(seg))
            return jnp.concatenate(parts)
        row_tfs.append(row_tf)
    if not row_tfs:
        return None

    def lex_lt(a, b):
        # first differing lane decides; signed int32 order matches the
        # host-side encode ordering
        diff = a != b
        idx = jnp.argmax(diff)
        return jnp.any(diff) & (a[idx] < b[idx])

    def canon_row(row):
        best = row
        for tf in row_tfs:
            cand = tf(row)
            best = jnp.where(lex_lt(cand, best), cand, best)
        return best

    return jax.vmap(canon_row)
