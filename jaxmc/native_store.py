r"""ctypes binding for the native host fingerprint store (native/fps_store.cc).

Builds the shared library on first use with g++ (pybind11 is not in the
image; the C ABI + ctypes keeps the binding dependency-free). Falls back
cleanly when no toolchain exists: callers must check is_available().
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "fps_store.cc")
_SO = os.path.join(_REPO, "native", "build", "libjaxmc_fps.so")
_lock = threading.Lock()
_lib = None
_build_err: Optional[str] = None


def _load():
    global _lib, _build_err
    with _lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", _SRC, "-o", _SO],
                    check=True, capture_output=True, text=True)
            lib = ctypes.CDLL(_SO)
            lib.jaxmc_fps_create.restype = ctypes.c_void_p
            lib.jaxmc_fps_create_ex.restype = ctypes.c_void_p
            lib.jaxmc_fps_create_ex.argtypes = [ctypes.c_char_p,
                                                ctypes.c_uint64]
            lib.jaxmc_fps_destroy.argtypes = [ctypes.c_void_p]
            lib.jaxmc_fps_count.argtypes = [ctypes.c_void_p]
            lib.jaxmc_fps_count.restype = ctypes.c_uint64
            lib.jaxmc_fps_insert.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                ctypes.c_uint64,
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ]
            lib.jaxmc_fps_insert.restype = ctypes.c_uint64
            lib.jaxmc_fps_contains.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                ctypes.c_uint64,
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ]
            lib.jaxmc_fps_export.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
            ]
            lib.jaxmc_fps_import.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                ctypes.c_uint64,
            ]
            lib.jaxmc_fps_import.restype = ctypes.c_uint64
            _lib = lib
        except subprocess.CalledProcessError as ex:
            _build_err = f"{ex}; stderr: {ex.stderr}"
        except OSError as ex:
            _build_err = str(ex)
        return _lib


def is_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_err


class FingerprintStore:
    """128-bit fingerprint set in native memory: LSM-tiered sorted runs
    in mmap regions with background compaction (native/fps_store.cc).

    spill_dir (default: env JAXMC_FPS_SPILL_DIR) switches large runs to
    file-backed mmap so seen-sets beyond RAM page out to disk instead of
    OOM-killing the search — the MCraft_3s-scale prerequisite (SURVEY.md
    §7.5; VERDICT r4 #8). spill_threshold_bytes (env
    JAXMC_FPS_SPILL_MB, in MB) is the per-run size that triggers
    file backing."""

    def __init__(self, spill_dir: Optional[str] = None,
                 spill_threshold_bytes: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_build_err}")
        self._lib = lib
        if spill_dir is None:
            spill_dir = os.environ.get("JAXMC_FPS_SPILL_DIR", "")
        if not spill_threshold_bytes:
            mb = os.environ.get("JAXMC_FPS_SPILL_MB")
            spill_threshold_bytes = int(mb) << 20 if mb else 0
        self._h = lib.jaxmc_fps_create_ex(
            spill_dir.encode() if spill_dir else None,
            ctypes.c_uint64(spill_threshold_bytes))

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.jaxmc_fps_destroy(self._h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.jaxmc_fps_count(self._h))

    def insert(self, fps: np.ndarray) -> np.ndarray:
        """fps: [N, 4] int32 fingerprints (as produced by
        tpu.bfs.fingerprint128). Returns a bool mask of the rows that were
        new (first in-batch occurrence of a previously-unseen fingerprint);
        those rows are now members."""
        fps = np.ascontiguousarray(fps, dtype=np.int32)
        u = fps.view(np.uint32).astype(np.uint64)
        hi = np.ascontiguousarray((u[:, 0] << np.uint64(32)) | u[:, 1])
        lo = np.ascontiguousarray((u[:, 2] << np.uint64(32)) | u[:, 3])
        out = np.zeros(len(fps), dtype=np.uint8)
        rc = self._lib.jaxmc_fps_insert(self._h, hi, lo,
                                        np.uint64(len(fps)), out)
        if rc == 0xFFFFFFFFFFFFFFFF:
            raise MemoryError(
                "native fingerprint store could not allocate a run "
                "(set JAXMC_FPS_SPILL_DIR to a disk path for seen-sets "
                "beyond RAM)")
        return out.astype(bool)

    def contains(self, fps: np.ndarray) -> np.ndarray:
        """Membership probe: bool mask, True for EVERY row whose
        fingerprint is already in the store. Nothing is inserted —
        the device-POR ample check reads this before insert()."""
        fps = np.ascontiguousarray(fps, dtype=np.int32)
        u = fps.view(np.uint32).astype(np.uint64)
        hi = np.ascontiguousarray((u[:, 0] << np.uint64(32)) | u[:, 1])
        lo = np.ascontiguousarray((u[:, 2] << np.uint64(32)) | u[:, 3])
        out = np.zeros(len(fps), dtype=np.uint8)
        self._lib.jaxmc_fps_contains(self._h, hi, lo,
                                     np.uint64(len(fps)), out)
        return out.astype(bool)

    def dump(self) -> np.ndarray:
        """Serialize the store: sorted [N, 2] uint64 (hi, lo) rows —
        the checkpoint surface (SURVEY.md §5 checkpoint/resume)."""
        n = len(self)
        hi = np.zeros(n, dtype=np.uint64)
        lo = np.zeros(n, dtype=np.uint64)
        self._lib.jaxmc_fps_export(self._h, hi, lo)
        return np.stack([hi, lo], axis=1)

    def load(self, arr: np.ndarray) -> None:
        """Replace the contents with a dump() array (sorted, unique)."""
        arr = np.ascontiguousarray(arr, dtype=np.uint64)
        hi = np.ascontiguousarray(arr[:, 0])
        lo = np.ascontiguousarray(arr[:, 1])
        ok = self._lib.jaxmc_fps_import(self._h, hi, lo,
                                        np.uint64(len(arr)))
        if not ok:
            raise ValueError("fingerprint import rejected: rows are not "
                             "sorted-unique (corrupt checkpoint?)")
