r"""Per-arm compilability prediction (ISSUE 9 tentpole, consumer 2).

`kernel2.compile_action2` discovers an arm's uncompilability at forced-
trace time — after grounding and (for recursive operators) after an
exponentially expensive unroll attempt.  This module recasts the
CompileError taxonomy as a syntactic/type scan over the arm's AST so
`tpu/bfs.py` can skip the doomed build outright, generalizing the corpus
manifest's measured `pin_interp_arms` pins to derived ones.

Prediction policy — a verdict is issued ONLY when the build is certain
to demote, and its reason string is EXACTLY what the build-time path
would report (the message constants live in compile/kernel2.py; the
satellite test pins predicted == built wording):

  * a construct outside the compilable subset (today: SUBSET of a
    state-dependent set) in an eagerly-evaluated position of an item
    while the action is still DEFINITELY enabled (`enabled is True` at
    trace time — before any state-dependent guard), where
    compile_action2 re-raises instead of recovering;
  * a RECURSIVE operator applied to state-dependent arguments anywhere
    reachable from the arm — UnrollLimitError is deliberately
    non-recoverable at every recovery site, so position does not matter.

Everything else returns no verdict and the build proceeds exactly as
before: a false negative costs one build attempt (today's behavior), a
false positive would wrongly demote a compilable arm — so the scan stays
narrow and stops at every lazily-recovered position (IF/CASE branches,
conjunction/disjunction operands, quantifier bodies).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Set, Tuple

from ..front import tla_ast as A

# eagerly-evaluated builtin operators: a CompileError inside their
# argument evaluation propagates to the enclosing item (no recovery)
_LAZY_OPS = {"/\\", "\\/", "=>", "<=>", "~", "\\lnot"}


def _op_unroll_limit() -> int:
    return int(os.environ.get("JAXMC_OP_UNROLL_LIMIT", "64"))


class _StateRefs:
    """Transitive does-this-expression-reference-state oracle."""

    def __init__(self, model):
        self.vars = set(model.vars)
        self.defs = model.defs
        self._memo: Dict[str, bool] = {}

    def expr(self, e: A.Node, shadow: Set[str] = frozenset()) -> bool:
        if isinstance(e, A.Ident):
            if e.name in shadow:
                return False
            if e.name in self.vars:
                return True
            return self._def(e.name)
        if isinstance(e, A.Prime):
            return True
        if isinstance(e, A.OpApp):
            if e.name not in shadow and \
                    (e.name in self.vars or self._def(e.name)):
                return True
            return any(self.expr(a, shadow) for a in e.args) or \
                any(any(self.expr(pa, shadow) for pa in pargs)
                    for _pn, pargs in e.path)
        shadow2 = shadow
        if isinstance(e, (A.Quant, A.SetFilter, A.SetMap, A.FnDef,
                          A.Choose, A.Lambda)):
            names: List[str] = []
            if isinstance(e, (A.SetFilter, A.Choose)):
                v = e.var
                names = list(v) if isinstance(v, tuple) else [v]
            elif isinstance(e, A.Lambda):
                names = list(e.params)
            else:
                for bnames, _s in e.binders:
                    names.extend(bnames)
            shadow2 = set(shadow) | set(names)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, A.Node):
                if self.expr(v, shadow2):
                    return True
            elif isinstance(v, tuple):
                if self._tuple(v, shadow2):
                    return True
        return False

    def _tuple(self, t, shadow) -> bool:
        for x in t:
            if isinstance(x, A.Node):
                if self.expr(x, shadow):
                    return True
            elif isinstance(x, tuple):
                if self._tuple(x, shadow):
                    return True
        return False

    def _def(self, name: str) -> bool:
        if name in self._memo:
            return self._memo[name]
        from ..sem.eval import OpClosure
        d = self.defs.get(name)
        if not isinstance(d, OpClosure):
            self._memo[name] = False
            return False
        self._memo[name] = False  # cycle-safe default while recursing
        body = d.body
        if isinstance(body, A.FnConstrDef):
            body = body.body
        res = self.expr(body, set(d.params))
        self._memo[name] = res
        return res


class _ArmScan:
    def __init__(self, model):
        self.model = model
        self.defs = model.defs
        self.vars = set(model.vars)
        self.refs = _StateRefs(model)
        self._nodes = 0

    # ---- fatal-construct scan over eager positions --------------------
    def fatal(self, e: A.Node, stack: Tuple[str, ...],
              local: Dict[str, Tuple]) -> Optional[Tuple[str, bool]]:
        """(reason, always_raises) when evaluating e is certain to raise
        a CompileError at trace time; None otherwise.  Descends ONLY
        eagerly-evaluated positions."""
        self._nodes += 1
        if self._nodes > 20000:
            return None
        from ..compile.kernel2 import (SUBSET_SYMBOLIC_MSG,
                                       unroll_limit_message)
        if isinstance(e, A.OpApp):
            name = e.name
            if e.path:
                return None
            if name == "SUBSET" and len(e.args) == 1:
                if self.refs.expr(e.args[0]):
                    return (SUBSET_SYMBOLIC_MSG, False)
                return None
            if name in _LAZY_OPS:
                return None
            # user-defined operator: expand through it
            d = local.get(name)
            body = params = None
            if d is not None:
                params, body = d
            else:
                from ..sem.eval import OpClosure
                dd = self.defs.get(name)
                if isinstance(dd, OpClosure) and \
                        not isinstance(dd.body, A.FnConstrDef):
                    params, body = dd.params, dd.body
            if body is not None and params is not None and \
                    len(params) == len(e.args):
                if name in stack:
                    # recursion: diverges at trace time iff it runs on
                    # symbolic data — UnrollLimitError re-raises through
                    # every recovery site, so this verdict is positional
                    # ly unconditional
                    if any(self.refs.expr(a) for a in e.args):
                        return (unroll_limit_message(
                            name, _op_unroll_limit()), True)
                    return None
                if len(stack) > 48:
                    return None
                from ..front.subst import subst
                try:
                    body2 = subst(body, dict(zip(params, e.args)))
                except Exception:
                    return None
                return self.fatal(body2, stack + (name,), local)
            # builtin with eager argument evaluation
            for a in e.args:
                r = self.fatal(a, stack, local)
                if r is not None:
                    return r
            return None
        if isinstance(e, A.Ident):
            d = local.get(e.name)
            if d is not None and not d[0]:
                return self.fatal(d[1], stack, local)
            from ..sem.eval import OpClosure
            dd = self.defs.get(e.name)
            if isinstance(dd, OpClosure) and not dd.params and \
                    e.name not in self.vars and \
                    not isinstance(dd.body, A.FnConstrDef):
                if e.name in stack or len(stack) > 48:
                    return None
                return self.fatal(dd.body, stack + (e.name,), local)
            return None
        if isinstance(e, A.FnApp):
            r = self.fatal(e.fn, stack, local)
            if r is not None:
                return r
            for a in e.args:
                r = self.fatal(a, stack, local)
                if r is not None:
                    return r
            return None
        if isinstance(e, A.Dot):
            return self.fatal(e.expr, stack, local)
        if isinstance(e, A.Prime):
            return self.fatal(e.expr, stack, local)
        if isinstance(e, (A.TupleExpr, A.SetEnum)):
            for x in e.items:
                r = self.fatal(x, stack, local)
                if r is not None:
                    return r
            return None
        if isinstance(e, A.RecordExpr):
            for _k, v in e.fields:
                r = self.fatal(v, stack, local)
                if r is not None:
                    return r
            return None
        if isinstance(e, A.Except):
            return self.fatal(e.fn, stack, local)
        if isinstance(e, A.Quant):
            # ISSUE 15 taxonomy: a quantifier whose binder has NO
            # domain, or whose domain is an infinite constant set,
            # is certain to raise at trace time (kernel2's
            # _binder_combos / set_elements) — the predictor names it
            # with the build-time constant.  Bounded-finite domains are
            # compilable: do not descend (binder scoping unmodelled)
            from ..compile.kernel2 import (UNBOUNDED_QUANTIFIER_MSG,
                                           cannot_enumerate_message)
            for _names, dom in e.binders:
                if dom is None:
                    return (UNBOUNDED_QUANTIFIER_MSG, False)
                iv = self._static_infinite(dom, local)
                if iv is not None:
                    return (cannot_enumerate_message(iv), False)
            return None
        # IF/CASE/LET/filters: lazily recovered or scoped — never
        # predict through them
        return None

    def _static_infinite(self, dom: A.Node, local):
        """The InfiniteSet a domain expression statically denotes, or
        None.  Only Ident / zero-arg applications resolved through the
        defs table are claimed — anything else might be finite."""
        from ..sem.values import InfiniteSet
        name = None
        if isinstance(dom, A.Ident):
            name = dom.name
        elif isinstance(dom, A.OpApp) and not dom.args and not dom.path:
            name = dom.name
        if name is None or name in local or name in self.vars:
            return None
        d = self.defs.get(name)
        return d if isinstance(d, InfiniteSet) else None

    # ---- arm-item walk ------------------------------------------------
    def scan_arm(self, arm) -> Optional[str]:
        # arm.bound holds static VALUE bindings (split_arms' \E
        # instantiation) — opaque and non-fatal, so they need no entry
        state = {"enabled": True, "assigned": set(), "stop": False,
                 "verdict": None}
        self._walk_items(arm.expr, {}, state, ())
        return state["verdict"]

    def _walk_items(self, e: A.Node, local: Dict[str, Tuple], state,
                    stack: Tuple[str, ...]) -> None:
        if state["stop"] or state["verdict"] is not None:
            return
        from ..sem.eval import OpClosure
        if isinstance(e, A.OpApp):
            name = e.name
            if name == "/\\":
                self._walk_items(e.args[0], local, state, stack)
                self._walk_items(e.args[1], local, state, stack)
                return
            if name == "=":
                tgt = e.args[0]
                if isinstance(tgt, A.Prime) and \
                        isinstance(tgt.expr, A.Ident) and \
                        tgt.expr.name in self.vars:
                    var, rhs = tgt.expr.name, e.args[1]
                    r = self.fatal(rhs, stack, local)
                    if r is not None and (state["enabled"] or r[1]):
                        state["verdict"] = r[0]
                        return
                    if var in state["assigned"]:
                        # second assignment compiles to an equality
                        # filter on traced lanes: enabled goes symbolic
                        state["enabled"] = False
                    state["assigned"].add(var)
                    return
                self._guard(e, local, state, stack)
                return
            if name == "\\in":
                tgt = e.args[0]
                if isinstance(tgt, A.Prime) and \
                        isinstance(tgt.expr, A.Ident) and \
                        tgt.expr.name in self.vars:
                    r = self.fatal(e.args[1], stack, local)
                    if r is not None and (state["enabled"] or r[1]):
                        state["verdict"] = r[0]
                        return
                    state["assigned"].add(tgt.expr.name)
                    state["enabled"] = False  # slot/member guards
                    return
                self._guard(e, local, state, stack)
                return
            # user operator expansion (the action-family case)
            d = local.get(name)
            if d is not None and d[0] is not None and \
                    len(d[0]) == len(e.args):
                from ..front.subst import subst
                try:
                    body = subst(d[1], dict(zip(d[0], e.args)))
                except Exception:
                    state["stop"] = True
                    return
                self._walk_items(body, local, state, stack)
                return
            dd = self.defs.get(name)
            if isinstance(dd, OpClosure) and dd.params and \
                    len(dd.params) == len(e.args) and \
                    not isinstance(dd.body, A.FnConstrDef):
                if name in stack or len(stack) > 24:
                    state["stop"] = True
                    return
                from ..front.subst import subst
                try:
                    body = subst(dd.body, dict(zip(dd.params, e.args)))
                except Exception:
                    state["stop"] = True
                    return
                self._walk_items(body, local, state, stack + (name,))
                return
            self._guard(e, local, state, stack)
            return
        if isinstance(e, A.Ident):
            dd = self.defs.get(e.name)
            if isinstance(dd, OpClosure) and not dd.params and \
                    e.name not in self.vars and \
                    not isinstance(dd.body, A.FnConstrDef):
                if e.name in stack or len(stack) > 24:
                    state["stop"] = True
                    return
                self._walk_items(dd.body, local, state,
                                 stack + (e.name,))
                return
            self._guard(e, local, state, stack)
            return
        if isinstance(e, A.Unchanged):
            return
        if isinstance(e, A.Quant) and e.kind == "E":
            from ..compile.ground import DYN_NESTED_MSG, DYN_SHAPE_MSG
            # a binder domain that IS a state variable certainly
            # raises in ground's static iter_binders, forcing the
            # dynamic slot path — the certainty the shape verdicts
            # below need (ISSUE 15: unsized dynamic \E axes).  Ground
            # failures demote the whole arm regardless of position, so
            # these verdicts ignore `enabled`.
            certain_dynamic = any(
                isinstance(sexpr, A.Ident) and sexpr.name in self.vars
                for _names, sexpr in e.binders if sexpr is not None)
            slot_ok = (len(e.binders) == 1
                       and len(e.binders[0][0]) == 1
                       and isinstance(e.binders[0][0][0], str))
            if certain_dynamic and not slot_ok:
                state["verdict"] = DYN_SHAPE_MSG
                return
            if certain_dynamic and state.get("dyn_slot"):
                state["verdict"] = DYN_NESTED_MSG
                return
            for _names, sexpr in e.binders:
                if sexpr is None:
                    state["stop"] = True
                    return
                r = self.fatal(sexpr, stack, local)
                if r is not None and (state["enabled"] or r[1]):
                    state["verdict"] = r[0]
                    return
                if self.refs.expr(sexpr):
                    # dynamic \E: slot guards make `enabled` symbolic
                    # before any item runs
                    state["enabled"] = False
            if certain_dynamic:
                state["dyn_slot"] = True
            self._walk_items(e.body, local, state, stack)
            return
        if isinstance(e, A.Let):
            local2 = dict(local)
            for d in e.defs:
                if isinstance(d, A.OpDef):
                    local2[d.name] = (d.params, d.body)
                else:
                    state["stop"] = True
                    return
            self._walk_items(e.body, local2, state, stack)
            return
        if isinstance(e, A.Bool):
            if not e.val:
                state["stop"] = True
            return
        # disjunction / IF / CASE / anything else structural: the
        # compile path through these has recovery we do not model
        if isinstance(e, (A.If, A.Case, A.BoxAction)):
            state["stop"] = True
            return
        self._guard(e, local, state, stack)

    def _guard(self, e: A.Node, local, state, stack) -> None:
        r = self.fatal(e, stack, local)
        if r is not None and (state["enabled"] or r[1]):
            state["verdict"] = r[0]
            return
        if self.refs.expr(e):
            state["enabled"] = False
        # a static guard evaluates to a python bool at trace time and
        # leaves `enabled is True` intact (or kills the arm — either
        # way no new verdict can be wrong, so keep scanning)


def predict_arm_demotions(model, arms) -> Dict[int, str]:
    """arm index -> build-time demotion reason, for arms the scan is
    CERTAIN compile_action2 would demote.  Reasons use kernel2's own
    message constants so the predicted and built wording is identical."""
    out: Dict[int, str] = {}
    try:
        scan = _ArmScan(model)
        for i, arm in enumerate(arms):
            try:
                v = scan.scan_arm(arm)
            except RecursionError:
                v = None
            if v is not None:
                out[i] = v
    except Exception:
        if os.environ.get("JAXMC_DEBUG"):
            raise
        return {}
    return out
