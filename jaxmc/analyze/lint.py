r"""The corpus linter (ISSUE 9 tentpole, consumer 3).

Pure parse-level diagnostics over a (spec, cfg) pair — no search, no
kernel build — each with a STABLE code, a severity, and a source
location:

  JMC100 error    spec/cfg does not parse (or a module is missing)
  JMC101 error    cfg names an undefined definition (INIT/NEXT/
                  SPECIFICATION/INVARIANT/PROPERTY/CONSTRAINT/
                  ACTION-CONSTRAINT/SYMMETRY/VIEW)
  JMC102 error    declared CONSTANT never assigned by the cfg
  JMC103 warning  cfg assigns a name that is not a declared CONSTANT
  JMC104 error    cfg substitution `c <- D` where D is undefined
  JMC201 warning  declared VARIABLE never referenced by any definition
  JMC202 warning  statically dead action: its guard is false in every
                  reachable state (interval analysis, analyze/bounds.py)
  JMC203 warning  symmetry-soundness hazard: a symmetry-set constant
                  (or an element bound from it) used in an
                  order-sensitive position (CHOOSE / < <= > >= ..)
  JMC301 info     definition never used (unreachable from the checked
                  cfg entrypoints)
  JMC302 info     declared CONSTANT never used

Severity is the triage contract: `check --analyze=strict` (and the
serve daemon's submit gate) fail on ERRORS; warnings and infos print
but never block.  `make lint-corpus` additionally fails on warnings in
the repo corpus unless the manifest carries an explicit waiver
(corpus.py Case.lint_waive).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from ..front import tla_ast as A

_SEV_RANK = {"error": 2, "warning": 1, "info": 0}


@dataclass
class Diagnostic:
    code: str
    severity: str  # "error" | "warning" | "info"
    message: str
    path: Optional[str] = None
    line: Optional[int] = None

    def render(self) -> str:
        loc = ""
        if self.path:
            loc = os.path.basename(self.path)
            if self.line:
                loc += f":{self.line}"
            loc += ": "
        return f"{loc}{self.code} {self.severity}: {self.message}"


def max_severity(diags: List[Diagnostic]) -> Optional[str]:
    if not diags:
        return None
    return max(diags, key=lambda d: _SEV_RANK[d.severity]).severity


def errors(diags: List[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == "error"]


# ------------------------------------------------------------------ utils

def _locate(src: str, name: str, defn: bool = False) -> Optional[int]:
    """1-based line of `name` in source text; with defn=True prefer its
    definition/declaration site (`Name ==` / `Name(..) ==`)."""
    if not src:
        return None
    if defn:
        pat = re.compile(r"^\s*(?:LOCAL\s+)?" + re.escape(name)
                         + r"\s*(?:\(|\[|==)", re.M)
        m = pat.search(src)
        if m:
            return src.count("\n", 0, m.start()) + 1
    m = re.search(r"\b" + re.escape(name) + r"\b", src)
    if m:
        return src.count("\n", 0, m.start()) + 1
    return None


def _ast_refs(e: Any, out: Set[str]) -> None:
    """Every identifier/operator name referenced under e (including
    binder names — an over-approximation that keeps 'unused' lints
    conservative)."""
    if isinstance(e, A.Ident):
        out.add(e.name)
    elif isinstance(e, A.OpApp):
        out.add(e.name)
        for pn, pargs in e.path:
            out.add(pn)
            for pa in pargs:
                _ast_refs(pa, out)
    if isinstance(e, A.Node):
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, A.Node):
                _ast_refs(v, out)
            elif isinstance(v, tuple):
                _tuple_refs(v, out)
    elif isinstance(e, tuple):
        _tuple_refs(e, out)


def _tuple_refs(t: tuple, out: Set[str]) -> None:
    for x in t:
        if isinstance(x, (A.Node, tuple)):
            _ast_refs(x, out)
        elif isinstance(x, str):
            # Except paths carry ('dot', name) items; harmless extras
            continue


# ------------------------------------------------------------------ lint


def lint_pair(spec_path: str, cfg_path: Optional[str],
              includes: Tuple[str, ...] = (),
              semantic: bool = True) -> List[Diagnostic]:
    """Lint one spec+cfg pair; never raises — every defect (including
    parse failures) comes back as a Diagnostic.  semantic=False skips
    the interval-analysis lints (dead actions, symmetry hazards) —
    they only ever produce warnings, so error-gating callers (the serve
    daemon's submit check) can stay parse-cheap."""
    from ..front.cfg import CfgError, ModelConfig, parse_cfg
    from ..sem.modules import Loader

    diags: List[Diagnostic] = []
    cfg_src = ""
    if cfg_path is None:
        guess = os.path.splitext(spec_path)[0] + ".cfg"
        cfg_path = guess if os.path.exists(guess) else None
    if cfg_path:
        try:
            with open(cfg_path, encoding="utf-8",
                      errors="replace") as fh:
                cfg_src = fh.read()
            cfg = parse_cfg(cfg_src)
        except (CfgError, OSError) as ex:
            return [Diagnostic("JMC100", "error",
                               f"cfg does not parse: {ex}",
                               path=cfg_path)]
    else:
        cfg = ModelConfig(specification="Spec")

    try:
        with open(spec_path, encoding="utf-8", errors="replace") as fh:
            spec_src = fh.read()
    except OSError as ex:
        return diags + [Diagnostic("JMC100", "error", str(ex),
                                   path=spec_path)]
    try:
        ldr = Loader([os.path.dirname(os.path.abspath(spec_path))]
                     + list(includes))
        mod = ldr.load_path(spec_path)
    except Exception as ex:  # LexError/ParseError/EvalError/IO
        return diags + [Diagnostic(
            "JMC100", "error",
            f"spec does not load: {type(ex).__name__}: {ex}",
            path=spec_path)]

    diags += _lint_cfg_refs(mod, cfg, cfg_path, cfg_src)
    diags += _lint_unused(mod, cfg, spec_path, spec_src, cfg_src)
    if semantic:
        diags += _lint_semantic(mod, cfg, spec_path, spec_src, diags)
    # a degenerate cfg can repeat one defect (INVARIANT { { {): one
    # diagnostic per distinct finding
    seen = set()
    uniq = []
    for d in diags:
        key = (d.code, d.message, d.path, d.line)
        if key not in seen:
            seen.add(key)
            uniq.append(d)
    uniq.sort(key=lambda d: (-_SEV_RANK[d.severity], d.code,
                             d.line or 0))
    return uniq


def _cfg_role_names(cfg) -> List[Tuple[str, str]]:
    out = []
    for role, nm in (("SPECIFICATION", cfg.specification),
                     ("INIT", cfg.init), ("NEXT", cfg.next),
                     ("SYMMETRY", cfg.symmetry), ("VIEW", cfg.view)):
        if nm:
            out.append((role, nm))
    for role, names in (("INVARIANT", cfg.invariants),
                        ("PROPERTY", cfg.properties),
                        ("CONSTRAINT", cfg.constraints),
                        ("ACTION-CONSTRAINT", cfg.action_constraints)):
        for nm in names:
            out.append((role, nm))
    return out


def _lint_cfg_refs(mod, cfg, cfg_path, cfg_src) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    declared = {n for n, _a in mod.constants}
    for role, nm in _cfg_role_names(cfg):
        if nm not in mod.defs:
            diags.append(Diagnostic(
                "JMC101", "error",
                f"cfg {role} names undefined definition {nm!r}",
                path=cfg_path, line=_locate(cfg_src, nm)))
    # declared constants that neither a cfg assignment, an override,
    # nor a module-level definition satisfies — bind_model would refuse
    for n in sorted(declared):
        if n not in cfg.constants and n not in cfg.overrides \
                and n not in mod.defs:
            diags.append(Diagnostic(
                "JMC102", "error",
                f"CONSTANT {n} is declared but never assigned by the "
                f"cfg", path=cfg_path,
                line=_locate(cfg_src, n) or 1))
    for n in sorted(cfg.constants):
        if n not in declared:
            diags.append(Diagnostic(
                "JMC103", "warning",
                f"cfg assigns {n}, which is not a declared CONSTANT",
                path=cfg_path, line=_locate(cfg_src, n)))
    for n, target in sorted(cfg.overrides.items()):
        if target not in mod.defs:
            diags.append(Diagnostic(
                "JMC104", "error",
                f"cfg substitutes {n} <- {target}, but {target} is "
                f"undefined", path=cfg_path,
                line=_locate(cfg_src, target)))
    return diags


def _reachable(mod, cfg) -> Tuple[Set[str], Set[str]]:
    """(reachable definition names, union of every name referenced from
    a reachable body / ASSUME)."""
    from ..sem.eval import OpClosure

    body_refs: Dict[str, Set[str]] = {}

    def refs_of(name: str) -> Set[str]:
        if name in body_refs:
            return body_refs[name]
        d = mod.defs.get(name)
        out: Set[str] = set()
        body_refs[name] = out
        if isinstance(d, OpClosure):
            _ast_refs(d.body, out)
        else:
            from ..sem.modules import InstanceNamespace
            if isinstance(d, InstanceNamespace):
                for _inner, expr in d.substs.items():
                    _ast_refs(expr, out)
        return out

    entries = [nm for _role, nm in _cfg_role_names(cfg)]
    entries += list(cfg.overrides.values())
    entries += [t for (_m, _c), t in cfg.scoped_overrides.items()]
    seen: Set[str] = set()
    refs_union: Set[str] = set()
    for a in mod.assumes:
        _ast_refs(a.expr, refs_union)
    stack = [e for e in entries if e in mod.defs]
    stack += [e for e in refs_union if e in mod.defs]
    seen.update(stack)
    while stack:
        nm = stack.pop()
        rs = refs_of(nm)
        refs_union |= rs
        for r in rs:
            if r in mod.defs and r not in seen:
                seen.add(r)
                stack.append(r)
    return seen, refs_union


def _lint_unused(mod, cfg, spec_path, spec_src,
                 cfg_src) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    reachable, refs_union = _reachable(mod, cfg)
    role_names = {nm for _r, nm in _cfg_role_names(cfg)}

    top_defs = [u for u in mod.ast.units
                if isinstance(u, (A.OpDef, A.FnConstrDef))]
    for u in top_defs:
        if u.name not in reachable and u.name not in role_names:
            diags.append(Diagnostic(
                "JMC301", "info",
                f"definition {u.name} is never used (unreachable from "
                f"the cfg entrypoints)", path=spec_path,
                line=_locate(spec_src, u.name, defn=True)))
    top_vars: List[str] = []
    top_consts: List[str] = []
    for u in mod.ast.units:
        if isinstance(u, A.Variables):
            top_vars.extend(u.names)
        elif isinstance(u, A.Constants):
            top_consts.extend(n for n, _a in u.names)
    for v in top_vars:
        if v not in refs_union:
            diags.append(Diagnostic(
                "JMC201", "warning",
                f"VARIABLE {v} is never used", path=spec_path,
                line=_locate(spec_src, v)))
    for c in top_consts:
        if c not in refs_union:
            diags.append(Diagnostic(
                "JMC302", "info",
                f"CONSTANT {c} is declared but never used",
                path=spec_path, line=_locate(spec_src, c)))
    return diags


def _sanitized_bind(mod, cfg):
    """bind_model with the already-reported cfg defects patched out, so
    the semantic lints (dead actions, symmetry hazards) still run on a
    broken-cfg fixture: undefined role names are dropped, unassigned
    constants get placeholder model values."""
    import copy
    from ..front.cfg import CfgModelValue
    from ..sem.modules import bind_model

    cfg2 = copy.deepcopy(cfg)
    for role in ("specification", "init", "next", "symmetry", "view"):
        nm = getattr(cfg2, role)
        if nm and nm not in mod.defs:
            setattr(cfg2, role, None)
    for role in ("invariants", "properties", "constraints",
                 "action_constraints"):
        setattr(cfg2, role,
                [nm for nm in getattr(cfg2, role) if nm in mod.defs])
    cfg2.overrides = {n: t for n, t in cfg2.overrides.items()
                      if t in mod.defs}
    for n, _a in mod.constants:
        if n not in cfg2.constants and n not in cfg2.overrides \
                and n not in mod.defs:
            cfg2.constants[n] = CfgModelValue(n)
    return bind_model(mod, cfg2)


_ORDER_OPS = {"<", "<=", "=<", "\\leq", ">", ">=", "\\geq", ".."}


def _lint_semantic(mod, cfg, spec_path, spec_src,
                   prior: List[Diagnostic]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    try:
        model = _sanitized_bind(mod, cfg)
    except Exception:
        return diags  # bind defects are already reported as errors
    # dead actions (JMC202) — interval analysis over the arm guards
    try:
        from ..compile.ground import split_arms
        from .bounds import dead_arms, infer_state_bounds
        arms = split_arms(model)
        report = infer_state_bounds(model)
        if report is not None:
            for _i, label in dead_arms(model, arms, report):
                diags.append(Diagnostic(
                    "JMC202", "warning",
                    f"action {label} is statically dead (its guard is "
                    f"false in every reachable state)", path=spec_path,
                    line=_locate(spec_src, label, defn=True)))
    except Exception:
        if os.environ.get("JAXMC_DEBUG"):
            raise
    # symmetry hazards (JMC203)
    try:
        diags += _lint_symmetry(mod, model, spec_path, spec_src)
    except Exception:
        if os.environ.get("JAXMC_DEBUG"):
            raise
    return diags


def _lint_symmetry(mod, model, spec_path, spec_src) -> List[Diagnostic]:
    if model.symmetry is None:
        return []
    from ..sem.eval import OpClosure
    sym_refs: Set[str] = set()
    _ast_refs(model.symmetry, sym_refs)
    declared = {n for n, _a in mod.constants}
    sym_consts = {n for n in sym_refs if n in declared
                  and isinstance(model.defs.get(n), frozenset)}
    if not sym_consts:
        return []
    reachable, _ = _reachable(mod, model.cfg)
    diags: List[Diagnostic] = []
    seen_sites: Set[Tuple[str, str]] = set()

    def refs_sym(e) -> bool:
        rs: Set[str] = set()
        _ast_refs(e, rs)
        return bool(rs & sym_consts)

    def scan(e, tainted: Set[str], where: str) -> None:
        if isinstance(e, A.Choose):
            if e.set is not None and refs_sym(e.set):
                key = (where, "CHOOSE")
                if key not in seen_sites:
                    seen_sites.add(key)
                    cs = sorted(sym_consts)[0]
                    diags.append(Diagnostic(
                        "JMC203", "warning",
                        f"{where}: CHOOSE over the symmetry set "
                        f"{cs} is order-sensitive — symmetry "
                        f"reduction may be unsound", path=spec_path,
                        line=_locate(spec_src, where, defn=True)))
        if isinstance(e, A.OpApp) and e.name in _ORDER_OPS:
            for a in e.args:
                if isinstance(a, A.Ident) and \
                        (a.name in sym_consts or a.name in tainted):
                    key = (where, e.name)
                    if key not in seen_sites:
                        seen_sites.add(key)
                        diags.append(Diagnostic(
                            "JMC203", "warning",
                            f"{where}: order-sensitive operator "
                            f"{e.name!r} applied to an element of the "
                            f"symmetry set ({a.name})", path=spec_path,
                            line=_locate(spec_src, where, defn=True)))
        t2 = tainted
        if isinstance(e, (A.Quant, A.SetFilter, A.SetMap, A.FnDef,
                          A.Choose)):
            names: List[str] = []
            sets: List[Any] = []
            if isinstance(e, (A.SetFilter, A.Choose)):
                v = e.var
                names = list(v) if isinstance(v, tuple) else [v]
                sets = [e.set] if getattr(e, "set", None) is not None \
                    else []
            else:
                for bnames, s in e.binders:
                    if s is not None and refs_sym(s):
                        names.extend(bnames)
                        sets.append(s)
            if names and any(s is not None and refs_sym(s)
                             for s in sets):
                t2 = set(tainted) | set(names)
        if isinstance(e, A.Node):
            for f in getattr(e, "__dataclass_fields__", {}):
                v = getattr(e, f)
                if isinstance(v, A.Node):
                    scan(v, t2, where)
                elif isinstance(v, tuple):
                    _scan_tuple(v, t2, where)

    def _scan_tuple(t, tainted, where):
        for x in t:
            if isinstance(x, A.Node):
                scan(x, tainted, where)
            elif isinstance(x, tuple):
                _scan_tuple(x, tainted, where)

    for nm in sorted(reachable):
        d = mod.defs.get(nm)
        if isinstance(d, OpClosure):
            scan(d.body, set(), nm)
    return diags
