r"""Python-side static analysis for jaxmc itself (ISSUE 9 satellite).

`make pylint` prefers ruff (rule selection in ruff.toml: pyflakes +
bugbear) when the host has it; this module is the container fallback —
a small stdlib-ast checker covering the two finding classes the
satellite gates on:

  JPY401  unused import (pyflakes F401)
  JPY841  local variable assigned but never used (pyflakes F841)

Conservative by construction: `__init__.py` re-exports, `__all__`
entries, underscore names, tuple-unpacking targets, and augmented /
annotated assignments are all exempt — a finding here is meant to be
FIXED, so false positives are worse than misses.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple


def _loads_in(tree: ast.AST) -> set:
    """Every name read anywhere under tree (Load context), plus names
    referenced by `global`/`nonlocal` declarations."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            out.add(node.id)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            out.update(node.names)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            # `x += 1` reads x even though the target ctx is Store
            out.add(node.target.id)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _all_strings(tree: ast.Module) -> set:
    """Names listed in a module-level __all__ literal."""
    out = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for el in ast.walk(node.value):
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            out.add(el.value)
    return out


def _check_imports(tree: ast.Module, path: str,
                   findings: List[str]) -> None:
    if os.path.basename(path) == "__init__.py":
        return  # re-export idiom: imported names ARE the public surface
    used = _loads_in(tree)
    used |= _all_strings(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                if name.startswith("_"):
                    continue
                if name not in used:
                    findings.append(
                        f"{path}:{node.lineno}: JPY401 unused import "
                        f"'{alias.asname or alias.name}'")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                if name.startswith("_"):
                    continue
                if name not in used:
                    findings.append(
                        f"{path}:{node.lineno}: JPY401 unused import "
                        f"'{name}' from {node.module or '.'}")


def _direct_assigns(fn: ast.AST) -> List[ast.Assign]:
    """Assign statements belonging to fn's own scope: the subtree minus
    nested FunctionDef/ClassDef bodies (those are other scopes)."""
    out: List[ast.Assign] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, ast.Assign):
                out.append(child)
            walk(child)

    walk(fn)
    return out


def _check_unused_locals(tree: ast.Module, path: str,
                         findings: List[str]) -> None:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        loads = _loads_in(fn)
        for node in _direct_assigns(fn):
            if len(node.targets) != 1 or \
                    not isinstance(node.targets[0], ast.Name):
                continue  # tuple unpacking / attributes: exempt
            name = node.targets[0].id
            if name.startswith("_") or name in loads:
                continue
            # a later read exists nowhere in the function: flag once
            findings.append(
                f"{path}:{node.lineno}: JPY841 local variable "
                f"'{name}' is assigned but never used")


def check_file(path: str) -> List[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError) as ex:
        return [f"{path}:1: JPY100 does not parse: {ex}"]
    findings: List[str] = []
    _check_imports(tree, path, findings)
    _check_unused_locals(tree, path, findings)
    return findings


def check_tree(root: str) -> Tuple[int, List[str]]:
    """(files checked, findings) over every .py under root."""
    findings: List[str] = []
    n = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                n += 1
                findings.extend(check_file(os.path.join(dirpath, fn)))
    return n, findings


def main(paths: List[str]) -> int:
    total = 0
    findings: List[str] = []
    for p in paths or ["jaxmc"]:
        if os.path.isdir(p):
            n, fs = check_tree(p)
            total += n
            findings.extend(fs)
        else:
            total += 1
            findings.extend(check_file(p))
    for f in findings:
        print(f)
    print(f"pylint (builtin): {total} files, {len(findings)} finding"
          f"{'s' if len(findings) != 1 else ''}", file=sys.stderr)
    return 1 if findings else 0
