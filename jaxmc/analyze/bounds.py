r"""Static bounds/type inference over the TLA+ AST (ISSUE 9 tentpole).

Abstract interpretation on an interval/type lattice: starting from the
cfg-bound CONSTANT values and Init's assignments, the analyzer walks the
next-state relation the way sem/enumerate.Walker does — conjunction
threads abstract assignments, disjunction joins, `v' = e` assigns an
abstract evaluation of e, `v' \in S` assigns S's element abstraction,
guards REFINE the pre-state intervals — and iterates to a fixpoint over
the transition relation, widening to ±inf when an interval keeps
growing.  The result is a per-variable summary interval covering every
integer scalar component the encoded value can hold.

Soundness contract (what compile/pack.py relies on): a variable's
summary must contain every int that can appear in ANY row the engines
encode — reachable states, their raw successors (CONSTRAINT-violating
candidates are fingerprinted before being discarded, so post-states are
NOT refined by constraints), and layout-sampler rows.  Anything the
abstract evaluator does not model precisely evaluates to TOP, and a
budget/branch-cap breach abandons the whole proof (returns no bounds)
rather than guessing.  Statically-proven lanes additionally keep the
runtime OV_PACK guard as a safety net — if a proof were ever wrong the
engine aborts exactly (naming the analyzer), never miscounts.

The same machinery answers the linter's dead-action question: an action
arm whose guards are definitely false under the fixpoint env can never
fire (analyze/lint.py JMC202).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..front import tla_ast as A
from ..sem.values import Fcn, InfiniteSet, ModelValue

# ---------------------------------------------------------------------------
# interval lattice
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Iv:
    """Integer interval; a None bound is ±infinity."""
    lo: Optional[int]
    hi: Optional[int]

    def join(self, o: "Iv") -> "Iv":
        lo = None if (self.lo is None or o.lo is None) \
            else min(self.lo, o.lo)
        hi = None if (self.hi is None or o.hi is None) \
            else max(self.hi, o.hi)
        return Iv(lo, hi)

    def bounded(self) -> bool:
        return self.lo is not None and self.hi is not None


TOP = Iv(None, None)


def _add(a, b):
    return None if a is None or b is None else a + b


def _neg(a):
    return None if a is None else -a


def iv_add(a: Iv, b: Iv) -> Iv:
    return Iv(_add(a.lo, b.lo), _add(a.hi, b.hi))


def iv_sub(a: Iv, b: Iv) -> Iv:
    return Iv(_add(a.lo, _neg(b.hi)), _add(a.hi, _neg(b.lo)))


def iv_mul(a: Iv, b: Iv) -> Iv:
    if not (a.bounded() and b.bounded()):
        return TOP
    cands = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
    return Iv(min(cands), max(cands))


def iv_div(a: Iv, b: Iv) -> Iv:
    # TLA \div on a positive divisor; anything else is TOP
    if not (a.bounded() and b.bounded()) or b.lo is None or b.lo < 1:
        return TOP
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            cands += [x // y, -((-x) // y)]  # floor and trunc variants
    return Iv(min(cands), max(cands))


def iv_mod(a: Iv, b: Iv) -> Iv:
    # TLA a % b with b > 0 always lands in [0, b-1]
    if b.lo is not None and b.lo >= 1:
        return Iv(0, None if b.hi is None else b.hi - 1)
    return TOP


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------
#
# AV = ("int", Iv)          integer scalar
#    | ("bool",)            boolean scalar
#    | ("enum", vals|None)  string / model value scalar; vals is the
#                           frozenset of every value it can hold (None:
#                           unknown / too many) — cardinalities feed
#                           state_space_estimate (ISSUE 15)
#    | ("set", elem|None)   set; elem abstracts every member (None: empty)
#    | ("seq", elem|None)   sequence/tuple
#    | ("fun", dom, rng)    function/record; dom/rng abstract keys/values
#    | ("rec", fields)      record with KNOWN string keys: fields is a
#                           sorted tuple of (key, AV) — per-key precision
#                           through Dot/EXCEPT (ISSUE 15); degrades to
#                           "fun" on any key mismatch
#    | ("blob", Iv)         opaque value whose int components lie in Iv
#
# summary(AV) -> Iv | None: every integer scalar component anywhere in
# the value (None = the value contains no ints).

AV = Tuple
INT_TOP = ("int", TOP)
BOOL = ("bool",)
ENUM = ("enum", None)
BLOB_TOP = ("blob", TOP)

_MAX_DEPTH = 8
# enum value-set tracking cap: past this many distinct scalar values the
# set degrades to None (unknown) — joins stay O(small)
_ENUM_MAX = 64
# record width cap for per-key tracking
_REC_MAX = 32


def _enum_join(a, b):
    if a is None or b is None:
        return None
    u = a | b
    return u if len(u) <= _ENUM_MAX else None


def summary(av: Optional[AV]) -> Optional[Iv]:
    if av is None:
        return TOP
    k = av[0]
    if k == "int":
        return av[1]
    if k in ("bool", "enum"):
        return None
    if k in ("set", "seq"):
        return summary(av[1]) if av[1] is not None else None
    if k == "fun":
        return _sum_join(summary(av[1]), summary(av[2]))
    if k == "rec":
        s = None
        for _k, v in av[1]:
            s = _sum_join(s, summary(v))
        return s
    if k == "blob":
        return av[1]
    return TOP


def _sum_join(a: Optional[Iv], b: Optional[Iv]) -> Optional[Iv]:
    if a is None:
        return b
    if b is None:
        return a
    return a.join(b)


def _rec_to_fun(av: AV) -> AV:
    """Degrade a per-key record to the keyless function abstraction."""
    rng = None
    keys = []
    for k, v in av[1]:
        keys.append(k)
        rng = join(rng, v)
    return ("fun", ("enum", frozenset(keys)),
            rng if rng is not None else BLOB_TOP)


def join(a: Optional[AV], b: Optional[AV], depth: int = 0) -> AV:
    if a is None:
        return b if b is not None else BLOB_TOP
    if b is None:
        return a
    if depth > _MAX_DEPTH:
        sa, sb = summary(a), summary(b)
        s = _sum_join(sa, sb)
        return ("blob", s) if s is not None else ENUM
    ka, kb = a[0], b[0]
    if ka == "rec" and kb == "rec":
        if tuple(k for k, _ in a[1]) == tuple(k for k, _ in b[1]):
            return ("rec", tuple(
                (k, join(v, w, depth + 1))
                for (k, v), (_k2, w) in zip(a[1], b[1])))
        return join(_rec_to_fun(a), _rec_to_fun(b), depth)
    if ka == "rec":
        return join(_rec_to_fun(a), b, depth)
    if kb == "rec":
        return join(a, _rec_to_fun(b), depth)
    if ka == kb:
        if ka == "int":
            return ("int", a[1].join(b[1]))
        if ka == "bool":
            return a
        if ka == "enum":
            return ("enum", _enum_join(a[1], b[1]))
        if ka in ("set", "seq"):
            if a[1] is None:
                return b
            if b[1] is None:
                return a
            return (ka, join(a[1], b[1], depth + 1))
        if ka == "fun":
            return ("fun", join(a[1], b[1], depth + 1),
                    join(a[2], b[2], depth + 1))
        if ka == "blob":
            return ("blob", a[1].join(b[1]))
    s = _sum_join(summary(a), summary(b))
    return ("blob", s) if s is not None else ENUM


def widen(new: AV, old: AV, depth: int = 0) -> AV:
    """Widen `new` against the previous iterate `old`: any interval bound
    that moved goes to infinity (guarantees fixpoint termination)."""
    if depth > _MAX_DEPTH or new[0] != old[0]:
        s = summary(new)
        if s is None:
            return new
        so = summary(old)
        lo = s.lo if (so is not None and so.lo is not None
                      and s.lo is not None and s.lo >= so.lo) else None
        hi = s.hi if (so is not None and so.hi is not None
                      and s.hi is not None and s.hi <= so.hi) else None
        return ("blob", Iv(lo, hi))
    k = new[0]
    if k == "int" or k == "blob":
        ln, lo_ = new[1], old[1]
        wlo = ln.lo if (lo_.lo is not None and ln.lo is not None
                        and ln.lo >= lo_.lo) else None
        whi = ln.hi if (lo_.hi is not None and ln.hi is not None
                        and ln.hi <= lo_.hi) else None
        return (k, Iv(wlo, whi))
    if k == "bool":
        return new
    if k == "enum":
        # a still-growing value set widens to unknown (termination)
        if new[1] is not None and old[1] is not None \
                and new[1] <= old[1]:
            return new
        return ENUM
    if k in ("set", "seq"):
        if new[1] is None or old[1] is None:
            return new
        return (k, widen(new[1], old[1], depth + 1))
    if k == "fun":
        return ("fun", widen(new[1], old[1], depth + 1),
                widen(new[2], old[2], depth + 1))
    if k == "rec":
        if tuple(kk for kk, _ in new[1]) == \
                tuple(kk for kk, _ in old[1]):
            return ("rec", tuple(
                (kk, widen(v, w, depth + 1))
                for (kk, v), (_k2, w) in zip(new[1], old[1])))
        return widen(_rec_to_fun(new), _rec_to_fun(old), depth)
    return new


def lift_concrete(v: Any, depth: int = 0) -> AV:
    """Abstract a concrete interpreter value (cfg constants, def
    results)."""
    if isinstance(v, bool):
        return BOOL
    if isinstance(v, int):
        return ("int", Iv(v, v))
    if isinstance(v, (str, ModelValue)):
        return ("enum", frozenset((v,)))
    if isinstance(v, InfiniteSet):
        if v.kind == "Nat":
            return ("set", ("int", Iv(0, None)))
        if v.kind in ("Int", "Real"):
            return ("set", INT_TOP)
        if v.kind == "STRING":
            return ("set", ENUM)
        if v.kind == "Seq":
            return ("set", ("seq", lift_concrete(v.param, depth + 1)
                            if v.param is not None else BLOB_TOP))
        return BLOB_TOP
    if depth > _MAX_DEPTH:
        return BLOB_TOP
    if isinstance(v, frozenset):
        elem = None
        for x in list(v)[:4096]:
            elem = join(elem, lift_concrete(x, depth + 1), depth)
        return ("set", elem)
    if isinstance(v, Fcn):
        items = list(v.d.items())
        if items and len(items) <= _REC_MAX and \
                all(isinstance(k, str) for k, _ in items):
            return ("rec", tuple(
                (k, lift_concrete(val, depth + 1))
                for k, val in sorted(items)))
        dom = rng = None
        for k, val in items[:4096]:
            dom = join(dom, lift_concrete(k, depth + 1), depth)
            rng = join(rng, lift_concrete(val, depth + 1), depth)
        if dom is None:
            return ("seq", None)
        return ("fun", dom, rng if rng is not None else BLOB_TOP)
    return BLOB_TOP


def elem_opt(av: AV) -> Optional[AV]:
    """Abstract element of a set/sequence-like value; None for a
    definitely-empty container (the lattice bottom for elements)."""
    if av[0] in ("set", "seq"):
        return av[1]
    if av[0] == "blob":
        return av
    return BLOB_TOP


def elem_of(av: AV) -> AV:
    e = elem_opt(av)
    return e if e is not None else BLOB_TOP


def join_opt(a: Optional[AV], b: Optional[AV]) -> Optional[AV]:
    if a is None:
        return b
    if b is None:
        return a
    return join(a, b)


# ---------------------------------------------------------------------------
# abstract expression evaluation
# ---------------------------------------------------------------------------

_CMP_OPS = {"<", ">", "<=", ">=", "=<", "\\leq", "\\geq"}
_NORM = {"=<": "<=", "\\leq": "<=", "\\geq": ">=", "\\mod": "%", "#": "/="}


def _norm(name: str) -> str:
    return _NORM.get(name, name)


class _Bail(Exception):
    """Analysis abandoned (budget/branch cap/recursion) — no proof."""


class AbsEval:
    """Abstract evaluator + abstract transition walker for one model."""

    def __init__(self, model, budget_s: float = 5.0):
        self.model = model
        self.vars = tuple(model.vars)
        self.defs = model.defs
        self.budget_s = budget_s
        self.t0 = time.time()
        self.branch_cap = int(os.environ.get("JAXMC_ANALYZE_BRANCH_CAP",
                                             "768"))
        self._branches = 0
        self._const_cache: Dict[int, AV] = {}

    def _tick(self):
        if time.time() - self.t0 > self.budget_s:
            raise _Bail("analysis budget exceeded")

    # ---- expression evaluation ---------------------------------------
    def eval(self, e: A.Node, env: Dict[str, AV], bound: Dict[str, Any],
             primes: Dict[str, AV], stack: Tuple[str, ...] = ()) -> AV:
        self._tick()
        if isinstance(e, A.Num):
            return ("int", Iv(e.val, e.val))
        if isinstance(e, A.Bool):
            return BOOL
        if isinstance(e, A.Str):
            return ("enum", frozenset((e.val,)))
        if isinstance(e, A.Prime):
            if isinstance(e.expr, A.Ident) and e.expr.name in self.vars:
                return primes.get(e.expr.name, BLOB_TOP)
            return BLOB_TOP
        if isinstance(e, A.Ident):
            return self._ident(e.name, env, bound, primes, stack)
        if isinstance(e, A.OpApp):
            return self._opapp(e, env, bound, primes, stack)
        if isinstance(e, A.If):
            return join(self.eval(e.then, env, bound, primes, stack),
                        self.eval(e.els, env, bound, primes, stack))
        if isinstance(e, A.Case):
            out = None
            for _g, b in e.arms:
                out = join(out, self.eval(b, env, bound, primes, stack))
            if e.other is not None:
                out = join(out, self.eval(e.other, env, bound, primes,
                                          stack))
            return out if out is not None else BLOB_TOP
        if isinstance(e, A.TupleExpr):
            elem = None
            for x in e.items:
                elem = join(elem, self.eval(x, env, bound, primes, stack))
            return ("seq", elem)
        if isinstance(e, A.SetEnum):
            elem = None
            for x in e.items:
                elem = join(elem, self.eval(x, env, bound, primes, stack))
            return ("set", elem)
        if isinstance(e, A.SetFilter):
            return ("set", elem_opt(self.eval(e.set, env, bound, primes,
                                              stack)))
        if isinstance(e, A.SetMap):
            b2 = dict(bound)
            for names, sexpr in e.binders:
                ev = elem_of(self.eval(sexpr, env, bound, primes, stack))
                for nm in names:
                    b2[nm] = ev
            return ("set", self.eval(e.expr, env, b2, primes, stack))
        if isinstance(e, A.FnDef):
            b2 = dict(bound)
            dom = None
            for names, sexpr in e.binders:
                ev = elem_of(self.eval(sexpr, env, bound, primes, stack))
                dom = join(dom, ev)
                for nm in names:
                    b2[nm] = ev
            return ("fun", dom if dom is not None else BLOB_TOP,
                    self.eval(e.body, env, b2, primes, stack))
        if isinstance(e, A.FnSet):
            return ("set", ("fun",
                            elem_of(self.eval(e.dom, env, bound, primes,
                                              stack)),
                            elem_of(self.eval(e.rng, env, bound, primes,
                                              stack))))
        if isinstance(e, A.RecordExpr):
            # per-key record abstraction (ISSUE 15): each field keeps
            # its own AV so Dot/EXCEPT stay field-precise
            if 0 < len(e.fields) <= _REC_MAX:
                return ("rec", tuple(sorted(
                    ((k, self.eval(vex, env, bound, primes, stack))
                     for k, vex in e.fields),
                    key=lambda kv: kv[0])))
            rng = None
            for _k, vex in e.fields:
                rng = join(rng, self.eval(vex, env, bound, primes, stack))
            return ("fun", ENUM, rng if rng is not None else BLOB_TOP)
        if isinstance(e, A.RecordSet):
            rng = None
            for _k, sexpr in e.fields:
                rng = join(rng, elem_of(self.eval(sexpr, env, bound,
                                                  primes, stack)))
            return ("set", ("fun", ENUM,
                            rng if rng is not None else BLOB_TOP))
        if isinstance(e, A.FnApp):
            # applied-element fact (ISSUE 15): a guard like
            # `turns[p] + k =< MaxTurns` refined THIS application's
            # interval — the fact outranks the keyless rng join
            if isinstance(e.fn, A.Ident) and e.fn.name in self.vars \
                    and e.fn.name not in bound and len(e.args) == 1:
                fav = self._fact_lookup(e.fn.name, e.args[0], env, bound)
                if fav is not None:
                    return fav
            f = self.eval(e.fn, env, bound, primes, stack)
            if f[0] == "rec":
                return self._rec_app(f, e.args, env, bound, primes,
                                     stack)
            if f[0] == "fun":
                return f[2]
            if f[0] == "seq":
                return f[1] if f[1] is not None else BLOB_TOP
            if f[0] == "blob":
                return f
            return BLOB_TOP
        if isinstance(e, A.Dot):
            if isinstance(e.expr, A.Ident) and e.expr.name in self.vars \
                    and e.expr.name not in bound:
                fav = self._fact_lookup(e.expr.name, A.Str(e.fld),
                                        env, bound)
                if fav is not None:
                    return fav
            f = self.eval(e.expr, env, bound, primes, stack)
            if f[0] == "rec":
                d = dict(f[1])
                return d.get(e.fld, BLOB_TOP)
            if f[0] == "fun":
                return f[2]
            if f[0] == "blob":
                return f
            return BLOB_TOP
        if isinstance(e, A.Except):
            f = self.eval(e.fn, env, bound, primes, stack)
            fname = e.fn.name if (isinstance(e.fn, A.Ident)
                                  and e.fn.name in self.vars
                                  and e.fn.name not in bound) else None
            acc = f
            for ui, (path, rhs) in enumerate(e.updates):
                # the applied-element FACT describes the PRE-state
                # value: only the FIRST update may bind @ through it —
                # later updates read the already-updated function,
                # whose joined rng/field covers the new value
                at = self._path_at(acc, list(path), env, bound,
                                   fname if ui == 0 else None)
                rv = self.eval(rhs, env, dict(bound, **{"@": at}),
                               primes, stack)
                acc = self._path_update(acc, list(path), rv, env, bound)
            return acc
        if isinstance(e, A.At):
            at = bound.get("@")
            return at if at is not None else BLOB_TOP
        if isinstance(e, A.Quant):
            return BOOL
        if isinstance(e, A.Choose):
            if e.set is not None:
                return elem_of(self.eval(e.set, env, bound, primes,
                                         stack))
            return BLOB_TOP
        if isinstance(e, A.Let):
            b2 = dict(bound)
            for d in e.defs:
                if isinstance(d, A.OpDef):
                    b2[d.name] = ("$closure", d.params, d.body)
                elif isinstance(d, A.FnConstrDef):
                    b2[d.name] = BLOB_TOP
            return self.eval(e.body, env, b2, primes, stack)
        if isinstance(e, (A.Unchanged, A.Enabled, A.Fair, A.BoxAction,
                          A.AngleAction, A.TemporalQuant)):
            return BOOL
        return BLOB_TOP

    def _ident(self, name, env, bound, primes, stack) -> AV:
        if name in bound:
            v = bound[name]
            if isinstance(v, tuple) and v and v[0] == "$closure":
                if v[1]:
                    return BLOB_TOP
                return self.eval(v[2], env, bound, primes, stack)
            return v if isinstance(v, tuple) else lift_concrete(v)
        if name in self.vars and name in env:
            return env[name]
        d = self.defs.get(name)
        if d is None:
            return BLOB_TOP
        return self._def_value(name, d, env, bound, primes, stack)

    def _def_value(self, name, d, env, bound, primes, stack) -> AV:
        from ..sem.eval import OpClosure
        if isinstance(d, OpClosure):
            if d.params:
                return BLOB_TOP  # operator used as a value
            if name in stack or len(stack) > 48:
                return BLOB_TOP  # recursion/depth: no proof through it
            body = d.body
            if isinstance(body, A.FnConstrDef):
                return BLOB_TOP
            return self.eval(body, env, dict(d.bound), primes,
                             stack + (name,))
        if not callable(d):
            key = id(d)
            av = self._const_cache.get(key)
            if av is None:
                av = lift_concrete(d)
                self._const_cache[key] = av
            return av
        return BLOB_TOP

    def _opapp(self, e: A.OpApp, env, bound, primes, stack) -> AV:
        name = _norm(e.name)
        if e.path:
            return BLOB_TOP  # instance-qualified: unmodelled
        args = e.args
        if name in ("/\\", "\\/", "=>", "<=>", "~", "=", "/=", "\\in",
                    "\\notin", "\\subseteq", "\\supseteq"):
            return BOOL
        if name in _CMP_OPS:
            return BOOL
        if name in ("+", "-", "*", "\\div", "/", "%"):
            if name == "-" and len(args) == 1:
                a = self._as_iv(args[0], env, bound, primes, stack)
                return ("int", Iv(_neg(a.hi), _neg(a.lo)))
            a = self._as_iv(args[0], env, bound, primes, stack)
            b = self._as_iv(args[1], env, bound, primes, stack)
            if name == "+":
                return ("int", iv_add(a, b))
            if name == "-":
                return ("int", iv_sub(a, b))
            if name == "*":
                return ("int", iv_mul(a, b))
            if name == "%":
                return ("int", iv_mod(a, b))
            return ("int", iv_div(a, b))
        if name == "-." and len(args) == 1:
            a = self._as_iv(args[0], env, bound, primes, stack)
            return ("int", Iv(_neg(a.hi), _neg(a.lo)))
        if name == "..":
            a = self._as_iv(args[0], env, bound, primes, stack)
            b = self._as_iv(args[1], env, bound, primes, stack)
            return ("set", ("int", Iv(a.lo, b.hi)))
        if name in ("\\cup", "\\union"):
            return ("set", join_opt(
                elem_opt(self.eval(args[0], env, bound, primes, stack)),
                elem_opt(self.eval(args[1], env, bound, primes,
                                   stack))))
        if name in ("\\cap", "\\intersect", "\\"):
            return ("set", elem_opt(self.eval(args[0], env, bound,
                                              primes, stack)))
        if name in ("Cardinality", "Len"):
            return ("int", Iv(0, None))
        if name == "SUBSET":
            return ("set", ("set", elem_of(
                self.eval(args[0], env, bound, primes, stack))))
        if name == "UNION":
            return ("set", elem_of(elem_of(
                self.eval(args[0], env, bound, primes, stack))))
        if name == "DOMAIN":
            f = self.eval(args[0], env, bound, primes, stack)
            if f[0] == "rec":
                return ("set", ("enum",
                                frozenset(k for k, _ in f[1])))
            if f[0] == "fun":
                return ("set", f[1])
            if f[0] == "seq":
                return ("set", ("int", Iv(1, None)))
            return ("set", ("blob", summary(f) or Iv(0, 0))) \
                if summary(f) is not None else ("set", ENUM)
        if name == "Append":
            s = self.eval(args[0], env, bound, primes, stack)
            x = self.eval(args[1], env, bound, primes, stack)
            return ("seq", join_opt(elem_opt(s) if s[0] in ("seq", "set")
                                    else s, x))
        if name in ("Head", "Last"):
            return elem_of(self.eval(args[0], env, bound, primes, stack))
        if name in ("Tail", "SubSeq", "Front", "SelectSeq"):
            s = self.eval(args[0], env, bound, primes, stack)
            return s if s[0] == "seq" else ("seq", elem_of(s))
        if name == "\\o":
            return ("seq", join_opt(
                elem_opt(self.eval(args[0], env, bound, primes, stack)),
                elem_opt(self.eval(args[1], env, bound, primes,
                                   stack))))
        if name == "Seq":
            return ("set", ("seq", elem_of(
                self.eval(args[0], env, bound, primes, stack))))
        if name in ("Min", "Max"):
            a = self._as_iv(args[0], env, bound, primes, stack)
            b = self._as_iv(args[1], env, bound, primes, stack)
            return ("int", a.join(b))
        # user-defined operator application
        tgt = bound.get(name)
        if isinstance(tgt, tuple) and tgt and tgt[0] == "$closure":
            if len(tgt[1]) != len(args):
                return BLOB_TOP
            b2 = dict(bound)
            for p, aex in zip(tgt[1], args):
                b2[p] = self.eval(aex, env, bound, primes, stack)
            return self.eval(tgt[2], env, b2, primes, stack)
        from ..sem.eval import OpClosure
        d = self.defs.get(name)
        if isinstance(d, OpClosure) and d.params and \
                len(d.params) == len(args):
            if name in stack or len(stack) > 48:
                return BLOB_TOP
            b2 = dict(d.bound)
            for p, aex in zip(d.params, args):
                b2[p] = self.eval(aex, env, bound, primes, stack)
            if isinstance(d.body, A.FnConstrDef):
                return BLOB_TOP
            return self.eval(d.body, env, b2, primes, stack + (name,))
        return BLOB_TOP

    def _as_iv(self, e, env, bound, primes, stack) -> Iv:
        av = self.eval(e, env, bound, primes, stack)
        if av[0] == "int":
            return av[1]
        s = summary(av)
        return s if s is not None else TOP

    # ---- per-element precision helpers (ISSUE 15) --------------------

    def _rec_app(self, f: AV, args, env, bound, primes, stack) -> AV:
        """Apply a per-key record: a literal (or enum-valued) key picks
        its field(s); anything else joins every field."""
        d = dict(f[1])
        if len(args) == 1:
            a0 = args[0]
            if isinstance(a0, A.Str):
                return d.get(a0.val, BLOB_TOP)
            kv = self.eval(a0, env, bound, primes, stack)
            if kv[0] == "enum" and kv[1] is not None and \
                    all(isinstance(x, str) and x in d for x in kv[1]):
                out = None
                for x in kv[1]:
                    out = join(out, d[x])
                if out is not None:
                    return out
        out = None
        for _k, v in f[1]:
            out = join(out, v)
        return out if out is not None else BLOB_TOP

    def _fact_id(self, fname: str, idx, bound):
        """(env key, binding token) for the applied element f[idx].
        The token is the CURRENT binding object of an identifier index,
        compared by identity at lookup, so a rebound binder name can
        never resurrect a stale fact."""
        if isinstance(idx, A.Ident):
            return f"{fname}[{idx.name}]", bound.get(idx.name)
        if isinstance(idx, A.Num):
            return f"{fname}[{idx.val}]", None
        if isinstance(idx, A.Str):
            return f"{fname}[{idx.val!r}]", None
        return None, None

    def _fact_lookup(self, fname: str, idx, env, bound) -> Optional[AV]:
        key, tok = self._fact_id(fname, idx, bound)
        if key is None:
            return None
        f = env.get(key)
        if isinstance(f, tuple) and len(f) == 3 and f[0] == "$fact" \
                and f[1] is tok:
            return f[2]
        return None

    def _fact_store(self, env, fname: str, idx, bound, av: AV):
        """Returns env (a copy on write) with the applied-element fact
        recorded; the pre-state value of f[idx] lies in av for the rest
        of this branch (pre-state vars never change mid-branch)."""
        key, tok = self._fact_id(fname, idx, bound)
        if key is None:
            return env
        env = dict(env)
        env[key] = ("$fact", tok, av)
        return env

    def _step_into(self, cur: AV, kind: str, part, env, bound) -> AV:
        """Abstract value one EXCEPT-path step below `cur`."""
        if cur[0] == "rec":
            d = dict(cur[1])
            if kind == "dot":
                return d.get(part, BLOB_TOP)
            if kind == "idx" and len(part) == 1 and \
                    isinstance(part[0], A.Str):
                return d.get(part[0].val, BLOB_TOP)
            out = None
            for _k, v in cur[1]:
                out = join(out, v)
            return out if out is not None else BLOB_TOP
        if cur[0] == "fun":
            return cur[2]
        if cur[0] == "seq":
            return cur[1] if cur[1] is not None else BLOB_TOP
        if cur[0] == "blob":
            return cur
        return BLOB_TOP

    def _path_at(self, acc: AV, path, env, bound,
                 fname: Optional[str]) -> AV:
        """The value @ is bound to for one EXCEPT update: the element at
        the full path, consulting applied-element facts at the root."""
        cur = acc
        for i, (kind, part) in enumerate(path):
            if i == 0 and fname is not None:
                idx = None
                if kind == "idx" and len(part) == 1:
                    idx = part[0]
                elif kind == "dot":
                    idx = A.Str(part)
                if idx is not None:
                    fav = self._fact_lookup(fname, idx, env, bound)
                    if fav is not None:
                        cur = fav
                        continue
            cur = self._step_into(cur, kind, part, env, bound)
        return cur if cur is not None else BLOB_TOP

    def _path_update(self, acc: AV, path, rv: AV, env, bound) -> AV:
        """[acc EXCEPT !<path> = rv]: strong update on known record
        keys, weak (join) update everywhere else — always covers both
        the updated and the untouched elements."""
        if not path:
            return rv
        (kind, part), rest = path[0], path[1:]
        inner = self._step_into(acc, kind, part, env, bound)
        nv = self._path_update(inner, rest, rv, env, bound)
        if acc[0] == "rec":
            key = None
            if kind == "dot":
                key = part
            elif kind == "idx" and len(part) == 1 and \
                    isinstance(part[0], A.Str):
                key = part[0].val
            if key is not None and any(k == key for k, _ in acc[1]):
                return ("rec", tuple(
                    (k, nv if k == key else v) for k, v in acc[1]))
            return ("rec", tuple((k, join(v, nv)) for k, v in acc[1]))
        if acc[0] == "fun":
            return ("fun", acc[1], join(acc[2], nv))
        if acc[0] == "seq":
            return ("seq", join(acc[1], nv))
        s = _sum_join(summary(acc), summary(nv))
        return ("blob", s) if s is not None else acc

    # ---- guard refinement --------------------------------------------
    def refine(self, e: A.Node, env: Dict[str, AV],
               bound: Dict[str, Any]) -> Dict[str, AV]:
        """Return env refined by guard e holding (pre-state vars only);
        refinement is best-effort — returning env unchanged is sound."""
        if isinstance(e, A.OpApp):
            name = _norm(e.name)
            if name == "/\\":
                return self.refine(e.args[1],
                                   self.refine(e.args[0], env, bound),
                                   bound)
            if name in ("<", "<=", ">", ">=", "="):
                return self._refine_cmp(name, e.args[0], e.args[1], env,
                                        bound)
            if name == "\\in":
                x, s = e.args
                sv = self.eval(s, env, bound, {})
                el = elem_of(sv)
                if el[0] == "int" and (el[1].lo is not None
                                       or el[1].hi is not None):
                    env = self._clamp_expr(x, env, bound,
                                           lo=el[1].lo, hi=el[1].hi)
                return env
        if isinstance(e, A.Ident):
            from ..sem.eval import OpClosure
            d = self.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params \
                    and e.name not in self.vars:
                return self.refine(d.body, env, dict(d.bound))
        return env

    def _clamp_expr(self, ex, env, bound, lo=None, hi=None):
        """Refine a comparable LVALUE by [lo, hi] (either side None =
        unconstrained): a state-variable Ident narrows its env interval;
        a single-index function application `f[i]` or record field
        access `r.fld` on a state variable records an applied-element
        FACT (ISSUE 15) — the pre-state value of that element lies in
        the clamped interval for the rest of this branch.  Unrefinable
        shapes return env unchanged (always sound)."""
        if lo is None and hi is None:
            return env
        if isinstance(ex, A.Ident):
            var = ex.name
            if var in self.vars and var not in bound and var in env \
                    and env[var][0] == "int":
                cur = env[var][1]
                nlo = cur.lo if lo is None else \
                    (lo if cur.lo is None else max(cur.lo, lo))
                nhi = cur.hi if hi is None else \
                    (hi if cur.hi is None else min(cur.hi, hi))
                env = dict(env)
                env[var] = ("int", Iv(nlo, nhi))
            return env
        fname = idx = None
        if isinstance(ex, A.FnApp) and isinstance(ex.fn, A.Ident) \
                and ex.fn.name in self.vars \
                and ex.fn.name not in bound and len(ex.args) == 1:
            fname, idx = ex.fn.name, ex.args[0]
        elif isinstance(ex, A.Dot) and isinstance(ex.expr, A.Ident) \
                and ex.expr.name in self.vars \
                and ex.expr.name not in bound:
            fname, idx = ex.expr.name, A.Str(ex.fld)
        if fname is None:
            return env
        base = self._as_iv(ex, env, bound, {}, ())
        nlo = base.lo if lo is None else \
            (lo if base.lo is None else max(base.lo, lo))
        nhi = base.hi if hi is None else \
            (hi if base.hi is None else min(base.hi, hi))
        return self._fact_store(env, fname, idx, bound,
                                ("int", Iv(nlo, nhi)))

    def _is_lvalue(self, ex, bound) -> bool:
        """Can _clamp_expr refine this shape?  Cheap pre-test so the
        comparison refinement only pays an abstract evaluation of the
        OPPOSING side when there is something to clamp."""
        if isinstance(ex, A.Ident):
            return ex.name in self.vars and ex.name not in bound
        if isinstance(ex, A.FnApp):
            return (isinstance(ex.fn, A.Ident)
                    and ex.fn.name in self.vars
                    and ex.fn.name not in bound and len(ex.args) == 1)
        if isinstance(ex, A.Dot):
            return (isinstance(ex.expr, A.Ident)
                    and ex.expr.name in self.vars
                    and ex.expr.name not in bound)
        return False

    def _refine_cmp(self, op, l, r, env, bound) -> Dict[str, AV]:
        def clamp(ex, lo=None, hi=None):
            nonlocal env
            env = self._clamp_expr(ex, env, bound, lo=lo, hi=hi)

        def iv(e):
            return self._as_iv(e, env, bound, {}, ())

        # x op e  /  e op x  (x an Ident, f[i] or r.fld lvalue)
        if self._is_lvalue(l, bound):
            b = iv(r)
            if op == "<" and b.hi is not None:
                clamp(l, hi=b.hi - 1)
            elif op == "<=" and b.hi is not None:
                clamp(l, hi=b.hi)
            elif op == ">" and b.lo is not None:
                clamp(l, lo=b.lo + 1)
            elif op == ">=" and b.lo is not None:
                clamp(l, lo=b.lo)
            elif op == "=":
                clamp(l, lo=b.lo, hi=b.hi)
        if self._is_lvalue(r, bound):
            a = iv(l)
            if op == "<" and a.lo is not None:
                clamp(r, lo=a.lo + 1)
            elif op == "<=" and a.lo is not None:
                clamp(r, lo=a.lo)
            elif op == ">" and a.hi is not None:
                clamp(r, hi=a.hi - 1)
            elif op == ">=" and a.hi is not None:
                clamp(r, hi=a.hi)
            elif op == "=":
                clamp(r, lo=a.lo, hi=a.hi)

        # x + y <= c  (CONSTRAINT shape, constoy; EXCEPT-guard shape,
        # symtoy/raft): bound each refinable addend by c - other.lo
        def sum_shape(sumex, cex, op2):
            x1, x2 = sumex.args
            if not (self._is_lvalue(x1, bound)
                    or self._is_lvalue(x2, bound)):
                return
            c = iv(cex)
            if c.hi is None:
                return
            chi = c.hi - (1 if op2 == "<" else 0)
            for me, other in ((x1, x2), (x2, x1)):
                if not self._is_lvalue(me, bound):
                    continue
                o = iv(other)
                if o.lo is not None:
                    clamp(me, hi=chi - o.lo)

        if op in ("<", "<=") and isinstance(l, A.OpApp) \
                and _norm(l.name) == "+" and len(l.args) == 2:
            sum_shape(l, r, op)
        if op in (">", ">=") and isinstance(r, A.OpApp) \
                and _norm(r.name) == "+" and len(r.args) == 2:
            sum_shape(r, l, {">": "<", ">=": "<="}[op])
        return env

    # ---- abstract transition walker ----------------------------------
    def walk(self, e: A.Node, env: Dict[str, AV], bound: Dict[str, Any],
             partial: Dict[str, AV], mode: str,
             stack: Tuple[str, ...] = ()) -> List[Tuple[Dict[str, AV],
                                                        Dict[str, AV]]]:
        """Abstract mirror of sem/enumerate.Walker.walk: returns a list
        of (assignments, refined-env) branches.  A definitely-false
        guard kills its branch; everything unmodelled keeps the branch
        with TOP effects (sound)."""
        self._tick()
        self._branches += 1
        if self._branches > self.branch_cap:
            raise _Bail("branch cap exceeded")
        from ..sem.eval import OpClosure
        if isinstance(e, A.OpApp):
            name = _norm(e.name)
            if name == "/\\":
                out = []
                for p1, env1 in self.walk(e.args[0], env, bound, partial,
                                          mode, stack):
                    out.extend(self.walk(e.args[1], env1, bound, p1,
                                         mode, stack))
                return out
            if name == "\\/":
                out = []
                for arm in e.args:
                    out.extend(self.walk(arm, env, bound, dict(partial),
                                         mode, stack))
                return out
            if name == "=":
                tgt = self._target(e.args[0], mode, bound)
                if tgt is not None:
                    if tgt in partial:
                        return [(partial, env)]
                    rhs = self.eval(e.args[1], env, bound, partial,
                                    stack)
                    p2 = dict(partial)
                    p2[tgt] = rhs
                    return [(p2, env)]
            if name == "\\in":
                tgt = self._target(e.args[0], mode, bound)
                if tgt is not None:
                    if tgt in partial:
                        return [(partial, env)]
                    sv = self.eval(e.args[1], env, bound, partial, stack)
                    p2 = dict(partial)
                    p2[tgt] = elem_of(sv)
                    return [(p2, env)]
            # user operator expansion
            tgt_d = bound.get(name)
            if isinstance(tgt_d, tuple) and tgt_d and \
                    tgt_d[0] == "$closure":
                from ..front.subst import subst
                if len(tgt_d[1]) != len(e.args) or name in stack \
                        or len(stack) > 48:
                    return [(partial, env)]
                try:
                    body = subst(tgt_d[2], dict(zip(tgt_d[1], e.args)))
                except Exception:
                    return [(partial, env)]
                return self.walk(body, env, bound, partial, mode,
                                 stack + (name,))
            d = self.defs.get(name) if name not in bound else None
            if isinstance(d, OpClosure) and d.params and \
                    len(d.params) == len(e.args):
                if name in stack or len(stack) > 48:
                    return [(partial, env)]
                from ..front.subst import subst
                try:
                    body = subst(d.body, dict(zip(d.params, e.args)))
                except Exception:
                    return [(partial, env)]
                # call-by-name, like Walker: the substituted body carries
                # the CALLER's arg ASTs, so it walks under the caller's
                # binder env (module-level closures capture nothing)
                return self.walk(body, env, {**d.bound, **bound},
                                 partial, mode, stack + (name,))
        elif isinstance(e, A.Ident):
            d = bound.get(e.name)
            if isinstance(d, tuple) and d and d[0] == "$closure" \
                    and not d[1] and e.name not in stack \
                    and len(stack) <= 48:
                return self.walk(d[2], env, bound, partial, mode,
                                 stack + (e.name,))
            if not (isinstance(d, tuple) and d) and e.name not in bound:
                dd = self.defs.get(e.name)
                from ..sem.eval import OpClosure as OC
                if isinstance(dd, OC) and not dd.params \
                        and e.name not in self.vars \
                        and e.name not in stack and len(stack) <= 48:
                    return self.walk(dd.body, env,
                                     {**bound, **dd.bound},
                                     partial, mode,
                                     stack + (e.name,))
        elif isinstance(e, A.Quant):
            if e.kind == "E":
                b2 = dict(bound)
                for names, sexpr in e.binders:
                    if sexpr is None:
                        for nm in names:
                            b2[nm] = BLOB_TOP
                        continue
                    ev = elem_of(self.eval(sexpr, env, bound, partial,
                                           stack))
                    for nm in names:
                        b2[nm] = ev
                return self.walk(e.body, env, b2, dict(partial), mode,
                                 stack)
            # \A as a guard: fall through
        elif isinstance(e, A.If):
            out = self.walk(e.then, env, bound, dict(partial), mode,
                            stack)
            out += self.walk(e.els, env, bound, dict(partial), mode,
                             stack)
            return out
        elif isinstance(e, A.Case):
            out = []
            for _g, b in e.arms:
                out += self.walk(b, env, bound, dict(partial), mode,
                                 stack)
            if e.other is not None:
                out += self.walk(e.other, env, bound, dict(partial),
                                 mode, stack)
            return out
        elif isinstance(e, A.Let):
            b2 = dict(bound)
            for d in e.defs:
                if isinstance(d, A.OpDef):
                    b2[d.name] = ("$closure", d.params, d.body)
                elif isinstance(d, A.FnConstrDef):
                    b2[d.name] = BLOB_TOP
            return self.walk(e.body, env, b2, partial, mode, stack)
        elif isinstance(e, A.Unchanged):
            p2 = dict(partial)
            self._unchanged(e.expr, env, bound, p2)
            return [(p2, env)]
        elif isinstance(e, A.BoxAction):
            out = self.walk(e.action, env, bound, dict(partial), mode,
                            stack)
            p2 = dict(partial)
            self._unchanged(e.sub, env, bound, p2)
            out.append((p2, env))
            return out
        elif isinstance(e, A.Bool):
            return [(partial, env)] if e.val else []
        # default: boolean guard — kill the branch only when DEFINITELY
        # false, refine the env otherwise
        verdict = self.guard_verdict(e, env, bound, partial, stack)
        if verdict is False:
            return []
        return [(partial, self.refine(e, env, bound))]

    def _target(self, e, mode, bound) -> Optional[str]:
        if mode == "next":
            if isinstance(e, A.Prime) and isinstance(e.expr, A.Ident) \
                    and e.expr.name in self.vars:
                return e.expr.name
            return None
        if isinstance(e, A.Ident) and e.name in self.vars \
                and e.name not in bound:
            return e.name
        return None

    def _unchanged(self, e, env, bound, partial) -> None:
        from ..sem.eval import OpClosure
        if isinstance(e, A.Ident):
            if e.name in self.vars:
                if e.name not in partial:
                    partial[e.name] = env.get(e.name, BLOB_TOP)
                return
            d = self.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params:
                self._unchanged(d.body, env, bound, partial)
            return
        if isinstance(e, A.TupleExpr):
            for x in e.items:
                self._unchanged(x, env, bound, partial)

    def guard_verdict(self, e, env, bound, primes,
                      stack=()) -> Optional[bool]:
        """True/False when the guard is decided under the abstract env,
        None when unknown.  Only interval-decidable comparisons are
        modelled — everything else is None (keep the branch)."""
        if isinstance(e, A.Bool):
            return e.val
        if not isinstance(e, A.OpApp):
            return None
        name = _norm(e.name)
        if name in ("<", "<=", ">", ">=") and len(e.args) == 2:
            a = self._as_iv(e.args[0], env, bound, primes, stack)
            b = self._as_iv(e.args[1], env, bound, primes, stack)
            if name in (">", ">="):
                a, b = b, a
                name = {"<": "<", ">": "<", ">=": "<=", "<=": "<="}[name]
            # now: a < b or a <= b
            if name == "<":
                if a.hi is not None and b.lo is not None \
                        and a.hi < b.lo:
                    return True
                if a.lo is not None and b.hi is not None \
                        and a.lo >= b.hi:
                    return False
            else:
                if a.hi is not None and b.lo is not None \
                        and a.hi <= b.lo:
                    return True
                if a.lo is not None and b.hi is not None \
                        and a.lo > b.hi:
                    return False
            return None
        if name == "/\\":
            va = self.guard_verdict(e.args[0], env, bound, primes, stack)
            vb = self.guard_verdict(e.args[1], env, bound, primes, stack)
            if va is False or vb is False:
                return False
            if va is True and vb is True:
                return True
            return None
        return None


# ---------------------------------------------------------------------------
# per-element proven bounds (ISSUE 15)
# ---------------------------------------------------------------------------


class EB:
    """Per-element PROVEN bounds for one variable — the structured shape
    compile/pack.py descends alongside the vspec tree, so a container's
    element lanes pack at their own proven widths instead of the
    whole-variable summary.

      all    (lo, hi) covering EVERY int component anywhere in the
             value (None: not fully bounded) — the sound fallback for
             any component without a more precise child bound
      dom    key-side bounds (fun/kvtable key lanes)
      rng    value-side bounds (fun/pfcn value lanes)
      elem   element bounds (seq/growset element lanes)
      keys   per-key bounds for record fields (str keys)
    """

    __slots__ = ("all", "dom", "rng", "elem", "keys")

    def __init__(self, all=None, dom=None, rng=None, elem=None,
                 keys=None):
        self.all = all
        self.dom = dom
        self.rng = rng
        self.elem = elem
        self.keys = keys

    def __repr__(self):
        parts = [f"all={self.all}"]
        for f in ("dom", "rng", "elem", "keys"):
            v = getattr(self, f)
            if v is not None:
                parts.append(f"{f}={v}")
        return "EB(" + ", ".join(parts) + ")"

    def empty(self) -> bool:
        return (self.all is None and self.dom is None
                and self.rng is None and self.elem is None
                and not self.keys)


def _fin(iv: Optional[Iv]) -> Optional[Tuple[int, int]]:
    if iv is None or not iv.bounded():
        return None
    if abs(iv.lo) >= 2 ** 31 or iv.hi >= 2 ** 31:
        return None
    return (int(iv.lo), int(iv.hi))


def av_to_eb(av: Optional[AV], depth: int = 0) -> Optional[EB]:
    """Structured proven bounds from a converged abstract value; None
    when nothing below this node is provably bounded (pack then falls
    back to structural/observed widths — never a wrong lane)."""
    if av is None or depth > _MAX_DEPTH:
        return None
    k = av[0]
    if k == "int":
        a = _fin(av[1])
        return EB(all=a) if a is not None else None
    if k in ("bool", "enum"):
        return None  # no int lanes below
    if k in ("set", "seq"):
        eb = EB(all=_fin(summary(av)),
                elem=av_to_eb(av[1], depth + 1) if av[1] is not None
                else None)
        return None if eb.empty() else eb
    if k == "fun":
        eb = EB(all=_fin(summary(av)), dom=av_to_eb(av[1], depth + 1),
                rng=av_to_eb(av[2], depth + 1))
        return None if eb.empty() else eb
    if k == "rec":
        keys = {kk: av_to_eb(v, depth + 1) for kk, v in av[1]}
        rng = None
        for _kk, v in av[1]:
            rng = join(rng, v)
        eb = EB(all=_fin(summary(av)), keys=keys,
                rng=av_to_eb(rng, depth + 1) if rng is not None
                else None)
        return None if eb.empty() else eb
    if k == "blob":
        a = _fin(av[1])
        return EB(all=a) if a is not None else None
    return None


# ---------------------------------------------------------------------------
# fixpoint driver
# ---------------------------------------------------------------------------


@dataclass
class BoundsReport:
    """The fixpoint result: per-variable abstract values + summaries."""
    env: Dict[str, AV]
    iterations: int
    converged: bool
    wall_s: float

    def summaries(self) -> Dict[str, Iv]:
        """var -> summary interval over every int component (only vars
        whose summary exists — vars with no int components are absent)."""
        out = {}
        for v, av in self.env.items():
            s = summary(av)
            if s is not None:
                out[v] = s
        return out

    def lane_bounds(self) -> Dict[str, Tuple[int, int]]:
        """var -> (lo, hi) for vars with a FINITE proven int summary —
        the shape compile/pack.py consumes as structural bounds.

        A truncated (non-converged) fixpoint proves NOTHING: its
        intervals only cover states reachable within max_iter abstract
        steps, so consuming them would mislabel correct values as
        analyzer bugs (OV_PACK) — no proofs in that case."""
        if not self.converged:
            return {}
        out = {}
        for v, s in self.summaries().items():
            if s.bounded() and abs(s.lo) < 2 ** 31 and s.hi < 2 ** 31:
                out[v] = (s.lo, s.hi)
        return out

    def element_bounds(self) -> Dict[str, "EB"]:
        """var -> structured per-element proven bounds (ISSUE 15): the
        richer shape compile/pack.py consumes — a variable appears as
        soon as ANY component below it proves, even when the whole-value
        summary does not (e.g. a bounded function range under an
        unbounded-count container).  Same truncation rule as
        lane_bounds: a non-converged fixpoint proves nothing."""
        if not self.converged:
            return {}
        out = {}
        for v, av in self.env.items():
            eb = av_to_eb(av)
            if eb is not None:
                out[v] = eb
        return out


def _join_env(a: Dict[str, AV], b: Dict[str, AV],
              vars_) -> Dict[str, AV]:
    return {v: join(a.get(v), b.get(v)) for v in vars_
            if v in a or v in b}


def infer_state_bounds(model, budget_s: Optional[float] = None
                       ) -> Optional[BoundsReport]:
    """Fixpoint interval inference for every state variable; returns
    None when the analysis bails (budget, branch explosion, internal
    error) — callers treat None as 'no proofs'."""
    t0 = time.time()
    if budget_s is None:
        budget_s = float(os.environ.get("JAXMC_ANALYZE_BUDGET", "5"))
    try:
        ae = AbsEval(model, budget_s=budget_s)
        # Init: abstract assignments from the initial predicate
        init_branches = ae.walk(model.init, {}, {}, {}, "init")
        env: Dict[str, AV] = {}
        for p, _e in init_branches:
            env = _join_env(env, p, model.vars)
        for v in model.vars:
            env.setdefault(v, BLOB_TOP)
        max_iter = int(os.environ.get("JAXMC_ANALYZE_MAX_ITER", "64"))
        widen_at = max(8, max_iter // 2)
        converged = False
        it = 0
        for it in range(1, max_iter + 1):
            ae._branches = 0
            pre = dict(env)
            # frontier states satisfy every CONSTRAINT; successors of
            # refined pre-states are NOT re-refined (candidate rows are
            # encoded before the constraint check discards them)
            for _nm, cexpr in model.constraints:
                pre = ae.refine(cexpr, pre, {})
            new = dict(env)
            for p, _e in ae.walk(model.next, pre, {}, {}, "next"):
                post = {v: p.get(v, BLOB_TOP) for v in model.vars}
                new = _join_env(new, post, model.vars)
            if it >= widen_at:
                new = {v: widen(new[v], env[v]) for v in model.vars}
            if new == env:
                converged = True
                break
            env = new
        return BoundsReport(env=env, iterations=it, converged=converged,
                            wall_s=time.time() - t0)
    except _Bail:
        return None
    except RecursionError:
        return None
    except Exception:
        # the analyzer must never break a build; no proof is always safe
        if os.environ.get("JAXMC_DEBUG"):
            raise
        return None


def dead_arms(model, arms, report: Optional[BoundsReport] = None
              ) -> List[Tuple[int, str]]:
    """Indices (+labels) of action arms that can NEVER fire: every
    abstract branch of the arm dies on a definitely-false guard under
    the fixpoint env.  Used by the linter (JMC202)."""
    if report is None:
        report = infer_state_bounds(model)
    if report is None or not report.converged:
        # a truncated fixpoint env is NOT an invariant: a guard that is
        # false under it may hold in deeper states — no dead verdicts
        return []
    out = []
    for i, arm in enumerate(arms):
        try:
            ae = AbsEval(model)
            env = dict(report.env)
            for _nm, cexpr in model.constraints:
                env = ae.refine(cexpr, env, {})
            branches = ae.walk(arm.expr, env, dict(arm.bound or {}), {},
                               "next")
            if not branches:
                out.append((i, arm.label or "Next"))
        except (_Bail, RecursionError):
            continue
        except Exception:
            if os.environ.get("JAXMC_DEBUG"):
                raise
            continue
    return out


# ---------------------------------------------------------------------------
# cross-model batch compatibility (ISSUE 13)
# ---------------------------------------------------------------------------
#
# The vmapped multi-model engine (backend/batch.py) shares ONE compiled
# kernel across layout-compatible models by LIFTING per-model CONSTANT
# values into traced batch-axis lanes (kernel2.KernelCtx.const_lanes).
# A constant is liftable only when every occurrence sits in a VALUE
# position — arithmetic, comparisons, boolean structure, IF/CASE arms,
# assignment right-hand sides — never in a position compilation needs
# statically (quantifier/set-constructor domains, `..` range endpoints,
# function application, container shapes).  The walk below is the
# conservative parse-time oracle; the kernel trace itself is the
# soundness net (a lifted constant reaching a static-only position
# raises CompileError, which the batch planner reads as "not
# batchable", never as a wrong kernel).

# boolean structure + comparisons + integer arithmetic: operand
# positions stay value-transparent (kernel2 evaluates them over traced
# lanes)
_LIFT_SAFE_OPS = frozenset({
    "/\\", "\\/", "~", "\\lnot", "\\neg", "=>", "<=>", "\\equiv",
    "=", "/=", "<", "<=", ">", ">=",
    "+", "-", "*", "\\div", "%", "-.",
})


def _lift_walk(e, safe: bool, consts: set, pinned: set,
               defs: Dict[str, Any], seen_ops: set) -> None:
    """Mark every constant Ident reached in a non-transparent context
    as pinned.  `safe` is the context flag for THIS node's position."""
    from ..sem.eval import OpClosure
    if e is None:
        return
    if isinstance(e, A.Ident):
        if e.name in consts and not safe:
            pinned.add(e.name)
        return
    if isinstance(e, (A.Num, A.Str, A.Bool, A.At)):
        return
    if isinstance(e, A.OpApp):
        nm = _norm(e.name)
        if e.path:  # instance-path application: opaque, pin everything
            for _inst, iargs in e.path:
                for a in iargs:
                    _lift_walk(a, False, consts, pinned, defs, seen_ops)
            for a in e.args:
                _lift_walk(a, False, consts, pinned, defs, seen_ops)
            return
        if nm in consts and not e.args:
            # zero-arg application of the constant itself
            if not safe:
                pinned.add(nm)
            return
        if nm in _LIFT_SAFE_OPS:
            for a in e.args:
                _lift_walk(a, safe, consts, pinned, defs, seen_ops)
            return
        d = defs.get(e.name)
        if isinstance(d, OpClosure):
            # user operator: walk its body ONCE (occurrences inside are
            # classified by their own contexts); call-site arguments are
            # conservatively pinned — the body may route a parameter
            # into a static-only position
            if e.name not in seen_ops:
                seen_ops.add(e.name)
                _lift_walk(d.body, True, consts, pinned, defs, seen_ops)
            for a in e.args:
                _lift_walk(a, False, consts, pinned, defs, seen_ops)
            return
        # unknown / static-shaped builtin (.., Cardinality, DOMAIN,
        # SUBSET, Append, ...): operand positions are pinned
        for a in e.args:
            _lift_walk(a, False, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.If):
        for c in (e.cond, e.then, e.els):
            _lift_walk(c, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.Case):
        for cond, body in e.arms:
            _lift_walk(cond, safe, consts, pinned, defs, seen_ops)
            _lift_walk(body, safe, consts, pinned, defs, seen_ops)
        _lift_walk(e.other, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.Quant):
        for _names, dom in e.binders:
            _lift_walk(dom, False, consts, pinned, defs, seen_ops)
        _lift_walk(e.body, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.SetFilter):
        _lift_walk(e.set, False, consts, pinned, defs, seen_ops)
        _lift_walk(e.pred, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.SetMap):
        for _names, dom in e.binders:
            _lift_walk(dom, False, consts, pinned, defs, seen_ops)
        _lift_walk(e.expr, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.FnDef):
        for _names, dom in e.binders:
            _lift_walk(dom, False, consts, pinned, defs, seen_ops)
        _lift_walk(e.body, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.Let):
        for d in e.defs:
            body = getattr(d, "body", None) or getattr(d, "expr", None)
            _lift_walk(body, safe, consts, pinned, defs, seen_ops)
        _lift_walk(e.body, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.Except):
        _lift_walk(e.fn, False, consts, pinned, defs, seen_ops)
        for path, rhs in e.updates:
            for kind, part in path:
                if kind == "idx":
                    for p in part:
                        _lift_walk(p, False, consts, pinned, defs,
                                   seen_ops)
            _lift_walk(rhs, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, (A.TupleExpr,)):
        for x in e.items:
            _lift_walk(x, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.RecordExpr):
        for _f, v in e.fields:
            _lift_walk(v, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, A.Prime):
        _lift_walk(e.expr, safe, consts, pinned, defs, seen_ops)
        return
    if isinstance(e, (A.BoxAction, A.AngleAction)):
        _lift_walk(e.expr, safe, consts, pinned, defs, seen_ops)
        return
    # everything else (SetEnum, FnApp, Dot, FnSet, RecordSet, Choose,
    # Unchanged, Enabled, Fair, Lambda, temporal forms): conservative —
    # every child is a pinned context
    for f in getattr(e, "__dataclass_fields__", ()):
        v = getattr(e, f)
        if isinstance(v, A.Node):
            _lift_walk(v, False, consts, pinned, defs, seen_ops)
        elif isinstance(v, tuple):
            for x in _flat_nodes(v):
                _lift_walk(x, False, consts, pinned, defs, seen_ops)


def _flat_nodes(v):
    for x in v:
        if isinstance(x, A.Node):
            yield x
        elif isinstance(x, tuple):
            yield from _flat_nodes(x)


def _pin_all(e, consts: set, pinned: set, defs: Dict[str, Any],
             seen_ops: set) -> None:
    """Pin EVERY constant reachable from `e`, including through user
    operator bodies — used for VIEW/SYMMETRY, whose whole expression
    feeds the dedup-key basis."""
    from ..sem.eval import OpClosure
    if e is None or isinstance(e, (A.Num, A.Str, A.Bool, A.At)):
        return
    if isinstance(e, A.Ident):
        if e.name in consts:
            pinned.add(e.name)
        return
    if isinstance(e, A.OpApp):
        if e.name in consts and not e.args:
            pinned.add(e.name)
        d = defs.get(e.name)
        if isinstance(d, OpClosure) and e.name not in seen_ops:
            seen_ops.add(e.name)
            _pin_all(d.body, consts, pinned, defs, seen_ops)
    for f in getattr(e, "__dataclass_fields__", ()):
        v = getattr(e, f)
        if isinstance(v, A.Node):
            _pin_all(v, consts, pinned, defs, seen_ops)
        elif isinstance(v, tuple):
            for x in _flat_nodes(v):
                _pin_all(x, consts, pinned, defs, seen_ops)


def liftable_constants(model) -> Tuple[str, ...]:
    """Sorted cfg CONSTANT names whose values may become per-model
    batch lanes: plain ints (not bools — bool lanes would change guard
    structure) used only in value positions across Init, Next, the
    checked predicates, and every reachable operator body."""
    consts = {n for n, v in model.cfg.constants.items()
              if type(model.defs.get(n)) is int}
    if not consts:
        return ()
    pinned: set = set()
    seen_ops: set = set()
    tops = [model.init, model.next]
    tops += [ex for _n, ex in model.invariants]
    tops += [ex for _n, ex in model.constraints]
    tops += [ex for _n, ex in model.action_constraints]
    tops += [ex for _n, ex in model.properties]
    try:
        for t in tops:
            _lift_walk(t, True, consts, pinned, model.defs, seen_ops)
        # VIEW and SYMMETRY feed the DEDUP-KEY basis, which the device
        # engines also trace OUTSIDE the constant-lane install sites
        # (_keys_of under _host_keys): any constant they reach — value
        # position or not — must stay baked, so pin wholesale
        for t in (model.view, model.symmetry):
            _pin_all(t, consts, pinned, model.defs, set())
    except RecursionError:
        return ()
    return tuple(sorted(consts - pinned))


_NO_REPORT = object()  # "never analyzed" vs a cached ran-and-bailed None


def av_cardinality(av: Optional[AV], depth: int = 0) -> Optional[int]:
    """Upper bound on the number of distinct concrete values the
    abstract value can denote; None = unbounded/unknown.  Soundly
    over-counts (a possibly-partial function counts each key as
    absent-or-any-value), never under-counts."""
    if av is None or depth > _MAX_DEPTH:
        return None
    k = av[0]
    if k == "bool":
        return 2
    if k == "int":
        iv = av[1]
        if iv.bounded():
            return max(int(iv.hi) - int(iv.lo) + 1, 1)
        return None
    if k == "enum":
        return len(av[1]) if av[1] else None
    if k == "set":
        if av[1] is None:
            return 1  # provably always empty
        c = av_cardinality(av[1], depth + 1)
        if c is not None and c <= 24:
            return 2 ** c
        return None
    if k == "fun":
        dc = av_cardinality(av[1], depth + 1)
        rc = av_cardinality(av[2], depth + 1)
        if dc is not None and rc is not None and dc <= 16 \
                and rc < 2 ** 20:
            # rc+1: each key may also be ABSENT (partial functions /
            # varying domains share this abstraction)
            return min((rc + 1) ** dc, 2 ** 62)
        return None
    if k == "rec":
        est = 1
        for _kk, v in av[1]:
            c = av_cardinality(v, depth + 1)
            if c is None:
                return None
            est *= c
            if est >= 2 ** 62:
                return 2 ** 62
        return est
    return None  # seq/blob: an unbounded count axis


def state_space_estimate(model, report: Optional[BoundsReport] = None
                         ) -> Optional[int]:
    """A pre-scheduling COST bound from the converged fixpoint: the
    product of per-variable value-count bounds (interval spans, enum
    value-set cardinalities, set powersets, function spaces — ISSUE 15
    widened this beyond pure-int vars).  None when the fixpoint bails,
    fails to converge, or ANY variable's count is unbounded — the fast
    lane and the predicted-capacity rung must never act on a guess (a
    multi-minute search jumping the queue, or an undersized engine
    paying growth recompiles, is the exact inversion they exist to
    prevent)."""
    if report is None:
        rep = getattr(model, "_bounds_report", _NO_REPORT)
        if rep is None:
            # the analysis already RAN on this model and bailed —
            # re-running the whole fixpoint would bail again after
            # paying the full budget a second time
            return None
        report = rep if isinstance(rep, BoundsReport) \
            else infer_state_bounds(model)
    if report is None or not report.converged:
        return None
    est = 1
    for v in model.vars:
        c = av_cardinality(report.env.get(v))
        if c is None:
            return None
        est *= max(c, 1)
        if est >= 2 ** 62:
            return 2 ** 62
    return est


def merge_lane_bounds(bounds_list) -> Dict[str, Tuple[int, int]]:
    """Interval-union of per-member proven lane bounds for a batched
    engine's shared layout: a variable keeps a proof only when EVERY
    member proves one (absent anywhere -> unproven, sampled+guarded)."""
    merged: Dict[str, Tuple[int, int]] = {}
    bl = [b for b in bounds_list]
    if not bl or any(b is None for b in bl):
        return {}
    common = set(bl[0])
    for b in bl[1:]:
        common &= set(b)
    for v in common:
        merged[v] = (min(b[v][0] for b in bl),
                     max(b[v][1] for b in bl))
    return merged


def _union_iv(a: Optional[Tuple[int, int]], b: Optional[Tuple[int, int]]
              ) -> Optional[Tuple[int, int]]:
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def merge_eb(a: Optional[EB], b: Optional[EB]) -> Optional[EB]:
    """Structural interval-union of two per-element bound trees: every
    node keeps a proof only when BOTH sides prove one (a None child on
    either side drops to None, and the consumer — pack._sb_child —
    falls back to the merged covering `all`, a superset for both
    members).  Record keys survive only where both sides track a
    non-None per-key bound."""
    if a is None or b is None:
        return None
    keys = None
    if a.keys and b.keys:
        keys = {}
        for k in set(a.keys) & set(b.keys):
            m = merge_eb(a.keys.get(k), b.keys.get(k))
            if m is not None:
                keys[k] = m
        keys = keys or None
    out = EB(all=_union_iv(a.all, b.all),
             dom=merge_eb(a.dom, b.dom),
             rng=merge_eb(a.rng, b.rng),
             elem=merge_eb(a.elem, b.elem),
             keys=keys)
    return None if out.empty() else out


def merge_element_bounds(eb_list) -> Dict[str, "EB"]:
    """Per-element analog of merge_lane_bounds (ISSUE 18): the
    STRUCTURAL union of every member's element_bounds() trees, so a
    batch donor's container element lanes still pack at proven
    per-element widths instead of dropping to whole-variable summary
    intervals.  A variable keeps its tree only when every member proves
    one; the result is sound for all members by construction (each node
    is an interval union, each missing node a superset fallback)."""
    el = [e for e in eb_list]
    if not el or any(e is None for e in el):
        return {}
    common = set(el[0])
    for e in el[1:]:
        common &= set(e)
    merged: Dict[str, EB] = {}
    for v in common:
        m = el[0][v]
        for e in el[1:]:
            m = merge_eb(m, e[v])
            if m is None:
                break
        if m is not None:
            merged[v] = m
    return merged
