r"""jaxmc.analyze — static analysis over the TLA+ AST (ISSUE 9).

Three consumers, one parse-time pass:

  bounds inference   (analyze/bounds.py)  interval/type fixpoint over
      the next-state relation; finite per-variable summaries flow into
      compile/pack.build_lane_plan as PROVEN lane widths (gauge
      `analyze.proven_lanes`), replacing sampled+guarded widths where
      the proof converges.  JAXMC_ANALYZE_BOUNDS=0 disables.
  demotion prediction (analyze/verdicts.py)  the kernel2 CompileError
      taxonomy as a syntactic scan; tpu/bfs.py skips building arms with
      a verdict (gauge `analyze.arm_verdicts`, counter
      `analyze.predicted_demotions`), with the exact build-time reason
      wording.  JAXMC_ANALYZE_PREDICT=0 disables.
  corpus linter       (analyze/lint.py)  spec/cfg diagnostics with
      stable codes; `python -m jaxmc.analyze lint`, `check
      --analyze={off,warn,strict}`, the serve daemon's submit-time
      rejection, and `make lint-corpus` all consume it.
  independence        (analyze/independence.py, ISSUE 15)  per-arm
      read/write footprints down to container ELEMENTS and a
      conservative commutativity matrix; feeds the fused-group
      regrouping planner (default ON, JAXMC_ANALYZE_INDEP=0 opts out)
      and the opt-in --por persistent-set frontier filter.

`python -m jaxmc.analyze pylint` is the repo's own Python static
analysis fallback (unused imports/locals) for containers without ruff;
ruff.toml carries the equivalent rule selection for hosts that have it.
"""

from __future__ import annotations

import os

_OFF = ("0", "off", "no", "false", "disabled")


def bounds_enabled() -> bool:
    """Static bounds -> proven pack lanes (JAXMC_ANALYZE_BOUNDS)."""
    return os.environ.get("JAXMC_ANALYZE_BOUNDS", "1").strip().lower() \
        not in _OFF


def predict_enabled() -> bool:
    """Static per-arm demotion verdicts (JAXMC_ANALYZE_PREDICT)."""
    return os.environ.get("JAXMC_ANALYZE_PREDICT", "1").strip().lower() \
        not in _OFF


from .bounds import (BoundsReport, EB, Iv, dead_arms,  # noqa: E402
                     infer_state_bounds, state_space_estimate)
from .verdicts import predict_arm_demotions  # noqa: E402
from .lint import Diagnostic, lint_pair  # noqa: E402
from .independence import (IndependenceReport,  # noqa: E402
                           independence_report, indep_enabled,
                           por_refusal)

__all__ = [
    "BoundsReport", "EB", "IndependenceReport", "Iv", "Diagnostic",
    "bounds_enabled", "dead_arms", "indep_enabled",
    "independence_report", "infer_state_bounds", "lint_pair",
    "por_refusal", "predict_arm_demotions", "predict_enabled",
    "state_space_estimate",
]
