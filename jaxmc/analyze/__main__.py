r"""`python -m jaxmc.analyze` — the static-analysis CLI (ISSUE 9).

    python -m jaxmc.analyze lint SPEC.tla [CFG.cfg] [-I DIR]...
        lint one spec/cfg pair; exit 2 on error diagnostics, 1 on
        warnings (use --errors-only to gate on errors alone), 0 clean.

    python -m jaxmc.analyze lint-corpus
        lint every corpus manifest pair (jaxmc/corpus.py).  Repo-local
        pairs must be clean modulo per-case waivers (Case.lint_waive);
        lint-only fixtures (Case.lint_expect) must produce exactly
        their expected diagnostic classes.  Reference-rooted pairs emit
        a parseable SKIP line when /root/reference is not mounted.
        Exit 1 on any violation — `make bench-check` gates on it.

    python -m jaxmc.analyze pylint [PATH]...
        the builtin Python checker (analyze/pylint.py) over jaxmc's own
        sources; `make pylint` uses ruff instead when available.
"""

from __future__ import annotations

import argparse
import os
import sys


def cmd_lint(args) -> int:
    from .lint import lint_pair
    diags = lint_pair(args.spec, args.cfg, tuple(args.include))
    worst = 0
    for d in diags:
        print(d.render())
        worst = max(worst, {"info": 0, "warning": 1, "error": 2}
                    [d.severity])
    if not diags:
        print(f"{os.path.basename(args.spec)}: clean")
    if worst == 2:
        return 2
    if worst == 1 and not args.errors_only:
        return 1
    return 0


def cmd_lint_corpus(args) -> int:
    from ..corpus import CASES, REFERENCE
    from .lint import lint_pair

    have_ref = os.path.isdir(REFERENCE)
    failures = 0
    checked = 0
    skipped = 0
    seen = set()
    for case in CASES:
        needs_ref = case.root == "ref" or any(
            not inc.startswith("repo:") for inc in case.includes)
        name = case.cfg or case.spec
        if needs_ref and not have_ref:
            skipped += 1
            print(f"[SKIP] {name}: reference corpus not mounted at "
                  f"{REFERENCE}")
            continue
        key = (case.spec_path(), case.cfg_path(), case.lint_waive,
               case.lint_expect)
        if key in seen:
            continue
        seen.add(key)
        checked += 1
        diags = lint_pair(case.spec_path(), case.cfg_path(),
                          tuple(case.include_dirs()))
        codes = sorted({d.code for d in diags})
        if case.lint_expect:
            missing = [c for c in case.lint_expect if c not in codes]
            if missing:
                failures += 1
                print(f"[FAIL] {name}: lint-only case missing expected "
                      f"diagnostics {missing} (got {codes})")
            else:
                print(f"[ok  ] {name}: lint-only case produced "
                      f"{codes}")
            continue
        unwaived = [d for d in diags if d.code not in case.lint_waive]
        if unwaived:
            failures += 1
            print(f"[FAIL] {name}: {len(unwaived)} unwaived "
                  f"diagnostic{'s' if len(unwaived) != 1 else ''}:")
            for d in unwaived:
                print(f"         {d.render()}")
        else:
            note = f" ({len(diags)} waived)" if diags else ""
            print(f"[ok  ] {name}: clean{note}")
    print(f"lint-corpus: {checked} pairs checked, {skipped} skipped, "
          f"{failures} failure{'s' if failures != 1 else ''}")
    return 1 if failures else 0


def cmd_pylint(args) -> int:
    from .pylint import main as pylint_main
    return pylint_main(args.paths)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="jaxmc.analyze")
    sub = ap.add_subparsers(dest="cmd", required=True)

    li = sub.add_parser("lint", help="lint one spec/cfg pair")
    li.add_argument("spec")
    li.add_argument("cfg", nargs="?", default=None)
    li.add_argument("-I", "--include", action="append", default=[])
    li.add_argument("--errors-only", action="store_true",
                    help="exit nonzero only on error diagnostics "
                         "(warnings/infos still print)")
    li.set_defaults(fn=cmd_lint)

    lc = sub.add_parser("lint-corpus",
                        help="lint every corpus manifest pair against "
                             "its waivers/expectations")
    lc.set_defaults(fn=cmd_lint_corpus)

    py = sub.add_parser("pylint",
                        help="builtin Python unused-import/-local "
                             "checker (ruff fallback)")
    py.add_argument("paths", nargs="*", default=[])
    py.set_defaults(fn=cmd_pylint)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
