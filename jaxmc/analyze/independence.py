r"""Action-independence analysis (ISSUE 15 tentpole, consumer 3).

Per split arm (compile/ground.split_arms), a conservative READ/WRITE
variable footprint over the arm's AST:

  reads   pre-state variables the arm's guards, binder domains and
          assignment right-hand sides may depend on
  writes  variables whose post-value may differ from the pre-value
          (primed assignments + any variable whose disposition the walk
          cannot prove — UNCHANGED variables are neither)

Two arms COMMUTE when their footprints are non-interfering:

  W_i \cap W_j = {}   and   W_i \cap R_j = {}   and   W_j \cap R_i = {}

which is the classic dependency relation of partial-order reduction
(Godefroid/Valmari persistent sets; Holzmann's SPIN): firing one arm
cannot enable, disable, or change the effect of the other, and both
orders reach the same state.  Anything the walk cannot analyze (instance
paths, unresolvable UNCHANGED targets, recursion) bails to the FULL
footprint — commuting with nothing, which is always sound.

Consumers:

  * safe arm REGROUPING (backend/bfs._hstep_groups, mesh grouped
    expand): commuting arms pack into the same <=24-instance fused
    dispatch via `plan_arm_groups`; the engines restore provenance
    order at the merge, so counts/traces stay byte-identical while
    `expand.fused_groups` shrinks.  Default ON; JAXMC_ANALYZE_INDEP=0
    keeps the legacy contiguous grouping.
  * POR frontier reduction (engine/explore.py, opt-in --por): a
    persistent-set-style filter expands ONE globally-commuting
    invisible arm per state (when all its successors are new — the BFS
    cycle proviso) instead of every enabled arm, preserving
    invariant/deadlock verdicts (not raw state counts).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..front import tla_ast as A


def indep_enabled() -> bool:
    """JAXMC_ANALYZE_INDEP=0 disables independence-driven regrouping
    (the POR filter has its own opt-in flag, --por)."""
    return os.environ.get("JAXMC_ANALYZE_INDEP", "1").strip().lower() \
        not in ("0", "off", "false")


class _NoKey:
    __slots__ = ()

    def __repr__(self):
        return "<nokey>"


_NOKEY = _NoKey()

# a binder domain larger than this never becomes a _KeySet: the
# interference rules take set intersections and (for key arithmetic)
# cross products over the domain values
_KEYSET_MAX = 64


class _KeySet:
    """A binder key known only by its DOMAIN: the set of values the
    binder may take (ISSUE 18 dynamic element keys).  Interferes with
    a concrete key iff the key is a possible value, and with another
    _KeySet iff the domains overlap — two arms writing msgs[self] for
    bindings with disjoint domains commute element-wise instead of
    bailing to the whole-variable footprint."""
    __slots__ = ("vals",)

    def __init__(self, vals):
        self.vals = frozenset(vals)

    def __eq__(self, other):
        return isinstance(other, _KeySet) and self.vals == other.vals

    def __hash__(self):
        return hash((_KeySet, self.vals))

    def __repr__(self):
        return "{%s}" % "|".join(sorted(str(v) for v in self.vals))


class _TupleKey:
    """A statically-resolved tuple index (msgs[<<p, q>>]) — a dedicated
    wrapper so tuple keys cannot collide with the internal raw-tuple
    markers ($slotv etc.) that _static_key must keep rejecting."""
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = tuple(items)

    def __eq__(self, other):
        return isinstance(other, _TupleKey) and self.items == other.items

    def __hash__(self):
        return hash((_TupleKey, self.items))

    def __repr__(self):
        return "<<%s>>" % ",".join(str(v) for v in self.items)


def _is_static_scalar(v) -> bool:
    from ..sem.values import ModelValue
    return isinstance(v, (int, str, ModelValue)) and \
        not isinstance(v, bool)


def _keys_may_equal(k1, k2) -> bool:
    """Could two STATIC keys denote the same container element?
    Concrete keys compare by equality; a _KeySet stands for any of its
    domain values; a tuple key never equals a scalar (TLA+ tuples and
    scalars are distinct values)."""
    if isinstance(k1, _TupleKey) and isinstance(k2, _TupleKey):
        if len(k1.items) != len(k2.items):
            return False
        return all(_keys_may_equal(a, b)
                   for a, b in zip(k1.items, k2.items))
    if isinstance(k1, _TupleKey) or isinstance(k2, _TupleKey):
        other = k2 if isinstance(k1, _TupleKey) else k1
        if isinstance(other, _KeySet):
            # scalar domain members never equal a tuple value; any
            # non-scalar member is conservatively a possible match
            return any(not _is_static_scalar(v) for v in other.vals)
        return False
    if isinstance(k1, _KeySet) and isinstance(k2, _KeySet):
        return bool(k1.vals & k2.vals)
    if isinstance(k1, _KeySet):
        return k2 in k1.vals
    if isinstance(k2, _KeySet):
        return k1 in k2.vals
    return k1 == k2


def _key_arith(op: str, a, b):
    """Static integer arithmetic over keys (msgs[self+1]): concrete op
    concrete folds; a _KeySet maps over its domain (bounded cross
    product)."""
    def ints(k):
        if isinstance(k, int) and not isinstance(k, bool):
            return [k]
        if isinstance(k, _KeySet) and all(
                isinstance(v, int) and not isinstance(v, bool)
                for v in k.vals):
            return list(k.vals)
        return None
    av, bv = ints(a), ints(b)
    if av is None or bv is None or len(av) * len(bv) > _KEYSET_MAX:
        return _NOKEY
    f = (lambda x, y: x + y) if op == "+" else (lambda x, y: x - y)
    out = {f(x, y) for x in av for y in bv}
    if len(out) == 1:
        return next(iter(out))
    return _KeySet(out)


# Footprint ATOMS are (var, key) pairs: key None = the whole variable,
# a concrete key = ONE container element (pc[p1]), a _KeySet = one
# element from a known domain, a _TupleKey = one tuple-indexed element.
# Two atoms interfere when they name the same variable and either is
# whole-var or the keys MAY be equal — the granularity that lets
# raft/Paxos-style per-process arms over one shared container commute.
Atom = Tuple[str, object]


def _interfere(a: FrozenSet[Atom], b: FrozenSet[Atom]) -> bool:
    for v1, k1 in a:
        for v2, k2 in b:
            if v1 != v2:
                continue
            if k1 is None or k2 is None or _keys_may_equal(k1, k2):
                return True
    return False


def _fmt_atoms(atoms: FrozenSet[Atom]) -> str:
    out = []
    for v, k in sorted(atoms, key=lambda a: (a[0], repr(a[1]))):
        out.append(v if k is None else f"{v}[{k}]")
    return ",".join(out)


@dataclass(frozen=True)
class ArmFootprint:
    label: str
    reads: FrozenSet[Atom]
    writes: FrozenSet[Atom]
    exact: bool  # False: the walk bailed and the footprint is ALL vars
    bail_reason: Optional[str] = None  # named, when exact is False

    def write_vars(self) -> FrozenSet[str]:
        return frozenset(v for v, _k in self.writes)

    def key_class(self) -> str:
        """Dynamic-key classification (ISSUE 18): did the writes
        resolve to element atoms — the granularity regrouping and POR
        consume — and when not, why."""
        if not self.exact:
            return ("full-footprint bail "
                    f"({self.bail_reason or 'unanalyzable'})")
        whole = sorted({v for v, k in self.writes if k is None})
        if whole:
            return f"whole-var writes: {','.join(whole)}"
        return "element-commuting"


def _bail(acc, why: str) -> None:
    acc["bail"] = True
    acc.setdefault("why", why)


class _FootprintWalk:
    """One model's footprint collector; def-body footprints memoized."""

    def __init__(self, model):
        self.model = model
        self.vars = set(model.vars)
        self.defs = model.defs
        self._def_memo: Dict[str, Tuple[Set[Atom], Set[Atom], Set[str],
                                        bool, Optional[str]]] = {}
        self._nodes = 0

    # ---- one arm ------------------------------------------------------
    def arm(self, arm) -> ArmFootprint:
        label = arm.label or "Next"
        acc = {"r": set(), "w": set(), "u": set(), "bail": False}
        try:
            self._walk(arm.expr, frozenset(), acc, (),
                       dict(arm.bound or {}))
        except RecursionError:
            _bail(acc, "python recursion limit")
        if acc["bail"]:
            allv = frozenset((v, None) for v in self.vars)
            return ArmFootprint(label, allv, allv, exact=False,
                                bail_reason=acc.get("why"))
        # a variable the walk never classified is an unknown write
        classified = {v for v, _k in acc["w"]} | acc["u"]
        for v in self.vars - classified:
            acc["w"].add((v, None))
        reads = frozenset((v, k) for v, k in acc["r"]
                          if v in self.vars)
        writes = frozenset((v, k) for v, k in acc["w"]
                           if v in self.vars)
        return ArmFootprint(label, reads, writes, exact=True)

    # ---- static-key resolution ---------------------------------------
    def _static_key(self, e, shadow, bound):
        """The static key of an index expression, or _NOKEY.  A key is
        concrete (binder/CONSTANT scalar), a _KeySet (binder over a
        statically-enumerable domain), a _TupleKey (tuple index of
        static components), or static +/- arithmetic over those."""
        if isinstance(e, A.Num):
            return e.val
        if isinstance(e, A.Str):
            return e.val
        if isinstance(e, A.TupleExpr):
            items = []
            for it in e.items:
                k = self._static_key(it, shadow, bound)
                if k is _NOKEY:
                    return _NOKEY
                items.append(k)
            return _TupleKey(items)
        if isinstance(e, A.OpApp) and not e.path and \
                e.name in ("+", "-") and len(e.args) == 2:
            return _key_arith(e.name,
                              self._static_key(e.args[0], shadow, bound),
                              self._static_key(e.args[1], shadow, bound))
        if isinstance(e, A.Ident) and e.name not in shadow:
            v = _NOKEY
            if e.name in bound:
                v = bound[e.name]
            elif e.name not in self.vars:
                # a cfg-bound CONSTANT scalar is as static as a binder
                from ..sem.values import ModelValue
                d = self.defs.get(e.name)
                if isinstance(d, (int, str, ModelValue)) and \
                        not isinstance(d, bool):
                    v = d
            if v is _NOKEY:
                return _NOKEY
            try:
                hash(v)
            except TypeError:
                return _NOKEY
            if isinstance(v, tuple):
                return _NOKEY  # internal markers ($slotv etc.)
            return v
        return _NOKEY

    def _index_key(self, args, shadow, bound):
        """The static key of an index-argument list: one argument is
        the key itself, several are the implicit tuple f[a, b]."""
        if len(args) == 1:
            return self._static_key(args[0], shadow, bound)
        items = []
        for a in args:
            k = self._static_key(a, shadow, bound)
            if k is _NOKEY:
                return _NOKEY
            items.append(k)
        return _TupleKey(items)

    def _static_domain(self, dom, shadow, bound):
        """The statically-enumerable value set of a binder domain, or
        None.  Members must be concrete scalar keys: the _KeySet
        interference rules reason over possible key VALUES, so one
        unresolvable member poisons the whole domain."""
        if dom is None:
            return None
        if isinstance(dom, A.SetEnum):
            vals = []
            for it in dom.items:
                k = self._static_key(it, shadow, bound)
                if not _is_static_scalar(k):
                    return None
                vals.append(k)
            return frozenset(vals) \
                if 0 < len(vals) <= _KEYSET_MAX else None
        if isinstance(dom, A.Ident) and dom.name not in shadow \
                and dom.name not in self.vars:
            d = self.defs.get(dom.name)
            if isinstance(d, (set, frozenset)) and \
                    0 < len(d) <= _KEYSET_MAX and \
                    all(_is_static_scalar(v) for v in d):
                return frozenset(d)
            return None
        if isinstance(dom, A.SetFilter):
            # a filter only narrows its base set: the base's value set
            # over-approximates the binder's possible keys, which is
            # sound (a larger _KeySet only interferes MORE) — this is
            # the dynamic raft shape `\E i \in {j \in Server : cond}`
            base = getattr(dom, "set", None)
            return None if base is None else \
                self._static_domain(base, shadow, bound)
        if isinstance(dom, A.OpApp) and not dom.path and \
                dom.name == ".." and len(dom.args) == 2:
            lo = self._static_key(dom.args[0], shadow, bound)
            hi = self._static_key(dom.args[1], shadow, bound)
            if _is_static_scalar(lo) and _is_static_scalar(hi) and \
                    isinstance(lo, int) and isinstance(hi, int) and \
                    0 < hi - lo + 1 <= _KEYSET_MAX:
                return frozenset(range(lo, hi + 1))
        return None

    # ---- recursive walk ----------------------------------------------
    def _walk(self, e, shadow: FrozenSet[str], acc, stack,
              bound) -> None:
        self._nodes += 1
        if e is None or acc["bail"] or self._nodes > 200000:
            if self._nodes > 200000:
                _bail(acc, "node budget exceeded")
            return
        if isinstance(e, (A.Num, A.Str, A.Bool, A.At)):
            return
        if isinstance(e, A.Ident):
            if e.name in shadow:
                return
            if e.name in self.vars:
                acc["r"].add((e.name, None))
                return
            self._def_use(e.name, acc, stack)
            return
        if isinstance(e, A.FnApp):
            # element read: pc[p] with a statically-bound p reads ONE
            # atom, not the whole container (f[a, b] = f[<<a, b>>])
            if isinstance(e.fn, A.Ident) and e.fn.name in self.vars \
                    and e.fn.name not in shadow and len(e.args) >= 1:
                k = self._index_key(e.args, shadow, bound)
                if k is not _NOKEY:
                    acc["r"].add((e.fn.name, k))
                    return
            self._walk(e.fn, shadow, acc, stack, bound)
            for a in e.args:
                self._walk(a, shadow, acc, stack, bound)
            return
        if isinstance(e, A.Prime):
            if isinstance(e.expr, A.Ident) and e.expr.name in self.vars:
                acc["w"].add((e.expr.name, None))
                return
            # primed compound: every var under it may be written
            sub = {"r": set(), "w": set(), "u": set(),
                   "bail": False}
            self._walk(e.expr, shadow, sub, stack, bound)
            if sub["bail"]:
                _bail(acc, sub.get("why", "unanalyzable primed "
                                          "expression"))
                return
            acc["w"] |= {(v, None) for v, _k in sub["r"] | sub["w"]}
            return
        if isinstance(e, A.Unchanged):
            if not self._unchanged(e.expr, shadow, acc, stack):
                _bail(acc, "unresolvable UNCHANGED target")
            return
        if isinstance(e, A.OpApp):
            if e.path:  # instance-qualified: unmodelled
                _bail(acc, "instance-qualified operator")
                return
            # the per-element assignment shape: v' = [v EXCEPT ![k]=e]
            if e.name == "=" and len(e.args) == 2 and \
                    self._prime_assign(e.args[0], e.args[1], shadow,
                                       acc, stack, bound):
                return
            # user operator with statically-resolvable args (Grab(p)
            # under a split \E binding): walk the BODY under the
            # argument binding so element keys inside stay resolvable
            from ..sem.eval import OpClosure
            d = self.defs.get(e.name) if e.name not in shadow else None
            if isinstance(d, OpClosure) and \
                    len(d.params) == len(e.args) and \
                    not isinstance(d.body, A.FnConstrDef):
                if e.name in stack or len(stack) > 32:
                    _bail(acc, f"recursive operator {e.name}")
                    return
                bound2 = {}
                static_args = True
                for p, aexpr in zip(d.params, e.args):
                    k = self._static_key(aexpr, shadow, bound)
                    if k is _NOKEY:
                        static_args = False
                        break
                    bound2[p] = k
                if static_args:
                    self._walk(d.body, frozenset(), acc,
                               stack + (e.name,), bound2)
                    return
            if e.name not in shadow:
                self._def_use(e.name, acc, stack)
            for a in e.args:
                self._walk(a, shadow, acc, stack, bound)
            return
        # binder forms extend the shadow for their bodies
        shadow2 = shadow
        binders = None
        if isinstance(e, (A.Quant, A.SetMap, A.FnDef)):
            binders = e.binders
        if binders is not None:
            # a binder over a statically-enumerable domain binds its
            # name to a _KeySet of the possible values instead of
            # shadowing it (ISSUE 18): element keys indexed by the
            # binder stay resolvable, so a DYNAMIC \E (one arm) still
            # gets an element-level footprint.  Names colliding with a
            # state variable or an operator keep the shadow path (the
            # Ident walk would misread them otherwise).
            names: List[str] = []
            ks_bound: Dict[str, object] = {}
            for bnames, dom in binders:
                names.extend(bnames)
                self._walk(dom, shadow, acc, stack, bound)
                dvals = self._static_domain(dom, shadow, bound)
                if dvals is not None:
                    ks = _KeySet(dvals)
                    for n in bnames:
                        if isinstance(n, str) and n not in self.vars \
                                and self.defs.get(n) is None:
                            ks_bound[n] = ks
            shadow2 = (shadow - frozenset(ks_bound)) | frozenset(
                n for n in names
                if isinstance(n, str) and n not in ks_bound)
            bound2 = bound if not ks_bound else {**bound, **ks_bound}
            self._walk(e.expr if isinstance(e, A.SetMap) else e.body,
                       shadow2, acc, stack, bound2)
            return
        if isinstance(e, (A.SetFilter, A.Choose)):
            v = e.var
            names = list(v) if isinstance(v, tuple) else [v]
            if getattr(e, "set", None) is not None:
                self._walk(e.set, shadow, acc, stack, bound)
            shadow2 = shadow | frozenset(n for n in names
                                         if isinstance(n, str))
            self._walk(e.pred, shadow2, acc, stack, bound)
            return
        if isinstance(e, A.Lambda):
            self._walk(e.body, shadow | frozenset(e.params), acc,
                       stack, bound)
            return
        if isinstance(e, A.Let):
            shadow2 = shadow
            for d in e.defs:
                body = getattr(d, "body", None)
                if body is not None:
                    params = tuple(getattr(d, "params", ()) or ())
                    self._walk(body, shadow2 | frozenset(
                        p for p in params if isinstance(p, str)),
                        acc, stack, bound)
                nm = getattr(d, "name", None)
                if isinstance(nm, str):
                    shadow2 = shadow2 | frozenset((nm,))
            self._walk(e.body, shadow2, acc, stack, bound)
            return
        # generic structural descent
        for f in getattr(e, "__dataclass_fields__", ()):
            v = getattr(e, f)
            if isinstance(v, A.Node):
                self._walk(v, shadow, acc, stack, bound)
            elif isinstance(v, tuple):
                self._walk_tuple(v, shadow, acc, stack, bound)

    def _walk_tuple(self, t, shadow, acc, stack, bound) -> None:
        for x in t:
            if isinstance(x, A.Node):
                self._walk(x, shadow, acc, stack, bound)
            elif isinstance(x, tuple):
                self._walk_tuple(x, shadow, acc, stack, bound)

    def _prime_assign(self, tgt, rhs, shadow, acc, stack,
                      bound) -> bool:
        """Element-precise handling of `v' = [v EXCEPT ![k] = e]` (and
        the identity `v' = v`): returns True when the shape was fully
        classified, False to fall back to the generic walk."""
        if not (isinstance(tgt, A.Prime) and isinstance(tgt.expr,
                                                        A.Ident)):
            return False
        var = tgt.expr.name
        if var not in self.vars:
            return False
        if isinstance(rhs, A.Ident) and rhs.name == var \
                and var not in shadow:
            acc["u"].add(var)  # v' = v: provably unchanged
            return True
        if isinstance(rhs, A.Except) and isinstance(rhs.fn, A.Ident) \
                and rhs.fn.name == var and var not in shadow:
            keys = []
            for path, upd in rhs.updates:
                if len(path) != 1 or path[0][0] != "idx" \
                        or len(path[0][1]) < 1:
                    return False  # nested/dot path: generic fallback
                k = self._index_key(path[0][1], shadow, bound)
                if k is _NOKEY:
                    return False
                keys.append(k)
                # @ refers to the SAME element being replaced
                self._walk(upd, shadow, acc, stack, bound)
            for k in keys:
                acc["w"].add((var, k))
                acc["r"].add((var, k))  # @ / read-modify-write shape
            return True
        return False

    def _unchanged(self, e, shadow, acc, stack) -> bool:
        """UNCHANGED target: vars under it are neither read nor
        written.  Returns False when a target cannot be resolved."""
        from ..sem.eval import OpClosure
        if isinstance(e, A.Ident):
            if e.name in self.vars:
                acc["u"].add(e.name)
                return True
            d = self.defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params:
                if e.name in stack or len(stack) > 24:
                    return False
                return self._unchanged(d.body, shadow, acc,
                                       stack + (e.name,))
            return False
        if isinstance(e, A.TupleExpr):
            return all(self._unchanged(x, shadow, acc, stack)
                       for x in e.items)
        return False

    def _def_use(self, name: str, acc, stack) -> None:
        """Fold a referenced definition's memoized footprint in."""
        from ..sem.eval import OpClosure
        d = self.defs.get(name)
        if not isinstance(d, OpClosure):
            return
        fp = self._def_memo.get(name)
        if fp is None:
            if name in stack or len(stack) > 32:
                _bail(acc, f"recursive operator {name}")
                return
            sub = {"r": set(), "w": set(), "u": set(), "bail": False}
            body = d.body
            if isinstance(body, A.FnConstrDef):
                body = body.body
            self._walk(body, frozenset(
                p for p in d.params if isinstance(p, str)),
                sub, stack + (name,), {})
            fp = (sub["r"], sub["w"], sub["u"], sub["bail"],
                  sub.get("why"))
            self._def_memo[name] = fp
        r, w, u, bail, why = fp
        if bail:
            _bail(acc, why or f"unanalyzable operator {name}")
            return
        acc["r"] |= r
        acc["w"] |= w
        acc["u"] |= u


def _expr_vars(model, e) -> Set[str]:
    """State variables an expression may depend on (transitively)."""
    fw = _FootprintWalk(model)
    acc = {"r": set(), "w": set(), "u": set(), "bail": False}
    try:
        fw._walk(e, frozenset(), acc, (), {})
    except RecursionError:
        acc["bail"] = True
    if acc["bail"]:
        return set(model.vars)
    return {v for v, _k in acc["r"] | acc["w"]} & set(model.vars)


@dataclass
class IndependenceReport:
    """Per-arm footprints + the conservative commutativity matrix."""
    labels: List[str]
    footprints: List[ArmFootprint]
    commutes: List[List[bool]]          # NxN, symmetric, False on diag
    visible: FrozenSet[str] = frozenset()  # property-support vars
    por_safe: Tuple[int, ...] = ()      # arms eligible as singleton
    # ample sets: globally commuting AND invisible
    wall_s: float = 0.0

    def commuting_pairs(self) -> int:
        n = len(self.labels)
        return sum(1 for i in range(n) for j in range(i + 1, n)
                   if self.commutes[i][j])

    def matrix_rows(self) -> List[str]:
        """Render for `jaxmc info --cfg` / logs: one row per arm."""
        out = []
        for i, lb in enumerate(self.labels):
            fp = self.footprints[i]
            marks = "".join("c" if self.commutes[i][j] else
                            ("." if i == j else "x")
                            for j in range(len(self.labels)))
            out.append(
                f"{lb:24s} [{marks}] R={{{_fmt_atoms(fp.reads)}}}"
                f" W={{{_fmt_atoms(fp.writes)}}}"
                + ("" if fp.exact else " (bailed: full footprint)")
                + (" por-safe" if i in self.por_safe else ""))
        return out

    def keyclass_rows(self) -> List[str]:
        """Dynamic-key classification per arm (ISSUE 18), rendered for
        `jaxmc info --cfg` next to the matrix: element-commuting /
        whole-var writes / full-footprint bail with the reason named."""
        return [f"{lb:24s} {self.footprints[i].key_class()}"
                for i, lb in enumerate(self.labels)]


def independence_report(model, arms=None) -> IndependenceReport:
    """Compute (and cache on the model) the arm-independence report.
    Never raises: an analysis defect degrades to full footprints."""
    import time
    cached = getattr(model, "_indep_report", None)
    if isinstance(cached, IndependenceReport):
        return cached
    t0 = time.time()
    if arms is None:
        from ..compile.ground import split_arms
        arms = split_arms(model)
    try:
        fw = _FootprintWalk(model)
        fps = [fw.arm(a) for a in arms]
    except Exception:
        if os.environ.get("JAXMC_DEBUG"):
            raise
        full = frozenset((v, None) for v in model.vars)
        fps = [ArmFootprint(a.label or "Next", full, full, exact=False,
                            bail_reason="analysis error")
               for a in arms]
    n = len(fps)
    mat = [[False] * n for _ in range(n)]
    for i in range(n):
        for j in range(i + 1, n):
            a, b = fps[i], fps[j]
            ok = not _interfere(a.writes, b.writes) and \
                not _interfere(a.writes, b.reads) and \
                not _interfere(b.writes, a.reads)
            mat[i][j] = mat[j][i] = ok
    # visibility: the support of every checked predicate — an arm
    # writing none of these cannot change any property verdict's
    # atomic propositions (POR condition C2)
    vis: Set[str] = set()
    try:
        for _nm, ex in list(model.invariants) + list(model.properties):
            vis |= _expr_vars(model, ex)
    except Exception:
        if os.environ.get("JAXMC_DEBUG"):
            raise
        vis = set(model.vars)
    safe = tuple(
        i for i in range(n)
        if fps[i].exact
        and all(mat[i][j] for j in range(n) if j != i)
        and not (fps[i].write_vars() & vis))
    rep = IndependenceReport(
        labels=[fp.label for fp in fps], footprints=fps, commutes=mat,
        visible=frozenset(vis), por_safe=safe,
        wall_s=round(time.time() - t0, 6))
    try:
        model._indep_report = rep
    except AttributeError:
        pass
    return rep


def por_refusal(model) -> Optional[str]:
    """Why --por must NOT reduce this model (run unreduced, named):
    constructs whose semantics interact with the reduction.  CONSTRAINT
    discards intermediate states (a commuting arm's effect could be
    lost through a discarded interleaving), SYMMETRY/VIEW already
    collapse the state space on their own orbits, and refinement/
    temporal properties quantify over the full behavior graph."""
    if model.constraints:
        return "cfg CONSTRAINT discards interleaving states"
    if model.action_constraints:
        return "cfg ACTION-CONSTRAINT filters interleavings"
    if model.symmetry is not None:
        return "cfg SYMMETRY (two reductions would compose unsoundly)"
    if getattr(model, "view", None) is not None:
        return "cfg VIEW collapses the dedup basis"
    if model.properties:
        return "temporal/refinement PROPERTYs need the full graph"
    return None


# ---------------------------------------------------------------------------
# fused-group planning (regrouping consumer)
# ---------------------------------------------------------------------------


def plan_arm_groups(weights: List[int], arm_of: List[int],
                    commutes: Optional[List[List[bool]]],
                    fused_max: int) -> List[List[int]]:
    """Partition compiled-action indices into fused dispatch groups of
    total instance weight <= fused_max.

    Legacy behavior (and the JAXMC_ANALYZE_INDEP=0 / no-matrix
    fallback): contiguous first-fit in index order.  With a
    commutativity matrix, actions cluster into mutually-commuting
    cliques first and the cliques bin-pack first-fit-decreasing — the
    plan with FEWER groups wins (ties keep the contiguous plan, zero
    churn).  Callers restore original provenance order at the merge,
    so ANY permutation here is result-identical; the matrix only
    steers which arms share a dispatch.
    """
    def contiguous() -> List[List[int]]:
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_w = 0
        for i, w in enumerate(weights):
            if cur and cur_w + w > fused_max:
                groups.append(cur)
                cur, cur_w = [], 0
            cur.append(i)
            cur_w += w
        if cur:
            groups.append(cur)
        return groups

    base = contiguous()
    if commutes is None or not indep_enabled() or len(weights) <= 1:
        return base

    def commute(i: int, j: int) -> bool:
        ai, aj = arm_of[i], arm_of[j]
        if ai == aj:
            return True  # instances of one arm always share a dispatch
        return commutes[ai][aj]

    # mutually-commuting cliques, greedy in index order
    cliques: List[List[int]] = []
    for i in range(len(weights)):
        for cl in cliques:
            if all(commute(i, o) for o in cl):
                cl.append(i)
                break
        else:
            cliques.append([i])
    # split any clique larger than the cap into weight-bounded runs
    units: List[List[int]] = []
    for cl in cliques:
        cur, cur_w = [], 0
        for i in cl:
            w = weights[i]
            if cur and cur_w + w > fused_max:
                units.append(cur)
                cur, cur_w = [], 0
            cur.append(i)
            cur_w += w
        if cur:
            units.append(cur)
    # first-fit-decreasing over clique units; a unit only joins a bin
    # whose members it fully commutes with (the point of regrouping is
    # commuting arms SHARING a dispatch, not arbitrary packing)
    units.sort(key=lambda u: -sum(weights[i] for i in u))
    packed: List[Tuple[int, List[int]]] = []  # (weight, members)
    for u in units:
        uw = sum(weights[i] for i in u)
        for gi, (gw, members) in enumerate(packed):
            if gw + uw <= fused_max and \
                    all(commute(i, o) for i in u for o in members):
                packed[gi] = (gw + uw, members + u)
                break
        else:
            packed.append((uw, list(u)))
    planned = [sorted(members) for _w, members in packed]
    # deterministic dispatch order: by first member index
    planned.sort(key=lambda g: g[0])
    if len(planned) < len(base):
        return planned
    return base
