r"""The corpus sweep: `jaxmc sweep` = the reference's `make test` contract
(`tlc *tla`, /root/reference/Makefile:6-7) — check every checkable
spec+cfg with its EXPECTED verdict, including the models whose defining
property is an expected violation. One manifest drives both the sweep and
the pytest pins (tests/test_corpus.py parametrizes over it).

Verdicts: "ok" (clean pass), "assumes" (ASSUME-calculator module, no
behavior spec), or "violation:<kind>" where kind is the Violation.kind the
checker must report (invariant/property/assert/deadlock).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

REFERENCE = os.environ.get("JAXMC_REFERENCE", "/root/reference")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SS = "examples/SpecifyingSystems"


@dataclass
class Case:
    spec: str                      # path, relative to root
    root: str = "ref"              # "ref" (reference) | "repo"
    cfg: Optional[str] = None      # defaults to spec with .cfg
    expect: str = "ok"             # ok | assumes | violation:<kind>
    distinct: Optional[int] = None
    generated: Optional[int] = None
    no_deadlock: bool = False
    includes: Tuple[str, ...] = ()  # extra -I dirs, relative to root kind
    slow: bool = False             # excluded from the default sweep/pins

    def spec_path(self) -> str:
        base = REFERENCE if self.root == "ref" else REPO
        return os.path.join(base, self.spec)

    def cfg_path(self) -> Optional[str]:
        if self.cfg == "":
            return None
        if self.cfg is not None:
            base = REFERENCE if self.root == "ref" else REPO
            return os.path.join(base, self.cfg)
        p = self.spec_path()[:-4] + ".cfg"
        return p if os.path.exists(p) else None

    def include_dirs(self) -> List[str]:
        out = []
        for inc in self.includes:
            if inc.startswith("repo:"):
                out.append(os.path.join(REPO, inc[5:]))
            else:
                out.append(os.path.join(REFERENCE, inc))
        return out


# Every reference cfg (all 21) plus the repo's MC shims. Counts are the
# TLC-semantics pins (CONSTRAINT-violating states are discarded, matching
# the golden testout2 run; see tests/test_corpus.py).
CASES: List[Case] = [
    # -- top level + tutorial variants
    Case("pcal_intro.tla", distinct=3800, generated=5850),
    Case("specs/pcal_intro_buggy.tla", root="repo", cfg="",
         expect="violation:assert"),
    Case("atomic_add.tla", cfg="", distinct=5, generated=7,
         no_deadlock=True),
    # -- Paxos chain
    Case("examples/Paxos/MCConsensus.tla", distinct=4, generated=7,
         no_deadlock=True),
    Case("examples/Paxos/MCVoting.tla", distinct=77, generated=406,
         no_deadlock=True),
    Case("examples/Paxos/MCPaxos.tla", distinct=25, generated=82),
    # -- Specifying Systems chapters
    Case(f"{SS}/SimpleMath/SimpleMath.tla", expect="assumes"),
    Case(f"{SS}/HourClock/HourClock.tla", distinct=12, generated=24),
    Case(f"{SS}/HourClock/HourClock2.tla", distinct=12, generated=24),
    Case(f"{SS}/AsynchronousInterface/AsynchInterface.tla",
         distinct=12, generated=30),
    Case(f"{SS}/AsynchronousInterface/Channel.tla",
         distinct=12, generated=30),
    Case(f"{SS}/AsynchronousInterface/PrintValues.tla", expect="assumes"),
    Case(f"{SS}/FIFO/MCInnerFIFO.tla", distinct=3864, generated=9660),
    Case(f"{SS}/CachingMemory/MCInternalMemory.tla",
         distinct=4408, generated=21400),
    Case(f"{SS}/CachingMemory/MCWriteThroughCache.tla",
         distinct=5196, generated=28170),
    Case(f"{SS}/Liveness/LiveHourClock.tla", distinct=12, generated=24),
    Case(f"{SS}/Liveness/MCLiveInternalMemory.tla",
         distinct=4408, generated=21400),
    Case(f"{SS}/Liveness/MCLiveWriteThroughCache.tla",
         distinct=5196, generated=28170),
    # ErrorTemporal is EXPECTED to fail (MCRealTimeHourClock.tla:43)
    Case(f"{SS}/RealTime/MCRealTimeHourClock.tla",
         expect="violation:property", distinct=216, generated=696),
    Case(f"{SS}/TLC/ABCorrectness.tla", distinct=20, generated=36),
    Case(f"{SS}/TLC/MCAlternatingBit.tla", distinct=240, generated=1392),
    Case(f"{SS}/AdvancedExamples/MCInnerSequential.tla",
         distinct=3528, generated=24368),
    # the golden testout2 model (6181/195, diameter 5 — TLC 1.57: 22h)
    Case(f"{SS}/AdvancedExamples/MCInnerSerial.tla",
         distinct=195, generated=6181),
    # -- repo MC shims for the cfg-less reference specs
    Case("specs/transfer_scaled.tla", root="repo",
         cfg="specs/transfer_scaled.cfg",
         distinct=153701, generated=311153, slow=True),
    Case("specs/MCraftMicro.tla", root="repo",
         cfg="specs/MCraft_micro.cfg", includes=("examples",),
         distinct=694, generated=6185),
    Case("specs/MCraftMicro.tla", root="repo",
         cfg="specs/MCraft_3s_bench.cfg", includes=("examples",),
         distinct=76654, generated=1138651, slow=True),
    Case("specs/MCtextbookSI.tla", root="repo",
         cfg="specs/MCtextbookSI_small.cfg", includes=("examples",),
         distinct=569, generated=945),
    # SI is EXPECTED non-serializable (textbookSnapshotIsolation.tla:91-96)
    Case("specs/MCtextbookSI.tla", root="repo",
         cfg="specs/MCtextbookSI_skew.cfg", includes=("examples",),
         expect="violation:invariant", slow=True),
    Case("specs/MCserializableSI.tla", root="repo",
         cfg="specs/MCserializableSI_small.cfg", includes=("examples",),
         distinct=569, generated=945),
]


def run_case(case: Case, backend: str = "interp"):
    """Returns (passed: bool, detail: str, result|None)."""
    from .front.cfg import ModelConfig, parse_cfg
    from .sem.modules import Loader, bind_model
    from .engine.explore import Explorer

    spec = case.spec_path()
    cfgp = case.cfg_path()
    cfg = parse_cfg(open(cfgp).read()) if cfgp else ModelConfig(
        specification="Spec")
    if case.no_deadlock:
        cfg.check_deadlock = False
    ldr = Loader([os.path.dirname(spec)] + case.include_dirs())
    mod = ldr.load_path(spec)

    if case.expect == "assumes":
        from .sem.eval import eval_expr, _bool, Ctx
        from .sem.modules import bind_model_defs
        defs = bind_model_defs(mod, cfg)
        ctx = Ctx(defs)
        n = 0
        for a in mod.assumes:
            if not _bool(eval_expr(a.expr, ctx), "ASSUME"):
                return False, "ASSUME violated", None
            n += 1
        return True, f"{n} assumptions checked", None

    model = bind_model(mod, cfg)
    if backend == "jax":
        from .tpu.bfs import TpuExplorer
        from .compile.vspec import CompileError
        from . import native_store
        try:
            r = TpuExplorer(model, store_trace=False,
                            host_seen=native_store.is_available()).run()
        except CompileError as ex:
            return True, f"SKIP (outside jax subset: {ex})", None
    else:
        r = Explorer(model).run()

    if case.expect == "ok":
        if not r.ok:
            return False, f"unexpected {r.violation.kind} violation " \
                          f"({r.violation.name})", r
    else:
        kind = case.expect.split(":", 1)[1]
        if r.ok or r.violation.kind != kind:
            return False, f"expected a {kind} violation, got " \
                          f"{'ok' if r.ok else r.violation.kind}", r
    if case.distinct is not None and r.distinct != case.distinct:
        return False, f"distinct {r.distinct} != pinned {case.distinct}", r
    if case.generated is not None and r.generated != case.generated:
        return False, f"generated {r.generated} != " \
                      f"pinned {case.generated}", r
    return True, f"{r.generated} generated / {r.distinct} distinct " \
                 f"({case.expect})", r


def sweep(backend: str = "interp", include_slow: bool = False,
          log=print) -> int:
    """Check the whole corpus; returns the number of failures."""
    failures = 0
    t0 = time.time()
    n = 0
    for case in CASES:
        if case.slow and not include_slow:
            continue
        n += 1
        name = case.cfg or case.spec
        t1 = time.time()
        try:
            ok, detail, _ = run_case(case, backend)
        except Exception as ex:  # a crash is a failure, not an abort
            ok, detail = False, f"CRASH {type(ex).__name__}: {ex}"
        status = "ok  " if ok else "FAIL"
        log(f"[{status}] {name:62s} {detail} "
            f"({time.time() - t1:.1f}s)")
        if not ok:
            failures += 1
    log(f"{n} corpus models checked, {failures} failures "
        f"({time.time() - t0:.1f}s, backend={backend})")
    return failures
